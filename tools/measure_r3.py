"""Round-3 measurement script: level profile + mask sparsity of the cached s24 layout."""
import numpy as np, time, sys

CACHE = "/root/repo/.bench_cache"

# ---- 1. level profile of the bench config ----
z = np.load(f"{CACHE}/rmat_native_s24_ef6_seed42_block8192.npz")
source = int(z["source"]); V = int(z["num_vertices"])
print("source", source, "V", V, flush=True)
src = z["src"].reshape(-1); dst = z["dst"].reshape(-1)
sent = V  # sentinel? check
print("src dtype/shape", src.dtype, src.shape, "max dst", dst.max(), flush=True)
keep = dst != dst.max() if dst.max() >= V else slice(None)
# build CSR on host quickly via bincount+argsort of src
t0=time.time()
mask = dst < V if dst.max() >= V else np.ones(len(dst), bool)
s2 = src[mask].astype(np.int64); d2 = dst[mask].astype(np.int64)
print("edges", len(s2), time.time()-t0, flush=True)
# level-synchronous BFS with numpy frontier expansion using CSR
order = np.argsort(s2, kind='stable')
t0=time.time()
s_sorted = s2[order]; d_sorted = d2[order]
indptr = np.zeros(V+1, np.int64); np.cumsum(np.bincount(s_sorted, minlength=V), out=indptr[1:])
print("csr built", time.time()-t0, flush=True)
dist = np.full(V, -1, np.int32); dist[source]=0
frontier = np.array([source], np.int64)
lvl=0
prof=[]
while len(frontier):
    # gather all out edges of frontier
    starts = indptr[frontier]; ends = indptr[frontier+1]
    cnt = ends-starts
    tot = int(cnt.sum())
    prof.append((lvl, len(frontier), tot))
    idx = np.repeat(starts + np.cumsum(cnt) - cnt, 1)  # not needed
    # flatten ranges
    flat = np.concatenate([np.arange(a,b) for a,b in zip(starts,ends)]) if len(frontier)<100000 else None
    if flat is None:
        # big frontier: do dense: mark neighbors via boolean over all edges
        fmask = np.zeros(V, bool); fmask[frontier]=True
        nb = d_sorted[fmask[s_sorted]]
    else:
        nb = d_sorted[flat]
    new = np.unique(nb)
    new = new[dist[new]<0]
    dist[new] = lvl+1
    frontier = new
    lvl+=1
print("LEVELS (level, frontier_vertices, frontier_out_edges):")
for p in prof: print(p, flush=True)
print("reached", int((dist>=0).sum()))
np.save(f"{CACHE}/s24_dist_host.npy", dist)
