#!/usr/bin/env python3
"""Diff two superstep phase ledgers: the before/after evidence tool.

Feeds on either a bench headline JSON (the line bench.py prints — the
ledger lives at ``details.superstep_phases``) or a raw ledger JSON (the
``python -m bfs_tpu.profiling`` output).  Prints a phase-by-phase delta
table (markdown, ready for BENCHMARKS.md) and exits non-zero when any
phase REGRESSED by more than ``--threshold`` (default 25% — the
in-container CPU run noise band) — the CI tripwire ROADMAP item 2's
acceptance asks for.

``--exact`` compares for bit-identical phase seconds AND an identical
``direction_schedule`` instead — the resumed-vs-golden invariant
(tools/chaos_run.py bench mode): both ledgers replay from the same
journal, so any difference means the resume path recomputed something it
should have restored.

No jax import: runs anywhere the repo does (the lint-stub discipline of
tools/obs_dashboard.py).
"""

from __future__ import annotations

import argparse
import json
import sys

#: Phases in ledger order (unknown extras are appended as found).
PHASE_ORDER = ["vperm", "broadcast", "net_apply", "rowmin", "state_update",
               "expansion", "full_superstep", "full_superstep_telemetry"]

#: Per-axis exchange columns of a 2D-grid capture (details.exchange).
AXIS_KEYS = ("col_bytes", "row_bytes", "col_schedule", "row_schedule")

#: Streaming-run totals of a ``details.stream`` ledger (ISSUE 18), in
#: table order.  Like the per-axis columns, the phase is compared only
#: when BOTH captures carry it — a streamed capture still diffs against
#: its pre-stream golden.
STREAM_KEYS = (
    "bytes_streamed", "hits", "misses", "evictions", "corrupt_refetches",
)

#: Label-tier record of a ``details.labels`` capture (ISSUE 20,
#: BENCH_LABELS mode), in table order.  The first five are deterministic
#: per (graph, K, pairs) and pinned under ``--exact``; the qps/speedup
#: tail is wall-clock and only tabulated.  Compared only when BOTH
#: captures carry the record — pre-label goldens simply lack it.
LABELS_PINNED = ("k", "pairs", "tight_hits", "fallbacks", "wrong_answers")
LABELS_KEYS = LABELS_PINNED + ("labels_qps", "exact_qps", "speedup")


def load_doc(path: str) -> dict:
    """Headline line(s) or raw ledger file -> the containing doc.  Bench
    output may hold several JSON lines (provisional + final): the LAST
    parseable line wins, matching how captures are read everywhere else."""
    with open(path) as f:
        text = f.read()
    try:
        # Whole-file document (the indent-2 profiling CLI output).
        return json.loads(text)
    except ValueError:
        pass
    doc = None
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
    if doc is None:
        raise SystemExit(f"{path}: no parseable JSON line")
    return doc


def extract(doc: dict, path: str):
    """(phases {name: seconds}, full ledger dict, direction_schedule|None,
    bytes {name: exchange bytes}, per_shard rows, exchange arm schedule,
    expansion-arm record, per-axis exchange columns).

    Understands BOTH capture shapes: single-chip headlines
    (``details.superstep_phases``) and sharded MULTICHIP headlines
    (``details.sharded_phases`` — per-shard rows + the exchange-bytes
    column riding each phase record, plus ``details.exchange.schedule``,
    the per-level arm record).  The last element is the EXPANSION-arm
    record (ISSUE 15): ``details.expansion``'s selected arm + per-level
    arm schedule, diffed under ``--exact`` like the direction and
    exchange schedules.  A ninth element carries the ``details.stream``
    ledger (ISSUE 18) — per-level bytes-streamed / hit / miss / evict
    rows plus run totals — ``None`` on captures that never streamed."""
    ledger = doc
    details = doc.get("details")
    if isinstance(details, dict):
        ledger = details.get("superstep_phases")
        if not isinstance(ledger, dict):
            ledger = details.get("sharded_phases")
    labels = None
    if isinstance(details, dict) and isinstance(details.get("labels"),
                                                dict):
        labels = details["labels"]
    if not isinstance(ledger, dict) or "phases" not in ledger:
        if labels is not None:
            # A BENCH_LABELS capture has no superstep ledger — the labels
            # record IS its ledger.
            ledger = {"phases": {}}
        else:
            raise SystemExit(
                f"{path}: no superstep phase ledger found (need a bench "
                "headline with details.superstep_phases or "
                "details.sharded_phases or details.labels, or a raw "
                "ledger JSON)"
            )
    phases = {
        name: float(rec["seconds"])
        for name, rec in ledger["phases"].items()
        if isinstance(rec, dict) and "seconds" in rec
    }
    xbytes = {
        name: int(rec["bytes_exchanged"])
        for name, rec in ledger["phases"].items()
        if isinstance(rec, dict) and "bytes_exchanged" in rec
    }
    per_shard = ledger.get("per_shard")
    sched = None
    xsched = None
    if isinstance(details, dict):
        ds = details.get("direction_schedule")
        if isinstance(ds, dict):
            sched = ds.get("schedule")
        ex = details.get("exchange")
        if isinstance(ex, dict):
            xsched = ex.get("schedule")
    esched = None
    if isinstance(details, dict):
        exp = details.get("expansion")
        if isinstance(exp, dict):
            esched = {
                "arm": exp.get("arm"),
                "per_level": exp.get("per_level"),
            }
    # Per-AXIS wire columns (ISSUE 17): grid captures split the
    # per-level exchange curve into a column-axis and a row-axis share
    # plus one arm schedule each.  Old 1D captures simply lack the keys
    # — the dict stays empty and every per-axis comparison is skipped,
    # so a grid capture still diffs against its pre-grid golden.
    axes = {}
    if isinstance(details, dict) and isinstance(details.get("exchange"),
                                                dict):
        ex = details["exchange"]
        axes = {
            k: ex[k] for k in AXIS_KEYS if ex.get(k) is not None
        }
    stream = None
    if isinstance(details, dict) and isinstance(details.get("stream"),
                                                dict):
        stream = details["stream"]
    return (phases, ledger, sched, xbytes, per_shard, xsched, esched,
            axes, stream, labels)


def fmt_s(s: float) -> str:
    if s >= 1e-3:
        return f"{s * 1e3:.3f} ms"
    return f"{s * 1e6:.1f} µs"


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("before")
    ap.add_argument("after")
    ap.add_argument(
        "--threshold", type=float, default=0.25,
        help="max tolerated per-phase regression (fraction; default 0.25)",
    )
    ap.add_argument(
        "--exact", action="store_true",
        help="require bit-identical phase seconds + direction schedule "
        "(the resumed-vs-golden invariant)",
    )
    args = ap.parse_args()

    pb, lb, sb, xb, shb, xsb, esb, axb, strb, labb = extract(
        load_doc(args.before), args.before
    )
    pa, la, sa, xa, sha, xsa, esa, axa, stra, laba = extract(
        load_doc(args.after), args.after
    )

    names = [p for p in PHASE_ORDER if p in pb or p in pa]
    names += [p for p in sorted(set(pb) | set(pa)) if p not in names]

    has_bytes = bool(xb or xa)
    rows = []
    regressed, mismatched = [], []
    for name in names:
        b, a = pb.get(name), pa.get(name)
        if b is None or a is None:
            rows.append((name, b, a, None))
            if args.exact:
                mismatched.append(name)
            continue
        delta = (a - b) / b if b > 0 else 0.0
        rows.append((name, b, a, delta))
        if args.exact and a != b:
            mismatched.append(name)
        elif not args.exact and delta > args.threshold:
            regressed.append((name, delta))

    if has_bytes:
        print("| phase | before | after | delta | exchange bytes |")
        print("|---|---|---|---|---|")
    else:
        print("| phase | before | after | delta |")
        print("|---|---|---|---|")
    for name, b, a, delta in rows:
        bs = fmt_s(b) if b is not None else "—"
        as_ = fmt_s(a) if a is not None else "—"
        ds = f"{delta * 100:+.1f}%" if delta is not None else "—"
        if has_bytes:
            bb, ba = xb.get(name), xa.get(name)
            xs = (
                f"{bb if bb is not None else '—'} -> "
                f"{ba if ba is not None else '—'}"
            )
            print(f"| {name} | {bs} | {as_} | {ds} | {xs} |")
            # Wire bytes are deterministic per (config, arm): more bytes
            # after than before is a regression of exactly the thing a
            # compressed-exchange PR claims (flat -> auto must shrink).
            if bb is not None and ba is not None:
                if args.exact and bb != ba:
                    mismatched.append(f"{name}:bytes")
                elif (
                    not args.exact and bb > 0
                    and (ba - bb) / bb > args.threshold
                ):
                    regressed.append((f"{name}:bytes", (ba - bb) / bb))
            if args.exact:
                # Grid phase rows split bytes per axis; compare each
                # column only when BOTH captures carry it.
                rb = lb.get("phases", {}).get(name)
                ra = la.get("phases", {}).get(name)
                for axk in ("col_bytes", "row_bytes"):
                    if (
                        isinstance(rb, dict) and isinstance(ra, dict)
                        and axk in rb and axk in ra
                        and rb[axk] != ra[axk]
                    ):
                        mismatched.append(f"{name}:{axk}")
        else:
            print(f"| {name} | {bs} | {as_} | {ds} |")

    if shb or sha:
        print()
        print("| shard | real_words | adj_entries | exchange bytes |")
        print("|---|---|---|---|")
        for row_b, row_a in zip(shb or [], sha or []):
            s = row_b.get("shard", row_a.get("shard"))
            rw = f"{row_b.get('real_words')} -> {row_a.get('real_words')}"
            ae = f"{row_b.get('adj_entries')} -> {row_a.get('adj_entries')}"
            eb = (
                f"{row_b.get('exchange_bytes_share')} -> "
                f"{row_a.get('exchange_bytes_share')}"
            )
            print(f"| {s} | {rw} | {ae} | {eb} |")
        if args.exact and (shb or []) != (sha or []):
            mismatched.append("per_shard")

    if axb or axa:
        # Per-axis per-level table (grid captures).  zip to the longer
        # curve so a level present on one side only renders as '—'.
        nlev = max(
            len(axb.get("col_bytes") or []), len(axa.get("col_bytes") or [])
        )
        print()
        print("| level | col bytes | row bytes | col arm | row arm |")
        print("|---|---|---|---|---|")

        def _cell(side, key, i):
            v = side.get(key)
            return v[i] if v is not None and i < len(v) else "—"

        for i in range(nlev):
            cols = " | ".join(
                f"{_cell(axb, k, i)} -> {_cell(axa, k, i)}"
                for k in AXIS_KEYS
            )
            print(f"| {i + 1} | {cols} |")
        if args.exact:
            for k in AXIS_KEYS:
                if (
                    axb.get(k) is not None and axa.get(k) is not None
                    and list(axb[k]) != list(axa[k])
                ):
                    mismatched.append(f"exchange:{k}")

    if strb or stra:
        # Streamed-run ledger (ISSUE 18): totals row + the per-level
        # bytes/hit/miss/evict curve.  zip to the longer level list so a
        # level present on one side only renders as '—'; the phase is
        # PINNED under --exact only when both captures carry it (an old
        # pre-stream golden simply lacks details.stream).
        def _tot(side, key):
            return side.get(key, "—") if side else "—"

        print()
        print("| stream | " + " | ".join(STREAM_KEYS) + " |")
        print("|---|" + "---|" * len(STREAM_KEYS))
        print(
            "| totals | "
            + " | ".join(
                f"{_tot(strb, k)} -> {_tot(stra, k)}" for k in STREAM_KEYS
            )
            + " |"
        )
        lev_b = (strb or {}).get("levels") or []
        lev_a = (stra or {}).get("levels") or []
        print()
        print("| level | arm | demanded | bytes streamed | hits | misses "
              "| evictions |")
        print("|---|---|---|---|---|---|---|")

        def _row(rows, i, key):
            return rows[i].get(key, "—") if i < len(rows) else "—"

        for i in range(max(len(lev_b), len(lev_a))):
            cols = " | ".join(
                f"{_row(lev_b, i, k)} -> {_row(lev_a, i, k)}"
                for k in ("arm", "demanded", "bytes_streamed", "hits",
                          "misses", "evictions")
            )
            lvl = _row(lev_b, i, "level")
            if lvl == "—":
                lvl = _row(lev_a, i, "level")
            print(f"| {lvl} | {cols} |")
        if args.exact and strb and stra:
            for k in STREAM_KEYS:
                if strb.get(k) != stra.get(k):
                    mismatched.append(f"stream:{k}")
            if lev_b != lev_a:
                mismatched.append("stream:levels")

    if labb or laba:
        # Label-tier record (ISSUE 20): one totals row.  The counter
        # half (k/pairs/hits/fallbacks/wrong) is deterministic per
        # (graph, K, pair batch) and pinned under --exact; the qps half
        # is wall clock and only tabulated.  A capture answering ANY
        # query wrongly, or whose label tier is not strictly faster than
        # the exact arm, fails the diff outright — that is the claim a
        # label-tier PR makes.
        def _lv(side, key):
            return side.get(key, "—") if side else "—"

        print()
        print("| labels | " + " | ".join(LABELS_KEYS) + " |")
        print("|---|" + "---|" * len(LABELS_KEYS))
        print(
            "| totals | "
            + " | ".join(
                f"{_lv(labb, k)} -> {_lv(laba, k)}" for k in LABELS_KEYS
            )
            + " |"
        )
        if args.exact and labb and laba:
            for k in LABELS_PINNED:
                if labb.get(k) != laba.get(k):
                    mismatched.append(f"labels:{k}")
        for side_name, side in (("before", labb), ("after", laba)):
            if not side:
                continue
            if int(side.get("wrong_answers", 0)) != 0:
                regressed.append((f"labels:{side_name}:wrong_answers", 1.0))
            if float(side.get("speedup", 0.0)) <= 1.0:
                regressed.append((
                    f"labels:{side_name}:speedup",
                    float(side.get("speedup", 0.0)) - 1.0,
                ))

    if args.exact and xsb != xsa:
        mismatched.append("exchange_schedule")
    if args.exact and esb != esa:
        # The expansion-arm record (selected arm + per-level arm
        # schedule): a resumed run flipping gather<->mxu, or replaying a
        # different per-level arm sequence, recomputed what it should
        # have restored.
        mismatched.append("expansion_arm_schedule")

    for side, led in (("before", lb), ("after", la)):
        sel = {
            p: led["phases"][p].get("selected")
            for p in ("rowmin", "state_update", "expansion")
            if p in led.get("phases", {})
            and isinstance(led["phases"][p], dict)
            and led["phases"][p].get("selected")
        }
        if sel:
            print(f"\n{side}: selected arms {sel}", file=sys.stderr)

    if args.exact:
        if sb != sa:
            mismatched.append("direction_schedule")
        if mismatched:
            print(
                f"\nEXACT MISMATCH: {mismatched} (resumed ledger must "
                "replay the golden one bit-identically)",
                file=sys.stderr,
            )
            return 2
        print("\nexact match (phases + direction schedule)", file=sys.stderr)
        return 0
    if regressed:
        print(
            "\nREGRESSION over threshold "
            f"{args.threshold * 100:.0f}%: "
            + ", ".join(f"{n} {d * 100:+.1f}%" for n, d in regressed),
            file=sys.stderr,
        )
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
