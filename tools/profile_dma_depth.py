import os, sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np
jax.config.update("jax_compilation_cache_dir", "/root/repo/.bench_cache/xla")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES=128
OPTS = {"xla_tpu_scoped_vmem_limit_kib": "65536"}
NS = 16
TR = 2048
rows_per_stage = 8192
m_np = np.random.default_rng(0).integers(0, 2**32, (NS*rows_per_stage, LANES), dtype=np.uint32)
m = jnp.asarray(m_np)
x0 = jnp.zeros((rows_per_stage, LANES), jnp.uint32)

def bench(nbuf, K=8):
    def kernel(x_ref, m_hbm, o_ref, mbuf, sem):
        pid = pl.program_id(0)
        xv = x_ref[...]
        def dma(slot, si):
            return pltpu.make_async_copy(
                m_hbm.at[pl.ds(si*rows_per_stage + pid*TR, TR), :],
                mbuf.at[slot], sem.at[slot])
        for si in range(min(nbuf-1, NS)):
            dma(si % nbuf, si).start()
        for si in range(NS):
            if si+nbuf-1 < NS: dma((si+nbuf-1)%nbuf, si+nbuf-1).start()
            dma(si%nbuf, si).wait()
            mm = mbuf[si%nbuf]
            t = (xv ^ (xv >> jnp.uint32(4))) & mm
            xv = xv ^ t ^ (t << jnp.uint32(4))
        o_ref[...] = xv
    @jax.jit
    def f(x, m):
        def body(i, x):
            y = pl.pallas_call(kernel,
                grid=(rows_per_stage//TR,),
                in_specs=[pl.BlockSpec((TR, LANES), lambda i: (i, 0)), pl.BlockSpec(memory_space=pl.ANY)],
                out_specs=pl.BlockSpec((TR, LANES), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, jnp.uint32),
                scratch_shapes=[pltpu.VMEM((nbuf, TR, LANES), jnp.uint32), pltpu.SemaphoreType.DMA((nbuf,))],
            )(x, m)
            return y ^ (x & 1)
        return jax.lax.fori_loop(0, K, body, x)
    c = f.lower(x0, m).compile(compiler_options=OPTS)
    r = c(x0, m); _ = np.asarray(jax.device_get(r)).ravel()[0]
    best=1e9
    for _ in range(6):
        t0=time.perf_counter(); r=c(x0,m); _=np.asarray(jax.device_get(r)).ravel()[0]
        best=min(best,time.perf_counter()-t0)
    t=(best-0.11)/K
    print(f"nbuf={nbuf}: {t*1000:6.2f} ms/pass -> {m_np.nbytes/t/1e9:5.0f} GB/s", flush=True)

for nbuf in (2, 4, 8):
    bench(nbuf)
