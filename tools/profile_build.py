"""Profile the relay layout build at s22 to find host-side hot spots."""
import numpy as np, time, sys
sys.path.insert(0, "/root/repo")
import cProfile, pstats

from bfs_tpu.graph.csr import Graph
from bfs_tpu.graph.native_gen import rmat_edges_native

t0=time.time()
u, v = rmat_edges_native(22, 6, seed=42)
g = Graph(1<<22, np.concatenate([u,v]), np.concatenate([v,u]))
print("gen", time.time()-t0, flush=True)

from bfs_tpu.graph import relay
t0=time.time()
pr = cProfile.Profile()
pr.enable()
rg = relay.build_relay_graph(g)
pr.disable()
print("build s22 total", time.time()-t0, flush=True)
st = pstats.Stats(pr)
st.sort_stats("cumulative").print_stats(25)
