import os, sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np
jax.config.update("jax_compilation_cache_dir", "/root/repo/.bench_cache/xla")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
from bfs_tpu.bench import load_or_build, load_or_build_relay
from bfs_tpu.ops import relay_pallas as RP

dg, _ = load_or_build(20, 16, 42, 8192, "native")
rg, _ = load_or_build_relay(dg, "native_s20_ef16_seed42_block8192")
K = 16
OPTS = {"xla_tpu_scoped_vmem_limit_kib": "65536"}
net_static = RP.pass_static(rg.net_table, rg.net_size)
arrays = [jnp.asarray(a) for a in RP.prepare_pass_masks(rg.net_masks, rg.net_table, rg.net_size)]
x0 = jnp.zeros(rg.net_size // 32, jnp.uint32)

def bench(fn, args, label, nbytes):
    f = jax.jit(fn)
    c = f.lower(*args).compile(compiler_options=OPTS)
    r = c(*args); _ = np.asarray(jax.device_get(r)).ravel()[0]
    ts=[]
    for _ in range(3):
        t0=time.perf_counter(); r=c(*args); _ = np.asarray(jax.device_get(r)).ravel()[0]
        ts.append(time.perf_counter()-t0)
    t=(min(ts)-0.107)/K
    print(f"{label:36s}: {t*1000:7.2f} ms/iter ({nbytes/t/1e9:5.0f} GB/s)", flush=True)

# local pass subsets by stage kind
mode, tr, tt, specs = net_static[1]
arr = arrays[1]
kinds = {
    "word (d<32)": [s for s in specs if s.d < 32],
    "lane (32<=d<4096)": [s for s in specs if 32 <= s.d < 4096],
    "row-compact (d>=4096)": [s for s in specs if s.d >= 4096],
}
for label, sub in kinds.items():
    sub = tuple(sub)
    nbytes = sum(s.nwords for s in sub) * 4
    def k(x, m, sub=sub):
        def body(i, x):
            return RP._run_pass(x, m, "local", tr, tt, sub, rg.net_size, False) ^ (x & 1)
        return jax.lax.fori_loop(0, K, body, x)
    bench(k, (x0, arr), f"local {label} x{len(sub)}", nbytes)

# DMA-only: stages with compute replaced? approximate: single word stage repeated
one = tuple([s for s in specs if s.d < 32][:1]) * 9
def k1(x, m):
    def body(i, x):
        return RP._run_pass(x, m, "local", tr, tt, one, rg.net_size, False) ^ (x & 1)
    return jax.lax.fori_loop(0, K, body, x)
bench(k1, (x0, arr), "local 9x same word stage", sum(s.nwords for s in one)*4)
