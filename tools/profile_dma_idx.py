import os, sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np
jax.config.update("jax_compilation_cache_dir", "/root/repo/.bench_cache/xla")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
LANES=128; OPTS={"xla_tpu_scoped_vmem_limit_kib": "65536"}
NS=16; TR=2048; RPS=8192
m_np = np.random.default_rng(0).integers(0,2**32,(NS*RPS,LANES),dtype=np.uint32)
x0 = jnp.zeros((RPS, LANES), jnp.uint32)

def bench(style, K=8):
    if style=="3d":
        mdev = jnp.asarray(m_np.reshape(NS, RPS, LANES))
        def dma(m_hbm, mbuf, sem, slot, si, pid):
            return pltpu.make_async_copy(m_hbm.at[si, pl.ds(pid*TR, TR), :], mbuf.at[slot], sem.at[slot])
    else:
        mdev = jnp.asarray(m_np)
        def dma(m_hbm, mbuf, sem, slot, si, pid):
            return pltpu.make_async_copy(m_hbm.at[pl.ds(si*RPS + pid*TR, TR), :], mbuf.at[slot], sem.at[slot])
    def kernel(x_ref, m_hbm, o_ref, mbuf, sem):
        pid = pl.program_id(0)
        xv = x_ref[...]
        dma(m_hbm, mbuf, sem, 0, 0, pid).start()
        for si in range(NS):
            if si+1<NS: dma(m_hbm,mbuf,sem,(si+1)%2,si+1,pid).start()
            dma(m_hbm,mbuf,sem,si%2,si,pid).wait()
            mm = mbuf[si%2]
            t = (xv ^ (xv >> jnp.uint32(4))) & mm
            xv = xv ^ t ^ (t << jnp.uint32(4))
        o_ref[...] = xv
    @jax.jit
    def f(x, m):
        def body(i, x):
            y = pl.pallas_call(kernel, grid=(RPS//TR,),
                in_specs=[pl.BlockSpec((TR,LANES), lambda i:(i,0)), pl.BlockSpec(memory_space=pl.ANY)],
                out_specs=pl.BlockSpec((TR,LANES), lambda i:(i,0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, jnp.uint32),
                scratch_shapes=[pltpu.VMEM((2,TR,LANES), jnp.uint32), pltpu.SemaphoreType.DMA((2,))],
            )(x, m)
            return y ^ (x & 1)
        return jax.lax.fori_loop(0, K, body, x)
    c = f.lower(x0, mdev).compile(compiler_options=OPTS)
    r=c(x0,mdev); _=np.asarray(jax.device_get(r)).ravel()[0]
    best=1e9
    for _ in range(6):
        t0=time.perf_counter(); r=c(x0,mdev); _=np.asarray(jax.device_get(r)).ravel()[0]
        best=min(best,time.perf_counter()-t0)
    t=(best-0.11)/K
    print(f"{style}: {t*1000:6.2f} ms/pass -> {m_np.nbytes/t/1e9:5.0f} GB/s", flush=True)

bench("3d"); bench("2d"); bench("3d"); bench("2d")
