"""Build + cache the v4 s24 relay layout, timing each phase (task 3 target:
cold-cache < 300 s)."""
import os, sys, time
sys.path.insert(0, "/root/repo")
os.environ["JAX_PLATFORMS"] = "cpu"  # no device needed
import numpy as np

t_all = time.perf_counter()
from bfs_tpu.bench import load_or_build, load_or_build_relay, _generator_backend
backend = _generator_backend()
dg, source = load_or_build(24, 6, 42, 8192, backend)
print(f"graph load: {time.perf_counter()-t_all:.1f}s", flush=True)

t0 = time.perf_counter()
rg, build_seconds = load_or_build_relay(dg, f"{backend}_s24_ef6_seed42_block8192")
print(f"relay layout: build_seconds={build_seconds:.1f} (incl. in wall {time.perf_counter()-t0:.1f}s with npz save)", flush=True)
print("net_size", rg.net_size, "m1", rg.m1, "m2", rg.m2, "vr", rg.vr, "vperm", rg.vperm_size)
print("net mask MB", rg.net_masks.nbytes/1e6, "vperm mask MB", rg.vperm_masks.nbytes/1e6)
