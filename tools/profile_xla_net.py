import os, sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np
jax.config.update("jax_compilation_cache_dir", "/root/repo/.bench_cache/xla")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
from bfs_tpu.ops import relay as R
from bfs_tpu.bench import load_or_build, load_or_build_relay
OPTS={"xla_tpu_scoped_vmem_limit_kib": "65536"}
dg, _ = load_or_build(20, 16, 42, 8192, "native")
rg, _ = load_or_build_relay(dg, "native_s20_ef16_seed42_block8192")
K=16
masks = jnp.asarray(rg.net_masks)
x0 = jnp.zeros(rg.net_size // 32, jnp.uint32)
def k(x, m):
    def body(i, x):
        return R.apply_benes_std(x, m, rg.net_table, rg.net_size) ^ (x & 1)
    return jax.lax.fori_loop(0, K, body, x)
c = jax.jit(k).lower(x0, masks).compile(compiler_options=OPTS)
r=c(x0,masks); _=np.asarray(jax.device_get(r)).ravel()[0]
for _ in range(6):
    t0=time.perf_counter(); r=c(x0,masks); _=np.asarray(jax.device_get(r)).ravel()[0]
    t=(time.perf_counter()-t0-0.11)/K
    print(f"XLA per-stage net: {t*1000:6.2f} ms/iter ({rg.net_masks.nbytes/t/1e9:4.0f} GB/s)", flush=True)
