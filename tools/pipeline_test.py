"""Do dispatches pipeline through the axon tunnel?"""
import os, time
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.bench_cache/xla")
import jax, jax.numpy as jnp, numpy as np

# a program with ~30ms of real device work (streaming ~2GB at 75GB/s)
big = jnp.zeros((1<<28,), jnp.uint32)  # 1GB
@jax.jit
def work(x, s):
    def body(i, acc):
        return acc ^ (x + i).sum(dtype=jnp.uint32)
    r = jax.lax.fori_loop(0, 1, lambda i, a: a ^ (x[i:] .sum(dtype=jnp.uint32)), jnp.uint32(0))
    return r + s

@jax.jit
def work2(x, s):
    return (x + s).sum(dtype=jnp.uint32)  # read 1GB + small

# warm
v = int(work2(big, jnp.uint32(0)))
# individual timing
ts=[]
for i in range(5):
    t0=time.perf_counter(); v=int(work2(big, jnp.uint32(i))); ts.append(time.perf_counter()-t0)
print("individual run:", [f"{t*1000:.0f}" for t in ts], "ms")

# pipelined: dispatch 8, chain results so they're sequential on device, sync once
t0=time.perf_counter()
s = jnp.uint32(0)
outs=[]
for i in range(8):
    s = work2(big, s)
    outs.append(s)
v = int(s)
t = time.perf_counter()-t0
print(f"8 chained dispatches, one sync: total {t*1000:.0f} ms -> {t/8*1000:.0f} ms/run")

# scan-inside-one-program version
@jax.jit
def scanned(x):
    def body(c, i):
        return c ^ (x + c).sum(dtype=jnp.uint32), c
    c, _ = jax.lax.scan(body, jnp.uint32(0), jnp.arange(8, dtype=jnp.uint32))
    return c
v = int(scanned(big))
t0=time.perf_counter(); v=int(scanned(big)); t=time.perf_counter()-t0
print(f"scan(8) in one program: total {t*1000:.0f} ms -> {t/8*1000:.0f} ms/run")
