#!/usr/bin/env python
"""Decisive XLA gather speed test on the current backend (slope method)."""

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

LO, HI = 4, 16
E = 1 << 25  # 33.5M
V = 1 << 20


def slope(label, fn, *args, items=E):
    f_lo = jax.jit(partial(fn, iters=LO))
    f_hi = jax.jit(partial(fn, iters=HI))
    jax.block_until_ready(f_lo(*args))
    jax.block_until_ready(f_hi(*args))
    t_lo = min(_t(f_lo, *args) for _ in range(3))
    t_hi = min(_t(f_hi, *args) for _ in range(3))
    per = max((t_hi - t_lo) / (HI - LO), 1e-9)
    print(f"{label:44s} {per * 1e3:9.3f} ms/iter  {items / per / 1e9:8.2f} G/s"
          f"   [raw lo={t_lo * 1e3:.2f}ms hi={t_hi * 1e3:.2f}ms]",
          flush=True)


def _t(fn, *args):
    t0 = time.perf_counter()
    jax.block_until_ready(fn(*args))
    return time.perf_counter() - t0


def chained(op):
    # acc folds a full min of each output; input xored with i (loop-variant).
    def run(x, *args, iters):
        def body(i, acc):
            return acc + (op(x ^ i, *args).min() & 3)

        return jax.lax.fori_loop(0, iters, body, jnp.int32(0), unroll=False)

    return run


def main():
    rng = np.random.default_rng(0)
    idx = jnp.asarray(rng.integers(0, V, size=E, dtype=np.int32))
    tab = jnp.asarray(rng.integers(0, 1 << 30, size=V, dtype=np.int32))

    slope("reduce-min over E int32 (calibration)", chained(lambda x: x), idx)
    slope("1D gather tab[idx^i] (random)",
          chained(lambda x, t: t[x & (V - 1)]), idx, tab)
    slope("2D-idx gather tab[idx2d] [E/32,32]",
          chained(lambda x, t: t[(x & (V - 1)).reshape(-1, 32)]), idx, tab)
    slope("1D gather + reshape rowmin",
          chained(lambda x, t: jnp.min(t[x & (V - 1)].reshape(-1, 32), axis=1)),
          idx, tab)


if __name__ == "__main__":
    main()
