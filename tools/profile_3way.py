import os, sys, time, importlib.util
sys.path.insert(0, "/root/repo")
os.environ["BFS_TPU_PALLAS"] = "1"
import jax, jax.numpy as jnp, numpy as np
jax.config.update("jax_compilation_cache_dir", "/root/repo/.bench_cache/xla")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
from bfs_tpu.ops import relay_pallas as RP
from bfs_tpu.ops import relay as R
from bfs_tpu.bench import load_or_build, load_or_build_relay
OPTS={"xla_tpu_scoped_vmem_limit_kib": "65536"}
K=16
dg, _ = load_or_build(20, 16, 42, 8192, "native")
rg, _ = load_or_build_relay(dg, "native_s20_ef16_seed42_block8192")
net_static = RP.pass_static(rg.net_table, rg.net_size)
arrays = [jnp.asarray(a) for a in RP.prepare_pass_masks(rg.net_masks, rg.net_table, rg.net_size)]
masks = jnp.asarray(rg.net_masks)
x0 = jnp.zeros(rg.net_size // 32, jnp.uint32)

spec = importlib.util.spec_from_file_location("benes_pallas_r2", "/tmp/benes_pallas_r2.py")
m2 = importlib.util.module_from_spec(spec); m2.__package__ = "bfs_tpu.ops"
sys.modules["benes_pallas_r2"] = m2; spec.loader.exec_module(m2)
z3 = np.load("/root/repo/.bench_cache/relay_v3_native_s20_ef16_seed42_block8192.npz")
m3 = jnp.asarray(z3["net_masks"]); n3 = int(z3["net_size"])
x3 = jnp.zeros(n3 // 32, jnp.uint32)

def compile_k(fn, args):
    c = jax.jit(fn).lower(*args).compile(compiler_options=OPTS)
    r = c(*args); _ = np.asarray(jax.device_get(r)).ravel()[0]
    return c
def k_mine(x, *m):
    def b(i, x): return RP.apply_benes_fused(x, m, net_static, rg.net_size) ^ (x & 1)
    return jax.lax.fori_loop(0, K, b, x)
def k_r2(x, m):
    def b(i, x): return m2.apply_benes_fused(x, m, n=n3) ^ (x & 1)
    return jax.lax.fori_loop(0, K, b, x)
def k_xla(x, m):
    def b(i, x): return R.apply_benes_std(x, m, rg.net_table, rg.net_size) ^ (x & 1)
    return jax.lax.fori_loop(0, K, b, x)
c_m = compile_k(k_mine, (x0, *arrays))
c_r = compile_k(k_r2, (x3, m3))
c_x = compile_k(k_xla, (x0, masks))
def t_of(c, args):
    t0=time.perf_counter(); r=c(*args); _=np.asarray(jax.device_get(r)).ravel()[0]
    return (time.perf_counter()-t0-0.11)/K*1000
for rnd in range(5):
    print(f"round {rnd}: mine {t_of(c_m,(x0,*arrays)):6.1f} ms | r2 {t_of(c_r,(x3,m3)):6.1f} ms | xla {t_of(c_x,(x0,masks)):6.1f} ms", flush=True)
