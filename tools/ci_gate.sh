#!/usr/bin/env bash
# The pre-merge gate: tier-1 tests + the full cached lint surface.
#
#   tools/ci_gate.sh            # run everything, non-zero on any failure
#   tools/ci_gate.sh --no-tests # lint surface only (tier-1 ran elsewhere)
#
# Stages, fail-fast:
#   1. tier-1: the full CPU test suite on the 8-device virtual platform
#      (tests/conftest.py forces it), -m 'not slow' — exactly the
#      ROADMAP.md verify command minus the log plumbing.
#   2. traversal-chaos smoke (ISSUE 14): the in-process chaos-marker
#      tests of tests/test_superstep_ckpt.py — kill one mid-traversal
#      segment, resume, assert bit-identity (~seconds).  Runs even with
#      --no-tests: a checkpoint/resume divergence must fail the gate
#      independently of where tier-1 ran.
#   3. bfs-tpu-lint --all: AST + IR + HLO + Pallas + Knobs with merged
#      baseline handling — one exit code over every analyzer rung.
#      The non-AST passes are content-address-cached
#      (.bench_cache/{ir,hlo,pal,knb}), so a tree tier-1 just ran on
#      lints in seconds.
#
# Exit 0 = mergeable.  Any test failure, any unbaselined finding, or any
# STALE baseline entry is non-zero.
set -euo pipefail
cd "$(dirname "$0")/.."

RUN_TESTS=1
if [[ "${1:-}" == "--no-tests" ]]; then
    RUN_TESTS=0
fi

if [[ "$RUN_TESTS" == "1" ]]; then
    echo "== ci gate 0/3: warm analysis caches =="
    # Populate the content-addressed lint caches
    # (.bench_cache/{ir,hlo,pal,knb}) BEFORE tier-1: the suite's
    # lint_ir/lint_hlo/lint_pallas/lint_knobs tests then
    # hit warm caches instead of each paying the cold jax trace/compile
    # (~74 s) inside the pytest run, and the final lint stage is pure
    # cache reads.  Lint FAILURES are deliberately not fatal here — this
    # stage only warms; stage 3 is the one that gates.
    JAX_PLATFORMS=cpu python -m bfs_tpu.analysis --all || true

    echo "== ci gate 1/3: tier-1 tests =="
    JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
        -p no:cacheprovider
fi

echo "== ci gate: traversal-chaos smoke (kill/resume one segment) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_superstep_ckpt.py -q \
    -m 'chaos and not slow' -p no:cacheprovider

echo "== ci gate: MXU-arm parity smoke (ISSUE 15) =="
# The gather-vs-mxu bit-identity core: kernel/twin raw-byte parity,
# forced-mxu end-to-end vs the gather arm, and the x8 sharded parity —
# a divergence between the expansion arms must fail the gate on its own
# stage, independent of where tier-1 ran (~seconds; the full matrix runs
# in tier-1's tests/test_expansion_mxu.py).
JAX_PLATFORMS=cpu python -m pytest tests/test_expansion_mxu.py -q \
    -m 'mxu_smoke' -p no:cacheprovider

echo "== ci gate: 2D grid parity smoke (ISSUE 17) =="
# The tile-grid engine's bit-identity core: 2x4/1x8 vs the 1D x8 mesh
# and the single-chip oracle (dist/parent, direction schedule, col-axis
# bytes + arm schedule ≡ the 1D curve), the >62-level packed fallback,
# and fused-vs-segmented parity — a grid/1D divergence must fail the
# gate on its own stage (~seconds; the full matrix incl. chaos
# kill/resume runs in tier-1's tests/test_grid.py).
JAX_PLATFORMS=cpu python -m pytest tests/test_grid.py -q \
    -m 'grid_smoke' -p no:cacheprovider

echo "== ci gate: algorithm-parity smoke (ISSUE 16) =="
# The semiring substrate's oracle core: SSSP vs Dijkstra (dist + the
# canonical parents), CC vs union-find, packed truncation fallback,
# fused-vs-segmented identity, x2/x8 sharded parity, and the graph500
# harness end-to-end — an algorithm diverging from its oracle must fail
# the gate on its own stage (~seconds; the full matrix incl. chaos
# kill/resume runs in tier-1's tests/test_algo_{sssp,cc}.py).
JAX_PLATFORMS=cpu python -m pytest tests/test_algo_sssp.py \
    tests/test_algo_cc.py tests/test_graph500.py -q \
    -m 'algo_smoke' -p no:cacheprovider

echo "== ci gate: serve-fleet smoke (ISSUE 20) =="
# The label-tier + router core: 2 replicas rolling-register over one
# shared label sidecar (replica 1 must warm-hit, not rebuild), an epoch
# swap under in-flight queries, an induced replica close with failover,
# and every routed answer checked against the host oracle — a wrong
# point answer or a thundering-herd rebuild must fail the gate on its
# own stage (~seconds; the label certificate/kill-resume matrix runs in
# tier-1's tests/test_labels.py).
JAX_PLATFORMS=cpu python -m pytest tests/test_serve_fleet.py -q \
    -m 'fleet_smoke' -p no:cacheprovider

if [[ "$RUN_TESTS" == "1" ]]; then
    echo "== ci gate 3/3: lint --all (AST + IR + HLO + Pallas + Knobs) =="
else
    echo "== ci gate: lint --all (AST + IR + HLO + Pallas + Knobs) =="
fi
JAX_PLATFORMS=cpu python -m bfs_tpu.analysis --all

echo "== ci gate: all green =="
