#!/usr/bin/env bash
# The pre-merge gate: tier-1 tests + the full cached lint surface.
#
#   tools/ci_gate.sh            # run everything, non-zero on any failure
#   tools/ci_gate.sh --no-tests # lint surface only (tier-1 ran elsewhere)
#
# Two stages, fail-fast:
#   1. tier-1: the full CPU test suite on the 8-device virtual platform
#      (tests/conftest.py forces it), -m 'not slow' — exactly the
#      ROADMAP.md verify command minus the log plumbing.
#   2. bfs-tpu-lint --all: AST + IR + HLO + Pallas with merged baseline
#      handling — one exit code over every analyzer rung.  The jax
#      passes are content-address-cached (.bench_cache/{ir,hlo,pal}),
#      so a tree tier-1 just ran on lints in seconds.
#
# Exit 0 = mergeable.  Any test failure, any unbaselined finding, or any
# STALE baseline entry is non-zero.
set -euo pipefail
cd "$(dirname "$0")/.."

RUN_TESTS=1
if [[ "${1:-}" == "--no-tests" ]]; then
    RUN_TESTS=0
fi

if [[ "$RUN_TESTS" == "1" ]]; then
    echo "== ci gate 1/2: tier-1 tests =="
    JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
        -p no:cacheprovider
fi

if [[ "$RUN_TESTS" == "1" ]]; then
    echo "== ci gate 2/2: lint --all (AST + IR + HLO + Pallas) =="
else
    echo "== ci gate: lint --all (AST + IR + HLO + Pallas) =="
fi
JAX_PLATFORMS=cpu python -m bfs_tpu.analysis --all

echo "== ci gate: all green =="
