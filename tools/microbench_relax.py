#!/usr/bin/env python
"""Microbenchmark the pieces of the relax superstep on the current backend.

Methodology: each op runs N times inside one jitted fori_loop, XOR-perturbed
by the loop counter (loop-variant, not separable through min/gather/sort)
with a full output reduction folded into the carry (defeats DCE).  Per-op
time is the SLOPE between N=LO and N=HI total wall times, which cancels
dispatch latency, tunnel RTT, and any constant overhead.

Run on the real TPU: `python tools/microbench_relax.py [scale]`.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bfs_tpu.graph.csr import build_device_graph
from bfs_tpu.graph.generators import rmat_graph
from bfs_tpu.ops.relax import INT32_MAX

LO, HI = 16, 128


def make_loop(op, *extras):
    def run(x, iters):
        def body(i, acc):
            out = op(x ^ i, *extras)
            return acc + (out.min() & 3)

        return jax.lax.fori_loop(0, iters, body, jnp.int32(0), unroll=False)

    return jax.jit(run, static_argnames=("iters",))


def timeit(label, op, x, *extras, edges=None):
    fn = make_loop(op, *extras)
    totals = {}
    for iters in (LO, HI):
        jax.block_until_ready(fn(x, iters))  # compile
        best = min(
            _timed(fn, x, iters) for _ in range(3)
        )
        totals[iters] = best
    t = (totals[HI] - totals[LO]) / (HI - LO)
    t = max(t, 1e-9)
    rate = f"  {edges / t / 1e9:8.2f} Gedges/s" if edges else ""
    print(f"{label:46s} {t * 1e3:9.3f} ms/iter{rate}", flush=True)
    return t


def _timed(fn, x, iters):
    t0 = time.perf_counter()
    jax.block_until_ready(fn(x, iters))
    return time.perf_counter() - t0


def main():
    scale = int(sys.argv[1]) if len(sys.argv) > 1 else 20
    graph = rmat_graph(scale, 16, seed=42)
    dg = build_device_graph(graph, block=8 * 1024)
    v = dg.num_vertices
    e = dg.padded_edges
    print(f"V={v} padded_E={e} device={jax.devices()[0]} slope {LO}->{HI}")

    src = jnp.asarray(dg.src)
    dst = jnp.asarray(dg.dst)
    rng = np.random.default_rng(0)
    frontier_i32 = jnp.asarray((rng.random(v + 1) < 0.1).astype(np.int32))
    vals = jnp.asarray(rng.integers(0, v, size=e, dtype=np.int32))

    n = v + 1

    timeit("reduce-min over E (bandwidth floor)", lambda x: x, vals, edges=e)
    timeit("gather i32 table[x & mask] (E gathers)",
           lambda x, t: t[x & (n - 2)], vals, frontier_i32, edges=e)
    timeit("gather 2D [E/128,128] rows table[x&m]",
           lambda x, t: t[(x & (n - 2)).reshape(-1, 128)], vals, frontier_i32,
           edges=e)
    timeit("segment_min sorted",
           lambda x, d: jax.ops.segment_min(
               x, d, num_segments=n, indices_are_sorted=True), vals, dst,
           edges=e)
    timeit("scatter-min .at[dst].min",
           lambda x, d: jnp.full(n, INT32_MAX, jnp.int32).at[d].min(x), vals,
           dst, edges=e)
    timeit("full relax superstep (gather+where+segmin)",
           lambda f, s, d: jax.ops.segment_min(
               jnp.where(f[s] > 0, s, INT32_MAX), d,
               num_segments=n, indices_are_sorted=True),
           frontier_i32, src, dst, edges=e)
    timeit("ELL rowmin only [E/32, 32] axis=1",
           lambda x: jnp.min(x.reshape(-1, 32), axis=1), vals, edges=e)
    timeit("ELL gather+where+rowmin [E/32, 32]",
           lambda x, t: jnp.min(
               jnp.where(t[(x & (n - 2)).reshape(-1, 32)] > 0,
                         x.reshape(-1, 32), INT32_MAX), axis=1),
           vals, frontier_i32, edges=e)
    timeit("sort i32[E]", lambda x: jax.lax.sort(x), vals, edges=e)


if __name__ == "__main__":
    main()
