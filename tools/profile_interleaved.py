import os, sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np
jax.config.update("jax_compilation_cache_dir", "/root/repo/.bench_cache/xla")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from bfs_tpu.ops import relay_pallas as RP
from bfs_tpu.bench import load_or_build, load_or_build_relay

LANES=128; OPTS={"xla_tpu_scoped_vmem_limit_kib": "65536"}
dg, _ = load_or_build(20, 16, 42, 8192, "native")
rg, _ = load_or_build_relay(dg, "native_s20_ef16_seed42_block8192")
K=16
net_static = RP.pass_static(rg.net_table, rg.net_size)
arrays = [jnp.asarray(a) for a in RP.prepare_pass_masks(rg.net_masks, rg.net_table, rg.net_size)]
x0 = jnp.zeros(rg.net_size // 32, jnp.uint32)
def k_mine(x, *m):
    def body(i, x):
        return RP.apply_benes_fused(x, m, net_static, rg.net_size) ^ (x & 1)
    return jax.lax.fori_loop(0, K, body, x)
c_mine = jax.jit(k_mine).lower(x0, *arrays).compile(compiler_options=OPTS)

big = jnp.asarray(np.random.default_rng(1).integers(0,2**32,(1<<27,),dtype=np.uint32))  # 512MB
@jax.jit
def k_xla(x, s):
    def body(i, acc):
        return acc ^ (x + acc).sum(dtype=jnp.uint32)
    return jax.lax.fori_loop(0, 8, body, s)
c_xla = jax.jit(k_xla).lower(big, jnp.uint32(0)).compile(compiler_options=OPTS)

def t_mine():
    t0=time.perf_counter(); r=c_mine(x0, *arrays); _=np.asarray(jax.device_get(r)).ravel()[0]
    return (time.perf_counter()-t0-0.11)/K
def t_xla():
    t0=time.perf_counter(); r=c_xla(big, jnp.uint32(3)); _=np.asarray(jax.device_get(r))
    return (time.perf_counter()-t0-0.11)/8
# warm
t_mine(); t_xla()
for rnd in range(6):
    a=t_mine(); b=t_xla()
    print(f"round {rnd}: net-kernel {a*1000:6.1f} ms ({rg.net_masks.nbytes/a/1e9:4.0f} GB/s) | xla-read {b*1000:6.1f} ms ({0.537/b:4.0f} GB/s)", flush=True)
