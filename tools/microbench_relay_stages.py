"""Stage-level timing of the relay superstep on the real TPU.

Loads the cached relay layout for a bench config and times each phase of
relay_candidates in isolation (pack/unpack, vperm route, class broadcast,
big Beneš route, class row-min) plus the fused whole, to locate the gap
between the measured superstep cost and the HBM-bandwidth floor.

Usage: BENCH_SCALE=24 BENCH_EDGE_FACTOR=6 python tools/microbench_relay_stages.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bfs_tpu.bench import _generator_backend, load_or_build, load_or_build_relay
from bfs_tpu.ops.relay import (
    INT32_MAX,
    apply_benes,
    pack_bits,
    relay_candidates,
    unpack_bits,
)


def _sync(out):
    """Force completion: a VALUE read of one element.  block_until_ready can
    return early through the axon remote-device tunnel (see bfs_tpu.bench),
    so timing must read data back."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(leaf.reshape(-1)[:1])


def timeit(name, fn, *args, repeats=5, iters=8):
    """Median time per call: ``iters`` back-to-back dispatches share ONE
    value-read sync (device stream executes them serially), amortizing the
    tunnel round-trip latency out of the per-call number."""
    fn_j = jax.jit(fn)
    out = fn_j(*args)
    _sync(out)  # compile + settle
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn_j(*args)
        _sync(out)
        times.append((time.perf_counter() - t0) / iters)
    t = float(np.median(times))
    print(f"{name:35s} {t * 1e3:9.2f} ms")
    return t


def main():
    scale = int(os.environ.get("BENCH_SCALE", "24"))
    ef = int(os.environ.get("BENCH_EDGE_FACTOR", "6"))
    backend = _generator_backend()
    key = f"{backend}_s{scale}_ef{ef}_seed42_block8192"
    dg, source = load_or_build(scale, ef, 42, 8 * 1024, backend)
    rg, _ = load_or_build_relay(dg, key)
    v = rg.num_vertices
    print(f"V={v} E={rg.num_edges} vperm={rg.vperm_size} net={rg.net_size} "
          f"m2={rg.m2} out_classes={len(rg.out_classes)} in_classes={len(rg.in_classes)}")

    from bfs_tpu.ops.relay import valid_slot_words

    vperm_masks = jnp.asarray(rg.vperm_masks)
    net_masks = jnp.asarray(rg.net_masks)
    valid_words = jnp.asarray(valid_slot_words(rg.src_l1, rg.net_size))
    rng = np.random.default_rng(0)
    frontier = jnp.asarray(rng.random(v + 1) < 0.3)

    # Whole candidate pipeline.  All device tensors are ARGUMENTS — a
    # closed-over concrete array would be baked into the program as a
    # constant (5.5GB at scale 24, breaking the remote compile transport).
    def whole(frontier, vperm_masks, net_masks, valid_words):
        return relay_candidates(
            frontier, num_vertices=v, vperm_masks=vperm_masks,
            vperm_size=rg.vperm_size, out_classes=rg.out_classes,
            net_masks=net_masks, net_size=rg.net_size, m2=rg.m2,
            in_classes=rg.in_classes, valid_words=valid_words,
        )

    timeit("relay_candidates (whole)", whole, frontier, vperm_masks, net_masks, valid_words)

    # Phase 1: frontier -> out-order bits (vperm route)
    def phase_vperm(frontier, vperm_masks):
        fbits = frontier[:v].astype(jnp.uint8)
        fbits = jnp.concatenate(
            [fbits, jnp.zeros(rg.vperm_size - v, dtype=jnp.uint8)]
        )
        return unpack_bits(
            apply_benes(pack_bits(fbits, rg.vperm_size), vperm_masks, rg.vperm_size),
            rg.vperm_size,
        )

    fout = jax.jit(phase_vperm)(frontier, vperm_masks)
    timeit("  vperm (pack+route+unpack)", phase_vperm, frontier, vperm_masks)

    # Phase 2: class broadcast -> l2 bits
    def phase_broadcast(fout):
        parts = []
        for cs in rg.out_classes:
            blk = fout[cs.va : cs.vb]
            if cs.vertex_major:
                parts.append(
                    jnp.broadcast_to(blk[:, None], (cs.count, cs.width)).reshape(-1)
                )
            else:
                parts.append(
                    jnp.broadcast_to(blk[None, :], (cs.width, cs.count)).reshape(-1)
                )
        parts.append(jnp.zeros(rg.net_size - rg.m2, dtype=jnp.uint8))
        return jnp.concatenate(parts)

    l2 = jax.jit(phase_broadcast)(fout)
    timeit("  broadcast (l2 build)", phase_broadcast, fout)

    # Phase 3: big network
    def phase_pack(l2):
        return pack_bits(l2, rg.net_size)

    l2w = jax.jit(phase_pack)(l2)
    timeit("  pack_bits(l2)", phase_pack, l2)

    def phase_net(l2w, net_masks):
        return apply_benes(l2w, net_masks, rg.net_size)

    l1w = jax.jit(phase_net)(l2w, net_masks)
    timeit("  apply_benes(net)", phase_net, l2w, net_masks)

    def phase_unpack(l1w):
        return unpack_bits(l1w, rg.net_size)

    l1bits = jax.jit(phase_unpack)(l1w)
    timeit("  unpack_bits(l1)", phase_unpack, l1w)

    # Phase 4: class row-min (iota slot candidates; see ops/relay.py)
    from bfs_tpu.ops.relay import _class_slot_iota

    def phase_rowmin(l1bits):
        cands = []
        for cs in rg.in_classes:
            seg = l1bits[cs.sa : cs.sb]
            if cs.vertex_major:
                bits = seg.reshape(cs.count, cs.width)
                cands.append(
                    jnp.min(jnp.where(bits != 0, _class_slot_iota(cs), INT32_MAX), axis=1)
                )
            else:
                bits = seg.reshape(cs.width, cs.count)
                cands.append(
                    jnp.min(jnp.where(bits != 0, _class_slot_iota(cs), INT32_MAX), axis=0)
                )
        return jnp.concatenate(cands)

    timeit("  rowmin", phase_rowmin, l1bits)

    # Single-stage butterfly costs at the three distance regimes
    nw = rg.net_size // 32
    words = l1w
    m0 = net_masks[0]

    def bf_bit(words, m):  # d >= nw: bit-position butterfly
        sh = jnp.uint32(4)
        t = (words ^ (words >> sh)) & m
        return words ^ t ^ (t << sh)

    timeit("  one bitpos stage (elementwise)", bf_bit, words, m0)

    r = nw // 128
    def bf_lane(words, m):  # d < 128 lane roll
        x = words.reshape(r, 128)
        mm = m.reshape(r, 128)
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
        has = (lane & 8) != 0
        partner = jnp.where(has, jnp.roll(x, 8, axis=1), jnp.roll(x, -8, axis=1))
        mb = jnp.where(has, jnp.roll(mm, 8, axis=1), mm)
        return (x ^ ((x ^ partner) & mb)).reshape(-1)

    timeit("  one lane-roll stage", bf_lane, words, m0)

    def bf_row(words, m):  # 128 <= d < nw: row-block roll
        x = words.reshape(r, 128)
        mm = m.reshape(r, 128)
        row = jax.lax.broadcasted_iota(jnp.int32, (r, 1), 0)
        has = (row & 64) != 0
        partner = jnp.where(has, jnp.roll(x, 64, axis=0), jnp.roll(x, -64, axis=0))
        mb = jnp.where(has, jnp.roll(mm, 64, axis=0), mm)
        return (x ^ ((x ^ partner) & mb)).reshape(-1)

    timeit("  one row-roll stage", bf_row, words, m0)

    # Bandwidth reference: same-size elementwise xor
    big = jnp.asarray(rng.integers(0, 2**32, size=nw, dtype=np.uint32))

    def xor2(a, b):
        return a ^ b

    timeit("  ref: xor of two uint32[nw]", xor2, big, words)


if __name__ == "__main__":
    main()
