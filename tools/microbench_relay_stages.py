"""Stage-level timing of the relay superstep on the real TPU.

Loads the cached relay layout for a bench config and times each phase of
relay_candidates (vperm route, class broadcast, pack, big Beneš route,
unpack, class row-min) plus the fused whole, to locate the gap between the
measured superstep cost and the HBM-bandwidth floor.

Methodology: through the axon remote-device tunnel a PROGRAM DISPATCH costs
~20 ms — more than most phases — so per-call timing of single ops measures
the tunnel, not the TPU.  Every phase is therefore run K times inside ONE
compiled program (`lax.fori_loop` whose carry folds the phase output back
into its input, defeating DCE/CSE), so dispatch cost amortizes to noise and
the loop body time is the real per-iteration cost.

Usage: BENCH_SCALE=24 BENCH_EDGE_FACTOR=6 python tools/microbench_relay_stages.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bfs_tpu.bench import _generator_backend, load_or_build, load_or_build_relay
from bfs_tpu.ops.relay import (
    INT32_MAX,
    _class_slot_iota,
    apply_benes,
    pack_bits,
    relay_candidates,
    unpack_bits,
    valid_slot_words,
)

K = int(os.environ.get("MB_ITERS", "8"))
REPEATS = int(os.environ.get("MB_REPEATS", "3"))


def _sync(out):
    """Force completion: a VALUE read of one element (block_until_ready can
    return early through the axon tunnel)."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(leaf.reshape(-1)[:1])


def timeit_loop(name, phase, x0, *consts, bytes_per_iter=None):
    """Median per-iteration time of ``phase(x, *consts) -> x`` run K times
    inside one jitted fori_loop; reports GB/s when given bytes_per_iter."""

    @jax.jit
    def looped(x, *consts):
        return jax.lax.fori_loop(0, K, lambda _, c: phase(c, *consts), x)

    out = looped(x0, *consts)
    _sync(out)  # compile + settle
    times = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        out = looped(x0, *consts)
        _sync(out)
        times.append((time.perf_counter() - t0) / K)
    t = float(np.median(times))
    bw = f"  ({bytes_per_iter / t / 1e9:7.1f} GB/s)" if bytes_per_iter else ""
    print(f"{name:38s} {t * 1e3:9.2f} ms{bw}")
    return t


def main():
    scale = int(os.environ.get("BENCH_SCALE", "24"))
    ef = int(os.environ.get("BENCH_EDGE_FACTOR", "6"))
    backend = _generator_backend()
    key = f"{backend}_s{scale}_ef{ef}_seed42_block8192"
    dg, source = load_or_build(scale, ef, 42, 8 * 1024, backend)
    rg, _ = load_or_build_relay(dg, key)
    v = rg.num_vertices
    net = rg.net_size
    nw = net // 32
    print(
        f"V={v} E={rg.num_edges} vperm={rg.vperm_size} net={net} "
        f"m2={rg.m2} out_classes={len(rg.out_classes)} in_classes={len(rg.in_classes)} "
        f"K={K}"
    )
    from bfs_tpu.ops.benes_pallas import local_stage_run, pallas_enabled

    lo, hi = local_stage_run(net)
    n_stages = 2 * (int(net).bit_length() - 1) - 1
    print(f"pallas={pallas_enabled()} local_run=[{lo},{hi}) of {n_stages} stages")

    vperm_masks = jnp.asarray(rg.vperm_masks)
    net_masks = jnp.asarray(rg.net_masks)
    valid_words = jnp.asarray(valid_slot_words(rg.src_l1, net))
    rng = np.random.default_rng(0)
    frontier = jnp.asarray(rng.random(v + 1) < 0.3)

    # ---- whole candidate pipeline (frontier -> frontier fold) ------------
    def whole(frontier, vperm_masks, net_masks, valid_words):
        cand = relay_candidates(
            frontier, num_vertices=v, vperm_masks=vperm_masks,
            vperm_size=rg.vperm_size, out_classes=rg.out_classes,
            net_masks=net_masks, net_size=rg.net_size, m2=rg.m2,
            in_classes=rg.in_classes, valid_words=valid_words,
        )
        return frontier.at[:v].set(frontier[:v] ^ (cand != INT32_MAX))

    timeit_loop(
        "relay_candidates (whole)", whole, frontier,
        vperm_masks, net_masks, valid_words,
    )

    # ---- phase 1: vperm (pack + route + unpack) --------------------------
    def phase_vperm(fr, vperm_masks):
        fbits = fr[:v].astype(jnp.uint8)
        fbits = jnp.concatenate(
            [fbits, jnp.zeros(rg.vperm_size - v, dtype=jnp.uint8)]
        )
        fout = unpack_bits(
            apply_benes(pack_bits(fbits, rg.vperm_size), vperm_masks, rg.vperm_size),
            rg.vperm_size,
        )
        return fr.at[:v].set(fout[:v] != 0)

    timeit_loop("  vperm (pack+route+unpack)", phase_vperm, frontier, vperm_masks)

    fbits = jnp.asarray((rng.random(rg.vperm_size) < 0.3).astype(np.uint8))

    # ---- phase 2: class broadcast (fout -> l2, fold back) ----------------
    def phase_broadcast(fout):
        parts = []
        for cs in rg.out_classes:
            blk = fout[cs.va : cs.vb]
            if cs.vertex_major:
                parts.append(
                    jnp.broadcast_to(blk[:, None], (cs.count, cs.width)).reshape(-1)
                )
            else:
                parts.append(
                    jnp.broadcast_to(blk[None, :], (cs.width, cs.count)).reshape(-1)
                )
        parts.append(jnp.zeros(rg.net_size - rg.m2, dtype=jnp.uint8))
        l2 = jnp.concatenate(parts)
        return fout ^ l2[: rg.vperm_size]

    timeit_loop(
        "  broadcast (l2 build)", phase_broadcast, fbits,
        bytes_per_iter=net + rg.vperm_size,
    )

    l2 = jnp.asarray((rng.random(net) < 0.3).astype(np.uint8))
    words0 = jnp.asarray(rng.integers(0, 2**32, size=nw, dtype=np.uint32))

    # ---- phase 3a: pack_bits(l2) -----------------------------------------
    def phase_pack(l2):
        w = pack_bits(l2, net)
        return l2.at[:nw].set(l2[:nw] ^ w.astype(jnp.uint8))

    timeit_loop("  pack_bits(l2)", phase_pack, l2, bytes_per_iter=net + nw * 4)

    # ---- phase 3b: big Beneš network -------------------------------------
    def phase_net(w, net_masks):
        return apply_benes(w, net_masks, net)

    timeit_loop(
        "  apply_benes(net)", phase_net, words0, net_masks,
        bytes_per_iter=net_masks.size * 4 + 2 * nw * 4,
    )

    # ---- phase 3c: unpack ------------------------------------------------
    def phase_unpack(w):
        bits = unpack_bits(w, net)
        return w ^ pack_bits(bits, net)  # unpack + pack pair; report half

    t_pair = timeit_loop(
        "  unpack+pack pair", phase_unpack, words0,
        bytes_per_iter=2 * (net + nw * 4),
    )
    print(f"{'  (implied one direction)':38s} {t_pair / 2 * 1e3:9.2f} ms")

    # ---- phase 4: class row-min (iota slot candidates) -------------------
    l1bits = jnp.asarray((rng.random(net) < 0.3).astype(np.uint8))

    def phase_rowmin(l1bits):
        cands = []
        for cs in rg.in_classes:
            seg = l1bits[cs.sa : cs.sb]
            if cs.vertex_major:
                bits = seg.reshape(cs.count, cs.width)
                cands.append(
                    jnp.min(
                        jnp.where(bits != 0, _class_slot_iota(cs), INT32_MAX), axis=1
                    )
                )
            else:
                bits = seg.reshape(cs.width, cs.count)
                cands.append(
                    jnp.min(
                        jnp.where(bits != 0, _class_slot_iota(cs), INT32_MAX), axis=0
                    )
                )
        cand = jnp.concatenate(cands)
        return l1bits.at[:v].set(l1bits[:v] ^ cand.astype(jnp.uint8))

    timeit_loop("  rowmin", phase_rowmin, l1bits, bytes_per_iter=net + v * 4)

    # ---- single-stage butterfly costs at the three distance regimes ------
    m0 = net_masks[0]

    def bf_bit(w, m):  # d >= nw: bit-position butterfly
        sh = jnp.uint32(4)
        t = (w ^ (w >> sh)) & m
        return w ^ t ^ (t << sh)

    timeit_loop(
        "  one bitpos stage (elementwise)", bf_bit, words0, m0,
        bytes_per_iter=3 * nw * 4,
    )

    r = nw // 128

    def bf_lane(w, m):  # d < 128: lane roll
        x = w.reshape(r, 128)
        mm = m.reshape(r, 128)
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
        has = (lane & 8) != 0
        partner = jnp.where(has, jnp.roll(x, 8, axis=1), jnp.roll(x, -8, axis=1))
        mb = jnp.where(has, jnp.roll(mm, 8, axis=1), mm)
        return (x ^ ((x ^ partner) & mb)).reshape(-1)

    timeit_loop("  one lane-roll stage", bf_lane, words0, m0, bytes_per_iter=3 * nw * 4)

    def bf_row(w, m):  # 128 <= d < nw: row-block roll
        x = w.reshape(r, 128)
        mm = m.reshape(r, 128)
        row = jax.lax.broadcasted_iota(jnp.int32, (r, 1), 0)
        has = (row & 64) != 0
        partner = jnp.where(has, jnp.roll(x, 64, axis=0), jnp.roll(x, -64, axis=0))
        mb = jnp.where(has, jnp.roll(mm, 64, axis=0), mm)
        return (x ^ ((x ^ partner) & mb)).reshape(-1)

    timeit_loop("  one row-roll stage", bf_row, words0, m0, bytes_per_iter=3 * nw * 4)

    # ---- bandwidth reference: same-size elementwise xor ------------------
    big = jnp.asarray(rng.integers(0, 2**32, size=nw, dtype=np.uint32))

    def xor2(a, b):
        return a ^ b

    timeit_loop(
        "  ref: xor of two uint32[nw]", xor2, words0, big, bytes_per_iter=3 * nw * 4
    )


if __name__ == "__main__":
    main()
