"""Per-phase timing of the v4 relay superstep on the real TPU.

Thin CLI over the shared phase ledger (bfs_tpu/profiling.py — the same
phase-isolated K-loop jits the bench ships as details.superstep_phases):
vperm / broadcast / net-apply / masked row-min / state-update (both
layouts, with the analytic dist/parent byte halving) + the full dense
superstep cross-check.  P_SCALE / P_EF select the cached bench graph.
"""
import json
import os
import sys

sys.path.insert(0, "/root/repo")
import jax

jax.config.update("jax_compilation_cache_dir", "/root/repo/.bench_cache/xla")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

from bfs_tpu.bench import load_or_build, load_or_build_relay
from bfs_tpu.models.bfs import RelayEngine
from bfs_tpu.profiling import superstep_phase_ledger

scale = int(os.environ.get("P_SCALE", "20"))
ef = int(os.environ.get("P_EF", "16"))
loops = int(os.environ.get("P_LOOPS", "16"))
dg, source = load_or_build(scale, ef, 42, 8192, "native")
key = f"native_s{scale}_ef{ef}_seed42_block8192"
rg, _ = load_or_build_relay(dg, key)
eng = RelayEngine(rg)

ledger = superstep_phase_ledger(eng, loops=loops, repeats=3)
for name, ph in ledger["phases"].items():
    print(f"{name:16s}: {ph['seconds'] * 1e3:8.2f} ms/superstep")
su = ledger["phases"]["state_update"]
print(
    f"state update packed {su['packed']['seconds'] * 1e3:.2f} ms "
    f"({su['packed']['bytes']['total'] >> 20} MB) vs unpacked "
    f"{su['unpacked']['seconds'] * 1e3:.2f} ms "
    f"({su['unpacked']['bytes']['total'] >> 20} MB) — dist/parent bytes "
    f"ratio {su['dist_parent_bytes_ratio']:.1f}x"
)
print(
    f"sum of phases {ledger['sum_of_phases_seconds'] * 1e3:.2f} ms vs "
    f"full superstep {ledger['full_superstep_seconds'] * 1e3:.2f} ms"
)
print(json.dumps(ledger))
