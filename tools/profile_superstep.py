"""Per-piece timing of the v4 relay superstep on the real TPU (K-loop
amortized — the tunnel costs ~107ms per sync)."""
import os, sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np
jax.config.update("jax_compilation_cache_dir", "/root/repo/.bench_cache/xla")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

from bfs_tpu.bench import load_or_build, load_or_build_relay
from bfs_tpu.models.bfs import RelayEngine, _superstep_fn, _relay_static
from bfs_tpu.ops import relay as R
from bfs_tpu.ops import relay_pallas as RP

scale = int(os.environ.get("P_SCALE", "20"))
ef = int(os.environ.get("P_EF", "16"))
dg, source = load_or_build(scale, ef, 42, 8192, "native")
key = f"native_s{scale}_ef{ef}_seed42_block8192"
rg, _ = load_or_build_relay(dg, key)
eng = RelayEngine(rg)
static = eng._static
K = 16
OPTS = {"xla_tpu_scoped_vmem_limit_kib": "65536"}

def timeit(make_fn, args, label):
    fn = jax.jit(make_fn)
    c = fn.lower(*args).compile(compiler_options=OPTS)
    r = c(*args); _ = np.asarray(jax.device_get(jax.tree_util.tree_leaves(r)[0])).ravel()[0]
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        r = c(*args)
        _ = np.asarray(jax.device_get(jax.tree_util.tree_leaves(r)[0])).ravel()[0]
        ts.append(time.perf_counter() - t0)
    t = (min(ts) - 0.107) / K  # remove tunnel latency, amortize K
    print(f"{label:28s}: {t*1000:7.2f} ms/iter  (raw {min(ts)*1000:.0f} ms)")
    return t

vperm_m, net_m, valid = eng._tensors
vp_static = RP.pass_static(rg.vperm_table, rg.vperm_size) if isinstance(vperm_m, tuple) else None
net_static = RP.pass_static(rg.net_table, rg.net_size) if isinstance(net_m, tuple) else None
print("pallas vperm:", vp_static is not None, " pallas net:", net_static is not None)

nwv = rg.vr // 32
fw0 = jnp.zeros(rg.vperm_size // 32, jnp.uint32).at[0].set(1)

def k_net(l2, *m):
    def body(i, x):
        y = RP.apply_benes_fused(x, m, net_static, rg.net_size) if net_static else R.apply_benes_std(x, m[0], rg.net_table, rg.net_size)
        return y ^ (x & 1)
    return jax.lax.fori_loop(0, K, body, l2)

l2_0 = jnp.zeros(rg.net_size // 32, jnp.uint32)
net_args = (l2_0, *net_m) if isinstance(net_m, tuple) else (l2_0, net_m)
timeit(k_net, net_args, "big net (fused passes)")

def k_vperm(x, *m):
    def body(i, x):
        y = RP.apply_benes_fused(x, m, vp_static, rg.vperm_size) if vp_static else R.apply_benes_std(x, m[0], rg.vperm_table, rg.vperm_size)
        return y ^ (x & 1)
    return jax.lax.fori_loop(0, K, body, x)

vp_args = (fw0, *vperm_m) if isinstance(vperm_m, tuple) else (fw0, vperm_m)
timeit(k_vperm, vp_args, "vperm (fused passes)")

def k_bcast(y):
    def body(i, c):
        l2 = R.broadcast_l2(y ^ c, rg.out_classes, rg.net_size, rg.out_space)
        return c ^ (l2[:y.shape[0]] & 1)
    return jax.lax.fori_loop(0, K, body, jnp.zeros_like(y))

y0 = jnp.zeros(rg.vperm_size // 32, jnp.uint32)
timeit(k_bcast, (y0,), "broadcast (XLA tiles)")

def k_rowmin(l1, valid):
    def body(i, c):
        cand = R.rowmin_candidates(l1 ^ c[: l1.shape[0]], valid, rg.in_classes, rg.vr)
        return c.at[: cand.shape[0]].set(c[: cand.shape[0]] ^ (cand.astype(jnp.uint32) & 1))
    return jax.lax.fori_loop(0, K, body, jnp.zeros(max(l1.shape[0], rg.vr), jnp.uint32))

l1_0 = jnp.zeros(rg.net_size // 32, jnp.uint32)
timeit(k_rowmin, (l1_0, valid), "rowmin (XLA classes)")

# full dense superstep
superstep = _superstep_fn(static, eng._use_pallas())
def k_step(dist, parent, fwords, *m):
    vm = m[:len(vperm_m)] if isinstance(vperm_m, tuple) else m[0]
    nm = m[len(vperm_m):-1] if isinstance(vperm_m, tuple) else m[1]
    vv = m[-1]
    st0 = R.RelayState(dist, parent, fwords, jnp.int32(0), jnp.bool_(True))
    def body(i, st):
        s2 = superstep(st, vm if isinstance(vperm_m, tuple) else m[0],
                       nm if isinstance(net_m, tuple) else m[1], vv)
        return R.RelayState(s2.dist, s2.parent, s2.fwords, st.level, st.changed)
    out = jax.lax.fori_loop(0, K, body, st0)
    return out.dist
d0 = jnp.full(rg.vr, np.int32(2**31-1), jnp.int32)
p0 = jnp.full(rg.vr, -1, jnp.int32)
f0 = jnp.zeros(nwv, jnp.uint32).at[0].set(1)
if isinstance(vperm_m, tuple):
    args = (d0, p0, f0, *vperm_m, *net_m, valid)
else:
    args = (d0, p0, f0, vperm_m, net_m, valid)
timeit(k_step, args, "FULL dense superstep")
