#!/usr/bin/env python
"""Generate the repo's test-sets/ datasets (Sedgewick text format).

  * tinyCG.txt   — the 6-vertex/8-edge worked example from the reference
    paper (docs/BigData_Project.pdf §1.2 Table 1; also Sedgewick &
    Wayne, Algorithms 4th ed.).  Written from the embedded edge list.
  * randomG.txt  — a generated stand-in for the reference's mediumG.txt
    (same V=250 / E=1273 shape, seeded G(n,m)).
  * largeG.txt   — optional (--large): V=1e6 / E≈7.6e6 G(n,m), the shape of
    the reference's gitignored largeG (paper §1.5).

Usage: python tools/gen_datasets.py [--large] [--out test-sets]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

TINY_EDGES = [(0, 5), (2, 4), (2, 3), (1, 2), (0, 1), (3, 4), (3, 5), (0, 2)]


def write_edges(path: str, num_vertices: int, edges) -> None:
    with open(path, "w") as f:
        f.write(f"{num_vertices}\n{len(edges)}\n")
        for u, v in edges:
            f.write(f"{u} {v}\n")


def gnm_unique_edges(num_vertices: int, num_edges: int, seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    seen = set()
    out = []
    while len(out) < num_edges:
        u, v = rng.integers(0, num_vertices, size=2)
        if u == v:
            continue
        key = (min(u, v), max(u, v))
        if key in seen:
            continue
        seen.add(key)
        out.append((int(u), int(v)))
    return np.asarray(out)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "test-sets"))
    ap.add_argument("--large", action="store_true")
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)

    write_edges(os.path.join(args.out, "tinyCG.txt"), 6, TINY_EDGES)
    write_edges(
        os.path.join(args.out, "randomG.txt"), 250, gnm_unique_edges(250, 1273, seed=7)
    )
    if args.large:
        from bfs_tpu.graph.generators import gnm_graph  # fast non-unique variant

        g = gnm_graph(1_000_000, 7_586_063, seed=7)
        mask = g.src < g.dst
        write_edges(
            os.path.join(args.out, "largeG.txt"),
            1_000_000,
            np.stack([g.src[mask], g.dst[mask]], axis=1).tolist(),
        )
    print(f"datasets written to {args.out}")


if __name__ == "__main__":
    main()
