"""Terminal dashboard over a run's observability artifacts.

One screen for one run: the span timeline (per-phase wall time across
every process generation, stitched from the journal), the device-side
level curve (ASCII bars + packed-cap proximity + per-level TEPS when the
superstep profile timed the levels), and — when the journal's headline
or a ``--serve`` report file carries them — the serve percentiles.

    python tools/obs_dashboard.py <journal.jsonl>
    python tools/obs_dashboard.py <journal.jsonl> --serve loadgen_out.json

Reads journals directly through the lint-stub bootstrap (no jax import,
sub-100ms); ``bfs-tpu-obs trace`` writes the Perfetto JSON twin of the
timeline section.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "tools"))
import lint  # noqa: F401  (side effect: stub bfs_tpu parent package)

from bfs_tpu.obs.__main__ import _find_curve  # noqa: E402
from bfs_tpu.obs.telemetry import render_curve_ascii  # noqa: E402
from bfs_tpu.resilience.journal import read_records  # noqa: E402

BAR = 40


def _rule(title: str) -> str:
    return f"\n=== {title} " + "=" * max(4, 66 - len(title))


def span_timeline(records) -> str:
    """Per-name span aggregate across all journaled generations, widest
    first — the text twin of the Perfetto view."""
    events = []
    for rec in records:
        if rec["phase"].startswith("spans:"):
            events.extend(rec["payload"].get("events", ()))
    if not events:
        return "(no journaled spans — run with BFS_TPU_SPANS=1, the default)"
    gens = sorted({e.get("pid") for e in events})
    agg: dict[str, dict] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        a = agg.setdefault(e["name"], {"count": 0, "us": 0, "flushed": 0})
        a["count"] += 1
        a["us"] += e.get("dur", 0)
        if (e.get("args") or {}).get("flushed"):
            a["flushed"] += 1
    total = max(sum(a["us"] for a in agg.values()), 1)
    lines = [f"{len(events)} events over {len(gens)} process generation(s)"]
    for name, a in sorted(agg.items(), key=lambda kv: -kv[1]["us"]):
        bar = "#" * max(1, round(BAR * a["us"] / total))
        flush = f"  [{a['flushed']} flushed by signal]" if a["flushed"] else ""
        lines.append(
            f"  {name:<26} {a['us'] / 1e6:>9.3f}s x{a['count']:<3} {bar}{flush}"
        )
    markers = [e for e in events if e.get("ph") == "i"]
    if markers:
        lines.append(f"  {len(markers)} instant marker(s):")
        for e in markers[:10]:
            lines.append(f"    {e['name']} {e.get('args')}")
    return "\n".join(lines)


def curve_section(records) -> str:
    curve = _find_curve(records)
    if curve is None:
        return "(no level curve journaled — BENCH_LEVEL_CURVE=1 is the default)"
    out = [render_curve_ascii(curve)]
    if "cap_proximity" in curve:
        out.append(
            f"packed-cap proximity: {curve['levels']}/{curve.get('cap')} "
            f"({curve['cap_proximity']:.2f})"
        )
    if curve.get("per_level_teps"):
        out.append("per-level TEPS (frontier out-edges / profiled seconds):")
        for l, teps in sorted(
            curve["per_level_teps"].items(), key=lambda kv: int(kv[0])
        ):
            out.append(f"  L{int(l):>3} {teps / 1e6:>12.1f} M TEPS")
    if "occupancy_sum_matches_reference" in curve:
        out.append(
            "occupancy sum matches oracle component: "
            f"{curve['occupancy_sum_matches_reference']}"
        )
    return "\n".join(out)


def serve_section(records, serve_path: str) -> str:
    report = None
    if serve_path:
        with open(serve_path) as f:
            doc = json.load(f)
        report = doc.get("server_report", doc)
    else:
        for rec in records:
            if rec["phase"] == "headline":
                d = (rec["payload"].get("headline") or {}).get("details") or {}
                report = d.get("serve") or report
    if not isinstance(report, dict):
        return "(no serve report; pass --serve <loadgen output json>)"
    keys = (
        "queries", "served", "timeouts", "errors", "latency_p50_ms",
        "latency_p99_ms", "queue_wait_p99_ms", "batch_size_mean",
        "queries_per_sec", "compile_hit_rate", "result_cache_hit_rate",
    )
    lines = []
    for k in keys:
        if k in report:
            v = report[k]
            lines.append(
                f"  {k:<24} {v:.3f}" if isinstance(v, float) else f"  {k:<24} {v}"
            )
    ev = (report.get("counters") or {}).get("evictions")
    if ev is not None:
        lines.append(f"  {'evictions':<24} {ev}")
    return "\n".join(lines) if lines else "(serve report had no known fields)"


def headline_section(records) -> str:
    for rec in records:
        if rec["phase"] == "headline":
            doc = rec["payload"].get("headline") or {}
            return (
                f"{doc.get('metric')}: {doc.get('value', 0):.3e} "
                f"{doc.get('unit')} — check: "
                f"{(doc.get('details') or {}).get('check')!r}"
            )
    return "(run not finished — no headline record yet)"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("journal", help="a bench RunJournal .jsonl file")
    ap.add_argument("--serve", default="", help="loadgen output JSON")
    args = ap.parse_args(argv)
    records = read_records(args.journal)
    if not records:
        print(f"no readable records in {args.journal}", file=sys.stderr)
        return 1
    print(f"run: {os.path.basename(args.journal)} ({len(records)} records)")
    print(headline_section(records))
    print(_rule("span timeline"))
    print(span_timeline(records))
    print(_rule("level curve"))
    print(curve_section(records))
    print(_rule("serve percentiles"))
    print(serve_section(records, args.serve))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
