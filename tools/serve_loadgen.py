"""Load generator for the bfs_tpu.serve micro-batching query server.

Replays a configurable single/multi-source query mix from concurrent
submitter threads against an in-process :class:`~bfs_tpu.serve.BfsServer`,
oracle-checks EVERY reply (distances bit-exact vs ``queue_bfs``, parents
through the ported algs4 ``check()`` invariants — a wrong answer is a hard
failure, same gating discipline as bench.py), and prints a
throughput/latency report: p50/p99, queries/sec, batch-size distribution,
and the steady-state compile-cache hit rate.

The warmup phase deterministically compiles every power-of-two batch
bucket (pause → stage b singles → resume = one batch of exactly b), so the
steady phase must run at a 100% compile-cache hit rate — the acceptance
gate this tool exists to demonstrate.  Exit code 1 on any wrong answer or
a sub-100% steady-state hit rate.

``--replicas N`` (ISSUE 20) switches to FLEET mode: the same oracle-checked
discipline driven through a :class:`~bfs_tpu.serve.FleetRouter` of N
replicas — a point-query-heavy mix through the landmark label tier
(``query_dist``), a mid-load rolling epoch swap (re-register under load;
later replicas warm-hit the shared sidecar store), and, with >= 2
replicas, an induced replica failure mid-run that MUST surface as router
failovers, never as a wrong or lost answer.  Compare a ``--replicas 1``
capture against ``--replicas 2`` for the QPS-scaling / p99-held evidence
pair (SERVE_FLEET_x*.json).

Usage (mirrors the tier-1 test platform: 8 virtual CPU devices):
    JAX_PLATFORMS=cpu python tools/serve_loadgen.py --scale 10 \
        --requests 200 --concurrency 8 --multi-frac 0.25
    JAX_PLATFORMS=cpu python tools/serve_loadgen.py --scale 10 \
        --replicas 2 --requests 200 --concurrency 8
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

# Mirror tests/conftest.py: virtual 8-device CPU mesh, set BEFORE jax loads.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from bfs_tpu.graph.generators import rmat_graph  # noqa: E402
from bfs_tpu.oracle.bfs import check, queue_bfs  # noqa: E402
from bfs_tpu.serve import AdmissionError, BfsServer, GraphRegistry  # noqa: E402
from bfs_tpu.utils.metrics import percentile  # noqa: E402


def make_queries(rng, v: int, n: int, args):
    """The replayed mix: singles, collapsed multis, per-source-tree multis.
    Sources are drawn from a limited pool so repeats exercise the result
    LRU like real hot-key traffic would."""
    pool = rng.integers(0, v, size=max(args.source_pool, 4))
    queries = []
    for _ in range(n):
        r = rng.random()
        if r < args.multi_frac:
            width = int(rng.integers(2, args.multi_width + 1))
            srcs = rng.choice(pool, size=width).tolist()
            mode = "collapse" if rng.random() < 0.5 else "tree"
            queries.append((srcs, mode))
        else:
            queries.append(([int(rng.choice(pool))], "single"))
    return queries


def oracle_check(graph, oracle_cache, srcs, mode, reply) -> list[str]:
    """Every reply is verified; returns a list of violations (empty = OK)."""
    key = tuple(sorted(set(srcs)))
    if mode in ("single", "collapse"):
        if key not in oracle_cache:
            oracle_cache[key] = queue_bfs(graph, list(key))[0]
        errs = []
        if not np.array_equal(reply.dist, oracle_cache[key]):
            errs.append(f"dist mismatch for sources {srcs}")
        errs += check(graph, reply.dist, reply.parent, srcs)
        return errs
    errs = []
    for i, s in enumerate(srcs):  # tree mode: each row is one source's tree
        if (s,) not in oracle_cache:
            oracle_cache[(s,)] = queue_bfs(graph, s)[0]
        if not np.array_equal(reply.dist[i], oracle_cache[(s,)]):
            errs.append(f"tree dist mismatch for source {s}")
        errs += check(graph, reply.dist[i], reply.parent[i], s)
    return errs


def warmup(server, name: str, v: int, max_batch: int) -> int:
    """Compile every power-of-two bucket ≤ max_batch deterministically:
    stage exactly b singles while paused, resume, collect — one batch of b
    per bucket.  Returns the number of warmup queries."""
    total = 0
    b = 1
    while True:
        stage = min(b, max_batch)  # a full tick covers the top bucket even
        server.pause()             # when max_batch is not a power of two
        # Distinct sources across rounds: a repeated source would hit the
        # result LRU, never enqueue, and shrink the staged batch below b —
        # leaving that bucket uncompiled for the steady phase.
        futs = [server.query(name, (total + s) % v) for s in range(stage)]
        server.resume()
        for f in futs:
            f.result(timeout=600)
        total += stage
        if b >= max_batch:
            return total
        b *= 2


def fleet_main(args) -> int:
    """FLEET mode: N routed replicas, point-query-heavy, every answer
    oracle-checked; a rolling epoch swap and an induced replica failure
    land mid-load.  Exit 1 on any wrong/lost answer, or when the induced
    failure produced zero router failovers."""
    from bfs_tpu.serve import FleetRouter

    if args.landmarks > 0:
        os.environ["BFS_TPU_LABELS"] = str(args.landmarks)
    rng = np.random.default_rng(args.seed)
    t0 = time.perf_counter()
    graph = rmat_graph(args.scale, args.edge_factor, seed=args.seed)
    v = graph.num_vertices
    name = f"rmat{args.scale}"
    print(
        f"graph: R-MAT scale {args.scale} ef {args.edge_factor} "
        f"(V={v}, E={graph.num_edges} directed) built in "
        f"{time.perf_counter() - t0:.1f}s",
        flush=True,
    )

    pool = rng.integers(0, v, size=max(args.source_pool, 4))

    def make_mix(n: int) -> list:
        mix = []
        for _ in range(n):
            if rng.random() < args.point_frac:
                mix.append(
                    ("point", int(rng.choice(pool)), int(rng.choice(pool)))
                )
            else:
                mix.append(("full", int(rng.choice(pool)), -1))
        return mix

    reqs = make_mix(args.requests)
    swap_at = (
        int(args.requests * args.swap_at) if args.swap_at >= 0 else -1
    )
    chaos_n = (
        int(args.requests * args.chaos_frac)
        if args.chaos_frac > 0 and args.replicas >= 2 else 0
    )

    wrong: list[str] = []
    latencies: list[float] = []
    lock = threading.Lock()
    oracle_cache: dict = {}

    def truth_row(s: int) -> np.ndarray:
        if (s,) not in oracle_cache:
            oracle_cache[(s,)] = queue_bfs(graph, s)[0]
        return oracle_cache[(s,)]

    with FleetRouter(
        replicas=args.replicas,
        layout_cache=args.cache_dir or None,
        engine=args.engine,
        max_batch=args.max_batch,
        tick_s=args.tick_ms / 1e3,
        queue_depth=args.queue_depth,
        watchdog_s=args.watchdog_s,
    ) as rt:
        t_reg = time.perf_counter()
        rt.register(name, graph)
        print(
            f"fleet: {args.replicas} replicas registered in "
            f"{time.perf_counter() - t_reg:.2f}s "
            f"(labels K={args.landmarks})",
            flush=True,
        )
        # Warm every replica directly (the router would only warm the
        # hash-selected one): every power-of-two batch bucket via the
        # classic staged warmup, plus the label-lookup shape.
        t0 = time.perf_counter()
        nwarm = 0
        for srv in rt.servers:
            nwarm += warmup(srv, name, v, args.max_batch)
            srv.query_dist(name, 0, min(1, v - 1)).result(timeout=600)
        print(
            f"warmup: {nwarm} queries over {args.replicas} replicas in "
            f"{time.perf_counter() - t0:.1f}s",
            flush=True,
        )

        events = {"swapped_s": None}

        def _maybe_event(i: int) -> None:
            if i == swap_at:
                t = time.perf_counter()
                rt.register(name, graph)  # rolling epoch bump under load
                events["swapped_s"] = time.perf_counter() - t
                print(
                    f"epoch swap at request {i}: rolled "
                    f"{args.replicas} replicas in {events['swapped_s']:.2f}s",
                    flush=True,
                )

        def one_request(batch: list, latency_sink: list, i: int) -> None:
            kind, a, b = batch[i]
            t = time.perf_counter()
            if kind == "point":
                reply = rt.query_dist(name, a, b).result(
                    timeout=args.timeout_s + 60
                )
                lat = time.perf_counter() - t
                want = int(truth_row(a)[b])
                errs = (
                    []
                    if args.no_check or int(reply.dist) == want
                    else [
                        f"dist({a},{b}) = {reply.dist} "
                        f"({reply.method}), oracle says {want}"
                    ]
                )
            else:
                reply = rt.query(name, a).result(timeout=args.timeout_s + 60)
                lat = time.perf_counter() - t
                errs = []
                if not args.no_check:
                    if not np.array_equal(reply.dist, truth_row(a)):
                        errs.append(f"dist mismatch for source {a}")
                    errs += check(graph, reply.dist, reply.parent, [a])
            with lock:
                latency_sink.append(lat)
                wrong.extend(errs)

        def run_phase(batch: list, latency_sink: list,
                      with_events: bool) -> float:
            cursor = [0]

            def worker():
                while True:
                    with lock:
                        if cursor[0] >= len(batch):
                            return
                        i = cursor[0]
                        cursor[0] += 1
                    try:
                        if with_events:
                            _maybe_event(i)
                        one_request(batch, latency_sink, i)
                    except Exception as exc:
                        with lock:
                            wrong.append(
                                f"request {i} ({batch[i]}) failed: {exc!r}"
                            )

            t0 = time.perf_counter()
            threads = [
                threading.Thread(target=worker)
                for _ in range(args.concurrency)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return time.perf_counter() - t0

        steady_s = run_phase(reqs, latencies, True)

        # ---- chaos phase (untimed for QPS): one replica down, every
        # request must complete through failover, still oracle-checked.
        chaos_latencies: list[float] = []
        chaos_s = None
        if chaos_n:
            # Close the server directly (NOT kill_replica): submits now
            # raise ServerClosed at admission — and in-flight chained
            # queries fail AFTER admission — which is exactly the
            # failover path the run must demonstrate.
            rt.servers[-1].close()
            print(
                f"chaos: replica {len(rt.servers) - 1} closed; driving "
                f"{chaos_n} requests through failover",
                flush=True,
            )
            chaos_s = run_phase(make_mix(chaos_n), chaos_latencies, False)
        report = rt.report()

    router = report["router"]
    label_counters = {
        k: sum(
            rep["counters"].get(k, 0) for rep in report["replicas"]
        )
        for k in ("label_hits", "label_fallbacks", "label_misses",
                  "label_builds", "label_build_cache_hits")
    }
    out = {
        "mode": "fleet",
        "replicas": args.replicas,
        "requests": args.requests,
        "concurrency": args.concurrency,
        "point_frac": args.point_frac,
        "landmarks": args.landmarks,
        "oracle_checked": 0 if args.no_check else args.requests + chaos_n,
        "wrong_answers": len(wrong),
        "steady_seconds": steady_s,
        "queries_per_sec": args.requests / steady_s if steady_s > 0 else 0.0,
        "latency_p50_ms": percentile(latencies, 50) * 1e3,
        "latency_p99_ms": percentile(latencies, 99) * 1e3,
        "epoch_swap_seconds": events["swapped_s"],
        "chaos_requests": chaos_n,
        "chaos_seconds": chaos_s,
        "chaos_latency_p99_ms": (
            percentile(chaos_latencies, 99) * 1e3 if chaos_latencies else None
        ),
        "router_failovers": router.get("router_failovers", 0),
        "router_breaker_opens": router.get("router_breaker_opens", 0),
        "router_rolling_registers": router.get("router_rolling_registers", 0),
        "labels": label_counters,
        "router_report": router,
    }
    print(json.dumps(out, indent=2, sort_keys=True))
    for msg in wrong[:10]:
        print(f"WRONG: {msg}", file=sys.stderr)
    if wrong:
        return 1
    if chaos_n and not router.get("router_failovers", 0):
        print(
            "FAIL: induced replica failure produced zero router failovers",
            file=sys.stderr,
        )
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scale", type=int, default=10, help="R-MAT scale")
    ap.add_argument("--edge-factor", type=int, default=8)
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--multi-frac", type=float, default=0.25)
    ap.add_argument("--multi-width", type=int, default=4)
    ap.add_argument("--source-pool", type=int, default=64,
                    help="distinct sources in the mix (repeats hit the LRU)")
    ap.add_argument("--engine", default="pull", choices=("pull", "push", "relay"))
    ap.add_argument("--max-batch", type=int, default=16)
    ap.add_argument("--tick-ms", type=float, default=2.0)
    ap.add_argument("--timeout-s", type=float, default=120.0)
    ap.add_argument("--queue-depth", type=int, default=4096)
    ap.add_argument("--budget-mb", type=int, default=0)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--no-check", action="store_true")
    ap.add_argument("--breaker-failures", type=int, default=3,
                    help="consecutive permanent failures per executable "
                    "before its circuit opens")
    ap.add_argument("--breaker-cooldown-s", type=float, default=5.0,
                    help="open-circuit cooldown before the half-open canary")
    ap.add_argument("--watchdog-s", type=float, default=60.0,
                    help="hung-call watchdog default budget (0 disables)")
    ap.add_argument("--verify-sample", type=int, default=0,
                    help="on-device integrity check every Kth executed "
                    "tick (0 disables); the run FAILS on any "
                    "integrity_failures — the device answered wrong")
    ap.add_argument("--cache-dir", default="",
                    help="persistent layout-bundle dir (default off; pass "
                    "a dir — e.g. .bench_cache/layout — to measure "
                    "warm-vs-cold registration across runs)")
    ap.add_argument("--replicas", type=int, default=0,
                    help="FLEET mode (ISSUE 20): drive a FleetRouter of N "
                    "replicas with a point-query-heavy mix, a mid-load "
                    "epoch swap, and (N >= 2) an induced replica failure; "
                    "0 = classic single-server mode")
    ap.add_argument("--point-frac", type=float, default=0.6,
                    help="fleet mode: fraction of requests that are "
                    "dist(u, v) point queries through the label tier")
    ap.add_argument("--landmarks", type=int, default=16,
                    help="fleet mode: landmark count for the label tier "
                    "(sets BFS_TPU_LABELS; 0 = exact-only)")
    ap.add_argument("--swap-at", type=float, default=0.5,
                    help="fleet mode: re-register the graph (rolling epoch "
                    "swap) after this fraction of requests (<0 disables)")
    ap.add_argument("--chaos-frac", type=float, default=0.2,
                    help="fleet mode, >= 2 replicas: after the timed "
                    "steady phase, close one replica and drive this "
                    "extra fraction of requests through the failover "
                    "path (0 disables); the run FAILS unless the router "
                    "failed over with zero wrong answers")
    args = ap.parse_args(argv)

    if args.replicas >= 1:
        return fleet_main(args)

    rng = np.random.default_rng(args.seed)
    t0 = time.perf_counter()
    graph = rmat_graph(args.scale, args.edge_factor, seed=args.seed)
    v = graph.num_vertices
    print(
        f"graph: R-MAT scale {args.scale} ef {args.edge_factor} "
        f"(V={v}, E={graph.num_edges} directed) built in "
        f"{time.perf_counter() - t0:.1f}s",
        flush=True,
    )

    registry = GraphRegistry(
        device_budget_bytes=args.budget_mb * (1 << 20) if args.budget_mb else None,
        layout_cache=args.cache_dir or None,
    )
    name = f"rmat{args.scale}"
    wrong: list[str] = []
    latencies: list[float] = []
    lock = threading.Lock()
    oracle_cache: dict = {}

    with BfsServer(
        registry,
        engine=args.engine,
        max_batch=args.max_batch,
        tick_s=args.tick_ms / 1e3,
        queue_depth=args.queue_depth,
        breaker_failures=args.breaker_failures,
        breaker_cooldown_s=args.breaker_cooldown_s,
        watchdog_s=args.watchdog_s,
        verify_sample=args.verify_sample,
    ) as server:
        t_reg = time.perf_counter()
        server.register(name, graph)
        server.query(name, 0).result(timeout=600)  # force the layout build
        from bfs_tpu.utils.metrics import artifact_report

        rep = artifact_report()
        li = server.registry.layout_info()
        flavor = (
            f"; builder={li.get('builder', 'host')}, "
            f"build {float(li.get('build_seconds', -1.0)):.2f}s"
            if li else ""  # non-relay engines build no relay layout
        )
        print(
            f"register+layout: {time.perf_counter() - t_reg:.2f}s "
            f"(layout cache: {rep.get('layout_cache_hits', 0)} hits / "
            f"{rep.get('layout_cache_misses', 0)} misses{flavor})",
            flush=True,
        )
        t0 = time.perf_counter()
        nwarm = warmup(server, name, v, args.max_batch)
        print(
            f"warmup: {nwarm} queries compiled "
            f"{server.report()['executables_cached']} batch shapes in "
            f"{time.perf_counter() - t0:.1f}s",
            flush=True,
        )
        pre = dict(server.metrics.report()["counters"])
        from bfs_tpu.analysis.runtime import retrace_report

        retrace_warm = retrace_report()  # post-warmup snapshot: steady
        # state must not move any of these counters

        queries = make_queries(rng, v, args.requests, args)
        cursor = [0]

        def one_request(i: int) -> None:
            srcs, mode = queries[i]
            t = time.perf_counter()
            while True:
                try:
                    fut = server.submit(
                        name, srcs, mode=mode, timeout_s=args.timeout_s
                    )
                    break
                except AdmissionError:
                    time.sleep(0.005)  # backpressure: retry later
            reply = fut.result(timeout=args.timeout_s + 60)
            lat = time.perf_counter() - t
            errs = (
                []
                if args.no_check
                else oracle_check(graph, oracle_cache, srcs, mode, reply)
            )
            with lock:
                latencies.append(lat)
                wrong.extend(errs)

        def worker():
            while True:
                with lock:
                    if cursor[0] >= len(queries):
                        return
                    i = cursor[0]
                    cursor[0] += 1
                try:
                    one_request(i)
                except Exception as exc:
                    # An unanswered query (timeout, server error, dead
                    # future) must fail the run, not silently kill this
                    # worker thread and under-count the checked total.
                    with lock:
                        wrong.append(
                            f"request {i} ({queries[i]}) failed: {exc!r}"
                        )

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=worker) for _ in range(args.concurrency)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        steady_s = time.perf_counter() - t0

        report = server.report()
        post = report["counters"]

    hits = post.get("compile_hits", 0) - pre.get("compile_hits", 0)
    misses = post.get("compile_misses", 0) - pre.get("compile_misses", 0)
    steady_rate = hits / (hits + misses) if hits + misses else 1.0
    out = {
        "requests": args.requests,
        "concurrency": args.concurrency,
        "oracle_checked": 0 if args.no_check else args.requests,
        "wrong_answers": len(wrong),
        "steady_seconds": steady_s,
        "queries_per_sec": args.requests / steady_s if steady_s > 0 else 0.0,
        "latency_p50_ms": percentile(latencies, 50) * 1e3,
        "latency_p99_ms": percentile(latencies, 99) * 1e3,
        "steady_compile_hit_rate": steady_rate,
        "server_report": report,
    }
    print(json.dumps(out, indent=2, sort_keys=True))
    # ONE snapshot surface (bfs_tpu.obs.MetricsRegistry) instead of the
    # old bespoke retrace table: serve report, artifact caches, retrace
    # counters WITH post-warmup drift (a sub-100% hit rate plus a non-zero
    # retrace_drift entry names exactly which program recompiled), span
    # summary, eviction counters.
    from bfs_tpu.obs import get_registry

    print(
        get_registry().to_json(retrace_baseline=retrace_warm),
        file=sys.stderr,
    )
    for msg in wrong[:10]:
        print(f"WRONG: {msg}", file=sys.stderr)
    if wrong:
        return 1
    if steady_rate < 1.0:
        print(
            f"FAIL: steady-state compile hit rate {steady_rate:.3f} < 1.0",
            file=sys.stderr,
        )
        return 1
    if post.get("integrity_failures", 0):
        # The sampled DeviceChecker caught a wrong on-device answer on a
        # HEALTHY run — that is a correctness bug, not noise, whatever the
        # fallback re-run then returned to callers.
        print(
            f"FAIL: {post['integrity_failures']} sampled integrity "
            "failure(s) on an uninjected run",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
