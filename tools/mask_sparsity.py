"""Per-stage sparsity of the cached s24 net masks (bit-major layout)."""
import numpy as np, time
z = np.load("/root/repo/.bench_cache/relay_v3_native_s24_ef6_seed42_block8192.npz")
print({k: (z[k].shape if hasattr(z[k],'shape') and z[k].ndim else int(z[k])) for k in z.files if k not in ('net_masks','vperm_masks','src_l1','new2old','old2new')})
net_size = int(z["net_size"]); m2=int(z["m2"])
ic = z["in_classes"]; m1 = int(ic[-1][4])
print(f"net_size=2^{int(np.log2(net_size))}, m1={m1} ({m1/net_size:.3f}), m2={m2} ({m2/net_size:.3f})")
print(f"in_classes: {len(ic)} classes, widths {ic[:,0].min()}..{ic[:,0].max()}")
oc = z["out_classes"]; print(f"out_classes: {len(oc)} classes, widths {oc[:,0].min()}..{oc[:,0].max()}, out_space={int(oc[-1][4])}")
nm = z["net_masks"]
S, nw = nm.shape
print("stages", S, "words/stage", nw)
SB = 1<<13   # words per chunk -> element blocks of 8192 elems per plane... we analyze chunks of words
tot_blocks0 = 0; nz_blocks0 = 0
print("stage | dist | bit_density | zero-bitmajor-word-frac | nz-elem-block-frac(2^13w=2^13e/plane) | elem nonzero range frac")
k = int(net_size).bit_length()-1
for s in range(S):
    d = net_size >> (s+1) if s < k else net_size >> (2*k-1-s)
    w = nm[s]
    pc = np.unpackbits(w.view(np.uint8)).sum()
    zword = float(np.mean(w==0))
    # element-space blocks: chunk words by SB, OR-reduce, then count set bits over (chunk, plane)
    orch = np.bitwise_or.reduce(w.reshape(-1, SB), axis=1)  # [nw/SB]
    nzblocks = np.unpackbits(orch.view(np.uint8)).sum()  # nonzero (plane,chunk) blocks
    totblocks = orch.shape[0]*32
    # element-space nonzero contiguous range: element = b*nw + wd; block id in element order = b*(nw/SB)+chunk
    bits = np.unpackbits(orch.view(np.uint8), bitorder='little').reshape(-1, 32).T.reshape(-1)  # [32, nchunk] -> element-ordered blocks
    nz = np.flatnonzero(bits)
    rng = (nz[0], nz[-1]+1) if len(nz) else (0,0)
    rngfrac = (rng[1]-rng[0])/len(bits)
    if s < 8 or s > S-8 or s % 5 == 0:
        print(f"{s:3d} | 2^{int(np.log2(d)):2d} | {pc/net_size:.3f} | {zword:.3f} | {nzblocks/totblocks:.3f} | {rngfrac:.3f}")
    tot_blocks0 += totblocks; nz_blocks0 += nzblocks
print(f"TOTAL elem-block(2^13 elems) nonzero fraction: {nz_blocks0/tot_blocks0:.4f}")
