"""Per-stage zero-word sparsity of the cached s24 net masks (v4 layout).

Published result (2026-07-30, relay_v4_native_s24_ef6_seed42_block8192):

  - 16+16 outer stages (d >= 2^12) are PAIR-COMPACTED at build time:
    4.19M words each, ~0% zero words — nothing left to elide.
  - 14 lane-distance stages (2^5 <= d <= 2^11, stages 16-22 and 32-38):
    8.39M words each, EXACTLY 50% zero words — the structural pair-zeros
    (mask bits live only at the lower lane of each pair) that pair
    compaction removes for d >= 4096 but which sub-row strides keep in the
    stored stream here.  Total structurally-zero traffic: 58.7M words =
    235 MB/superstep (16% of the 1.46 GB mask stream).
  - 9 intra-word stages (d < 2^5): 8.39M words each, ~0% zero WORDS (the
    pair-zeros are at the BIT level inside each word — half the bits — so
    word-level elision cannot see them; bit-level repacking would trade
    ~5 VPU ops/word for 50% of these stages' bytes, breakeven at the
    device's fast-window bandwidth).
  - No stage has leading/trailing all-zero block runs (nz-range frac = 1.0
    everywhere; the identity-tail skip in ops/relay_pallas.py already
    covers the only case that occurs, via StageSpec.lo/hi).

Conclusion recorded in docs/ARCHITECTURE.md: elision's ceiling is ~16% of
mask bytes; the concat-friendly subset (lane distance >= 16 words) is ~8%.
"""
import numpy as np

z = np.load("/root/repo/.bench_cache/relay_v4_native_s24_ef6_seed42_block8192.npz")
nt = z["net_table"]  # rows: d, offset, nwords, compact, lo, hi
net_size = int(z["net_size"])
nm = z["net_masks"]
print(f"net_size=2^{int(np.log2(net_size))}, m1={int(z['m1'])}, m2={int(z['m2'])}")
print("stage | d | nwords(M) | compact | zero-word frac | nz-range frac")
tot = nz_tot = 0
lane_zero_words = 0
for s, (d, off, nw, comp, lo, hi) in enumerate(nt):
    w = nm[off : off + nw]
    zf = float(np.mean(w == 0))
    nz = np.flatnonzero(w)
    rng = (int(nz[0]), int(nz[-1]) + 1) if len(nz) else (0, 0)
    tot += nw
    nz_tot += len(nz)
    if 32 <= d < 4096 and not comp:
        lane_zero_words += int(nw) - len(nz)
    print(
        f"{s:3d} | 2^{int(np.log2(d)):2d} | {nw/1e6:8.2f} | {comp} | "
        f"{zf:.4f} | {(rng[1]-rng[0])/nw:.3f}"
    )
print(
    f"TOTAL words {tot/1e6:.1f}M, nonzero {nz_tot/1e6:.1f}M ({nz_tot/tot:.4f}); "
    f"lane-stage structural zeros {lane_zero_words/1e6:.1f}M words "
    f"({lane_zero_words*4/1e6:.0f} MB/superstep)"
)
