#!/usr/bin/env python3
"""Diff two HLO fingerprint snapshots: the ledger_compare twin for
compiled artifacts, so a TPU-window before/after is one command.

Feeds on any of the shapes the HLO pass emits:

* a metrics snapshot (``bfs-tpu-lint --hlo --snapshot out.json``);
* the committed ``bfs_tpu/analysis/hlo_fingerprints.json``;
* a cached result file from ``.bench_cache/hlo/`` (the
  ``meta.fingerprints`` rows are used).

Prints a per-program markdown delta table (temp bytes, fusion count,
loop collectives, loop materializations) and exits non-zero when any
program REGRESSED: temp bytes grew more than ``--threshold`` (default
10% — the HLO002 tripwire), the emitted fusion count grew (fusion
break), the loop-collective count changed (a collective hoisted out of
or duplicated into the superstep loop), the loop materialization count
grew, or a program present before is gone after (a hot program that
silently left the registry is a coverage regression, not a win).

Environments must match (backend/jax/devices) when both snapshots carry
one — comparing CPU fusion counts against TPU counts proves nothing and
exits 2.

No jax import: runs anywhere the repo does (the lint-stub discipline of
tools/obs_dashboard.py and tools/ledger_compare.py).
"""

from __future__ import annotations

import argparse
import json
import sys

#: The columns rendered and the regression predicate per metric.
COLUMNS = ("temp_bytes", "fusions", "loop_collectives",
           "loop_materializations")


def load_programs(path: str) -> tuple[dict, dict]:
    """``(env, programs)`` from any supported file shape."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict):
        raise SystemExit(f"{path}: not a JSON object")
    meta = doc.get("meta", {})
    if "programs" in doc and isinstance(doc["programs"], dict):
        return doc.get("env", {}), doc["programs"]
    if isinstance(meta.get("fingerprints"), dict):  # cached result file
        return {}, meta["fingerprints"]
    # Bare {program: metrics-row} mapping.
    if doc and all(isinstance(v, dict) for v in doc.values()):
        return {}, doc
    raise SystemExit(f"{path}: no fingerprint rows found")


def fmt_delta(old, new, pct: bool = False) -> str:
    if old == new:
        return "="
    d = new - old
    s = f"{'+' if d > 0 else ''}{d}"
    if pct and old:
        s += f" ({d * 100.0 / old:+.0f}%)"
    return s


def diff(old: dict, new: dict, threshold: float):
    """``(markdown_lines, regressions)`` for two program->metrics maps."""
    lines = [
        "| program | temp bytes | Δ | fusions | Δ | loop colls | Δ "
        "| loop mats | Δ |",
        "|---|---:|---:|---:|---:|---:|---:|---:|---:|",
    ]
    regressions: list[str] = []
    for name in sorted(set(old) | set(new)):
        o, n = old.get(name), new.get(name)
        if n is None:
            lines.append(f"| {name} | — | REMOVED | | | | | | |")
            regressions.append(
                f"{name}: program disappeared from the fingerprint set "
                "(hot-coverage regression)"
            )
            continue
        if o is None:
            lines.append(
                f"| {name} (new) | {n.get('temp_bytes', 0)} | | "
                f"{n.get('fusions', 0)} | | {n.get('loop_collectives', 0)} "
                f"| | {n.get('loop_materializations', 0)} | |"
            )
            continue
        cells = [name]
        for col in COLUMNS:
            ov, nv = int(o.get(col, 0)), int(n.get(col, 0))
            cells.append(str(nv))
            cells.append(fmt_delta(ov, nv, pct=(col == "temp_bytes")))
        lines.append("| " + " | ".join(cells) + " |")
        ot, nt = int(o.get("temp_bytes", 0)), int(n.get("temp_bytes", 0))
        if nt > ot * (1 + threshold):
            regressions.append(
                f"{name}: temp bytes {ot} -> {nt} "
                f"(+{(nt - ot) * 100.0 / ot if ot else float('inf'):.0f}%, "
                f"threshold +{threshold:.0%})"
            )
        of, nf = int(o.get("fusions", 0)), int(n.get("fusions", 0))
        if nf > of:
            regressions.append(
                f"{name}: fusion count {of} -> {nf} (fusion break: more "
                "emitted kernels)"
            )
        oc = int(o.get("loop_collectives", 0))
        nc = int(n.get("loop_collectives", 0))
        if nc != oc:
            what = "duplicated into" if nc > oc else "hoisted out of"
            regressions.append(
                f"{name}: loop collectives {oc} -> {nc} (collective "
                f"{what} the superstep loop)"
            )
        om = int(o.get("loop_materializations", 0))
        nm = int(n.get("loop_materializations", 0))
        if nm > om:
            regressions.append(
                f"{name}: loop materializations {om} -> {nm} (new "
                "while-body copy/transpose)"
            )
    return lines, regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Diff two HLO fingerprint snapshots (markdown table; "
                    "non-zero exit on regression)."
    )
    ap.add_argument("old", help="before snapshot (JSON)")
    ap.add_argument("new", help="after snapshot (JSON)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="temp-bytes regression tolerance (default 0.10)")
    args = ap.parse_args(argv)

    old_env, old_programs = load_programs(args.old)
    new_env, new_programs = load_programs(args.new)
    if old_env and new_env and old_env != new_env:
        print(
            f"hlo_diff: environments differ ({old_env} vs {new_env}) — "
            "compiled-artifact counts are not comparable across "
            "backend/jax/device-count", file=sys.stderr,
        )
        return 2

    lines, regressions = diff(old_programs, new_programs, args.threshold)
    print("\n".join(lines))
    print()
    if regressions:
        print(f"hlo_diff: {len(regressions)} regression(s):")
        for r in regressions:
            print(f"  REGRESSED  {r}")
        return 1
    print(f"hlo_diff: no regressions across {len(new_programs)} program(s) "
          f"(threshold +{args.threshold:.0%})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
