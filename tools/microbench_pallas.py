#!/usr/bin/env python
"""Measure Pallas TPU primitives for the BFS kernel design.

Times tpu.dynamic_gather (per-lane table lookup) and calibrating elementwise
kernels, using the slope method (N vs 4N chained iterations inside jit).
"""

import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LO, HI = 8, 64


def slope_time(label, fn, *args, items=None):
    f_lo = jax.jit(partial(fn, iters=LO))
    f_hi = jax.jit(partial(fn, iters=HI))
    jax.block_until_ready(f_lo(*args))
    jax.block_until_ready(f_hi(*args))
    t_lo = min(_t(f_lo, *args) for _ in range(3))
    t_hi = min(_t(f_hi, *args) for _ in range(3))
    per = max((t_hi - t_lo) / (HI - LO), 1e-9)
    rate = f"  {items / per / 1e9:8.2f} Gitems/s" if items else ""
    print(f"{label:46s} {per * 1e3:9.3f} ms/iter{rate}", flush=True)


def _t(fn, *args):
    t0 = time.perf_counter()
    jax.block_until_ready(fn(*args))
    return time.perf_counter() - t0


# ---- kernels ----------------------------------------------------------------

def gather_kernel(table_ref, idx_ref, out_ref):
    # out[i, j] = table[idx[i, j], j]  — per-lane sublane gather.
    out_ref[:] = jnp.take_along_axis(
        table_ref[:], idx_ref[:], axis=0, mode="promise_in_bounds"
    )


def gather_min_kernel(table_ref, idx_ref, out_ref):
    g = jnp.take_along_axis(table_ref[:], idx_ref[:], axis=0, mode="promise_in_bounds")
    out_ref[:] = jnp.min(g, axis=1, keepdims=True)


def ew_kernel(x_ref, out_ref):
    out_ref[:] = x_ref[:] * 3 + 1


def main():
    rows_tab = int(os.environ.get("TAB_ROWS", str(8192)))          # table rows
    rows_idx = rows_tab  # dynamic_gather requires idx.shape == table.shape
    lanes = 128
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.integers(0, 1 << 30, size=(rows_tab, lanes), dtype=np.int32))
    idx = jnp.asarray(rng.integers(0, rows_tab, size=(rows_idx, lanes), dtype=np.int32))
    x = jnp.asarray(rng.integers(0, 100, size=(rows_idx, lanes), dtype=np.int32))
    print(f"table [{rows_tab},{lanes}] ({rows_tab * lanes * 4 / 1e6:.1f} MB)  "
          f"idx [{rows_idx},{lanes}] = {rows_idx * lanes / 1e6:.1f} M lookups/call  "
          f"device={jax.devices()[0]}")

    gather = pl.pallas_call(
        gather_kernel,
        out_shape=jax.ShapeDtypeStruct((rows_idx, lanes), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )

    def chained_gather(table, idx, *, iters):
        # Dependency chain through the table argument defeats hoisting.
        def body(i, carry):
            t, acc = carry
            out = gather(t, idx)
            m = out.min()
            return (t.at[0, 0].set(m % 7), acc + m)

        t, acc = jax.lax.fori_loop(0, iters, body, (table, jnp.int32(0)))
        return acc

    slope_time("pallas dynamic_gather (sublane, per-lane)",
               chained_gather, table, idx, items=rows_idx * lanes)

    gather_min = pl.pallas_call(
        gather_min_kernel,
        out_shape=jax.ShapeDtypeStruct((rows_idx, 1), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )

    def chained_gather_min(table, idx, *, iters):
        def body(i, carry):
            t, acc = carry
            out = gather_min(t, idx)
            m = out.min()
            return (t.at[0, 0].set(m % 7), acc + m)

        _, acc = jax.lax.fori_loop(0, iters, body, (table, jnp.int32(0)))
        return acc

    slope_time("pallas gather + lane-min fused",
               chained_gather_min, table, idx, items=rows_idx * lanes)

    ew = pl.pallas_call(
        ew_kernel,
        out_shape=jax.ShapeDtypeStruct((rows_idx, lanes), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )

    def chained_ew(x, *, iters):
        def body(i, carry):
            x, acc = carry
            out = ew(x)
            return (x.at[0, 0].set(out.min() % 5), acc + out.min())

        _, acc = jax.lax.fori_loop(0, iters, body, (x, jnp.int32(0)))
        return acc

    slope_time("pallas elementwise (calibration)",
               chained_ew, x, items=rows_idx * lanes)


if __name__ == "__main__":
    main()
