#!/usr/bin/env python
"""Graph500-style harness: BFS + SSSP kernels over multiple roots/scales.

The externally comparable face of the semiring substrate (ISSUE 16):
R-MAT graphs at Graph500 parameters (A=0.57, B=C=0.19, edgefactor 16),
NBFS sampled search keys with nonzero degree, both traversal kernels
timed per root, and the OFFICIAL output statistics block per kernel —
``min/firstquartile/median/thirdquartile/max/mean/stddev`` over time and
traversed-edge counts plus the TEPS block with its harmonic mean/stddev
(the Graph500 v3 reference's ``output_results`` keys, ``bfs``/``sssp``
prefixed) — so the TEPS trajectory reads side by side with published
Graph500 lists.

Deviations from the reference spec, stated rather than hidden: SSSP
weights are the repo's deterministic endpoint-hash integers in
[1, max_weight] (:func:`bfs_tpu.algo.substrate.edge_weights_np`), not
uniform [0,1) reals — the oracle and every engine arm recompute
identical values from the edge arrays alone; and validation is the
repo's oracle gate (host Dijkstra / canonical BFS on the first root +
the on-device invariant counters on every root), not the reference's
five-clause validator.

Every run journals through the existing bench ledger: one
:class:`~bfs_tpu.resilience.journal.RunJournal` per config under the
journal dir (completed scales are skipped on re-invocation — the same
resume contract as ``bench.py``), and ``--capture`` appends the bench
JSONL metric lines (``{"metric", "value", "unit", "vs_baseline",
"details"}``) the ledger tools already parse.

Usage::

    python tools/graph500_run.py --scales 8,10 --roots 8 \
        --capture BENCH_GRAPH500_s10.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: Official stat order for the time/nedge blocks.
_QSTATS = ("min", "firstquartile", "median", "thirdquartile", "max")


def _quartiles(x: np.ndarray) -> dict:
    q1, q2, q3 = np.percentile(x, [25, 50, 75])
    return {
        "min": float(np.min(x)),
        "firstquartile": float(q1),
        "median": float(q2),
        "thirdquartile": float(q3),
        "max": float(np.max(x)),
    }


def kernel_stats(times: np.ndarray, nedges: np.ndarray) -> dict:
    """The official per-kernel statistics block: quartile/mean/stddev over
    time and nedge, quartiles over per-root TEPS, and the HARMONIC mean /
    stddev of TEPS (the Graph500 aggregate: TEPS is a rate, so the mean
    of 1/TEPS is what adds; stddev via the jackknife form the reference
    uses, stddev(1/x) / (mean(1/x)^2 * sqrt(n-1)))."""
    times = np.asarray(times, dtype=np.float64)
    nedges = np.asarray(nedges, dtype=np.float64)
    teps = nedges / times
    inv = 1.0 / teps
    n = teps.size
    hmean = 1.0 / np.mean(inv)
    if n > 1:
        hstd = float(
            np.std(inv, ddof=1) / (np.mean(inv) ** 2 * np.sqrt(n - 1))
        )
    else:
        hstd = 0.0
    out = {}
    for key, val in _quartiles(times).items():
        out[f"{key}_time"] = val
    out["mean_time"] = float(np.mean(times))
    out["stddev_time"] = float(np.std(times, ddof=1)) if n > 1 else 0.0
    for key, val in _quartiles(nedges).items():
        out[f"{key}_nedge"] = val
    out["mean_nedge"] = float(np.mean(nedges))
    out["stddev_nedge"] = float(np.std(nedges, ddof=1)) if n > 1 else 0.0
    for key, val in _quartiles(teps).items():
        out[f"{key}_TEPS"] = val
    out["harmonic_mean_TEPS"] = float(hmean)
    out["harmonic_stddev_TEPS"] = hstd
    return out


def format_output(scale: int, edgefactor: int, nbfs: int, gen_s: float,
                  con_s: float, blocks: dict) -> str:
    """The official Graph500 output format: header keys then one
    ``<kernel>  <stat>: <value>`` line per statistic, kernels prefixed
    ``bfs``/``sssp`` as in the v3 reference."""
    lines = [
        f"SCALE: {scale}",
        f"edgefactor: {edgefactor}",
        f"NBFS: {nbfs}",
        f"graph_generation: {gen_s:.6g}",
        "num_mpi_processes: 1",
        f"construction_time: {con_s:.6g}",
    ]
    for kernel, stats in blocks.items():
        lines.append(f"{kernel} validation: PASSED")
        for key, val in stats.items():
            lines.append(f"{kernel}  {key}: {val:.6g}")
    return "\n".join(lines) + "\n"


def sample_roots(graph, nbfs: int, seed: int) -> np.ndarray:
    """NBFS distinct search keys with degree >= 1 (the spec's key
    sampling); deterministic in ``seed``."""
    deg = np.zeros(graph.num_vertices, dtype=np.int64)
    np.add.at(deg, graph.src, 1)
    candidates = np.flatnonzero(deg > 0)
    if candidates.size == 0:
        raise ValueError("graph has no edges to traverse")
    rng = np.random.default_rng(seed)
    take = min(nbfs, candidates.size)
    return rng.choice(candidates, size=take, replace=False).astype(np.int64)


def traversed_edges(graph, dist: np.ndarray) -> int:
    """Undirected edge count of the traversed component: directed edges
    whose source is reached, halved (the bi-directed store counts each
    input edge twice) — the spec's traversed-edge convention."""
    from bfs_tpu.graph.csr import INF_DIST

    reached = np.asarray(dist) != INF_DIST
    return max(int(reached[graph.src].sum()) // 2, 1)


def run_scale(scale: int, *, edgefactor: int, nbfs: int, seed: int,
              max_weight: int, jr=None) -> dict:
    """Generate, construct, run BFS + SSSP over the sampled roots, and
    return the result document (journal-resumable per scale)."""
    from bfs_tpu.algo import edge_weights_np, sssp
    from bfs_tpu.graph.csr import Graph, build_device_graph
    from bfs_tpu.graph.generators import rmat_edges
    from bfs_tpu.models.bfs import bfs
    from bfs_tpu.oracle import (
        DeviceChecker,
        canonical_bfs,
        dijkstra,
        sssp_device_check,
    )

    phase = f"scale:{scale}"
    if jr is not None:
        done = jr.get(phase)
        if done is not None:
            print(f"[graph500] scale {scale}: journal hit, skipping re-run",
                  file=sys.stderr)
            return done

    t0 = time.perf_counter()
    edges = rmat_edges(scale, edgefactor, seed=seed)
    gen_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    graph = Graph.from_undirected_edges(1 << scale, edges.astype(np.int32))
    dg = build_device_graph(graph)
    con_s = time.perf_counter() - t0
    roots = sample_roots(graph, nbfs, seed)
    weights = edge_weights_np(graph.src, graph.dst, max_weight)
    checker = DeviceChecker.from_graph(dg)

    bfs_times, bfs_nedges = [], []
    sssp_times, sssp_nedges = [], []
    for i, root in enumerate(roots.tolist()):
        t0 = time.perf_counter()
        bres = bfs(graph, root, engine="push")
        bfs_times.append(time.perf_counter() - t0)
        bfs_nedges.append(traversed_edges(graph, bres.dist))
        t0 = time.perf_counter()
        sres = sssp(graph, root, max_weight=max_weight)
        sssp_times.append(time.perf_counter() - t0)
        sssp_nedges.append(traversed_edges(graph, sres.dist))
        # Validation: device invariant counters on every root, the host
        # oracles on the first (the expensive exact gate once per scale).
        viol = checker.check(bres.dist, bres.parent, root)
        if viol:
            raise SystemExit(f"BFS device check failed at root {root}: {viol}")
        viol = sssp_device_check(
            dg.src, dg.dst, sres.dist, sres.parent,
            root, graph.num_vertices, max_weight,
        )
        if viol:
            raise SystemExit(
                f"SSSP device check failed at root {root}: {viol}"
            )
        if i == 0:
            odist, _ = canonical_bfs(graph, root)
            if not np.array_equal(bres.dist, odist):
                raise SystemExit(f"BFS oracle mismatch at root {root}")
            odist, opar = dijkstra(graph, weights, root)
            if not (np.array_equal(sres.dist, odist)
                    and np.array_equal(sres.parent, opar)):
                raise SystemExit(f"SSSP oracle mismatch at root {root}")

    doc = {
        "scale": scale,
        "edgefactor": edgefactor,
        "nbfs": len(roots),
        "graph_generation": gen_s,
        "construction_time": con_s,
        "roots": [int(r) for r in roots],
        "max_weight": max_weight,
        "bfs": kernel_stats(np.array(bfs_times), np.array(bfs_nedges)),
        "sssp": kernel_stats(np.array(sssp_times), np.array(sssp_nedges)),
    }
    if jr is not None:
        jr.put(phase, doc)
    return doc


def capture_lines(doc: dict) -> list[dict]:
    """Bench-ledger JSONL lines for one scale's result document."""
    s = doc["scale"]
    out = []
    for kernel in ("bfs", "sssp"):
        stats = doc[kernel]
        out.append({
            "metric": f"graph500_s{s}_{kernel}_harmonic_TEPS",
            "value": stats["harmonic_mean_TEPS"],
            "unit": "TEPS",
            "vs_baseline": None,
            "details": {
                "scale": s,
                "edgefactor": doc["edgefactor"],
                "nbfs": doc["nbfs"],
                "kernel": kernel,
                "max_weight": doc["max_weight"],
                "harmonic_stddev_TEPS": stats["harmonic_stddev_TEPS"],
                "median_time": stats["median_time"],
                "median_nedge": stats["median_nedge"],
                "construction_time": doc["construction_time"],
                "validation": "PASSED",
            },
        })
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--scales", default="8,10",
                    help="comma-separated R-MAT scales (default 8,10)")
    ap.add_argument("--edgefactor", type=int, default=16)
    ap.add_argument("--roots", type=int, default=8,
                    help="NBFS search keys per scale (default 8)")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--max-weight", type=int, default=255,
                    help="SSSP hash-weight range [1, max-weight]")
    ap.add_argument("--out", default=None,
                    help="also write the official output blocks here")
    ap.add_argument("--capture", default=None,
                    help="append bench-ledger JSONL metric lines here")
    ap.add_argument("--no-journal", action="store_true",
                    help="skip the run journal (fresh run, no resume)")
    args = ap.parse_args(argv)

    scales = [int(s) for s in str(args.scales).split(",") if s.strip()]
    cfg = {
        "tool": "graph500_run",
        "scales": scales,
        "edgefactor": args.edgefactor,
        "roots": args.roots,
        "seed": args.seed,
        "max_weight": args.max_weight,
    }
    jr = None
    from bfs_tpu import knobs

    if not args.no_journal and knobs.get("BFS_TPU_JOURNAL"):
        from bfs_tpu.config import journal_dir
        from bfs_tpu.resilience.journal import RunJournal

        os.makedirs(journal_dir(), exist_ok=True)
        jr = RunJournal.open_for(journal_dir(), cfg)

    blocks_text = []
    lines = []
    for scale in scales:
        doc = run_scale(
            scale, edgefactor=args.edgefactor, nbfs=args.roots,
            seed=args.seed, max_weight=args.max_weight, jr=jr,
        )
        text = format_output(
            doc["scale"], doc["edgefactor"], doc["nbfs"],
            doc["graph_generation"], doc["construction_time"],
            {"bfs": doc["bfs"], "sssp": doc["sssp"]},
        )
        blocks_text.append(text)
        lines.extend(capture_lines(doc))
        sys.stdout.write(text)
        sys.stdout.flush()
    if jr is not None:
        jr.close()
    if args.out:
        with open(args.out, "w") as fh:
            fh.write("\n".join(blocks_text))
    if args.capture:
        with open(args.capture, "a") as fh:
            for line in lines:
                fh.write(json.dumps(line) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
