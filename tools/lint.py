"""Fast project-lint entry point: ``python tools/lint.py`` ==
``python -m bfs_tpu.analysis``, minus the jax import.

The analyzers are stdlib-only (ast + tokenize), but ``python -m`` has to
execute the parent ``bfs_tpu/__init__`` first, which imports the engine
stack (~1.5 s of jax).  This wrapper installs a stub parent package so
``bfs_tpu.analysis`` loads alone — the lint stays sub-100ms, which is
what makes it cheap enough to run on every commit.  All flags pass
through, including ``--ir``/``--hlo``/``--pallas``/``--all`` (those
passes import jax on purpose — the stub only keeps the DEFAULT AST
path light).
"""

from __future__ import annotations

import importlib
import os
import sys
import types

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if "bfs_tpu" not in sys.modules:
    sys.path.insert(0, ROOT)
    _pkg = types.ModuleType("bfs_tpu")
    _pkg.__path__ = [os.path.join(ROOT, "bfs_tpu")]
    sys.modules["bfs_tpu"] = _pkg

main = importlib.import_module("bfs_tpu.analysis.__main__").main

if __name__ == "__main__":
    raise SystemExit(main())
