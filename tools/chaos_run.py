"""Chaos driver: kill a bench (or loadgen) subprocess on a randomized
schedule and prove every resumed run converges to the golden result.

The bench mode is the resilience subsystem's acceptance harness
(docs/ARCHITECTURE.md §11): one uninterrupted golden run establishes the
reference headline, then each iteration SIGKILLs a fresh run at a random
phase boundary (``BFS_TPU_FAULT=kill:<phase>[:nth]``), re-invokes with the
same config until it completes, and diffs the final headline against the
golden on every deterministic field.  When the killed run had already
emitted its provisional headline, the resumed value must additionally be
BIT-IDENTICAL to it — the resumed run finishes the dead run's measurement,
it does not take a new one.  Any divergence exits non-zero.

The loadgen mode is simpler (the load generator owns no resume state):
kill ``tools/serve_loadgen.py`` after a random delay, then run it to
completion — its own oracle gate (exit 1 on any wrong answer or sub-100%
steady-state compile hit rate) is the divergence check, and the kill
proves a dead client never wedges or corrupts the serving artifacts
(layout bundles, compile caches) it shares with the next run.

The serve mode (ISSUE 9) is the SELF-HEALING acceptance schedule: one
in-process :class:`~bfs_tpu.serve.BfsServer` driven through a scripted
fault+swap sequence — classified-permanent device faults
(``raise:serve.batch``, the in-process analog of a killed device call)
until the circuit breaker opens, a cooldown canary that closes it again,
hung-call delays (``delay:serve.batch:s``) the watchdog must convert
into degraded ticks instead of a frozen server, a corrupt on-device
answer (``raise:serve.verify`` = a failed integrity verdict) that must
quarantine the executable, and a mid-load epoch swap whose in-flight
queries must be answered against their admission-time snapshot.  EVERY
reply is oracle-checked against the graph its epoch pinned; the driver
exits non-zero on any wrong answer, any frozen tick (a future that never
resolves inside ``--serve-tick-timeout``), or any missing breaker /
watchdog / integrity / epoch transition in the final metrics snapshot.

The traversal mode (ISSUE 14) is the superstep-checkpoint acceptance
harness: each subject process runs ONE traversal as bounded segments
with per-epoch checkpoints (``python -m
bfs_tpu.resilience.superstep_ckpt``), gets SIGKILLed at a randomized
SUPERSTEP boundary (``BFS_TPU_FAULT=kill:superstep:<n>`` — mid-
traversal, not mid-phase), and is re-invoked against the same
checkpoint directory until it completes.  The resumed result must be
bit-identical to an un-killed golden run on dist/parent content hashes,
the direction schedule AND the exchange-arm sequence, and must provably
have resumed from a checkpoint epoch rather than silently restarting.
Covers the single-chip relay (packed + sparse hybrid, auto direction),
batched multi-source, the x8 sharded relay (whose per-shard epoch
files also exercise the shard-loss fallback in tests), and the 2D
r x c grid engine (``--mesh 2x4`` — per-cell epochs, and the resumed
run must also replay BOTH per-axis byte curves and arm schedules).

Usage (CPU, tiny config — the tier-1-adjacent shape):
    python tools/chaos_run.py --iterations 5 --seed 1
    python tools/chaos_run.py --mode loadgen --iterations 3
    python tools/chaos_run.py --mode serve --scale 8
    python tools/chaos_run.py --mode traversal --iterations 2 --seed 1

Heavier configs pass through the usual BENCH_* env knobs.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Instrumented single-source bench phase families (resilience/faults.py
#: family matching: "verify:2" = second verification boundary).
BENCH_PHASES = [
    "graph", "reference", "roots", "warm", "repeats_plan", "repeat",
    "repeat:2", "provisional", "profile", "verify", "verify:2", "headline",
]

#: MULTICHIP (BENCH_MESH) journal phases — no scale fallback / probe /
#: provisional boundaries; the exchange curve is its own phase.
MULTICHIP_PHASES = [
    "graph", "layout", "reference", "roots", "repeat", "exchange_curve",
    "verify", "headline",
]

DETERMINISTIC_DETAILS = (
    "roots", "directed_edges_traversed", "vertices_reached",
    "supersteps_last_root", "num_vertices", "num_directed_edges",
    "check", "engine",
)


def log(msg: str) -> None:
    print(f"[chaos] {msg}", flush=True)


def bench_env(args, journal_dir: str) -> dict:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("BENCH_SCALE", str(args.scale))
    env.setdefault("BENCH_EDGE_FACTOR", str(args.edge_factor))
    env.setdefault("BENCH_ROOTS", str(args.roots))
    env.setdefault("BENCH_REPEATS", str(args.repeats))
    env.setdefault("BENCH_ENGINE", args.engine)
    env.setdefault("BENCH_TIME_BUDGET", "600")
    env["BFS_TPU_CACHE_DIR"] = args.cache_dir
    env["BFS_TPU_JOURNAL_DIR"] = journal_dir
    env.pop("BFS_TPU_FAULT", None)
    if args.mesh:
        # MULTICHIP journals (ISSUE 11): sharded relay on an n-shard
        # virtual mesh — the engine is forced and the virtual CPU
        # platform must expose enough devices BEFORE jax initializes.
        env["BENCH_ENGINE"] = "relay"
        env["BENCH_MESH"] = str(args.mesh)
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    return env


def run_bench(args, journal_dir: str, fault: str | None = None):
    env = bench_env(args, journal_dir)
    if fault is not None:
        env["BFS_TPU_FAULT"] = fault
    proc = subprocess.run(
        [sys.executable, "-m", "bfs_tpu.bench"],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT,
        timeout=args.timeout,
    )
    lines = [
        json.loads(l) for l in proc.stdout.splitlines() if l.startswith("{")
    ]
    return proc, lines


def diff_headline(final: dict, golden: dict) -> list[str]:
    bad = []
    for k in ("metric", "unit"):
        if final.get(k) != golden.get(k):
            bad.append(f"{k}: {final.get(k)!r} != {golden.get(k)!r}")
    for k in DETERMINISTIC_DETAILS:
        if final["details"].get(k) != golden["details"].get(k):
            bad.append(
                f"details.{k}: {final['details'].get(k)!r} != "
                f"{golden['details'].get(k)!r}"
            )
    return bad


def diff_schedule(final: dict, golden: dict) -> list[str]:
    """The direction schedule is a pure on-device function of graph +
    thresholds (models/direction.py), so it is deterministic ACROSS
    processes: a resumed run's schedule must equal the golden run's
    exactly, kill or no kill."""
    sg = golden["details"].get("direction_schedule")
    sf = final["details"].get("direction_schedule")
    if not isinstance(sg, dict):
        return []
    if not isinstance(sf, dict) or sf.get("schedule") != sg.get("schedule"):
        return [
            "details.direction_schedule: resumed "
            f"{(sf or {}).get('schedule')!r} != golden "
            f"{sg.get('schedule')!r} (the schedule must be a pure "
            "function of graph + thresholds)"
        ]
    return []


def diff_exchange(final: dict, golden: dict) -> list[str]:
    """MULTICHIP determinism: the exchange arm schedule and the per-level
    bytes-on-the-wire are pure functions of (graph, arm config), so a
    resumed run must reproduce the golden run's exactly — a drift means
    the resume re-ran a DIFFERENT exchange than it journaled.  Grid
    captures (ISSUE 17) additionally carry one byte curve + arm schedule
    PER MESH AXIS; those keys are diffed only when the golden has them,
    so 1D captures keep their original contract."""
    eg = golden["details"].get("exchange")
    ef = final["details"].get("exchange")
    if not isinstance(eg, dict):
        return []
    keys = ["arm", "schedule", "bytes_per_level", "total_bytes"]
    keys += [
        k for k in ("col_bytes", "row_bytes", "col_schedule",
                    "row_schedule", "per_chip_bytes")
        if k in eg
    ]
    bad = []
    for k in keys:
        if not isinstance(ef, dict) or ef.get(k) != eg.get(k):
            bad.append(
                f"details.exchange.{k}: resumed "
                f"{(ef or {}).get(k)!r} != golden {eg.get(k)!r}"
            )
    return bad


def diff_ledgers(final: dict, replayed: dict) -> list[str]:
    """Resumed-vs-replayed ledger + schedule invariant via
    tools/ledger_compare.py --exact (ISSUE 7 satellite): ``replayed`` is
    one more invocation over the SAME completed journal (a pure replay),
    so its superstep_phases seconds and direction_schedule must be
    BIT-IDENTICAL to the resumed run's — a mismatch means the replay
    path re-measured something it should have restored.  (Phase seconds
    are NOT deterministic across independent measurements, so the golden
    run is the wrong reference for exactness — diff_schedule covers the
    deterministic part against it.)  Skipped when either run shipped no
    ledger (budget-gated phases record a 'skipped' string)."""
    fl = final["details"].get("superstep_phases")
    gl = replayed["details"].get("superstep_phases")
    if not (isinstance(fl, dict) and isinstance(gl, dict)):
        return []
    with tempfile.NamedTemporaryFile(
        "w", suffix=".json", delete=False
    ) as fg, tempfile.NamedTemporaryFile(
        "w", suffix=".json", delete=False
    ) as ff:
        json.dump(replayed, fg)
        json.dump(final, ff)
    try:
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO_ROOT, "tools", "ledger_compare.py"),
                fg.name, ff.name, "--exact",
            ],
            capture_output=True, text=True,
        )
        if proc.returncode != 0:
            return [
                "superstep_phases/direction_schedule: resumed ledger "
                f"diverged from golden:\n{proc.stderr.strip()}"
            ]
        return []
    finally:
        os.unlink(fg.name)
        os.unlink(ff.name)


def chaos_bench(args, rng: random.Random) -> int:
    with tempfile.TemporaryDirectory(prefix="chaos_golden_") as golden_dir:
        log("golden run (uninterrupted)...")
        proc, lines = run_bench(args, golden_dir)
        if proc.returncode != 0 or not lines:
            log(f"golden run failed rc={proc.returncode}")
            sys.stderr.write(proc.stderr[-4000:])
            return 2
        golden = lines[-1]
        log(f"golden headline: value={golden['value']:.1f} "
            f"check={golden['details']['check']!r}")

    # The profile boundary only exists on the relay path; picking it for
    # other engines would silently burn the iteration without a kill.
    engine = os.environ.get("BENCH_ENGINE", args.engine)
    if args.mesh:
        phases = list(MULTICHIP_PHASES)
    else:
        phases = [
            p for p in BENCH_PHASES if p != "profile" or engine == "relay"
        ]
    failures = 0
    for it in range(args.iterations):
        with tempfile.TemporaryDirectory(prefix="chaos_j_") as journal_dir:
            provisional = None
            kills = 0
            # Randomized kill schedule: keep killing at random boundaries
            # (possibly several in a row — each resume makes progress)
            # until a run survives to completion.
            while True:
                fault = rng.choice(phases)
                if kills >= args.max_kills_per_iteration:
                    fault = None
                proc, lines = run_bench(
                    args, journal_dir,
                    fault=f"kill:{fault}" if fault else None,
                )
                for l in lines:
                    if l["details"].get("provisional"):
                        provisional = l
                if proc.returncode == 0:
                    break
                if proc.returncode != -signal.SIGKILL:
                    log(f"iter {it}: unexpected rc={proc.returncode} "
                        f"(fault={fault})")
                    sys.stderr.write(proc.stderr[-4000:])
                    return 2
                kills += 1
                log(f"iter {it}: killed at {fault!r} "
                    f"(kill #{kills}); resuming...")
            if not lines:
                log(f"iter {it}: FAIL — completed run emitted no headline")
                failures += 1
                continue
            final = lines[-1]
            bad = diff_headline(final, golden) + diff_schedule(final, golden)
            if args.mesh:
                bad += diff_exchange(final, golden)
            # One more invocation over the completed journal is a pure
            # replay: its ledger + schedule must be bit-identical to the
            # resumed run's (ledger_compare --exact).
            rproc, rlines = run_bench(args, journal_dir)
            if rproc.returncode != 0 or not rlines:
                bad.append("pure replay run failed or emitted no headline")
            else:
                bad += diff_ledgers(final, rlines[-1])
            if provisional is not None and final["value"] != provisional["value"]:
                bad.append(
                    f"value: resumed {final['value']!r} != provisional "
                    f"{provisional['value']!r} (the resume re-timed instead "
                    "of finishing the journaled measurement)"
                )
            if bad:
                log(f"iter {it}: FAIL after {kills} kill(s):")
                for b in bad:
                    log(f"  - {b}")
                failures += 1
            else:
                log(f"iter {it}: ok after {kills} kill(s) "
                    f"(value={final['value']:.1f})")
    log(f"bench chaos: {args.iterations - failures}/{args.iterations} ok")
    return 1 if failures else 0


def chaos_loadgen(args, rng: random.Random) -> int:
    cmd = [
        sys.executable, os.path.join(REPO_ROOT, "tools", "serve_loadgen.py"),
        "--scale", str(args.scale), "--requests", str(args.requests),
        "--cache-dir", args.cache_dir,
    ]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    failures = 0
    for it in range(args.iterations):
        delay = rng.uniform(1.0, args.loadgen_kill_max_s)
        proc = subprocess.Popen(
            cmd, env=env, cwd=REPO_ROOT,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        try:
            proc.wait(timeout=delay)
            log(f"iter {it}: loadgen finished before the {delay:.1f}s kill")
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            log(f"iter {it}: loadgen SIGKILLed at {delay:.1f}s")
        # The next full run must pass its own oracle gate despite the
        # shared on-disk artifacts a dead client just abandoned.
        proc2 = subprocess.run(
            cmd, env=env, cwd=REPO_ROOT, capture_output=True, text=True,
            timeout=args.timeout,
        )
        if proc2.returncode != 0:
            log(f"iter {it}: FAIL — post-kill loadgen rc={proc2.returncode}")
            sys.stderr.write(proc2.stderr[-4000:])
            failures += 1
        else:
            log(f"iter {it}: post-kill loadgen ok")
    log(f"loadgen chaos: {args.iterations - failures}/{args.iterations} ok")
    return 1 if failures else 0


def chaos_serve(args, rng: random.Random) -> int:
    """The in-process self-healing schedule (see module docstring).

    Runs in THIS process so the driver can pause/resume the batcher,
    hot-swap epochs mid-load, and reset fault-arrival counts between
    injections — the faults themselves still travel through the same
    ``BFS_TPU_FAULT`` boundary production would use."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, REPO_ROOT)
    import numpy as np

    from bfs_tpu.graph.generators import rmat_graph
    from bfs_tpu.oracle.bfs import check, queue_bfs
    from bfs_tpu.resilience import faults
    from bfs_tpu.serve import BfsServer

    failures: list[str] = []
    seed = args.seed if args.seed is not None else 1
    graph_a = rmat_graph(args.scale, args.edge_factor, seed=seed)
    graph_b = rmat_graph(args.scale, args.edge_factor, seed=seed + 1)
    v = graph_a.num_vertices
    name = "chaos"
    oracle: dict = {}
    counter = [0]

    def expect(gid, graph, s):
        if (gid, s) not in oracle:
            oracle[(gid, s)] = queue_bfs(graph, s)[0]
        return oracle[(gid, s)]

    def next_source() -> int:
        # Distinct sources per query (7 is coprime with the power-of-two
        # vertex count): a repeat would hit the result LRU and the tick
        # under test would never execute.
        counter[0] += 1
        return (3 + 7 * counter[0]) % v

    def set_fault(spec: str | None) -> None:
        faults.reset()  # kill/raise fire on the nth ARRIVAL; fresh count
        if spec is None:
            os.environ.pop("BFS_TPU_FAULT", None)
        else:
            os.environ["BFS_TPU_FAULT"] = spec

    def settle(reply_check, phase: str):
        """Resolve one staged (future, expected) pair; frozen/errored
        ticks and wrong answers are recorded, never raised."""
        fut, s, gid, graph, want_status, want_epoch = reply_check
        t0 = time.monotonic()
        try:
            reply = fut.result(timeout=args.serve_tick_timeout)
        except Exception as exc:
            failures.append(
                f"{phase}: FROZEN or errored tick for source {s}: {exc!r}"
            )
            return None
        wall = time.monotonic() - t0
        od = expect(gid, graph, s)
        if not np.array_equal(reply.dist, od) or check(
            graph, reply.dist, reply.parent, [s]
        ):
            failures.append(
                f"{phase}: WRONG answer for source {s} against graph "
                f"{gid!r} (status={reply.record.status}, "
                f"epoch={reply.record.epoch})"
            )
        if want_status is not None and reply.record.status != want_status:
            failures.append(
                f"{phase}: source {s} served status "
                f"{reply.record.status!r}, schedule wanted {want_status!r}"
            )
        if want_epoch is not None and reply.record.epoch != want_epoch:
            failures.append(
                f"{phase}: source {s} answered from epoch "
                f"{reply.record.epoch}, admitted under epoch {want_epoch}"
            )
        log(
            f"{phase}: source={s} status={reply.record.status} "
            f"epoch={reply.record.epoch} wait={wall * 1e3:.0f}ms"
        )
        return reply

    try:
        with BfsServer(
            engine=args.serve_engine,
            max_batch=4,
            tick_s=0.0,
            breaker_failures=2,
            breaker_cooldown_s=args.serve_cooldown_s,
            watchdog_s=30.0,
            watchdog_min_s=0.2,
            verify_sample=1,
        ) as server:
            server.register(name, graph_a)

            def ask(phase, *, gid="a", graph=graph_a, timeout_s=None,
                    want_status=None, want_epoch=None):
                s = next_source()
                fut = server.submit(name, [s], timeout_s=timeout_s)
                return settle(
                    (fut, s, gid, graph, want_status, want_epoch), phase
                )

            def recover(phase):
                set_fault(None)
                time.sleep(args.serve_cooldown_s + 0.1)
                ask(phase, want_status="ok")  # the half-open canary closes

            # Phase 1 — healthy load: every answer device-served, correct.
            for _ in range(args.serve_requests):
                ask("healthy", want_status="ok")

            # Phase 2 — permanent device faults until the breaker opens;
            # every faulted tick must still answer correctly (oracle
            # degradation), and the circuit must be OPEN in the snapshot.
            for _ in range(3):
                set_fault("raise:serve.batch")
                ask("device-fault", want_status="oracle")
            states = [
                cell["state"]
                for cell in server.report()["health"]["breaker"].values()
            ]
            if "open" not in states:
                failures.append(
                    f"device-fault: no open circuit in snapshot ({states})"
                )
            recover("recovery")

            # Phase 3 — hung calls: the delay wedges EVERY device attempt;
            # the request-deadline-tightened watchdog must convert each
            # into a degraded (still correct) tick, never a frozen server,
            # and two wedges re-open the breaker.
            set_fault(f"delay:serve.batch:{args.serve_delay_s}")
            for _ in range(2):
                ask("hung-call", timeout_s=0.5, want_status="oracle")
            recover("recovery-2")

            # Phase 4 — corrupt answer: a failed sampled verdict must
            # quarantine the executable and re-run the batch on the
            # fallback path.
            set_fault("raise:serve.verify")
            ask("integrity", want_status="oracle")
            recover("recovery-3")

            # Phase 5 — epoch swap MID-LOAD: queries staged before the
            # swap must be answered against graph A (their admission-time
            # snapshot), queries after it against graph B.
            old_epoch = server.registry.epoch(name)
            server.pause()
            staged = []
            for _ in range(3):
                s = next_source()
                staged.append((
                    server.submit(name, [s]), s, "a", graph_a, None,
                    old_epoch,
                ))
            server.register(name, graph_b)  # the hot swap
            for _ in range(3):
                s = next_source()
                staged.append((
                    server.submit(name, [s]), s, "b", graph_b, None,
                    old_epoch + 1,
                ))
            server.resume()
            for rc_ in staged:
                settle(rc_, "epoch-swap")
            disagree = any(
                not np.array_equal(
                    expect("a", graph_a, s), expect("b", graph_b, s)
                )
                for (_, s, gid, *_rest) in staged
                if gid == "a"
            )
            if not disagree:
                failures.append(
                    "epoch-swap: graphs A and B agree on every staged "
                    "source — the snapshot check proved nothing"
                )

            report = server.report()
    finally:
        set_fault(None)

    # The self-healing transitions the schedule exercised must all be
    # visible in the one metrics snapshot.
    c = report["counters"]
    for key, least in (
        ("breaker_opened", 3),       # device-fault, hung-call, quarantine
        ("breaker_half_open", 3),    # one canary per recovery
        ("breaker_closed", 3),
        ("breaker_short_circuits", 1),
        ("watchdog_timeouts", 1),
        ("integrity_failures", 1),
        ("epochs_swapped", 1),
        ("epochs_retired", 1),
        ("oracle_served", 1),
    ):
        if c.get(key, 0) < least:
            failures.append(
                f"snapshot: counter {key}={c.get(key, 0)} < {least}"
            )
    log("serve chaos metrics snapshot:")
    log(json.dumps(
        {"counters": c, "health": report["health"],
         "registry": report["registry"]},
        indent=2, sort_keys=True, default=str,
    ))
    for f in failures:
        log(f"FAIL: {f}")
    log(
        f"serve chaos: {'FAIL' if failures else 'ok'} "
        f"({len(failures)} violation(s))"
    )
    return 1 if failures else 0


#: Traversal-chaos subject configs (ISSUE 14): the superstep_ckpt CLI
#: runner's --config values — relay = single-chip packed + sparse-hybrid
#: auto-direction, multi = batched multi-source push, sharded = the x8
#: sharded relay with auto direction + auto exchange, grid = the 2D
#: r x c grid engine (ISSUE 17) with per-CELL checkpoint epochs and
#: per-axis exchange determinism, stream = the host-paged mxu arm
#: (ISSUE 18) under a one-superblock cache budget — a kill loses the
#: HBM cache (derived content) but the resumed run must replay
#: dist/parent and the direction schedule bit-identically with a cold
#: cache; the stream hit/miss/bytes ledger is deliberately NOT in the
#: deterministic key set.
TRAVERSAL_CONFIGS = ("relay", "multi", "sharded", "grid", "stream")

#: Result-document fields that must be BIT-IDENTICAL between a resumed
#: run and the un-killed golden run (dist/parent content hashes, the
#: direction schedule, the exchange-arm sequence and its per-level
#: bytes; grid runs additionally pin both per-axis byte curves and arm
#: schedules).  Fields a config does not produce are absent on both
#: sides.
TRAVERSAL_DETERMINISTIC = (
    "dist_hash", "parent_hash", "num_levels", "direction_schedule",
    "exchange_schedule", "exchange_bytes",
    "col_schedule", "col_bytes", "row_schedule", "row_bytes",
)


def run_traversal(args, cfg: str, ckpt_dir: str, out: str,
                  fault: str | None = None):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("BFS_TPU_FAULT", None)
    if fault is not None:
        env["BFS_TPU_FAULT"] = fault
    if cfg in ("sharded", "grid"):
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    cmd = [
        sys.executable, "-m", "bfs_tpu.resilience.superstep_ckpt",
        "--config", cfg, "--ckpt-dir", ckpt_dir, "--out", out,
        "--scale", str(args.scale), "--edge-factor",
        str(args.edge_factor),
        "--seed", str(args.seed if args.seed is not None else 3),
        "--interval", str(args.ckpt_interval),
    ]
    if cfg == "grid":
        # --mesh carries an 'rxc' spec through to the grid runner; the
        # bench-mode integer spelling means "not a grid shape" here.
        mesh = str(args.mesh)
        cmd += ["--mesh", mesh if "x" in mesh else "2x4"]
    proc = subprocess.run(
        cmd,
        capture_output=True, text=True, env=env, cwd=REPO_ROOT,
        timeout=args.timeout,
    )
    doc = None
    if proc.returncode == 0 and os.path.exists(out):
        with open(out, encoding="utf-8") as fh:
            doc = json.load(fh)
    return proc, doc


def chaos_traversal(args, rng: random.Random) -> int:
    """Kill-at-superstep-boundary chaos (ISSUE 14 acceptance): for each
    traversal config, one un-killed golden run pins the reference result
    document, then every iteration SIGKILLs a fresh run at a RANDOM
    segment boundary (``BFS_TPU_FAULT=kill:superstep:<n>`` — the
    boundary fires right after that epoch is durable), re-invokes with
    the same --ckpt-dir until a run completes, and diffs the resumed
    document against the golden: dist/parent content hashes, the
    direction schedule and the exchange-arm sequence must all be
    BIT-IDENTICAL, and the resumed run must actually have resumed from a
    checkpoint epoch (a silent fresh-restart would also pass the value
    diff — the ``resumed_from_epoch`` check keeps the proof honest)."""
    failures = 0
    configs = [c for c in args.traversal_configs.split(",") if c]
    for cfg in configs:
        with tempfile.TemporaryDirectory(prefix=f"chaos_tg_{cfg}_") as gd:
            gout = os.path.join(gd, "golden.json")
            log(f"[{cfg}] golden run (uninterrupted)...")
            proc, golden = run_traversal(args, cfg, gd, gout)
            if golden is None:
                log(f"[{cfg}] golden run failed rc={proc.returncode}")
                sys.stderr.write(proc.stderr[-4000:])
                return 2
            segments = int(golden["superstep_ckpt"]["segments"])
            log(f"[{cfg}] golden: levels={golden['num_levels']} "
                f"segments={segments}")
            for it in range(args.iterations):
                with tempfile.TemporaryDirectory(
                    prefix=f"chaos_t_{cfg}_"
                ) as cd:
                    rout = os.path.join(cd, "resumed.json")
                    kills = 0
                    while True:
                        n = rng.randint(1, max(1, segments))
                        fault = (
                            f"kill:superstep:{n}"
                            if kills < args.max_kills_per_iteration
                            else None
                        )
                        proc, doc = run_traversal(
                            args, cfg, cd, rout, fault=fault
                        )
                        if proc.returncode == 0:
                            break
                        if proc.returncode != -signal.SIGKILL:
                            log(f"[{cfg}] iter {it}: unexpected "
                                f"rc={proc.returncode} (fault={fault})")
                            sys.stderr.write(proc.stderr[-4000:])
                            return 2
                        kills += 1
                        log(f"[{cfg}] iter {it}: killed at boundary "
                            f"{n} (kill #{kills}); resuming...")
                    bad = []
                    if doc is None:
                        bad.append("completed run wrote no result doc")
                    else:
                        for k in TRAVERSAL_DETERMINISTIC:
                            if doc.get(k) != golden.get(k):
                                bad.append(
                                    f"{k}: resumed {doc.get(k)!r} != "
                                    f"golden {golden.get(k)!r}"
                                )
                        if (
                            kills
                            and doc["superstep_ckpt"]["resumed_from_epoch"]
                            is None
                        ):
                            bad.append(
                                "killed run's successor never resumed "
                                "from a checkpoint epoch (silent fresh "
                                "restart)"
                            )
                    if bad:
                        log(f"[{cfg}] iter {it}: FAIL after {kills} "
                            "kill(s):")
                        for b in bad:
                            log(f"  - {b}")
                        failures += 1
                    else:
                        log(f"[{cfg}] iter {it}: ok after {kills} "
                            "kill(s) — dist/parent, schedule and "
                            "exchange arms bit-identical")
    log(f"traversal chaos: "
        f"{len(configs) * args.iterations - failures}/"
        f"{len(configs) * args.iterations} ok")
    return 1 if failures else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", default="bench",
                    choices=("bench", "loadgen", "serve", "traversal"))
    ap.add_argument("--iterations", type=int, default=5)
    ap.add_argument("--seed", type=int, default=None,
                    help="RNG seed for the kill schedule (default: time)")
    ap.add_argument("--max-kills-per-iteration", type=int, default=3)
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="per-subprocess wall bound")
    # Bench shape (only used when the BENCH_* env knobs are unset).
    ap.add_argument("--scale", type=int, default=8)
    ap.add_argument("--edge-factor", type=int, default=4)
    ap.add_argument("--roots", type=int, default=3)
    ap.add_argument("--repeats", type=int, default=2)
    ap.add_argument("--engine", default="push")
    ap.add_argument("--mesh", default="",
                    help="bench mode: run the MULTICHIP bench and chaos "
                    "its journal phases (forces engine=relay). An integer "
                    "n runs the 1D sharded relay (BENCH_MESH=n); an 'rxc' "
                    "spec (e.g. 2x4) runs the 2D grid engine with per-"
                    "axis exchange determinism checks. Traversal mode "
                    "passes an rxc value through to the grid config")
    ap.add_argument("--cache-dir",
                    default=os.path.join(tempfile.gettempdir(), "chaos_cache"),
                    help="shared artifact cache across all runs (graph npz "
                    "built once)")
    # Loadgen shape.
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--loadgen-kill-max-s", type=float, default=20.0)
    # Traversal (superstep-checkpoint) schedule shape (ISSUE 14).
    ap.add_argument("--traversal-configs", default=",".join(TRAVERSAL_CONFIGS),
                    help="comma list of superstep_ckpt runner configs to "
                    "chaos (relay = packed + sparse-hybrid single chip, "
                    "multi = batched multi-source push, sharded = x8 "
                    "sharded relay with auto direction/exchange)")
    ap.add_argument("--ckpt-interval", type=int, default=2,
                    help="traversal mode: supersteps per checkpoint "
                    "segment (every:<k>)")
    # Serve (self-healing) schedule shape.
    ap.add_argument("--serve-engine", default="pull",
                    choices=("pull", "push", "relay"))
    ap.add_argument("--serve-requests", type=int, default=10,
                    help="healthy-phase query count before the faults")
    ap.add_argument("--serve-cooldown-s", type=float, default=0.5,
                    help="breaker cooldown before each half-open canary")
    ap.add_argument("--serve-delay-s", type=float, default=2.0,
                    help="injected hung-call sleep (must exceed the "
                    "deadline-tightened watchdog budget)")
    ap.add_argument("--serve-tick-timeout", type=float, default=120.0,
                    help="a reply not resolved within this bound is a "
                    "FROZEN tick (hard failure)")
    args = ap.parse_args(argv)

    seed = args.seed if args.seed is not None else int(time.time())
    log(f"kill-schedule seed: {seed}")
    rng = random.Random(seed)
    rc = {
        "bench": chaos_bench, "loadgen": chaos_loadgen, "serve": chaos_serve,
        "traversal": chaos_traversal,
    }[args.mode](args, rng)
    # Unified metrics snapshot (bfs_tpu.obs.MetricsRegistry — replaces the
    # bespoke retrace table): the driver itself runs no traced programs, so
    # non-empty retraces here mean an in-process leak; the bench/loadgen
    # SUBPROCESSES print their own snapshots in the captured logs above.
    # Importing tools/lint.py installs its stub bfs_tpu parent package
    # (ONE shared bootstrap) — obs.registry and its collaborators are
    # stdlib-only, so the snapshot costs no engine-stack jax import.
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    import lint  # noqa: F401  (side effect: stub parent package)

    from bfs_tpu.obs import get_registry

    log("driver metrics snapshot:")
    log(get_registry().to_json())
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
