import os, sys, time
sys.path.insert(0, "/root/repo"); sys.path.insert(0, "/tmp")
import importlib.util
import jax, jax.numpy as jnp, numpy as np
jax.config.update("jax_compilation_cache_dir", "/root/repo/.bench_cache/xla")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

# load round-2 kernel module standalone (it imports bfs_tpu.graph.benes for stage math)
spec = importlib.util.spec_from_file_location("benes_pallas_r2", "/tmp/benes_pallas_r2.py")
m2 = importlib.util.module_from_spec(spec)
import types
# fake package context for its relative import
m2.__package__ = "bfs_tpu.ops"
sys.modules["benes_pallas_r2"] = m2
os.environ["BFS_TPU_PALLAS"] = "1"
spec.loader.exec_module(m2)

z = np.load("/root/repo/.bench_cache/relay_v3_native_s20_ef16_seed42_block8192.npz")
net_masks = z["net_masks"]; net_size = int(z["net_size"])
print("v3 s20 net", net_size, net_masks.shape, net_masks.nbytes/1e6, "MB")
masks = jnp.asarray(net_masks)
x0 = jnp.zeros(net_size // 32, jnp.uint32)
K = 16
OPTS = {"xla_tpu_scoped_vmem_limit_kib": "65536"}
def k(x, m):
    def body(i, x):
        return m2.apply_benes_fused(x, m, n=net_size) ^ (x & 1)
    return jax.lax.fori_loop(0, K, body, x)
f = jax.jit(k)
c = f.lower(x0, masks).compile(compiler_options=OPTS)
r = c(x0, masks); _ = np.asarray(jax.device_get(r)).ravel()[0]
best=1e9
for _ in range(8):
    t0=time.perf_counter(); r=c(x0,masks); _=np.asarray(jax.device_get(r)).ravel()[0]
    best=min(best,time.perf_counter()-t0)
t=(best-0.11)/K
print(f"ROUND-2 kernel full net: {t*1000:.2f} ms/iter -> {net_masks.nbytes/t/1e9:.0f} GB/s")
