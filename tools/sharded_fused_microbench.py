"""Sharded-relay applier microbench on a REAL-chip 1-device mesh.

VERDICT r3 weak #5: the mesh path applied the Beneš networks with the
per-stage XLA path only (~55 launches x ~0.4 ms/superstep of launch
overhead), so the ARCHITECTURE §6 real-hardware model described a program
that could not run.  parallel/sharded.py now routes the fused 3-pass Pallas
kernels through ``shard_map`` (applier='auto'/'pallas'); this tool proves
the sharded program COMPILES AND RUNS on real TPU hardware and measures the
per-superstep cost of both appliers on the same mesh — the kernel-count
collapse (~55 stage kernels + launch train -> 3 fused passes/network).

Runs on the one available chip as a graph=1 mesh (the per-shard program is
identical at any shard count; only the all-gather width changes).
"""
import os
import sys
import time

sys.path.insert(0, "/root/repo")
import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.bench_cache/xla")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

from bfs_tpu.bench import load_or_build
from bfs_tpu.graph.relay import build_sharded_relay_graph
from bfs_tpu.oracle.bfs import canonical_bfs  # noqa: F401 (host check path)
from bfs_tpu.parallel import sharded as S

SCALE = int(os.environ.get("MB_SCALE", "20"))
EF = int(os.environ.get("MB_EF", "16"))

dg, source = load_or_build(SCALE, EF, 42, 8192, "native")
from bfs_tpu.graph.csr import Graph, unpad_edges

esrc, edst = unpad_edges(dg)
g = Graph(dg.num_vertices, esrc, edst)
srg = build_sharded_relay_graph(g, 1)
mesh = S.make_mesh(graph=1, batch=1, devices=jax.devices()[:1])

print(
    f"s{SCALE} ef{EF}: V={dg.num_vertices}, E={dg.num_edges}, "
    f"per-shard net 2^{int(np.log2(srg.net_size))}", flush=True,
)

import jax.numpy as jnp

results = {}
for applier in ("pallas", "xla"):
    use_pallas = applier == "pallas"
    static = S._sharded_relay_static(srg, 1, use_pallas)
    vperm_arg, net_arg = S._sharded_relay_mask_args(srg, use_pallas)
    valid = S._relay_valid_words(srg)
    src_new = jnp.int32(int(srg.old2new[source]))
    # Dense-only flavor (direction=None, adjacency dummies): the applier
    # comparison this tool measures is the Beneš-network superstep.
    args = (
        vperm_arg, net_arg, valid, S._own_word_table_dev(srg),
        *S._sharded_adj_dummies(1), jnp.zeros((1,), jnp.int32), src_new,
    )
    max_levels = srg.num_vertices
    t0 = time.perf_counter()
    from bfs_tpu.models.bfs import RelayEngine
    from bfs_tpu.parallel.exchange import resolve_exchange

    compiled = S._bfs_sharded_relay_fused.lower(
        *args, mesh=mesh, static=static, max_levels=max_levels,
        exchange=resolve_exchange().key(),
    ).compile(compiler_options=RelayEngine._COMPILER_OPTIONS)
    t_compile = time.perf_counter() - t0
    dist, parent, level, _changed = compiled(*args)
    levels = int(np.asarray(jax.device_get(level)))  # warm + sync
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        dist, parent, level, _changed = compiled(*args)
        _ = int(np.asarray(jax.device_get(level)))
        times.append(time.perf_counter() - t0)
    t = float(np.median(times))
    per_ss = t / max(levels, 1)
    results[applier] = (dist, parent)
    print(
        f"sharded-relay applier={applier:6s}: search {t*1000:8.1f} ms "
        f"({levels} supersteps, {per_ss*1000:6.1f} ms/superstep; "
        f"compile {t_compile:.1f} s; device buffers staged once)",
        flush=True,
    )

np.testing.assert_array_equal(
    np.asarray(jax.device_get(results["pallas"][0])),
    np.asarray(jax.device_get(results["xla"][0])),
)
np.testing.assert_array_equal(
    np.asarray(jax.device_get(results["pallas"][1])),
    np.asarray(jax.device_get(results["xla"][1])),
)
print("pallas vs xla sharded results: bit-exact", flush=True)
