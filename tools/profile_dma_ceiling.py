import os, sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np
from functools import partial
jax.config.update("jax_compilation_cache_dir", "/root/repo/.bench_cache/xla")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES=128
OPTS = {"xla_tpu_scoped_vmem_limit_kib": "65536"}
NROWS = 65536   # 32MB mask-like array
NS = 8          # 8 "stages" per x-pass
TR = 2048
m_np = np.random.default_rng(0).integers(0, 2**32, (NS*NROWS//8, LANES), dtype=np.uint32)  # 4MB*8 stages... rows per stage = NROWS//8
m = jnp.asarray(m_np)
rows_per_stage = NROWS//8
x0 = jnp.zeros((NROWS//8, LANES), jnp.uint32)   # x same size as one stage

def make_kernel(compute):
    def kernel(x_ref, m_hbm, o_ref, mbuf, sem):
        pid = pl.program_id(0)
        xv = x_ref[...]
        def dma(slot, si):
            return pltpu.make_async_copy(
                m_hbm.at[pl.ds(si*rows_per_stage + pid*TR, TR), :],
                mbuf.at[slot], sem.at[slot])
        dma(0, 0).start()
        for si in range(NS):
            if si+1 < NS: dma((si+1)%2, si+1).start()
            dma(si%2, si).wait()
            if compute == "or":
                xv = xv | mbuf[si%2]
            elif compute == "butterfly":
                mm = mbuf[si%2]
                t = (xv ^ (xv >> jnp.uint32(4))) & mm
                xv = xv ^ t ^ (t << jnp.uint32(4))
        o_ref[...] = xv
    return kernel

def bench(compute, K=8):
    kern = make_kernel(compute)
    @jax.jit
    def f(x, m):
        def body(i, x):
            y = pl.pallas_call(kern,
                grid=(rows_per_stage//TR,),
                in_specs=[pl.BlockSpec((TR, LANES), lambda i: (i, 0)), pl.BlockSpec(memory_space=pl.ANY)],
                out_specs=pl.BlockSpec((TR, LANES), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, jnp.uint32),
                scratch_shapes=[pltpu.VMEM((2, TR, LANES), jnp.uint32), pltpu.SemaphoreType.DMA((2,))],
            )(x, m)
            return y ^ (x & 1)
        return jax.lax.fori_loop(0, K, body, x)
    c = f.lower(x0, m).compile(compiler_options=OPTS)
    r = c(x0, m); _ = np.asarray(jax.device_get(r)).ravel()[0]
    best = 1e9
    for _ in range(6):
        t0=time.perf_counter(); r=c(x0,m); _=np.asarray(jax.device_get(r)).ravel()[0]
        best=min(best, time.perf_counter()-t0)
    t=(best-0.11)/K
    bw = m_np.nbytes/t/1e9
    print(f"{compute:12s}: {t*1000:6.2f} ms/pass  -> {bw:5.0f} GB/s", flush=True)

bench("none")
bench("or")
bench("butterfly")
