"""Pre-build the bench matrix's persistent artifacts so a driver run is
warm from its first second (ISSUE 2 satellite).

For every scale in the matrix (BENCH_SCALE + BENCH_FALLBACK_SCALES by
default) this builds-or-loads, in order:

  1. the device-ready R-MAT graph npz (bench.load_or_build);
  2. the relay layout bundle (content-addressed, memmap-loadable —
     bfs_tpu/cache/layout.py) and, with --pull, the ELL pull bundle;
  3. with --compile (TPU backends only), the fused single-source relay
     program, populating the serialized-executable cache the bench loads
     from (models/bfs.py compile_exe_cached).

Each step prints its warm/cold status and timing; the final line is the
artifact-cache counter report.  Run it once per (machine, cache dir) —
CI/driver runs then start with every cold cost already paid:

    python tools/cache_warm.py --scales 24,22,20 --compile
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--scales",
        default=None,
        help="comma-separated R-MAT scales (default: BENCH_SCALE + "
        "BENCH_FALLBACK_SCALES, i.e. the bench matrix)",
    )
    ap.add_argument("--edge-factor", type=int,
                    default=int(os.environ.get("BENCH_EDGE_FACTOR", "6")))
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--block", type=int, default=8 * 1024)
    ap.add_argument("--pull", action="store_true",
                    help="also warm the ELL pull-layout bundles")
    ap.add_argument("--compile", action="store_true",
                    help="also AOT-compile the fused relay program per "
                    "scale (TPU backends; populates the exe cache)")
    args = ap.parse_args(argv)

    from bfs_tpu.config import enable_compile_cache

    print(f"caches: {json.dumps(enable_compile_cache())}", flush=True)

    if args.scales:
        scales = [int(s) for s in args.scales.split(",") if s.strip()]
    else:
        scales = [int(os.environ.get("BENCH_SCALE", "24"))] + [
            int(s)
            for s in os.environ.get("BENCH_FALLBACK_SCALES", "22,20").split(",")
            if s.strip()
        ]
    scales = sorted(set(scales), reverse=True)

    import jax

    from bfs_tpu.bench import (
        _generator_backend,
        load_or_build,
        load_or_build_pull,
        load_or_build_relay,
    )

    backend = _generator_backend()
    for scale in scales:
        key = (
            f"{backend}_s{scale}_ef{args.edge_factor}_seed{args.seed}"
            f"_block{args.block}"
        )
        t0 = time.perf_counter()
        dg, source = load_or_build(
            scale, args.edge_factor, args.seed, args.block, backend
        )
        print(
            f"s{scale}: graph ready in {time.perf_counter() - t0:.1f}s "
            f"(V={dg.num_vertices} E={dg.num_edges})",
            flush=True,
        )
        t0 = time.perf_counter()
        rg, build_seconds = load_or_build_relay(dg, key)
        print(
            f"s{scale}: relay layout ready in {time.perf_counter() - t0:.1f}s "
            f"(cold build was {build_seconds:.1f}s)",
            flush=True,
        )
        if args.pull:
            t0 = time.perf_counter()
            load_or_build_pull(dg, key)
            print(
                f"s{scale}: pull layout ready in "
                f"{time.perf_counter() - t0:.1f}s",
                flush=True,
            )
        if args.compile:
            if jax.default_backend() != "tpu":
                print(
                    f"s{scale}: --compile skipped (backend is "
                    f"{jax.default_backend()}, exe cache is TPU-only)",
                    flush=True,
                )
            else:
                from bfs_tpu.models.bfs import RelayEngine

                from bfs_tpu.bench import _mark_exe_warm

                t0 = time.perf_counter()
                eng = RelayEngine(rg, sparse_hybrid=False)
                _ = int(eng.run_many_device([source])[-1].level)
                _mark_exe_warm(key)
                print(
                    f"s{scale}: fused program compiled + warm in "
                    f"{time.perf_counter() - t0:.1f}s "
                    f"(applier={eng.applier})",
                    flush=True,
                )

    from bfs_tpu.utils.metrics import artifact_report

    print(json.dumps({"artifact_caches": artifact_report()}), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
