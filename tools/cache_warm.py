"""Pre-build the bench matrix's persistent artifacts so a driver run is
warm from its first second (ISSUE 2 satellite).

For every scale in the matrix (BENCH_SCALE + BENCH_FALLBACK_SCALES by
default) this builds-or-loads, in order:

  1. the device-ready R-MAT graph npz (bench.load_or_build);
  2. the relay layout bundle (content-addressed, memmap-loadable —
     bfs_tpu/cache/layout.py) and, with --pull, the ELL pull bundle;
  3. with --compile (TPU backends only), the fused single-source relay
     program, populating the serialized-executable cache the bench loads
     from (models/bfs.py compile_exe_cached).

Each step prints its warm/cold status and timing; the final line is the
artifact-cache counter report.  Run it once per (machine, cache dir) —
CI/driver runs then start with every cold cost already paid:

    python tools/cache_warm.py --scales 24,22,20 --compile
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _compare_builders(dg, scale: int, reps: int) -> None:
    """Time ``reps`` interleaved UNCACHED builds per flavor and print the
    medians — the ISSUE 10 build-seconds evidence table
    (BENCHMARKS.md 'Layout build: device vs host')."""
    import statistics

    from bfs_tpu.graph.relay import build_relay_graph
    from bfs_tpu.graph.relay_device import build_relay_graph_device

    build_relay_graph(dg)  # warm both paths once (numpy/native/jit caches)
    stages: dict = {}
    build_relay_graph_device(dg, stage_times=stages)
    host_s, dev_s, deltas = [], [], []
    for i in range(reps):
        # Alternate which flavor builds first: the SECOND build of a pair
        # measures ~2-3 ms slower at toy scale (allocator/cache pollution
        # from its predecessor), so a fixed order would bias the
        # comparison by more than the effect being measured.
        order = ("host", "device") if i % 2 == 0 else ("device", "host")
        pair = {}
        for flavor in order:
            t0 = time.perf_counter()
            if flavor == "host":
                build_relay_graph(dg)
            else:
                build_relay_graph_device(dg)
            pair[flavor] = time.perf_counter() - t0
        host_s.append(pair["host"])
        dev_s.append(pair["device"])
        deltas.append(pair["host"] - pair["device"])
    print(
        json.dumps({
            "scale": scale,
            "reps": reps,
            "host_build_s": {
                "median": statistics.median(host_s), "min": min(host_s),
            },
            "device_build_s": {
                "median": statistics.median(dev_s), "min": min(dev_s),
            },
            "paired_delta_s_median": statistics.median(deltas),
            "device_wins": sum(1 for d in deltas if d > 0),
            "device_stage_seconds": {
                k: round(v, 5) if isinstance(v, float) else v
                for k, v in stages.items()
            },
        }),
        flush=True,
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--scales",
        default=None,
        help="comma-separated R-MAT scales (default: BENCH_SCALE + "
        "BENCH_FALLBACK_SCALES, i.e. the bench matrix)",
    )
    ap.add_argument("--edge-factor", type=int,
                    default=int(os.environ.get("BENCH_EDGE_FACTOR", "6")))
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--block", type=int, default=8 * 1024)
    ap.add_argument("--pull", action="store_true",
                    help="also warm the ELL pull-layout bundles")
    ap.add_argument("--tiles", action="store_true",
                    help="also prebuild + verify the adj-tiles sidecar "
                    "bundle per scale (the streamed arm's host-store "
                    "feed, ISSUE 18): builds through the on-disk layout "
                    "cache, re-loads it fingerprint-checked, and prints "
                    "superblock counts + host-store bytes")
    ap.add_argument("--labels", action="store_true",
                    help="also prebuild + verify the landmark distance-"
                    "label sidecar per scale (the serve label tier's "
                    "index, ISSUE 20): builds through the on-disk layout "
                    "cache, re-loads it fingerprint-checked, and prints "
                    "K, index bytes, and build seconds")
    ap.add_argument("--landmarks", type=int, metavar="K", default=0,
                    help="landmark count for --labels (default: the "
                    "BFS_TPU_LABELS knob, or 32 when that is off)")
    ap.add_argument("--compile", action="store_true",
                    help="also AOT-compile the fused relay program per "
                    "scale (TPU backends; populates the exe cache)")
    ap.add_argument("--builder", choices=("auto", "device", "host"),
                    default="auto",
                    help="relay layout builder flavor for cold builds "
                    "(default: BFS_TPU_LAYOUT_BUILD, i.e. device)")
    ap.add_argument("--compare", type=int, metavar="N", default=0,
                    help="instead of warming, time N interleaved UNCACHED "
                    "builds per flavor per scale and print a "
                    "device-vs-host build-seconds table")
    args = ap.parse_args(argv)

    from bfs_tpu.config import enable_compile_cache

    print(f"caches: {json.dumps(enable_compile_cache())}", flush=True)

    if args.scales:
        scales = [int(s) for s in args.scales.split(",") if s.strip()]
    else:
        scales = [int(os.environ.get("BENCH_SCALE", "24"))] + [
            int(s)
            for s in os.environ.get("BENCH_FALLBACK_SCALES", "22,20").split(",")
            if s.strip()
        ]
    scales = sorted(set(scales), reverse=True)

    import jax

    from bfs_tpu.bench import (
        _generator_backend,
        load_or_build,
        load_or_build_pull,
        load_or_build_relay,
    )

    backend = _generator_backend()
    if args.builder != "auto":
        os.environ["BFS_TPU_LAYOUT_BUILD"] = args.builder
    for scale in scales:
        key = (
            f"{backend}_s{scale}_ef{args.edge_factor}_seed{args.seed}"
            f"_block{args.block}"
        )
        t0 = time.perf_counter()
        dg, source = load_or_build(
            scale, args.edge_factor, args.seed, args.block, backend
        )
        print(
            f"s{scale}: graph ready in {time.perf_counter() - t0:.1f}s "
            f"(V={dg.num_vertices} E={dg.num_edges})",
            flush=True,
        )
        if args.compare:
            _compare_builders(dg, scale, args.compare)
            continue
        t0 = time.perf_counter()
        rg, build_seconds = load_or_build_relay(dg, key)
        from bfs_tpu.bench import _LAST_RELAY_INFO

        print(
            f"s{scale}: relay layout ready in {time.perf_counter() - t0:.1f}s "
            f"(cold build was {build_seconds:.1f}s, "
            f"builder={_LAST_RELAY_INFO.get('builder', 'host')})",
            flush=True,
        )
        if args.pull:
            t0 = time.perf_counter()
            load_or_build_pull(dg, key)
            print(
                f"s{scale}: pull layout ready in "
                f"{time.perf_counter() - t0:.1f}s",
                flush=True,
            )
        if args.tiles:
            from bfs_tpu.cache.layout import (
                LayoutCache,
                load_or_build_tiles,
                verify_tiles_bundle,
            )
            from bfs_tpu.stream import HostTileStore

            tile_cache = LayoutCache()
            t0 = time.perf_counter()
            at, tinfo = load_or_build_tiles(rg, cache=tile_cache)
            verdict = verify_tiles_bundle(rg, cache=tile_cache)
            store_report = HostTileStore(at).report()
            print(
                f"s{scale}: adj-tiles sidecar ready in "
                f"{time.perf_counter() - t0:.1f}s "
                f"(cache={tinfo.get('cache')}, "
                f"verify={'ok' if verdict['ok'] else verdict['status']})",
                flush=True,
            )
            print(
                json.dumps({
                    "scale": scale,
                    "tiles_key": verdict["key"],
                    "verify_ok": verdict["ok"],
                    **store_report,
                }),
                flush=True,
            )
            if not verdict["ok"]:
                return 1
        if args.labels:
            from bfs_tpu import knobs
            from bfs_tpu.cache.layout import (
                LayoutCache,
                load_or_build_labels,
                verify_labels_bundle,
            )

            k = args.landmarks or knobs.get("BFS_TPU_LABELS") or 32
            label_cache = LayoutCache()
            t0 = time.perf_counter()
            idx, linfo = load_or_build_labels(dg, k, cache=label_cache)
            lverdict = verify_labels_bundle(dg, k, cache=label_cache)
            print(
                f"s{scale}: label sidecar ready in "
                f"{time.perf_counter() - t0:.1f}s "
                f"(K={idx.k}, index={idx.device_bytes >> 20} MB on device, "
                f"cold build was {linfo.get('build_seconds', -1.0):.1f}s, "
                f"cache={linfo.get('cache')}, "
                f"verify={'ok' if lverdict['ok'] else lverdict['status']})",
                flush=True,
            )
            print(
                json.dumps({
                    "scale": scale,
                    "labels_key": lverdict["key"],
                    "verify_ok": lverdict["ok"],
                    "k": idx.k,
                    "index_bytes": idx.nbytes,
                    "device_bytes": idx.device_bytes,
                    "build_seconds": linfo.get("build_seconds", -1.0),
                }),
                flush=True,
            )
            if not lverdict["ok"]:
                return 1
        if args.compile:
            if jax.default_backend() != "tpu":
                print(
                    f"s{scale}: --compile skipped (backend is "
                    f"{jax.default_backend()}, exe cache is TPU-only)",
                    flush=True,
                )
            else:
                from bfs_tpu.models.bfs import RelayEngine

                from bfs_tpu.bench import _mark_exe_warm

                t0 = time.perf_counter()
                eng = RelayEngine(rg, sparse_hybrid=False)
                _ = int(eng.run_many_device([source])[-1].level)
                _mark_exe_warm(key)
                print(
                    f"s{scale}: fused program compiled + warm in "
                    f"{time.perf_counter() - t0:.1f}s "
                    f"(applier={eng.applier})",
                    flush=True,
                )

    from bfs_tpu.utils.metrics import artifact_report

    print(json.dumps({"artifact_caches": artifact_report()}), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
