"""Round-3 device microbench: program overhead, gather/scatter rates."""
import os, time
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.bench_cache/xla")
import jax, jax.numpy as jnp, numpy as np
jax.config.update("jax_compilation_cache_dir", "/root/repo/.bench_cache/xla")
dev = jax.devices()[0]
print("device", dev)

def timeit(fn, n=20):
    fn()  # warm
    _ = int(jax.device_get(fn())[0]) if hasattr(fn(), '__getitem__') else None
    ts=[]
    for _ in range(n):
        t0=time.perf_counter(); r=fn(); v=np.asarray(jax.device_get(r)).ravel()[0]; ts.append(time.perf_counter()-t0)
    return float(np.median(ts))

# 1. fixed program overhead: trivial program
@jax.jit
def trivial(x): return x + 1
x0 = jnp.zeros((8,), jnp.int32)
t = timeit(lambda: trivial(x0))
print(f"trivial program round-trip: {t*1000:.1f} ms")

# small while_loop program (6 iterations of tiny work) - mimics bfs structure
@jax.jit
def loop6(x):
    def body(c):
        i, x = c
        return i+1, x*2+1
    return jax.lax.while_loop(lambda c: c[0]<6, body, (0, x))[1]
t = timeit(lambda: loop6(x0))
print(f"6-iter while_loop round-trip: {t*1000:.1f} ms")

# 2. gather rates at various sizes
V = 1<<24
table = jnp.arange(V, dtype=jnp.int32)
for sz in [1<<15, 1<<17, 1<<19, 1<<21, 1<<23]:
    idx = jnp.asarray(np.random.default_rng(0).integers(0, V, sz).astype(np.int32))
    @jax.jit
    def g(idx):
        # loop K times to amortize: chain to prevent CSE
        def body(i, acc):
            return acc + table[(idx + acc[0]) & (V-1)].sum()//jnp.int32(1<<30)
        K=8
        acc = jnp.zeros((1,), jnp.int32)
        for _ in range(K): acc = acc + table[(idx + acc[0]) & (V-1)][:8]
        return acc
    t = timeit(lambda: g(idx), n=8)
    rate = 8*sz/ t / 1e9
    print(f"gather {sz>>10}K elems x8: {t*1000:.1f} ms -> {rate:.3f} G/s")

# 3. scatter-min rate
for sz in [1<<17, 1<<21]:
    idx = jnp.asarray(np.random.default_rng(1).integers(0, V, sz).astype(np.int32))
    vals = jnp.asarray(np.random.default_rng(2).integers(0, 1<<30, sz).astype(np.int32))
    @jax.jit
    def s(idx, vals):
        out = jnp.full((V,), np.int32(2**31-1))
        for k in range(4):
            out = out.at[(idx+k) & (V-1)].min(vals)
        return out[:8]
    t = timeit(lambda: s(idx, vals), n=8)
    print(f"scatter-min {sz>>10}K x4: {t*1000:.1f} ms -> {4*sz/t/1e9:.3f} G/s")

# 4. dense V-sized pass (nonzero-style cumsum) cost
big = jnp.zeros((1<<24,), jnp.uint8)
@jax.jit
def scan_cost(b):
    c = jnp.cumsum(b.astype(jnp.int32))
    return c[-8:]
t = timeit(lambda: scan_cost(big), n=8)
print(f"cumsum over 2^24: {t*1000:.1f} ms")
