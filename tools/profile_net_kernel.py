"""Isolated net-kernel timing at a given scale with variants."""
import os, sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np
jax.config.update("jax_compilation_cache_dir", "/root/repo/.bench_cache/xla")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
from bfs_tpu.bench import load_or_build, load_or_build_relay
from bfs_tpu.ops import relay_pallas as RP

scale = int(os.environ.get("P_SCALE", "20"))
ef = int(os.environ.get("P_EF", "16"))
dg, source = load_or_build(scale, ef, 42, 8192, "native")
rg, _ = load_or_build_relay(dg, f"native_s{scale}_ef{ef}_seed42_block8192")
K = int(os.environ.get("P_K", "16"))
OPTS = {"xla_tpu_scoped_vmem_limit_kib": "65536"}

net_static = RP.pass_static(rg.net_table, rg.net_size)
arrays = [jnp.asarray(a) for a in RP.prepare_pass_masks(rg.net_masks, rg.net_table, rg.net_size)]
print("passes:", [(m[0], len(m[3])) for m in net_static], "mask MB", rg.net_masks.nbytes/1e6)

def bench(fn, args, label):
    f = jax.jit(fn)
    c = f.lower(*args).compile(compiler_options=OPTS)
    r = c(*args); _ = np.asarray(jax.device_get(r)).ravel()[0]
    ts=[]
    for _ in range(3):
        t0=time.perf_counter(); r=c(*args); _ = np.asarray(jax.device_get(r)).ravel()[0]
        ts.append(time.perf_counter()-t0)
    t=(min(ts)-0.107)/K
    bw = rg.net_masks.nbytes/t/1e9
    print(f"{label:24s}: {t*1000:7.2f} ms/iter  ({bw:.0f} GB/s mask stream)")

x0 = jnp.zeros(rg.net_size // 32, jnp.uint32)

def k_full(x, *m):
    def body(i, x):
        return RP.apply_benes_fused(x, m, net_static, rg.net_size) ^ (x & 1)
    return jax.lax.fori_loop(0, K, body, x)
bench(k_full, (x0, *arrays), "all passes")

# each pass alone
for pi, (ps, arr) in enumerate(zip(net_static, arrays)):
    def k_pass(x, m, ps=ps):
        def body(i, x):
            return RP._run_pass(x, m, ps[0], ps[1], ps[2], ps[3], rg.net_size, False) ^ (x & 1)
        return jax.lax.fori_loop(0, K, body, x)
    bench(k_pass, (x0, arr), f"pass {pi} ({ps[0]}, {len(ps[3])} stages)")
