import os, sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np
jax.config.update("jax_compilation_cache_dir", "/root/repo/.bench_cache/xla")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
LANES=128; OPTS={"xla_tpu_scoped_vmem_limit_kib": "65536"}
NS=16; TR=2048; RPS=8192
m_np = np.random.default_rng(0).integers(0,2**32,(NS*RPS,LANES),dtype=np.uint32)
mdev = jnp.asarray(m_np.reshape(NS, RPS, LANES))
x0 = jnp.zeros((RPS, LANES), jnp.uint32)
K=8

def run(c, args):
    r=c(*args); _=np.asarray(jax.device_get(r)).ravel()[0]
    best=1e9
    for _ in range(6):
        t0=time.perf_counter(); r=c(*args); _=np.asarray(jax.device_get(r)).ravel()[0]
        best=min(best,time.perf_counter()-t0)
    return (best-0.11)/K

# A: auto-pipelined masks: grid (tiles, NS); x revisited per tile
def kernel_a(x_ref, m_ref, o_ref):
    si = pl.program_id(1)
    xv = x_ref[...] if False else None
    mm = m_ref[0]
    @pl.when(si == 0)
    def _():
        o_ref[...] = x_ref[...]
    xv = o_ref[...]
    t = (xv ^ (xv >> jnp.uint32(4))) & mm
    o_ref[...] = xv ^ t ^ (t << jnp.uint32(4))

@jax.jit
def fa(x, m):
    def body(i, x):
        y = pl.pallas_call(kernel_a, grid=(RPS//TR, NS),
            in_specs=[pl.BlockSpec((TR,LANES), lambda i,s:(i,0)),
                      pl.BlockSpec((1,TR,LANES), lambda i,s:(s,i,0))],
            out_specs=pl.BlockSpec((TR,LANES), lambda i,s:(i,0)),
            out_shape=jax.ShapeDtypeStruct(x.shape, jnp.uint32),
        )(x, m)
        return y ^ (x & 1)
    return jax.lax.fori_loop(0, K, body, x)
ca = fa.lower(x0, mdev).compile(compiler_options=OPTS)
t = run(ca, (x0, mdev))
print(f"auto-pipelined: {t*1000:6.2f} ms/pass -> {m_np.nbytes/t/1e9:5.0f} GB/s", flush=True)

# B: XLA elementwise same math per stage (unrolled over NS on full arrays)
@jax.jit
def fb(x, m):
    def body(i, x):
        def stage(s, xv):
            mm = jax.lax.dynamic_index_in_dim(m, s, 0, keepdims=False)
            t = (xv ^ (xv >> jnp.uint32(4))) & mm[: xv.shape[0]]
            return xv ^ t ^ (t << jnp.uint32(4))
        y = jax.lax.fori_loop(0, NS, stage, x)
        return y ^ (x & 1)
    return jax.lax.fori_loop(0, K, body, x)
cb = fb.lower(x0, mdev).compile(compiler_options=OPTS)
t = run(cb, (x0, mdev))
print(f"XLA per-stage : {t*1000:6.2f} ms/pass -> {m_np.nbytes/t/1e9:5.0f} GB/s", flush=True)
