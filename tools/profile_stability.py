import os, sys, time
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp, numpy as np
jax.config.update("jax_compilation_cache_dir", "/root/repo/.bench_cache/xla")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
from bfs_tpu.bench import load_or_build, load_or_build_relay
from bfs_tpu.ops import relay_pallas as RP

dg, _ = load_or_build(20, 16, 42, 8192, "native")
rg, _ = load_or_build_relay(dg, "native_s20_ef16_seed42_block8192")
K = 16
OPTS = {"xla_tpu_scoped_vmem_limit_kib": "65536"}
net_static = RP.pass_static(rg.net_table, rg.net_size)
arrays = [jnp.asarray(a) for a in RP.prepare_pass_masks(rg.net_masks, rg.net_table, rg.net_size)]
x0 = jnp.zeros(rg.net_size // 32, jnp.uint32)

def k_full(x, *m):
    def body(i, x):
        return RP.apply_benes_fused(x, m, net_static, rg.net_size) ^ (x & 1)
    return jax.lax.fori_loop(0, K, body, x)
f = jax.jit(k_full)
c = f.lower(x0, *arrays).compile(compiler_options=OPTS)
r = c(x0, *arrays); _ = np.asarray(jax.device_get(r)).ravel()[0]
ts=[]
for i in range(10):
    t0=time.perf_counter(); r=c(x0, *arrays); _ = np.asarray(jax.device_get(r)).ravel()[0]
    ts.append(time.perf_counter()-t0)
print("full-net K=16 raw times:", [f"{t:.3f}" for t in ts])
# trivial program latency right now
@jax.jit
def triv(x): return x + 1
t_ = triv(jnp.zeros(8)); _ = np.asarray(jax.device_get(t_))[0]
ts2=[]
for i in range(5):
    t0=time.perf_counter(); t_=triv(jnp.zeros(8)); _=np.asarray(jax.device_get(t_))[0]
    ts2.append(time.perf_counter()-t0)
print("trivial roundtrip:", [f"{t:.3f}" for t in ts2])
