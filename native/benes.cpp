// Beneš network router: compile an arbitrary static permutation into
// per-stage butterfly switch masks.
//
// Why this exists: on TPU, XLA lowers per-element gather/scatter to scalar
// loops (~0.12 G/s measured on v5e) while dense vector ops run at memory
// bandwidth (~200 Gint32/s).  BFS frontier exchange is a fixed permutation
// of edge slots (src-grouped order -> dst-grouped order), so we route it
// through a Beneš network: 2*log2(N)-1 butterfly stages of conditional
// pair-swaps whose control bits are precomputed here, once per graph.  Each
// superstep then applies the stages as pure elementwise ops on bit-packed
// words — the TPU-native replacement for the reference's Spark shuffle
// (BfsSpark.java:90-108 reduceByKey wire transfer).
//
// Conventions (must match bfs_tpu/ops/relay.py):
//   * N = 2^k elements; stage s in [0, 2k-1) has pair distance
//     d_s = N >> (s+1) for s < k, and N >> (2k-1-s) for s >= k
//     (distances N/2, N/4, ..., 2, 1, 2, ..., N/4, N/2).
//   * A stage swaps x[i] <-> x[i+d] iff mask bit i is set; mask bits are
//     stored only at the lower index of each pair (i with (i & d) == 0).
//   * Masks are bit-packed little-endian into int32 words: bit i of the
//     stage mask = (mask_words[i >> 5] >> (i & 31)) & 1.
//   * The network computes y with y[j] = x[perm[j]].

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <ctime>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

#if defined(__linux__)
#include <sys/mman.h>
#endif

namespace {

// 2MB-page allocation for the router's working set (a/b/inv — 5 GB at
// n=2^28), which is walked in a random dependent-miss pattern: on 4 KB
// pages nearly every access is also a TLB miss whose page walk serializes
// with the data miss.  Preference order:
//   1. mmap(MAP_HUGETLB) — explicit 2 MB pages, measured +21-26% on the
//      build VM's interleaved pointer chase.  Requires a reservation
//      (/proc/sys/vm/nr_hugepages); bfs_tpu/graph/benes.py::route_std
//      raises it best-effort before routing and restores the prior value
//      after (BFS_TPU_HUGEPAGES=0 skips).
//   2. posix_memalign + MADV_HUGEPAGE — worthless on the build VM (the
//      kernel grants 0 huge pages in madvise mode there, verified via
//      smaps_rollup), but correct where THP actually works.
struct HugeBuf {
  void* p = nullptr;
  size_t bytes = 0;
  bool mapped = false;
  explicit HugeBuf(size_t n_bytes) {
    constexpr size_t kHuge = size_t{2} << 20;
    bytes = (n_bytes + kHuge - 1) & ~(kHuge - 1);
#if defined(__linux__) && defined(MAP_HUGETLB)
    void* m = mmap(nullptr, bytes, PROT_READ | PROT_WRITE,
                   MAP_PRIVATE | MAP_ANONYMOUS | MAP_HUGETLB, -1, 0);
    if (m != MAP_FAILED) {
      p = m;
      mapped = true;
      return;
    }
#endif
    if (posix_memalign(&p, kHuge, bytes) != 0) {
      p = nullptr;
      bytes = 0;
      return;
    }
#if defined(__linux__) && defined(MADV_HUGEPAGE)
    madvise(p, bytes, MADV_HUGEPAGE);
#endif
  }
  ~HugeBuf() {
#if defined(__linux__) && defined(MAP_HUGETLB)
    if (mapped) {
      munmap(p, bytes);
      return;
    }
#endif
    std::free(p);
  }
  HugeBuf(const HugeBuf&) = delete;
  HugeBuf& operator=(const HugeBuf&) = delete;
  int32_t* i32() const { return static_cast<int32_t*>(p); }
};

// Route one Beneš block covering positions [base, base+n) at recursion
// level l.  perm is block-local: output slot j (local) must receive the
// element entering at block-local input slot perm[j].  Writes the in-stage
// (stage l) and out-stage (stage 2k-2-l) mask bits, builds the two
// half-size sub-permutations, and recurses.
struct Router {
  int64_t n_total;
  int32_t k;  // log2(n_total)
  uint32_t* masks;         // [num_stages][n_total/32] packed words
  int64_t words_per_stage;
  int32_t parallel_levels = 3;  // thread fan-out depth (2^d subtrees)

  // Masks are written WORD-MAJOR here regardless of the requested layout:
  // sibling subtrees cover disjoint pow2-aligned position ranges, which in
  // word-major packing touch disjoint words — so the threaded fan-out needs
  // no atomics.  (Bit-major interleaves positions h apart into the same
  // word.)  benes_route transposes to bit-major afterwards if asked.
  void set_bit(int32_t stage, int64_t pos) {
    masks[stage * words_per_stage + (pos >> 5)] |=
        (uint32_t{1} << (pos & 31));
  }

  void route(int64_t base, int64_t n, int32_t level,
             std::vector<int64_t>& perm) {
    if (n == 1) return;
    const int64_t h = n / 2;
    const int32_t in_stage = level;
    const int32_t out_stage = 2 * k - 2 - level;
    if (n == 2) {
      // Single middle stage: swap iff output 0 takes input 1.
      if (perm[0] == 1) set_bit(in_stage, base);
      return;
    }
    // inv[i] = output slot consuming input i.
    std::vector<int64_t> inv(n);
    for (int64_t j = 0; j < n; ++j) inv[perm[j]] = j;
    // color[j] in {0,1}: which subnet (0 = upper half) output j routes
    // through.  Constraints: paired outputs (j, j^h... j and j+h) differ;
    // outputs consuming paired inputs (i, i+h) differ.
    std::vector<int8_t> color(n, -1);
    for (int64_t seed = 0; seed < n; ++seed) {
      if (color[seed] != -1) continue;
      int64_t j = seed;
      int8_t c = 0;
      while (color[j] == -1) {
        color[j] = c;
        // Output partner must take the other subnet.
        const int64_t jp = (j < h) ? j + h : j - h;
        if (color[jp] == -1) {
          color[jp] = int8_t(1 - c);
          // The input paired with jp's source forces its consumer's color.
          const int64_t i = perm[jp];
          const int64_t ip = (i < h) ? i + h : i - h;
          j = inv[ip];
          c = c;  // consumer of ip must differ from consumer of i -> same c
          continue;
        }
        break;
      }
    }
    // In-stage switches: input pair (p, p+h).  After the stage, position p
    // carries the upper-subnet element.  Swap iff x[p] must go lower.
    for (int64_t p = 0; p < h; ++p) {
      if (color[inv[p]] == 1) set_bit(in_stage, base + p);
    }
    // Out-stage switches: pre-stage position q holds the upper subnet's
    // output q; swap iff output q wants the lower subnet's element.
    for (int64_t q = 0; q < h; ++q) {
      if (color[q] == 1) set_bit(out_stage, base + q);
    }
    // Sub-permutations.  Upper subnet: its local output q is the member of
    // out-pair q routed upper; its local input is the in-pair index of that
    // member's source.
    std::vector<int64_t> up(h), lo(h);
    for (int64_t q = 0; q < h; ++q) {
      const int64_t j_up = (color[q] == 0) ? q : q + h;
      const int64_t j_lo = (color[q] == 0) ? q + h : q;
      up[q] = perm[j_up] % h;
      lo[q] = perm[j_lo] % h;
    }
    // Free this level's temporaries before recursing (bounds peak memory to
    // O(N) instead of O(N log N) on 10^8-slot networks).
    std::vector<int64_t>().swap(inv);
    std::vector<int8_t>().swap(color);
    std::vector<int64_t>().swap(perm);
    // The two subnets are fully independent (disjoint mask bits, disjoint
    // position ranges): fan out across cores for the first few levels.
    // Depth 3 -> up to 8 concurrent subtrees; the sequential top-level
    // coloring walk remains the critical path.
    if (level < parallel_levels && h >= (int64_t{1} << 20)) {
      std::thread t([this, base, h, level, &up] {
        route(base, h, level + 1, up);
      });
      route(base + h, h, level + 1, lo);
      t.join();
    } else {
      route(base, h, level + 1, up);
      std::vector<int64_t>().swap(up);
      route(base + h, h, level + 1, lo);
    }
  }
};

// Word-major -> bit-major stage conversion: output word w, bit b holds
// element e = b*nw + w.  For nw a multiple of 32 the source bit position is
// constant (w & 31) and source words stride nw/32, so each output word is 32
// strided single-bit reads.
void transpose_stage(const uint32_t* in, uint32_t* out, int64_t n) {
  const int64_t nw = n / 32;
  if (nw % 32 == 0) {
    const int64_t nw32 = nw / 32;
    for (int64_t w = 0; w < nw; ++w) {
      const int64_t base_word = w >> 5;
      const uint32_t src_bit = uint32_t(w & 31);
      uint32_t acc = 0;
      for (int64_t b = 0; b < 32; ++b) {
        acc |= ((in[b * nw32 + base_word] >> src_bit) & 1u) << b;
      }
      out[w] = acc;
    }
  } else {  // tiny networks: per-element fallback
    for (int64_t w = 0; w < nw; ++w) out[w] = 0;
    for (int64_t e = 0; e < n; ++e) {
      if ((in[e >> 5] >> (e & 31)) & 1u) {
        out[e % nw] |= uint32_t{1} << (e / nw);
      }
    }
  }
}

// ---------------------------------------------------------------------------
// v2 router: iterative, int32, preallocated workspace, word-major output.
//
// The recursive int64 Router above costs ~27 min at n=2^28 on the 1-core
// build VM (measured round 2: per-level std::vector churn + int64 memory
// traffic + a final bit-major transpose).  This version routes the same
// networks in a level sweep with two ping-pong int32 buffers, no per-block
// allocation, and emits word-major masks directly — word-major IS the
// layout-v4 "standard packing" the device kernels consume, so the transpose
// pass disappears entirely.
// The constraint graph per block: nodes = outputs; edges = "colors differ"
// between (a) output pairs (j, j+h) and (b) consumers of paired inputs.
// Nodes have degree 2, so constraints form even cycles; a valid 2-coloring
// alternates around each cycle.  The classic walk (Router::route above) is a
// strictly serial pointer chase — ~100 ns/step of dependent cache misses on
// blocks larger than LLC, which made routing the 2^28-slot net cost ~27 min
// on the 1-core build VM.  Here WALKERS independent walks are interleaved in
// one thread so the out-of-order core overlaps their cache misses (~6x
// measured).  Each walker colors a contiguous arc of some cycle and tags
// every node with its segment id (c_[x] = seg<<1 | color); wherever a walker
// meets already-colored territory it records a parity constraint between the
// two segments instead of stopping the world.  A tiny union-find with parity
// then decides which segments flip, and one sequential pass applies flips.
struct RouterV2 {
#ifndef BENES_WALKERS
// 64 interleaved walks: measured best at n=2^26 on the build VM (color
// 33 s at 32 walkers round 4 -> 14.5 s at 64; 128 adds only ~1 s more
// while doubling the per-round bookkeeping scan).
#define BENES_WALKERS 64
#endif
  static constexpr int kWalkers = BENES_WALKERS;
  struct Con {
    int32_t a, b;
    int8_t rel;  // flip[a] ^ flip[b] must equal rel
  };
  // Perm value + color word in ONE 8-byte struct.  The coloring walk's hot
  // loop reads p[x] at the node it just colored, so keeping them in the
  // same cache line turns 4 random lines per walk step (c[jp], p[jp],
  // iv[ip], c[nj]) into 3 — the walk is random-line-throughput-bound on the
  // build VM (~45M lines/s measured, W>=16 interleave saturated).  c is
  // seg<<1 | color, -1 = uncolored; sub-perm emission stores {p, -1}, which
  // also replaces the old per-level 4*n-byte memset of the color array.
  struct PC {
    int32_t p;
    int32_t c;
  };

  int64_t n;
  int32_t k;
  uint32_t* masks;
  int64_t words_per_stage;
  PC* a;         // current level's block-local perms + colors
  PC* b;         // next level's perms (+ colors reset to -1)
  int32_t* inv;  // scratch
  std::vector<Con> cons;
  std::vector<int32_t> uf;
  std::vector<int8_t> ufp, segflip;

  inline void set_bit(int32_t stage, int64_t pos) {
    masks[stage * words_per_stage + (pos >> 5)] |=
        (uint32_t{1} << (pos & 31));
  }

  // union-find with parity: parity(x) = xor of ufp along x's root path
  int32_t find(int32_t x, int8_t& par) {
    int8_t p = 0;
    int32_t r = x;
    while (uf[r] != r) {
      p ^= ufp[r];
      r = uf[r];
    }
    int32_t c2 = x;
    int8_t pc = 0;
    while (uf[c2] != r) {
      const int32_t nx = uf[c2];
      const int8_t np = ufp[c2];
      uf[c2] = r;
      ufp[c2] = static_cast<int8_t>(p ^ pc);
      pc ^= np;
      c2 = nx;
    }
    par = p;
    return r;
  }

  // Interleaved-walker 2-coloring of one block; colors land in pc[0..m).c.
  void color_block_walkers(PC* pc, const int32_t* iv, int64_t m) {
    const int64_t h = m / 2;
    int32_t nseg = 0;
    cons.clear();
    int64_t cursor = 0;
    struct WS {
      int64_t j;
      int32_t seg;
      int8_t c;
      bool live;
    };
    WS ws[kWalkers];
    int live = 0;
    for (auto& w : ws) w.live = false;
    for (;;) {
      for (auto& s : ws) {
        if (s.live) continue;
        while (cursor < m && pc[cursor].c != -1) ++cursor;
        if (cursor >= m) continue;
        const int32_t seg = nseg++;
        pc[cursor].c = seg << 1;  // color 0
        // The walk leaves the seed across its pair edge; the seed's OTHER
        // constraint edge (consumer-pair companion x) would go unexamined if
        // x's segment also walks away — record it now when x is colored.
        {
          const int64_t i = pc[cursor].p;
          const int64_t ip = (i < h) ? i + h : i - h;
          const int64_t x = iv[ip];
          const int32_t vx = pc[x].c;
          if (vx != -1)  // required: color[x] = 1
            cons.push_back({seg, vx >> 1, static_cast<int8_t>(1 ^ (vx & 1))});
        }
        s = {cursor, seg, 0, true};
        ++cursor;
        ++live;
      }
      if (!live) break;
      for (auto& s : ws) {
        if (!s.live) continue;
        const int64_t j = s.j;  // invariant: colored by this walker, color s.c
        const int64_t jp = (j < h) ? j + h : j - h;
        const int32_t vjp = pc[jp].c;
        if (vjp != -1) {  // pair edge into foreign arc: jp must be 1-c
          cons.push_back(
              {s.seg, vjp >> 1,
               static_cast<int8_t>(((vjp & 1) == s.c) ? 1 : 0)});
          s.live = false;
          --live;
          continue;
        }
        pc[jp].c = (s.seg << 1) | (1 - s.c);
        const int64_t i = pc[jp].p;  // same cache line as the c write above
        const int64_t ip = (i < h) ? i + h : i - h;
        const int64_t nj = iv[ip];
        const int32_t vnj = pc[nj].c;
        if (vnj != -1) {  // consumer edge into foreign arc: nj must be c
          cons.push_back(
              {s.seg, vnj >> 1,
               static_cast<int8_t>(((vnj & 1) != s.c) ? 1 : 0)});
          s.live = false;
          --live;
        } else {
          pc[nj].c = (s.seg << 1) | s.c;
          s.j = nj;
        }
      }
    }
    // Resolve segment flips.  Every recorded constraint is implied by any
    // valid alternating coloring, so the system is consistent; union-find
    // with parity yields one satisfying assignment.
    uf.resize(static_cast<size_t>(nseg));
    ufp.assign(static_cast<size_t>(nseg), 0);
    for (int32_t i2 = 0; i2 < nseg; ++i2) uf[i2] = i2;
    for (const Con& c2 : cons) {
      int8_t pa, pb;
      const int32_t ra = find(c2.a, pa), rb = find(c2.b, pb);
      if (ra == rb) continue;
      uf[ra] = rb;
      ufp[ra] = static_cast<int8_t>(pa ^ pb ^ c2.rel);
    }
    segflip.assign(static_cast<size_t>(nseg), 0);
    for (int32_t s0 = 0; s0 < nseg; ++s0) {
      int8_t par;
      find(s0, par);
      segflip[s0] = par;
    }
    for (int64_t j = 0; j < m; ++j) pc[j].c ^= segflip[pc[j].c >> 1];
  }

  static double now_s() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec + 1e-9 * ts.tv_nsec;
  }

  //: below this block size the depth-first tail takes over: a block's PC +
  // scratch + inv working set (20 B/slot = 40 MB at 2^21) fits the build
  // VM's 105 MB L3, so one DRAM pass routes ALL its remaining levels
  // instead of re-streaming the whole array once per level (the
  // breadth-first sweep's tail levels each cost a full-array pass;
  // measured ~27% of route time at n=2^26).  2^21 (L3-resident regions)
  // was tried and measured SLOWER (55.5 vs 50.8 s at n=2^26): walkers
  // already hide the big-level latency, so early depth-first only trades
  // streamed passes for worse mask-write locality.
  static constexpr int64_t kDFMax = int64_t{1} << 15;
  //: prefetch distance for the sequential-scan random-target loops (inv
  // build, emit's pc[iv[q]] read) — far enough to cover a DRAM miss at
  // ~4 B/cycle scan speed, near enough to stay in the L1 prefetch window.
  static constexpr int64_t kPF = 24;

  // Serial cycle walk (colors only; c low bit).  Correct for any block;
  // used where the block is cache-resident.
  static void serial_color(PC* pc, const int32_t* iv, int64_t m) {
    const int64_t h = m / 2;
    for (int64_t seed = 0; seed < m; ++seed) {
      if (pc[seed].c != -1) continue;
      int64_t j = seed;
      int32_t c = 0;
      while (pc[j].c == -1) {
        pc[j].c = c;
        const int64_t jp = (j < h) ? j + h : j - h;
        if (pc[jp].c != -1) break;
        pc[jp].c = 1 - c;
        const int64_t i = pc[jp].p;
        const int64_t ip = (i < h) ? i + h : i - h;
        j = iv[ip];
      }
    }
  }

  // Switch bits + sub-perms in one pass.  In-stage switches read iv[q]/c
  // sequentially+independently (overlappable misses) and accumulate mask
  // words in registers — much faster than the random read-modify-write
  // set_bit pattern for blocks >= 32.  ``base`` is the block's global slot
  // offset (32-aligned whenever h >= 32).
  void emit_level(const PC* pc, const int32_t* iv, PC* up, PC* lo,
                  int64_t m, int64_t base, int32_t in_stage,
                  int32_t out_stage, bool prefetch) {
    const int64_t h = m / 2;
    if ((h & 31) == 0) {
      uint32_t* inw = masks + static_cast<int64_t>(in_stage) * words_per_stage;
      uint32_t* outw =
          masks + static_cast<int64_t>(out_stage) * words_per_stage;
      for (int64_t q0 = 0; q0 < h; q0 += 32) {
        uint32_t win = 0, wout = 0;
        for (int64_t q = q0; q < q0 + 32; ++q) {
          if (prefetch && q + kPF < h)
            __builtin_prefetch(&pc[iv[q + kPF]], 0, 0);
          if (pc[iv[q]].c & 1) win |= uint32_t{1} << (q - q0);
          const int32_t cq = pc[q].c & 1;
          if (cq) wout |= uint32_t{1} << (q - q0);
          const int64_t j_up = cq == 0 ? q : q + h;
          const int64_t j_lo = cq == 0 ? q + h : q;
          const int32_t pu = pc[j_up].p;
          const int32_t pl = pc[j_lo].p;
          up[q] = {pu >= h ? pu - static_cast<int32_t>(h) : pu, -1};
          lo[q] = {pl >= h ? pl - static_cast<int32_t>(h) : pl, -1};
        }
        if (win) inw[(base + q0) >> 5] |= win;
        if (wout) outw[(base + q0) >> 5] |= wout;
      }
    } else {  // h < 32: bit-at-a-time
      for (int64_t q = 0; q < h; ++q) {
        if (pc[iv[q]].c & 1) set_bit(in_stage, base + q);
        const int32_t cq = pc[q].c & 1;
        if (cq) set_bit(out_stage, base + q);
        const int64_t j_up = cq == 0 ? q : q + h;
        const int64_t j_lo = cq == 0 ? q + h : q;
        const int32_t pu = pc[j_up].p;
        const int32_t pl = pc[j_lo].p;
        up[q] = {pu >= h ? pu - static_cast<int32_t>(h) : pu, -1};
        lo[q] = {pl >= h ? pl - static_cast<int32_t>(h) : pl, -1};
      }
    }
  }

  // Depth-first tail: route ONE kDFMax-or-smaller region across ALL its
  // remaining levels while it is cache-resident.  ``pc`` holds the
  // region's current sub-perms (level ``level0``), ``tmp`` is an
  // m0-PC scratch, ``iv`` an m0-int32 scratch; ``gbase`` the region's
  // global slot offset.  Mask bits for every remaining stage are emitted;
  // the sub-perm buffers are dead afterwards.
  void df_region(PC* pc, PC* tmp, int32_t* iv, int64_t m0, int32_t level0,
                 int64_t gbase) {
    PC* cur = pc;
    PC* nxt = tmp;
    int64_t m = m0;
    for (int32_t lev = level0;; ++lev) {
      if (m == 2) {  // final middle stage
        for (int64_t sb = 0; sb < m0 / 2; ++sb) {
          if (cur[sb * 2].p == 1) set_bit(lev, gbase + sb * 2);
        }
        return;
      }
      const int64_t h = m / 2;
      const int32_t in_stage = lev;
      const int32_t out_stage = 2 * k - 2 - lev;
      for (int64_t sb = 0; sb < m0 / m; ++sb) {
        PC* p = cur + sb * m;
        int32_t* v = iv + sb * m;
        for (int64_t j = 0; j < m; ++j) v[p[j].p] = static_cast<int32_t>(j);
        // DF sub-blocks are L2-resident by construction (m <= kDFMax);
        // the serial walk wins there — walker bookkeeping only pays for
        // itself when the chase misses cache (see run()'s breadth loop).
        serial_color(p, v, m);
        emit_level(p, v, nxt + sb * m, nxt + sb * m + h, m,
                   gbase + sb * m, in_stage, out_stage, false);
      }
      std::swap(cur, nxt);
      m >>= 1;
    }
  }

  void run() {
    // Every breadth-loop block exceeds kDFMax, i.e. is beyond L2 — walker
    // coloring always wins there (serial-walk misses dominated levels
    // with m in [2^16, 2^20) under round 4's 2^20 walker threshold —
    // measured 2.5 s for one m=2^19 level at n=2^26).  The DF tail owns
    // every cache-resident size and walks serially.
    const bool timing = std::getenv("BENES_TIME") != nullptr;
    std::vector<PC> dfscratch(static_cast<size_t>(std::min(n, kDFMax)));
    std::vector<int32_t> dfiv(static_cast<size_t>(std::min(n, kDFMax)));
    for (int32_t level = 0; level < k; ++level) {
      const int64_t m = n >> level;
      const int64_t nblocks = int64_t{1} << level;
      if (m <= kDFMax) {  // cache-blocked depth-first tail
        const double t0 = timing ? now_s() : 0;
        for (int64_t blk = 0; blk < nblocks; ++blk) {
          df_region(a + blk * m, dfscratch.data(), dfiv.data(), m, level,
                    blk * m);
        }
        if (timing)
          std::fprintf(stderr,
                       "benes df tail from level %2d m=2^%d  %.2fs\n", level,
                       63 - __builtin_clzll(static_cast<uint64_t>(m)),
                       now_s() - t0);
        break;
      }
      const int64_t h = m / 2;
      const int32_t in_stage = level;
      const int32_t out_stage = 2 * k - 2 - level;
      double t_inv = 0, t_col = 0, t_emit = 0, t0 = timing ? now_s() : 0;
      for (int64_t blk = 0; blk < nblocks; ++blk) {
        const int64_t base = blk * m;
        PC* pc = a + base;
        int32_t* iv = inv + base;
        PC* up = b + base;
        PC* lo = b + base + h;
        for (int64_t j = 0; j < m; ++j) {
          if (j + kPF < m) __builtin_prefetch(&iv[pc[j + kPF].p], 1, 0);
          iv[pc[j].p] = static_cast<int32_t>(j);
        }
        if (timing) {
          const double t = now_s();
          t_inv += t - t0;
          t0 = t;
        }
        color_block_walkers(pc, iv, m);
        if (timing) {
          const double t = now_s();
          t_col += t - t0;
          t0 = t;
        }
        emit_level(pc, iv, up, lo, m, base, in_stage, out_stage, true);
        if (timing) {
          const double t = now_s();
          t_emit += t - t0;
          t0 = t;
        }
      }
      if (timing)
        std::fprintf(stderr, "benes level %2d m=2^%d  inv %.2fs  color %.2fs  emit %.2fs\n",
                     level, 63 - __builtin_clzll(static_cast<uint64_t>(m)),
                     t_inv, t_col, t_emit);
      std::swap(a, b);
    }
  }
};

}  // namespace

extern "C" {

// v2 entry point: int32 perm, word-major masks ("standard packing": mask
// element e at word e>>5, bit e&31 — what bfs_tpu/ops/relay.py layout v4
// consumes).  masks_out: uint32[(2k-1) * (n/32)] zero-initialised by the
// caller.  trusted != 0 skips the bijection check (a random-access pass
// worth ~10% of routing time at n=2^28; layout-internal perms are
// constructed bijective by _pad_identity).  Returns 0 on success, -1 on
// invalid input, -2 when the ~20n-byte working set cannot be allocated.
int32_t benes_route_i32_v2(int64_t n, const int32_t* perm,
                           uint32_t* masks_out, int32_t trusted) {
  if (n < 32 || (n & (n - 1)) != 0 || n > (int64_t{1} << 30)) return -1;
  int32_t k = 0;
  while ((int64_t{1} << k) < n) ++k;
  if (!trusted) {
    std::vector<uint64_t> seen(static_cast<size_t>(n / 64 + 1), 0);
    for (int64_t j = 0; j < n; ++j) {
      const int64_t p = perm[j];
      if (p < 0 || p >= n) return -1;
      uint64_t& w = seen[static_cast<size_t>(p >> 6)];
      const uint64_t bit = uint64_t{1} << (p & 63);
      if (w & bit) return -1;
      w |= bit;
    }
  }
  const size_t nb_pc = static_cast<size_t>(n) * sizeof(RouterV2::PC);
  HugeBuf a(nb_pc), b(nb_pc), inv(static_cast<size_t>(n) * 4);
  if (!a.p || !b.p || !inv.p) return -2;
  // HugeBuf memory is uninitialized (mmap pages are zeroed, the
  // posix_memalign fallback is not).  a/b/inv are fully rewritten per
  // level for a BIJECTIVE perm, but with trusted=1 the bijection check is
  // skipped and a caller bug would make the inv walk read garbage; zero
  // inv once so that failure mode stays bounded (ADVICE r4 — 4n bytes,
  // negligible vs routing time).
  std::memset(inv.p, 0, static_cast<size_t>(n) * 4);
  RouterV2::PC* ap = static_cast<RouterV2::PC*>(a.p);
  for (int64_t j = 0; j < n; ++j) ap[j] = {perm[j], -1};
  RouterV2 r;
  r.n = n;
  r.k = k;
  r.masks = masks_out;
  r.words_per_stage = n / 32;
  r.a = ap;
  r.b = static_cast<RouterV2::PC*>(b.p);
  r.inv = inv.i32();
  r.run();
  return 0;
}

int32_t benes_route_i32(int64_t n, const int32_t* perm, uint32_t* masks_out) {
  return benes_route_i32_v2(n, perm, masks_out, 0);
}

// perm: int64[n] with perm[j] = source index for output j (a bijection).
// masks_out: uint32[(2k-1) * (n/32)] zero-initialised by the caller.
// bit_major != 0 packs mask element e at (word e % nw, bit e / nw) — the
// transpose-free layout the XLA applier uses.  Returns 0 on success, -1 on
// invalid input (n not a power of two >= 2, or perm not a bijection).
int32_t benes_route(int64_t n, const int64_t* perm, uint32_t* masks_out,
                    int32_t bit_major) {
  if (n < 2 || (n & (n - 1)) != 0) return -1;
  int32_t k = 0;
  while ((int64_t{1} << k) < n) ++k;
  {
    std::vector<uint8_t> seen(static_cast<size_t>(n), 0);
    for (int64_t j = 0; j < n; ++j) {
      const int64_t p = perm[j];
      if (p < 0 || p >= n || seen[p]) return -1;
      seen[p] = 1;
    }
  }
  Router r;
  r.n_total = n;
  r.k = k;
  r.masks = masks_out;
  r.words_per_stage = n / 32 > 0 ? n / 32 : 1;
  std::vector<int64_t> p(perm, perm + n);
  r.route(0, n, 0, p);
  if (bit_major && n >= 32) {
    const int32_t num_stages = 2 * k - 1;
    const int64_t nw = r.words_per_stage;
    unsigned hw = std::thread::hardware_concurrency();
    const int32_t workers =
        int32_t(hw ? (hw < 16u ? hw : 16u) : 4u);
    std::vector<std::thread> pool;
    for (int32_t t = 0; t < workers; ++t) {
      pool.emplace_back([=] {
        std::vector<uint32_t> tmp(static_cast<size_t>(nw));
        for (int32_t s = t; s < num_stages; s += workers) {
          transpose_stage(masks_out + int64_t(s) * nw, tmp.data(), n);
          std::memcpy(masks_out + int64_t(s) * nw, tmp.data(),
                      size_t(nw) * sizeof(uint32_t));
        }
      });
    }
    for (auto& th : pool) th.join();
  }
  return 0;
}

}  // extern "C"
