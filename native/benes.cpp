// Beneš network router: compile an arbitrary static permutation into
// per-stage butterfly switch masks.
//
// Why this exists: on TPU, XLA lowers per-element gather/scatter to scalar
// loops (~0.12 G/s measured on v5e) while dense vector ops run at memory
// bandwidth (~200 Gint32/s).  BFS frontier exchange is a fixed permutation
// of edge slots (src-grouped order -> dst-grouped order), so we route it
// through a Beneš network: 2*log2(N)-1 butterfly stages of conditional
// pair-swaps whose control bits are precomputed here, once per graph.  Each
// superstep then applies the stages as pure elementwise ops on bit-packed
// words — the TPU-native replacement for the reference's Spark shuffle
// (BfsSpark.java:90-108 reduceByKey wire transfer).
//
// Conventions (must match bfs_tpu/ops/relay.py):
//   * N = 2^k elements; stage s in [0, 2k-1) has pair distance
//     d_s = N >> (s+1) for s < k, and N >> (2k-1-s) for s >= k
//     (distances N/2, N/4, ..., 2, 1, 2, ..., N/4, N/2).
//   * A stage swaps x[i] <-> x[i+d] iff mask bit i is set; mask bits are
//     stored only at the lower index of each pair (i with (i & d) == 0).
//   * Masks are bit-packed little-endian into int32 words: bit i of the
//     stage mask = (mask_words[i >> 5] >> (i & 31)) & 1.
//   * The network computes y with y[j] = x[perm[j]].

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Route one Beneš block covering positions [base, base+n) at recursion
// level l.  perm is block-local: output slot j (local) must receive the
// element entering at block-local input slot perm[j].  Writes the in-stage
// (stage l) and out-stage (stage 2k-2-l) mask bits, builds the two
// half-size sub-permutations, and recurses.
struct Router {
  int64_t n_total;
  int32_t k;  // log2(n_total)
  uint32_t* masks;         // [num_stages][n_total/32] packed words
  int64_t words_per_stage;
  int32_t parallel_levels = 3;  // thread fan-out depth (2^d subtrees)

  // Masks are written WORD-MAJOR here regardless of the requested layout:
  // sibling subtrees cover disjoint pow2-aligned position ranges, which in
  // word-major packing touch disjoint words — so the threaded fan-out needs
  // no atomics.  (Bit-major interleaves positions h apart into the same
  // word.)  benes_route transposes to bit-major afterwards if asked.
  void set_bit(int32_t stage, int64_t pos) {
    masks[stage * words_per_stage + (pos >> 5)] |=
        (uint32_t{1} << (pos & 31));
  }

  void route(int64_t base, int64_t n, int32_t level,
             std::vector<int64_t>& perm) {
    if (n == 1) return;
    const int64_t h = n / 2;
    const int32_t in_stage = level;
    const int32_t out_stage = 2 * k - 2 - level;
    if (n == 2) {
      // Single middle stage: swap iff output 0 takes input 1.
      if (perm[0] == 1) set_bit(in_stage, base);
      return;
    }
    // inv[i] = output slot consuming input i.
    std::vector<int64_t> inv(n);
    for (int64_t j = 0; j < n; ++j) inv[perm[j]] = j;
    // color[j] in {0,1}: which subnet (0 = upper half) output j routes
    // through.  Constraints: paired outputs (j, j^h... j and j+h) differ;
    // outputs consuming paired inputs (i, i+h) differ.
    std::vector<int8_t> color(n, -1);
    for (int64_t seed = 0; seed < n; ++seed) {
      if (color[seed] != -1) continue;
      int64_t j = seed;
      int8_t c = 0;
      while (color[j] == -1) {
        color[j] = c;
        // Output partner must take the other subnet.
        const int64_t jp = (j < h) ? j + h : j - h;
        if (color[jp] == -1) {
          color[jp] = int8_t(1 - c);
          // The input paired with jp's source forces its consumer's color.
          const int64_t i = perm[jp];
          const int64_t ip = (i < h) ? i + h : i - h;
          j = inv[ip];
          c = c;  // consumer of ip must differ from consumer of i -> same c
          continue;
        }
        break;
      }
    }
    // In-stage switches: input pair (p, p+h).  After the stage, position p
    // carries the upper-subnet element.  Swap iff x[p] must go lower.
    for (int64_t p = 0; p < h; ++p) {
      if (color[inv[p]] == 1) set_bit(in_stage, base + p);
    }
    // Out-stage switches: pre-stage position q holds the upper subnet's
    // output q; swap iff output q wants the lower subnet's element.
    for (int64_t q = 0; q < h; ++q) {
      if (color[q] == 1) set_bit(out_stage, base + q);
    }
    // Sub-permutations.  Upper subnet: its local output q is the member of
    // out-pair q routed upper; its local input is the in-pair index of that
    // member's source.
    std::vector<int64_t> up(h), lo(h);
    for (int64_t q = 0; q < h; ++q) {
      const int64_t j_up = (color[q] == 0) ? q : q + h;
      const int64_t j_lo = (color[q] == 0) ? q + h : q;
      up[q] = perm[j_up] % h;
      lo[q] = perm[j_lo] % h;
    }
    // Free this level's temporaries before recursing (bounds peak memory to
    // O(N) instead of O(N log N) on 10^8-slot networks).
    std::vector<int64_t>().swap(inv);
    std::vector<int8_t>().swap(color);
    std::vector<int64_t>().swap(perm);
    // The two subnets are fully independent (disjoint mask bits, disjoint
    // position ranges): fan out across cores for the first few levels.
    // Depth 3 -> up to 8 concurrent subtrees; the sequential top-level
    // coloring walk remains the critical path.
    if (level < parallel_levels && h >= (int64_t{1} << 20)) {
      std::thread t([this, base, h, level, &up] {
        route(base, h, level + 1, up);
      });
      route(base + h, h, level + 1, lo);
      t.join();
    } else {
      route(base, h, level + 1, up);
      std::vector<int64_t>().swap(up);
      route(base + h, h, level + 1, lo);
    }
  }
};

// Word-major -> bit-major stage conversion: output word w, bit b holds
// element e = b*nw + w.  For nw a multiple of 32 the source bit position is
// constant (w & 31) and source words stride nw/32, so each output word is 32
// strided single-bit reads.
void transpose_stage(const uint32_t* in, uint32_t* out, int64_t n) {
  const int64_t nw = n / 32;
  if (nw % 32 == 0) {
    const int64_t nw32 = nw / 32;
    for (int64_t w = 0; w < nw; ++w) {
      const int64_t base_word = w >> 5;
      const uint32_t src_bit = uint32_t(w & 31);
      uint32_t acc = 0;
      for (int64_t b = 0; b < 32; ++b) {
        acc |= ((in[b * nw32 + base_word] >> src_bit) & 1u) << b;
      }
      out[w] = acc;
    }
  } else {  // tiny networks: per-element fallback
    for (int64_t w = 0; w < nw; ++w) out[w] = 0;
    for (int64_t e = 0; e < n; ++e) {
      if ((in[e >> 5] >> (e & 31)) & 1u) {
        out[e % nw] |= uint32_t{1} << (e / nw);
      }
    }
  }
}

}  // namespace

extern "C" {

// perm: int64[n] with perm[j] = source index for output j (a bijection).
// masks_out: uint32[(2k-1) * (n/32)] zero-initialised by the caller.
// bit_major != 0 packs mask element e at (word e % nw, bit e / nw) — the
// transpose-free layout the XLA applier uses.  Returns 0 on success, -1 on
// invalid input (n not a power of two >= 2, or perm not a bijection).
int32_t benes_route(int64_t n, const int64_t* perm, uint32_t* masks_out,
                    int32_t bit_major) {
  if (n < 2 || (n & (n - 1)) != 0) return -1;
  int32_t k = 0;
  while ((int64_t{1} << k) < n) ++k;
  {
    std::vector<uint8_t> seen(static_cast<size_t>(n), 0);
    for (int64_t j = 0; j < n; ++j) {
      const int64_t p = perm[j];
      if (p < 0 || p >= n || seen[p]) return -1;
      seen[p] = 1;
    }
  }
  Router r;
  r.n_total = n;
  r.k = k;
  r.masks = masks_out;
  r.words_per_stage = n / 32 > 0 ? n / 32 : 1;
  std::vector<int64_t> p(perm, perm + n);
  r.route(0, n, 0, p);
  if (bit_major && n >= 32) {
    const int32_t num_stages = 2 * k - 1;
    const int64_t nw = r.words_per_stage;
    unsigned hw = std::thread::hardware_concurrency();
    const int32_t workers =
        int32_t(hw ? (hw < 16u ? hw : 16u) : 4u);
    std::vector<std::thread> pool;
    for (int32_t t = 0; t < workers; ++t) {
      pool.emplace_back([=] {
        std::vector<uint32_t> tmp(static_cast<size_t>(nw));
        for (int32_t s = t; s < num_stages; s += workers) {
          transpose_stage(masks_out + int64_t(s) * nw, tmp.data(), n);
          std::memcpy(masks_out + int64_t(s) * nw, tmp.data(),
                      size_t(nw) * sizeof(uint32_t));
        }
      });
    }
    for (auto& th : pool) th.join();
  }
  return 0;
}

}  // extern "C"
