// Native sequential BFS oracle: CSR adjacency + ring-buffer queue.
//
// Plays the role of the reference's vendored algs4 oracle
// (sequential-libs/algs4.jar!/BreadthFirstPaths.java:93-132): the serial
// baseline the parallel engine is benchmarked against ("serial version"
// column of docs/BigData_Project.pdf §1.5 Table 7).  Re-implemented from
// behavior — FIFO queue, dist/parent arrays, multi-source seeding — not
// translated.  Exposed via a C ABI for ctypes (no pybind11 in the image).
//
// Two parent policies:
//   policy=0  first-discovery (enqueue order over sorted adjacency) —
//             algs4 edgeTo semantics.
//   policy=1  canonical min-parent per level (level-synchronous) — the rule
//             the TPU engine uses, for bit-exact differential testing.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {
constexpr int32_t kInf = INT32_MAX;
constexpr int32_t kNoParent = -1;
}  // namespace

extern "C" {

// indptr: int64[V+1]; indices: int32[E]; sources: int32[num_sources];
// dist/parent: int32[V] (outputs).  Returns the number of BFS levels
// (max finite distance), or -1 on bad input.
int32_t bfs_csr(int64_t num_vertices, const int64_t* indptr,
                const int32_t* indices, int32_t num_sources,
                const int32_t* sources, int32_t policy, int32_t* dist,
                int32_t* parent) {
  if (num_vertices < 0 || num_sources <= 0) return -1;
  const int64_t v = num_vertices;
  for (int64_t i = 0; i < v; ++i) {
    dist[i] = kInf;
    parent[i] = kNoParent;
  }
  std::vector<int32_t> queue(static_cast<size_t>(v));
  int64_t head = 0, tail = 0;
  for (int32_t i = 0; i < num_sources; ++i) {
    const int32_t s = sources[i];
    if (s < 0 || s >= v) return -1;
    if (dist[s] != 0) {
      dist[s] = 0;
      parent[s] = s;
      queue[tail++] = s;
    }
  }
  int32_t max_level = 0;
  if (policy == 0) {
    while (head < tail) {
      const int32_t u = queue[head++];
      const int32_t du = dist[u];
      for (int64_t e = indptr[u]; e < indptr[u + 1]; ++e) {
        const int32_t w = indices[e];
        if (parent[w] == kNoParent) {
          parent[w] = u;
          dist[w] = du + 1;
          if (dist[w] > max_level) max_level = dist[w];
          queue[tail++] = w;
        }
      }
    }
  } else {
    // Level-synchronous with min-parent: process the queue level by level;
    // within a level, a vertex discovered twice keeps the smaller parent.
    while (head < tail) {
      const int64_t level_end = tail;
      while (head < level_end) {
        const int32_t u = queue[head++];
        const int32_t du = dist[u];
        for (int64_t e = indptr[u]; e < indptr[u + 1]; ++e) {
          const int32_t w = indices[e];
          if (dist[w] == kInf) {
            dist[w] = du + 1;
            parent[w] = u;
            if (dist[w] > max_level) max_level = dist[w];
            queue[tail++] = w;
          } else if (dist[w] == du + 1 && u < parent[w] && parent[w] != w) {
            parent[w] = u;
          }
        }
      }
    }
  }
  return max_level;
}

// Optimality verifier, port of BreadthFirstPaths.check semantics
// (BreadthFirstPaths.java:172-221).  Returns 0 if all invariants hold,
// otherwise a bitmask: 1 = source distance != 0; 2 = edge crosses the
// reachable boundary or violates the triangle inequality; 4 = tree-edge
// distance property violated.
int32_t bfs_check(int64_t num_vertices, const int64_t* indptr,
                  const int32_t* indices, int32_t num_sources,
                  const int32_t* sources, const int32_t* dist,
                  const int32_t* parent) {
  int32_t bad = 0;
  for (int32_t i = 0; i < num_sources; ++i) {
    if (dist[sources[i]] != 0) bad |= 1;
  }
  for (int64_t u = 0; u < num_vertices; ++u) {
    const bool ru = dist[u] != kInf;
    for (int64_t e = indptr[u]; e < indptr[u + 1]; ++e) {
      const int32_t w = indices[e];
      const bool rw = dist[w] != kInf;
      // Directional (correct for directed CSR too): reachable source endpoint
      // forces a reachable destination.
      if (ru && !rw) bad |= 2;
      if (ru && rw && dist[w] > dist[u] + 1) bad |= 2;
    }
  }
  for (int64_t w = 0; w < num_vertices; ++w) {
    if (dist[w] == kInf || dist[w] == 0) continue;
    const int32_t p = parent[w];
    if (p == kNoParent || dist[w] != dist[p] + 1) {
      bad |= 4;
      continue;
    }
    bool found = false;  // tree edge must exist: scan p's adjacency
    for (int64_t e = indptr[p]; e < indptr[p + 1] && !found; ++e) {
      found = indices[e] == w;
    }
    if (!found) bad |= 4;
  }
  return bad;
}

}  // extern "C"
