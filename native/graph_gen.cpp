// Native graph ingest + generation: the framework's data-loader layer.
//
// The reference's ingest is driver-side Java (GraphFileUtil.java:45-69 text
// conversion; Graph.java:85-94 file ctor).  Here the hot host-side paths —
// R-MAT edge generation (Graph500 kernel-1 style), destination-major edge
// sorting for the TPU engine's sorted segment reduction, and Sedgewick text
// parsing — are C++ behind a C ABI for ctypes.  NumPy fallbacks live in
// bfs_tpu/graph/generators.py / io.py; this library only accelerates them.
//
// All functions are deterministic for a given seed (SplitMix64 / a counter-
// free per-edge PRNG) so Python and future runs agree.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

namespace {

// SplitMix64: tiny, high-quality, seedable. Used per edge+bit so generation
// order (and any future parallelisation) cannot change results.
inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

inline double u01(uint64_t h) {
  return static_cast<double>(h >> 11) * (1.0 / 9007199254740992.0);  // 2^53
}

}  // namespace

extern "C" {

// R-MAT generator: writes num_edges (src, dst) endpoint pairs for a graph of
// 2^scale vertices.  Matches Graph500 defaults when a=.57 b=.19 c=.19.
// permute!=0 applies a pseudorandom label permutation (Fisher-Yates keyed by
// seed) so degree skew is not correlated with vertex id.  Self-loops and
// duplicates are kept, like the Graph500 reference generator.
void rmat_edges(int32_t scale, int64_t num_edges, double a, double b, double c,
                uint64_t seed, int32_t permute, int32_t* src_out,
                int32_t* dst_out) {
  const double ab = a + b;
  const double c_norm = c / (1.0 - ab);
  const double a_norm = a / ab;
  for (int64_t e = 0; e < num_edges; ++e) {
    uint64_t s = 0, d = 0;
    const uint64_t base = seed * 0x9e3779b97f4a7c15ULL + static_cast<uint64_t>(e) * 2654435761ULL;
    for (int32_t bit = 0; bit < scale; ++bit) {
      const uint64_t h1 = splitmix64(base + (static_cast<uint64_t>(bit) << 32));
      const uint64_t h2 = splitmix64(base + (static_cast<uint64_t>(bit) << 32) + 1);
      const bool src_bit = u01(h1) > ab;
      const bool dst_bit = src_bit ? (u01(h2) > c_norm) : (u01(h2) > a_norm);
      s |= static_cast<uint64_t>(src_bit) << bit;
      d |= static_cast<uint64_t>(dst_bit) << bit;
    }
    src_out[e] = static_cast<int32_t>(s);
    dst_out[e] = static_cast<int32_t>(d);
  }
  if (permute) {
    const int64_t n = int64_t{1} << scale;
    std::vector<int32_t> perm(static_cast<size_t>(n));
    for (int64_t i = 0; i < n; ++i) perm[i] = static_cast<int32_t>(i);
    uint64_t state = seed ^ 0xda3e39cb94b95bdbULL;
    for (int64_t i = n - 1; i > 0; --i) {  // Fisher-Yates
      state = splitmix64(state);
      const int64_t j = static_cast<int64_t>(state % static_cast<uint64_t>(i + 1));
      const int32_t t = perm[i];
      perm[i] = perm[j];
      perm[j] = t;
    }
    for (int64_t e = 0; e < num_edges; ++e) {
      src_out[e] = perm[src_out[e]];
      dst_out[e] = perm[dst_out[e]];
    }
  }
}

// In-place stable sort of (src, dst) pairs by (dst, src): LSD radix on the
// packed 64-bit key (dst << 32) | src, 8 bits per pass.  ~O(8·E); orders of
// magnitude faster than np.lexsort on 10^8 edges.
void sort_edges_by_dst(int64_t num_edges, int32_t* src, int32_t* dst) {
  if (num_edges <= 1) return;
  const size_t n = static_cast<size_t>(num_edges);
  std::vector<uint64_t> keys(n), tmp(n);
  for (size_t i = 0; i < n; ++i) {
    keys[i] = (static_cast<uint64_t>(static_cast<uint32_t>(dst[i])) << 32) |
              static_cast<uint32_t>(src[i]);
  }
  uint64_t or_all = 0;
  for (size_t i = 0; i < n; ++i) or_all |= keys[i];
  for (int shift = 0; shift < 64; shift += 8) {
    if (((or_all >> shift) & 0xff) == 0 && shift > 0) continue;  // pass has no bits
    size_t count[257] = {0};
    for (size_t i = 0; i < n; ++i) ++count[((keys[i] >> shift) & 0xff) + 1];
    bool single_bucket = false;
    for (int b = 0; b < 256; ++b) {
      if (count[b + 1] == n) { single_bucket = true; break; }
    }
    if (single_bucket) continue;
    for (int b = 0; b < 256; ++b) count[b + 1] += count[b];
    for (size_t i = 0; i < n; ++i) tmp[count[(keys[i] >> shift) & 0xff]++] = keys[i];
    keys.swap(tmp);
  }
  for (size_t i = 0; i < n; ++i) {
    src[i] = static_cast<int32_t>(keys[i] & 0xffffffffULL);
    dst[i] = static_cast<int32_t>(keys[i] >> 32);
  }
}

// Stable sort of edge records by (key_hi, key_lo) with rank-within-hi-run
// output — the layout build's replacement for np.lexsort + searchsorted
// (each ~1-2 min at 2*10^8 edges on the 1-core VM).
// order_out[i] = original index of the i-th record in sorted order;
// rank_out[i] = position of record i within its run of equal key_hi values
// (in sorted order).  Keys must be non-negative int32.
//
// Bucket-by-hi + per-row sort: one counting pass over hi, one scatter into
// row-grouped order, then a tiny sort per row over (lo, idx) packed u64s
// (ties on lo resolve by original index ascending == LSD-radix stability).
// The old 7-pass LSD radix re-streamed 12 B/record per pass (~34 GB of
// traffic at s24, 72 s measured); this does one random scatter + cache-
// local row sorts.
void sort_rank_pairs(int64_t n, const int32_t* key_hi, const int32_t* key_lo,
                     int32_t* order_out, int32_t* rank_out) {
  if (n <= 0) return;
  constexpr int64_t kPF = 24;
  const size_t sn = static_cast<size_t>(n);
  int32_t max_hi = 0;
  for (size_t i = 0; i < sn; ++i) max_hi = std::max(max_hi, key_hi[i]);
  if (static_cast<int64_t>(max_hi) > 4 * n + 1024) {
    // Sparse key_hi space: the bucket table would cost ~16 B per key
    // VALUE, not per record (34 GB at key_hi near INT32_MAX).  Comparison
    // sort keeps the O(n)-memory contract for such callers; the layout
    // build's dense vertex-id keys always take the bucket path.
    std::vector<std::pair<uint64_t, uint32_t>> rec(sn);
    for (size_t i = 0; i < sn; ++i) {
      rec[i] = {
          (static_cast<uint64_t>(static_cast<uint32_t>(key_hi[i])) << 31) |
              static_cast<uint32_t>(key_lo[i]),
          static_cast<uint32_t>(i)};
    }
    std::sort(rec.begin(), rec.end());
    int64_t run_start = 0;
    uint64_t run_hi = rec.empty() ? 0 : (rec[0].first >> 31);
    for (size_t i = 0; i < sn; ++i) {
      const uint64_t hi = rec[i].first >> 31;
      if (hi != run_hi) {
        run_hi = hi;
        run_start = static_cast<int64_t>(i);
      }
      order_out[i] = static_cast<int32_t>(rec[i].second);
      rank_out[i] = static_cast<int32_t>(static_cast<int64_t>(i) - run_start);
    }
    return;
  }
  const size_t nk = static_cast<size_t>(max_hi) + 1;
  std::vector<int64_t> off(nk + 1, 0);
  for (size_t i = 0; i < sn; ++i) ++off[static_cast<size_t>(key_hi[i]) + 1];
  for (size_t k = 0; k < nk; ++k) off[k + 1] += off[k];
  std::vector<int64_t> cur(off.begin(), off.end() - 1);
  std::vector<uint64_t> buf(sn);
  for (size_t i = 0; i < sn; ++i) {
    if (i + kPF < sn)
      __builtin_prefetch(&cur[key_hi[i + kPF]], 1, 3);
    const int64_t o = cur[key_hi[i]]++;
    buf[static_cast<size_t>(o)] =
        (static_cast<uint64_t>(static_cast<uint32_t>(key_lo[i])) << 32) | i;
  }
  for (size_t k = 0; k < nk; ++k) {
    uint64_t* lo = buf.data() + off[k];
    uint64_t* hi = buf.data() + off[k + 1];
    const int64_t len = hi - lo;
    if (len > 1) {
      if (len <= 24) {  // insertion sort: rows average ~E/V entries
        for (uint64_t* p = lo + 1; p < hi; ++p) {
          const uint64_t v = *p;
          uint64_t* q = p;
          while (q > lo && q[-1] > v) {
            *q = q[-1];
            --q;
          }
          *q = v;
        }
      } else {
        std::sort(lo, hi);
      }
    }
  }
  for (size_t k = 0; k < nk; ++k) {
    const int64_t s = off[k];
    const int64_t e = off[k + 1];
    for (int64_t i = s; i < e; ++i) {
      order_out[i] = static_cast<int32_t>(buf[static_cast<size_t>(i)] &
                                          0xffffffffULL);
      rank_out[i] = static_cast<int32_t>(i - s);
    }
  }
}

// Plain int32 gather/scatter loops: numpy fancy indexing runs ~0.1 G/s on
// the 1-core build VM while a simple loop lets the OoO core overlap the
// random loads (~3x).  Used by the relay layout build's slot-assembly
// phases (graph/relay.py), which are a chain of E-sized gathers.
// Sequential-scan/random-target loops below all software-prefetch their
// random line kPF iterations ahead (idx is sequential, so the target is
// computable early) — measured ~2-3x on the DRAM-resident sizes.
static constexpr int64_t kPFg = 24;

void gather_i32(int64_t n, const int32_t* table, const int32_t* idx,
                int32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    if (i + kPFg < n) __builtin_prefetch(&table[idx[i + kPFg]], 0, 3);
    out[i] = table[idx[i]];
  }
}

void scatter_i32(int64_t n, const int32_t* idx, const int32_t* val,
                 int32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    if (i + kPFg < n) __builtin_prefetch(&out[idx[i + kPFg]], 1, 3);
    out[idx[i]] = val[i];
  }
}

// out[i] = base[idx[i]] + rank[i] * stride[idx[i]] — the fused slot
// computation (one pass instead of two gathers + mul + add temporaries).
void slot_assign_i32(int64_t n, const int32_t* base, const int32_t* stride,
                     const int32_t* idx, const int32_t* rank, int32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    if (i + kPFg < n) {
      __builtin_prefetch(&base[idx[i + kPFg]], 0, 3);
      __builtin_prefetch(&stride[idx[i + kPFg]], 0, 3);
    }
    const int32_t v = idx[i];
    out[i] = base[v] + rank[i] * stride[v];
  }
}

// Arbitrary-rank counting pass: rank_out[i] = how many earlier records
// share key[i].  Replaces a full (key, tiebreak) radix sort wherever the
// within-group order is free — the L2 slot assignment is one such place:
// the Beneš network routes ANY permutation, so any bijection of a source's
// edges onto its rank slots is routable, and the broadcast fills every
// rank slot of a source with the same bit regardless of which edge owns
// it (graph/relay.py L2 phase; measured 272 s as a radix sort at s25,
// ~3 s as this single pass).
void rank_by_count(int64_t n, const int32_t* key, int64_t nk,
                   int32_t* rank_out) {
  std::vector<int32_t> cnt(static_cast<size_t>(nk), 0);
  for (int64_t i = 0; i < n; ++i) {
    if (i + kPFg < n) __builtin_prefetch(&cnt[key[i + kPFg]], 1, 3);
    rank_out[i] = cnt[key[i]]++;
  }
}

// One-pass int32 bincount (numpy's runs ~10x slower on the 1-core VM).
void bincount_i32(int64_t n, const int32_t* key, int64_t nk, int32_t* out) {
  std::memset(out, 0, static_cast<size_t>(nk) * sizeof(int32_t));
  for (int64_t i = 0; i < n; ++i) {
    if (i + kPFg < n) __builtin_prefetch(&out[key[i + kPFg]], 1, 3);
    ++out[key[i]];
  }
}

// Counting-sort CSR fill: group edges by srcn WITHOUT sorting — the
// sparse-path superstep re-sorts its gathered candidates by (dst, slot)
// itself (models/bfs.py _sparse_superstep), so within-row order is free.
// indptr_out: int32[nk+2] exclusive offsets (last entry duplicated, the
// sentinel row the gather path expects).
void csr_fill(int64_t n, int64_t nk, const int32_t* srcn, const int32_t* dstn,
              const int32_t* slotv, int32_t* indptr_out, int32_t* adj_dst,
              int32_t* adj_slot) {
  std::vector<int32_t> off(static_cast<size_t>(nk), 0);
  for (int64_t i = 0; i < n; ++i) {
    if (i + kPFg < n) __builtin_prefetch(&off[srcn[i + kPFg]], 1, 3);
    ++off[srcn[i]];
  }
  int32_t run = 0;
  for (int64_t k = 0; k < nk; ++k) {
    indptr_out[k] = run;
    const int32_t c = off[k];
    off[k] = run;
    run += c;
  }
  indptr_out[nk] = run;
  indptr_out[nk + 1] = run;
  for (int64_t i = 0; i < n; ++i) {
    if (i + kPFg < n) __builtin_prefetch(&off[srcn[i + kPFg]], 1, 3);
    const int32_t o = off[srcn[i]]++;
    adj_dst[o] = dstn[i];
    adj_slot[o] = slotv[i];
  }
}

// used[idx[i]] = 1 (uint8 scatter; numpy bool fancy-assign is ~10x slower).
void mark_u8(int64_t n, const int32_t* idx, uint8_t* used) {
  for (int64_t i = 0; i < n; ++i) {
    if (i + kPFg < n) __builtin_prefetch(&used[idx[i + kPFg]], 1, 3);
    used[idx[i]] = 1;
  }
}

// Complete a partial mapping to a bijection, IDENTITY-FIRST (output j takes
// input j wherever both are free — switch-free pad routing keeps the
// compacted stage ranges tight, see graph/relay._pad_identity), then wire
// the remaining holes to the remaining unused inputs ascending.  ``used``
// is updated in place.  Replaces the numpy multi-scan (~30-60 s at net
// 2^29) with two linear passes.
void pad_identity_i32(int64_t n, int32_t* perm, uint8_t* used) {
  for (int64_t i = 0; i < n; ++i) {
    if (perm[i] < 0 && !used[i]) {
      perm[i] = static_cast<int32_t>(i);
      used[i] = 1;
    }
  }
  int64_t j = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (perm[i] >= 0) continue;
    while (used[j]) ++j;
    perm[i] = static_cast<int32_t>(j);
    used[j] = 1;
    ++j;
  }
}

// Sedgewick text parser, pass 1: return V and E from the header, or -1 on
// malformed input.  (Format: line1=V, line2=E, then E lines "v w";
// GraphFileUtil.java:48-63 / Graph.java:85-94.)
int64_t sedgewick_header(const char* path, int64_t* v_out, int64_t* e_out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  long long v = 0, e = 0;
  const int got = std::fscanf(f, "%lld %lld", &v, &e);
  std::fclose(f);
  if (got != 2 || v < 0 || e < 0) return -1;
  *v_out = v;
  *e_out = e;
  return 0;
}

// Sedgewick text parser, pass 2: fill src/dst (each int32[E]) with the E
// undirected edge endpoint pairs (caller bi-directs).  Returns the number of
// edges read, or -1 on I/O or range errors.  Hand-rolled integer scanning:
// ~10x faster than fscanf, ~100x faster than Python line splitting.
int64_t sedgewick_edges(const char* path, int64_t num_vertices,
                        int64_t num_edges, int32_t* src, int32_t* dst) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  std::fseek(f, 0, SEEK_END);
  // ftell returns long (32-bit on LLP64), capping files at 2 GiB there;
  // ftello's off_t is 64-bit wherever this builds.  Fail cleanly on error.
  const int64_t size = static_cast<int64_t>(ftello(f));
  if (size < 0) { std::fclose(f); return -1; }
  std::fseek(f, 0, SEEK_SET);
  std::vector<char> buf(static_cast<size_t>(size) + 1);
  const size_t rd = std::fread(buf.data(), 1, static_cast<size_t>(size), f);
  std::fclose(f);
  buf[rd] = '\0';
  const char* p = buf.data();
  const char* end = p + rd;
  auto next_int = [&](long long* out) -> bool {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
    if (p >= end) return false;
    bool neg = false;
    if (*p == '-') { neg = true; ++p; }
    if (p >= end || *p < '0' || *p > '9') return false;
    long long v = 0;
    while (p < end && *p >= '0' && *p <= '9') v = v * 10 + (*p++ - '0');
    *out = neg ? -v : v;
    return true;
  };
  long long hv = 0, he = 0;
  if (!next_int(&hv) || !next_int(&he)) return -1;
  if (hv != num_vertices || he < num_edges) return -1;
  for (int64_t i = 0; i < num_edges; ++i) {
    long long a = 0, b = 0;
    if (!next_int(&a) || !next_int(&b)) return -1;
    if (a < 0 || a >= num_vertices || b < 0 || b >= num_vertices) return -1;
    src[i] = static_cast<int32_t>(a);
    dst[i] = static_cast<int32_t>(b);
  }
  return num_edges;
}

}  // extern "C"
