"""bfs_tpu — a TPU-native BFS-with-MapReduce framework.

A ground-up re-design of NorthernDemon/BFS-with-MapReduce (iterative Spark
MapReduce single-source BFS, see SURVEY.md) for TPU: the superstep loop is a
single compiled XLA program (`jax.lax.while_loop`), frontier expansion is a
segmented-min relaxation over dst-sorted edge arrays, and scaling is a
`shard_map` over a `jax.sharding.Mesh` with `pmin` all-reduces riding ICI —
replacing the Spark shuffle, driver collect, and filesystem superstep carry.

Public API surface (capability map to the reference):
  graph.io / graph.csr      — ingest + graph model (GraphFileUtil, algs4 Graph)
  graph.vertex              — Vertex/Color wire format, state dumps (Vertex.java)
  oracle                    — sequential queue BFS + check() (algs4 BreadthFirstPaths)
  models.bfs                — the parallel engine (BfsSpark superstep loop)
  models.multisource        — batched multi-source BFS (vmapped frontier axis)
  parallel.sharded          — mesh-sharded engine (Spark worker parallelism)
  config                    — service.properties layer (ServiceConfiguration)
  utils.{timing,metrics,checkpoint,logging} — aux subsystems (SURVEY.md §5)
  runners                   — CLI drivers (BfsSpark.main / SequentialTest.main)
"""

from .graph.csr import (
    Graph,
    DeviceGraph,
    build_device_graph,
    INF_DIST,
    NO_PARENT,
)
from .graph.io import read_sedgewick, parse_sedgewick, read_snap_edge_list
from .graph.generators import rmat_graph, gnm_graph, path_graph
from .graph.vertex import Color, Vertex, path_to, serialize_state, parse_state
from .oracle.bfs import queue_bfs, canonical_bfs, check
from .models.bfs import bfs, BfsResult, SuperstepRunner
from .models.multisource import bfs_multi, MultiBfsResult, collapse_multi_source
from .config import ServiceConfiguration

__version__ = "0.1.0"

__all__ = [
    "Graph",
    "DeviceGraph",
    "build_device_graph",
    "INF_DIST",
    "NO_PARENT",
    "read_sedgewick",
    "parse_sedgewick",
    "read_snap_edge_list",
    "rmat_graph",
    "gnm_graph",
    "path_graph",
    "Color",
    "Vertex",
    "path_to",
    "serialize_state",
    "parse_state",
    "queue_bfs",
    "canonical_bfs",
    "check",
    "bfs",
    "BfsResult",
    "SuperstepRunner",
    "bfs_multi",
    "MultiBfsResult",
    "collapse_multi_source",
    "ServiceConfiguration",
    "__version__",
]
