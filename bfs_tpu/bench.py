"""Headline benchmark: single-source BFS TEPS on an R-MAT graph (TPU).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "TEPS", "vs_baseline": N}

Baseline: the reference's best serial number — largeG 15.2M directed edges /
1.170 s ≈ 13 M TEPS (BASELINE.md, derived from docs/BigData_Project.pdf §1.5
Table 7; the reference's own parallel version never beat it, OOMing on
largeG).

Timing methodology (round 3): Graph500-style — K single-source searches from
random roots in the traversed component are dispatched back-to-back WITHOUT
intermediate synchronization and the wall clock divided by K.  A
synchronized round-trip through the axon device tunnel costs ~107 ms
regardless of work (tools/microbench_r3.py); chained dispatch amortizes it
to ~10 ms/search while every search still executes fully and sequentially
on the device.  This mirrors Graph500's mean-over-64-roots reporting.

TEPS convention (Graph500-honest): the numerator is the number of INPUT
undirected edges inside the traversed component — all roots are drawn from
one component, so every search traverses the same edge set.

Every run is verified: BENCH_CHECK_ROOTS results (default: ALL roots) must
pass the ported algs4 ``check()`` optimality invariants
(BreadthFirstPaths.java:172-221), and all roots must reach exactly the
component.  Verification runs ON DEVICE by default (oracle/device.py —
one 24-byte counter pull per root instead of a 128 MB dist+parent
transfer; BENCH_DEVICE_CHECK=0 restores the host sweep), and the whole
phase is skipped with ``check: "skipped (budget)"`` when the run is
already behind budget.  BENCH_CHECK=0 skips unconditionally.

The run is self-diagnosing (VERDICT round 3): the relay engine times BOTH
Beneš appliers on the real mask arrays at init and keeps the faster
(``applier`` + ``applier_probe`` in details, incl. mask-stream and
dense-read bandwidths measured THIS run), and a stepped pass decomposes one
search into per-superstep times with the dense/sparse path decision
(``superstep_profile``).

Evidence is emitted INCREMENTALLY (VERDICT r4 #1): phase stamps go to
stderr as the run progresses, a PROVISIONAL headline JSON line is printed
the moment the timed repeats finish (``"check": "pending"``), and the
final line — verification status filled in — follows.  A wall-clock
budget (BENCH_TIME_BUDGET, default 1200 s) degrades the run gracefully
when behind: the applier probe, extra repeats, the superstep profile and
all-but-one verification roots are dropped rather than timing out with
zero output.

Env knobs: BENCH_TIME_BUDGET (seconds, default 1200), BENCH_PROBE
(``fresh`` re-measures the applier probe instead of reusing the cached
outcome), BFS_TPU_PROBE_BUDGET (probe wall budget, default 600 s),
BENCH_SCALE (default 24), BENCH_EDGE_FACTOR (default 6 — exactly
the BASELINE.json "100M-edge R-MAT scale-24" config), BENCH_ROOTS (8),
BENCH_REPEATS (3), BENCH_ENGINE (relay|pull|push), BENCH_CHECK (1),
BENCH_CHECK_ROOTS (default = BENCH_ROOTS), BENCH_APPLIER
(auto|pallas|xla, default auto — the measured probe), BENCH_STEP_PROFILE
(1), BENCH_PROFILE (path — jax.profiler trace of one timed batch),
BENCH_SOURCES (>1 runs the BASELINE.json config-5 batched multi-source
benchmark reporting AGGREGATE TEPS), BENCH_SPARSE (default 0: measured
round 4, a sparse superstep costs ~25 ms of INTRINSIC gather work at the
TPU's scalar-gather rate — frontier extraction 9 ms, degree gathers
3.4 ms, then edge gathers + 64K-pair sort + scatters — while a dense
superstep with the fused Pallas applier costs ~13 ms, so the hybrid LOSES
at s24 even with the cond-free nested-while dispatch; it remains right
for high-diameter / CPU-bound cases where dense supersteps dominate),
BENCH_DEVICE_CHECK (default 1 — verify on device; the multi-source path
verifies every tree through the same DeviceChecker via per-tree
on-device extraction), BENCH_PHASE_LEDGER (default 1 — ship the
per-phase superstep ledger, bfs_tpu/profiling.py, as
details.superstep_phases), BENCH_LEVEL_CURVE (default 1 — ship
details.level_curve from one UNTIMED telemetry-carrying fused run:
per-level frontier occupancy/out-edges measured on device and pulled
once at loop exit, bfs_tpu/obs/telemetry.py), BENCH_TRACE (path —
override where the stitched Chrome-trace JSON lands; default
``<journal>.trace.json``; ``bfs-tpu-obs trace`` re-exports),
BFS_TPU_SPANS (default 1 — phase spans, bfs_tpu/obs/spans.py),
BFS_TPU_PACKED (0/1 forces the packed
fused-word state off/on — ops/packed.py; default: packed whenever the
layout fits), BFS_TPU_CACHE_DIR (artifact-cache root for layout
bundles / compile caches, default .bench_cache — see bfs_tpu/config.py;
tools/cache_warm.py pre-builds the whole bench matrix).

Crash resume (ISSUE 3): every completed phase — scale decision, graph,
reference run, roots, each timed repeat, superstep profile, each per-root
verification verdict, the final headline — is journaled durably to an
append-only JSONL file keyed by (bench config, graph hash)
(bfs_tpu/resilience/journal.py, under BFS_TPU_JOURNAL_DIR, default
``<cache root>/journal``).  A run killed at any phase boundary (the round-5
failure: SIGKILL ~40 s before the final check line threw away ~1,700 s of
completed phases) resumes on the next invocation with the SAME config:
completed phases replay from the journal (no reference re-run, journaled
repeat times, already-verified roots skipped) and the run finishes the
same verified headline it would have emitted uninterrupted.  A run whose
journal is already complete replays the headline and exits.  SIGTERM /
SIGALRM (the ``timeout -k 10`` harness shape) flush a partial headline and
the journal tail instead of dying mid-line.  BFS_TPU_JOURNAL=0 disables;
BFS_TPU_FAULT=kill:<phase>[:nth] injects crashes at phase boundaries for
the resume tests (bfs_tpu/resilience/faults.py, tools/chaos_run.py).
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax

# Wall clock starts at import: every stamp and budget decision is relative
# to process start, which is what the driver's timeout measures.
_T0 = time.perf_counter()


def _elapsed() -> float:
    return time.perf_counter() - _T0


def _stamp(msg: str) -> None:
    """Progress stamp on stderr (VERDICT r4 #1b): if the driver's timeout
    kills the run, the captured tail shows exactly which phase ate the
    budget instead of nothing at all (BENCH_r04.json's empty tail)."""
    print(f"[bench +{_elapsed():7.1f}s] {msg}", file=sys.stderr, flush=True)


def _budget() -> float:
    """Wall-clock budget in seconds (BENCH_TIME_BUDGET).  The driver's
    round-4 capture was rc=124 — a timeout with zero output — so every
    phase after the timed repeats degrades gracefully against this budget
    instead of holding the only JSON line hostage (VERDICT r4 #1c)."""
    return float(os.environ.get("BENCH_TIME_BUDGET", "1200"))


def _behind(frac: float) -> bool:
    return _elapsed() > frac * _budget()


# --------------------------------------------------------------- resilience --
# Crash-resumable phases: each completed phase lands one durable journal
# record, and _boundary() marks the phase boundary (where BFS_TPU_FAULT can
# inject a crash and where a resumed run picks up).  See module docstring.

from . import knobs
from .obs.spans import span as obs_span
from .resilience.faults import fault_point
from .resilience.journal import env_config

#: Set once the provisional headline is computable: a zero-arg-to-status
#: emitter the SIGTERM/SIGALRM handler uses to flush a partial result line
#: before exiting (satellite: BENCH_r05.json's truncated tail).
_PARTIAL: dict = {"emit": None}


def _boundary(jr, phase: str, payload=None, arrays=None) -> None:
    """Journal ``phase`` (once — replayed phases are not re-recorded) and
    pass its fault-injection point."""
    if jr is not None and payload is not None and jr.get(phase) is None:
        jr.put(phase, payload, arrays=arrays)
    fault_point(phase)


def _restore_mask(jr, dg):
    """The reference phase's component mask from its journal sidecar
    (packed bits, V/8 bytes) — the shared restore expression of the
    single- and multi-source paths."""
    arrs = jr.load_arrays("reference")
    return np.unpackbits(arrs["mask_packed"])[: dg.num_vertices].astype(bool)


def _open_journal(cfg: dict):
    """The run journal for this exact bench config (None when disabled via
    BFS_TPU_JOURNAL=0)."""
    if not knobs.get("BFS_TPU_JOURNAL"):
        return None
    from .config import journal_dir
    from .resilience.journal import RunJournal

    jr = RunJournal.open_for(journal_dir(), cfg)
    if jr.resumed_phases:
        _stamp(
            f"journal: resuming {os.path.basename(jr.path)} — "
            f"{len(jr.resumed_phases)} completed phases: "
            f"{', '.join(jr.resumed_phases)}"
        )
    else:
        _stamp(f"journal: fresh run -> {os.path.basename(jr.path)}")
    return jr


def _install_signal_handlers(jr, _exit=os._exit):
    """SIGTERM/SIGALRM: flush the current partial result and the journal
    tail, then exit 128+sig.  ``timeout -k 10`` (the tier-1 and driver
    harness shape) sends SIGTERM first — this turns what used to be a
    mid-line truncation (BENCH_r05.json) into a flushed partial headline
    plus a journal the next invocation resumes from.  Returns the handler
    (tests call it with an injected ``_exit``)."""
    import signal

    def _handler(signum, frame):
        name = signal.Signals(signum).name
        _stamp(f"caught {name}: flushing partial result + journal tail")
        emit = _PARTIAL.get("emit")
        if emit is not None:
            try:
                emit(
                    f"interrupted ({name}); re-invoke with the same config "
                    "to resume from the journal"
                )
            except Exception:
                pass
        # Flush the open span stack (each still-open phase span gets its
        # real duration so far + a "signal:<name>" marker) and journal this
        # generation's events, so an interrupted run leaves a USABLE trace
        # — the resumed run's spans land in the next spans:<k> record and
        # stitch_journal_trace re-assembles the full timeline.
        try:
            from .obs.spans import flush_open_spans, journal_spans

            flush_open_spans(f"signal:{name}")
            if jr is not None:
                journal_spans(jr)
        except Exception:
            pass
        if jr is not None:
            try:
                jr.put(
                    "interrupted", {"signal": name, "elapsed_s": _elapsed()}
                )
                jr.close()
            except Exception:
                pass
        try:
            _write_stitched_trace(jr)
        except Exception:
            pass
        try:
            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:
            pass
        _exit(128 + signum)

    for sig in (signal.SIGTERM, signal.SIGALRM):
        signal.signal(sig, _handler)
    return _handler


def _write_stitched_trace(jr) -> str | None:
    """Write the Perfetto-loadable Chrome trace for this run: stitched
    from every generation's journaled span records when a journal exists
    (default path: ``<journal>.trace.json``; BENCH_TRACE overrides), else
    the in-process buffer to BENCH_TRACE.  Returns the path written."""
    from .obs import spans as _spans

    override = os.environ.get("BENCH_TRACE", "")
    if jr is not None:
        out = override or (os.path.splitext(jr.path)[0] + ".trace.json")
        doc = _spans.stitch_journal_trace(jr.path)
        tmp = f"{out}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, out)
    elif override:
        out = _spans.export_chrome_trace(override)
    else:
        return None
    _stamp(f"trace: wrote {out}")
    return out


def _finish_obs(jr) -> None:
    """End-of-run observability flush (shared by both bench paths): this
    generation's spans journaled (BEFORE the journal closes), the journal
    closed, and the stitched trace written next to it."""
    try:
        from .obs.spans import journal_spans

        if jr is not None:
            journal_spans(jr)
    except Exception as exc:
        _stamp(f"span journaling failed ({exc!r})")
    if jr is not None:
        jr.close()
    try:
        _write_stitched_trace(jr)
    except Exception as exc:
        _stamp(f"trace export failed ({exc!r})")

# Persistent compile caches (config.enable_compile_cache): jax's own
# persistent cache for the ~minutes-long remote compiles, plus the
# serialized-executable cache (models/bfs.py compile_exe_cached) because
# jax's cache is inert under the axon remote-compile transport.  Must run
# before the first trace; BFS_TPU_EXE_CACHE="" disables the exe side.
# Enabled at IMPORT time deliberately: every importer of this module (the
# bench entry point, benchmarks.py, the tools/profile_* scripts) is a
# bench surface that compiles bench-scale programs and has always relied
# on this module configuring the caches (see enable_compile_cache's
# docstring for the package-level rule).
from .analysis.runtime import guarded_region
from .config import cache_root, enable_compile_cache

enable_compile_cache()

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import jax.numpy as jnp
import numpy as np

from .graph.csr import DeviceGraph, Graph, build_device_graph, unpad_edges
from .graph.generators import rmat_graph
from .models.bfs import _bfs_fused, _bfs_pull_fused

BASELINE_TEPS = 15_172_126 / 1.170  # ≈ 13.0 M TEPS (BASELINE.md derived floor)

_CACHE_DIR = os.environ.get("BENCH_CACHE_DIR", cache_root())


def _cached(key: str, unpack, build):
    """Load-or-rebuild an npz cache entry.  ``unpack(npz) -> obj``;
    ``build() -> (obj, dict_of_arrays)``.  Corrupt entries are treated as
    misses; writes are atomic and per-process to survive concurrent runs."""
    path = os.path.join(_CACHE_DIR, key + ".npz")
    if os.path.exists(path):
        try:
            with np.load(path) as z:
                return unpack(z)
        except Exception:
            try:
                os.remove(path)
            except FileNotFoundError:
                pass
    obj, arrays = build()
    os.makedirs(_CACHE_DIR, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.npz"
    np.savez(tmp, **arrays)
    os.replace(tmp, path)
    return obj


def _generator_backend() -> str:
    try:
        from .graph.native_gen import native_available

        return "native" if native_available() else "numpy"
    except Exception:
        return "numpy"


def _measure_tunnel_mbs(probe_mb: int = 16) -> float:
    """Host->device bandwidth through the axon tunnel, measured with one
    ``probe_mb``-MB ship + 1-element value sync.  The tunnel's effective
    bandwidth is time-varying by ORDERS OF MAGNITUDE (3 MB/s observed in
    the window after round 4's driver timeout vs 100+ MB/s in healthy
    windows), and the relay engine must ship ~1.4 GB of routing masks
    before it can run at all — the difference between a 15-second init
    and a 7-minute one.  Costs ~1 s healthy, ~5 s degraded."""
    import jax.numpy as jnp

    x = np.ones((probe_mb << 20) // 4, np.uint32)
    t0 = time.perf_counter()
    d = jnp.asarray(x)
    _ = int(np.asarray(jax.device_get(d.ravel()[:1]))[0])
    dt = time.perf_counter() - t0
    del d
    return probe_mb / max(dt, 1e-6)


def load_or_build(scale: int, edge_factor: int, seed: int, block: int, backend: str):
    """Device-ready R-MAT arrays, cached on disk: host-side generation +
    dst-sorting of ~10^8 edges takes minutes, so the prepared DeviceGraph
    (and the chosen source) is built once per config."""

    def unpack(z):
        return (
            DeviceGraph(
                num_vertices=int(z["num_vertices"]),
                num_edges=int(z["num_edges"]),
                src=z["src"],
                dst=z["dst"],
            ),
            int(z["source"]),
        )

    def build():
        if backend == "native":
            from .graph.native_gen import rmat_edges_native

            u, v = rmat_edges_native(scale, edge_factor, seed=seed)
            graph = Graph(
                1 << scale, np.concatenate([u, v]), np.concatenate([v, u])
            )  # bi-directed (GraphFileUtil.java:64-65 parity)
        else:
            graph = rmat_graph(scale, edge_factor, seed=seed)
        dg = build_device_graph(graph, block=block)
        # Deterministic source in the giant component: the max-degree vertex.
        degrees = np.bincount(graph.src, minlength=graph.num_vertices)
        source = int(np.argmax(degrees))
        arrays = dict(
            num_vertices=dg.num_vertices,
            num_edges=dg.num_edges,
            src=dg.src,
            dst=dg.dst,
            source=source,
        )
        return (dg, source), arrays

    return _cached(
        f"rmat_{backend}_s{scale}_ef{edge_factor}_seed{seed}_block{block}",
        unpack,
        build,
    )


def _layout_cache():
    """The persistent layout-bundle store (bfs_tpu/cache/layout.py),
    rooted under the bench cache dir."""
    from .cache.layout import LayoutCache

    return LayoutCache(os.path.join(_CACHE_DIR, "layout"))


def _relay_tag(key: str) -> str:
    from .graph.relay import LAYOUT_VERSION

    return f"relay_v{LAYOUT_VERSION}_{key}"


#: Layout-cache info of the last load_or_build_relay call (shipped in the
#: headline details so every capture carries its own warm-vs-cold story).
_LAST_RELAY_INFO: dict = {}


def _stamp_layout_cache(kind: str, info: dict) -> None:
    """The measured warm-vs-cold line (ISSUE 2 acceptance: printed by the
    bench): on a hit, the warm load time next to the cold build time the
    bundle recorded when it was first written."""
    if info.get("cache") == "hit":
        cold = float(info.get("build_seconds", -1.0))
        warm = float(info.get("load_seconds", 0.0))
        ratio = f" (~{cold / warm:.0f}x faster than cold)" if cold > 0 and warm > 0 else ""
        _stamp(
            f"{kind} layout cache HIT: warm load {warm:.2f}s vs cold build "
            f"{cold:.1f}s{ratio}"
        )
    elif info.get("cache") == "miss":
        _stamp(
            f"{kind} layout cache MISS: built in "
            f"{info.get('build_seconds', -1.0):.1f}s, bundle saved in "
            f"{info.get('save_seconds', 0.0):.1f}s"
        )


def _migrate_legacy_npz(dg, npz_name: str, kind: str, cache, tag: str) -> None:
    """One-time migration of a pre-round-6 flat-npz cache entry into a
    layout bundle, so the bench host's already-paid-for build artifacts
    (the 350-700 s s24 relay layout) survive the format change.  The npz
    field names ARE the bundle field names (the old unpack code and
    relay_to_arrays/pull_to_arrays describe the same mapping)."""
    if cache.resolve_tag(tag) is not None:
        return  # bundle already exists
    path = os.path.join(_CACHE_DIR, npz_name)
    if not os.path.exists(path):
        return
    try:
        from .cache.layout import pull_key, relay_key
        from .graph.ell import DEFAULT_K

        with np.load(path) as z:
            if int(z["num_vertices"]) != dg.num_vertices or (
                int(z["num_edges"]) != dg.num_edges
            ):
                return  # stale config alias; leave it alone
            arrays = {k: z[k] for k in z.files if k != "build_seconds"}
            build_seconds = (
                float(z["build_seconds"]) if "build_seconds" in z.files else -1.0
            )
        key = relay_key(dg) if kind == "relay" else pull_key(dg, DEFAULT_K, 64)
        cache.save(
            key,
            arrays,
            {
                "kind": kind,
                "build_seconds": build_seconds,
                "num_vertices": dg.num_vertices,
                "num_edges": dg.num_edges,
                "migrated_from": npz_name,
            },
            tag=tag,
        )
        _stamp(f"migrated legacy cache entry {npz_name} into a layout bundle")
    except Exception as exc:
        _stamp(f"legacy cache migration of {npz_name} failed ({exc!r})")


def load_or_build_pull(dg, key: str):
    """ELL pull layout via the persistent layout-bundle cache; ``key`` (the
    bench config string) doubles as the bundle tag."""
    from .cache.layout import load_or_build_pull as _lob
    from .graph.ell import DEFAULT_K

    cache, tag = _layout_cache(), f"pull_{key}"
    _migrate_legacy_npz(dg, f"pull_{key}_k{DEFAULT_K}.npz", "pull", cache, tag)
    pg, info = _lob(dg, cache=cache, tag=tag)
    _stamp_layout_cache("pull", info)
    return pg


def load_or_build_relay(dg, key: str):
    """Relay layout v4 via the persistent layout-bundle cache
    (content-addressed, memmap-loaded, integrity-checked —
    bfs_tpu/cache/layout.py).  Returns ``(rg, build_seconds)`` where
    ``build_seconds`` is the COLD build cost — recorded in the bundle at
    first build and reported on every warm run since (the paper excludes
    construction from timings but reports it — BigData_Project.pdf §1.5)."""
    from .cache.layout import load_or_build_relay as _lob

    cache, tag = _layout_cache(), _relay_tag(key)
    _migrate_legacy_npz(dg, f"{tag}.npz", "relay", cache, tag)
    rg, info = _lob(dg, cache=cache, tag=tag)
    _stamp_layout_cache("relay", info)
    _LAST_RELAY_INFO.clear()
    _LAST_RELAY_INFO.update(info)
    return rg, float(info.get("build_seconds", -1.0))



def _layout_build_detail() -> dict:
    """Builder flavor + per-stage timings of the build that produced the
    current relay layout (ISSUE 10): journaled with the layout phase and
    shipped in every capture's details.  On a warm run these replay the
    COLD build's provenance from the bundle meta."""
    return {
        "builder": _LAST_RELAY_INFO.get("builder", "host"),
        "build_seconds": float(_LAST_RELAY_INFO.get("build_seconds", -1.0)),
        "stages": dict(_LAST_RELAY_INFO.get("build_stages", {})),
    }


def _relay_cache_detail() -> dict:
    """The bundle-cache half of the last load_or_build_relay info (hit/miss,
    key, load/save seconds).  Build provenance (builder flavor, build
    seconds, per-stage timings) lives in `_layout_build_detail` ONLY —
    shipping any of it twice per capture invited drift between copies."""
    return {
        k: v for k, v in _LAST_RELAY_INFO.items()
        if k not in ("builder", "build_stages", "build_seconds")
    }


def _expansion_detail(eng) -> dict:
    """``details.expansion`` (ISSUE 15): which expansion arm the timed
    repeats ran, why (forced / measured / static gate), the probe's arm
    seconds when one ran, and the tile-layout density evidence.  The
    per-level arm schedule is joined in by :func:`_expansion_per_level`
    once the direction schedule is known."""
    detail = {
        "arm": getattr(eng, "expansion", "gather"),
        "requested": getattr(eng, "expansion_requested", "auto"),
        "selection_basis": getattr(eng, "expansion_basis", None),
    }
    probe = getattr(eng, "expansion_probe", None)
    if probe is not None:
        detail["probe"] = probe
    at = getattr(eng, "adj_tiles", None)
    if at is not None:
        from .graph.adj_tiles import tile_occupancy_hist

        detail["tile_occupancy"] = tile_occupancy_hist(at)
        detail.update(getattr(eng, "tiles_info", {}) or {})
    return detail


def _expansion_per_level(layout_detail: dict) -> None:
    """Join the per-level ARM schedule into ``details.expansion``: a pull
    level ran this engine's expansion arm (gather's Beneš pipeline or the
    mxu matmul), a push level the sparse gather body — derived from the
    SAME direction schedule the capture already pins, so the two views
    can never disagree."""
    exp = layout_detail.get("expansion")
    sched = layout_detail.get("direction_schedule")
    if not isinstance(exp, dict) or not isinstance(sched, dict):
        return
    arm = exp.get("arm", "gather")
    exp["per_level"] = [
        arm if s == "pull" else "sparse" for s in sched.get("schedule", [])
    ]

@jax.jit
def _pack_dist_words(d):
    """Reached-bit words from a dist vector, padded to a multiple of 32.
    Module-level jit: the old per-call ``jax.jit(_pack)`` handed jit a
    fresh callable (and a retrace) on every coverage pull (RCD001)."""
    from .ops.relay import pack_std

    pad = (-d.shape[0]) % 32
    if pad:
        d = jnp.concatenate(
            [d, jnp.full(pad, np.iinfo(np.int32).max, d.dtype)]
        )
    return pack_std(d != np.iinfo(np.int32).max)


#: Module-level sync probe (the old per-call ``jax.jit(lambda a: a + 1)``
#: in _superstep_profile retraced per profile run — RCD001).
_sync_probe = jax.jit(lambda a: a + 1)


def _reached_mask_packed(state, npad: int, remap=None):
    """Component mask from a DEVICE result state via a packed-bit pull:
    V/8 bytes through the tunnel instead of the 8 bytes/vertex of a full
    dist+parent download (128 MB at s24 — minutes in the degraded-tunnel
    windows that killed round 4's driver capture).  ``remap``: old->new id
    table when the state lives in a relabeled space."""
    packed = _pack_dist_words(state.dist)
    words = np.asarray(jax.device_get(packed))
    bits = (
        (words[:, None] >> np.arange(32, dtype=np.uint32)) & 1
    ).astype(bool).reshape(-1)[:npad]
    return bits[remap] if remap is not None else bits


def _superstep_profile(eng, source, *, max_steps: int = 64, passes: int = 3):
    """Stepped decomposition of one search: per-superstep wall time and the
    dense/sparse path decision, running the same superstep body the fused
    loop would pick for each frontier (RelayEngine.step_dispatch on the
    SPARSE_BV/BE predicate, decided from the measured stats).  Each entry's
    time includes one device sync; the measured empty round-trip is
    reported as ``sync_overhead_seconds`` so the reader can subtract it.

    The decomposition runs ``passes`` times and reports the per-level
    MEDIAN, with the [min, max] spread per entry and a ``contaminated``
    flag when the spread exceeds 10x — a concurrent tenant on the shared
    bench chip can poison any single draw by orders of magnitude (round
    4's s25 capture shipped a 531 s entry; VERDICT r4 #8)."""

    tiny = jnp.zeros(8, jnp.uint32)
    _ = np.asarray(jax.device_get(_sync_probe(tiny)))[0]  # warm

    def _t_sync():
        t0 = time.perf_counter()
        _ = np.asarray(jax.device_get(_sync_probe(tiny)))[0]
        return time.perf_counter() - t0

    t_sync = min(_t_sync() for _ in range(3))

    # Compile + warm BOTH path bodies so no in-loop entry pays compile
    # time.  The profiled state is the HOT flavor (packed fused words when
    # the engine runs packed) so the stepped bodies are byte-for-byte the
    # ones the fused loop executes; packed stepping is capped at the
    # packed level field.
    from .ops.packed import PACKED_MAX_LEVELS

    if eng.packed:
        max_steps = min(max_steps, PACKED_MAX_LEVELS)
    state = eng.init_hot_state(source)
    eng.warm_step_bodies(state)
    _ = int(eng.step_dispatch(state)[0].level)
    runs = []
    aborted = False
    for _p in range(passes):
        if runs and _behind(0.75):
            # A contaminated window can stretch one pass by orders of
            # magnitude; never let an untimed diagnostic eat the budget
            # the verified final line needs (VERDICT r4 #1).
            break
        state = eng.init_hot_state(source)
        prof = []
        while bool(state.changed) and len(prof) < max_steps:
            if _behind(0.85):
                # Mid-pass guard: in a degraded-tunnel window each sync
                # can take tens of seconds; keep whatever completed.
                aborted = True
                break
            fsize, fedges = eng.frontier_stats(state)
            decide = eng.take_sparse(state)  # predicate round-trip untimed
            t0 = time.perf_counter()
            state, path = eng.step_dispatch(state, take_sparse=decide)
            level = int(state.level)  # sync
            dt = time.perf_counter() - t0
            prof.append(
                {
                    "level": level,
                    "frontier_vertices": fsize,
                    "frontier_edges": fedges,
                    "path": path,
                    "seconds_incl_sync": dt,
                }
            )
        runs.append(prof)
        _stamp(
            f"profile pass {len(runs)}/{passes}: {len(prof)} supersteps"
            + (" [aborted: budget]" if aborted else "")
        )
        if aborted:
            break
    # The walk is deterministic (same levels/paths each pass); merge by
    # index with a per-entry median + spread.
    merged = []
    for i, entry in enumerate(runs[0]):
        ts = sorted(r[i]["seconds_incl_sync"] for r in runs if i < len(r))
        med = float(ts[len(ts) // 2])
        out = dict(entry)
        out["seconds_incl_sync"] = med
        out["seconds_spread"] = [float(ts[0]), float(ts[-1])]
        if ts[0] > 0 and ts[-1] / max(ts[0], 1e-9) > 10.0:
            out["contaminated"] = True
        merged.append(out)
    out = {
        "sync_overhead_seconds": t_sync,
        "passes": len(runs),
        "supersteps": merged,
    }
    if aborted:
        out["note"] = "aborted mid-pass on the time budget; entries partial"
    return out


def _multi_source_bench(rg, eng, dg, source, *, num_sources, do_check,
                        probe_note=None, jr=None):
    """BASELINE.json config-5: ``num_sources`` independent lock-step BFS
    trees on the relay layout, ELEMENT-MAJOR: 32 trees per uint32 element,
    every routing-mask word read once per superstep for the WHOLE batch, 64
    sources in ONE program (no chunking — VERDICT r2 item 2).  Sources are
    padded to a multiple of 32 by repeating (numerator counts real ones).

    Also times ``min(8, num_sources)`` chained SINGLE-source searches in the
    same run so the batching multiplier (``aggregate_vs_single``) is a
    same-device-state measurement, and — unless BENCH_CHECK=0 — verifies
    EVERY tree against the ported algs4 ``check()`` invariants.

    If the graph is deeper than elem mode's 31-level distance planes the
    warm run comes back unconverged; the bench then falls back to the
    vmapped batched engine IN THE SAME INVOCATION (VERDICT r4 #6) instead
    of dying with a SystemExit mid-benchmark."""
    from .oracle.bfs import check

    ref_rec = jr.get("reference") if jr is not None else None
    if ref_rec is not None:
        reached_mask = _restore_mask(jr, dg)
        directed_per_tree = int(ref_rec["directed_traversed"])
        _stamp("journal: multi-source reference restored; skipping re-run")
    else:
        _stamp("multi-source bench: reference run (compile + warm)...")
        with obs_span("bench.reference"):
            ref_state = eng.run_many_device([source])[0]
            reached_mask = _reached_mask_packed(
                ref_state, rg.vr, remap=rg.old2new
            )
        esrc_h, _ = unpad_edges(dg)
        directed_per_tree = int(np.count_nonzero(reached_mask[esrc_h]))
        _boundary(
            jr, "reference",
            {
                "directed_traversed": directed_per_tree,
                "vertices_reached": int(reached_mask.sum()),
            },
            arrays={"mask_packed": np.packbits(reached_mask)},
        )

    roots_rec = jr.get("roots") if jr is not None else None
    if roots_rec is not None:
        sources = np.asarray(roots_rec["roots"], dtype=np.int32)
        _stamp("journal: sources restored")
    else:
        rng = np.random.default_rng(987)
        pool = np.flatnonzero(reached_mask)
        sources = rng.choice(pool, size=num_sources, replace=False).astype(np.int32)
        _boundary(jr, "roots", {"roots": [int(s) for s in sources]})
    padded = sources
    if padded.shape[0] % 32:
        padded = np.concatenate(
            [padded, padded[: (-padded.shape[0]) % 32]]
        )

    # Same-run single-source reference: K chained searches, one sync (the
    # headline methodology) — the denominator of the batching multiplier.
    # Median of the same repeat count as the batch side, so the multiplier
    # does not rest on one draw from a time-varying device.
    repeats = int(os.environ.get("BENCH_REPEATS", "3"))
    k_single = min(8, num_sources)
    ss_roots = [int(s) for s in sources[:k_single]]
    st_rec = jr.get("single_times") if jr is not None else None
    if st_rec is not None:
        single_times = [float(t) for t in st_rec["times"]]
        _stamp("journal: chained single-source times restored")
    else:
        _stamp(f"warming {k_single} chained single-source searches...")
        _ = int(eng.run_many_device(ss_roots)[-1].level)  # warm
        single_times = []
        for _i in range(repeats):
            t0 = time.perf_counter()
            _ = int(eng.run_many_device(ss_roots)[-1].level)
            single_times.append(time.perf_counter() - t0)
        _boundary(jr, "single_times", {"times": single_times})
    t_single = float(np.median(single_times)) / k_single
    single_teps = (directed_per_tree / 2) / t_single

    times = []
    if jr is not None:
        for i in range(repeats):
            rep = jr.get(f"repeat:{i}")
            if rep is None:
                break
            times.append(float(rep["seconds"]))
        if times:
            _stamp(f"journal: {len(times)}/{repeats} batch repeats restored")
    warm_rec = jr.get("warm") if jr is not None else None
    if warm_rec is not None and len(times) >= repeats:
        # Fully-timed run: the batching decision and superstep count come
        # from the journal; no device warm needed on this invocation.
        batching = warm_rec["batching"]
        levels = [int(warm_rec["supersteps"])]
        run_batch = None
        state = None  # device verification recreates the batch if needed
    else:
        _stamp(f"warming element-major batch ({padded.shape[0]} trees)...")
        state = eng.run_multi_elem_device(padded)
        _ = int(state.level)  # compile + sync

        batching = "element-major (32 trees/uint32, one program)"
        run_batch = eng.run_multi_elem_device
        if bool(np.asarray(jax.device_get(state.changed))):
            # Eccentricity > 31 from at least one source: elem mode's
            # bit-sliced distance planes cannot converge.  Fall back to the
            # vmapped batched engine (full int32 distances, no depth cap)
            # and keep going.
            _stamp(
                "element-major unconverged at its 31-level cap; falling back "
                "to the vmapped batched engine"
            )
            batching = "vmapped (element-major fell back: eccentricity > 31)"
            run_batch = eng.run_multi_device
            state = run_batch(padded)
            _ = int(state.level)  # compile + warm
            from .ops.packed import PACKED_MAX_LEVELS

            if (
                eng.packed
                and int(state.level) >= PACKED_MAX_LEVELS
                and bool(np.asarray(jax.device_get(state.changed)))
            ):
                # Deeper than the packed cap too: drop to the unpacked
                # carry for the timed repeats (truncated numbers must
                # never ship even with verification skipped).
                _stamp(
                    "vmapped batch hit the packed 62-level cap: "
                    "disabling packed state"
                )
                eng.packed = False
                state = run_batch(padded)
                _ = int(state.level)
        levels = [int(state.level)]
        _boundary(jr, "warm", {
            "batching": batching, "supersteps": levels[0],
        })
        _stamp("warm done; timing batch repeats...")

    # bfs_tpu: hot-start — multi-source timed-repeat region: one batched
    # dispatch, one intended sync, nothing else touches the host.
    for i in range(len(times), repeats):
        t0 = time.perf_counter()
        with obs_span("bench.repeat", i=i):
            with guarded_region("bench.timed_repeat_multi"):
                state = run_batch(padded)
            levels = [int(state.level)]  # bfs_tpu: ok TRC002 the one intended sync per repeat
        times.append(time.perf_counter() - t0)
        _stamp(f"batch repeat: {times[-1]:.3f}s")
        _boundary(jr, f"repeat:{i}", {"seconds": times[-1]})
    # bfs_tpu: hot-end
    t = float(np.median(times))

    aggregate_teps = (num_sources * directed_per_tree / 2) / t
    common = {
        "device": str(jax.devices()[0]),
        "engine": "relay",
        "applier": eng.applier,
        "applier_probe": eng.applier_probe or probe_note,
        "num_vertices": dg.num_vertices,
        "num_directed_edges": dg.num_edges,
        "num_sources": num_sources,
        "batching": batching,
        "supersteps": levels,
        "directed_edges_traversed_per_tree": directed_per_tree,
        "teps_convention": "graph500 aggregate: sources * input undirected edges in traversed component / total time",
        "total_seconds": t,
        "batch_times": times,
        "seconds_per_tree": t / num_sources,
        "single_source_teps_same_run": single_teps,
        "single_source_seconds_same_run": t_single,
        "aggregate_vs_single": aggregate_teps / single_teps,
        "relay_layout_cache": _relay_cache_detail(),
        "layout_build": _layout_build_detail(),
    }

    def emit(check_status, extra):
        doc = {
            "metric": f"rmat{int(np.log2(dg.num_vertices))}_multi{num_sources}_aggregate_teps",
            "value": aggregate_teps,
            "unit": "TEPS",
            "vs_baseline": aggregate_teps / BASELINE_TEPS,
            "details": {**common, "check": check_status, **extra},
        }
        print(json.dumps(doc), flush=True)
        return doc

    _PARTIAL["emit"] = lambda status: emit(status, {"partial": True})
    emit("pending (final line follows)", {"provisional": True})
    _stamp("provisional headline emitted; verifying trees...")
    _boundary(jr, "provisional", {"value": aggregate_teps})

    check_status = "skipped"
    if do_check and _behind(0.90):
        # Behind budget at the verification phase: never force the
        # all-trees host pull — the provisional line already carries the
        # timed evidence, and the final line says exactly what happened.
        check_status = "skipped (budget)"
        _stamp("behind budget at verification phase: skipping tree checks")
        do_check = False
    if do_check:
        def _tree_done(i: int) -> bool:
            return jr is not None and jr.get(f"verify:{i}") is not None

        remaining = [i for i in range(num_sources) if not _tree_done(i)]
        mode = "host check"

        def host_tree_verify() -> int:
            if not remaining:
                _stamp("journal: all tree verdicts restored")
                return num_sources
            if batching.startswith("element-major"):
                mr = eng.run_multi_elem(padded)  # host results for ALL trees
            else:
                mr = eng.run_multi(padded)
            host_graph = Graph(dg.num_vertices, *unpad_edges(dg))
            n = 0
            for i in range(num_sources):
                if _tree_done(i):
                    n += 1
                    continue
                if n >= 1 and _behind(0.90):
                    _stamp(
                        f"behind budget: stopping verification after "
                        f"{n}/{num_sources} trees"
                    )
                    break
                s = int(padded[i])
                np.testing.assert_array_equal(
                    mr.dist[i] != np.iinfo(np.int32).max, reached_mask,
                    err_msg="tree does not cover the source's component",
                )
                violations = check(host_graph, mr.dist[i], mr.parent[i], s)
                if violations:
                    raise SystemExit(
                        f"BFS invariant violations on tree {i}: "
                        f"{violations[:5]}"
                    )
                n += 1
                _boundary(jr, f"verify:{i}", {"tree": i, "verdict": "passed"})
            return n

        def device_tree_verify() -> int:
            # Per-tree on-device check (VERDICT r5 item 6): each tree is
            # extracted from the batched device state IN PLACE
            # (RelayEngine.multi_tree_to_original_device) and verified by
            # the same DeviceChecker the single-source path uses — a
            # counter pull per tree instead of S full dist+parent
            # downloads, so the 64-source capture reports 64/64 instead
            # of "skipped".
            from .oracle.device import DeviceChecker
            from .ops.relay import pack_std_host

            if not remaining:
                _stamp("journal: all tree verdicts restored")
                return num_sources
            st = state
            if st is None:
                # Journal-restored timing: re-run one batch for its state.
                if batching.startswith("element-major"):
                    st = eng.run_multi_elem_device(padded)
                else:
                    st = eng.run_multi_device(padded)
            _stamp(
                "shipping edge arrays for on-device tree check "
                f"({(dg.src.nbytes + dg.dst.nbytes) >> 20} MB)..."
            )
            checker = DeviceChecker.from_graph(dg)
            pad_bits = (-dg.num_vertices) % 32
            ref_bits = (
                np.concatenate([reached_mask, np.zeros(pad_bits, bool)])
                if pad_bits
                else reached_mask
            )
            ref_words = jnp.asarray(pack_std_host(ref_bits))
            n = 0
            for i in range(num_sources):
                if _tree_done(i):
                    n += 1
                    continue
                if n >= 1 and _behind(0.95):
                    _stamp(
                        f"behind budget: stopping verification after "
                        f"{n}/{num_sources} trees"
                    )
                    break
                s = int(padded[i])
                dist_d, parent_d = eng.multi_tree_to_original_device(
                    st, i, s
                )
                mismatch = checker.coverage_mismatch(dist_d, ref_words)
                if mismatch:
                    raise SystemExit(
                        f"tree {i} does not cover the component "
                        f"({mismatch} vertices differ)"
                    )
                bad = checker.check(dist_d, parent_d, s)
                if bad:
                    raise SystemExit(
                        f"BFS invariant violations on tree {i} "
                        f"(on-device check): {bad}"
                    )
                n += 1
                _stamp(f"tree {i} verified on-device ({n}/{num_sources})")
                _boundary(jr, f"verify:{i}", {
                    "tree": i, "mode": "on-device check",
                    "verdict": "passed",
                })
            return n

        with obs_span("bench.verify", trees=num_sources):
            if os.environ.get("BENCH_DEVICE_CHECK", "1") != "0":
                try:
                    n_checked = device_tree_verify()
                    mode = "on-device check"
                except SystemExit:
                    raise  # real invariant violation: the run must fail
                except Exception as exc:
                    _stamp(
                        f"on-device tree check unavailable ({exc!r}); "
                        "host fallback"
                    )
                    n_checked = host_tree_verify()
            else:
                n_checked = host_tree_verify()
        check_status = (
            f"passed ({n_checked}/{num_sources} trees fully verified, "
            f"{mode})"
        )
        if n_checked < num_sources:
            check_status += " [budget-limited]"

    from .utils.metrics import artifact_report

    doc = emit(check_status, {"artifact_caches": artifact_report()})
    if jr is not None:
        jr.put("headline", {"headline": doc})
    _finish_obs(jr)
    fault_point("headline")
    from .analysis.runtime import format_retrace_report

    _stamp(format_retrace_report())
    _stamp("final line emitted; done")


#: Measured cold costs (VERDICT round 5): 434 s relay layout build at s24
#: (~linear in E) and ~830 s of cold XLA compile through the remote compile
#: service (program-structure-bound, treated as scale-independent).  These
#: feed the scale-fallback budget model ONLY — real runs measure.
RELAY_BUILD_S24_SECONDS = 434.0
#: The device builder (graph/relay_device.py, the first-touch default since
#: ISSUE 10) overlaps the vperm route, sparse CSR and both compactions
#: behind the big-net route — the round-5 phase ledger prices that
#: overlapped tail at ~17% of the sequential build, so the estimate is
#: 0.83x the host constant (same lineage; real runs measure).
RELAY_DEVICE_BUILD_S24_SECONDS = 360.0
COLD_COMPILE_SECONDS = 830.0


def _sharded_phase_ledger(srg, n: int, search_seconds: float, levels: int,
                          exchange: dict) -> dict:
    """The MULTICHIP phase ledger (ISSUE 11): per-phase seconds + an
    exchange-bytes column, plus PER-SHARD rows of the static quantities
    that drive each shard's work and wire share — real frontier words,
    dst-owned adjacency entries, real L1 slots.  (Per-shard SECONDS are
    not separable on a virtual SPMD mesh — every device runs the one
    program — so the rows carry the static work drivers instead;
    tools/ledger_compare.py renders both tables.)"""
    import numpy as np

    nw = srg.block // 32
    real_words = (
        (srg.new2old.reshape(n, srg.block) != -1)
        .reshape(n, nw, 32).any(axis=2).sum(axis=1)
    )
    if srg.adj_indptr is not None:
        adj_entries = srg.adj_indptr[:, -1].astype(np.int64)
    else:
        adj_entries = np.zeros(n, np.int64)
    l1_real = (srg.src_l1 != np.int32(2**31 - 1)).sum(axis=1)
    total_bytes = int(exchange.get("total_bytes", 0))
    # ONE executed-superstep count for both columns: the telemetry
    # per-level view clamps past TEL_SLOTS, so dividing bytes by the
    # bytes_per_level length would overstate deep-graph per-superstep
    # wire bytes while seconds divided by the true level count.
    steps = max(int(exchange.get("supersteps", levels)), levels, 1)
    return {
        "shards": int(n),
        "phases": {
            "full_search": {
                "seconds": float(search_seconds),
                "bytes_exchanged": total_bytes,
            },
            "full_superstep": {
                "seconds": float(search_seconds) / steps,
                "bytes_exchanged": total_bytes // steps,
            },
        },
        "per_shard": [
            {
                "shard": int(s),
                "real_words": int(real_words[s]),
                "adj_entries": int(adj_entries[s]),
                "l1_real_slots": int(l1_real[s]),
                "exchange_bytes_share": total_bytes // int(n),
            }
            for s in range(n)
        ],
    }


def _multichip_bench(scale: int, edge_factor: int, repeats: int,
                     num_roots: int, do_check: bool) -> None:
    """The MULTICHIP (sharded relay) headline: BENCH_MESH=<n> shards on
    the ``graph`` axis, journaled like the single-chip run — every phase
    (graph, sharded layout, reference, roots, timed repeats, telemetry
    curve, headline) lands one durable record, so a killed capture
    resumes instead of restarting, and a completed journal replays its
    headline verbatim.

    The headline carries ``details.exchange`` (arm, bytes-on-the-wire per
    level, per-level arm schedule — parallel/exchange.py), the direction
    schedule, and the sharded phase ledger (per-shard rows + exchange-
    bytes column, read by tools/ledger_compare.py).  Results are verified
    against the single-chip-convention component the same way the
    single-chip bench is.

    Timing note (honest caveat, shipped in the capture): bfs_sharded
    pulls dist/parent to the host per search, so in-container virtual-
    mesh numbers include that pull and measure the EXCHANGE/byte story,
    not peak TEPS; the s25/s26 TEPS headline rides the first TPU window
    with this same harness."""
    from .models.direction import resolve_direction
    from .parallel.exchange import resolve_exchange
    from .parallel.sharded import bfs_sharded, make_mesh

    n = int(os.environ.get("BENCH_MESH", "0"))
    if len(jax.devices()) < n:
        raise SystemExit(
            f"BENCH_MESH={n} needs {n} devices, have {len(jax.devices())} "
            "(CPU: put --xla_force_host_platform_device_count=8 in "
            "XLA_FLAGS before jax initializes)"
        )
    backend = _generator_backend()
    seed, block = 42, 8 * 1024
    ex_cfg = resolve_exchange()
    dir_cfg = resolve_direction()
    # BENCH_GRAPH widens the multichip workload beyond the R-MAT: the
    # exchange-arm byte story depends on the LEVEL STRUCTURE (a
    # low-diameter R-MAT's dense middle sits at the 1-bit/vertex floor
    # where no arm can beat flat; a deep graph's word-list levels cut
    # >= 4x) — "path:N" and "gnm:N:M" make both shapes journalable.
    graph_spec = os.environ.get("BENCH_GRAPH", "rmat") or "rmat"
    jr = _open_journal({
        "bench": "multichip", "mesh": n, "scale": scale,
        "edge_factor": edge_factor, "repeats": repeats,
        "num_roots": num_roots, "engine": "relay", "check": do_check,
        "backend": backend, "seed": seed, "block": block,
        "graph": graph_spec,
        "exchange": list(ex_cfg.key()),
        "direction": dir_cfg.mode,
        "direction_alpha": dir_cfg.alpha, "direction_beta": dir_cfg.beta,
    })
    _install_signal_handlers(jr)

    _stamp(f"multichip config: mesh=x{n} graph={graph_spec} scale={scale} "
           f"ef={edge_factor} exchange={ex_cfg.mode} "
           f"direction={dir_cfg.mode}")
    with obs_span("bench.load_graph", scale=scale, graph=graph_spec):
        if graph_spec == "rmat":
            dg, source = load_or_build(
                scale, edge_factor, seed, block, backend
            )
        elif graph_spec.startswith("path:"):
            from .graph.generators import path_graph

            dg, source = path_graph(int(graph_spec.split(":")[1])), 0
        elif graph_spec.startswith("gnm:"):
            from .graph.generators import gnm_graph

            _, nv, ne = graph_spec.split(":")
            dg, source = gnm_graph(int(nv), int(ne), seed=seed), 0
        else:
            raise SystemExit(
                f"unknown BENCH_GRAPH {graph_spec!r}; use rmat, path:N or "
                "gnm:N:M"
            )
    _stamp(f"device graph ready: V={dg.num_vertices} E={dg.num_edges}")
    if jr is not None:
        from .cache.layout import graph_content_hash

        ghash = graph_content_hash(dg)
        grec = jr.get("graph")
        if grec is not None and grec["content_hash"] != ghash:
            _stamp("journal: graph content hash mismatch — rotating")
            jr.restart("graph-hash mismatch")
            grec = None
        if grec is None:
            _boundary(jr, "graph", {
                "content_hash": ghash,
                "num_vertices": int(dg.num_vertices),
                "num_edges": int(dg.num_edges),
                "source": int(source),
            })
        done = jr.get("headline")
        if done is not None:
            _stamp("journal: multichip run complete; replaying headline")
            print(json.dumps(done["headline"]), flush=True)
            _finish_obs(jr)
            return
    fault_point("graph")

    from .graph.relay import build_sharded_relay_graph

    _stamp(f"building x{n} sharded relay layout...")
    t0 = time.perf_counter()
    with obs_span("bench.layout", kind="sharded-relay", shards=n):
        srg = build_sharded_relay_graph(dg, n)
    build_seconds = time.perf_counter() - t0
    _stamp(f"sharded layout ready (build_seconds={build_seconds:.1f})")
    _boundary(jr, "layout", {"build_seconds": build_seconds})
    mesh = make_mesh(graph=n)

    # ---- reference: component + numerator from the sharded engine itself
    ref_rec = jr.get("reference") if jr is not None else None
    if ref_rec is not None:
        reached_mask = _restore_mask(jr, dg)
        directed_traversed = int(ref_rec["directed_traversed"])
        _stamp("journal: reference restored")
    else:
        _stamp("reference run (compile + warm)...")
        with obs_span("bench.reference"):
            ref = bfs_sharded(srg, int(source), mesh=mesh, engine="relay")
        reached_mask = ref.dist != np.iinfo(np.int32).max
        esrc_h = (
            unpad_edges(dg)[0]
            if isinstance(dg, DeviceGraph)
            else np.asarray(dg.src)
        )
        directed_traversed = int(np.count_nonzero(reached_mask[esrc_h]))
        _boundary(jr, "reference", {
            "directed_traversed": directed_traversed,
            "vertices_reached": int(reached_mask.sum()),
        }, arrays={"mask_packed": np.packbits(reached_mask)})
    roots_rec = jr.get("roots") if jr is not None else None
    if roots_rec is not None:
        roots = [int(r) for r in roots_rec["roots"]]
    else:
        rng = np.random.default_rng(4242)
        pool = np.flatnonzero(reached_mask)
        roots = [int(source)] + [
            int(s)
            for s in rng.choice(pool, size=num_roots - 1, replace=False)
        ]
        _boundary(jr, "roots", {"roots": roots})

    # ---- timed repeats (journaled per repeat; warm run compiles) ------
    times = []
    if jr is not None:
        for i in range(repeats):
            rep = jr.get(f"repeat:{i}")
            if rep is None:
                break
            times.append(float(rep["seconds"]))
        if times:
            _stamp(f"journal: {len(times)}/{repeats} repeats restored")
    levels = 0
    if len(times) < repeats:
        _stamp("warming sharded program...")
        with obs_span("bench.warm"):
            levels = bfs_sharded(
                srg, roots[0], mesh=mesh, engine="relay"
            ).num_levels
    for i in range(len(times), repeats):
        t0 = time.perf_counter()
        with obs_span("bench.repeat", i=i):
            for s in roots:
                levels = bfs_sharded(
                    srg, s, mesh=mesh, engine="relay"
                ).num_levels
        times.append(time.perf_counter() - t0)
        _stamp(f"repeat {i + 1}/{repeats}: {times[-1]:.3f}s")
        _boundary(jr, f"repeat:{i}", {"seconds": times[-1]})
    total = float(np.median(times))
    per_search = total / num_roots
    teps = (directed_traversed / 2) / per_search

    # ---- telemetry curve: exchange bytes + direction schedule ---------
    curve_rec = jr.get("exchange_curve") if jr is not None else None
    if curve_rec is not None:
        curve = curve_rec["curve"]
        _stamp("journal: exchange curve restored")
    else:
        _stamp("telemetry run (exchange bytes + schedules)...")
        with obs_span("bench.level_curve"):
            res_t, curve = bfs_sharded(
                srg, int(source), mesh=mesh, engine="relay", telemetry=True
            )
        levels = res_t.num_levels
        _boundary(jr, "exchange_curve", {"curve": curve})
    exchange = curve.get("exchange", {})
    ledger = _sharded_phase_ledger(
        srg, n, per_search, curve.get("levels", levels), exchange
    )

    check_status = "skipped"
    if do_check:
        from .oracle.bfs import check

        if isinstance(dg, DeviceGraph):
            esrc, edst = unpad_edges(dg)
            host_graph = Graph(dg.num_vertices, esrc, edst)
        else:
            host_graph = dg
        inf = np.iinfo(np.int32).max
        to_check = roots[: max(1, min(len(roots), int(os.environ.get(
            "BENCH_CHECK_ROOTS", str(num_roots)
        )))) ]
        nv = 0
        for s in to_check:
            if jr is not None and jr.get(f"verify:{int(s)}") is not None:
                nv += 1
                continue
            res = bfs_sharded(srg, s, mesh=mesh, engine="relay")
            np.testing.assert_array_equal(
                res.dist != inf, reached_mask,
                err_msg=f"root {s} does not cover the component",
            )
            violations = check(host_graph, res.dist, res.parent, s)
            if violations:
                raise SystemExit(
                    f"BFS invariant violations from root {s}: "
                    f"{violations[:5]}"
                )
            nv += 1
            _stamp(f"root {s} verified ({nv}/{len(to_check)})")
            _boundary(jr, f"verify:{int(s)}", {
                "root": int(s), "verdict": "passed",
            })
        check_status = f"passed ({nv}/{num_roots} roots, host check)"

    gtag = f"rmat{scale}" if graph_spec == "rmat" else graph_spec.replace(
        ":", ""
    )
    doc = {
        "metric": f"{gtag}_multichip{n}_teps",
        "value": teps,
        "unit": "TEPS",
        "vs_baseline": teps / BASELINE_TEPS,
        "details": {
            "device": str(jax.devices()[0]),
            "engine": "relay",
            "graph": graph_spec,
            "mesh": {"graph": n, "batch": 1},
            "num_vertices": int(dg.num_vertices),
            "num_directed_edges": int(dg.num_edges),
            "num_roots": num_roots,
            "roots": roots,
            "vertices_reached": int(reached_mask.sum()),
            "directed_edges_traversed": directed_traversed,
            "seconds_per_search": per_search,
            "batch_seconds_median": total,
            "batch_times": times,
            "supersteps_last_root": int(curve.get("levels", levels)),
            "layout_build_seconds": build_seconds,
            "check": check_status,
            "exchange": exchange,
            "direction_schedule": curve.get("direction_schedule"),
            "level_curve": {
                k: v for k, v in curve.items()
                if k not in ("exchange", "direction_schedule")
            },
            "sharded_phases": ledger,
            "timing_note": (
                "per-search wall clock includes the host dist/parent "
                "pull of bfs_sharded; in-container virtual-mesh captures "
                "measure the exchange/byte story, not peak TEPS"
            ),
        },
    }
    print(json.dumps(doc), flush=True)
    if jr is not None:
        jr.put("headline", {"headline": doc})
    _finish_obs(jr)
    fault_point("headline")
    _stamp("multichip final line emitted; done")


def _grid_multichip_bench(r: int, c: int, scale: int, edge_factor: int,
                          repeats: int, num_roots: int,
                          do_check: bool) -> None:
    """The 2D-grid MULTICHIP headline (ISSUE 17): ``BENCH_MESH=rxc``
    runs :func:`bfs_tpu.parallel.grid.bfs_grid` on the r x c mesh with
    the same journal phases as the 1D multichip bench.  The headline's
    ``details.exchange`` carries the PER-AXIS wire story — ``col_bytes``
    / ``row_bytes`` per level, both arm schedules, ``per_chip_bytes`` —
    the O(V/sqrt(n)) evidence tools/ledger_compare.py diffs against a 1D
    capture's flat curve.

    The journal config includes ``mesh_shape`` (and its own ``bench``
    tag), so flipping ``BENCH_MESH`` between shapes — or between the
    grid and the legacy integer spelling — rotates the journal instead
    of resuming a capture measured on a different wire topology.  Legacy
    integer-mesh journals key exactly as before."""
    from .graph.grid_layout import grid_tile_placement
    from .models.direction import resolve_direction
    from .parallel.exchange import resolve_exchange
    from .parallel.grid import bfs_grid, make_grid_mesh

    n = r * c
    if len(jax.devices()) < n:
        raise SystemExit(
            f"BENCH_MESH={r}x{c} needs {n} devices, have "
            f"{len(jax.devices())} (CPU: put "
            "--xla_force_host_platform_device_count=8 in XLA_FLAGS "
            "before jax initializes)"
        )
    backend = _generator_backend()
    seed, block = 42, 8 * 1024
    ex_cfg = resolve_exchange()
    dir_cfg = resolve_direction()
    graph_spec = os.environ.get("BENCH_GRAPH", "rmat") or "rmat"
    jr = _open_journal({
        "bench": "multichip_grid", "mesh_shape": f"{r}x{c}",
        "scale": scale, "edge_factor": edge_factor, "repeats": repeats,
        "num_roots": num_roots, "engine": "grid", "check": do_check,
        "backend": backend, "seed": seed, "block": block,
        "graph": graph_spec,
        "exchange": list(ex_cfg.key()),
        "direction": dir_cfg.mode,
        "direction_alpha": dir_cfg.alpha, "direction_beta": dir_cfg.beta,
    })
    _install_signal_handlers(jr)

    _stamp(f"grid multichip config: mesh={r}x{c} graph={graph_spec} "
           f"scale={scale} ef={edge_factor} exchange={ex_cfg.mode} "
           f"direction={dir_cfg.mode}")
    with obs_span("bench.load_graph", scale=scale, graph=graph_spec):
        if graph_spec == "rmat":
            dg, source = load_or_build(
                scale, edge_factor, seed, block, backend
            )
        elif graph_spec.startswith("path:"):
            from .graph.generators import path_graph

            dg, source = path_graph(int(graph_spec.split(":")[1])), 0
        elif graph_spec.startswith("gnm:"):
            from .graph.generators import gnm_graph

            _, nv, ne = graph_spec.split(":")
            dg, source = gnm_graph(int(nv), int(ne), seed=seed), 0
        else:
            raise SystemExit(
                f"unknown BENCH_GRAPH {graph_spec!r}; use rmat, path:N "
                "or gnm:N:M"
            )
    _stamp(f"device graph ready: V={dg.num_vertices} E={dg.num_edges}")
    if jr is not None:
        from .cache.layout import graph_content_hash

        ghash = graph_content_hash(dg)
        grec = jr.get("graph")
        if grec is not None and grec["content_hash"] != ghash:
            _stamp("journal: graph content hash mismatch — rotating")
            jr.restart("graph-hash mismatch")
            grec = None
        if grec is None:
            _boundary(jr, "graph", {
                "content_hash": ghash,
                "num_vertices": int(dg.num_vertices),
                "num_edges": int(dg.num_edges),
                "source": int(source),
            })
        done = jr.get("headline")
        if done is not None:
            _stamp("journal: grid multichip run complete; replaying "
                   "headline")
            print(json.dumps(done["headline"]), flush=True)
            _finish_obs(jr)
            return
    fault_point("graph")

    from .graph.grid_layout import grid_layout_for
    from .graph.relay import build_sharded_relay_graph

    _stamp(f"building {r}x{c} grid layout ({n} shards)...")
    t0 = time.perf_counter()
    with obs_span("bench.layout", kind="grid", shards=n):
        srg = build_sharded_relay_graph(dg, n)
        layout = grid_layout_for(srg, r, c)
        placement = grid_tile_placement(srg, r, c)
    build_seconds = time.perf_counter() - t0
    _stamp(f"grid layout ready (build_seconds={build_seconds:.1f}, "
           f"emax={layout.emax}, tiles={placement['total_tiles']})")
    _boundary(jr, "layout", {
        "build_seconds": build_seconds,
        "emax": int(layout.emax),
        "tile_placement": {
            "cells": [[int(x) for x in row] for row in placement["cells"]],
            "total_tiles": placement["total_tiles"],
            "tile_rows_per_stripe": placement["tile_rows_per_stripe"],
        },
    })
    mesh = make_grid_mesh(r, c)

    # ---- reference: component + numerator from the grid engine itself
    ref_rec = jr.get("reference") if jr is not None else None
    if ref_rec is not None:
        reached_mask = _restore_mask(jr, dg)
        directed_traversed = int(ref_rec["directed_traversed"])
        _stamp("journal: reference restored")
    else:
        _stamp("reference run (compile + warm)...")
        with obs_span("bench.reference"):
            ref = bfs_grid(srg, int(source), mesh=mesh)
        reached_mask = ref.dist != np.iinfo(np.int32).max
        esrc_h = (
            unpad_edges(dg)[0]
            if isinstance(dg, DeviceGraph)
            else np.asarray(dg.src)
        )
        directed_traversed = int(np.count_nonzero(reached_mask[esrc_h]))
        _boundary(jr, "reference", {
            "directed_traversed": directed_traversed,
            "vertices_reached": int(reached_mask.sum()),
        }, arrays={"mask_packed": np.packbits(reached_mask)})
    roots_rec = jr.get("roots") if jr is not None else None
    if roots_rec is not None:
        roots = [int(x) for x in roots_rec["roots"]]
    else:
        rng = np.random.default_rng(4242)
        pool = np.flatnonzero(reached_mask)
        roots = [int(source)] + [
            int(s)
            for s in rng.choice(pool, size=num_roots - 1, replace=False)
        ]
        _boundary(jr, "roots", {"roots": roots})

    # ---- timed repeats (journaled per repeat; warm run compiles) ------
    times = []
    if jr is not None:
        for i in range(repeats):
            rep = jr.get(f"repeat:{i}")
            if rep is None:
                break
            times.append(float(rep["seconds"]))
        if times:
            _stamp(f"journal: {len(times)}/{repeats} repeats restored")
    levels = 0
    if len(times) < repeats:
        _stamp("warming grid program...")
        with obs_span("bench.warm"):
            levels = bfs_grid(srg, roots[0], mesh=mesh).num_levels
    for i in range(len(times), repeats):
        t0 = time.perf_counter()
        with obs_span("bench.repeat", i=i):
            for s in roots:
                levels = bfs_grid(srg, s, mesh=mesh).num_levels
        times.append(time.perf_counter() - t0)
        _stamp(f"repeat {i + 1}/{repeats}: {times[-1]:.3f}s")
        _boundary(jr, f"repeat:{i}", {"seconds": times[-1]})
    total = float(np.median(times))
    per_search = total / num_roots
    teps = (directed_traversed / 2) / per_search

    # ---- telemetry curve: per-axis exchange bytes + schedules ---------
    curve_rec = jr.get("exchange_curve") if jr is not None else None
    if curve_rec is not None:
        curve = curve_rec["curve"]
        _stamp("journal: exchange curve restored")
    else:
        _stamp("telemetry run (per-axis exchange bytes + schedules)...")
        with obs_span("bench.level_curve"):
            res_t, curve = bfs_grid(
                srg, int(source), mesh=mesh, telemetry=True
            )
        levels = res_t.num_levels
        _boundary(jr, "exchange_curve", {"curve": curve})
    exchange = curve.get("exchange", {})
    ledger = _sharded_phase_ledger(
        srg, n, per_search, curve.get("levels", levels), exchange
    )
    # Per-axis wire columns on the phase rows (the grid twin of the 1D
    # exchange-bytes column tools/ledger_compare.py renders).
    steps = max(int(exchange.get("supersteps", levels)), levels, 1)
    for phase, div in (("full_search", 1), ("full_superstep", steps)):
        ledger["phases"][phase]["col_bytes"] = (
            int(exchange.get("col_total_bytes", 0)) // div
        )
        ledger["phases"][phase]["row_bytes"] = (
            int(exchange.get("row_total_bytes", 0)) // div
        )
    for row in ledger["per_shard"]:
        s = row["shard"]
        row["mesh_cell"] = [s // c, s % c]
        row["resident_tiles"] = int(placement["cells"][s // c][s % c])

    check_status = "skipped"
    if do_check:
        from .oracle.bfs import check

        if isinstance(dg, DeviceGraph):
            esrc, edst = unpad_edges(dg)
            host_graph = Graph(dg.num_vertices, esrc, edst)
        else:
            host_graph = dg
        inf = np.iinfo(np.int32).max
        to_check = roots[: max(1, min(len(roots), int(os.environ.get(
            "BENCH_CHECK_ROOTS", str(num_roots)
        )))) ]
        nv = 0
        for s in to_check:
            if jr is not None and jr.get(f"verify:{int(s)}") is not None:
                nv += 1
                continue
            res = bfs_grid(srg, s, mesh=mesh)
            np.testing.assert_array_equal(
                res.dist != inf, reached_mask,
                err_msg=f"root {s} does not cover the component",
            )
            violations = check(host_graph, res.dist, res.parent, s)
            if violations:
                raise SystemExit(
                    f"BFS invariant violations from root {s}: "
                    f"{violations[:5]}"
                )
            nv += 1
            _stamp(f"root {s} verified ({nv}/{len(to_check)})")
            _boundary(jr, f"verify:{int(s)}", {
                "root": int(s), "verdict": "passed",
            })
        check_status = f"passed ({nv}/{num_roots} roots, host check)"

    gtag = f"rmat{scale}" if graph_spec == "rmat" else graph_spec.replace(
        ":", ""
    )
    doc = {
        "metric": f"{gtag}_multichip{r}x{c}_teps",
        "value": teps,
        "unit": "TEPS",
        "vs_baseline": teps / BASELINE_TEPS,
        "details": {
            "device": str(jax.devices()[0]),
            "engine": "grid",
            "graph": graph_spec,
            "mesh": {"row": r, "col": c},
            "num_vertices": int(dg.num_vertices),
            "num_directed_edges": int(dg.num_edges),
            "num_roots": num_roots,
            "roots": roots,
            "vertices_reached": int(reached_mask.sum()),
            "directed_edges_traversed": directed_traversed,
            "seconds_per_search": per_search,
            "batch_seconds_median": total,
            "batch_times": times,
            "supersteps_last_root": int(curve.get("levels", levels)),
            "layout_build_seconds": build_seconds,
            "layout_emax": int(layout.emax),
            "tile_placement": {
                "cells": [
                    [int(x) for x in row] for row in placement["cells"]
                ],
                "total_tiles": placement["total_tiles"],
                "tile_rows_per_stripe": placement[
                    "tile_rows_per_stripe"
                ],
            },
            "check": check_status,
            "exchange": exchange,
            "direction_schedule": curve.get("direction_schedule"),
            "level_curve": {
                k: v for k, v in curve.items()
                if k not in ("exchange", "direction_schedule")
            },
            "sharded_phases": ledger,
            "timing_note": (
                "per-search wall clock includes the host dist/parent "
                "pull of bfs_grid; in-container virtual-mesh captures "
                "measure the per-axis exchange/byte story, not peak "
                "TEPS"
            ),
        },
    }
    print(json.dumps(doc), flush=True)
    if jr is not None:
        jr.put("headline", {"headline": doc})
    _finish_obs(jr)
    fault_point("headline")
    _stamp("grid multichip final line emitted; done")


def _exe_warm_marker(key: str) -> str:
    return os.path.join(
        knobs.raw("BFS_TPU_EXE_CACHE") or "", f"warm_{key}.json"
    )


def _exe_cache_warm(key: str) -> bool:
    """PER-CONFIG compile-cache warmth: a marker written by
    :func:`_mark_exe_warm` after this exact config's fused program
    compiled+warmed on a TPU.  (A mere "any exe_* file exists" probe would
    let warm artifacts from a smaller fallback scale zero the ~830 s cold
    compile estimate at the requested scale — exactly the blind spot the
    estimator exists to close.)"""
    d = knobs.raw("BFS_TPU_EXE_CACHE") or ""
    return bool(d) and os.path.exists(_exe_warm_marker(key))


def _mark_exe_warm(key: str) -> None:
    """Record that ``key``'s fused program is in the exe cache (called
    after the warm run completes on a TPU backend)."""
    d = knobs.raw("BFS_TPU_EXE_CACHE") or ""
    if not d or jax.default_backend() != "tpu":
        return
    try:
        os.makedirs(d, exist_ok=True)
        tmp = f"{_exe_warm_marker(key)}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"key": key, "ts": time.time()}, f)
        os.replace(tmp, _exe_warm_marker(key))
    except OSError:
        pass


def _cold_path_estimator(mbs: float, backend: str, edge_factor: int,
                         seed: int, block: int):
    """Per-scale cold-path cost model for the fallback decision (VERDICT
    r5 weak #1: the old model was blind to the two largest cold costs).
    Layout-build warmth is probed through the bundle TAG — no graph, no
    content hash needed; compile warmth through the exe-cache directory."""
    cache = _layout_cache()
    on_tpu = jax.default_backend() == "tpu"
    from .cache.layout import resolve_builder

    builder = resolve_builder()
    build_s24 = (
        RELAY_DEVICE_BUILD_S24_SECONDS
        if builder == "device"
        else RELAY_BUILD_S24_SECONDS
    )

    def est(s: int) -> dict:
        # ~1.4 GB of device operands at s24, ~proportional to E.
        ship = 1400.0 * 2.0 ** (s - 24) / max(mbs, 1e-6)
        key = f"{backend}_s{s}_ef{edge_factor}_seed{seed}_block{block}"
        layout_warm = cache.resolve_tag(_relay_tag(key)) is not None
        build = 0.0 if layout_warm else build_s24 * 2.0 ** (s - 24)
        compile_warm = (not on_tpu) or _exe_cache_warm(key)
        comp = 0.0 if compile_warm else COLD_COMPILE_SECONDS
        return {
            "est_ship_s": ship,
            "est_layout_build_s": build,
            "est_compile_s": comp,
            "est_total_s": ship + build + comp,
            "layout_cache": "warm" if layout_warm else "cold",
            "layout_builder": builder,
            "compile_cache": "warm" if compile_warm else "cold",
        }

    return est


def _labels_bench(scale: int, edge_factor: int, k: int) -> None:
    """The LABEL-TIER headline (ISSUE 20): BENCH_LABELS=<K> measures the
    landmark distance-label oracle against the exact-traversal serve shape
    on one batch of random point queries.

    Two timed arms over the SAME pairs: **exact** runs one single-source
    traversal per query — what every ``dist(u, v)`` cost before the label
    tier — and **labels** answers tight pairs from the device-resident
    index and pays a traversal only for the fallbacks, which is exactly
    the serve path's dispatch (serve/server.py query_dist).  Every label
    answer is compared against the exact arm's answer for the same pair —
    the headline journals ``wrong_answers`` (must be 0) next to the
    hit/fallback split, and ``details.labels`` is the ledger-diffable
    record (tools/ledger_compare.py labels table).

    Journaled like the other dedicated modes: graph -> labels_build ->
    pairs -> exact -> labels_serve -> headline, each a durable record a
    killed capture resumes from; the label index itself rides the
    content-addressed sidecar cache, so a resumed build is a warm hit."""
    from .cache.layout import graph_content_hash, load_or_build_labels
    from .models.multisource import bfs_multi
    from .serve.labels import LabelOracle, labels_budget_bytes

    backend = _generator_backend()
    seed, block = 42, 8 * 1024
    pairs = int(os.environ.get("BENCH_PAIRS", "128"))
    engine = "pull"
    jr = _open_journal({
        "bench": "labels", "k": k, "scale": scale,
        "edge_factor": edge_factor, "pairs": pairs, "engine": engine,
        "backend": backend, "seed": seed, "block": block,
        **env_config(),
    })
    _install_signal_handlers(jr)
    _stamp(f"labels config: k={k} scale={scale} ef={edge_factor} "
           f"pairs={pairs} device={jax.devices()[0]}")

    with obs_span("bench.load_graph", scale=scale):
        dg, _source = load_or_build(scale, edge_factor, seed, block, backend)
    _stamp(f"device graph ready: V={dg.num_vertices} E={dg.num_edges}")
    if jr is not None:
        ghash = graph_content_hash(dg)
        grec = jr.get("graph")
        if grec is not None and grec["content_hash"] != ghash:
            _stamp("journal: graph content hash mismatch — rotating")
            jr.restart("graph-hash mismatch")
            grec = None
        if grec is None:
            _boundary(jr, "graph", {
                "content_hash": ghash,
                "num_vertices": int(dg.num_vertices),
                "num_edges": int(dg.num_edges),
            })
        done = jr.get("headline")
        if done is not None:
            _stamp("journal: labels run complete; replaying headline")
            print(json.dumps(done["headline"]), flush=True)
            _finish_obs(jr)
            return
    fault_point("graph")

    # ---- label index: sidecar-cached, budget-gated --------------------
    t0 = time.perf_counter()
    with obs_span("bench.labels_build", k=k):
        idx, linfo = load_or_build_labels(
            dg, k, cache=_layout_cache(), engine=engine
        )
        oracle = LabelOracle(idx, budget_bytes=labels_budget_bytes())
    build_wall = time.perf_counter() - t0
    _stamp(
        f"label index ready in {build_wall:.1f}s (K={idx.k}, "
        f"{idx.device_bytes >> 10} KB on device, "
        f"cache={linfo.get('cache')})"
    )
    _boundary(jr, "labels_build", {
        "k": idx.k, "cache": linfo.get("cache"),
        "build_seconds": float(linfo.get("build_seconds", -1.0)),
        "index_bytes": int(idx.nbytes),
        "device_bytes": int(idx.device_bytes),
    })

    # ---- query pairs (journaled so a resume re-times the same batch) --
    prec = jr.get("pairs") if jr is not None else None
    if prec is not None:
        us = np.asarray(prec["u"], dtype=np.int32)
        vs = np.asarray(prec["v"], dtype=np.int32)
    else:
        rng = np.random.default_rng(4242)
        us = rng.integers(0, dg.num_vertices, size=pairs).astype(np.int32)
        vs = rng.integers(0, dg.num_vertices, size=pairs).astype(np.int32)
        _boundary(jr, "pairs", {
            "u": [int(x) for x in us], "v": [int(x) for x in vs],
        })

    def _exact_row(u: int) -> np.ndarray:
        return np.asarray(bfs_multi(dg, [int(u)], engine=engine).dist)[0]

    # ---- exact arm: one traversal per point query ---------------------
    erec = jr.get("exact") if jr is not None else None
    if erec is not None:
        exact_seconds = float(erec["seconds"])
        exact_d = np.asarray(erec["dist"], dtype=np.int64)
        _stamp("journal: exact arm restored")
    else:
        _exact_row(int(us[0]))  # compile + warm outside the clock
        t0 = time.perf_counter()
        with obs_span("bench.labels_exact_arm", pairs=pairs):
            exact_d = np.asarray(
                [_exact_row(int(u))[int(v)] for u, v in zip(us, vs)],
                dtype=np.int64,
            )
        exact_seconds = time.perf_counter() - t0
        _boundary(jr, "exact", {
            "seconds": exact_seconds, "dist": [int(d) for d in exact_d],
        })
    _stamp(f"exact arm: {pairs} queries in {exact_seconds:.2f}s "
           f"({pairs / exact_seconds:.1f} q/s)")

    # ---- label arm: batched lookup, traversal only on fallback --------
    srec = jr.get("labels_serve") if jr is not None else None
    if srec is not None:
        label_seconds = float(srec["seconds"])
        label_d = np.asarray(srec["dist"], dtype=np.int64)
        tight_hits = int(srec["tight_hits"])
        _stamp("journal: label arm restored")
    else:
        oracle.dist(us, vs)  # compile + warm at batch shape, off the clock
        t0 = time.perf_counter()
        with obs_span("bench.labels_serve_arm", pairs=pairs):
            d, tight, _bk = oracle.dist(us, vs)
            label_d = d.astype(np.int64)
            for i in np.flatnonzero(~tight):
                label_d[i] = int(_exact_row(int(us[i]))[int(vs[i])])
        label_seconds = time.perf_counter() - t0
        tight_hits = int(tight.sum())
        _boundary(jr, "labels_serve", {
            "seconds": label_seconds, "tight_hits": tight_hits,
            "dist": [int(x) for x in label_d],
        })
    fallbacks = pairs - tight_hits
    wrong = int(np.count_nonzero(label_d != exact_d))
    _stamp(
        f"label arm: {pairs} queries in {label_seconds:.2f}s "
        f"({pairs / label_seconds:.1f} q/s; {tight_hits} tight, "
        f"{fallbacks} fallbacks, {wrong} wrong)"
    )
    if wrong:
        raise SystemExit(
            f"label tier returned {wrong} answers that disagree with the "
            "exact traversal — the tightness certificate is broken"
        )

    labels_qps = pairs / label_seconds
    exact_qps = pairs / exact_seconds
    doc = {
        "metric": f"rmat{scale}_labels_k{idx.k}_qps",
        "value": labels_qps,
        "unit": "queries/s",
        "details": {
            "device": str(jax.devices()[0]),
            "engine": engine,
            "num_vertices": int(dg.num_vertices),
            "num_directed_edges": int(dg.num_edges),
            "labels": {
                "k": int(idx.k),
                "pairs": int(pairs),
                "tight_hits": tight_hits,
                "fallbacks": fallbacks,
                "tight_rate": tight_hits / pairs,
                "wrong_answers": wrong,
                "labels_qps": labels_qps,
                "exact_qps": exact_qps,
                "speedup": labels_qps / exact_qps,
                "build_seconds": float(linfo.get("build_seconds", -1.0)),
                "index_bytes": int(idx.nbytes),
                "device_bytes": int(idx.device_bytes),
                "cache": linfo.get("cache"),
            },
        },
    }
    print(json.dumps(doc), flush=True)
    if jr is not None:
        jr.put("headline", {"headline": doc})
    _finish_obs(jr)
    fault_point("headline")
    _stamp("labels final line emitted; done")


def main():
    # A cold driver run pays the full relay layout build; per-phase stderr
    # stamps make a slow build diagnosable from the capture's tail instead
    # of reading as a silent stall (BFS_TPU_BUILD_LOG=0 restores quiet
    # builds).  Set here, not at module level: benchmarks.py and the tools
    # import this module for its cache helpers and must not inherit the
    # logging default from a mere import.
    os.environ.setdefault("BFS_TPU_BUILD_LOG", "1")
    scale = int(os.environ.get("BENCH_SCALE", "24"))
    edge_factor = int(os.environ.get("BENCH_EDGE_FACTOR", "6"))
    repeats = int(os.environ.get("BENCH_REPEATS", "3"))
    num_roots = int(os.environ.get("BENCH_ROOTS", "8"))
    engine = os.environ.get("BENCH_ENGINE", "relay")
    do_check = os.environ.get("BENCH_CHECK", "1") != "0"
    # Default: verify EVERY timed root (untimed host work — VERDICT r3 #8).
    check_roots = int(os.environ.get("BENCH_CHECK_ROOTS", str(num_roots)))
    profile_dir = os.environ.get("BENCH_PROFILE", "")
    num_sources = int(os.environ.get("BENCH_SOURCES", "1"))
    sparse = os.environ.get("BENCH_SPARSE", "0") != "0"
    if engine not in ("relay", "pull", "push"):
        raise SystemExit(f"unknown BENCH_ENGINE {engine!r}; use relay/pull/push")
    if num_sources > 1 and engine != "relay":
        raise SystemExit("BENCH_SOURCES > 1 requires BENCH_ENGINE=relay")

    # MULTICHIP mode (ISSUE 11): BENCH_MESH=<n> runs the sharded relay
    # on an n-shard ``graph`` mesh with its own journal phases; the
    # headline carries details.exchange + the sharded phase ledger.
    # ISSUE 17: BENCH_MESH=<r>x<c> routes to the 2D grid engine instead
    # (per-axis exchange columns, mesh_shape in the journal key).
    mesh_spec = (os.environ.get("BENCH_MESH", "0") or "0").strip().lower()
    if "x" in mesh_spec:
        if engine != "relay":
            raise SystemExit("BENCH_MESH requires BENCH_ENGINE=relay")
        from .graph.grid_layout import parse_mesh_spec

        gr, gc = parse_mesh_spec(mesh_spec)
        _grid_multichip_bench(
            gr, gc, scale, edge_factor, repeats, num_roots, do_check
        )
        return
    if int(mesh_spec) > 0:
        if engine != "relay":
            raise SystemExit("BENCH_MESH requires BENCH_ENGINE=relay")
        _multichip_bench(scale, edge_factor, repeats, num_roots, do_check)
        return

    # LABEL-TIER mode (ISSUE 20): BENCH_LABELS=<K> benches the landmark
    # distance-label oracle vs the exact-traversal point-query shape.
    labels_k = int(os.environ.get("BENCH_LABELS", "0") or "0")
    if labels_k > 0:
        _labels_bench(scale, edge_factor, labels_k)
        return

    _stamp(
        f"config: scale={scale} ef={edge_factor} engine={engine} "
        f"roots={num_roots} repeats={repeats} sources={num_sources} "
        f"budget={_budget():.0f}s device={jax.devices()[0]}"
    )
    backend = _generator_backend()
    seed, block = 42, 8 * 1024
    layout_detail = {}

    # Crash-resume journal, content-addressed to the EXACT bench config the
    # way bfs_tpu/cache/ keys layouts (any knob change -> different journal
    # -> fresh run; the graph content hash is validated below as well).
    jr = _open_journal({
        "bench": "ssbfs" if num_sources == 1 else f"multi{num_sources}",
        "scale": scale, "edge_factor": edge_factor, "repeats": repeats,
        "num_roots": num_roots, "engine": engine, "check": do_check,
        "check_roots": check_roots, "num_sources": num_sources,
        "sparse": sparse, "backend": backend, "seed": seed, "block": block,
        # The applier changes what the timed repeats measure: a different
        # BENCH_APPLIER must map to a different journal, never to a resume
        # that mixes xla- and pallas-timed repeats into one median.
        "applier": os.environ.get("BENCH_APPLIER", "auto"),
        # Every registered knob declaring the ``journal`` domain rides
        # in via the registry-derived map (ISSUE 7/15/18/19: direction
        # schedule, kernel arms, expansion, exchange, tile residency,
        # packing, sssp delta) — two different knob configs must never
        # blend into one median, and conversely a resumed run with the
        # same knobs replays the SAME schedule bit-identically.  KNB002
        # proves this set matches bfs_tpu/knobs.py.
        **env_config(),
    })
    _install_signal_handlers(jr)

    if engine == "relay":
        # Cold-path scale fallback (insurance against the degraded windows
        # that killed round 4's driver capture, EXTENDED per VERDICT r5
        # weak #1): the budget model now covers all three cold costs —
        # mask shipping at the measured tunnel bandwidth, the relay layout
        # build, and the XLA compile — with each of the latter two zeroed
        # when its persistent cache is warm.  If the requested scale's
        # cold path would eat the budget, drop to a smaller scale; an
        # honest smaller-scale number in the capture beats rc=124 with
        # nothing.  Disable with BENCH_FALLBACK_SCALES="".
        fb_env = os.environ.get("BENCH_FALLBACK_SCALES", "22,20")
        fb_scales = [int(s) for s in fb_env.split(",") if s.strip()]
        fb_scales = [s for s in fb_scales if s < scale]
        srec = jr.get("scale") if jr is not None else None
        if srec is not None:
            # A resumed run must re-use the killed run's scale decision:
            # the journaled phases downstream all describe THAT graph.
            scale = int(srec["used_scale"])
            layout_detail.update(srec.get("layout_detail", {}))
            _stamp(f"journal: scale decision restored (s{scale})")
        elif fb_scales:
            mbs = _measure_tunnel_mbs()
            layout_detail["tunnel_mbs"] = mbs
            _stamp(f"tunnel bandwidth ~{mbs:.1f} MB/s")
            est = _cold_path_estimator(mbs, backend, edge_factor, seed, block)
            requested = scale
            for cand in [scale] + fb_scales:
                e = est(cand)
                # The ship threshold matches the old (warm-cache) rule;
                # the total adds layout-build + compile awareness.
                if e["est_ship_s"] < 0.35 * _budget() and e["est_total_s"] < 0.7 * _budget():
                    scale = cand
                    break
            else:
                scale = fb_scales[-1]
            layout_detail["cold_path_estimates"] = {
                f"s{c}": est(c) for c in dict.fromkeys([requested] + fb_scales)
            }
            if scale != requested:
                er = est(requested)
                _stamp(
                    f"cold path too expensive for s{requested} "
                    f"(~{er['est_total_s']:.0f}s est: ship {er['est_ship_s']:.0f}s "
                    f"+ layout {er['est_layout_build_s']:.0f}s "
                    f"+ compile {er['est_compile_s']:.0f}s); "
                    f"falling back to s{scale}"
                )
                layout_detail["scale_fallback"] = {
                    "requested_scale": requested,
                    "used_scale": scale,
                    "reason": (
                        f"tunnel ~{mbs:.1f} MB/s; estimated "
                        f"{er['est_total_s']:.0f}s cold path at s{requested} "
                        f"(ship {er['est_ship_s']:.0f}s, layout build "
                        f"{er['est_layout_build_s']:.0f}s "
                        f"[{er['layout_cache']}], compile "
                        f"{er['est_compile_s']:.0f}s [{er['compile_cache']}]) "
                        f"vs {_budget():.0f}s budget"
                    ),
                }
            _boundary(jr, "scale", {
                "used_scale": scale,
                "requested_scale": requested,
                "layout_detail": dict(layout_detail),
            })

    graph_key = f"{backend}_s{scale}_ef{edge_factor}_seed{seed}_block{block}"
    _stamp("loading device graph (npz cache or rebuild)...")
    with obs_span("bench.load_graph", scale=scale):
        dg, source = load_or_build(scale, edge_factor, seed, block, backend)
    # Touch the backend BEFORE the layout phase: engine init pays backend
    # startup anyway, and leaving it lazy would bill the one-time jax
    # platform init to whichever build flavor happens to touch jax first
    # (the device builder), skewing the layout_build phase attribution.
    _stamp(
        f"device graph ready: V={dg.num_vertices} E={dg.num_edges} "
        f"(backend {jax.default_backend()})"
    )
    if jr is not None:
        # Journal invalidation rule: same config but different graph bytes
        # (a regenerated npz cache, a knob the key missed) means every
        # journaled phase describes a DIFFERENT graph -> fresh run.
        from .cache.layout import graph_content_hash

        ghash = graph_content_hash(dg)
        grec = jr.get("graph")
        if grec is not None and grec["content_hash"] != ghash:
            _stamp(
                "journal: graph content hash mismatch — rotating journal "
                "aside and starting a fresh run"
            )
            srec = jr.get("scale")
            jr.restart("graph-hash mismatch")
            if srec is not None:
                jr.put("scale", srec)  # the decision still applies
            grec = None
        if grec is None:
            _boundary(jr, "graph", {
                "content_hash": ghash,
                "num_vertices": int(dg.num_vertices),
                "num_edges": int(dg.num_edges),
                "source": int(source),
                "graph_key": graph_key,
            })
        else:
            fault_point("graph")
        done = jr.get("headline")
        if done is not None:
            # Pure replay — placed AFTER the graph-hash validation above,
            # so a journaled "verified" headline can never be replayed for
            # a graph whose bytes have since changed (that case just
            # rotated the journal and falls through to a fresh run).
            _stamp("journal: run already complete; replaying final headline")
            print(json.dumps(done["headline"]), flush=True)
            _finish_obs(jr)
            return
    else:
        fault_point("graph")

    if engine == "relay":
        from .models.bfs import RelayEngine

        _stamp("loading relay layout (npz cache or rebuild)...")
        with obs_span("bench.layout", kind="relay"):
            rg, build_seconds = load_or_build_relay(dg, graph_key)
        _stamp(f"relay layout ready (build_seconds={build_seconds:.1f})")
        _boundary(jr, "layout", {
            "build_seconds": build_seconds,
            "relay_layout_cache": _relay_cache_detail(),
            # ISSUE 10: the journaled layout_build phase — builder flavor
            # plus per-stage wall seconds (and, on the device flavor, the
            # amortized compile_seconds next to them).
            "layout_build": _layout_build_detail(),
        })
        applier = os.environ.get("BENCH_APPLIER", "auto")
        # The probe ships ~2.5 GB of masks through the tunnel and times
        # four programs — minutes of wall clock that round 4's driver
        # capture died inside.  Its outcome is stable per graph layout, so
        # a successful probe is CACHED and reused (BENCH_PROBE=fresh
        # re-measures; the cached dict is shipped in the capture with a
        # note so the evidence trail stays intact).
        probe_cache = os.path.join(_CACHE_DIR, f"probe_{graph_key}.json")
        if applier == "auto" and os.environ.get("BENCH_PROBE", "") != "fresh":
            try:
                with open(probe_cache) as f:
                    cached_probe = json.load(f)
                applier = cached_probe["selected"]
                layout_detail["applier_probe"] = {
                    **cached_probe,
                    "note": "cached probe outcome (BENCH_PROBE=fresh "
                    "re-measures)",
                }
                _stamp(f"using cached probe outcome: {applier}")
            except (OSError, ValueError, KeyError):
                pass
        if applier == "auto" and _behind(0.30):
            # Behind budget at the probe: do NOT fall back to an unmeasured
            # default (VERDICT r5 item 8 — no capture ships "selected by
            # default").  Force the probe's COARSE arms — a single K-loop
            # pair for pallas plus the XLA applier timed on a ~100 MB
            # stage prefix — an ENFORCED bound (the full mask ship and
            # adaptive repeat loops never start), not a clock race, and
            # the user's own BFS_TPU_PROBE_BUDGET is left untouched.
            os.environ["BFS_TPU_PROBE_COARSE"] = "1"
            _stamp(
                "behind budget: probe forced to coarse arms "
                "(BFS_TPU_PROBE_COARSE=1, subsampled xla prefix)"
            )
        # Engine init ships ~1.4 GB of routing masks through the tunnel —
        # the time-varying transport whose bad windows killed two driver
        # captures.  A transient transport failure here gets a bounded
        # retry with backoff; a real bug still raises immediately
        # (resilience/retry.py classifier).
        from .resilience.retry import RetryPolicy, retry_call

        with obs_span("bench.engine_init"):
            eng = retry_call(
                lambda: RelayEngine(rg, sparse_hybrid=sparse, applier=applier),
                policy=RetryPolicy(
                    max_attempts=int(os.environ.get("BENCH_INIT_RETRIES", "2")),
                    base_delay_s=2.0, max_delay_s=30.0,
                ),
                on_retry=lambda a, e, d: _stamp(
                    f"engine init failed transiently (attempt {a}: {e!r}); "
                    f"retrying in {d:.1f}s"
                ),
                describe="relay engine init",
            )
        _stamp(f"engine init done (applier={eng.applier})")
        if jr is not None:
            # BENCH_APPLIER=auto can RESOLVE differently across processes
            # (cached probe vs budget default): timed repeats from two
            # different appliers must never blend into one median, so an
            # applier drift invalidates the journal like a config change.
            erec = jr.get("engine_init")
            if erec is not None and erec["applier"] != eng.applier:
                _stamp(
                    f"journal: applier drift ({erec['applier']} -> "
                    f"{eng.applier}); rotating journal aside (fresh run)"
                )
                keep = {
                    p: jr.get(p) for p in ("scale", "graph", "layout")
                    if jr.get(p) is not None
                }
                jr.restart("applier drift")
                for p, payload in keep.items():
                    jr.put(p, payload)  # still true for this run
        # The per-phase kernel verdicts travel with the applier record: a
        # resumed run whose phase selection resolved differently (cached
        # TPU probe vs a fresh one) must rotate for the same reason an
        # applier drift does — two phase mixes never blend into one
        # median.
        if jr is not None:
            erec = jr.get("engine_init")
            drifted = erec is not None and erec.get("phase_selection") not in (
                None,
                {k: v for k, v in eng.phase_selection.items() if k != "basis"},
            )
            if drifted:
                _stamp(
                    "journal: phase-kernel selection drift; rotating "
                    "journal aside (fresh run)"
                )
                keep = {
                    p: jr.get(p) for p in ("scale", "graph", "layout")
                    if jr.get(p) is not None
                }
                jr.restart("phase-selection drift")
                for p, payload in keep.items():
                    jr.put(p, payload)
        _boundary(jr, "engine_init", {
            "applier": eng.applier,
            "phase_selection": {
                k: v for k, v in eng.phase_selection.items() if k != "basis"
            },
        })
        layout_detail["phase_kernel_selection"] = eng.phase_selection
        if eng.phase_probe is not None:
            layout_detail["phase_kernel_probe"] = eng.phase_probe
        # details.expansion (ISSUE 15): which expansion arm the timed
        # repeats ran and WHY, plus the tile-layout density evidence; the
        # per-level arm schedule joins once the direction schedule is
        # known (the level-curve phase below).
        layout_detail["expansion"] = _expansion_detail(eng)
        if (
            isinstance(eng.applier_probe, dict)
            and "selected" in eng.applier_probe
            # Only a COMPLETE probe (selection_basis == "measured": both
            # appliers timed and compared) is worth pinning: a
            # budget-exhausted probe's selection is a default, not a
            # measurement, and caching it would lock the default in
            # across healthy windows too.
            and "xla_net_apply_seconds" in eng.applier_probe
            and eng.applier_probe.get("selection_basis") == "measured"
        ):
            os.makedirs(_CACHE_DIR, exist_ok=True)
            tmp = f"{probe_cache}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(eng.applier_probe, f)
            os.replace(tmp, probe_cache)
        if num_sources > 1:
            _multi_source_bench(
                rg, eng, dg, source,
                num_sources=num_sources, do_check=do_check,
                probe_note=layout_detail.get("applier_probe"),
                jr=jr,
            )
            return
        layout_detail = {
            **layout_detail,
            "applier": eng.applier,
            "applier_probe": eng.applier_probe
            or layout_detail.get("applier_probe"),
            "relay_layout_build_seconds": build_seconds,
            "relay_layout_cache": _relay_cache_detail(),
            # ISSUE 10 acceptance: the capture itself carries the
            # device-vs-host evidence.
            "layout_build": _layout_build_detail(),
            "relay_mask_bytes": int(rg.net_masks.nbytes + rg.vperm_masks.nbytes),
            "relay_net_mask_bytes": int(rg.net_masks.nbytes),
            "relay_vperm_mask_bytes": int(rg.vperm_masks.nbytes),
            "relay_sparse_adj_bytes": int(
                rg.adj_dst.nbytes + rg.adj_slot.nbytes + rg.adj_indptr.nbytes
            ),
            "relay_net_size_log2": int(np.log2(rg.net_size)),
            "sparse_hybrid": sparse,
        }

        def run_one(s):
            return eng.run_many_device([s])[0]

        def run_roots(roots):
            return eng.run_many_device(roots)

        def host_result(s):
            return eng.run(s)

    elif engine == "pull":
        pg = load_or_build_pull(dg, graph_key)
        from .graph.ell import device_ell

        ell0, folds = device_ell(pg)

        from .ops.packed import packed_parent_fits, resolve_packed

        # Packed fused-word carry when V fits; the warm-phase guard below
        # flips this off (and re-warms) if any root hits the 62-level cap,
        # so timed repeats can never ship truncated numbers.
        packed_flag = {
            "on": resolve_packed(packed_parent_fits(pg.num_vertices))
        }

        def run_roots(roots):
            # Explicit per-root scalar upload (transfer-guard-clean: the
            # implicit jnp.int32 conversion raised under
            # BFS_TPU_TRANSFER_GUARD=1 inside the timed-repeat region).
            return [
                _bfs_pull_fused(
                    ell0, folds, jax.device_put(np.int32(s)), pg.num_vertices,
                    pg.num_vertices, packed_flag["on"],
                )
                for s in roots
            ]

        def host_result(s):
            from .models.bfs import BfsResult

            st = jax.device_get(run_roots([s])[0])
            return BfsResult(
                dist=np.asarray(st.dist[: pg.num_vertices]),
                parent=np.asarray(st.parent[: pg.num_vertices]),
                num_levels=int(st.level),
            )

    else:
        from .ops.packed import packed_parent_fits, resolve_packed

        src = jnp.asarray(dg.src)
        dst = jnp.asarray(dg.dst)
        packed_flag = {
            "on": resolve_packed(packed_parent_fits(dg.num_vertices))
        }

        def run_roots(roots):
            return [
                _bfs_fused(
                    src, dst, jax.device_put(np.int32(s)), dg.num_vertices,
                    dg.num_vertices, packed_flag["on"],
                )
                for s in roots
            ]

        def host_result(s):
            from .models.bfs import BfsResult

            st = jax.device_get(run_roots([s])[0])
            return BfsResult(
                dist=np.asarray(st.dist[: dg.num_vertices]),
                parent=np.asarray(st.parent[: dg.num_vertices]),
                num_levels=int(st.level),
            )

    # ---- reference run: component, numerator, random roots -----------------
    # The component mask comes down as packed bits (V/8 bytes), NOT a full
    # dist+parent pull — 2 MB vs 128 MB at s24, minutes of difference in a
    # degraded-tunnel window.
    ref_rec = jr.get("reference") if jr is not None else None
    if ref_rec is not None:
        reached_mask = _restore_mask(jr, dg)
        directed_traversed = int(ref_rec["directed_traversed"])
        _stamp(
            "journal: reference run restored (component mask + numerator); "
            "skipping re-run"
        )
    else:
        _stamp("reference run (compile + warm)...")
        with obs_span("bench.reference"):
            ref_state = run_roots([source])[0]  # device state; also compiles + warms
            if engine == "relay":
                reached_mask = _reached_mask_packed(
                    ref_state, eng.relay_graph.vr, remap=eng.relay_graph.old2new
                )
            else:
                reached_mask = _reached_mask_packed(ref_state, dg.num_vertices)
        _stamp("reference run done; computing component + roots...")
        esrc_h, _ = unpad_edges(dg)
        directed_traversed = int(np.count_nonzero(reached_mask[esrc_h]))
        _boundary(
            jr, "reference",
            {
                "directed_traversed": directed_traversed,
                "vertices_reached": int(reached_mask.sum()),
            },
            arrays={"mask_packed": np.packbits(reached_mask)},
        )
    roots_rec = jr.get("roots") if jr is not None else None
    if roots_rec is not None:
        roots = [int(r) for r in roots_rec["roots"]]
        _stamp("journal: roots restored")
    else:
        rng = np.random.default_rng(4242)
        pool = np.flatnonzero(reached_mask)
        roots = [source] + [
            int(s) for s in rng.choice(pool, size=num_roots - 1, replace=False)
        ]
        _boundary(jr, "roots", {"roots": roots})

    def sync(states):
        # Reading a VALUE forces a real sync; block_until_ready can return
        # early through the tunnel.  Device execution is in-order, so the
        # last state's level syncs the whole batch.
        return int(states[-1].level)

    # The budget-driven repeat reduction is a PLAN phase: journaled before
    # any repeat runs, so a resumed run honors the killed run's decision
    # (a headline's batch_times must describe one coherent plan, not a mix).
    plan = jr.get("repeats_plan") if jr is not None else None
    if plan is not None:
        if int(plan["repeats"]) != repeats:
            _stamp(f"journal: honoring recorded repeats plan ({plan['repeats']})")
        repeats = int(plan["repeats"])

    times = []
    if jr is not None:
        for i in range(repeats):
            rep = jr.get(f"repeat:{i}")
            if rep is None:
                break
            times.append(float(rep["seconds"]))
        if times:
            _stamp(f"journal: {len(times)}/{repeats} timed repeats restored")

    warm_rec = jr.get("warm") if jr is not None else None
    if len(times) < repeats or warm_rec is None:
        _stamp(f"warming {num_roots}-root chained batch...")
        with obs_span("bench.warm", roots=num_roots):
            states = run_roots(roots)  # warm every root's program instance
            levels = sync(states)
            # Packed-cap guard (untimed, code-review finding): if ANY warm
            # root stopped on the packed 62-level cap, disable the packed
            # carry and re-warm unpacked — the timed repeats must never ship
            # truncated supersteps, even when verification is later skipped
            # on budget or disabled.  Zero cost on shallow graphs (the level
            # test short-circuits the flag pulls).
            from .ops.packed import PACKED_MAX_LEVELS

            if levels >= PACKED_MAX_LEVELS:
                flags = jax.device_get([(s.changed, s.level) for s in states])
                if any(
                    bool(c) and int(l) >= PACKED_MAX_LEVELS for c, l in flags
                ):
                    _stamp(
                        "warm run hit the packed 62-level cap: disabling "
                        "packed state and re-warming unpacked"
                    )
                    if engine == "relay":
                        eng.packed = False
                    else:
                        packed_flag["on"] = False
                    levels = sync(run_roots(roots))
            del states
        if engine == "relay":
            # The fused program for this exact config is now in the exe
            # cache; the scale-fallback estimator keys its compile estimate
            # off this.
            _mark_exe_warm(graph_key)
        _boundary(jr, "warm", {"supersteps_last_root": levels})
        _stamp("warm done; timing repeats...")
    else:
        levels = int(warm_rec["supersteps_last_root"])

    if plan is None:
        if _behind(0.60) and repeats > 1:
            _stamp(f"behind budget: repeats {repeats} -> 1")
            repeats = 1
        _boundary(jr, "repeats_plan", {"repeats": repeats})
        del times[repeats:]
    # bfs_tpu: hot-start — headline timed-repeat region: dispatch K chained
    # searches with NO transfer until the single sync() after the guard
    # (BFS_TPU_TRANSFER_GUARD=1 enforces this at runtime; the static TRC
    # rules police it in review).
    for i in range(len(times), repeats):
        if profile_dir and i == repeats - 1:
            with jax.profiler.trace(profile_dir):
                t0 = time.perf_counter()
                with obs_span("bench.repeat", i=i):
                    with guarded_region("bench.timed_repeat"):
                        states = run_roots(roots)
                    levels = sync(states)
                times.append(time.perf_counter() - t0)
        else:
            t0 = time.perf_counter()
            with obs_span("bench.repeat", i=i):
                with guarded_region("bench.timed_repeat"):
                    states = run_roots(roots)
                levels = sync(states)
            times.append(time.perf_counter() - t0)
        _stamp(f"repeat {i + 1}/{repeats}: {times[-1]:.3f}s")
        _boundary(jr, f"repeat:{i}", {"seconds": times[-1]})
    # bfs_tpu: hot-end
    total = float(np.median(times))
    per_search = total / num_roots

    teps = (directed_traversed / 2) / per_search
    teps_directed_total = dg.num_edges / per_search

    common = {
        "device": str(jax.devices()[0]),
        "engine": engine,
        "num_vertices": dg.num_vertices,
        "num_directed_edges": dg.num_edges,
        "num_roots": num_roots,
        "roots": roots,
        "supersteps_last_root": levels,
        "vertices_reached": int(reached_mask.sum()),
        "teps_convention": (
            "graph500: input undirected edges in traversed "
            "component / mean time per search (K chained "
            "searches, one sync)"
        ),
        "directed_edges_traversed": directed_traversed,
        "teps_directed_total": teps_directed_total,
        "seconds_per_search": per_search,
        "batch_seconds_median": total,
        "batch_times": times,
    }

    def emit(check_status, extra):
        doc = {
            "metric": f"rmat{scale}_ssbfs_teps",
            "value": teps,
            "unit": "TEPS",
            "vs_baseline": teps / BASELINE_TEPS,
            "details": {**common, "check": check_status, **extra},
        }
        print(json.dumps(doc), flush=True)
        return doc

    # From here the run HAS a result: arm the SIGTERM/SIGALRM flush with it
    # so a harness timeout emits a partial-but-valid headline line.
    _PARTIAL["emit"] = lambda status: emit(
        status, {"partial": True, **layout_detail}
    )

    # Provisional headline IMMEDIATELY after the timed repeats (VERDICT r4
    # #1a): if any later phase — profile, verification — dies or outlives
    # the driver's timeout, the evidence line is already in the tail.  The
    # final line (verification status filled in) follows and supersedes it.
    emit("pending (final line follows)", {"provisional": True, **layout_detail})
    _stamp("provisional headline emitted; starting diagnostics + checks")
    _boundary(jr, "provisional", {"value": teps})

    # Per-superstep dense/sparse decomposition of the first (hub) root —
    # untimed diagnostics, after the timed repeats (VERDICT r3 #2).
    if engine == "relay" and os.environ.get("BENCH_STEP_PROFILE", "1") != "0":
        prof_rec = jr.get("profile") if jr is not None else None
        if prof_rec is not None:
            layout_detail["superstep_profile"] = prof_rec["superstep_profile"]
            _stamp("journal: superstep profile restored")
        elif _behind(0.65):
            _stamp("behind budget: skipping superstep profile")
            layout_detail["superstep_profile"] = "skipped (time budget)"
            _boundary(jr, "profile", {
                "superstep_profile": "skipped (time budget)",
            })
        else:
            with obs_span("bench.superstep_profile"):
                layout_detail["superstep_profile"] = _superstep_profile(
                    eng, source
                )
            _stamp("superstep profile done")
            _boundary(jr, "profile", {
                "superstep_profile": layout_detail["superstep_profile"],
            })

    # Per-phase on-chip superstep ledger (VERDICT r5 task #4): the
    # non-mask residual attributed by phase-isolated jits — vperm /
    # broadcast / net-apply / row-min / state-update (both layouts, with
    # the analytic dist/parent byte halving) — instead of guessed.
    if engine == "relay" and os.environ.get("BENCH_PHASE_LEDGER", "1") != "0":
        ledger_rec = jr.get("phase_ledger") if jr is not None else None
        if ledger_rec is not None:
            layout_detail["superstep_phases"] = ledger_rec["superstep_phases"]
            _stamp("journal: superstep phase ledger restored")
        elif _behind(0.70):
            _stamp("behind budget: skipping superstep phase ledger")
            layout_detail["superstep_phases"] = "skipped (time budget)"
            _boundary(jr, "phase_ledger", {
                "superstep_phases": "skipped (time budget)",
            })
        else:
            from .profiling import superstep_phase_ledger

            _stamp("superstep phase ledger (phase-isolated jits)...")
            with obs_span("bench.phase_ledger"):
                # Small graphs need more K-loop iterations for the
                # difference timing to clear the timer floor; the knobs
                # are part of methodology, not config (not in the
                # journal key — a resumed run restores the measured
                # ledger rather than re-running it).
                layout_detail["superstep_phases"] = superstep_phase_ledger(
                    eng,
                    loops=int(os.environ.get("BENCH_LEDGER_LOOPS", "4")),
                    repeats=int(os.environ.get("BENCH_LEDGER_REPEATS", "2")),
                )
            _stamp("superstep phase ledger done")
            _boundary(jr, "phase_ledger", {
                "superstep_phases": layout_detail["superstep_phases"],
            })

    # Superstep-granular checkpoint overhead (ISSUE 14): with BFS_TPU_CKPT
    # enabled, one UNTIMED segmented-with-checkpoints run is measured next
    # to one fused run and the manager's report ships as
    # details.superstep_ckpt — the capture carries the checkpoint cost
    # (snapshot seconds/bytes, resolved interval, overhead ratio) next to
    # the headline, so no capture hides it.  Epochs land in the journal's
    # sidecar directory, content-keyed by the bench config like every
    # other capture.  Off (the default) leaves the capture and every
    # timed program byte-identical to the pre-ISSUE-14 bench.
    if engine == "relay":
        from .resilience.superstep_ckpt import resolve_ckpt

        _ckpt_cfg = resolve_ckpt()
        if _ckpt_cfg.enabled:
            ck_rec = jr.get("superstep_ckpt") if jr is not None else None
            if ck_rec is not None:
                layout_detail["superstep_ckpt"] = ck_rec["superstep_ckpt"]
                _stamp("journal: superstep checkpoint overhead restored")
            else:
                from .resilience.superstep_ckpt import SuperstepCheckpointer

                _stamp(
                    "superstep checkpoint overhead "
                    f"(segmented run, {_ckpt_cfg.mode})..."
                )
                mgr = SuperstepCheckpointer(
                    os.path.dirname(jr.path) if jr is not None else _CACHE_DIR,
                    {
                        "bench": graph_key, "engine": engine,
                        "source": int(source),
                        "direction": eng.direction.key(),
                    },
                    cfg=_ckpt_cfg,
                )
                with obs_span("bench.superstep_ckpt"):
                    t0 = time.perf_counter()
                    # eng.run, not run_one: the single-root path carries
                    # the packed-truncation detect-and-rerun fallback,
                    # so on a >62-level graph both arms compare FULL
                    # traversals (run_many_device returns the truncated
                    # packed state by contract).
                    off_res = eng.run(source)
                    fused_s = time.perf_counter() - t0
                    t0 = time.perf_counter()
                    seg_res = eng.run_segmented(source, ckpt=mgr)
                    seg_s = time.perf_counter() - t0
                detail = {
                    **mgr.report(),
                    "fused_seconds": fused_s,
                    "segmented_seconds": seg_s,
                    "overhead_ratio": (
                        seg_s / fused_s if fused_s > 0 else None
                    ),
                    # The segment contract, checked in-capture: the
                    # segmented run's result is bit-identical to the
                    # fused program's.
                    "bit_identical": bool(
                        np.array_equal(seg_res.dist, off_res.dist)
                        and np.array_equal(seg_res.parent, off_res.parent)
                    ),
                }
                layout_detail["superstep_ckpt"] = detail
                ratio = detail["overhead_ratio"]
                _stamp(
                    "superstep checkpoint overhead done "
                    + (f"(x{ratio:.2f} vs fused)" if ratio else "")
                )
                _boundary(jr, "superstep_ckpt", {"superstep_ckpt": detail})

    # Streamed-arm ledger (ISSUE 18): when the engine pages adjacency
    # from the host store (BFS_TPU_TILES=stream, or auto over budget),
    # one UNTIMED streamed traversal journals the per-level
    # bytes-streamed / hit / miss / evict curve as details.stream, with
    # an in-capture bit-identity check against the resident mxu arm
    # (dist/parent + direction schedule).  BENCH_STREAM_CHECK=0 skips
    # the resident compare at true beyond-HBM scales, where shipping the
    # whole tile layout is exactly what streaming exists to avoid.
    if engine == "relay" and getattr(eng, "_stream_effective",
                                     lambda: False)():
        st_rec = jr.get("stream") if jr is not None else None
        if st_rec is not None:
            layout_detail["stream"] = st_rec["stream"]
            _stamp("journal: stream ledger restored")
        else:
            _stamp("stream ledger (untimed streamed traversal)...")
            with obs_span("bench.stream"):
                t0 = time.perf_counter()
                s_res, s_curve = eng.run_streamed(source, telemetry=True)
                stream_s = time.perf_counter() - t0
            detail = dict(eng.stream_report)
            detail["seconds"] = stream_s
            detail["direction_schedule"] = s_curve["direction_schedule"]
            if os.environ.get("BENCH_STREAM_CHECK", "1") != "0":
                prev_mode = eng.tiles_mode
                eng.tiles_mode = "resident"
                try:
                    with obs_span("bench.stream_resident_check"):
                        r_res = eng.run(source)
                finally:
                    eng.tiles_mode = prev_mode
                detail["bit_identical"] = bool(
                    np.array_equal(s_res.dist, r_res.dist)
                    and np.array_equal(s_res.parent, r_res.parent)
                )
            layout_detail["stream"] = detail
            _stamp(
                "stream ledger done "
                f"({detail['bytes_streamed']} bytes streamed, "
                f"{detail['evictions']} evictions)"
            )
            _boundary(jr, "stream", {"stream": detail})

    # Device level curve (ISSUE 6 tentpole b): one UNTIMED fused search
    # carrying the obs/telemetry accumulator as extra while_loop state —
    # per-level frontier occupancy (+ out-edges on relay), pulled once at
    # loop exit.  Ships as details.level_curve; its occupancy sum is
    # cross-checked against the reference component size, and with the
    # superstep profile's per-level seconds it yields per-level TEPS.
    # This is the direction-switching input for ROADMAP item 2.
    if os.environ.get("BENCH_LEVEL_CURVE", "1") != "0":
        curve_rec = jr.get("level_curve") if jr is not None else None
        if curve_rec is not None:
            layout_detail["level_curve"] = curve_rec["level_curve"]
            if isinstance(curve_rec["level_curve"], dict):
                sched = curve_rec["level_curve"].get("direction_schedule")
                if sched is not None:
                    layout_detail["direction_schedule"] = sched
                    _expansion_per_level(layout_detail)
            _stamp("journal: level curve restored (direction schedule rides it)")
        elif _behind(0.80):
            _stamp("behind budget: skipping level curve")
            layout_detail["level_curve"] = "skipped (time budget)"
            _boundary(jr, "level_curve", {
                "level_curve": "skipped (time budget)",
            })
        else:
            _stamp("level curve (telemetry-carrying fused run)...")
            with obs_span("bench.level_curve"):
                reference = int(reached_mask.sum())
                if engine == "relay":
                    curve = eng.run_level_curve(
                        source, reference_reached=reference
                    )
                else:
                    from .models.bfs import bfs_level_curve

                    curve = bfs_level_curve(
                        pg if engine == "pull" else dg, source,
                        engine=engine, reference_reached=reference,
                    )
            prof = layout_detail.get("superstep_profile")
            fe = curve.get("frontier_edges")
            if isinstance(prof, dict) and fe:
                # Edges traversed DURING the superstep that settled level l
                # are the out-edges of the level l-1 frontier.
                per_level = {}
                sync_s = float(prof.get("sync_overhead_seconds", 0.0))
                for e in prof.get("supersteps", []):
                    l = int(e["level"])
                    s = float(e["seconds_incl_sync"]) - sync_s
                    if 1 <= l <= len(fe) and s > 0:
                        per_level[str(l)] = fe[l - 1] / s
                curve["per_level_teps"] = per_level
            if not curve["occupancy_sum_matches_reference"]:
                _stamp(
                    "WARNING: level-curve occupancy sum "
                    f"{curve['reachable']} != reference component "
                    f"{curve['reference_reached']}"
                )
            layout_detail["level_curve"] = curve
            sched = curve.get("direction_schedule")
            if sched is not None:
                # details.direction_schedule next to the curve (ISSUE 7):
                # the per-superstep push/pull record from the SAME
                # telemetry pull, journaled with the curve so a resumed
                # bench replays it bit-identically.
                layout_detail["direction_schedule"] = sched
                _expansion_per_level(layout_detail)
                _stamp(
                    "direction schedule: "
                    + "".join(
                        "P" if s == "push" else "L" for s in sched["schedule"]
                    )
                    + f" ({sched['switches']} switches, mode={sched['mode']})"
                )
            _stamp(
                f"level curve done: {curve['levels']} levels, peak "
                f"{curve['peak_occupancy']} at L{curve['peak_level']}, "
                f"occupancy sum matches reference: "
                f"{curve['occupancy_sum_matches_reference']}"
            )
            _boundary(jr, "level_curve", {"level_curve": curve})

    check_status = "skipped"
    if do_check and _behind(0.90):
        # Behind budget AT the verification phase: emit the final headline
        # unverified and exit 0 — never force even one 128 MB-pull host
        # verification (the exact line the r5 driver capture died on).
        check_status = "skipped (budget)"
        _stamp("behind budget at verification phase: skipping checks")
    elif do_check:
        to_check = roots[: max(1, check_roots)]
        n_checked = 0
        mode = "host check"

        def _root_done(s) -> bool:
            """True when this root's verdict is already journaled — a
            resumed run never re-pays a completed verification."""
            return jr is not None and jr.get(f"verify:{int(s)}") is not None

        def _mark_root(s, root_mode: str) -> None:
            _boundary(jr, f"verify:{int(s)}", {
                "root": int(s), "mode": root_mode, "verdict": "passed",
            })

        def host_verify() -> int:
            from .oracle.bfs import check

            remaining = [s for s in to_check if not _root_done(s)]
            if not remaining:
                _stamp("journal: all verification verdicts restored")
                return len(to_check)
            esrc, edst = unpad_edges(dg)
            host_graph = Graph(dg.num_vertices, esrc, edst)
            inf = np.iinfo(np.int32).max
            n = 0
            for s in to_check:
                if _root_done(s):
                    n += 1
                    _stamp(f"root {s} verified (journal) ({n}/{len(to_check)})")
                    continue
                if n >= 1 and _behind(0.90):
                    _stamp(
                        f"behind budget: stopping verification after "
                        f"{n}/{len(to_check)} roots"
                    )
                    break
                res = host_result(s)
                np.testing.assert_array_equal(
                    res.dist != inf, reached_mask,
                    err_msg=f"root {s} does not cover the component",
                )
                violations = check(host_graph, res.dist, res.parent, s)
                if violations:
                    raise SystemExit(
                        f"BFS invariant violations from root {s}: "
                        f"{violations[:5]}"
                    )
                n += 1
                _stamp(f"root {s} verified ({n}/{len(to_check)})")
                _mark_root(s, "host check")
            return n

        def device_verify() -> int:
            # On-device check() (ISSUE 2 tentpole c): the three algs4
            # invariants as XLA reductions over device-resident arrays —
            # each root costs a 24-byte counter pull + one coverage int
            # instead of a 128 MB dist+parent transfer + host edge sweep.
            # Host check() (oracle/bfs.py) stays the parity oracle; the
            # device port is asserted against it in tests.
            from .oracle.device import DeviceChecker

            remaining = [s for s in to_check if not _root_done(s)]
            if not remaining:
                # Every verdict is journaled: no edge ship, no checker.
                _stamp("journal: all verification verdicts restored")
                return len(to_check)
            if engine == "push":
                checker = DeviceChecker(src, dst, dg.num_vertices)
            else:
                _stamp(
                    "shipping edge arrays for on-device check "
                    f"({(dg.src.nbytes + dg.dst.nbytes) >> 20} MB)..."
                )
                checker = DeviceChecker.from_graph(dg)

            def dev_state(s):
                st = run_roots([s])[0]
                if engine == "relay":
                    return eng.to_original_device(st, s)
                return st.dist, st.parent

            # Coverage reference = the SAME host mask the TEPS numerator
            # was counted from (packed + shipped: V/8 bytes) — NOT a fresh
            # device rerun, which would let a consistently-wrong device
            # verify itself against itself while the headline numerator
            # stayed pinned to the earlier reference run.
            from .ops.relay import pack_std_host

            pad = (-dg.num_vertices) % 32
            ref_bits = (
                np.concatenate([reached_mask, np.zeros(pad, bool)])
                if pad
                else reached_mask
            )
            ref_words = jnp.asarray(pack_std_host(ref_bits))
            n = 0
            for s in to_check:
                if _root_done(s):
                    n += 1
                    _stamp(f"root {s} verified (journal) ({n}/{len(to_check)})")
                    continue
                if n >= 1 and _behind(0.95):
                    _stamp(
                        f"behind budget: stopping verification after "
                        f"{n}/{len(to_check)} roots"
                    )
                    break
                dist_d, parent_d = dev_state(s)
                mismatch = checker.coverage_mismatch(dist_d, ref_words)
                if mismatch:
                    raise SystemExit(
                        f"root {s} does not cover the component "
                        f"({mismatch} vertices differ)"
                    )
                bad = checker.check(dist_d, parent_d, s)
                if bad:
                    raise SystemExit(
                        f"BFS invariant violations from root {s} "
                        f"(on-device check): {bad}"
                    )
                n += 1
                _stamp(f"root {s} verified on-device ({n}/{len(to_check)})")
                _mark_root(s, "on-device check")
            return n

        with obs_span("bench.verify", roots=len(to_check)):
            if os.environ.get("BENCH_DEVICE_CHECK", "1") != "0":
                try:
                    n_checked = device_verify()
                    mode = "on-device check"
                except SystemExit:
                    raise  # real invariant violation: the run must fail
                except Exception as exc:
                    _stamp(
                        f"on-device check unavailable ({exc!r}); host fallback"
                    )
                    n_checked = host_verify()
            else:
                n_checked = host_verify()
        check_status = f"passed ({n_checked}/{num_roots} roots, {mode})"
        if n_checked < len(to_check):
            check_status += " [budget-limited]"

    from .utils.metrics import artifact_report

    layout_detail["artifact_caches"] = artifact_report()
    doc = emit(check_status, layout_detail)
    # Journal the headline LAST: its presence means "this run is complete,
    # replay me verbatim" — a kill between the print and this record only
    # costs the next invocation a re-emit from already-journaled phases.
    if jr is not None:
        jr.put("headline", {"headline": doc})
    _finish_obs(jr)
    fault_point("headline")
    from .analysis.runtime import format_retrace_report

    _stamp(format_retrace_report())
    _stamp("final line emitted; done")


if __name__ == "__main__":
    main()
