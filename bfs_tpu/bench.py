"""Headline benchmark: single-source BFS TEPS on an R-MAT graph (TPU).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "TEPS", "vs_baseline": N}

Baseline: the reference's best serial number — largeG 15.2M directed edges /
1.170 s ≈ 13 M TEPS (BASELINE.md, derived from docs/BigData_Project.pdf §1.5
Table 7; the reference's own parallel version never beat it, OOMing on
largeG).

TEPS convention (Graph500-honest): the numerator is the number of INPUT
undirected edges inside the traversed component — i.e. directed edges whose
source is reached, divided by 2 for the bi-directing — not the total edge
count of the graph.  The round-1 all-directed-edges convention is reported
alongside in ``details.teps_directed_total`` for continuity.

Every run is verified: the result must pass the ported algs4 ``check()``
optimality invariants (BreadthFirstPaths.java:172-221) before the number is
printed.  Set BENCH_CHECK=0 to skip.

Env knobs: BENCH_SCALE (default 24), BENCH_EDGE_FACTOR (default 6 — exactly
the BASELINE.json "100M-edge R-MAT scale-24" config: 2^24 * 6 = 100.7M input
undirected edges), BENCH_REPEATS (5), BENCH_ENGINE (relay|pull|push),
BENCH_CHECK (1), BENCH_PROFILE (path — write a jax.profiler trace of one
timed run there), BENCH_SOURCES (default 1 — >1 runs the BASELINE.json
config-5 batched multi-source benchmark: that many independent BFS trees in
device-resident chunks of BENCH_MULTI_CHUNK (8; 16 exhausts HBM at scale 24
— the vmapped pipeline materializes ~1 GB of per-tree intermediates),
reporting AGGREGATE TEPS.  The routing masks amortize across a chunk, but
per-tree byte-array traffic does not, so per-tree time lands near the
single-source number; lock-step chunks also run max-eccentricity supersteps).
"""

from __future__ import annotations

import json
import os
import time

import jax

# Persistent XLA compile cache: the relay engine's ~100-stage programs take
# minutes to compile through the remote compile service; cache across runs.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(_REPO_ROOT, ".bench_cache", "xla")
    ),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

import jax.numpy as jnp
import numpy as np

from .graph.csr import Graph, DeviceGraph, build_device_graph, unpad_edges
from .graph.ell import build_pull_graph
from .graph.generators import rmat_graph
from .models.bfs import _bfs_fused, _bfs_pull_fused

BASELINE_TEPS = 15_172_126 / 1.170  # ≈ 13.0 M TEPS (BASELINE.md derived floor)

_CACHE_DIR = os.environ.get(
    "BENCH_CACHE_DIR", os.path.join(_REPO_ROOT, ".bench_cache")
)


def _cached(key: str, unpack, build):
    """Load-or-rebuild an npz cache entry.  ``unpack(npz) -> obj``;
    ``build() -> (obj, dict_of_arrays)``.  Corrupt entries are treated as
    misses; writes are atomic and per-process to survive concurrent runs."""
    path = os.path.join(_CACHE_DIR, key + ".npz")
    if os.path.exists(path):
        try:
            with np.load(path) as z:
                return unpack(z)
        except Exception:
            # Corrupt/stale entry: treat as a miss.  A concurrent process
            # may have removed it first; that's fine.
            try:
                os.remove(path)
            except FileNotFoundError:
                pass
    obj, arrays = build()
    os.makedirs(_CACHE_DIR, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.npz"
    np.savez(tmp, **arrays)
    os.replace(tmp, path)
    return obj


def _generator_backend() -> str:
    try:
        from .graph.native_gen import native_available

        return "native" if native_available() else "numpy"
    except Exception:
        return "numpy"


def load_or_build(scale: int, edge_factor: int, seed: int, block: int, backend: str):
    """Device-ready R-MAT arrays, cached on disk: host-side generation +
    dst-sorting of ~10^8 edges takes minutes in NumPy, so the prepared
    DeviceGraph (and the chosen source) is built once per config.  Uses the
    native generator/sorter (native/graph_gen.cpp) when available."""

    def unpack(z):
        return (
            DeviceGraph(
                num_vertices=int(z["num_vertices"]),
                num_edges=int(z["num_edges"]),
                src=z["src"],
                dst=z["dst"],
            ),
            int(z["source"]),
        )

    def build():
        if backend == "native":
            from .graph.native_gen import rmat_edges_native

            u, v = rmat_edges_native(scale, edge_factor, seed=seed)
            graph = Graph(
                1 << scale, np.concatenate([u, v]), np.concatenate([v, u])
            )  # bi-directed (GraphFileUtil.java:64-65 parity)
        else:
            graph = rmat_graph(scale, edge_factor, seed=seed)
        dg = build_device_graph(graph, block=block)
        # Deterministic source in the giant component: the max-degree vertex.
        degrees = np.bincount(graph.src, minlength=graph.num_vertices)
        source = int(np.argmax(degrees))
        arrays = dict(
            num_vertices=dg.num_vertices,
            num_edges=dg.num_edges,
            src=dg.src,
            dst=dg.dst,
            source=source,
        )
        return (dg, source), arrays

    return _cached(
        f"rmat_{backend}_s{scale}_ef{edge_factor}_seed{seed}_block{block}",
        unpack,
        build,
    )


def load_or_build_pull(dg, key: str):
    """ELL pull layout, cached next to the DeviceGraph cache (the _group_rows
    packing re-walks all E edges in NumPy — minutes at scale 22)."""
    from .graph.ell import DEFAULT_K, PullGraph

    def unpack(z):
        nf = int(z["num_folds"])
        return PullGraph(
            num_vertices=int(z["num_vertices"]),
            num_edges=int(z["num_edges"]),
            ell0=z["ell0"],
            folds=tuple(z[f"fold{i}"] for i in range(nf)),
        )

    def build():
        pg = build_pull_graph(dg)
        arrays = dict(
            num_vertices=pg.num_vertices,
            num_edges=pg.num_edges,
            ell0=pg.ell0,
            num_folds=len(pg.folds),
            **{f"fold{i}": f for i, f in enumerate(pg.folds)},
        )
        return pg, arrays

    return _cached(f"pull_{key}_k{DEFAULT_K}", unpack, build)


def load_or_build_relay(dg, key: str):
    """Relay layout (relabeling + Beneš networks), cached on disk — the
    router walks ~N log N pointers host-side (minutes at scale 22, once).
    Build cost (seconds + routing-mask bytes) is recorded in the cache so
    the bench can report it without rebuilding."""
    from .graph.relay import ClassSlice, RelayGraph, build_relay_graph

    def unpack(z):
        rg = RelayGraph(
            num_vertices=int(z["num_vertices"]),
            num_edges=int(z["num_edges"]),
            new2old=z["new2old"],
            old2new=z["old2new"],
            vperm_masks=z["vperm_masks"],
            vperm_size=int(z["vperm_size"]),
            out_classes=tuple(
                ClassSlice(*row[:5], vertex_major=bool(row[5]))
                for row in z["out_classes"].tolist()
            ),
            net_masks=z["net_masks"],
            net_size=int(z["net_size"]),
            m2=int(z["m2"]),
            in_classes=tuple(
                ClassSlice(*row[:5], vertex_major=bool(row[5]))
                for row in z["in_classes"].tolist()
            ),
            src_l1=z["src_l1"],
        )
        return rg, float(z["build_seconds"]) if "build_seconds" in z else -1.0

    def build():
        t0 = time.perf_counter()
        rg = build_relay_graph(dg)
        build_seconds = time.perf_counter() - t0
        arrays = dict(
            num_vertices=rg.num_vertices,
            num_edges=rg.num_edges,
            new2old=rg.new2old,
            old2new=rg.old2new,
            vperm_masks=rg.vperm_masks,
            vperm_size=rg.vperm_size,
            out_classes=np.array(
                [[c.width, c.va, c.vb, c.sa, c.sb, int(c.vertex_major)]
                 for c in rg.out_classes],
                dtype=np.int64,
            ),
            net_masks=rg.net_masks,
            net_size=rg.net_size,
            m2=rg.m2,
            in_classes=np.array(
                [[c.width, c.va, c.vb, c.sa, c.sb, int(c.vertex_major)]
                 for c in rg.in_classes],
                dtype=np.int64,
            ),
            src_l1=rg.src_l1,
            build_seconds=build_seconds,
        )
        return (rg, build_seconds), arrays

    from .graph.relay import LAYOUT_VERSION

    return _cached(f"relay_v{LAYOUT_VERSION}_{key}", unpack, build)


def _multi_source_bench(rg, eng, dg, source, *, num_sources, chunk, do_check):
    """BASELINE.json config-5: ``num_sources`` independent BFS trees on the
    relay layout, in device-resident chunks — the batched program applies
    the SAME routing masks to every tree in a chunk, so mask traffic (the
    single-source bottleneck) amortizes across the batch.

    The numerator is exact, not extrapolated: sources are drawn from the
    traversed component of a reference run, and level-synchronous BFS from
    any source inside a component reaches exactly that component, so each
    tree traverses the same input edge set (verified on the first chunk,
    which also runs the full ``check()`` invariants per tree)."""
    from .oracle.bfs import check

    # Reference tree (untimed): component mask + per-tree edge numerator.
    ref = eng.run(source)
    reached_mask = ref.dist != np.iinfo(np.int32).max
    esrc, edst = unpad_edges(dg)
    directed_per_tree = int(np.count_nonzero(reached_mask[esrc]))

    rng = np.random.default_rng(987)
    pool = np.flatnonzero(reached_mask)
    sources = rng.choice(pool, size=num_sources, replace=False).astype(np.int32)
    chunks = [sources[i : i + chunk] for i in range(0, num_sources, chunk)]
    if len(chunks[-1]) < chunk:  # keep one compiled chunk shape
        pad = chunk - len(chunks[-1])
        chunks[-1] = np.concatenate([chunks[-1], chunks[-1][:1].repeat(pad)])

    def run_chunk(srcs):
        return eng.run_multi_device(srcs)

    state = run_chunk(chunks[0])
    _ = int(state.level)  # compile + sync (value read; see below)

    t0 = time.perf_counter()
    levels = []
    for c in chunks:
        st = run_chunk(c)
        levels.append(int(st.level))  # per-chunk sync keeps device mem flat
    t = time.perf_counter() - t0

    check_status = "skipped"
    if do_check:
        from .models.bfs import slots_to_parent

        st0 = jax.device_get(run_chunk(chunks[0]))
        dist0 = np.asarray(st0.dist[:, : rg.num_vertices])[:, rg.old2new]
        parent0 = slots_to_parent(
            np.asarray(st0.parent[:, : rg.num_vertices]), rg.src_l1
        )[:, rg.old2new]
        host_graph = Graph(dg.num_vertices, esrc, edst)
        for i, s in enumerate(chunks[0]):
            parent0[i, s] = s
            np.testing.assert_array_equal(
                dist0[i] != np.iinfo(np.int32).max, reached_mask,
                err_msg="tree does not cover the source's component",
            )
            violations = check(host_graph, dist0[i], parent0[i], int(s))
            if violations:
                raise SystemExit(
                    f"BFS invariant violations on tree {i}: {violations[:5]}"
                )
        check_status = "passed (first chunk, all trees)"

    aggregate_teps = (num_sources * directed_per_tree / 2) / t
    print(
        json.dumps(
            {
                "metric": f"rmat{int(np.log2(dg.num_vertices))}_multi{num_sources}_aggregate_teps",
                "value": aggregate_teps,
                "unit": "TEPS",
                "vs_baseline": aggregate_teps / BASELINE_TEPS,
                "details": {
                    "device": str(jax.devices()[0]),
                    "engine": "relay",
                    "num_vertices": dg.num_vertices,
                    "num_directed_edges": dg.num_edges,
                    "num_sources": num_sources,
                    "chunk": len(chunks[0]),
                    "num_chunks": len(chunks),
                    "supersteps_per_chunk": levels,
                    "directed_edges_traversed_per_tree": directed_per_tree,
                    "teps_convention": "graph500 aggregate: sources * input undirected edges in traversed component / total time",
                    "total_seconds": t,
                    "seconds_per_tree": t / num_sources,
                    "check": check_status,
                },
            }
        )
    )


def main():
    scale = int(os.environ.get("BENCH_SCALE", "24"))
    edge_factor = int(os.environ.get("BENCH_EDGE_FACTOR", "6"))
    repeats = int(os.environ.get("BENCH_REPEATS", "5"))
    engine = os.environ.get("BENCH_ENGINE", "relay")
    do_check = os.environ.get("BENCH_CHECK", "1") != "0"
    profile_dir = os.environ.get("BENCH_PROFILE", "")
    num_sources = int(os.environ.get("BENCH_SOURCES", "1"))
    if engine not in ("relay", "pull", "push"):
        raise SystemExit(f"unknown BENCH_ENGINE {engine!r}; use relay/pull/push")
    if num_sources > 1 and engine != "relay":
        raise SystemExit("BENCH_SOURCES > 1 requires BENCH_ENGINE=relay")

    backend = _generator_backend()
    seed, block = 42, 8 * 1024
    graph_key = f"{backend}_s{scale}_ef{edge_factor}_seed{seed}_block{block}"
    dg, source = load_or_build(scale, edge_factor, seed, block, backend)
    layout_detail = {}

    if engine == "relay":
        from .models.bfs import RelayEngine

        rg, build_seconds = load_or_build_relay(dg, graph_key)
        eng = RelayEngine(rg)
        if num_sources > 1:
            chunk = int(os.environ.get("BENCH_MULTI_CHUNK", "8"))
            _multi_source_bench(
                rg, eng, dg, source,
                num_sources=num_sources, chunk=chunk, do_check=do_check,
            )
            return
        source_new = jnp.int32(int(rg.old2new[source]))
        run = lambda: eng._fused(source_new, rg.num_vertices)  # noqa: E731
        layout_detail = {
            "relay_layout_build_seconds": build_seconds,
            "relay_mask_bytes": int(rg.net_masks.nbytes + rg.vperm_masks.nbytes),
            "relay_src_table_bytes": int(rg.src_l1.nbytes),
        }

        def host_result():
            return eng.run(source)

    elif engine == "pull":
        pg = load_or_build_pull(dg, graph_key)
        ell0 = jnp.asarray(pg.ell0)
        folds = tuple(jnp.asarray(f) for f in pg.folds)
        run = lambda: _bfs_pull_fused(  # noqa: E731
            ell0, folds, jnp.int32(source), pg.num_vertices, pg.num_vertices
        )

        def host_result():
            from .models.bfs import BfsResult

            st = jax.device_get(run())
            return BfsResult(
                dist=np.asarray(st.dist[: pg.num_vertices]),
                parent=np.asarray(st.parent[: pg.num_vertices]),
                num_levels=int(st.level),
            )

    else:
        src = jnp.asarray(dg.src)
        dst = jnp.asarray(dg.dst)
        run = lambda: _bfs_fused(  # noqa: E731
            src, dst, jnp.int32(source), dg.num_vertices, dg.num_vertices
        )

        def host_result():
            from .models.bfs import BfsResult

            st = jax.device_get(run())
            return BfsResult(
                dist=np.asarray(st.dist[: dg.num_vertices]),
                parent=np.asarray(st.parent[: dg.num_vertices]),
                num_levels=int(st.level),
            )

    state = run()  # warm-up: compile + first run
    levels = int(state.level)  # forces a real sync (block_until_ready can
    # return early through remote-device tunnels; value reads cannot)

    times = []
    for i in range(repeats):
        if profile_dir and i == repeats - 1:
            with jax.profiler.trace(profile_dir):
                t0 = time.perf_counter()
                _ = int(run().level)
                times.append(time.perf_counter() - t0)
        else:
            t0 = time.perf_counter()
            _ = int(run().level)
            times.append(time.perf_counter() - t0)
    t = float(np.median(times))

    # ---- honest TEPS numerator + invariant verification (host, once) ------
    result = host_result()  # original-id dist/parent
    reached_mask = result.dist != np.iinfo(np.int32).max
    reached = int(reached_mask.sum())
    esrc, edst = unpad_edges(dg)
    # Graph500 numerator: input (undirected) edges inside the traversed
    # component = directed edges with reached source endpoint, / 2.
    directed_traversed = int(np.count_nonzero(reached_mask[esrc]))
    teps = (directed_traversed / 2) / t
    teps_directed_total = dg.num_edges / t  # round-1 convention, for continuity

    check_status = "skipped"
    if do_check:
        from .oracle.bfs import check

        host_graph = Graph(dg.num_vertices, esrc, edst)
        violations = check(host_graph, result.dist, result.parent, source)
        if violations:
            raise SystemExit(
                f"BFS invariant violations on bench result: {violations[:5]}"
            )
        check_status = "passed"

    print(
        json.dumps(
            {
                "metric": f"rmat{scale}_ssbfs_teps",
                "value": teps,
                "unit": "TEPS",
                "vs_baseline": teps / BASELINE_TEPS,
                "details": {
                    "device": str(jax.devices()[0]),
                    "engine": engine,
                    "num_vertices": dg.num_vertices,
                    "num_directed_edges": dg.num_edges,
                    "source": source,
                    "supersteps": levels,
                    "vertices_reached": reached,
                    "teps_convention": "graph500: input undirected edges in traversed component / time",
                    "directed_edges_traversed": directed_traversed,
                    "teps_directed_total": teps_directed_total,
                    "check": check_status,
                    "median_seconds": t,
                    "times": times,
                    **layout_detail,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
