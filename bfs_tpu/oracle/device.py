"""On-device BFS verification: ``check()`` without the 128 MB download.

Host :func:`bfs_tpu.oracle.bfs.check` is the algs4 parity oracle
(BreadthFirstPaths.java:172-221) and stays the ground truth — but running
it per bench root means pulling the full dist+parent arrays through the
axon tunnel (128 MB at s24; minutes in the degraded windows that killed
the round-5 driver capture) and then sweeping 201 M edges on the host.

The three invariants are embarrassingly data-parallel reductions over the
edge set (VERDICT r5 "missing" #2), so this module evaluates them AS ONE
XLA program over device-resident arrays and returns a six-counter verdict
vector — the only thing that crosses the tunnel is 24 bytes:

  counts[0] — sources with ``dist != 0``;
  counts[1] — edges whose source is reached but destination is not;
  counts[2] — edges with ``dist[dst] > dist[src] + 1``;
  counts[3] — reached non-source vertices with no parent;
  counts[4] — reached non-source vertices with ``dist != dist[parent]+1``;
  counts[5] — reached non-source vertices whose ``(parent, w)`` tree edge
              is not a graph edge.

All zero <=> host ``check()`` returns no violations (asserted by
tests/test_device_check.py on tinyCG/randomG, including corrupted-state
cases).  The edge membership test (the host's sorted-key searchsorted)
becomes an edge-side scatter: edge ``(u, w)`` covers ``w`` iff
``parent[w] == u``; a reached non-source vertex left uncovered has a
phantom tree edge.  One scatter per verification is fine — this is the
once-per-root check, not the superstep hot path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..graph.csr import DeviceGraph, Graph, INF_DIST, NO_PARENT

#: Human-readable names for the verdict vector, index-aligned.
COUNT_FIELDS = (
    "source_dist_nonzero",
    "edge_dst_unreached",
    "edge_dist_gap",
    "reached_without_parent",
    "tree_dist_mismatch",
    "tree_edge_missing",
)


@functools.partial(jax.jit, static_argnames=("v",))
def _check_counts(srcv, dstv, dist, parent, sources, v: int):
    """The verdict program: int32[6] violation counts (see module doc).

    ``srcv``/``dstv`` may contain sentinel padding (endpoint == v, inert);
    ``dist``/``parent`` may carry the engines' sentinel slot (sliced off).
    """
    inf = jnp.int32(INF_DIST)
    dist = jax.lax.slice_in_dim(dist, 0, v)
    parent = jax.lax.slice_in_dim(parent, 0, v)
    # One appended slot so clipped sentinel endpoints gather inert values.
    dist_p = jnp.concatenate([dist, jnp.full((1,), inf, jnp.int32)])
    par_p = jnp.concatenate([parent, jnp.full((1,), NO_PARENT, jnp.int32)])
    si = jnp.minimum(srcv, v)
    di = jnp.minimum(dstv, v)
    real = (srcv < v) & (dstv < v)
    ds, dd = dist_p[si], dist_p[di]

    # Invariant 1 (BreadthFirstPaths.java:178-183): sources at distance 0.
    c_src = (dist_p[jnp.minimum(sources, v)] != 0).sum(dtype=jnp.int32)

    # Invariant 2 (:188-201): per directed edge, reachability agrees and
    # the distance gap is at most one relaxation.
    reach_s = real & (ds != inf)
    reach_d = dd != inf
    c_unreached = (reach_s & ~reach_d).sum(dtype=jnp.int32)
    c_gap = (reach_s & reach_d & (dd > ds + 1)).sum(dtype=jnp.int32)

    # Invariant 3 (:205-217): every reached non-source has a parent one
    # level up, connected by a real graph edge.
    srcmask = jnp.zeros(v + 1, bool).at[jnp.minimum(sources, v)].set(True)
    reached = dist != inf
    non_src = reached & ~srcmask[:v]
    c_noparent = (non_src & (parent == NO_PARENT)).sum(dtype=jnp.int32)
    hasp = non_src & (parent != NO_PARENT)
    pc = jnp.clip(parent, 0, v - 1)
    c_treedist = (hasp & (dist != dist[pc] + 1)).sum(dtype=jnp.int32)
    tree_target = jnp.where(real & (par_p[di] == srcv), di, jnp.int32(v))
    covered = jnp.zeros(v + 1, bool).at[tree_target].set(True)
    c_missing = (hasp & ~covered[:v]).sum(dtype=jnp.int32)

    return jnp.stack(
        [c_src, c_unreached, c_gap, c_noparent, c_treedist, c_missing]
    )


@functools.partial(jax.jit, static_argnames=("v",))
def _packed_reached(dist, v: int):
    """uint32[ceil(v/32)] reached-bit words (standard packing) from a
    device dist array — the component signature for coverage comparison."""
    from ..ops.relay import pack_std

    reached = jax.lax.slice_in_dim(dist, 0, v) != jnp.int32(INF_DIST)
    pad = (-v) % 32
    if pad:
        reached = jnp.concatenate([reached, jnp.zeros(pad, bool)])
    return pack_std(reached)


@functools.partial(jax.jit, static_argnames=("v",))
def _coverage_mismatch(dist, ref_words, v: int):
    """Scalar count of vertices whose reached-bit differs from the
    reference component words (one int32 down the tunnel instead of the
    per-root host ``assert_array_equal`` over V bools)."""
    return (
        jax.lax.population_count(_packed_reached(dist, v) ^ ref_words)
        .sum(dtype=jnp.int32)
    )


# ------------------------------------------------------- algo verdicts --
# The semiring algorithms' on-device invariant programs (ISSUE 16): the
# same shape as the BFS verdict — data-parallel reductions over the edge
# set, a handful of bytes down the tunnel.  The HOST oracles
# (oracle/sssp.py, oracle/cc.py) stay the ground truth; these are the
# per-run cheap checks the harness can afford per root.

#: Names for the SSSP verdict vector, index-aligned.
SSSP_COUNT_FIELDS = (
    "source_dist_nonzero",
    "edge_dst_unreached",
    "edge_relaxable",
    "reached_without_parent",
    "tree_edge_not_tight",
)

#: Names for the CC verdict vector, index-aligned.
CC_COUNT_FIELDS = (
    "edge_label_mismatch",
    "label_above_id",
    "root_not_self_labeled",
)


@functools.partial(jax.jit, static_argnames=("v", "max_weight"))
def _sssp_check_counts(srcv, dstv, dist, parent, source, v: int,
                       max_weight: int):
    """int32[5] SSSP violation counts (see :data:`SSSP_COUNT_FIELDS`).

    Weights are recomputed from the endpoint hash
    (:func:`bfs_tpu.algo.substrate.edge_weights`) — the same
    zero-operand-plumbing trick the engines use.  Sentinel-padded edges
    are inert; dist/parent may carry the engines' sentinel slot.
    """
    from ..algo.substrate import edge_weights

    inf = jnp.int32(INF_DIST)
    dist = jax.lax.slice_in_dim(dist, 0, v)
    parent = jax.lax.slice_in_dim(parent, 0, v)
    dist_p = jnp.concatenate([dist, jnp.full((1,), inf, jnp.int32)])
    si = jnp.minimum(srcv, v)
    di = jnp.minimum(dstv, v)
    real = (srcv < v) & (dstv < v)
    wv = edge_weights(srcv, dstv, max_weight)
    ds, dd = dist_p[si], dist_p[di]

    c_src = (dist_p[jnp.minimum(source, v)] != 0).sum(dtype=jnp.int32)

    reach_s = real & (ds != inf)
    reach_d = dd != inf
    c_unreached = (reach_s & ~reach_d).sum(dtype=jnp.int32)
    # A relaxable edge remaining means the fixpoint was not reached.
    c_relaxable = (reach_s & reach_d & (dd > ds + wv)).sum(dtype=jnp.int32)

    reached = dist != inf
    non_src = reached & (jnp.arange(v, dtype=jnp.int32) != source)
    c_noparent = (non_src & ((parent < 0) | (parent >= v))).sum(
        dtype=jnp.int32
    )
    hasp = non_src & (parent >= 0) & (parent < v)
    # Tree-edge tightness via the edge-side scatter: edge (u, w) covers w
    # iff parent[w] == u AND dist[w] == dist[u] + weight(u, w).
    par_p = jnp.concatenate(
        [parent, jnp.full((1,), NO_PARENT, jnp.int32)]
    )
    tight = real & (par_p[di] == srcv) & (dd == ds + wv)
    covered = (
        jnp.zeros(v + 1, bool)
        .at[jnp.where(tight, di, jnp.int32(v))]
        .set(True)
    )
    c_loose = (hasp & ~covered[:v]).sum(dtype=jnp.int32)

    return jnp.stack(
        [c_src, c_unreached, c_relaxable, c_noparent, c_loose]
    )


@functools.partial(jax.jit, static_argnames=("v",))
def _cc_check_counts(srcv, dstv, label, v: int):
    """int32[3] CC violation counts (see :data:`CC_COUNT_FIELDS`)."""
    label = jax.lax.slice_in_dim(label, 0, v)
    label_p = jnp.concatenate([label, jnp.full((1,), -1, jnp.int32)])
    si = jnp.minimum(srcv, v)
    di = jnp.minimum(dstv, v)
    real = (srcv < v) & (dstv < v)
    c_edge = (real & (label_p[si] != label_p[di])).sum(dtype=jnp.int32)
    ids = jnp.arange(v, dtype=jnp.int32)
    c_above = (label > ids).sum(dtype=jnp.int32)
    inrange = (label >= 0) & (label < v)
    roots = label_p[jnp.where(inrange, label, jnp.int32(v))]
    c_root = (inrange & (roots != label)).sum(dtype=jnp.int32)
    return jnp.stack([c_edge, c_above, c_root])


def sssp_device_check(
    src, dst, dist, parent, source, num_vertices: int, max_weight: int
) -> dict[str, int]:
    """Named nonzero SSSP violation counts (empty dict == all invariants
    hold); only the counter vector crosses the tunnel."""
    host = np.asarray(
        jax.device_get(
            _sssp_check_counts(
                jnp.asarray(src).reshape(-1),
                jnp.asarray(dst).reshape(-1),
                jnp.asarray(dist),
                jnp.asarray(parent),
                jnp.int32(source),
                int(num_vertices),
                int(max_weight),
            )
        )
    )
    return {
        name: int(n)
        for name, n in zip(SSSP_COUNT_FIELDS, host.tolist())
        if n
    }


def cc_device_check(src, dst, label, num_vertices: int) -> dict[str, int]:
    """Named nonzero CC violation counts (empty dict == consistent,
    self-rooted, id-dominated labels)."""
    host = np.asarray(
        jax.device_get(
            _cc_check_counts(
                jnp.asarray(src).reshape(-1),
                jnp.asarray(dst).reshape(-1),
                jnp.asarray(label),
                int(num_vertices),
            )
        )
    )
    return {
        name: int(n)
        for name, n in zip(CC_COUNT_FIELDS, host.tolist())
        if n
    }


class DeviceChecker:
    """Device-resident verifier bound to one graph's edge arrays.

    Ships the flat ``(src, dst)`` edge arrays once (or reuses arrays that
    are already on device — the push engine's operands) and then verifies
    any number of results for a handful of bytes each.  States from any
    engine work, as long as dist/parent are in ORIGINAL id space — the
    relay engine's :meth:`~bfs_tpu.models.bfs.RelayEngine.to_original_device`
    produces exactly that without leaving the device.
    """

    def __init__(self, src, dst, num_vertices: int):
        self.num_vertices = int(num_vertices)
        self.src = jnp.asarray(src).reshape(-1)
        self.dst = jnp.asarray(dst).reshape(-1)

    @classmethod
    def from_graph(cls, graph: Graph | DeviceGraph) -> "DeviceChecker":
        """From a host :class:`Graph` or padded :class:`DeviceGraph`
        (sentinel padding edges are inert in the verdict program)."""
        return cls(graph.src, graph.dst, graph.num_vertices)

    @property
    def edge_bytes(self) -> int:
        return int(self.src.size + self.dst.size) * 4

    # ------------------------------------------------------------ verdicts --
    def counts(self, dist, parent, sources) -> jax.Array:
        """DEVICE int32[6] violation counters (see :data:`COUNT_FIELDS`);
        nothing is transferred."""
        sources = jnp.atleast_1d(jnp.asarray(sources, dtype=jnp.int32))
        return _check_counts(
            self.src, self.dst, dist, parent, sources, self.num_vertices
        )

    def check(self, dist, parent, sources) -> dict[str, int]:
        """Named nonzero violation counts (empty dict == all invariants
        hold) — the only host transfer is the 24-byte counter vector."""
        host = np.asarray(jax.device_get(self.counts(dist, parent, sources)))
        return {
            name: int(n) for name, n in zip(COUNT_FIELDS, host.tolist()) if n
        }

    def ok(self, dist, parent, sources) -> bool:
        return not self.check(dist, parent, sources)

    # ------------------------------------------------------------ coverage --
    def packed_reached(self, dist) -> jax.Array:
        """Device reached-bit words for ``dist`` — compute once on a
        reference result, compare against every root via
        :meth:`coverage_mismatch`."""
        return _packed_reached(dist, self.num_vertices)

    def coverage_mismatch(self, dist, ref_words) -> int:
        """Vertices whose reachability differs from ``ref_words``
        (one int32 pull)."""
        return int(
            jax.device_get(
                _coverage_mismatch(dist, ref_words, self.num_vertices)
            )
        )
