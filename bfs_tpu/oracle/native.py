"""ctypes bindings for the native C++ oracle (native/oracle_bfs.cpp).

The reference's serial baseline runs on the JVM (algs4 jar); ours is a small
C++ CSR BFS built on demand with the system compiler and loaded via ctypes
(pybind11 is not in the image).  Falls back cleanly: callers should guard
with :func:`native_available` and use the pure-Python oracle otherwise.
"""

from __future__ import annotations

import ctypes
import os
from collections.abc import Sequence

import numpy as np

from ..graph.csr import Graph
from ..utils.native_loader import NativeLib

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_I32 = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_I64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")


def _register(lib: ctypes.CDLL) -> None:
    lib.bfs_csr.restype = ctypes.c_int32
    lib.bfs_csr.argtypes = [
        ctypes.c_int64, _I64, _I32, ctypes.c_int32, _I32, ctypes.c_int32,
        _I32, _I32,
    ]
    lib.bfs_check.restype = ctypes.c_int32
    lib.bfs_check.argtypes = [
        ctypes.c_int64, _I64, _I32, ctypes.c_int32, _I32, _I32, _I32,
    ]


_LIB = NativeLib(
    src=os.path.join(_REPO_ROOT, "native", "oracle_bfs.cpp"),
    so=os.path.join(_REPO_ROOT, "native", "build", "liboracle_bfs.so"),
    register=_register,
)


def _load() -> ctypes.CDLL | None:
    return _LIB.load()


def native_available() -> bool:
    return _LIB.available()


def native_bfs(
    graph: Graph,
    sources: int | Sequence[int] = 0,
    *,
    policy: str = "queue",
):
    """Run the C++ oracle.  ``policy='queue'`` = algs4 first-discovery
    parents; ``policy='canonical'`` = min-parent (engine-compatible).
    Returns ``(dist, parent, num_levels)``; raises if the native lib is
    unavailable (check :func:`native_available`)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native oracle unavailable (compiler or load failure)")
    indptr, indices = graph.csr()
    srcs = np.atleast_1d(np.asarray(sources, dtype=np.int32))
    dist = np.empty(graph.num_vertices, dtype=np.int32)
    parent = np.empty(graph.num_vertices, dtype=np.int32)
    pol = {"queue": 0, "canonical": 1}[policy]
    indices32 = np.ascontiguousarray(indices, dtype=np.int32)
    levels = lib.bfs_csr(
        graph.num_vertices,
        np.ascontiguousarray(indptr, dtype=np.int64),
        indices32,
        np.int32(srcs.size),
        np.ascontiguousarray(srcs),
        pol,
        dist,
        parent,
    )
    if levels < 0:
        raise ValueError("native oracle rejected input")
    return dist, parent, int(levels)


def native_check(graph: Graph, dist, parent, sources=0) -> int:
    """Invariant bitmask from the native verifier; 0 = OK."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native oracle unavailable")
    indptr, indices = graph.csr()
    srcs = np.atleast_1d(np.asarray(sources, dtype=np.int32))
    return int(
        lib.bfs_check(
            graph.num_vertices,
            np.ascontiguousarray(indptr, dtype=np.int64),
            np.ascontiguousarray(indices, dtype=np.int32),
            np.int32(srcs.size),
            np.ascontiguousarray(srcs),
            np.ascontiguousarray(dist, dtype=np.int32),
            np.ascontiguousarray(parent, dtype=np.int32),
        )
    )
