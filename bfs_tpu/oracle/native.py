"""ctypes bindings for the native C++ oracle (native/oracle_bfs.cpp).

The reference's serial baseline runs on the JVM (algs4 jar); ours is a small
C++ CSR BFS built on demand with the system compiler and loaded via ctypes
(pybind11 is not in the image).  Falls back cleanly: callers should guard
with :func:`native_available` and use the pure-Python oracle otherwise.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from collections.abc import Sequence

import numpy as np

from ..graph.csr import Graph

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "oracle_bfs.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "build", "liboracle_bfs.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def _build() -> bool:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O3", "-march=native", "-std=c++17", "-fPIC", "-shared",
        "-o", _SO, _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def _load() -> ctypes.CDLL | None:
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            if not _build():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _load_failed = True
            return None
        lib.bfs_csr.restype = ctypes.c_int32
        lib.bfs_csr.argtypes = [
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_int32,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_int32,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ]
        lib.bfs_check.restype = ctypes.c_int32
        lib.bfs_check.argtypes = [
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_int32,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def native_bfs(
    graph: Graph,
    sources: int | Sequence[int] = 0,
    *,
    policy: str = "queue",
):
    """Run the C++ oracle.  ``policy='queue'`` = algs4 first-discovery
    parents; ``policy='canonical'`` = min-parent (engine-compatible).
    Returns ``(dist, parent, num_levels)``; raises if the native lib is
    unavailable (check :func:`native_available`)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native oracle unavailable (compiler or load failure)")
    indptr, indices = graph.csr()
    srcs = np.atleast_1d(np.asarray(sources, dtype=np.int32))
    dist = np.empty(graph.num_vertices, dtype=np.int32)
    parent = np.empty(graph.num_vertices, dtype=np.int32)
    pol = {"queue": 0, "canonical": 1}[policy]
    indices32 = np.ascontiguousarray(indices, dtype=np.int32)
    levels = lib.bfs_csr(
        graph.num_vertices,
        np.ascontiguousarray(indptr, dtype=np.int64),
        indices32,
        np.int32(srcs.size),
        np.ascontiguousarray(srcs),
        pol,
        dist,
        parent,
    )
    if levels < 0:
        raise ValueError("native oracle rejected input")
    return dist, parent, int(levels)


def native_check(graph: Graph, dist, parent, sources=0) -> int:
    """Invariant bitmask from the native verifier; 0 = OK."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native oracle unavailable")
    indptr, indices = graph.csr()
    srcs = np.atleast_1d(np.asarray(sources, dtype=np.int32))
    return int(
        lib.bfs_check(
            graph.num_vertices,
            np.ascontiguousarray(indptr, dtype=np.int64),
            np.ascontiguousarray(indices, dtype=np.int32),
            np.int32(srcs.size),
            np.ascontiguousarray(srcs),
            np.ascontiguousarray(dist, dtype=np.int32),
            np.ascontiguousarray(parent, dtype=np.int32),
        )
    )
