from .bfs import queue_bfs, canonical_bfs, check, has_path_to, dist_to, path_to  # noqa: F401
from .device import COUNT_FIELDS, DeviceChecker  # noqa: F401
from .native import native_bfs, native_available  # noqa: F401
