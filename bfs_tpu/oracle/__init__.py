from .bfs import queue_bfs, canonical_bfs, check, has_path_to, dist_to, path_to  # noqa: F401
from .cc import check_cc, union_find_labels  # noqa: F401
from .device import (  # noqa: F401
    CC_COUNT_FIELDS,
    COUNT_FIELDS,
    SSSP_COUNT_FIELDS,
    DeviceChecker,
    cc_device_check,
    sssp_device_check,
)
from .native import native_bfs, native_available  # noqa: F401
from .sssp import check_sssp, dijkstra  # noqa: F401
