"""Sequential BFS oracle: the correctness anchor for the TPU engine.

Re-implements (from behavior, not code) the vendored algs4 oracle used by the
reference's ``SequentialTest``:

  * :func:`queue_bfs` — classic FIFO queue BFS with ``dist/parent/marked``,
    single- and multi-source, mirroring ``BreadthFirstPaths.bfs``
    (sequential-libs/algs4.jar!/BreadthFirstPaths.java:93-111 single-source,
    :114-132 multi-source).
  * :func:`canonical_bfs` — level-synchronous BFS whose parent choice is the
    canonical *minimum* frontier neighbour.  The reference's parallel reducer
    tie-break is order-dependent (BfsSpark.java:97, paper Table 2: "0,5,3 or
    0,2,3 depending on the order"); both this oracle and the TPU engine use
    min-parent so distances AND parents are bit-exact across engines
    (SURVEY.md §5 race-detection row).
  * :func:`check` — port of the ``check()`` optimality verifier
    (BreadthFirstPaths.java:172-221), exposed as a reusable invariant
    function instead of a JVM ``assert``.
  * Query API :func:`has_path_to` / :func:`dist_to` / ``path_to``
    (BreadthFirstPaths.java:139-168).
"""

from __future__ import annotations

from collections import deque
from collections.abc import Sequence

import numpy as np

from ..graph.csr import Graph, INF_DIST, NO_PARENT
from ..graph.vertex import path_to  # re-exported query API

__all__ = [
    "queue_bfs",
    "canonical_bfs",
    "check",
    "has_path_to",
    "dist_to",
    "path_to",
]


def _sources_array(sources: int | Sequence[int], num_vertices: int) -> np.ndarray:
    arr = np.atleast_1d(np.asarray(sources, dtype=np.int64))
    if arr.size == 0:
        raise ValueError("at least one source required")
    if arr.min() < 0 or arr.max() >= num_vertices:
        raise ValueError("source vertex out of range")
    return arr


def queue_bfs(graph: Graph, sources: int | Sequence[int] = 0):
    """FIFO-queue BFS.  Returns ``(dist int32[V], parent int32[V])``.

    Parent is first-discovery order (enqueue order), exactly like algs4's
    ``edgeTo`` (BreadthFirstPaths.java:93-111); with our sorted-adjacency CSR
    this is deterministic.  Sources have ``parent == themselves``; unreached
    vertices have ``dist == INF_DIST`` and ``parent == NO_PARENT``.
    """
    v = graph.num_vertices
    srcs = _sources_array(sources, v)
    indptr, indices = graph.csr()
    dist = np.full(v, INF_DIST, dtype=np.int32)
    parent = np.full(v, NO_PARENT, dtype=np.int32)
    q = deque()
    for s in srcs:  # multi-source seeds the queue with all sources at dist 0
        if dist[s] != 0:
            dist[s] = 0
            parent[s] = s
            q.append(int(s))
    while q:
        u = q.popleft()
        for w in indices[indptr[u] : indptr[u + 1]]:
            w = int(w)
            if parent[w] == NO_PARENT:
                parent[w] = u
                dist[w] = dist[u] + 1
                q.append(w)
    return dist, parent


def canonical_bfs(graph: Graph, sources: int | Sequence[int] = 0):
    """Level-synchronous BFS with canonical min-parent tie-break.

    Per level, every next-frontier vertex's parent is the MINIMUM id among its
    current-frontier neighbours — the same deterministic rule the TPU engine's
    ``segment_min`` implements, so outputs are comparable bit-for-bit.
    Distances agree with :func:`queue_bfs` always; only parents may differ.
    """
    v = graph.num_vertices
    srcs = _sources_array(sources, v)
    dist = np.full(v, INF_DIST, dtype=np.int32)
    parent = np.full(v, NO_PARENT, dtype=np.int32)
    dist[srcs] = 0
    parent[srcs] = srcs
    src_arr, dst_arr = graph.src, graph.dst
    frontier = np.zeros(v, dtype=bool)
    frontier[srcs] = True
    level = np.int32(0)
    while frontier.any():
        active = frontier[src_arr]
        cand_parent = np.full(v, INF_DIST, dtype=np.int32)
        np.minimum.at(cand_parent, dst_arr[active], src_arr[active])
        improved = (cand_parent != INF_DIST) & (dist == INF_DIST)
        dist[improved] = level + 1
        parent[improved] = cand_parent[improved]
        frontier = improved
        level += 1
    return dist, parent


def has_path_to(dist: np.ndarray, v: int) -> bool:
    """BreadthFirstPaths.java:139-141 parity."""
    return bool(np.asarray(dist)[v] != INF_DIST)


def dist_to(dist: np.ndarray, v: int) -> int:
    """BreadthFirstPaths.java:149-151 parity."""
    return int(np.asarray(dist)[v])


def check(
    graph: Graph,
    dist: np.ndarray,
    parent: np.ndarray,
    sources: int | Sequence[int] = 0,
) -> list[str]:
    """BFS optimality verifier; returns a list of violations (empty = OK).

    Port of ``BreadthFirstPaths.check`` (BreadthFirstPaths.java:172-221):
      1. every source has distance 0;
      2. for every edge v-w: reachability agrees and |dist difference| <= 1
         (checked one-directionally per directed edge: dist[w] <= dist[v]+1);
      3. for every reached non-source w: dist[w] == dist[parent[w]] + 1 and
         the tree edge (parent[w], w) exists in the graph.
    Vectorised over edges instead of the oracle's per-edge loop.
    """
    dist = np.asarray(dist)[: graph.num_vertices].astype(np.int64)
    parent = np.asarray(parent)[: graph.num_vertices].astype(np.int64)
    srcs = _sources_array(sources, graph.num_vertices)
    violations: list[str] = []

    bad_src = srcs[dist[srcs] != 0]
    for s in bad_src:
        violations.append(f"distance of source {s} to itself = {dist[s]}, not 0")

    sv, dv = graph.src.astype(np.int64), graph.dst.astype(np.int64)
    reach_s, reach_d = dist[sv] != INF_DIST, dist[dv] != INF_DIST
    # Directional: a reachable source endpoint forces a reachable destination
    # (one relaxation away).  Checking per directed edge keeps this correct
    # for directed graphs too; on bi-directed inputs it is equivalent to the
    # oracle's undirected mismatch test.
    mismatch = reach_s & ~reach_d
    for i in np.flatnonzero(mismatch)[:5]:
        violations.append(
            f"edge {sv[i]}->{dv[i]}: source reachable but destination is not"
        )
    both = reach_s & reach_d
    tri = both & (dist[dv] > dist[sv] + 1)
    for i in np.flatnonzero(tri)[:5]:
        violations.append(
            f"edge {sv[i]}-{dv[i]}: dist[{dv[i]}]={dist[dv[i]]} > dist[{sv[i]}]+1={dist[sv[i]] + 1}"
        )

    reached = np.flatnonzero(dist != INF_DIST)
    non_src = reached[~np.isin(reached, srcs)]
    p = parent[non_src]
    if (p == NO_PARENT).any():
        for w in non_src[p == NO_PARENT][:5]:
            violations.append(f"reached vertex {w} has no parent")
        non_src = non_src[p != NO_PARENT]
        p = parent[non_src]
    bad_tree = dist[non_src] != dist[p] + 1
    for idx in np.flatnonzero(bad_tree)[:5]:
        w = non_src[idx]
        violations.append(
            f"tree edge {parent[w]}->{w}: dist[{w}]={dist[w]} != dist[{parent[w]}]+1"
        )
    # Tree edges must exist in the graph.  Membership via one sort +
    # searchsorted over packed (src, dst) keys — O(E log E) and a few
    # int64[E] arrays, instead of a Python set of all E edges (which at
    # bench scale would need tens of GB of host memory and could never run
    # on the benchmark outputs it exists to verify).  The sorted keys depend
    # only on the graph, so they are computed once and cached on it — the
    # 201M-element sort dominated every per-root verification at bench
    # scale (8-root sweeps, bench.py).
    v64 = np.int64(graph.num_vertices)
    edge_keys = getattr(graph, "_check_edge_keys", None)
    if edge_keys is None or edge_keys.shape[0] != sv.shape[0]:
        edge_keys = np.sort(sv * v64 + dv)
        try:
            graph._check_edge_keys = edge_keys
        except AttributeError:  # frozen/slotted graph object: skip caching
            pass
    tree_keys = p * v64 + non_src
    if edge_keys.shape[0]:
        pos = np.minimum(np.searchsorted(edge_keys, tree_keys), edge_keys.shape[0] - 1)
        missing = edge_keys[pos] != tree_keys
    else:  # edgeless graph: every claimed tree edge is missing
        missing = np.ones(tree_keys.shape[0], dtype=bool)
    for idx in np.flatnonzero(missing)[:5]:
        w = non_src[idx]
        violations.append(f"tree edge {parent[w]}->{w} is not a graph edge")
    return violations
