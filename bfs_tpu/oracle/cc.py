"""Sequential connected-components oracle: union-find with min-id labels.

The correctness anchor for :mod:`bfs_tpu.algo.cc`: a weighted-union +
path-compression DSU over the edge list, with each component labeled by its
MINIMUM vertex id — the same canonical representative the device's
label-min fixpoint converges to, so labels are comparable bit-for-bit.
"""

from __future__ import annotations

import numpy as np

from ..graph.csr import Graph

__all__ = ["union_find_labels", "check_cc"]


def union_find_labels(graph: Graph) -> np.ndarray:
    """int32[V] component labels: ``label[v]`` is the minimum vertex id
    of v's component (edges treated as undirected unions)."""
    v = graph.num_vertices
    parent = np.arange(v, dtype=np.int64)

    def find(x: int) -> int:
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:  # path compression
            parent[x], x = root, parent[x]
        return root

    for u, w in zip(graph.src.tolist(), graph.dst.tolist()):
        ru, rw = find(u), find(w)
        if ru != rw:
            # Union by smaller root id: the root IS the min candidate.
            if ru < rw:
                parent[rw] = ru
            else:
                parent[ru] = rw
    # Final flatten; with union-by-min-id the root is the component min.
    label = np.empty(v, dtype=np.int32)
    for x in range(v):
        label[x] = find(x)
    return label


def check_cc(graph: Graph, label: np.ndarray) -> list[str]:
    """CC label verifier; returns violations (empty = OK).

      1. every edge's endpoints share a label (consistency);
      2. ``label[v] <= v`` (a representative never exceeds its member);
      3. the representative labels itself (``label[label[v]] ==
         label[v]``) — with 1 and 2 this pins min-id canonical labels
         up to cross-component mixups, which the union-find equality
         test in the test suite rules out.
    """
    v = graph.num_vertices
    label = np.asarray(label)[:v].astype(np.int64)
    violations: list[str] = []
    sv, dv = graph.src.astype(np.int64), graph.dst.astype(np.int64)
    mismatch = label[sv] != label[dv]
    for i in np.flatnonzero(mismatch)[:5]:
        violations.append(
            f"edge {sv[i]}-{dv[i]}: labels {label[sv[i]]} != {label[dv[i]]}"
        )
    above = np.flatnonzero(label > np.arange(v))
    for w in above[:5]:
        violations.append(f"vertex {w}: label {label[w]} exceeds its id")
    bad = (label < 0) | (label >= v)
    for w in np.flatnonzero(bad)[:5]:
        violations.append(f"vertex {w}: label {label[w]} out of range")
    ok = ~bad
    roots = label[np.where(ok, label, 0)]
    notself = ok & (roots != label)
    for w in np.flatnonzero(notself)[:5]:
        violations.append(
            f"vertex {w}: representative {label[w]} carries label "
            f"{roots[w]}, not itself"
        )
    return violations
