"""Sequential SSSP oracle: binary-heap Dijkstra with the canonical
min-parent tie-break.

The correctness anchor for :mod:`bfs_tpu.algo.sssp`, playing the role
algs4's ``BreadthFirstPaths`` plays for BFS: a textbook host
implementation against which the device engines must be EXACT, plus a
:func:`check_sssp` invariant verifier usable on any claimed result.

Parents use the identical canonicalization rule as the device: after the
distances are final, ``parent[v] = min u`` over in-edges with
``dist[u] + w(u, v) == dist[v]`` — computed as a vectorized post-pass
(``np.minimum.at``), NOT as heap pop order, so parents are bit-exact
across the host oracle and every device arm regardless of relaxation
schedule.

Weights are an explicit per-directed-edge array, aligned with
``graph.src``/``graph.dst`` — pass
:func:`bfs_tpu.algo.substrate.edge_weights_np` output for parity with the
device's hash weights.
"""

from __future__ import annotations

import heapq

import numpy as np

from ..graph.csr import Graph, INF_DIST, NO_PARENT

__all__ = ["dijkstra", "check_sssp"]


def dijkstra(graph: Graph, weights: np.ndarray, source: int = 0):
    """Single-source shortest paths.  Returns ``(dist int32[V],
    parent int32[V])``: INF_DIST / NO_PARENT for unreached vertices,
    ``parent[source] == source``, canonical min-parent tie-break.

    ``weights`` must be positive int per directed edge, aligned with
    ``graph.src`` / ``graph.dst``.
    """
    v = graph.num_vertices
    if not (0 <= source < v):
        raise ValueError("source vertex out of range")
    weights = np.asarray(weights)
    if weights.shape != graph.src.shape:
        raise ValueError("weights must align with graph.src/graph.dst")
    if graph.num_edges and int(weights.min(initial=1)) < 1:
        raise ValueError("weights must be >= 1")
    # CSR over (dst, weight) per source vertex.
    order = np.argsort(graph.src, kind="stable")
    s_sorted = graph.src[order]
    d_sorted = graph.dst[order]
    w_sorted = weights[order].astype(np.int64)
    indptr = np.zeros(v + 1, dtype=np.int64)
    np.add.at(indptr, s_sorted + 1, 1)
    indptr = np.cumsum(indptr)

    dist = np.full(v, np.iinfo(np.int64).max, dtype=np.int64)
    done = np.zeros(v, dtype=bool)
    dist[source] = 0
    heap = [(0, source)]
    while heap:
        du, u = heapq.heappop(heap)
        if done[u] or du != dist[u]:
            continue
        done[u] = True
        for i in range(indptr[u], indptr[u + 1]):
            nd = du + w_sorted[i]
            t = d_sorted[i]
            if nd < dist[t]:
                dist[t] = nd
                heapq.heappush(heap, (int(nd), int(t)))

    reached = dist != np.iinfo(np.int64).max
    if reached.any() and int(dist[reached].max()) >= INF_DIST:
        raise OverflowError("shortest distance exceeds int32 range")
    out = np.full(v, INF_DIST, dtype=np.int32)
    out[reached] = dist[reached].astype(np.int32)

    # Canonical parents: the same exit-time rule as the device
    # (algo/sssp.py::_sssp_parents) — min u among optimal predecessors.
    parent = np.full(v, INF_DIST, dtype=np.int64)
    sv, dv = graph.src.astype(np.int64), graph.dst.astype(np.int64)
    ok = (dist[sv] != np.iinfo(np.int64).max) & (
        dist[sv] + weights.astype(np.int64) == dist[dv]
    )
    np.minimum.at(parent, dv[ok], sv[ok])
    parent = np.where(reached & (parent != INF_DIST), parent, NO_PARENT)
    parent = parent.astype(np.int32)
    parent[source] = source
    return out, parent


def check_sssp(
    graph: Graph,
    weights: np.ndarray,
    dist: np.ndarray,
    parent: np.ndarray,
    source: int = 0,
) -> list[str]:
    """SSSP optimality verifier; returns violations (empty = OK).

    The min-plus analog of the BFS ``check()``:
      1. the source has distance 0;
      2. per directed edge (u, v): if u is reached, v is reached and
         ``dist[v] <= dist[u] + w`` (no relaxable edge remains);
      3. every reached non-source v has a parent with
         ``dist[v] == dist[parent] + w(parent, v)`` on a real edge, and
         that parent is the canonical MINIMUM optimal predecessor.
    """
    v = graph.num_vertices
    dist = np.asarray(dist)[:v].astype(np.int64)
    parent = np.asarray(parent)[:v].astype(np.int64)
    weights = np.asarray(weights).astype(np.int64)
    violations: list[str] = []

    if dist[source] != 0:
        violations.append(
            f"distance of source {source} to itself = {dist[source]}, not 0"
        )

    sv, dv = graph.src.astype(np.int64), graph.dst.astype(np.int64)
    reach_s, reach_d = dist[sv] != INF_DIST, dist[dv] != INF_DIST
    for i in np.flatnonzero(reach_s & ~reach_d)[:5]:
        violations.append(
            f"edge {sv[i]}->{dv[i]}: source reachable but destination is not"
        )
    slack = reach_s & reach_d & (dist[dv] > dist[sv] + weights)
    for i in np.flatnonzero(slack)[:5]:
        violations.append(
            f"edge {sv[i]}->{dv[i]}: dist[{dv[i]}]={dist[dv[i]]} > "
            f"dist[{sv[i]}]+w={dist[sv[i]] + weights[i]}"
        )

    reached = np.flatnonzero(dist != INF_DIST)
    non_src = reached[reached != source]
    p = parent[non_src]
    bad = non_src[(p < 0) | (p >= v)]
    for w_ in bad[:5]:
        violations.append(f"reached vertex {w_} has no valid parent")
    good = non_src[(p >= 0) & (p < v)]
    # Canonical parent: recompute min optimal predecessor per vertex.
    canon = np.full(v, INF_DIST, dtype=np.int64)
    ok = (dist[sv] != INF_DIST) & (dist[sv] + weights == dist[dv])
    np.minimum.at(canon, dv[ok], sv[ok])
    mismatch = good[parent[good] != canon[good]]
    for w_ in mismatch[:5]:
        violations.append(
            f"vertex {w_}: parent {parent[w_]} is not the canonical "
            f"min optimal predecessor {canon[w_]}"
        )
    return violations
