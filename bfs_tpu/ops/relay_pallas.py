"""Pallas TPU kernels for the v4 relay superstep's Beneš networks.

The XLA path runs one kernel per stage — an HBM round-trip of the word
array plus ~0.4 ms launch overhead each (measured; 55 stages at net 2^28).
Here the stages factor into at most three fused passes per network with the
word array VMEM-resident and only the per-stage masks DMA-streamed (the
masks are the irreducible traffic):

viewing the standard-packed words as [R, 128] and a stage's element
distance d as

  * an intra-word bit distance d          (d < 32, elementwise)
  * a lane distance d/32                  (32 <= d < 4096)
  * a row distance d/4096                 (4096 <= d < TR*4096)
  * an outer-block distance d/4096/TR     (above)

pass B fuses the consecutive middle run (d < TR*4096) on [TR, 128] tiles;
passes A/C fuse the outer prefix/suffix on [B, tt, 128] blocks.  v4
additionally (a) streams PAIR-COMPACTED masks for d >= 4096 — half the
words are structurally zero (graph/relay.py) — and (b) skips DMA + compute
for pass-B tiles outside a stage's static nonzero range (the
identity-wired tail).  Outer-stage masks are re-chunked host-side
(:func:`prepare_pass_masks`) so every DMA is one contiguous row slice.
"""

from __future__ import annotations

import logging
import os

import jax
import jax.numpy as jnp
import numpy as np

from .. import knobs
from ..graph.relay import StageSpec

logger = logging.getLogger(__name__)

LANES = 128
#: pass-B tile rows: 2048 rows * 128 lanes * 4 B = 1 MB of VMEM for x.
#: Env-tunable for on-chip sweeps (tools/profile_net_kernel.py).
TILE_ROWS = knobs.get("BFS_TPU_TILE_ROWS")
#: outer-pass inner-chunk rows; the x block is (B, OUTER_TT, 128).
OUTER_TT = knobs.get("BFS_TPU_OUTER_TT")
#: mask-DMA pipeline depth (buffers per pass).  2 = classic double
#: buffering: stage si+1's DMA is issued when stage si starts computing.
#: The per-stage mask DMA is ~0.5-1 MB, whose issue+semaphore latency
#: exceeds its transfer time, so at depth 2 the pipeline is
#: issue-latency-bound; deeper prefetch (4) keeps more copies in flight.
#: Only relevant on the per-stage path (BFS_TPU_TM=0).
DMA_DEPTH = max(2, knobs.get("BFS_TPU_DMA_DEPTH"))

#: Tile-major pass-B mask streaming: the local pass's masks are relaid
#: host-side so ALL ~45 stages' rows for one x-tile are contiguous, and the
#: kernel fetches them in ONE ~36 MB DMA per tile (double-buffered across
#: grid steps) instead of ~45 per-stage ~0.5-1 MB copies.  Measured on the
#: bench chip (interleaved same-process A/B at s24): marginally faster
#: than the per-stage path in mixed windows (46-54 vs 48-62 ms/apply) and
#: equal in the chip's write-collapsed windows, where both are bound by
#: the pass outputs' HBM writes, not the mask reads — amortized probes
#: showed read streaming at 163-449 GB/s at EVERY DMA size while
#: read+write paths collapsed to ~1 GB/s (docs/ARCHITECTURE.md §8).  Kept
#: as default for the structural simplicity (no DMA-depth tuning).
#: Incompatible with BFS_TPU_LANE_COMPACT (which keeps the per-stage
#: path).
TILE_MAJOR = knobs.get("BFS_TPU_TM")


def _tile_major_enabled() -> bool:
    return TILE_MAJOR and not knobs.get("BFS_TPU_LANE_COMPACT")

_warned = False

#: Tail-range DMA/compute guards (static per stage, dynamic per tile).  At
#: m1 ~ 0.94n the skippable ranges are tiny while the conditional DMAs can
#: cost pipeline overlap — BFS_TPU_GUARDS=0 disables them for measurement.
_GUARDS = knobs.get("BFS_TPU_GUARDS")


def pallas_enabled() -> bool:
    """Use the Pallas path only on real TPU backends (the CPU test platform
    runs the pure-XLA stages).  BFS_TPU_PALLAS=0/1 overrides.  Accepts either
    backend name or device platform 'tpu' (the axon tunnel can report the
    platform differently — ADVICE.md round 2), and logs once when the fused
    path is disabled so a silent fallback is visible."""
    global _warned
    env = knobs.get("BFS_TPU_PALLAS")
    if env in ("0", "1"):
        return env == "1"
    try:
        ok = jax.default_backend() == "tpu" or any(
            d.platform == "tpu" for d in jax.devices()
        )
    except Exception:  # pragma: no cover - backend init failure
        ok = False
    if not ok and not _warned:
        _warned = True
        logger.info(
            "relay fused Pallas path disabled (backend=%s); per-stage XLA",
            jax.default_backend(),
        )
    return ok


def pallas_net_ok(n: int) -> bool:
    """The fused passes need at least a [128, 128]-word view."""
    return n // 32 // LANES >= 128


def split_passes(table: tuple[StageSpec, ...], n: int, tile_rows: int = TILE_ROWS):
    """(prefix outer stages, local run, suffix outer stages, tr)."""
    r = n // 32 // LANES
    tr = min(tile_rows, max(r, 1))
    # Env overrides (BFS_TPU_TILE_ROWS / BFS_TPU_OUTER_TT) must keep the
    # grid exact: a tr that does not divide r makes ``grid = r // tr`` drop
    # the tail rows and both local paths then silently produce a wrong
    # permutation (ADVICE r4).  Fail loudly instead.
    if r > 1:
        if tr <= 0 or r % tr:
            raise ValueError(
                f"tile_rows={tr} does not divide the {r}-row network view; "
                "pick a power-of-two BFS_TPU_TILE_ROWS that divides it"
            )
        tt = min(OUTER_TT, tr)
        if tt <= 0 or tr % tt:
            raise ValueError(
                f"BFS_TPU_OUTER_TT={OUTER_TT} does not divide tile_rows={tr}"
            )
    local = [i for i, st in enumerate(table) if st.d < tr * 4096]
    assert local, "no local stages — network too small for the fused path"
    lo, hi = local[0], local[-1] + 1
    assert local == list(range(lo, hi)), "local stages must be consecutive"
    return list(range(lo)), list(range(lo, hi)), list(range(hi, len(table))), tr


#: Lane-distance stages (32 <= d < 4096) store mask bits only at the lower
#: lane of each pair — exactly 50% structurally-zero words in the flat
#: stream (tools/mask_sparsity.py).  For word distances dw >= this bound
#: the prepared pass-B operand drops the zero lanes ([r, 64] blocks, a
#: separate side array) and the kernel re-expands with <= 2 conditional
#: lane rolls; smaller dw would need too many relayout pieces.  Saves
#: ~100 MB of the s24 net mask stream per superstep.
LANE_COMPACT_MIN_DW = 16


def _lane_compactable(st: StageSpec) -> bool:
    """Default OFF by measurement (round 4, interleaved same-process A/B at
    ~200 GB/s DMA): the in-kernel expansion relayouts (sublane repeat +
    conditional lane rolls) cost ~1 ms MORE per net apply than the ~100 MB
    of zero-lane DMA they save (~7.3 vs ~6.5 ms).  The trade flips in
    DMA-starved windows (3-27 GB/s was typical in round 3, where 100 MB is
    4-30 ms) — hence BFS_TPU_LANE_COMPACT=1 as an opt-in switch rather
    than dead code."""
    if not knobs.get("BFS_TPU_LANE_COMPACT"):
        return False
    return (
        32 <= st.d < 4096
        and not st.compact
        and (st.d >> 5) >= LANE_COMPACT_MIN_DW
    )


def _is_lane_compact(st: StageSpec) -> bool:
    """Pass-local spec marker: lane-compacted stages are flagged compact
    with d < 4096 (the stored table never pair-compacts below 4096)."""
    return bool(st.compact) and st.d < 4096


def _stage_rows(st: StageSpec, tr: int) -> int:
    """Storage rows a local-pass stage spans within one x-tile of ``tr``
    rows: pair-compacted (and lane-compacted) stages store half.  THE
    single definition — the host relayout (pass_static /
    prepare_pass_masks) and the kernels' buffer offsets must agree on it
    exactly."""
    return (tr // 2) if st.compact else tr


def pass_static(
    table: tuple[StageSpec, ...], n: int,
    tile_rows: int = TILE_ROWS, outer_tt: int = OUTER_TT,
):
    """Static (hashable) per-pass info: ``((mode, tr, tt, specs), ...)`` in
    execution order, with outer-stage specs rewritten to their local offsets
    in the rearranged arrays.  Must mirror :func:`prepare_pass_masks`.
    Lane-compactable local stages are flagged (compact=True, d < 4096) with
    offsets into the side lane64 array."""
    pre, local, suf, tr = split_passes(table, n, tile_rows)
    tt = min(outer_tt, tr)
    out = []

    def outer(idx):
        specs = []
        off = 0
        for i in idx:
            st = table[i]
            specs.append(st._replace(offset=off, nwords=st.nwords,
                                     lo=0, hi=st.nwords))
            off += st.nwords
        return ("outer", tr, tt, tuple(specs))

    if pre:
        out.append(outer(pre))
    if _tile_major_enabled():
        # Tile-major local pass: specs' offsets become WORD offsets within
        # one tile's concatenated mask block (all stages' rows for that
        # tile contiguous — ONE DMA per tile).  lo/hi keep their
        # within-stage word semantics for the compute guards.
        row_off = 0
        tm_specs = []
        for i in local:
            st = table[i]
            tm_specs.append(st._replace(offset=row_off * LANES))
            row_off += _stage_rows(st, tr)
        out.append(("local_tm", tr, tt, tuple(tm_specs)))
    else:
        lane_off = 0
        local_specs = []
        for i in local:
            st = table[i]
            if _lane_compactable(st):
                half = st.nwords // 2
                local_specs.append(
                    st._replace(compact=True, offset=lane_off, nwords=half,
                                lo=0, hi=half)
                )
                lane_off += half
            else:
                local_specs.append(st)
        out.append(("local", tr, tt, tuple(local_specs)))
    if suf:
        out.append(outer(suf))
    return tuple(out)


def prepare_pass_masks(
    masks_flat: np.ndarray, table: tuple[StageSpec, ...], n: int,
    tile_rows: int = TILE_ROWS, outer_tt: int = OUTER_TT,
):
    """Host-side, once per layout: per-pass mask arrays + local stage specs.

    Pass B reuses the stored layout as-is (stage tiles are already
    contiguous row slices).  Outer passes get rearranged copies: a stage
    stored (span, tr, LANES) becomes chunk-major (tr/tt, span, tt, LANES) so
    each grid step's mask block is ONE contiguous DMA.
    Returns ``[(mode, tr, tt, specs, array2d), ...]`` in execution order.
    """
    pre, local, suf, tr = split_passes(table, n, tile_rows)
    r = n // 32 // LANES
    b = r // tr
    tt = min(outer_tt, tr)
    arrays = []

    def outer_arr(idx):
        parts = []
        for i in idx:
            st = table[i]
            assert st.compact, "outer stages are always pair-compacted"
            span = b // 2
            w = masks_flat[st.offset : st.offset + st.nwords]
            parts.append(
                w.reshape(span, tr // tt, tt, LANES)
                .swapaxes(0, 1)
                .reshape(-1, LANES)
            )
        return (
            np.concatenate(parts)
            if parts
            else np.zeros((0, LANES), np.uint32)
        )

    if pre:
        arrays.append(outer_arr(pre))
    if _tile_major_enabled():
        # Tile-major local array: for each x-tile, all local stages' row
        # slices concatenated (mirrors pass_static's "local_tm" offsets).
        m2d = masks_flat.reshape(-1, LANES)
        ntiles = max(r // tr, 1)
        tile_parts = []
        for pid in range(ntiles):
            for i in local:
                st = table[i]
                rows = _stage_rows(st, tr)
                base = st.offset // LANES + pid * rows
                tile_parts.append(m2d[base : base + rows])
        arrays.append(
            np.ascontiguousarray(np.concatenate(tile_parts))
            if tile_parts
            else np.zeros((0, LANES), np.uint32)
        )
    else:
        arrays.append(masks_flat.reshape(-1, LANES))
        # Side array for lane-compacted local stages: even-group lanes only
        # ([r, 64] per stage, concatenated).  Appended directly after the
        # local array; apply_benes_fused consumes both for the local pass.
        lane_parts = []
        for i in local:
            st = table[i]
            if _lane_compactable(st):
                dw = st.d >> 5
                w = masks_flat[st.offset : st.offset + st.nwords].reshape(
                    -1, LANES
                )
                lanes = np.arange(LANES)
                lane_parts.append(
                    np.ascontiguousarray(w[:, (lanes & dw) == 0]).reshape(-1)
                )
        if lane_parts:
            # [-1, 128] storage (HBM DMA slices must be 128-lane aligned):
            # storage row q packs x-rows 2q | 2q+1's compacted 64 lanes.
            arrays.append(np.concatenate(lane_parts).reshape(-1, LANES))
    if suf:
        arrays.append(outer_arr(suf))
    return arrays


def _kroll(x, shift: int, axis: int, interpret: bool):
    """In-kernel roll by a STATIC shift.  pltpu.roll in compiled mode —
    jnp.roll's closed_call lowering hits an MLIR cache bug when several
    Pallas kernels in one program contain same-shaped rolls."""
    size = x.shape[axis]
    if interpret:
        return jnp.roll(x, shift % size, axis)
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.roll(x, shift % size, axis)


def _stage_local(x, m, st: StageSpec, interpret: bool):
    """One butterfly stage on a pass-B tile x: (tr, LANES)."""
    d = st.d
    if d < 32:
        sh = jnp.uint32(d)
        t = (x ^ (x >> sh)) & m
        return x ^ t ^ (t << sh)
    dw = d >> 5
    if dw < LANES:  # lane butterfly; mask bits live at lower pair lanes
        idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        has = (idx & dw) != 0
        if _is_lane_compact(st):
            # m: (tr/2, 128) — storage row q packs x-rows 2q|2q+1's
            # compacted (even-group-lane) masks in its two 64-lane halves.
            # Reconstruct mv (tr, 128) whose EVEN-GROUP lanes hold the
            # stage's mask (odd-group lanes end up garbage, which is fine:
            # m_both only reads even-group lanes of mv — directly at even
            # lanes, rolled by dw at odd ones):
            #   1. sublane-double so each x-row sees its storage row,
            #   2. odd x-rows take the upper 64-lane half,
            #   3. duplicate the low half across the lane dim,
            #   4. shift each 2s-lane block into place (largest shift
            #      first — the selects test the DESTINATION lane, so
            #      composition goes coarse to fine).
            mcr = jnp.repeat(m, 2, axis=0)
            row = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
            a = jnp.where(
                (row & 1) != 0, _kroll(mcr, -64, 1, interpret), mcr
            )
            mv = jnp.where(idx >= 64, _kroll(a, 64, 1, interpret), a)
            s = 32
            while s >= dw:
                mv = jnp.where(
                    (idx & (2 * s)) != 0, _kroll(mv, s, 1, interpret), mv
                )
                s //= 2
        else:
            mv = m
        partner = jnp.where(
            has, _kroll(x, dw, 1, interpret), _kroll(x, -dw, 1, interpret)
        )
        m_both = jnp.where(has, _kroll(mv, dw, 1, interpret), mv)
        return x ^ ((x ^ partner) & m_both)
    rw = dw // LANES  # row butterfly; compact mask (tr/2 rows)
    a = x.shape[0] // (2 * rw)
    xr = x.reshape(a, 2, rw, LANES)
    lo, hi = xr[:, 0], xr[:, 1]
    t = (lo ^ hi) & m.reshape(a, rw, LANES)
    return jnp.stack([lo ^ t, hi ^ t], axis=1).reshape(x.shape)


def _stage_outer(x, m, st: StageSpec, tr: int):
    """One outer-block butterfly on a pass-A/C block x: (B, tt, LANES);
    m: (B/2, tt, LANES) pair-compacted."""
    bw = (st.d >> 12) // tr
    bdim = x.shape[0]
    a = bdim // (2 * bw)
    xr = x.reshape(a, 2, bw, *x.shape[1:])
    lo, hi = xr[:, 0], xr[:, 1]
    t = (lo ^ hi) & m.reshape(a, bw, *m.shape[1:])
    return jnp.stack([lo ^ t, hi ^ t], axis=1).reshape(x.shape)


def _run_local_tile_major(x, arr2d, tr, specs, n, interpret, vma=None):
    """Pass B with tile-major masks: one big DMA per x-tile (all local
    stages' rows contiguous), double-buffered across grid steps — the
    next tile's block streams in while this tile computes.  See TILE_MAJOR
    for the measured rationale (big DMAs ride the chip's fast sequential
    path; many small per-stage copies collapse in slow-DMA windows)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nw = n // 32
    r = nw // LANES
    ntiles = max(r // tr, 1)
    block_rows = sum(_stage_rows(st, tr) for st in specs)
    x_view = x.reshape(r, LANES)
    x_spec = pl.BlockSpec((tr, LANES), lambda i: (i, 0))

    def guard(st, pid):
        if not _GUARDS:
            return None
        rows = _stage_rows(st, tr)
        if st.lo <= 0 and st.hi >= st.nwords:
            return None
        w0 = pid * rows * LANES
        return (w0 < st.hi) & (w0 + rows * LANES > st.lo)

    # bfs_tpu: hot
    def kernel(x_ref, m_hbm, o_ref, buf, sem):
        pid = pl.program_id(0)

        def dma(slot, t):
            return pltpu.make_async_copy(
                m_hbm.at[pl.ds(t * block_rows, block_rows), :],
                buf.at[slot],
                sem.at[slot],
            )

        @pl.when(pid == 0)
        def _():
            dma(0, 0).start()

        @pl.when(pid + 1 < ntiles)
        def _():
            dma((pid + 1) % 2, pid + 1).start()

        dma(pid % 2, pid).wait()
        xv = x_ref[...]
        slot = pid % 2
        for st in specs:
            rows = _stage_rows(st, tr)
            mv = buf[slot, pl.ds(st.offset // LANES, rows), :]
            g = guard(st, pid)
            if g is None:
                xv = _stage_local(xv, mv, st, interpret)
            else:
                xv = jnp.where(g, _stage_local(xv, mv, st, interpret), xv)
        o_ref[...] = xv

    if vma is None:
        out_shape = jax.ShapeDtypeStruct(x_view.shape, jnp.uint32)
    else:
        out_shape = jax.ShapeDtypeStruct(
            x_view.shape, jnp.uint32, vma=frozenset(vma)
        )
    out = pl.pallas_call(
        kernel,
        grid=(ntiles,),
        in_specs=[x_spec, pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=x_spec,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((2, block_rows, LANES), jnp.uint32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(x_view, arr2d)
    return out.reshape(-1)


def _run_pass(x, arr2d, mode, tr, tt, specs, n, interpret, vma=None,
              lane64=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nw = n // 32
    r = nw // LANES
    b = r // tr

    if mode == "local_tm":
        return _run_local_tile_major(
            x, arr2d, tr, specs, n, interpret, vma
        )
    if mode == "local":
        grid = (r // tr,)
        x_view = x.reshape(r, LANES)
        x_spec = pl.BlockSpec((tr, LANES), lambda i: (i, 0))
        buf_rows = tr
        has_lane64 = any(_is_lane_compact(st) for st in specs)
        assert not has_lane64 or lane64 is not None

        def stage_rows(st):
            # lane-compact and row-compact stages both span tr//2 storage
            # rows of the 128-lane view; full stages span tr.
            return _stage_rows(st, tr)

        def dma(refs, mbufs, sem, slot, st, rows, pid):
            ref = refs[1] if _is_lane_compact(st) else refs[0]
            buf = mbufs[1] if _is_lane_compact(st) else mbufs[0]
            return pltpu.make_async_copy(
                ref.at[pl.ds(st.offset // LANES + pid * rows, rows), :],
                buf.at[slot, pl.ds(0, rows), :],
                sem.at[slot],
            )

        def guard(st, pid):
            if not _GUARDS:
                return None
            rows = stage_rows(st)
            if st.lo <= 0 and st.hi >= st.nwords:
                return None  # dense stage: unconditional (keeps DMA pipeline)
            w0 = pid * rows * LANES
            return (w0 < st.hi) & (w0 + rows * LANES > st.lo)

        def run_stage(xv, mbufs, slot, st):
            rows = stage_rows(st)
            buf = mbufs[1] if _is_lane_compact(st) else mbufs[0]
            return _stage_local(
                xv, buf[slot, pl.ds(0, rows), :], st, interpret
            )
    else:
        span = b // 2  # outer stages are always compact
        grid = (tr // tt,)
        x_view = x.reshape(b, tr, LANES)
        x_spec = pl.BlockSpec((b, tt, LANES), lambda j: (0, j, 0))
        buf_rows = span * tt
        has_lane64 = False

        def stage_rows(st):
            return span * tt

        def dma(refs, mbufs, sem, slot, st, rows, pid):
            return pltpu.make_async_copy(
                refs[0].at[pl.ds(st.offset // LANES + pid * rows, rows), :],
                mbufs[0].at[slot],
                sem.at[slot],
            )

        def guard(st, pid):
            del st, pid
            return None  # outer tiles always intersect live words

        def run_stage(xv, mbufs, slot, st):
            return _stage_outer(
                xv, mbufs[0][slot].reshape(span, tt, LANES), st, tr
            )

    depth = DMA_DEPTH

    def make_kernel(nrefs):
        # bfs_tpu: hot
        def kernel(x_ref, *rest):
            refs = rest[:nrefs]
            o_ref = rest[nrefs]
            scratch = rest[nrefs + 1 :]
            mbufs = scratch[:-1]
            sem = scratch[-1]
            pid = pl.program_id(0)
            xv = x_ref[...]
            n_st = len(specs)
            guards = [guard(st, pid) for st in specs]

            def start(si):
                st = specs[si]
                g = guards[si]
                if g is None:
                    dma(refs, mbufs, sem, si % depth, st, stage_rows(st),
                        pid).start()
                else:

                    @pl.when(g)
                    def _():
                        dma(refs, mbufs, sem, si % depth, st, stage_rows(st),
                            pid).start()

            # Keep depth-1 mask copies in flight: stage si+depth-1's DMA is
            # issued as stage si begins.  Slot si%depth is reclaimed at issue
            # time si+depth-1, whose program point is after stage si's
            # compute consumed it.
            for w in range(min(depth - 1, n_st)):
                start(w)
            for si, st in enumerate(specs):
                if si + depth - 1 < n_st:
                    start(si + depth - 1)
                g = guards[si]
                if g is None:
                    dma(refs, mbufs, sem, si % depth, st, stage_rows(st),
                        pid).wait()
                    xv = run_stage(xv, mbufs, si % depth, st)
                else:

                    @pl.when(g)
                    def _():
                        dma(refs, mbufs, sem, si % depth, st, stage_rows(st),
                            pid).wait()

                    xv = jnp.where(g, run_stage(xv, mbufs, si % depth, st), xv)
            o_ref[...] = xv

        return kernel

    if vma is None:
        out_shape = jax.ShapeDtypeStruct(x_view.shape, jnp.uint32)
    else:
        # Inside shard_map with varying-mesh-axes checking, a pallas output
        # must declare which mesh axes it varies over (parallel/sharded.py
        # passes the graph axis).
        out_shape = jax.ShapeDtypeStruct(
            x_view.shape, jnp.uint32, vma=frozenset(vma)
        )
    operands = [x_view, arr2d]
    in_specs = [x_spec, pl.BlockSpec(memory_space=pl.ANY)]
    scratch = [pltpu.VMEM((depth, buf_rows, LANES), jnp.uint32)]
    if has_lane64:
        operands.append(lane64)
        in_specs.append(pl.BlockSpec(memory_space=pl.ANY))
        scratch.append(pltpu.VMEM((depth, tr // 2, LANES), jnp.uint32))
    scratch.append(pltpu.SemaphoreType.DMA((depth,)))
    out = pl.pallas_call(
        make_kernel(len(operands) - 1),
        grid=grid,
        in_specs=in_specs,
        out_specs=x_spec,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(*operands)
    return out.reshape(-1)


# ---------------------------------------------------------------------------
# Element-major mode: x carries one uint32 PER ELEMENT (bit t = tree t of a
# 32-tree batch, ops/relay_elem.py).  Stage masks are re-packed VERTICALLY
# host-side (:func:`prepare_elem_pass_masks`): word (R, l) holds bits for
# elements (32R + j, l) — so the in-kernel bit->select expansion is one
# sublane broadcast plus a per-row variable shift, no lane shuffles.

#: element-mode pass-B tile rows: (1, TILE_ROWS_E, 128) uint32 elements —
#: 4 MB in + 4 MB out under the raised 64 MB scoped-vmem budget.  Element
#: rows are 32x more numerous than word rows, so tree GROUPS run through
#: the passes sequentially (G=1 per pallas call); at net 2^28 this keeps
#: the outer span at 256 blocks instead of the 2048 that OOMed VMEM.
TILE_ROWS_E = 8192
OUTER_TT_E = 64


def elem_pass_static(
    table: tuple[StageSpec, ...], n: int,
    tile_rows: int = TILE_ROWS_E, outer_tt: int = OUTER_TT_E,
):
    """Pass split for element-major mode (element rows of 128; local run is
    d < tile_rows*128).  Mirrors :func:`prepare_elem_pass_masks`."""
    r = n // LANES
    tr = min(tile_rows, max(r, 1))
    local = [i for i, st in enumerate(table) if st.d < tr * LANES]
    assert local and local == list(
        range(local[0], local[-1] + 1)
    ), "local stages must be consecutive"
    lo, hi = local[0], local[-1] + 1
    tt = min(outer_tt, tr)
    out = []

    def seg(idx, mode):
        specs = []
        off = 0
        for i in idx:
            st = table[i]
            nw = st.nwords
            specs.append(st._replace(offset=off, nwords=nw, lo=0, hi=nw))
            off += nw
        return (mode, tr, tt, tuple(specs))

    if lo > 0:
        out.append(seg(list(range(lo)), "outer"))
    out.append(seg(list(range(lo, hi)), "local"))
    if hi < len(table):
        out.append(seg(list(range(hi, len(table))), "outer"))
    return tuple(out)


def _vertical_repack(words: np.ndarray, nelem: int) -> np.ndarray:
    """Standard-packed stage words -> vertical packing: output word (R, l)
    holds bits of elements (32R + j, l), j in [0, 32)."""
    bits = np.unpackbits(
        words.view(np.uint8), bitorder="little"
    ).reshape(-1, 32, LANES)
    by = np.packbits(bits, axis=1, bitorder="little")  # (R, 4, LANES) bytes
    return (
        np.ascontiguousarray(by.transpose(0, 2, 1))  # word bytes contiguous
        .view(np.uint32)
        .reshape(-1)
    )


def prepare_elem_pass_masks(
    masks_flat: np.ndarray, table: tuple[StageSpec, ...], n: int,
    tile_rows: int = TILE_ROWS_E, outer_tt: int = OUTER_TT_E,
):
    """Host-side (cached by engines): per-pass vertically-packed mask arrays
    for element-major mode.  Outer stages additionally re-chunk to
    (tr/tt, span, tt_rows...) order so each DMA is contiguous, mirroring
    :func:`prepare_pass_masks`."""
    ps = elem_pass_static(table, n, tile_rows, outer_tt)
    r = n // LANES
    tr = min(tile_rows, max(r, 1))
    b = r // tr
    tt = min(outer_tt, tr)
    # map pass-local specs back to the original global stages in order
    arrays = []
    gi = 0
    for mode, _tr, _tt, specs in ps:
        parts = []
        for st_local in specs:
            st = table[gi]
            gi += 1
            w = masks_flat[st.offset : st.offset + st.nwords]
            wv = _vertical_repack(w, st.nwords * 32)
            if mode == "outer":
                # stage rows (in vertical-packed units of 32 elem rows):
                # (span, tr/32, LANES) -> chunk-major (tr/tt, span, tt/32, L)
                span = b // 2  # outer stages are compact
                wv = (
                    wv.reshape(span, tr // 32 // (tt // 32), tt // 32, LANES)
                    .swapaxes(0, 1)
                    .reshape(-1)
                )
            parts.append(wv)
        arrays.append(
            np.concatenate(parts).reshape(-1, LANES)
            if parts
            else np.zeros((0, LANES), np.uint32)
        )
    return arrays


def _expand_vertical(mv, rows: int, interpret: bool):
    """Vertically-packed mask words (rows//32, LANES) -> per-element select
    (rows, LANES) uint32 0/~0."""
    rep = jnp.repeat(mv, 32, axis=0)
    ri = jax.lax.broadcasted_iota(jnp.uint32, (rows, LANES), 0) & 31
    return jnp.uint32(0) - ((rep >> ri) & 1)


def _elem_stage_local(x, sel_rows, st: StageSpec, interpret: bool):
    """One butterfly stage on an element tile x: (G, tr, LANES).
    ``sel_rows``: expanded select for the stage's stored rows."""
    d = st.d
    g = x.shape[0]
    tr = x.shape[1]
    if d < LANES:  # lane butterfly: select at lower pair lanes, roll-mirror
        idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, 2)
        has = (idx & d) != 0
        sel = sel_rows[None, :, :]
        partner = jnp.where(
            has, _kroll(x, d, 2, interpret), _kroll(x, -d, 2, interpret)
        )
        m_both = jnp.where(has, _kroll(sel, d, 2, interpret), sel)
        return x ^ ((x ^ partner) & m_both)
    rw = d // LANES  # row butterfly
    if st.compact:
        a = tr // (2 * rw)
        xr = x.reshape(g, a, 2, rw, LANES)
        lo, hi = xr[:, :, 0], xr[:, :, 1]
        t = (lo ^ hi) & sel_rows.reshape(1, a, rw, LANES)
        return jnp.stack([lo ^ t, hi ^ t], axis=2).reshape(x.shape)
    a = tr // (2 * rw)
    xr = x.reshape(g, a, 2, rw, LANES)
    lo, hi = xr[:, :, 0], xr[:, :, 1]
    sl = sel_rows.reshape(a, 2, rw, LANES)[:, 0]
    t = (lo ^ hi) & sl.reshape(1, a, rw, LANES)
    return jnp.stack([lo ^ t, hi ^ t], axis=2).reshape(x.shape)


def _elem_stage_outer(x, sel, st: StageSpec, tr: int):
    """Outer-block butterfly: x (G, B, tt, LANES); sel (B/2, tt, LANES)."""
    bw = (st.d // LANES) // tr
    bdim = x.shape[1]
    a = bdim // (2 * bw)
    xr = x.reshape(x.shape[0], a, 2, bw, *x.shape[2:])
    lo, hi = xr[:, :, 0], xr[:, :, 1]
    t = (lo ^ hi) & sel.reshape(1, a, bw, *sel.shape[1:])
    return jnp.stack([lo ^ t, hi ^ t], axis=2).reshape(x.shape)


def _run_elem_pass(x, arr2d, mode, tr, tt, specs, n, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    g = x.shape[0]
    r = n // LANES
    b = r // tr

    if mode == "local":
        grid = (r // tr,)
        x_view = x.reshape(g, r, LANES)
        x_spec = pl.BlockSpec((g, tr, LANES), lambda i: (0, i, 0))

        def stage_mrows(st):
            rows = tr // 2 if st.compact else tr
            return rows // 32

        def dma(m_hbm, mbuf, sem, slot, st, mrows, pid):
            return pltpu.make_async_copy(
                m_hbm.at[pl.ds(st.offset // LANES + pid * mrows, mrows), :],
                mbuf.at[slot, pl.ds(0, mrows), :],
                sem.at[slot],
            )

        def run_stage(xv, mbuf, slot, st):
            mrows = stage_mrows(st)
            sel = _expand_vertical(
                mbuf[slot, pl.ds(0, mrows), :], mrows * 32, interpret
            )
            return _elem_stage_local(xv, sel, st, interpret)

        buf_rows = tr // 32
    else:
        span = b // 2
        grid = (tr // tt,)
        x_view = x.reshape(g, b, tr, LANES)
        x_spec = pl.BlockSpec((g, b, tt, LANES), lambda j: (0, 0, j, 0))

        def stage_mrows(st):
            return span * (tt // 32)

        def dma(m_hbm, mbuf, sem, slot, st, mrows, pid):
            return pltpu.make_async_copy(
                m_hbm.at[pl.ds(st.offset // LANES + pid * mrows, mrows), :],
                mbuf.at[slot],
                sem.at[slot],
            )

        def run_stage(xv, mbuf, slot, st):
            mrows = stage_mrows(st)
            sel = _expand_vertical(
                mbuf[slot].reshape(mrows, LANES), mrows * 32, interpret
            ).reshape(span, tt, LANES)
            return _elem_stage_outer(xv, sel, st, tr)

        buf_rows = span * (tt // 32)

    depth = DMA_DEPTH

    # bfs_tpu: hot
    def kernel(x_ref, m_hbm, o_ref, mbuf, sem):
        pid = pl.program_id(0)
        xv = x_ref[...]
        n_st = len(specs)
        for w in range(min(depth - 1, n_st)):
            dma(m_hbm, mbuf, sem, w % depth, specs[w], stage_mrows(specs[w]),
                pid).start()
        for si, st in enumerate(specs):
            if si + depth - 1 < n_st:
                nst = specs[si + depth - 1]
                dma(m_hbm, mbuf, sem, (si + depth - 1) % depth, nst,
                    stage_mrows(nst), pid).start()
            dma(m_hbm, mbuf, sem, si % depth, st, stage_mrows(st), pid).wait()
            xv = run_stage(xv, mbuf, si % depth, st)
        o_ref[...] = xv

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[x_spec, pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=x_spec,
        out_shape=jax.ShapeDtypeStruct(x_view.shape, jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((depth, buf_rows, LANES), jnp.uint32),
            pltpu.SemaphoreType.DMA((depth,)),
        ],
        interpret=interpret,
    )(x_view, arr2d)
    return out.reshape(g, n)


def apply_benes_elem_fused(
    x: jax.Array, pass_arrays, pass_static_info, n: int,
    interpret: bool = False,
) -> jax.Array:
    """Element-major routed Beneš network in fused passes: x uint32[G, n]."""
    for (mode, tr, tt, specs), arr in zip(pass_static_info, pass_arrays):
        x = _run_elem_pass(x, arr, mode, tr, tt, specs, n, interpret)
    return x


def elem_superstep_tpu_factory(static, plane_offsets, pt: int):
    """Element-major superstep for real TPUs: the two Beneš networks run as
    fused element-major passes (x VMEM-resident, vertically-packed masks
    streamed once per superstep FOR ALL 32*G trees); broadcast, row-min
    tournament and the bit-sliced apply stay in XLA."""
    (vr, vperm_size, vperm_table, out_classes, out_space, net_table,
     net_size, in_classes) = static
    from . import relay_elem as RE

    vp_ok = pallas_net_ok(vperm_size)
    net_ok = pallas_net_ok(net_size)
    vp_static = elem_pass_static(vperm_table, vperm_size) if vp_ok else None
    net_static = elem_pass_static(net_table, net_size) if net_ok else None

    def superstep(st, vperm_m, net_m, valid_words):
        g = st.frontier.shape[0]
        fw = jnp.concatenate(
            [st.frontier, jnp.zeros((g, vperm_size - vr), jnp.uint32)],
            axis=1,
        )
        if vp_ok:  # groups run sequentially: element tiles are VMEM-hungry
            y = jnp.concatenate([
                apply_benes_elem_fused(
                    fw[gi : gi + 1], vperm_m, vp_static, vperm_size
                )
                for gi in range(g)
            ])
        else:
            y = RE.apply_benes_elem(fw, vperm_m, vperm_table, vperm_size)
        l2 = RE.broadcast_l2_elem(y, out_classes, net_size)
        if net_ok:
            l1 = jnp.concatenate([
                apply_benes_elem_fused(
                    l2[gi : gi + 1], net_m, net_static, net_size
                )
                for gi in range(g)
            ])
        else:
            l1 = RE.apply_benes_elem(l2, net_m, net_table, net_size)
        found, rp_new = RE.rowmin_elem(
            l1, valid_words, in_classes, vr, plane_offsets, pt
        )
        newly = found & ~st.visited
        visited = st.visited | newly
        new_level = st.level + 1
        lev = new_level.astype(jnp.uint32)
        dist_planes = jnp.stack(
            [
                jnp.where(
                    (lev >> b) & 1, st.dist_planes[b] | newly,
                    st.dist_planes[b],
                )
                for b in range(RE.DIST_PLANES)
            ]
        )
        rp_mask_parts = []
        for cs in sorted(in_classes, key=lambda c: c.va):
            _, nb = plane_offsets[cs.va]
            if nb:
                seg = jax.lax.slice_in_dim(newly, cs.va, cs.vb, axis=1)
                rp_mask_parts.append(jnp.tile(seg, (1, nb)))
        rp_mask = (
            jnp.concatenate(rp_mask_parts, axis=1)
            if rp_mask_parts
            else jnp.zeros_like(st.rank_planes)
        )
        rank_planes = st.rank_planes | (rp_new & rp_mask)
        return RE.ElemState(
            visited=visited, frontier=newly, dist_planes=dist_planes,
            rank_planes=rank_planes, level=new_level,
            changed=(newly != 0).any(),
        )

    return superstep


# ---------------------------------------------------------------------------
# Per-phase kernels beyond the Beneš appliers (ISSUE 7 tentpole b): the two
# next-largest ledger phases after net-apply — the masked row-min tournament
# and the packed lexicographic-min state update — as fused Pallas kernels,
# each bit-exact against its XLA twin (ops/relay.rowmin_ranks /
# apply_relay_candidates_packed) and selected PER PHASE by measurement
# (profiling.probe_phase_kernels feeds the engine's phase_selection), never
# by default.  Off-TPU they run in interpret mode — measured for the ledger
# verdict and exercised for parity in tests, but interpret overheads mean
# XLA wins the selection there.


def pallas_interpret() -> bool:
    """Interpret-mode flag for the per-phase kernels: real Mosaic on TPU
    backends, the Pallas interpreter everywhere else (parity tests + the
    ledger's measured-arm probes on CPU)."""
    try:
        return jax.default_backend() != "tpu"
    except Exception:  # pragma: no cover - backend init failure
        return True


def _pow2(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


#: VMEM word budget for one row-min tile ([width, chunk] uint32 x2 operands).
ROWMIN_TILE_WORDS = 1 << 19


def _rowmin_chunk(width: int, cw: int) -> int:
    """Lane-chunk for one class's [width, cw] tournament tile: the whole
    row span when it fits the VMEM budget, else the largest divisor of
    ``cw`` under it (preferring 128-lane multiples — the TPU-friendly
    shape); 0 when nothing fits (class falls back to XLA)."""
    p2 = 1 << max((width - 1).bit_length(), 0)
    limit = ROWMIN_TILE_WORDS // max(p2, 1)
    if limit < 1:
        return 0
    if cw <= limit:
        return cw
    aligned = [d for d in range(1, limit + 1) if cw % d == 0 and d % LANES == 0]
    anyd = [d for d in range(1, limit + 1) if cw % d == 0]
    return aligned[-1] if aligned else (anyd[-1] if anyd else 0)


def rowmin_class_ok(cs) -> bool:
    """Is one class eligible for the fused tournament kernel?  Rank-major
    with at least two rows (the kernel zero-pads rows to the next power
    of two, mirroring the XLA tournament) and a chunk under the VMEM
    budget must exist."""
    return (
        not cs.vertex_major
        and cs.width >= 2
        and _rowmin_chunk(cs.width, cs.count // 32) > 0
    )


def _class_tournament_call(x2d, v2d, width: int, cw: int, interpret: bool):
    """Masked min-row-index tournament over one class's [width, cw] word
    view: returns uint32[1 + log2(width), cw] — row 0 the found words,
    rows 1.. the rank bit-plane words low..high, bit-exact with
    ops/relay._word_tournament on ``x & v``.  The grid streams lane
    chunks; each instance holds one [width, chunk] tile x2 in VMEM and
    runs the log2(width) merge rounds register-resident — the XLA path
    round-trips every round through HBM."""
    from jax.experimental import pallas as pl

    nb = max(width - 1, 1).bit_length() if width > 1 else 0
    chunk = _rowmin_chunk(width, cw)

    # bfs_tpu: hot
    def kernel(x_ref, v_ref, o_ref):
        f = x_ref[...] & v_ref[...]
        rows = f.shape[0]
        p2 = 1 << max((rows - 1).bit_length(), 0)
        if p2 != rows:
            # Zero-pad rows to the power of two the log reduce halves —
            # exactly the XLA tournament's padding (zero words never win).
            f = jnp.concatenate(
                [f, jnp.zeros((p2 - rows, f.shape[-1]), jnp.uint32)], axis=0
            )
            rows = p2
        planes: list = []
        while rows > 1:
            fr = f.reshape(rows // 2, 2, f.shape[-1])
            fa, fb = fr[:, 0, :], fr[:, 1, :]
            new_planes = []
            for pl_w in planes:
                pr = pl_w.reshape(rows // 2, 2, pl_w.shape[-1])
                new_planes.append(pr[:, 0, :] | (pr[:, 1, :] & ~fa))
            new_planes.append(fb & ~fa)
            planes = new_planes
            f = fa | fb
            rows //= 2
        o_ref[...] = jnp.concatenate([f] + planes, axis=0)

    in_spec = pl.BlockSpec((width, chunk), lambda j: (0, j))
    return pl.pallas_call(
        kernel,
        grid=(cw // chunk,),
        in_specs=[in_spec, in_spec],
        out_specs=pl.BlockSpec((nb + 1, chunk), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((nb + 1, cw), jnp.uint32),
        interpret=interpret,
    )(x2d, v2d)


# bfs_tpu: hot traced
def rowmin_ranks_pallas(
    l1words: jax.Array, valid_words: jax.Array, in_classes, vr: int,
    interpret: bool | None = None,
) -> jax.Array:
    """Pallas flavor of :func:`bfs_tpu.ops.relay.rowmin_ranks`: min active
    RANK per relabeled vertex (uint32, PACKED_SENTINEL where none), with
    eligible rank-major classes' tournaments fused into one VMEM-resident
    kernel per class and the masking (``l1 & valid``) applied in-kernel —
    the two net-sized operands stream through VMEM exactly once.
    Ineligible classes (vertex-major, non-pow2 width, unaligned chunk)
    take the XLA tournament, so the output is bit-exact with the XLA path
    for every layout."""
    from . import relay as R
    from .packed import PACKED_SENTINEL

    if interpret is None:
        interpret = pallas_interpret()
    cands = []
    covered = 0
    for cs in sorted(in_classes, key=lambda c: c.va):
        assert cs.va == covered, "in_classes must tile the vertex space"
        if rowmin_class_ok(cs):
            a, b = cs.sa // 32, cs.sb // 32
            cw = cs.count // 32
            x2d = jax.lax.slice_in_dim(l1words, a, b).reshape(cs.width, cw)
            v2d = jax.lax.slice_in_dim(valid_words, a, b).reshape(
                cs.width, cw
            )
            out = _class_tournament_call(
                x2d, v2d, cs.width, cw, interpret
            )
            found = R.unpack_std(out[0], cs.count) != 0
            rank = jnp.zeros(cs.count, jnp.int32)
            for j in range(out.shape[0] - 1):
                rank = rank | (
                    R.unpack_std(out[j + 1], cs.count).astype(jnp.int32)
                    << j
                )
        else:
            found, rank = R._class_found_rank(
                R._masked_class_words(l1words, valid_words, cs), cs
            )
        cands.append(
            jnp.where(found, rank.astype(jnp.uint32), PACKED_SENTINEL)
        )
        covered = cs.vb
    if covered < vr:
        cands.append(jnp.full(vr - covered, PACKED_SENTINEL, jnp.uint32))
    return jnp.concatenate(cands)


#: State-update view: packed words as [vr/128, 128]; a tile of ``tr`` rows
#: (tr % 32 == 0) emits its frontier words as one [tr/32, 128] block, so
#: vr must pad to a multiple of 32*128 = 4096 elements.
_UPDATE_ALIGN = 32 * LANES


def _update_tile_rows(rows: int) -> int:
    for tr in (2048, 1024, 512, 256, 128, 64, 32):
        if rows % tr == 0:
            return tr
    return 0


def _apply_packed_kernel_factory(tr: int, interpret: bool):
    # bfs_tpu: hot
    def kernel(x_ref, c_ref, o_ref, f_ref):
        pk = x_ref[...]
        pk2 = jnp.minimum(pk, c_ref[...])  # THE lexicographic min
        newly = (pk2 != pk).astype(jnp.uint32)
        lane = jax.lax.broadcasted_iota(jnp.uint32, pk.shape, 1)
        lmod = lane & 31
        # Standard packing in-register: t0 = bit << (lane%32), then a
        # 5-step guarded prefix-OR within each 32-lane group leaves the
        # group's packed word at its lane-0 slot; the stride-32 gather +
        # minor reshape lays the tr*4 words out as the [tr/32, 128]
        # fwords block (flat word g = row*4 + lane/32 = the standard
        # ``e >> 5`` word order).
        t = newly << lmod
        for k in (1, 2, 4, 8, 16):
            rolled = _kroll(t, -k, 1, interpret)
            t = t | jnp.where(lmod + k < 32, rolled, jnp.uint32(0))
        f_ref[...] = t[:, ::32].reshape(tr // 32, LANES)
        o_ref[...] = pk2

    return kernel


# bfs_tpu: hot traced
def apply_relay_candidates_packed_pallas(
    state, rank_or_sent: jax.Array, interpret: bool | None = None,
):
    """Pallas flavor of
    :func:`bfs_tpu.ops.relay.apply_relay_candidates_packed`: the packed
    lexicographic-min state update with the frontier-word repack fused
    into the same kernel — the packed carry and candidate words stream
    through VMEM once and the newly-bits never materialize as a V-sized
    bool array in HBM (the XLA path's ``pack_std`` reads them back).
    Bit-exact with the XLA twin; the carry tail (level, changed) follows
    the same contract."""
    from jax.experimental import pallas as pl

    from .packed import PACKED_SENTINEL, level_word
    from .relay import PackedRelayState

    if interpret is None:
        interpret = pallas_interpret()
    cand = rank_or_sent | level_word(state.level + 1)
    vr = state.packed.shape[0]
    vrp = ((vr + _UPDATE_ALIGN - 1) // _UPDATE_ALIGN) * _UPDATE_ALIGN
    pk = state.packed
    if vrp != vr:
        pad = jnp.full(vrp - vr, PACKED_SENTINEL, jnp.uint32)
        pk = jnp.concatenate([pk, pad])
        cand = jnp.concatenate([cand, pad])
    rows = vrp // LANES
    tr = _update_tile_rows(rows)
    x_spec = pl.BlockSpec((tr, LANES), lambda i: (i, 0))
    pk2, fw = pl.pallas_call(
        _apply_packed_kernel_factory(tr, interpret),
        grid=(rows // tr,),
        in_specs=[x_spec, x_spec],
        out_specs=(x_spec, pl.BlockSpec((tr // 32, LANES), lambda i: (i, 0))),
        out_shape=(
            jax.ShapeDtypeStruct((rows, LANES), jnp.uint32),
            jax.ShapeDtypeStruct((rows // 32, LANES), jnp.uint32),
        ),
        interpret=interpret,
    )(pk.reshape(rows, LANES), cand.reshape(rows, LANES))
    packed2 = pk2.reshape(-1)[:vr]
    fwords = fw.reshape(-1)[: vr // 32]
    return PackedRelayState(
        packed2, fwords, state.level + 1, (fwords != jnp.uint32(0)).any()
    )


def apply_benes_fused(
    words: jax.Array,
    pass_arrays,  # device arrays in prepare_pass_masks order
    pass_static,  # tuple of (mode, tr, tt, specs) in the same order
    n: int,
    interpret: bool = False,
    vma=None,  # mesh axes the result varies over (shard_map callers)
) -> jax.Array:
    """The full routed Beneš network in at most three fused Pallas passes.
    The local pass consumes TWO arrays (main + lane64 side array) when any
    of its stages is lane-compacted — prepare_pass_masks emits them
    adjacently."""
    x = words
    ai = 0
    for mode, tr, tt, specs in pass_static:
        arr = pass_arrays[ai]
        ai += 1
        lane64 = None
        if mode == "local" and any(_is_lane_compact(st) for st in specs):
            lane64 = pass_arrays[ai]
            ai += 1
        x = _run_pass(x, arr, mode, tr, tt, specs, n, interpret, vma, lane64)
    return x
