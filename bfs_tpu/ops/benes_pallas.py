"""Pallas TPU kernels: the whole Beneš network in three fused passes.

:func:`~bfs_tpu.ops.relay.apply_benes` applies 2·log2(N)-1 butterfly stages
to the bit-major packed word array.  In plain XLA every stage is its own
kernel: an HBM round-trip of the word array plus ~0.4 ms of per-kernel
launch overhead (measured on the bench TPU) — 55 kernels at net 2^28.
The stages factor into three runs, each closed under a tiling that fits
VMEM, so the network needs only THREE kernels with x resident in VMEM
across every stage of a pass and the per-stage masks DMA-streamed from
HBM with double buffering (the masks are the irreducible traffic):

viewing the words as [R, 128] and an element distance d as

  * a lane distance d                 (d < 128)
  * a row distance  d // 128          (128 <= d < nw)
  * a bit-plane distance d // nw      (d >= nw, elementwise)

pick tile rows TR (power of two).  A stage with d < TR*128 is closed under
aligned contiguous [TR, 128] tiles (row ^ br keeps high row bits for
br < TR) — and the Beneš schedule descends N/2 → 1 → N/2, so those LOCAL
stages form one consecutive run in the middle.  The OUTER stages (bit
planes and row distances >= TR) are closed under the complementary tiling:
view [B, TR, 128] with B = R/TR and take a (B, tt, 128) block — full outer
axis, a chunk of the inner rows — since row ^ br for br >= TR only touches
the outer index (b ^ (br/TR)), elementwise bit stages don't care, and the
down/up halves put those stages in a prefix and a suffix run.

So: pass A = prefix outer stages, pass B = the local run, pass C = suffix
outer stages; x traffic is 3 round-trips instead of 55, and kernel count
drops ~18x.  Verified bit-exact against the per-stage XLA path
(tests/test_benes_pallas.py) and by the bench's check() invariants.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

LANES = 128
#: Local-pass tile rows: 2048 rows * 128 lanes * 4 B = 1 MB of VMEM for x,
#: double that for the streamed mask buffers.
TILE_ROWS = 2048
#: Outer-pass inner-chunk rows; the block is (B, OUTER_TT, 128).
OUTER_TT = 64


def pallas_enabled() -> bool:
    """Use the Pallas path only on real TPU backends (the CPU test platform
    runs the pure-XLA stages).  BFS_TPU_PALLAS=0/1 overrides."""
    env = os.environ.get("BFS_TPU_PALLAS", "")
    if env in ("0", "1"):
        return env == "1"
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - backend init failure
        return False


def stage_distances(n: int) -> list[int]:
    """Element distance of every Beneš stage for an n-element network
    (must match apply_benes / native/benes.cpp stage order)."""
    k = int(n).bit_length() - 1
    return [n >> (s + 1) if s < k else n >> (2 * k - 1 - s)
            for s in range(2 * k - 1)]


def local_stage_run(n: int, tile_rows: int = TILE_ROWS) -> tuple[int, int]:
    """[lo, hi) stage-index range with element distance < tr*128 (tr = the
    EFFECTIVE tile rows, clamped to the network's row count) — the
    consecutive middle run pass B fuses."""
    tr = min(tile_rows, max(n // 32 // LANES, 1))
    dists = stage_distances(n)
    local = [s for s, d in enumerate(dists) if d < tr * LANES]
    if not local:
        return (0, 0)
    lo, hi = local[0], local[-1] + 1
    assert local == list(range(lo, hi)), "local stages must be consecutive"
    return (lo, hi)


def _stage_on_tile(x, m, d, *, nw, rows, lane_axis, row_axis, outer_axis,
                   outer_span, tr):
    """One butterfly stage on a VMEM-resident tile.

    ``rows``: size of the row axis inside the tile (pass B); ``outer_span``:
    size of the outer axis (pass A/C).  Exactly one regime applies per d.
    """
    if d >= nw:  # bit-plane butterfly: elementwise on every word
        sh = jnp.uint32(d // nw)
        t = (x ^ (x >> sh)) & m
        return x ^ t ^ (t << sh)
    if d < LANES:  # lane butterfly inside each 128-word row
        lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, lane_axis)
        has = (lane & d) != 0
        partner = jnp.where(
            has, jnp.roll(x, d, axis=lane_axis), jnp.roll(x, -d, axis=lane_axis)
        )
        m_both = jnp.where(has, jnp.roll(m, d, axis=lane_axis), m)
        return x ^ ((x ^ partner) & m_both)
    br = d // LANES
    if br < tr:  # row butterfly inside the local tile (pass B)
        idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, row_axis)
        has = (idx & br) != 0
        partner = jnp.where(
            has, jnp.roll(x, br, axis=row_axis), jnp.roll(x, -br, axis=row_axis)
        )
        m_both = jnp.where(has, jnp.roll(m, br, axis=row_axis), m)
        return x ^ ((x ^ partner) & m_both)
    cb = br // tr  # outer-block butterfly (pass A/C): partner block b ^ cb
    idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, outer_axis)
    has = (idx & cb) != 0
    partner = jnp.where(
        has, jnp.roll(x, cb, axis=outer_axis), jnp.roll(x, -cb, axis=outer_axis)
    )
    m_both = jnp.where(has, jnp.roll(m, cb, axis=outer_axis), m)
    return x ^ ((x ^ partner) & m_both)


def _streamed_pass(x, masks, dists, *, nw, tr, mode, interpret):
    """One fused pass: all ``dists`` stages with x VMEM-resident, masks
    DMA-streamed stage-by-stage with double buffering.

    mode 'local': x viewed [R, 128], grid over TR-row tiles.
    mode 'outer': x viewed [B, TR, 128], grid over tt-chunks of TR.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r = nw // LANES
    s_n = len(dists)

    if mode == "local":
        grid = (r // tr,)
        x_view = x.reshape(r, LANES)
        m_view = masks.reshape(s_n, r, LANES)
        block = (tr, LANES)
        x_spec = pl.BlockSpec(block, lambda i: (i, 0), memory_space=pltpu.VMEM)

        def dma(m_hbm, mbuf, sem, slot, si):
            i = pl.program_id(0)
            return pltpu.make_async_copy(
                m_hbm.at[si, pl.ds(i * tr, tr), :], mbuf.at[slot], sem.at[slot]
            )

        def stage(x, m, d):
            return _stage_on_tile(
                x, m, d, nw=nw, rows=tr, lane_axis=1, row_axis=0,
                outer_axis=None, outer_span=None, tr=tr,
            )
    else:
        b = r // tr
        tt = min(OUTER_TT, tr)
        grid = (tr // tt,)
        x_view = x.reshape(b, tr, LANES)
        m_view = masks.reshape(s_n, b, tr, LANES)
        block = (b, tt, LANES)
        x_spec = pl.BlockSpec(block, lambda j: (0, j, 0), memory_space=pltpu.VMEM)

        def dma(m_hbm, mbuf, sem, slot, si):
            j = pl.program_id(0)
            return pltpu.make_async_copy(
                m_hbm.at[si, :, pl.ds(j * tt, tt), :], mbuf.at[slot], sem.at[slot]
            )

        def stage(x, m, d):
            return _stage_on_tile(
                x, m, d, nw=nw, rows=None, lane_axis=2, row_axis=None,
                outer_axis=0, outer_span=b, tr=tr,
            )

    def kernel(x_ref, m_hbm, o_ref, mbuf, sem):
        dma(m_hbm, mbuf, sem, 0, 0).start()
        x = x_ref[:]
        for si, d in enumerate(dists):
            if si + 1 < s_n:
                dma(m_hbm, mbuf, sem, (si + 1) % 2, si + 1).start()
            dma(m_hbm, mbuf, sem, si % 2, si).wait()
            x = stage(x, mbuf[si % 2], d)
        o_ref[:] = x

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[x_spec, pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=x_spec,
        out_shape=jax.ShapeDtypeStruct(x_view.shape, jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((2,) + block, jnp.uint32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(x_view, m_view)
    return out.reshape(-1)


#: pack/unpack kernels engage above this bit count (and when nw % 128 == 0).
PACK_KERNEL_MIN_BITS = 1 << 20
_PACK_CHUNK = 4096  # words per grid step: (32, 4096) uint8 block = 128 KB


def pack_kernel_ok(n: int) -> bool:
    return (
        pallas_enabled()
        and n >= PACK_KERNEL_MIN_BITS
        and (n // 32) % _PACK_CHUNK == 0
    )


@functools.partial(jax.jit, static_argnames=("n", "interpret"))
def pack_bits_pallas(bits: jax.Array, n: int, interpret: bool = False) -> jax.Array:
    """Bit-major pack as ONE Pallas kernel: uint8[n] -> uint32[n/32].

    The bit-major layout (word w bit b = element b*nw + w) makes the XLA
    formulation read the byte array with plane-interleaved strides (measured
    ~12 GB/s); here each grid step reads a (32, chunk) byte block — 32
    contiguous plane rows — widens in VMEM and writes or-combined words."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nw = n // 32

    def kernel(x_ref, o_ref):
        x = x_ref[:].astype(jnp.uint32)  # (32, chunk)
        sh = jax.lax.broadcasted_iota(jnp.uint32, (32, 1), 0)
        o_ref[:] = (x << sh).sum(axis=0, dtype=jnp.uint32)[None, :]

    out = pl.pallas_call(
        kernel,
        grid=(nw // _PACK_CHUNK,),
        in_specs=[
            pl.BlockSpec((32, _PACK_CHUNK), lambda i: (0, i), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((1, _PACK_CHUNK), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, nw), jnp.uint32),
        interpret=interpret,
    )(bits.reshape(32, nw))
    return out.reshape(-1)


@functools.partial(jax.jit, static_argnames=("n", "interpret"))
def unpack_bits_pallas(words: jax.Array, n: int, interpret: bool = False) -> jax.Array:
    """Bit-major unpack as ONE Pallas kernel: uint32[n/32] -> uint8[n]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nw = n // 32

    def kernel(x_ref, o_ref):
        w = x_ref[:]  # (1, chunk)
        sh = jax.lax.broadcasted_iota(jnp.uint32, (32, 1), 0)
        o_ref[:] = ((w >> sh) & jnp.uint32(1)).astype(jnp.uint8)

    out = pl.pallas_call(
        kernel,
        grid=(nw // _PACK_CHUNK,),
        in_specs=[
            pl.BlockSpec((1, _PACK_CHUNK), lambda i: (0, i), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((32, _PACK_CHUNK), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((32, nw), jnp.uint8),
        interpret=interpret,
    )(words.reshape(1, nw))
    return out.reshape(-1)


@functools.partial(
    jax.jit, static_argnames=("n", "tile_rows", "interpret")
)
def apply_benes_fused(
    words: jax.Array, masks: jax.Array, *, n: int,
    tile_rows: int = TILE_ROWS, interpret: bool = False,
) -> jax.Array:
    """The full routed Beneš network (all 2·log2(n)-1 stages) in at most
    three fused Pallas passes.  ``words``: uint32[n/32] bit-major;
    ``masks``: uint32[stages, n/32] from ``benes.route(..., bit_major=True)``.
    """
    nw = n // 32
    r = nw // LANES
    tr = min(tile_rows, r)
    dists = stage_distances(n)
    lo, hi = local_stage_run(n, tile_rows)
    assert lo < hi, "no local run — network too small for the fused path"

    x = words
    if lo > 0:  # pass A: prefix outer stages (bit planes + big row rolls)
        x = _streamed_pass(
            x, masks[:lo], dists[:lo], nw=nw, tr=tr, mode="outer",
            interpret=interpret,
        )
    # pass B: the local run
    x = _streamed_pass(
        x, masks[lo:hi], dists[lo:hi], nw=nw, tr=tr, mode="local",
        interpret=interpret,
    )
    if hi < len(dists):  # pass C: suffix outer stages
        x = _streamed_pass(
            x, masks[hi:], dists[hi:], nw=nw, tr=tr, mode="outer",
            interpret=interpret,
        )
    return x
