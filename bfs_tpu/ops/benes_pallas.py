"""Pallas TPU kernels: the whole Beneš network in three fused passes.

:func:`~bfs_tpu.ops.relay.apply_benes` applies 2·log2(N)-1 butterfly stages
to the bit-major packed word array.  In plain XLA every stage is its own
kernel: an HBM round-trip of the word array plus ~0.4 ms of per-kernel
launch overhead (measured on the bench TPU) — 55 kernels at net 2^28.
The stages factor into three runs, each closed under a tiling that fits
VMEM, so the network needs only THREE kernels with x resident in VMEM
across every stage of a pass and the per-stage masks DMA-streamed from
HBM with double buffering (the masks are the irreducible traffic):

viewing the words as [R, 128] and an element distance d as

  * a lane distance d                 (d < 128)
  * a row distance  d // 128          (128 <= d < nw)
  * a bit-plane distance d // nw      (d >= nw, elementwise)

pick tile rows TR (power of two).  A stage with d < TR*128 is closed under
aligned contiguous [TR, 128] tiles (row ^ br keeps high row bits for
br < TR) — and the Beneš schedule descends N/2 → 1 → N/2, so those LOCAL
stages form one consecutive run in the middle.  The OUTER stages (bit
planes and row distances >= TR) are closed under the complementary tiling:
view [B, TR, 128] with B = R/TR and take a (B, tt, 128) block — full outer
axis, a chunk of the inner rows — since row ^ br for br >= TR only touches
the outer index (b ^ (br/TR)), elementwise bit stages don't care, and the
down/up halves put those stages in a prefix and a suffix run.

So: pass A = prefix outer stages, pass B = the local run, pass C = suffix
outer stages; x traffic is 3 round-trips instead of 55, and kernel count
drops ~18x.  Verified bit-exact against the per-stage XLA path
(tests/test_benes_pallas.py) and by the bench's check() invariants.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

LANES = 128
#: Local-pass tile rows: 2048 rows * 128 lanes * 4 B = 1 MB of VMEM for x,
#: double that for the streamed mask buffers.
TILE_ROWS = 2048
#: Outer-pass inner-chunk rows; the block is (B, OUTER_TT, 128).
OUTER_TT = 64


def pallas_enabled() -> bool:
    """Use the Pallas path only on real TPU backends (the CPU test platform
    runs the pure-XLA stages).  BFS_TPU_PALLAS=0/1 overrides."""
    env = os.environ.get("BFS_TPU_PALLAS", "")
    if env in ("0", "1"):
        return env == "1"
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - backend init failure
        return False


def stage_distances(n: int) -> list[int]:
    """Element distance of every Beneš stage for an n-element network —
    delegates to graph/benes.py so there is one source of truth for the
    stage schedule shared with the native router."""
    from ..graph import benes

    return [benes.stage_distance(n, s) for s in range(benes.num_stages(n))]


def local_stage_run(n: int, tile_rows: int = TILE_ROWS) -> tuple[int, int]:
    """[lo, hi) stage-index range with element distance < tr*128 (tr = the
    EFFECTIVE tile rows, clamped to the network's row count) — the
    consecutive middle run pass B fuses."""
    tr = min(tile_rows, max(n // 32 // LANES, 1))
    dists = stage_distances(n)
    local = [s for s, d in enumerate(dists) if d < tr * LANES]
    if not local:
        return (0, 0)
    lo, hi = local[0], local[-1] + 1
    assert local == list(range(lo, hi)), "local stages must be consecutive"
    return (lo, hi)


def _kroll(x, shift: int, axis: int):
    """In-kernel roll by a STATIC shift (normalized positive).  Uses
    pltpu.roll — jnp.roll's closed_call lowering hits an MLIR cache bug
    when several Pallas kernels in one program contain same-shaped rolls."""
    from jax.experimental.pallas import tpu as pltpu

    size = x.shape[axis]
    return pltpu.roll(x, shift % size, axis)


def _stage_on_tile(x, m, d, *, nw, rows, lane_axis, row_axis, outer_axis,
                   outer_span, tr):
    """One butterfly stage on a VMEM-resident tile.

    ``rows``: size of the row axis inside the tile (pass B); ``outer_span``:
    size of the outer axis (pass A/C).  Exactly one regime applies per d.
    """
    if d >= nw:  # bit-plane butterfly: elementwise on every word
        sh = jnp.uint32(d // nw)
        t = (x ^ (x >> sh)) & m
        return x ^ t ^ (t << sh)
    if d < LANES:  # lane butterfly inside each 128-word row
        axis, dist = lane_axis, d
    elif d // LANES < tr:  # row butterfly inside the local tile (pass B)
        axis, dist = row_axis, d // LANES
    else:  # outer-block butterfly (pass A/C): partner block b ^ cb
        axis, dist = outer_axis, d // LANES // tr
    idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, axis)
    has = (idx & dist) != 0
    partner = jnp.where(
        has, _kroll(x, dist, axis), _kroll(x, -dist, axis)
    )
    m_both = jnp.where(has, _kroll(m, dist, axis), m)
    return x ^ ((x ^ partner) & m_both)


def _streamed_pass(x, masks, lo, dists, *, nw, tr, mode, interpret):
    """One fused pass: stages ``dists`` (= schedule[lo:lo+len]) with x
    VMEM-resident, masks DMA-streamed stage-by-stage with double buffering.
    ``masks`` is the FULL [all_stages, nw] array — the stage offset is
    applied inside the DMA index, because an XLA-level ``masks[lo:hi]``
    slice materializes a copy of hundreds of MB every superstep (profiler:
    ~10 ms/superstep of slice ops at net 2^28).

    mode 'local': x viewed [R, 128], grid over TR-row tiles.
    mode 'outer': x viewed [B, TR, 128], grid over tt-chunks of TR.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r = nw // LANES
    s_n = len(dists)
    s_all = masks.shape[0]

    if mode == "local":
        grid = (r // tr,)
        x_view = x.reshape(r, LANES)
        m_view = masks.reshape(s_all, r, LANES)
        block = (tr, LANES)
        x_spec = pl.BlockSpec(block, lambda i: (i, 0), memory_space=pltpu.VMEM)

        def dma(m_hbm, mbuf, sem, slot, si):
            i = pl.program_id(0)
            return pltpu.make_async_copy(
                m_hbm.at[lo + si, pl.ds(i * tr, tr), :],
                mbuf.at[slot],
                sem.at[slot],
            )

        def stage(x, m, d):
            return _stage_on_tile(
                x, m, d, nw=nw, rows=tr, lane_axis=1, row_axis=0,
                outer_axis=None, outer_span=None, tr=tr,
            )
    else:
        b = r // tr
        tt = min(OUTER_TT, tr)
        grid = (tr // tt,)
        x_view = x.reshape(b, tr, LANES)
        m_view = masks.reshape(s_all, b, tr, LANES)
        block = (b, tt, LANES)
        x_spec = pl.BlockSpec(block, lambda j: (0, j, 0), memory_space=pltpu.VMEM)

        def dma(m_hbm, mbuf, sem, slot, si):
            j = pl.program_id(0)
            return pltpu.make_async_copy(
                m_hbm.at[lo + si, :, pl.ds(j * tt, tt), :],
                mbuf.at[slot],
                sem.at[slot],
            )

        def stage(x, m, d):
            return _stage_on_tile(
                x, m, d, nw=nw, rows=None, lane_axis=2, row_axis=None,
                outer_axis=0, outer_span=b, tr=tr,
            )

    def kernel(x_ref, m_hbm, o_ref, mbuf, sem):
        dma(m_hbm, mbuf, sem, 0, 0).start()
        x = x_ref[:]
        for si, d in enumerate(dists):
            if si + 1 < s_n:
                dma(m_hbm, mbuf, sem, (si + 1) % 2, si + 1).start()
            dma(m_hbm, mbuf, sem, si % 2, si).wait()
            x = stage(x, mbuf[si % 2], d)
        o_ref[:] = x

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[x_spec, pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=x_spec,
        out_shape=jax.ShapeDtypeStruct(x_view.shape, jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((2,) + block, jnp.uint32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(x_view, m_view)
    return out.reshape(-1)


#: pack/unpack kernels engage above this bit count AND when the word count
#: divides evenly into _PACK_CHUNK-word grid steps (nw % 32768 == 0, i.e.
#: n % 2^20 == 0); other shapes take the XLA fallback.
PACK_KERNEL_MIN_BITS = 1 << 20
_PACK_CHUNK = 32768  # words per grid step: (32, 32768) uint8 block = 1 MB
# (plane rows sit nw bytes apart in HBM; 32 KB per row per step keeps the
# strided DMA in large transfers — 4 KB rows measured only ~15 GB/s)


def pack_kernel_ok(n: int) -> bool:
    return (
        pallas_enabled()
        and n >= PACK_KERNEL_MIN_BITS
        and (n // 32) % _PACK_CHUNK == 0
    )


@functools.partial(jax.jit, static_argnames=("n", "interpret"))
def pack_bits_pallas(bits: jax.Array, n: int, interpret: bool = False) -> jax.Array:
    """Bit-major pack as ONE Pallas kernel: uint8[n] -> uint32[n/32].

    Bit-major means word w bit b = element b*nw + w, i.e. plane b is the
    CONTIGUOUS byte range [b*nw, (b+1)*nw).  A (32, chunk)-block formulation
    reads 32 plane rows nw bytes apart — strided HBM traffic measured at
    only ~14 GB/s however large the chunk.  Instead the grid is
    (chunks, 32) with the plane index fastest: each step reads ONE
    contiguous plane chunk and ORs it (shifted) into the output word block,
    which Pallas keeps VMEM-resident across the 32 revisits (its block
    index only depends on the slow grid axis)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nw = n // 32
    rows = _PACK_CHUNK // LANES  # block = (rows, 128), tile-aligned
    nblk = nw // _PACK_CHUNK

    def kernel(x_ref, o_ref):
        b = pl.program_id(1)
        term = x_ref[:].astype(jnp.uint32) << b.astype(jnp.uint32)

        @pl.when(b == 0)
        def _():
            o_ref[:] = term

        @pl.when(b != 0)
        def _():
            o_ref[:] = o_ref[:] | term

    out = pl.pallas_call(
        kernel,
        grid=(nblk, 32),
        in_specs=[
            pl.BlockSpec(
                (rows, LANES),
                lambda i, b: (b * nblk + i, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (rows, LANES), lambda i, b: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((nw // LANES, LANES), jnp.uint32),
        interpret=interpret,
    )(bits.reshape(32 * nw // LANES, LANES))
    return out.reshape(-1)


@functools.partial(jax.jit, static_argnames=("n", "interpret"))
def unpack_bits_pallas(words: jax.Array, n: int, interpret: bool = False) -> jax.Array:
    """Bit-major unpack as ONE Pallas kernel: uint32[n/32] -> uint8[n].

    Mirror of :func:`pack_bits_pallas`: grid (chunks, 32), plane fastest;
    the word block is fetched once per chunk (its index ignores the plane
    axis) and each step writes one contiguous plane chunk."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nw = n // 32

    def kernel(x_ref, o_ref):
        b = pl.program_id(1)
        o_ref[:] = ((x_ref[:] >> b.astype(jnp.uint32)) & jnp.uint32(1)).astype(
            jnp.uint8
        )

    rows = _PACK_CHUNK // LANES
    nblk = nw // _PACK_CHUNK
    out = pl.pallas_call(
        kernel,
        grid=(nblk, 32),
        in_specs=[
            # Index ignores the plane axis -> the word block is fetched once
            # per chunk and reused for all 32 plane writes.
            pl.BlockSpec(
                (rows, LANES), lambda i, b: (i, 0), memory_space=pltpu.VMEM
            )
        ],
        out_specs=pl.BlockSpec(
            (rows, LANES), lambda i, b: (b * nblk + i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((32 * nw // LANES, LANES), jnp.uint8),
        interpret=interpret,
    )(words.reshape(nw // LANES, LANES))
    return out.reshape(-1)


@functools.partial(
    jax.jit, static_argnames=("n", "tile_rows", "interpret")
)
def apply_benes_fused(
    words: jax.Array, masks: jax.Array, *, n: int,
    tile_rows: int = TILE_ROWS, interpret: bool = False,
) -> jax.Array:
    """The full routed Beneš network (all 2·log2(n)-1 stages) in at most
    three fused Pallas passes.  ``words``: uint32[n/32] bit-major;
    ``masks``: uint32[stages, n/32] from ``benes.route(..., bit_major=True)``.
    """
    nw = n // 32
    r = nw // LANES
    tr = min(tile_rows, r)
    dists = stage_distances(n)
    lo, hi = local_stage_run(n, tile_rows)
    assert lo < hi, "no local run — network too small for the fused path"

    x = words
    if lo > 0:  # pass A: prefix outer stages (bit planes + big row rolls)
        x = _streamed_pass(
            x, masks, 0, dists[:lo], nw=nw, tr=tr, mode="outer",
            interpret=interpret,
        )
    # pass B: the local run
    x = _streamed_pass(
        x, masks, lo, dists[lo:hi], nw=nw, tr=tr, mode="local",
        interpret=interpret,
    )
    if hi < len(dists):  # pass C: suffix outer stages
        x = _streamed_pass(
            x, masks, hi, dists[hi:], nw=nw, tr=tr, mode="outer",
            interpret=interpret,
        )
    return x
