"""MXU-native frontier expansion: BFS as bit-packed masked matmul (ISSUE 15).

The third expansion arm next to the sparse gather (push) and the Beneš
relay pipeline (the gather-free pull): dense frontier levels expand as
tiled products of the frontier bitmap against the bit-packed 128x128
adjacency tiles of :mod:`bfs_tpu.graph.adj_tiles` — the BLEST /
graph-traversal-on-tensor-cores formulation, shaped for the TPU MXU.

Per (frontier row-block, adjacency tile) the kernel computes the
CONTRIBUTION MASKS as one matmul:

    FW[g, u]  = frontier_bit(u) * 2^(u mod 16)   for u // 16 == g   (8x128)
    M = FW @ A_tile                                                 (8x128)

``A_tile`` is the tile unpacked to 0/1 f32.  Each group sums at most 16
distinct powers of two < 2^16, so the f32 accumulation is EXACT and
``M[g, v]`` is literally the 16-bit bitmask of group-``g`` sources that
reach destination ``v`` — the matmul does the whole neighborhood
intersection.  The epilogue reduces each mask to the minimum ORIGINAL
source id (``keys2d``) and min-accumulates across the column's tiles:

    cand[v] = min over contributing frontier sources u of orig_id(u)

which is the canonical min-parent candidate every engine shares, emitted
as ``uint32`` with ``PACKED_SENTINEL`` where no source contributes — the
exact operand :func:`bfs_tpu.ops.relay.apply_relay_candidates_packed`
merges (the parent field carries the ORIGINAL id; models/bfs.py's mxu
finish decodes it without the rank->slot reconstruction).

Early-out: a tile whose 128-bit frontier block is all zero is SKIPPED
before its 2 KB DMA is even issued (``pl.when`` on the 4 preloaded
frontier words), so sparse-frontier supersteps touch no adjacency bytes —
though the direction optimizer routes those levels to the push arm anyway.

:func:`expand_frontier_mxu_xla` is the bit-identical XLA twin (the PAL005
parity oracle diffs raw bytes against it; it is also the shipping arm on
CPU backends and under ``vmap`` in the batched multi-source program —
min over uint32 keys is associative/commutative and exact, so any
evaluation order produces identical bytes).

Knobs::

    BFS_TPU_EXPANSION    auto | gather | mxu   (default auto)
    BFS_TPU_MXU_KERNEL   auto | pallas | xla   (default auto: pallas on
                         TPU backends, the XLA twin elsewhere)
    BFS_TPU_MXU_TILE_GB  float tile-storage budget for auto/mxu (default 4)
    BFS_TPU_TILES        resident | stream | auto   (default resident):
                         where the tile layout LIVES — device-resident, or
                         paged per-superblock from host RAM by demand
                         (bfs_tpu/stream, ISSUE 18); auto streams exactly
                         when the layout exceeds the stream cache budget
    BFS_TPU_STREAM_CACHE_GB  float HBM superblock-cache budget for the
                         streamed arm (default 1)
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from .. import knobs
from ..graph.adj_tiles import SB_TILES, SB_VERTS, TILE, TILE_WORDS

__all__ = [
    "EXPANSION_MODES",
    "TILES_MODES",
    "resolve_expansion",
    "resolve_mxu_kernel",
    "resolve_tiles_mode",
    "tiles_budget_bytes",
    "stream_cache_budget_bytes",
    "expand_frontier_mxu",
    "expand_frontier_mxu_xla",
    "mxu_device_operands",
    "mxu_superstep_packed",
    "mxu_superstep",
]

SENT = np.uint32(0xFFFFFFFF)  # == ops.packed.PACKED_SENTINEL
GROUPS = TILE // 16  # 8 weight groups of 16 rows; 2^0..2^15 exact in f32

EXPANSION_MODES = ("auto", "gather", "mxu")


def resolve_expansion(mode: str | None = None) -> str:
    """``BFS_TPU_EXPANSION`` (an explicit argument wins).  Raises on
    unknown modes — a typo'd knob must never silently change what a
    capture measured."""
    if mode is None:
        mode = knobs.get("BFS_TPU_EXPANSION")
    if mode not in EXPANSION_MODES:
        raise ValueError(
            f"unknown expansion {mode!r}; use 'auto', 'gather' or 'mxu'"
        )
    return mode


def resolve_mxu_kernel(kernel: str | None = None) -> str:
    """Which implementation the mxu arm's DENSE superstep compiles:
    ``pallas`` (the fused kernel; interpret-mode off-TPU — parity tests
    only, never a shipping loop) or ``xla`` (the twin).  ``auto`` follows
    the backend like every other per-phase kernel here."""
    if kernel is None:
        kernel = knobs.get("BFS_TPU_MXU_KERNEL")
    if kernel not in ("auto", "pallas", "xla"):
        raise ValueError(
            f"unknown mxu kernel {kernel!r}; use 'auto', 'pallas' or 'xla'"
        )
    if kernel == "auto":
        try:
            return "pallas" if jax.default_backend() == "tpu" else "xla"
        except Exception:  # pragma: no cover - backend init failure
            return "xla"
    return kernel


TILES_MODES = ("resident", "stream", "auto")


def resolve_tiles_mode(mode: str | None = None) -> str:
    """``BFS_TPU_TILES`` (an explicit argument wins): where the mxu arm's
    tile layout lives.  ``resident`` ships the whole layout to HBM at
    engine init (the ISSUE 15 behavior and the default); ``stream`` pages
    column superblocks from a pinned host store on frontier demand
    (bfs_tpu/stream, ISSUE 18); ``auto`` streams exactly when the layout
    exceeds :func:`stream_cache_budget_bytes` — the layout fits, keep it
    resident.  Raises on unknown modes, same contract as
    :func:`resolve_expansion`."""
    if mode is None:
        mode = knobs.get("BFS_TPU_TILES")
    if mode not in TILES_MODES:
        raise ValueError(
            f"unknown tiles mode {mode!r}; use 'resident', 'stream' or "
            "'auto'"
        )
    return mode


def stream_cache_budget_bytes() -> int:
    """HBM budget for the streamed arm's superblock cache
    (``BFS_TPU_STREAM_CACHE_GB``, default 1 GB) — the working-set ceiling
    the LRU accounts against, NOT a hard allocator limit (in-flight
    expands keep their operand references alive past eviction, exactly
    like the serve registry's resident map)."""
    return int(knobs.get("BFS_TPU_STREAM_CACHE_GB") * (1 << 30))


def tiles_budget_bytes() -> int:
    """Tile-storage ceiling for building the mxu layout
    (``BFS_TPU_MXU_TILE_GB``, default 4 GB): a scale-free tail can
    degrade toward one 2 KB tile per edge, and the arm must never OOM a
    host just by being probed."""
    return int(knobs.get("BFS_TPU_MXU_TILE_GB") * (1 << 30))


def mxu_device_operands(at) -> tuple:
    """Ship an :class:`~bfs_tpu.graph.adj_tiles.AdjTiles` layout as the
    fused programs' tile-operand tuple ``(tiles, row_idx, col_id,
    sb_indptr, keys2d)`` — the one pytree both the kernel and the twin
    consume (static geometry travels separately via
    :func:`mxu_static`)."""
    return (
        jnp.asarray(at.tiles),
        jnp.asarray(at.row_idx),
        jnp.asarray(at.col_id),
        jnp.asarray(at.sb_indptr),
        jnp.asarray(at.keys2d),
    )


def mxu_static(at) -> tuple:
    """Hashable geometry for program cache keys: (rows, cols, rtp, vtp,
    ntp)."""
    return (int(at.rows), int(at.cols), int(at.rtp), int(at.vtp),
            int(at.ntp))


def _pad_frontier_words(fwords: jax.Array, rows: int, rtp: int) -> jax.Array:
    """Frontier words padded to the row space + ONE zero pad block (the
    ``row_idx = rtp // TILE`` padding target reads guaranteed zeros)."""
    have = fwords.shape[-1]
    want = rtp // 32 + TILE // 32
    pad = jnp.zeros((*fwords.shape[:-1], want - have), jnp.uint32)
    return jnp.concatenate([fwords, pad], axis=-1)


# bfs_tpu: hot traced
def expand_frontier_mxu_xla(
    fwords: jax.Array, tile_ops: tuple, *, rows: int, cols: int, rtp: int,
    vtp: int, chunk: int = 256,
) -> jax.Array:
    """Bit-identical XLA twin of :func:`expand_frontier_mxu`:
    ``uint32[cols]`` min-original-id candidate per destination
    (``SENT`` where no frontier in-neighbor).  Tiles stream in
    ``chunk``-sized slabs through ``lax.map`` so the unpacked
    (chunk, 128, 128) contribution tensor never scales with the graph;
    uint32 min is exact and order-free, so chunking cannot perturb a
    bit."""
    tiles, row_idx, col_id, _sb, keys2d = tile_ops
    ntp = tiles.shape[0]
    nc = -(-ntp // chunk)
    npad = nc * chunk - ntp
    if npad:
        # Inert padding: the zero frontier pad block + the dropped
        # overflow column segment (graph/adj_tiles padding convention).
        tiles = jnp.concatenate(
            [tiles, jnp.zeros((npad, TILE, TILE_WORDS), jnp.uint32)]
        )
        row_idx = jnp.concatenate(
            [row_idx, jnp.full(npad, rtp // TILE, jnp.int32)]
        )
        col_id = jnp.concatenate(
            [col_id, jnp.full(npad, vtp // TILE, jnp.int32)]
        )
    fwp = _pad_frontier_words(fwords, rows, rtp)
    fblk = fwp.reshape(-1, TILE_WORDS)[row_idx]  # [ntp, 4]
    shifts = jnp.arange(32, dtype=jnp.uint32)

    def per_chunk(args):
        tk, fb, rk = args
        lane = jnp.arange(TILE, dtype=jnp.int32)
        fbits = (fb[:, lane >> 5] >> (lane & 31).astype(jnp.uint32)) & 1
        rowmask = jnp.uint32(0) - fbits  # 0 / ~0 per (tile, u)
        contrib = tk & rowmask[:, :, None]  # [chunk, 128, 4]
        bits = (contrib[:, :, :, None] >> shifts) & 1  # [chunk,128,4,32]
        keyrow = keys2d[rk]  # [chunk, 128]
        cand = jnp.min(
            jnp.where(
                bits != 0,
                keyrow[:, :, None, None],
                SENT,
            ),
            axis=1,
        )  # [chunk, 4, 32]
        return cand.reshape(-1, TILE)

    cands = jax.lax.map(
        per_chunk,
        (
            tiles.reshape(nc, chunk, TILE, TILE_WORDS),
            fblk.reshape(nc, chunk, TILE_WORDS),
            row_idx.reshape(nc, chunk),
        ),
    ).reshape(-1, TILE)
    out = jax.ops.segment_min(
        cands, col_id, num_segments=vtp // TILE + 1,
        indices_are_sorted=False,
    )[: vtp // TILE]
    return out.reshape(-1)[:cols]


def _mxu_kernel_factory():
    """One column-superblock per grid step; the per-tile inner loop DMAs
    the frontier block first and early-outs (no tile DMA, no matmul) when
    it is zero."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    # bfs_tpu: hot
    def kernel(sb_ref, cl_ref, row_ref, tiles_hbm, fblk_hbm, keys_hbm,
               o_ref, tbuf, fbuf, kbuf, sem):
        from jax.experimental.pallas import tpu as pltpu

        pid = pl.program_id(0)
        o_ref[...] = jnp.full((SB_TILES, TILE), SENT, jnp.uint32)
        t0 = sb_ref[pid]
        t1 = sb_ref[pid + 1]

        def body(t, carry):
            cp_f = pltpu.make_async_copy(
                fblk_hbm.at[t], fbuf.at[0], sem.at[0]
            )
            cp_f.start()
            cp_f.wait()
            nz = (fbuf[0] != 0).any()

            @pl.when(nz)
            def _():
                r = row_ref[t]
                cl = cl_ref[t]
                cp_t = pltpu.make_async_copy(
                    tiles_hbm.at[t], tbuf.at[0], sem.at[1]
                )
                cp_k = pltpu.make_async_copy(
                    keys_hbm.at[r], kbuf.at[0], sem.at[2]
                )
                cp_t.start()
                cp_k.start()
                cp_t.wait()
                cp_k.wait()
                tile = tbuf[0]  # [128, 4] uint32
                keys = kbuf[0]  # [128] uint32
                # Frontier bit + group weight per source row, as the
                # [GROUPS, 128] weighted LHS.  The word select unrolls
                # over the 4 static frontier words (no in-kernel gather).
                u = jax.lax.broadcasted_iota(jnp.int32, (GROUPS, TILE), 1)
                g = jax.lax.broadcasted_iota(jnp.int32, (GROUPS, TILE), 0)
                fbit = jnp.zeros((GROUPS, TILE), jnp.uint32)
                for j in range(TILE_WORDS):
                    fbit = jnp.where(
                        (u >> 5) == j,
                        (fbuf[0, j] >> (u & 31).astype(jnp.uint32)) & 1,
                        fbit,
                    )
                member = (u >> 4) == g
                fw = jnp.where(
                    member & (fbit == 1),
                    (jnp.uint32(1) << (u & 15).astype(jnp.uint32)),
                    jnp.uint32(0),
                ).astype(jnp.float32)
                # Tile unpack: [128, 128] 0/1 — word select unrolled over
                # the 4 static v-words, shifts per lane.
                vv = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 1)
                aw = jnp.zeros((TILE, TILE), jnp.uint32)
                for j in range(TILE_WORDS):
                    aw = jnp.where((vv >> 5) == j, tile[:, j][:, None], aw)
                a = ((aw >> (vv & 31).astype(jnp.uint32)) & 1).astype(
                    jnp.float32
                )
                # THE masked matmul: 16-bit contribution masks per group,
                # exact in f32 (sums of distinct powers of two < 2^16).
                m = jnp.dot(fw, a, preferred_element_type=jnp.float32)
                masks = m.astype(jnp.uint32)  # [GROUPS, 128]
                # Reduce each mask to the min ORIGINAL id; accumulate the
                # column minimum into this tile's output row.
                ii = jax.lax.broadcasted_iota(jnp.int32, (16, TILE), 0)
                cand = jnp.full((TILE,), SENT, jnp.uint32)
                for gi in range(GROUPS):
                    bits = (masks[gi][None, :] >> ii.astype(jnp.uint32)) & 1
                    kg = jax.lax.dynamic_slice_in_dim(keys, gi * 16, 16)
                    cand = jnp.minimum(
                        cand,
                        jnp.min(
                            jnp.where(bits == 1, kg[:, None], SENT), axis=0
                        ),
                    )
                cur = o_ref[pl.ds(cl, 1), :]
                o_ref[pl.ds(cl, 1), :] = jnp.minimum(cur, cand[None, :])

            return carry

        jax.lax.fori_loop(t0, t1, body, jnp.int32(0))

    return kernel


# bfs_tpu: hot traced
def expand_frontier_mxu(
    fwords: jax.Array, tile_ops: tuple, *, rows: int, cols: int, rtp: int,
    vtp: int, interpret: bool | None = None,
) -> jax.Array:
    """The fused Pallas expansion: ``uint32[cols]`` min-original-id
    candidates, bit-identical to :func:`expand_frontier_mxu_xla` (the
    PAL005 oracle pins raw bytes).  Grid = one 16384-destination column
    superblock per step (a (128, 128) uint32 output block — the PAL002
    ``mxu=True`` contract); tiles, frontier blocks and key rows stream
    via per-tile DMA with the empty-frontier early-out."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        from .relay_pallas import pallas_interpret

        interpret = pallas_interpret()
    tiles, row_idx, col_id, sb_indptr, keys2d = tile_ops
    col_local = (col_id % SB_TILES).astype(jnp.int32)
    fwp = _pad_frontier_words(fwords, rows, rtp)
    fblk = fwp.reshape(-1, TILE_WORDS)[row_idx]  # [ntp, 4]
    grid = vtp // SB_VERTS
    out = pl.pallas_call(
        _mxu_kernel_factory(),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # sb_indptr
            pl.BlockSpec(memory_space=pltpu.SMEM),  # col_local
            pl.BlockSpec(memory_space=pltpu.SMEM),  # row_idx
            pl.BlockSpec(memory_space=pl.ANY),  # tiles
            pl.BlockSpec(memory_space=pl.ANY),  # fblk
            pl.BlockSpec(memory_space=pl.ANY),  # keys2d
        ],
        out_specs=pl.BlockSpec((SB_TILES, TILE), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((vtp // TILE, TILE), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((1, TILE, TILE_WORDS), jnp.uint32),  # tile buf
            pltpu.VMEM((1, TILE_WORDS), jnp.uint32),  # frontier block
            pltpu.VMEM((1, TILE), jnp.uint32),  # key row
            pltpu.SemaphoreType.DMA((3,)),
        ],
        interpret=interpret,
    )(sb_indptr, col_local, row_idx, tiles, fblk, keys2d)
    return out.reshape(-1)[:cols]


def _expand(st_fwords, tile_ops, geo: tuple, use_kernel: bool):
    rows, cols, rtp, vtp, _ntp = geo
    if use_kernel:
        return expand_frontier_mxu(
            st_fwords, tile_ops, rows=rows, cols=cols, rtp=rtp, vtp=vtp
        )
    return expand_frontier_mxu_xla(
        st_fwords, tile_ops, rows=rows, cols=cols, rtp=rtp, vtp=vtp
    )


# bfs_tpu: hot traced
def mxu_superstep_packed(st, tile_ops, geo: tuple, use_kernel: bool):
    """One mxu pull superstep on the packed carry: expand -> one
    lexicographic min (the candidate's parent field is the ORIGINAL id —
    the mxu finish decodes it directly, no rank->slot pass)."""
    from . import relay as R

    cand = _expand(st.fwords, tile_ops, geo, use_kernel)
    return R.apply_relay_candidates_packed(st, cand)


# bfs_tpu: hot traced
def mxu_superstep(st, tile_ops, geo: tuple, use_kernel: bool):
    """Unpacked twin (the >62-level fallback carry): parent VALUES are
    original ids (INT32_MAX convention at the apply boundary)."""
    from . import relay as R
    from .relax import INT32_MAX

    cand = _expand(st.fwords, tile_ops, geo, use_kernel)
    cand_i = jnp.where(
        cand == SENT, jnp.int32(INT32_MAX), cand.astype(jnp.int32)
    )
    return R.apply_relay_candidates(st, cand_i)
