from .relax import BfsState, init_state, init_batched_state, relax_superstep, relax_superstep_batched, frontier_size, INT32_MAX  # noqa: F401
