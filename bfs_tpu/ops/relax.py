"""The frontier-relaxation op: one BFS superstep as XLA-friendly tensor math.

This is the TPU-native replacement for the reference's map+shuffle+reduce
superstep (BfsSpark.java:66-108):

  * mapper (flatMapToPair emitting GRAY neighbours at distance+1,
    BfsSpark.java:73-79)  ->  a gather of the frontier bitmap over edge
    sources; every active edge is a candidate relaxation at ``level + 1``.
  * shuffle + reducer monoid (min-distance, argmin-path, max-color,
    BfsSpark.java:90-108)  ->  ``jax.ops.segment_min`` over edge
    destinations.  Because all candidates in a level-synchronous superstep
    share the same distance ``level + 1``, the distance reduce degenerates to
    "any active in-edge?" and the path/parent reduce to "min source id" —
    one segmented min over int32, fully VPU-vectorised, deterministic.
  * GRAY->BLACK demotion + termination substring test (BfsSpark.java:80,117)
    ->  the new frontier is exactly the improved set; termination is
    ``~improved.any()``, an on-device scalar instead of a driver-side file
    scan.

Edges must be dst-sorted with sentinel padding (csr.build_device_graph) so
``indices_are_sorted=True`` holds and padded lanes are inert.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# NumPy (not jnp) scalar: a module-level jnp constant would initialize the
# JAX backend at import time, locking the platform before callers (tests,
# dryrun) can pin CPU.  Weak-typed at trace time exactly like jnp.int32.
INT32_MAX = np.int32(2**31 - 1)


class BfsState(NamedTuple):
    """Loop carry: the device-resident replacement for the ``problemFile_i``
    files the reference writes/re-reads every superstep (BfsSpark.java:62,116).

    Shapes are ``[V+1]`` — slot V is the inert sentinel for padded edges.
    ``dist`` uses INT32_MAX for unreached (Integer.MAX_VALUE parity);
    ``parent`` is -1 for unreached, self for sources.
    """

    dist: jax.Array  # int32[V+1]
    parent: jax.Array  # int32[V+1]
    frontier: jax.Array  # bool[V+1]
    level: jax.Array  # int32 scalar: current BFS level (supersteps done)
    changed: jax.Array  # bool scalar: did the last superstep relax anything?


def init_state(num_vertices: int, source, *, sentinel: bool = True) -> BfsState:
    """Iteration-0 state (GraphFileUtil.java:50-56 parity): source at
    distance 0 on the frontier (GRAY), everything else unreached (WHITE).

    ``sentinel=False`` sizes the arrays exactly ``[V]`` — for engines whose
    candidates never index through padded edges (relay), where the ``[V+1]``
    convention would force a 4-byte-per-vertex concatenate copy every
    superstep just to append the inert slot."""
    n = num_vertices + (1 if sentinel else 0)
    source = jnp.asarray(source, dtype=jnp.int32)
    dist = jnp.full((n,), INT32_MAX, dtype=jnp.int32).at[source].set(0)
    parent = jnp.full((n,), -1, dtype=jnp.int32).at[source].set(source)
    frontier = jnp.zeros((n,), dtype=bool).at[source].set(True)
    return BfsState(dist, parent, frontier, jnp.int32(0), jnp.bool_(True))


# bfs_tpu: hot traced
def apply_candidates(
    state: BfsState,
    cand_parent: jax.Array,
    *,
    batch_axis_name: str | None = None,
) -> BfsState:
    """Merge per-vertex candidate parents into the carry: the shared tail of
    every engine's superstep (the reducer's min-merge outcome applied to
    state, BfsSpark.java:90-108).  ``cand_parent`` is INT32_MAX where no
    active in-edge exists; only unreached vertices improve (level-synchronous
    BFS discovers each vertex exactly once)."""
    improved = (cand_parent != INT32_MAX) & (state.dist == INT32_MAX)
    new_level = state.level + 1
    dist = jnp.where(improved, new_level, state.dist)
    parent = jnp.where(improved, cand_parent, state.parent)
    changed = improved.any()
    if batch_axis_name is not None:
        changed = jax.lax.pmax(changed.astype(jnp.int32), batch_axis_name) > 0
    return BfsState(dist, parent, improved, new_level, changed)


# bfs_tpu: hot traced
def relax_superstep(
    state: BfsState,
    src: jax.Array,
    dst: jax.Array,
    *,
    axis_name: str | None = None,
) -> BfsState:
    """One level-synchronous superstep.

    With ``axis_name`` set, ``src``/``dst`` are this device's edge shard and
    the candidate arrays are merged across the mesh with ``lax.pmin`` — the
    ICI all-reduce that replaces the Spark shuffle + driver collect
    (SURVEY.md §2.5).  All devices then compute identical updates, keeping
    dist/parent/frontier replicated without further collectives.
    """
    num_segments = state.dist.shape[0]
    cand_parent = _push_candidates(state.frontier, src, dst, num_segments)
    if axis_name is not None:
        cand_parent = jax.lax.pmin(cand_parent, axis_name)
    return apply_candidates(state, cand_parent)


# bfs_tpu: hot traced
def combine_min(values, dst, num_segments: int) -> jax.Array:
    """THE semiring combine: one segmented min of per-edge contribution
    values over edge destinations (identity = the dtype's max sentinel).

    Every algorithm on the superstep machinery reduces through this one
    op — BFS contributes ``src`` ids (min-id parent), SSSP contributes
    ``dist[src] + w`` min-plus sums, connected components contributes
    ``label[src]`` (bfs_tpu/algo/substrate.py's semiring table).  Edges
    must be dst-sorted with sentinel padding (csr.build_device_graph) so
    ``indices_are_sorted=True`` holds and padded lanes are inert."""
    return jax.ops.segment_min(
        values, dst, num_segments=num_segments, indices_are_sorted=True
    )


def _push_candidates(frontier, src, dst, num_segments: int) -> jax.Array:
    """Min source id among active in-edges per destination; INT32_MAX where
    none (the mapper + reducer monoid as one segmented min) — BFS's
    instance of :func:`combine_min`."""
    active = frontier[src]
    return combine_min(
        jnp.where(active, src, INT32_MAX), dst, num_segments
    )


# ----------------------------------------------------------- packed state --
# The ``level:6 | parent:26`` fused-word carry (ops/packed.py): dist and
# parent collapse into one uint32 per vertex, halving the per-superstep
# state-update HBM bytes, and the improvement test + canonical min-parent
# tie-break collapse into one unsigned ``min``.  Engines run this by
# default (V permitting) and fall back to BfsState past PACKED_MAX_LEVELS.


class PackedBfsState(NamedTuple):
    """Packed loop carry: ``packed`` is uint32[V+1] (``level:6|parent:26``,
    all-ones unreached — ops/packed.py); other fields as in BfsState."""

    packed: jax.Array  # uint32[V+1]
    frontier: jax.Array  # bool[V+1]
    level: jax.Array
    changed: jax.Array


def init_packed_state(
    num_vertices: int, source, *, sentinel: bool = True
) -> PackedBfsState:
    """Packed twin of :func:`init_state`: source at level 0 with itself as
    parent (word ``0<<26 | source``), everything else the sentinel."""
    from .packed import PACKED_SENTINEL

    n = num_vertices + (1 if sentinel else 0)
    source = jnp.asarray(source, dtype=jnp.int32)
    packed = (
        jnp.full((n,), PACKED_SENTINEL, dtype=jnp.uint32)
        .at[source]
        .set(source.astype(jnp.uint32))
    )
    frontier = jnp.zeros((n,), dtype=bool).at[source].set(True)
    return PackedBfsState(packed, frontier, jnp.int32(0), jnp.bool_(True))


def init_packed_batched_state(num_vertices: int, sources) -> PackedBfsState:
    """Packed twin of :func:`init_batched_state` ([S, V+1] fields)."""
    from .packed import PACKED_SENTINEL

    n = num_vertices + 1
    sources = jnp.asarray(sources, dtype=jnp.int32)
    s = sources.shape[0]
    rows = jnp.arange(s)
    packed = (
        jnp.full((s, n), PACKED_SENTINEL, dtype=jnp.uint32)
        .at[rows, sources]
        .set(sources.astype(jnp.uint32))
    )
    frontier = jnp.zeros((s, n), dtype=bool).at[rows, sources].set(True)
    return PackedBfsState(packed, frontier, jnp.int32(0), jnp.bool_(True))


# bfs_tpu: hot traced
def apply_candidates_packed(
    state: PackedBfsState,
    cand_parent: jax.Array,
    *,
    batch_axis_name: str | None = None,
) -> PackedBfsState:
    """Packed tail of the push/pull supersteps: the candidate parent ids
    (int32, INT32_MAX where none) become packed words at ``level+1`` and
    merge with ONE lexicographic min — half the dist/parent HBM bytes of
    :func:`apply_candidates`, same canonical tie-break."""
    from .packed import PACKED_SENTINEL, level_word, merge_packed

    lev = level_word(state.level + 1)
    cand = jnp.where(
        cand_parent == INT32_MAX,
        jnp.uint32(PACKED_SENTINEL),
        cand_parent.astype(jnp.uint32) | lev,
    )
    packed = merge_packed(state.packed, cand)
    improved = packed != state.packed
    changed = improved.any()
    if batch_axis_name is not None:
        changed = jax.lax.pmax(changed.astype(jnp.int32), batch_axis_name) > 0
    return PackedBfsState(packed, improved, state.level + 1, changed)


# bfs_tpu: hot traced
def relax_superstep_packed(
    state: PackedBfsState,
    src: jax.Array,
    dst: jax.Array,
    *,
    axis_name: str | None = None,
) -> PackedBfsState:
    """Packed twin of :func:`relax_superstep` (same candidates, min-merge
    state update)."""
    num_segments = state.packed.shape[0]
    cand_parent = _push_candidates(state.frontier, src, dst, num_segments)
    if axis_name is not None:
        cand_parent = jax.lax.pmin(cand_parent, axis_name)
    return apply_candidates_packed(state, cand_parent)


# bfs_tpu: hot traced
def _batched_push_candidates(frontier, src, dst, num_segments: int):
    """Edge-major batched candidates: gather the frontier per EDGE
    (``frontier.T[src]`` -> (E, S)) and run ONE segment_min over the
    leading edge axis, transposing back at the end.  The vmap-over-rows
    spelling computed the same values but made XLA:CPU materialize a
    layout-changing (E, S) copy of the whole candidate buffer inside the
    while body every superstep (HLO003's first dogfood catch — E*S*4
    bytes/superstep of copy traffic); edge-major keeps the gather, the
    where and the scatter-min in one natural layout and the closing
    transpose fuses into the elementwise consumer."""
    active = frontier.T[src]  # (E, S)
    cand = jnp.where(active, src[:, None], INT32_MAX)
    return jax.ops.segment_min(
        cand, dst, num_segments=num_segments, indices_are_sorted=True
    ).T


# bfs_tpu: hot traced
def relax_superstep_batched_packed(
    state: PackedBfsState,
    src: jax.Array,
    dst: jax.Array,
    *,
    axis_name: str | None = None,
    batch_axis_name: str | None = None,
) -> PackedBfsState:
    """Packed twin of :func:`relax_superstep_batched`."""
    cand_parent = _batched_push_candidates(
        state.frontier, src, dst, state.packed.shape[-1]
    )
    if axis_name is not None:
        cand_parent = jax.lax.pmin(cand_parent, axis_name)
    return apply_candidates_packed(
        state, cand_parent, batch_axis_name=batch_axis_name
    )


def unpack_bfs_state(state: PackedBfsState) -> BfsState:
    """The ONCE-PER-RUN unpack at fused-loop exit (on device): packed words
    back to the int32 dist/parent contract every downstream consumer
    (oracle check, wire format, serve replies) already speaks."""
    from .packed import packed_dist, packed_parent

    return BfsState(
        dist=packed_dist(state.packed),
        parent=packed_parent(state.packed),
        frontier=state.frontier,
        level=state.level,
        changed=state.changed,
    )


def init_batched_state(
    num_vertices: int, sources: jax.Array, *, sentinel: bool = True
) -> BfsState:
    """Batched multi-source state: fields carry a leading sources axis
    ``[S, V+1]`` while ``level``/``changed`` stay scalar (all sources advance
    in lock-step supersteps).  The oracle's multi-source ctor seeds all
    sources at distance 0 (BreadthFirstPaths.java:114-132); batching them as
    a tensor axis instead is the vmap analogue (BASELINE.json config 5).
    ``sentinel`` as in :func:`init_state`."""
    n = num_vertices + (1 if sentinel else 0)
    sources = jnp.asarray(sources, dtype=jnp.int32)
    s = sources.shape[0]
    rows = jnp.arange(s)
    dist = jnp.full((s, n), INT32_MAX, dtype=jnp.int32).at[rows, sources].set(0)
    parent = jnp.full((s, n), -1, dtype=jnp.int32).at[rows, sources].set(sources)
    frontier = jnp.zeros((s, n), dtype=bool).at[rows, sources].set(True)
    return BfsState(dist, parent, frontier, jnp.int32(0), jnp.bool_(True))


# bfs_tpu: hot traced
def relax_superstep_batched(
    state: BfsState,
    src: jax.Array,
    dst: jax.Array,
    *,
    axis_name: str | None = None,
    batch_axis_name: str | None = None,
) -> BfsState:
    """Batched superstep over a leading sources axis.

    ``axis_name`` merges edge shards with ``pmin`` (graph/"context" axis);
    ``batch_axis_name`` reduces the termination flag across a sharded sources
    axis (data-parallel axis) so every device agrees on loop exit.
    """
    cand_parent = _batched_push_candidates(
        state.frontier, src, dst, state.dist.shape[-1]
    )
    if axis_name is not None:
        cand_parent = jax.lax.pmin(cand_parent, axis_name)
    return apply_candidates(state, cand_parent, batch_axis_name=batch_axis_name)


def frontier_size(state: BfsState) -> jax.Array:
    """Number of GRAY vertices — the per-superstep metric the reference can
    only obtain by scanning the serialized file (BfsSpark.java:117)."""
    return state.frontier.sum(dtype=jnp.int32)
