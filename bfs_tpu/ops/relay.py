"""Relay superstep: broadcast -> Beneš bit routing -> class row-min.

The gather-free BFS superstep over a :class:`~bfs_tpu.graph.relay.RelayGraph`
layout.  Every op here is dense (elementwise / reshape / broadcast / reduce)
— the only data-dependent values are the bits themselves, never an index.
See graph/relay.py for the measured rationale and the layout; conventions of
the butterfly stages are shared with native/benes.cpp.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .relax import INT32_MAX, BfsState, apply_candidates


def pack_bits(bits: jax.Array) -> jax.Array:
    """uint8/bool[n] -> uint32[n/32] little-endian (n a multiple of 32)."""
    b = bits.reshape(-1, 32).astype(jnp.uint32)
    return (b << jnp.arange(32, dtype=jnp.uint32)).sum(axis=1, dtype=jnp.uint32)


def unpack_bits(words: jax.Array) -> jax.Array:
    """uint32[n/32] -> uint8[n]."""
    return (
        ((words[:, None] >> jnp.arange(32, dtype=jnp.uint32)) & 1)
        .astype(jnp.uint8)
        .reshape(-1)
    )


def apply_benes(words: jax.Array, masks: jax.Array, n: int) -> jax.Array:
    """Apply a routed Beneš network to bit-packed words.

    ``words``: uint32[n/32]; ``masks``: uint32[stages, n/32] from
    :func:`bfs_tpu.graph.benes.route`.  Stage ``s`` swaps bit pairs at
    distance ``d_s``; for ``d >= 32`` that is a word-block swap, for
    ``d < 32`` an intra-word butterfly — all elementwise, ~3 ops per word
    per stage.
    """
    k = int(n).bit_length() - 1
    x = words
    for s in range(2 * k - 1):
        d = n >> (s + 1) if s < k else n >> (2 * k - 1 - s)
        m = masks[s]
        if d >= 32:
            dw = d // 32
            xr = x.reshape(-1, 2, dw)
            lo = xr[:, 0, :]
            hi = xr[:, 1, :]
            mlo = m.reshape(-1, 2, dw)[:, 0, :]
            t = (lo ^ hi) & mlo
            x = jnp.stack([lo ^ t, hi ^ t], axis=1).reshape(-1)
        else:
            t = (x ^ (x >> jnp.uint32(d))) & m
            x = x ^ t ^ (t << jnp.uint32(d))
    return x


def relay_candidates(
    frontier: jax.Array,
    *,
    num_vertices: int,
    vperm_masks: jax.Array,
    vperm_size: int,
    out_classes,
    net_masks: jax.Array,
    net_size: int,
    m2: int,
    in_classes,
    src_l1_parts,
) -> jax.Array:
    """Min active ORIGINAL-id in-neighbour per (relabeled) vertex: int32[V].

    ``frontier``: bool[V+1] in relabeled vertex order (sentinel slot
    ignored).  ``src_l1_parts``: per-in-class int32[Nc, Wc] original-id
    tables with INF padding.
    """
    v = num_vertices
    fbits = frontier[:v].astype(jnp.uint8)
    fbits = jnp.concatenate(
        [fbits, jnp.zeros(vperm_size - v, dtype=jnp.uint8)]
    )
    fout = unpack_bits(apply_benes(pack_bits(fbits), vperm_masks, vperm_size))

    parts = []
    for cs in out_classes:
        blk = fout[cs.va : cs.vb]
        parts.append(
            jnp.broadcast_to(blk[:, None], (cs.vb - cs.va, cs.width)).reshape(-1)
        )
    parts.append(jnp.zeros(net_size - m2, dtype=jnp.uint8))
    l2 = jnp.concatenate(parts)

    l1bits = unpack_bits(apply_benes(pack_bits(l2), net_masks, net_size))

    cands = []
    for cs, src_tab in zip(in_classes, src_l1_parts):
        bits = l1bits[cs.sa : cs.sb].reshape(-1, cs.width)
        cands.append(jnp.min(jnp.where(bits != 0, src_tab, INT32_MAX), axis=1))
    return jnp.concatenate(cands)


def relay_superstep(state: BfsState, cand_fn) -> BfsState:
    """One superstep given ``cand_fn(frontier) -> int32[V]`` candidates.

    NOTE: ``state`` lives in the RELABELED vertex space; ``cand`` VALUES are
    original ids (the canonical min-parent), which the loop never indexes
    with — only the engine wrapper maps spaces at the end.
    """
    cand = cand_fn(state.frontier)
    cand = jnp.concatenate([cand, jnp.full((1,), INT32_MAX, jnp.int32)])
    return apply_candidates(state, cand)
