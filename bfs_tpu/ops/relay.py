"""Relay superstep: broadcast -> Beneš bit routing -> class row-min.

The gather-free BFS superstep over a :class:`~bfs_tpu.graph.relay.RelayGraph`
layout.  Every op here is dense (elementwise / reshape / broadcast / reduce)
— the only data-dependent values are the bits themselves, never an index.
See graph/relay.py for the measured rationale and the layout.

TPU layout discipline (the whole point of this module): every 2-D view
keeps a LARGE trailing dimension, because (8,128) tiling pads small
trailing dims ~100x (measured ~50x slowdown on naive reshapes):

  * bits pack **bit-major**: element ``e`` lives at (word ``e % nw``, bit
    ``e // nw``), so pack/unpack are a 32-way reduce/concat over full-size
    word arrays — never a ``[nw, 32]`` view.  native/benes.cpp emits masks
    in the same layout (``route(..., bit_major=True)``).
  * butterfly stages run on a fixed ``[R, 128]`` word view: intra-word
    shifts for bit-level pairs, lane-rolls for word distance < 128, and
    sublane-preserving row-block reshapes above that.
  * degree-class phases choose vertex-major or rank-major slot order per
    class (ClassSlice.vertex_major) so broadcast/reduce views are
    ``[small, large]``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .relax import INT32_MAX, BfsState, apply_candidates

LANES = 128
#: Networks smaller than this run the simple unpacked element path.
MIN_PACKED_BITS = 32 * LANES * 2


def pack_bits(bits: jax.Array, n: int) -> jax.Array:
    """uint8/bool[..., n] -> uint32[..., n/32], bit-major (element e -> word
    e % nw); broadcasts over leading axes.

    Two-level pack keeps the traffic narrow (measured 76 ms -> ~4 ms on the
    2^29-slot net): rows combine 8-at-a-time IN uint8 (no 4-byte widening of
    the full bit array), then the four byte planes widen and OR — bit b of
    word w is element b*nw + w, so byte plane k holds rows 8k..8k+7.
    This is THE packed-word convention: ops/pull.py's frontier blocks and
    native/benes.cpp's masks use the same layout."""
    nw = max(n // 32, 1)
    lead = bits.shape[:-1]
    if n <= 32:
        b = bits.astype(jnp.uint32)
        shifts = jnp.arange(n, dtype=jnp.uint32)
        return (b << shifts).sum(axis=-1, dtype=jnp.uint32)[..., None]
    from .benes_pallas import pack_bits_pallas, pack_kernel_ok

    if not lead and pack_kernel_ok(n):
        return pack_bits_pallas(bits.astype(jnp.uint8), n)
    b = bits.reshape(*lead, 4, 8, nw).astype(jnp.uint8)
    shifts8 = jnp.arange(8, dtype=jnp.uint8)[:, None]
    planes = (b << shifts8).sum(axis=-2, dtype=jnp.uint8).astype(jnp.uint32)
    return (
        planes[..., 0, :]
        | (planes[..., 1, :] << 8)
        | (planes[..., 2, :] << 16)
        | (planes[..., 3, :] << 24)
    )


def pack_bits_host(bits: np.ndarray, n: int) -> np.ndarray:
    """NumPy twin of :func:`pack_bits` (same bit-major layout): uint8/bool[n]
    -> uint32[n/32].  Used host-side to precompute static word masks (e.g.
    the valid-slot mask) without touching the device."""
    bits = np.asarray(bits, dtype=np.uint8)
    if n <= 32:
        word = np.uint32(0)
        for b in range(n):
            word |= np.uint32(bits[b]) << np.uint32(b)
        return np.array([word], dtype=np.uint32)
    nw = n // 32
    planes = bits.reshape(32, nw)
    words = np.zeros(nw, dtype=np.uint32)
    for b in range(32):  # 32 cheap passes instead of one 32x-widened temp
        words |= planes[b].astype(np.uint32) << np.uint32(b)
    return words


def unpack_bits(words: jax.Array, n: int) -> jax.Array:
    """uint32[n/32] -> uint8[n], bit-major."""
    if n <= 32:
        return ((words[0] >> jnp.arange(n, dtype=jnp.uint32)) & 1).astype(jnp.uint8)
    from .benes_pallas import pack_kernel_ok, unpack_bits_pallas

    if words.ndim == 1 and pack_kernel_ok(n):
        return unpack_bits_pallas(words, n)
    shifts = jnp.arange(32, dtype=jnp.uint32)[:, None]
    return ((words[None, :] >> shifts) & 1).astype(jnp.uint8).reshape(-1)


def _apply_benes_small(words: jax.Array, masks: jax.Array, n: int) -> jax.Array:
    """Unpacked element-space applier for tiny networks (test graphs)."""
    k = int(n).bit_length() - 1
    x = unpack_bits(words, n)
    for s in range(2 * k - 1):
        d = n >> (s + 1) if s < k else n >> (2 * k - 1 - s)
        me = unpack_bits(masks[s], n).reshape(-1, 2, d)[:, 0, :]
        xr = x.reshape(-1, 2, d)
        lo, hi = xr[:, 0, :], xr[:, 1, :]
        t = (lo ^ hi) & me
        x = jnp.stack([lo ^ t, hi ^ t], axis=1).reshape(-1)
    return pack_bits(x, n)


def apply_benes(words: jax.Array, masks: jax.Array, n: int) -> jax.Array:
    """Apply a routed Beneš network to bit-major packed words.

    ``words``: uint32[n/32]; ``masks``: uint32[stages, n/32] from
    ``benes.route(perm, bit_major=True)``.  Stage ``s`` swaps element pairs
    at distance ``d_s``; in the bit-major layout an element distance ``d``
    means a word-index distance ``d`` when ``d < nw`` and a bit-position
    distance ``d // nw`` otherwise.
    """
    k = int(n).bit_length() - 1
    nw = n // 32
    if n < MIN_PACKED_BITS:
        return _apply_benes_small(words, masks, n)

    from .benes_pallas import apply_benes_fused, pallas_enabled

    if pallas_enabled():
        # Whole network in <= 3 fused Pallas passes (x VMEM-resident,
        # masks DMA-streamed); the per-stage loop below is the portable
        # XLA fallback for CPU platforms.
        return apply_benes_fused(words, masks, n=n)

    r = nw // LANES
    x = words.reshape(r, LANES)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (r, 1), 0)
    for s in range(2 * k - 1):
        d = n >> (s + 1) if s < k else n >> (2 * k - 1 - s)
        m = masks[s].reshape(r, LANES)
        if d >= nw:
            sh = jnp.uint32(d // nw)  # bit-position butterfly, elementwise
            t = (x ^ (x >> sh)) & m
            x = x ^ t ^ (t << sh)
        elif d < LANES:
            # Word pairs in the same 128-lane row: partner lane = lane ^ d.
            has_bit = (lane & d) != 0
            partner = jnp.where(
                has_bit, jnp.roll(x, d, axis=1), jnp.roll(x, -d, axis=1)
            )
            # Mask bits sit at the lower lane of each pair; mirror them onto
            # the upper lane so one xor fixes both sides.
            m_both = jnp.where(has_bit, jnp.roll(m, d, axis=1), m)
            x = x ^ ((x ^ partner) & m_both)
        else:
            br = d // LANES  # partner row = row ^ br; same roll+select form
            has_bit = (row & br) != 0
            partner = jnp.where(
                has_bit, jnp.roll(x, br, axis=0), jnp.roll(x, -br, axis=0)
            )
            m_both = jnp.where(has_bit, jnp.roll(m, br, axis=0), m)
            x = x ^ ((x ^ partner) & m_both)
    return x.reshape(-1)


def valid_slot_words(src_l1: np.ndarray, net_size: int) -> np.ndarray:
    """Static valid-slot bitmask for :func:`relay_candidates`:
    uint32[net_size/32], bit set iff that L1 slot holds a REAL edge.

    The Beneš pad-routing may deliver stray 1-bits to padded row slots
    (pad_perm wires unused outputs to arbitrary unused inputs, some of which
    are broadcast copies of live frontier bits).  The old int32 src table
    made those inert via INF entries; with iota slot candidates the mask
    must zero them before the row-min instead."""
    bits = np.zeros(net_size, dtype=np.uint8)
    m1 = src_l1.shape[0]
    bits[:m1] = src_l1 != np.int32(INT32_MAX)
    return pack_bits_host(bits, net_size)


def relay_candidates(
    frontier: jax.Array,
    *,
    num_vertices: int,
    vperm_masks: jax.Array,
    vperm_size: int,
    out_classes,
    net_masks: jax.Array,
    net_size: int,
    m2: int,
    in_classes,
    valid_words: jax.Array,
) -> jax.Array:
    """Min active in-edge SLOT per (relabeled) vertex: int32[V].

    ``frontier``: bool[V+1] in relabeled vertex order (sentinel slot
    ignored).  Candidate VALUES are global L1 slot indices, not src ids:
    within a dst row, slots are filled in ascending ORIGINAL src-id order
    (graph/relay.py ord1 lexsort), so min active slot == min active src id —
    the canonical min-parent tie-break survives, while the hot loop never
    reads the int32 src table (~4 bytes/edge/superstep saved).  Engines map
    slot -> original src id once on the host via ``RelayGraph.src_l1``.
    ``valid_words``: static bitmask from :func:`valid_slot_words`.
    """
    v = num_vertices
    fbits = frontier[:v].astype(jnp.uint8)
    fbits = jnp.concatenate([fbits, jnp.zeros(vperm_size - v, dtype=jnp.uint8)])
    return relay_candidates_packed(
        pack_bits(fbits, vperm_size),
        vperm_masks=vperm_masks,
        vperm_size=vperm_size,
        out_classes=out_classes,
        net_masks=net_masks,
        net_size=net_size,
        m2=m2,
        in_classes=in_classes,
        valid_words=valid_words,
    )


def _class_slot_iota(cs) -> jax.Array:
    """Global L1 slot index per position of one in-class view — generated
    on-chip (broadcasted_iota), zero HBM traffic."""
    if cs.vertex_major:  # view [Nc, w], slot = sa + p*w + r
        p = jax.lax.broadcasted_iota(jnp.int32, (cs.count, cs.width), 0)
        r = jax.lax.broadcasted_iota(jnp.int32, (cs.count, cs.width), 1)
        return cs.sa + p * cs.width + r
    # view [w, Nc], slot = sa + r*Nc + p
    r = jax.lax.broadcasted_iota(jnp.int32, (cs.width, cs.count), 0)
    p = jax.lax.broadcasted_iota(jnp.int32, (cs.width, cs.count), 1)
    return cs.sa + r * cs.count + p


def relay_candidates_packed(
    fwords: jax.Array,
    *,
    vperm_masks: jax.Array,
    vperm_size: int,
    out_classes,
    net_masks: jax.Array,
    net_size: int,
    m2: int,
    in_classes,
    valid_words: jax.Array,
) -> jax.Array:
    """:func:`relay_candidates` from ALREADY-PACKED frontier words
    (uint32[vperm_size/32]).  The sharded engine feeds the bit-packed
    frontier all-gather here directly — the per-shard vperm network's routed
    permutation absorbs the gathered block layout, so no unpack/repack sits
    between the ICI exchange and the butterflies."""
    fout = unpack_bits(
        apply_benes(fwords, vperm_masks, vperm_size), vperm_size
    )

    parts = []
    for cs in out_classes:
        blk = fout[cs.va : cs.vb]
        if cs.vertex_major:  # slot = p*w + r -> view [Nc, w]
            parts.append(
                jnp.broadcast_to(blk[:, None], (cs.count, cs.width)).reshape(-1)
            )
        else:  # slot = r*Nc + p -> view [w, Nc]
            parts.append(
                jnp.broadcast_to(blk[None, :], (cs.width, cs.count)).reshape(-1)
            )
    parts.append(jnp.zeros(net_size - m2, dtype=jnp.uint8))
    l2 = jnp.concatenate(parts)

    l1words = apply_benes(pack_bits(l2, net_size), net_masks, net_size)
    l1bits = unpack_bits(l1words & valid_words, net_size)

    cands = []
    for cs in in_classes:
        seg = l1bits[cs.sa : cs.sb]
        if cs.vertex_major:
            bits = seg.reshape(cs.count, cs.width)
            cands.append(
                jnp.min(jnp.where(bits != 0, _class_slot_iota(cs), INT32_MAX), axis=1)
            )
        else:
            bits = seg.reshape(cs.width, cs.count)
            cands.append(
                jnp.min(jnp.where(bits != 0, _class_slot_iota(cs), INT32_MAX), axis=0)
            )
    return jnp.concatenate(cands)


def relay_superstep(state: BfsState, cand_fn) -> BfsState:
    """One superstep given ``cand_fn(frontier) -> int32[V]`` candidates.

    NOTE: ``state`` lives in the RELABELED vertex space; ``cand`` VALUES are
    L1 slot indices (min active slot == canonical min-parent, see
    :func:`relay_candidates`), which the loop never indexes with — engine
    wrappers map slot -> original src id at the end (models/bfs.py
    ``slots_to_parent``).
    """
    cand = cand_fn(state.frontier)
    if cand.shape[-1] != state.dist.shape[-1]:
        # [V+1] sentinel-carrying state (stepped runner) pads the inert slot;
        # the fused engines run exact [V] shapes and skip this copy.
        cand = jnp.concatenate([cand, jnp.full((1,), INT32_MAX, jnp.int32)])
    return apply_candidates(state, cand)
