"""Relay superstep v4: broadcast -> Beneš bit routing -> class row-min.

The gather-free BFS superstep over a :class:`~bfs_tpu.graph.relay.RelayGraph`
layout.  Every op here is dense (elementwise / reshape / broadcast / reduce)
— the only data-dependent values are the bits themselves, never an index.
See graph/relay.py for the measured rationale and the v4 layout.

Everything uses STANDARD (word-major) packing: element ``e`` at word
``e >> 5``, bit ``e & 31`` — the layout the native router emits and the one
where 32-aligned degree classes make the broadcast a word replication and
the row-min a word-level scan (no pack/unpack kernels anywhere, unlike the
round-2 bit-major layout).

This module is the portable XLA reference path (CPU tests, sharded CPU
matrix, fallback).  On real TPUs the same math runs as fused Pallas passes
(:mod:`bfs_tpu.ops.relay_pallas`), bit-exact against this implementation.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..graph.relay import StageSpec
from .relax import INT32_MAX

__all__ = [
    "RelayState",
    "PackedRelayState",
    "init_relay_state",
    "init_packed_relay_state",
    "pack_std",
    "unpack_std",
    "apply_benes_std",
    "broadcast_l2",
    "rowmin_candidates",
    "rowmin_ranks",
    "apply_relay_candidates",
    "apply_relay_candidates_packed",
    "unpack_relay_packed",
    "relay_superstep_words",
    "relay_superstep_words_packed",
    "segment_live",
    "relay_segment_words",
    "relay_segment_words_packed",
]


class RelayState(NamedTuple):
    """Relay-engine loop carry, all in the RELABELED vertex space of size vr.

    ``dist``: int32[vr] (INT32_MAX unreached); ``parent``: int32[vr] L1 SLOT
    index of the parent edge (-1 unreached; the source's self-entry holds its
    relabeled id and is fixed up host-side); ``fwords``: uint32[vr/32]
    frontier bits, standard packing — fed to the vperm network directly.

    This is the UNPACKED carry: the observability path (SuperstepRunner)
    and the >PACKED_MAX_LEVELS fallback run it; the fused hot path carries
    :class:`PackedRelayState` and unpacks to this shape once at loop exit.
    """

    dist: jax.Array
    parent: jax.Array
    fwords: jax.Array
    level: jax.Array
    changed: jax.Array


class PackedRelayState(NamedTuple):
    """Packed loop carry (the hot path): ``packed`` is uint32[vr] of
    ``level:6 | parent_rank:26`` words (ops/packed.py) — the parent field
    holds the within-row RANK the row-min tournament natively produces
    (slot = base + rank*stride, graph/relay._vertex_tables), reconstructed
    to L1 slots once per run by :func:`unpack_relay_packed`."""

    packed: jax.Array  # uint32[vr]
    fwords: jax.Array  # uint32[vr/32]
    level: jax.Array
    changed: jax.Array


def init_relay_state(vr: int, source_new) -> RelayState:
    source_new = jnp.asarray(source_new, dtype=jnp.int32)
    dist = jnp.full((vr,), INT32_MAX, jnp.int32).at[source_new].set(0)
    parent = jnp.full((vr,), -1, jnp.int32).at[source_new].set(source_new)
    fwords = (
        jnp.zeros((vr // 32,), jnp.uint32)
        .at[source_new >> 5]
        .set(jnp.uint32(1) << (source_new & 31).astype(jnp.uint32))
    )
    return RelayState(dist, parent, fwords, jnp.int32(0), jnp.bool_(True))


def init_packed_relay_state(vr: int, source_new) -> PackedRelayState:
    """Packed twin of :func:`init_relay_state`: the source's word is
    ``level 0 | rank 0`` (any non-sentinel parent works there — callers fix
    the source's self-parent up host-side exactly as on the unpacked
    path)."""
    from .packed import PACKED_SENTINEL

    source_new = jnp.asarray(source_new, dtype=jnp.int32)
    packed = (
        jnp.full((vr,), PACKED_SENTINEL, jnp.uint32)
        .at[source_new]
        .set(jnp.uint32(0))
    )
    fwords = (
        jnp.zeros((vr // 32,), jnp.uint32)
        .at[source_new >> 5]
        .set(jnp.uint32(1) << (source_new & 31).astype(jnp.uint32))
    )
    return PackedRelayState(packed, fwords, jnp.int32(0), jnp.bool_(True))


def pack_std(bits: jax.Array) -> jax.Array:
    """bool/uint8[..., n] -> uint32[..., n/32], standard packing (element e
    -> word e>>5, bit e&31).  XLA reference; fine on CPU, the TPU path packs
    in-kernel instead."""
    lead = bits.shape[:-1]
    n = bits.shape[-1]
    b = bits.reshape(*lead, n // 32, 32).astype(jnp.uint32)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return (b << shifts).sum(axis=-1, dtype=jnp.uint32)


def unpack_std(words: jax.Array, n: int) -> jax.Array:
    """uint32[n/32] -> uint8[n], standard packing."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return (
        ((words[..., :, None] >> shifts) & 1).astype(jnp.uint8).reshape(
            *words.shape[:-1], n
        )
    )


def pack_std_host(bits: np.ndarray) -> np.ndarray:
    """NumPy twin of :func:`pack_std` for host-side precomputes."""
    b = np.asarray(bits, dtype=bool).reshape(-1, 32)
    return np.packbits(b, axis=1, bitorder="little").view(np.uint32).reshape(-1)


def _stage_slice(masks_flat: jax.Array, st: StageSpec) -> jax.Array:
    return jax.lax.slice_in_dim(masks_flat, st.offset, st.offset + st.nwords)


LANES = 128


# bfs_tpu: hot traced
def apply_benes_std(
    words: jax.Array, masks_flat: jax.Array, table: tuple[StageSpec, ...],
    n: int,
) -> jax.Array:
    """Apply a routed Beneš network to standard-packed words (XLA path).

    ``masks_flat``/``table`` come from the v4 layout: per-stage storage is
    either full (n/32 words; only bits/words at the lower pair index are
    nonzero) or pair-compacted (n/64 words, d >= COMPACT_MIN_D).  Stage
    ``s`` swaps element pairs at distance ``d``: intra-word bit shifts for
    d < 32, word-pair butterflies above.

    Large networks use a roll-form on a fixed [r, 128] view: lane rolls for
    word distances < 128, row rolls above, with pair-compacted masks
    broadcast-expanded along the pair axis.  Every intermediate keeps a
    128-lane trailing dim — the naive ``reshape(-1, 2, dw)`` pairing tiles
    catastrophically on TPU for small dw (a (..,2,2) u32 reshape at net 2^26
    materializes 19.8 GB of padding).
    """
    nw = n // 32
    if nw < 2 * LANES:
        return _apply_benes_std_small(words, masks_flat, table, n)
    r = nw // LANES
    x = words.reshape(r, LANES)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (r, 1), 0)
    for st in table:
        m = _stage_slice(masks_flat, st)
        d = st.d
        if d < 32:
            sh = jnp.uint32(d)
            mv = m.reshape(r, LANES)
            t = (x ^ (x >> sh)) & mv
            x = x ^ t ^ (t << sh)
            continue
        dw = d >> 5
        if dw < LANES:  # lane butterfly; full storage, bits at lower lanes
            mv = m.reshape(r, LANES)
            has = (lane & dw) != 0
            partner = jnp.where(
                has, jnp.roll(x, dw, axis=1), jnp.roll(x, -dw, axis=1)
            )
            m_both = jnp.where(has, jnp.roll(mv, dw, axis=1), mv)
            x = x ^ ((x ^ partner) & m_both)
        else:  # row butterfly; pair-compacted storage, broadcast-expanded
            rw = dw // LANES
            a = r // (2 * rw)
            m_both = jnp.broadcast_to(
                m.reshape(a, 1, rw, LANES), (a, 2, rw, LANES)
            ).reshape(r, LANES)
            has = (row & rw) != 0
            partner = jnp.where(
                has, jnp.roll(x, rw, axis=0), jnp.roll(x, -rw, axis=0)
            )
            x = x ^ ((x ^ partner) & m_both)
    return x.reshape(-1)


def _apply_benes_std_small(
    words: jax.Array, masks_flat: jax.Array, table: tuple[StageSpec, ...],
    n: int,
) -> jax.Array:
    """Reshape-form applier for small networks (tests / tiny graphs)."""
    x = words
    for st in table:
        m = _stage_slice(masks_flat, st)
        d = st.d
        if d < 32:
            sh = jnp.uint32(d)
            t = (x ^ (x >> sh)) & m
            x = x ^ t ^ (t << sh)
        else:
            dw = d >> 5
            if st.compact:
                mv = m.reshape(-1, dw)
            else:
                mv = m.reshape(-1, 2, dw)[:, 0, :]
            xr = x.reshape(-1, 2, dw)
            lo, hi = xr[:, 0, :], xr[:, 1, :]
            t = (lo ^ hi) & mv
            x = jnp.stack([lo ^ t, hi ^ t], axis=1).reshape(-1)
    return x


def broadcast_l2(
    ywords: jax.Array, out_classes, net_size: int, out_space: int
) -> jax.Array:
    """Vperm-output words (out-position space, standard packing) -> L2 slot
    words.  Rank-major classes replicate whole words (slot = sa + r*count + p:
    each rank's 32-slot word IS the class's position-bit word); the few
    vertex-major classes fill width/32 words per position bit."""
    parts = []
    for cs in out_classes:
        if not cs.vertex_major:
            cw = cs.count // 32
            blk = jax.lax.slice_in_dim(ywords, cs.va // 32, cs.va // 32 + cw)
            parts.append(jnp.tile(blk, cs.width))
        else:
            # arbitrary (possibly unaligned) va: extract the few bits
            pos = cs.va + jnp.arange(cs.count)
            bits = (ywords[pos >> 5] >> (pos & 31).astype(jnp.uint32)) & 1
            fill = (jnp.uint32(0) - bits).astype(jnp.uint32)  # 0 or ~0
            parts.append(jnp.repeat(fill, cs.width // 32))
    used = sum(int(p.shape[0]) for p in parts)
    parts.append(jnp.zeros(net_size // 32 - used, jnp.uint32))
    return jnp.concatenate(parts)


def _ctz32(word: jax.Array) -> jax.Array:
    """Count trailing zeros of nonzero uint32 words."""
    low = word & (jnp.uint32(0) - word)
    return jax.lax.population_count(low - 1).astype(jnp.int32)


def _word_tournament(wv: jax.Array):
    """Min-row-index reduce over packed word rows: wv uint32[rows, cw] ->
    (found word row [cw], rank bit-plane word rows list low..high).

    Pure word-level elementwise merges in log2(rows) rounds — the unpack-free
    formulation that keeps the XLA rowmin at word bandwidth (the naive
    per-bit unpack materializes 8x the class bytes and dominated the
    round-3 superstep profile)."""
    rows, cw = wv.shape
    p2 = 1 << max((int(rows) - 1).bit_length(), 0)
    if p2 != rows:
        wv = jnp.concatenate(
            [wv, jnp.zeros((p2 - rows, cw), jnp.uint32)], axis=0
        )
        rows = p2
    f = wv
    planes: list[jax.Array] = []
    while rows > 1:
        fr = f.reshape(rows // 2, 2, cw)
        fa, fb = fr[:, 0, :], fr[:, 1, :]
        choose_b = fb & ~fa
        new_planes = []
        for pl in planes:
            pr = pl.reshape(rows // 2, 2, cw)
            new_planes.append(pr[:, 0, :] | (pr[:, 1, :] & ~fa))
        new_planes.append(choose_b)
        planes = new_planes
        f = fa | fb
        rows //= 2
    return f[0], [pl[0] for pl in planes]


def _masked_class_words(l1words, valid_words, cs):
    """One class's routed slot words ANDed with its valid-slot words — the
    MASKED row-min reads: the validity mask is applied per class slice, so
    the scan touches valid slot storage only (padded in-row slots read as
    zero, and the identity tail beyond the last class is never read at
    all).  Class slot ranges are 32-aligned by construction
    (graph/relay._build_classes), so the word slice is exact."""
    a, b = cs.sa // 32, cs.sb // 32
    return jax.lax.slice_in_dim(l1words, a, b) & jax.lax.slice_in_dim(
        valid_words, a, b
    )


def _class_found_rank(lw, cs):
    """(found bool[count], rank int32[count]) for one class from its masked
    slot words ``lw``: the min active RANK per vertex — ranks within a dst
    row ascend by ORIGINAL src id (graph/relay.py sort order), so min rank
    == canonical min-parent.  Rank values are meaningful only where
    ``found``."""
    if not cs.vertex_major:
        cw = cs.count // 32
        wv = lw.reshape(cs.width, cw)
        found_w, plane_w = _word_tournament(wv)
        rank = jnp.zeros(cs.count, jnp.int32)
        for j in range(len(plane_w)):
            rank = rank | (
                unpack_std(plane_w[j], cs.count).astype(jnp.int32) << j
            )
        found = unpack_std(found_w, cs.count) != 0
        return found, rank
    ww = cs.width // 32
    wv = lw.reshape(cs.count, ww)
    nz = wv != 0
    widx = jnp.min(
        jnp.where(nz, jnp.arange(ww, dtype=jnp.int32)[None, :], ww),
        axis=1,
    )
    word = jnp.take_along_axis(
        wv, jnp.clip(widx, 0, ww - 1)[:, None], axis=1
    )[:, 0]
    rank = widx * 32 + _ctz32(jnp.maximum(word, 1))
    return widx < ww, rank


def _class_slot(cs, rank):
    """rank -> global L1 slot for one class (the static slot formula:
    rank-major ``sa + r*count + p``, vertex-major ``sa + p*width + r``)."""
    p = jnp.arange(cs.count, dtype=jnp.int32)
    if not cs.vertex_major:
        return cs.sa + rank * cs.count + p
    return cs.sa + p * cs.width + rank


# bfs_tpu: hot traced
def rowmin_candidates(
    l1words: jax.Array, valid_words: jax.Array, in_classes, vr: int
) -> jax.Array:
    """Min active L1 slot per relabeled vertex: int32[vr], INT32_MAX where
    none.  The unpacked-path flavor: rank from the masked per-class
    tournament, then the static slot formula."""
    cands = []
    covered = 0
    for cs in sorted(in_classes, key=lambda c: c.va):
        assert cs.va == covered, "in_classes must tile the vertex space"
        found, rank = _class_found_rank(
            _masked_class_words(l1words, valid_words, cs), cs
        )
        cands.append(jnp.where(found, _class_slot(cs, rank), INT32_MAX))
        covered = cs.vb
    if covered < vr:
        cands.append(jnp.full(vr - covered, INT32_MAX, jnp.int32))
    return jnp.concatenate(cands)


# bfs_tpu: hot traced
def rowmin_ranks(
    l1words: jax.Array, valid_words: jax.Array, in_classes, vr: int
) -> jax.Array:
    """Min active RANK per relabeled vertex: uint32[vr], PACKED_SENTINEL
    where none — the packed-path flavor.  This is what the tournament
    natively produces; no slot arithmetic at all, and the sentinel is
    exactly the packed-word lattice top, so the output feeds
    :func:`apply_relay_candidates_packed` with one OR."""
    from .packed import PACKED_SENTINEL

    cands = []
    covered = 0
    for cs in sorted(in_classes, key=lambda c: c.va):
        assert cs.va == covered, "in_classes must tile the vertex space"
        found, rank = _class_found_rank(
            _masked_class_words(l1words, valid_words, cs), cs
        )
        cands.append(
            jnp.where(found, rank.astype(jnp.uint32), PACKED_SENTINEL)
        )
        covered = cs.vb
    if covered < vr:
        cands.append(jnp.full(vr - covered, PACKED_SENTINEL, jnp.uint32))
    return jnp.concatenate(cands)


def apply_relay_candidates(state: RelayState, cand: jax.Array) -> RelayState:
    """Merge per-vertex candidate slots into the carry (the reducer's
    min-merge applied to state, BfsSpark.java:90-108)."""
    newly = (cand != INT32_MAX) & (state.dist == INT32_MAX)
    new_level = state.level + 1
    dist = jnp.where(newly, new_level, state.dist)
    parent = jnp.where(newly, cand, state.parent)
    fwords = pack_std(newly)
    return RelayState(dist, parent, fwords, new_level, newly.any())


# bfs_tpu: hot traced
def apply_relay_candidates_packed(
    state: PackedRelayState, rank_or_sent: jax.Array
) -> PackedRelayState:
    """Packed state update: one lexicographic ``min`` over
    ``level:6|rank:26`` words — HALF the dist/parent HBM bytes of
    :func:`apply_relay_candidates` (one uint32 read + one written per
    vertex instead of two int32s each way).  The improvement test is
    implicit: an already-reached vertex has a smaller level field, so the
    min keeps it; the sentinel absorbs the level OR, so unreached
    candidates stay the lattice top."""
    from .packed import level_word, merge_packed

    cand = rank_or_sent | level_word(state.level + 1)
    packed = merge_packed(state.packed, cand)
    newly = packed != state.packed
    fwords = pack_std(newly)
    return PackedRelayState(packed, fwords, state.level + 1, newly.any())


def unpack_relay_packed(packed: jax.Array, in_classes, vr: int):
    """The ONCE-PER-RUN unpack at fused-loop exit (on device): packed
    ``level:6|rank:26`` words -> ``(dist int32[vr], parent int32[vr])``
    with parent as the global L1 SLOT index — the exact contract the
    unpacked RelayState carries, so every downstream consumer
    (slots_to_parent, to_original_device, the sharded map-back) is
    unchanged.  The rank -> slot reconstruction is the static per-class
    formula; it runs once per run, not once per superstep."""
    from .packed import PARENT_MASK, PACKED_SENTINEL, packed_dist

    dist = packed_dist(packed)
    rank = (packed & PARENT_MASK).astype(jnp.int32)
    parts = []
    covered = 0
    for cs in sorted(in_classes, key=lambda c: c.va):
        r = jax.lax.slice_in_dim(rank, cs.va, cs.vb)
        parts.append(_class_slot(cs, r))
        covered = cs.vb
    if covered < vr:
        parts.append(jnp.full(vr - covered, -1, jnp.int32))
    slots = jnp.concatenate(parts)
    parent = jnp.where(packed == PACKED_SENTINEL, jnp.int32(-1), slots)
    return dist, parent


# bfs_tpu: hot traced
def relay_superstep_words(
    state: RelayState,
    *,
    vperm_masks: jax.Array,
    vperm_table: tuple[StageSpec, ...],
    vperm_size: int,
    out_classes,
    out_space: int,
    net_masks: jax.Array,
    net_table: tuple[StageSpec, ...],
    net_size: int,
    in_classes,
    valid_words: jax.Array,
    vr: int,
) -> RelayState:
    """One full relay superstep, XLA reference path."""
    fw = jnp.concatenate(
        [state.fwords, jnp.zeros((vperm_size - vr) // 32, jnp.uint32)]
    )
    y = apply_benes_std(fw, vperm_masks, vperm_table, vperm_size)
    l2 = broadcast_l2(y, out_classes, net_size, out_space)
    l1 = apply_benes_std(l2, net_masks, net_table, net_size)
    cand = rowmin_candidates(l1, valid_words, in_classes, vr)
    return apply_relay_candidates(state, cand)


def segment_live(state, cap, seg_end):
    """THE segment-loop predicate (ISSUE 14): the fused predicate
    ``changed & level < cap`` plus the segment bound — a TRACED operand,
    so advancing ``seg_end`` never retraces.  Shared by the reference
    segment runners below and (structurally) by every segment program in
    models/ and parallel/: a segment boundary changes where the loop
    pauses, never what it computes."""
    return state.changed & (state.level < cap) & (state.level < seg_end)


# bfs_tpu: hot traced
def relay_segment_words(state: RelayState, seg_end, *, cap: int, **layout):
    """ONE bounded segment of unpacked relay supersteps — the XLA
    reference segment runner: :func:`relay_superstep_words` iterated
    until convergence, the level cap, or ``seg_end``, whichever first.
    Running segments of any size back-to-back is bit-identical to one
    fused loop (the parity proof the segmented engine programs lean on;
    tests/test_superstep_ckpt.py pins it)."""
    return jax.lax.while_loop(
        lambda s: segment_live(s, cap, seg_end),
        lambda s: relay_superstep_words(s, **layout),
        state,
    )


# bfs_tpu: hot traced
def relay_segment_words_packed(
    state: PackedRelayState, seg_end, *, cap: int, **layout
):
    """Packed twin of :func:`relay_segment_words`."""
    return jax.lax.while_loop(
        lambda s: segment_live(s, cap, seg_end),
        lambda s: relay_superstep_words_packed(s, **layout),
        state,
    )


# bfs_tpu: hot traced
def relay_superstep_words_packed(
    state: PackedRelayState,
    *,
    vperm_masks: jax.Array,
    vperm_table: tuple[StageSpec, ...],
    vperm_size: int,
    out_classes,
    out_space: int,
    net_masks: jax.Array,
    net_table: tuple[StageSpec, ...],
    net_size: int,
    in_classes,
    valid_words: jax.Array,
    vr: int,
) -> PackedRelayState:
    """Packed twin of :func:`relay_superstep_words`: identical routing
    pipeline, rank row-min + packed min-merge state update."""
    fw = jnp.concatenate(
        [state.fwords, jnp.zeros((vperm_size - vr) // 32, jnp.uint32)]
    )
    y = apply_benes_std(fw, vperm_masks, vperm_table, vperm_size)
    l2 = broadcast_l2(y, out_classes, net_size, out_space)
    l1 = apply_benes_std(l2, net_masks, net_table, net_size)
    cand = rowmin_ranks(l1, valid_words, in_classes, vr)
    return apply_relay_candidates_packed(state, cand)
