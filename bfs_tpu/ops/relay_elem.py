"""Element-major batched multi-source relay: 32 BFS trees per uint32.

The round-2 batched mode vmapped the single-source pipeline over a sources
axis, which re-read the same static routing masks once PER TREE — batching
brought no aggregate speedup (VERDICT round 2, weak #2).  Here the tree axis
moves into the BIT dimension: every network element (edge slot / vertex)
carries one uint32 whose bit t is tree t's frontier bit.  One superstep then

  * reads each mask word ONCE and applies the butterfly to whole uint32
    elements (a 32-64x amortization of the single-source bottleneck),
  * broadcasts/reduces whole uint32s (no pack/unpack at all — the packing
    dimension IS the tree axis),
  * keeps per-tree state bit-sliced: ``visited``/``frontier`` as uint32[vr],
    distances as level bit-planes, parents as per-class rank bit-planes
    (a vertex's parent slot = sa + rank*stride, so only ceil(log2 width)
    planes per degree class are needed).

All trees advance in lock-step supersteps (BreadthFirstPaths.java:114-132
multi-source semantics crossed with BASELINE.json config 5); 64 sources run
as TWO uint32 groups inside the same program — no host-level chunking.

This module is the portable XLA reference; the TPU path reuses these
shapes with fused Pallas passes (ops/relay_pallas.py element-major mode).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..graph.relay import StageSpec
from .relay import unpack_std

#: Distance bit-planes carried in the loop: levels must stay < 2^DB.  A run
#: that hits this cap stops UNCONVERGED with ``state.changed`` still True;
#: RelayEngine.run_multi_elem tests the flag and falls back to the vmapped
#: engine (``run_multi`` — no depth cap, host results), while the raw
#: device path leaves the test to the caller (models/bfs.py
#: run_multi_elem_device, which also documents the one-extra-confirming-
#: step rule for eccentricity exactly 31).
DIST_PLANES = 5
MAX_ELEM_LEVELS = (1 << DIST_PLANES) - 1


class ElemState(NamedTuple):
    """Loop carry for G groups of 32 trees, relabeled vertex space.

    ``visited``/``frontier``: uint32[G, vr] (bit t = tree t).
    ``dist_planes``: uint32[DIST_PLANES, G, vr] — bit b of a vertex's level.
    ``rank_planes``: uint32[G, PT] — per-class-packed parent rank bits
    (see :func:`rank_plane_layout`).
    """

    visited: jax.Array
    frontier: jax.Array
    dist_planes: jax.Array
    rank_planes: jax.Array
    level: jax.Array
    changed: jax.Array


def _nbits(width: int) -> int:
    return max(int(width - 1).bit_length(), 0)


def rank_plane_layout(in_classes):
    """Static layout of the packed rank planes: per class (sorted by va) a
    slice of ``nb * count`` words; returns (offsets dict keyed by va, total).
    Width-1 classes need no planes at all."""
    offsets = {}
    total = 0
    for cs in sorted(in_classes, key=lambda c: c.va):
        nb = _nbits(cs.width)
        offsets[cs.va] = (total, nb)
        total += nb * cs.count
    return offsets, total


def init_elem_state(vr: int, sources_new: np.ndarray, pt: int) -> ElemState:
    """``sources_new``: int32[G, 32] relabeled source ids."""
    g = sources_new.shape[0]
    rows = jnp.repeat(jnp.arange(g), 32)
    cols = jnp.asarray(sources_new).reshape(-1)
    bits = jnp.uint32(1) << jnp.tile(
        jnp.arange(32, dtype=jnp.uint32), g
    )
    visited = (
        jnp.zeros((g, vr), jnp.uint32).at[rows, cols].add(bits)
    )
    return ElemState(
        visited=visited,
        frontier=visited,
        dist_planes=jnp.zeros((DIST_PLANES, g, vr), jnp.uint32),
        rank_planes=jnp.zeros((g, pt), jnp.uint32),
        level=jnp.int32(0),
        changed=jnp.bool_(True),
    )


def _stage_select(m: jax.Array, st: StageSpec, n: int) -> jax.Array:
    """Per-lower-pair-element select mask (uint32 0/~0) for one stage:
    unpacks the stored words once; compact storage is already lower-half
    only, full storage interleaves zero uppers that the pair reshape drops."""
    if st.compact:
        mb = unpack_std(m, n // 2)
    else:
        mb = (
            unpack_std(m, n)
            .reshape(-1, 2, st.d)[:, 0, :]
            .reshape(-1)
        )
    return jnp.uint32(0) - mb.astype(jnp.uint32)


# bfs_tpu: hot traced
def apply_benes_elem(
    x: jax.Array, masks_flat: jax.Array, table: tuple[StageSpec, ...], n: int
) -> jax.Array:
    """Routed Beneš network over uint32 ELEMENTS (leading groups axis):
    x: uint32[G, n].  Every stage reads its mask words once and swaps whole
    uint32s — the tree-amortized form of ops.relay.apply_benes_std."""
    g = x.shape[0]
    for st in table:
        m = jax.lax.slice_in_dim(masks_flat, st.offset, st.offset + st.nwords)
        sel = _stage_select(m, st, n).reshape(1, -1, st.d)
        xr = x.reshape(g, -1, 2, st.d)
        lo, hi = xr[:, :, 0, :], xr[:, :, 1, :]
        t = (lo ^ hi) & sel
        x = jnp.stack([lo ^ t, hi ^ t], axis=2).reshape(g, n)
    return x


def broadcast_l2_elem(
    y: jax.Array, out_classes, net_size: int
) -> jax.Array:
    """Out-position uint32s -> L2 slot uint32s: rank-major classes tile the
    class block width times; vertex-major repeat each element width times."""
    g = y.shape[0]
    parts = []
    used = 0
    for cs in sorted(out_classes, key=lambda c: c.va):
        blk = jax.lax.slice_in_dim(y, cs.va, cs.vb, axis=1)
        if not cs.vertex_major:
            parts.append(jnp.tile(blk, (1, cs.width)))
        else:
            parts.append(jnp.repeat(blk, cs.width, axis=1))
        used += cs.count * cs.width
    parts.append(jnp.zeros((g, net_size - used), jnp.uint32))
    return jnp.concatenate(parts, axis=1)


def _tournament(xv: jax.Array, axis_rows: int):
    """Min-index reduce over rows of xv: [G, rows, count] uint32 tree-bits ->
    (found [G, count], rank planes list low..high bit).  Rows are padded to a
    power of two with zeros; pure elementwise merges, log2(rows) rounds."""
    g, rows, count = xv.shape
    p2 = 1 << max((rows - 1).bit_length(), 0)
    if p2 != rows:
        xv = jnp.concatenate(
            [xv, jnp.zeros((g, p2 - rows, count), jnp.uint32)], axis=1
        )
        rows = p2
    f = xv
    planes: list[jax.Array] = []
    k = 0
    while rows > 1:
        fr = f.reshape(g, rows // 2, 2, count)
        fa, fb = fr[:, :, 0, :], fr[:, :, 1, :]
        choose_b = fb & ~fa
        new_planes = []
        for pl in planes:
            pr = pl.reshape(g, rows // 2, 2, count)
            new_planes.append(pr[:, :, 0, :] | (pr[:, :, 1, :] & ~fa))
        new_planes.append(choose_b)
        planes = new_planes
        f = fa | fb
        rows //= 2
        k += 1
    return f[:, 0, :], [pl[:, 0, :] for pl in planes]


# bfs_tpu: hot traced
def rowmin_elem(
    l1: jax.Array, valid_words: jax.Array, in_classes, vr: int,
    plane_offsets, pt: int,
):
    """Per-vertex found mask + packed rank planes from the routed L1 slots.

    Returns ``(found uint32[G, vr], rank_planes uint32[G, PT])`` — rank
    planes only meaningful at bits where ``found`` is set.

    MASKED row-min: the valid-slot mask is expanded and applied PER CLASS
    SLICE (class slot ranges are 32-aligned), so the scan touches valid
    slot storage only — the old whole-array expansion materialized one
    uint32 select per slot over the FULL net including the identity tail
    beyond the last class, 4 bytes/slot of pure padding traffic at net
    sizes where m1 < n.
    """
    g = l1.shape[0]
    found_parts = []
    rp = jnp.zeros((g, pt), jnp.uint32)
    covered = 0
    for cs in sorted(in_classes, key=lambda c: c.va):
        vw = jax.lax.slice_in_dim(valid_words, cs.sa // 32, cs.sb // 32)
        vsel = jnp.uint32(0) - unpack_std(vw, cs.sb - cs.sa).astype(
            jnp.uint32
        )
        seg = jax.lax.slice_in_dim(l1, cs.sa, cs.sb, axis=1) & vsel[None, :]
        if not cs.vertex_major:
            xv = seg.reshape(g, cs.width, cs.count)
        else:
            xv = seg.reshape(g, cs.count, cs.width).swapaxes(1, 2)
        found, planes = _tournament(xv, cs.width)
        found_parts.append(found)
        off, nb = plane_offsets[cs.va]
        if nb:
            block = jnp.stack(planes[:nb], axis=1).reshape(g, nb * cs.count)
            rp = jax.lax.dynamic_update_slice_in_dim(
                rp, block, off, axis=1
            )
        covered = cs.vb
    if covered < vr:
        found_parts.append(jnp.zeros((g, vr - covered), jnp.uint32))
    return jnp.concatenate(found_parts, axis=1), rp


# bfs_tpu: hot traced
def elem_superstep(
    state: ElemState,
    *,
    vperm_masks,
    vperm_table,
    vperm_size: int,
    out_classes,
    net_masks,
    net_table,
    net_size: int,
    in_classes,
    valid_words,
    vr: int,
    plane_offsets,
    pt: int,
) -> ElemState:
    """One lock-step superstep for all 32*G trees (XLA reference path)."""
    g = state.frontier.shape[0]
    fw = jnp.concatenate(
        [state.frontier, jnp.zeros((g, vperm_size - vr), jnp.uint32)], axis=1
    )
    y = apply_benes_elem(fw, vperm_masks, vperm_table, vperm_size)
    l2 = broadcast_l2_elem(y, out_classes, net_size)
    l1 = apply_benes_elem(l2, net_masks, net_table, net_size)
    found, rp_new = rowmin_elem(
        l1, valid_words, in_classes, vr, plane_offsets, pt
    )
    newly = found & ~state.visited
    visited = state.visited | newly
    new_level = state.level + 1
    lev = new_level.astype(jnp.uint32)
    dist_planes = jnp.stack(
        [
            jnp.where(
                (lev >> b) & 1, state.dist_planes[b] | newly,
                state.dist_planes[b],
            )
            for b in range(DIST_PLANES)
        ]
    )
    # rank planes: adopt the new bits only for newly reached vertices; the
    # per-class expansion of `newly` mirrors rank_plane_layout's packing
    rp_mask_parts = []
    for cs in sorted(in_classes, key=lambda c: c.va):
        _, nb = plane_offsets[cs.va]
        if nb:
            seg = jax.lax.slice_in_dim(newly, cs.va, cs.vb, axis=1)
            rp_mask_parts.append(jnp.tile(seg, (1, nb)))
    rp_mask = (
        jnp.concatenate(rp_mask_parts, axis=1)
        if rp_mask_parts
        else jnp.zeros_like(state.rank_planes)
    )
    rank_planes = state.rank_planes | (rp_new & rp_mask)
    return ElemState(
        visited=visited,
        frontier=newly,
        dist_planes=dist_planes,
        rank_planes=rank_planes,
        level=new_level,
        changed=(newly != 0).any(),
    )


def extract_results(state, rg, sources: np.ndarray):
    """Host-side: bit-sliced device state -> per-tree (dist, parent) in
    ORIGINAL id space.  ``sources``: int32[S] original ids, S = 32*G."""
    from ..graph.relay import _vertex_tables
    from ..models.bfs import slots_to_parent

    visited = np.asarray(state.visited)  # [G, vr]
    dist_planes = np.asarray(state.dist_planes)  # [DB, G, vr]
    rank_planes = np.asarray(state.rank_planes)  # [G, PT]
    g, vr = visited.shape
    s = sources.shape[0]
    inf = np.int32(np.iinfo(np.int32).max)

    base1, stride1 = _vertex_tables(list(rg.in_classes), rg.vr)
    offsets, _ = rank_plane_layout(rg.in_classes)

    dist = np.full((s, rg.num_vertices), inf, np.int32)
    parent = np.full((s, rg.num_vertices), -1, np.int32)
    for gi in range(g):
        for t in range(32):
            ti = gi * 32 + t
            if ti >= s:
                break
            vis = (visited[gi] >> t) & 1
            dv = np.zeros(vr, np.int64)
            for b in range(DIST_PLANES):
                dv |= (((dist_planes[b, gi] >> t) & 1).astype(np.int64)) << b
            rank = np.zeros(vr, np.int64)
            for cs in rg.in_classes:
                off, nb = offsets[cs.va]
                for j in range(nb):
                    seg = rank_planes[
                        gi, off + j * cs.count : off + (j + 1) * cs.count
                    ]
                    rank[cs.va : cs.vb] |= (
                        ((seg >> t) & 1).astype(np.int64) << j
                    )
            slot = base1 + rank * stride1
            pn = np.where(vis == 1, slot, -1).astype(np.int64)
            d_orig = np.where(vis == 1, dv, inf)[rg.old2new]
            p_orig = slots_to_parent(
                pn.astype(np.int32), rg.src_l1
            )[rg.old2new]
            src = int(sources[ti])
            d_orig[src] = 0
            p_orig[src] = src
            dist[ti] = d_orig.astype(np.int32)
            parent[ti] = p_orig
    return dist, parent
