"""Pull-mode frontier relaxation: gather + row-min over ELL levels.

The scatter-free superstep (see :mod:`bfs_tpu.graph.ell` for the layout and
the measured rationale).  Semantics are identical to
:func:`bfs_tpu.ops.relax.relax_superstep` — per destination vertex, the
candidate parent is the minimum-id active in-neighbour, the deterministic
tie-break shared with the oracle's ``canonical_bfs`` — but the reduction is
dense: one 2-D gather from the frontier table and a row-min per ELL level,
instead of ``segment_min`` (which XLA lowers to a serial scatter loop on
TPU, ~0.1 Gedges/s vs near-bandwidth for gather+rowmin).

The frontier table ``F[u] = u if frontier[u] else INF`` folds the activity
test and the parent id into a single gathered value, so each edge costs one
int32 gather lane-op and one min.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from .. import knobs
from .relax import (
    INT32_MAX,
    BfsState,
    PackedBfsState,
    apply_candidates,
    apply_candidates_packed,
)

#: Row-chunk budget for the ELL gather (elements of the materialized
#: [rows, K] gather, ~4 bytes each).  One whole-matrix gather materializes
#: rows*K int32s as an HLO temp — ~3 GB at the LiveJournal-shape's 23M
#: rows, which OOMed the single-chip pull cell (BENCHMARKS.md ERR;
#: VERDICT r4 #7).  Levels larger than this are gathered in row chunks,
#: bounding the temp at ~4*BUDGET bytes while leaving small graphs' (and
#: every test's) program unchanged.
_CHUNK_ELEMS = int(knobs.get("BFS_TPU_PULL_CHUNK_MB") * (1 << 20) / 4)


def _rowmin_level(tab: jax.Array, mat_t: jax.Array) -> jax.Array:
    """Per-row min of gathered table values: ``mat_t`` is the TRANSPOSED
    ELL index matrix ``int32[K, rows]`` and the result is
    ``min_k tab[mat_t[k, r]]`` per row r (shape [..., rows]).

    Why transposed: TPU tiles 2-D int32 as (8, 128), so the natural
    [rows, K=32] layout pads its minor dimension 32 -> 128 — a 4.0x HBM
    expansion on BOTH the index operand and the gather temp (the
    LiveJournal-shape pull cell OOMed at 15.92/15.75 GB with "extra
    memory due to padding: 4.0x expansion").  [K, rows] puts the huge
    dimension minor (padded to 128 elements, negligible) and reduces over
    the MAJOR axis.

    The gather is additionally chunked over rows when the materialized
    [K, rows] temp would exceed the chunk budget (~4*_CHUNK_ELEMS bytes;
    batch axes of ``tab`` count against it)."""
    k, rows = mat_t.shape[-2], mat_t.shape[-1]
    batch = 1
    for d in tab.shape[:-1]:
        batch *= int(d)
    chunk_rows = max(_CHUNK_ELEMS // max(k * batch, 1), 1)
    if rows <= chunk_rows:
        return jnp.min(jnp.take(tab, mat_t, axis=-1), axis=-2)
    outs = []
    for a in range(0, rows, chunk_rows):
        b = min(a + chunk_rows, rows)
        outs.append(
            jnp.min(jnp.take(tab, mat_t[..., :, a:b], axis=-1), axis=-2)
        )
    return jnp.concatenate(outs, axis=-1)


def frontier_table(state) -> jax.Array:
    """``F[u] = u`` if u is on the frontier else INF — int32[V+1].
    Accepts either carry (BfsState or the packed one): only the frontier
    field is read."""
    n = state.frontier.shape[-1]
    ids = jnp.arange(n, dtype=jnp.int32)
    return jnp.where(state.frontier, ids, INT32_MAX)


# bfs_tpu: hot traced
def pull_candidates(frontier_tab: jax.Array, ell0: jax.Array, folds) -> jax.Array:
    """Min active in-neighbour id per vertex: int32[V+1] (slot V = INF).

    ``frontier_tab`` may be [V+1] or batched [..., V+1]; ELL gathers
    broadcast over leading axes.  ``ell0``/``folds`` are the TRANSPOSED
    [K, rows] device matrices (:func:`bfs_tpu.graph.ell.device_ell` — see
    :func:`_rowmin_level` for the TPU tile-padding rationale).
    """
    num_vertices = frontier_tab.shape[-1] - 1
    cand = _rowmin_level(frontier_tab, ell0)
    for fold in folds:
        inf = jnp.full(cand.shape[:-1] + (1,), INT32_MAX, dtype=jnp.int32)
        cand_ext = jnp.concatenate([cand, inf], axis=-1)
        cand = _rowmin_level(cand_ext, fold)
    inf = jnp.full(cand.shape[:-1] + (1,), INT32_MAX, dtype=jnp.int32)
    return jnp.concatenate([cand[..., :num_vertices], inf], axis=-1)


def pull_candidates_rows(
    frontier_tab_ext: jax.Array, ell0: jax.Array, folds, num_rows: int
) -> jax.Array:
    """Shard-local variant of :func:`pull_candidates`: ``frontier_tab_ext``
    already carries its trailing INF slot (size = table + 1) and the result
    is the first ``num_rows`` row-mins (one per owned vertex), with no slot
    appended.  Broadcasts over leading axes of ``frontier_tab_ext``;
    ``ell0``/``folds`` are TRANSPOSED [K, rows] device matrices."""
    cand = _rowmin_level(frontier_tab_ext, ell0)
    for fold in folds:
        inf = jnp.full(cand.shape[:-1] + (1,), INT32_MAX, dtype=jnp.int32)
        cand_ext = jnp.concatenate([cand, inf], axis=-1)
        cand = _rowmin_level(cand_ext, fold)
    return cand[..., :num_rows]


def pack_frontier_block(bits: jax.Array, num_words: int) -> jax.Array:
    """bool[..., B] -> uint32[..., B/32], STANDARD packing (element e at
    word e>>5, bit e&31 — the v4 convention shared with the relay layout)."""
    from .relay import pack_std

    del num_words
    return pack_std(bits)


def unpack_frontier_blocks(
    words: jax.Array, num_blocks: int, num_words: int
) -> jax.Array:
    """uint32[..., n*B/32] -> bool[..., n*B] for an all-gathered frontier
    (standard packing, shard blocks concatenated)."""
    from .relay import unpack_std

    return unpack_std(words, num_blocks * num_words * 32) != 0


# bfs_tpu: hot traced
def relax_pull_superstep(
    state: BfsState,
    ell0: jax.Array,
    folds,
    *,
    axis_name: str | None = None,
    batch_axis_name: str | None = None,
) -> BfsState:
    """One level-synchronous superstep in pull mode.

    With ``axis_name``, ``ell0``/``folds`` describe this device's edge shard
    and candidates are merged across the mesh with ``lax.pmin`` (the ICI
    all-reduce replacing the Spark shuffle, SURVEY.md §2.5), after which all
    devices apply identical updates to the replicated state.
    """
    cand_parent = pull_candidates(frontier_table(state), ell0, folds)
    if axis_name is not None:
        cand_parent = jax.lax.pmin(cand_parent, axis_name)
    return apply_candidates(state, cand_parent, batch_axis_name=batch_axis_name)


# bfs_tpu: hot traced
def relax_pull_superstep_packed(
    state: PackedBfsState,
    ell0: jax.Array,
    folds,
    *,
    axis_name: str | None = None,
    batch_axis_name: str | None = None,
) -> PackedBfsState:
    """Packed twin of :func:`relax_pull_superstep`: identical gather +
    row-min candidates, one min-merge state update on the fused
    ``level:6|parent:26`` words (ops/packed.py) — half the dist/parent
    HBM bytes per superstep."""
    cand_parent = pull_candidates(frontier_table(state), ell0, folds)
    if axis_name is not None:
        cand_parent = jax.lax.pmin(cand_parent, axis_name)
    return apply_candidates_packed(
        state, cand_parent, batch_axis_name=batch_axis_name
    )
