"""The packed BFS state word: ``level:6 | parent:26`` in one uint32.

Round-5 profiling put the relay superstep ~1.9x off its own mask-stream
roofline, and the largest non-mask term is the per-superstep dist/parent
state update: two int32[V] arrays read AND written every superstep (128 MB
of HBM traffic per superstep at s24).  Level-synchronous BFS never needs
their full range at once — a vertex's distance is at most the superstep
count and its parent is fixed the superstep it is reached — so both fuse
into ONE 32-bit word per vertex:

    bits 31..26   level   (6 bits; 0..PACKED_MAX_LEVELS)
    bits 25..0    parent  (26 bits; engine-specific meaning, below)

with all-ones (``PACKED_SENTINEL``) as the unreached value.  The packing
is chosen so the state update degenerates to a single unsigned
``min(state, candidate)``:

  * the level field is MAJOR, so an already-reached vertex (smaller level)
    always wins against a later candidate — the ``(cand != INF) &
    (dist == INF)`` improvement test disappears into the min;
  * the parent field is MINOR, so among same-superstep candidates the min
    picks the smallest parent value — exactly the canonical min-parent
    tie-break every engine and the oracle share (the reducer monoid of
    BfsSpark.java:90-108 as one lattice ``min``);
  * the sentinel is the lattice top: ``min(SENTINEL, x) == x`` for any
    candidate, and ``x | level_bits`` leaves the sentinel intact
    (all-ones absorbs), so no masking is needed to build candidates.

Per-superstep dist/parent HBM traffic is thereby HALVED (one uint32 word
per vertex instead of two int32s, read and write sides both), and the
row-min's tie-break becomes one lexicographic ``min`` over packed words.

Parent-field meaning per engine (the 26-bit budget):

  * push/pull engines: the parent VERTEX id — fits iff ``V <= 2^26``
    (:func:`packed_parent_fits`).
  * relay engine: the parent's within-row RANK in the vertex's degree
    class (slot = base + rank*stride, graph/relay._vertex_tables) — fits
    iff the largest class width is <= 2^26 (:func:`packed_rank_fits`).
    The rank is what the row-min tournament natively produces; the global
    L1 slot is reconstructed ONCE per run at unpack time.

Level capacity is ``PACKED_MAX_LEVELS`` (62; 63 is the sentinel's level
field).  Engines run packed by default and FALL BACK to the unpacked
int32 state when a search fails to converge under the cap
(:func:`packed_truncated`) — the same detect-and-fallback contract the
element-major engine uses for its 31-level distance planes
(models/bfs.py ``run_multi_elem``).  Oracle results and wire formats are
unchanged: every fused program unpacks once at loop exit, on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# NumPy (not jnp) scalar, same convention as ops/relax.py: a module-level
# jnp constant would initialize the JAX backend at import time.  Defined
# here (not imported from relax) so relax.py can import this module
# without a cycle.
INT32_MAX = np.int32(2**31 - 1)

#: Field widths.  Level is MAJOR so the min-merge prefers earlier levels.
LEVEL_BITS = 6
PARENT_BITS = 26
PARENT_MASK = np.uint32((1 << PARENT_BITS) - 1)

#: Unreached sentinel: all ones.  Its level field (63) is reserved, so the
#: deepest representable level is 62.
PACKED_SENTINEL = np.uint32(0xFFFFFFFF)
PACKED_MAX_LEVELS = (1 << LEVEL_BITS) - 2  # 62


def packed_parent_fits(num_vertices: int) -> bool:
    """Can a parent VERTEX id (push/pull engines) fit the 26-bit field?"""
    return int(num_vertices) <= (1 << PARENT_BITS)


def packed_rank_fits(in_classes) -> bool:
    """Can every relay parent RANK fit the 26-bit field?  Ranks are
    bounded by the class width (strictly below it)."""
    widths = [int(c.width) for c in in_classes]
    return (max(widths) if widths else 1) <= (1 << PARENT_BITS)


def resolve_packed(fits: bool) -> bool:
    """``BFS_TPU_PACKED=0/1`` forces the carry flavor; otherwise run
    packed exactly when the layout fits."""
    from .. import knobs

    env = knobs.get("BFS_TPU_PACKED")
    if env in ("0", "1"):
        return env == "1"
    return bool(fits)


def packed_cap(max_levels: int) -> int:
    """The level bound a packed fused loop may run to."""
    return min(int(max_levels), PACKED_MAX_LEVELS)


def packed_truncated(changed, level, max_levels: int) -> bool:
    """Host-side: did the packed loop stop on its level capacity rather
    than converging or hitting the caller's own ``max_levels``?  True
    means the caller must re-run on the unpacked path."""
    return (
        bool(changed)
        and int(level) >= PACKED_MAX_LEVELS
        and int(max_levels) > PACKED_MAX_LEVELS
    )


# ------------------------------------------------------------------ device --

def level_word(level) -> jax.Array:
    """``level`` (int32 scalar/array) -> the uint32 level-field bits.
    OR-ing these onto a parent value (or onto the sentinel, which absorbs)
    builds a candidate word."""
    return level.astype(jnp.uint32) << np.uint32(PARENT_BITS)


def merge_packed(packed: jax.Array, cand: jax.Array) -> jax.Array:
    """THE state update: one lexicographic (level, parent) min per word."""
    return jnp.minimum(packed, cand)


def packed_dist(packed: jax.Array) -> jax.Array:
    """int32 distances from packed words (INT32_MAX where unreached)."""
    return jnp.where(
        packed == PACKED_SENTINEL,
        jnp.int32(INT32_MAX),
        (packed >> np.uint32(PARENT_BITS)).astype(jnp.int32),
    )


def packed_parent(packed: jax.Array) -> jax.Array:
    """int32 parent field from packed words (-1 where unreached)."""
    return jnp.where(
        packed == PACKED_SENTINEL,
        jnp.int32(-1),
        (packed & PARENT_MASK).astype(jnp.int32),
    )


# -------------------------------------------------------------------- host --

def pack_host(dist: np.ndarray, parent: np.ndarray) -> np.ndarray:
    """NumPy twin: (dist, parent) -> packed words (tests / fixtures)."""
    dist = np.asarray(dist)
    parent = np.asarray(parent)
    unreached = dist == INT32_MAX
    word = (dist.astype(np.uint32) << np.uint32(PARENT_BITS)) | (
        parent.astype(np.uint32) & PARENT_MASK
    )
    return np.where(unreached, PACKED_SENTINEL, word).astype(np.uint32)


def unpack_host(packed: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """NumPy twin of :func:`packed_dist` / :func:`packed_parent`."""
    packed = np.asarray(packed, dtype=np.uint32)
    unreached = packed == PACKED_SENTINEL
    dist = np.where(
        unreached, np.int32(INT32_MAX),
        (packed >> np.uint32(PARENT_BITS)).astype(np.int32),
    )
    parent = np.where(
        unreached, np.int32(-1), (packed & PARENT_MASK).astype(np.int32)
    )
    return dist.astype(np.int32), parent.astype(np.int32)
