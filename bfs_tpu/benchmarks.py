"""Table-7 comparison harness: serial oracle vs every engine vs shard counts.

Reproduces the reference paper's entire benchmark methodology
(docs/BigData_Project.pdf §1.5 Table 7: serial BFS vs parallel BFS at
1/2/10 workers over tinyCG/mediumG/largeG, timings excluding startup and
graph construction) as ONE command, and emits the comparison matrix as
``BENCHMARKS.json`` + ``BENCHMARKS.md`` next to the repo root.

Differences from the reference, by design:
  * The serial column is our native C++ oracle (algs4 ``BreadthFirstPaths``
    parity, SURVEY.md §2.2) — same role as the paper's JVM serial runs.
  * "N workers" becomes N mesh shards.  Real multi-chip hardware is not
    assumed: shard-count cells run on the single-host 8-device virtual CPU
    platform (the paper's own "master + N workers on one machine"
    methodology), while single-chip engine cells run on the real TPU when
    present.  Each cell runs in a SUBPROCESS because a JAX process cannot
    switch platforms after backend init.
  * Alongside wall time we report Graph500-honest TEPS (input undirected
    edges inside the traversed component / time).

Datasets: tinyCG (the paper's worked example), randomG (in-repo
mediumG-shape fixture, 250 V / 1,273 E), largeG-shape (seeded G(n,m) with
largeG's exact shape, 1,000,000 V / 7,586,063 E — the graph the reference
OOMed on), and the R-MAT benchmark graph (BENCHMARKS_SCALE, default 20).
Plus the BASELINE.json config-5 row: 64-source batched BFS.

Usage:
    python -m bfs_tpu.benchmarks              # full matrix (minutes; caches)
    BENCHMARKS_SCALE=22 python -m bfs_tpu.benchmarks
    python -m bfs_tpu.benchmarks --cell '{"dataset":"tinyCG","mode":"pull"}'
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SHARD_COUNTS = (1, 2, 8)
ENGINES = ("push", "pull", "relay")
LARGEG_V, LARGEG_E = 1_000_000, 7_586_063  # paper §1.5 / service.properties:9
#: soc-Pokec's exact shape (SNAP): BASELINE.json config 4, synthesized with
#: R-MAT skew and shipped through the SNAP text format end-to-end.
POKEC_V, POKEC_E = 1_632_803, 30_622_564
#: soc-LiveJournal1's exact shape (SNAP): the second BASELINE.json config-4
#: graph (4.8M V / 69M directed E).  Zero-egress environment: synthesized at
#: the exact vertex/edge counts with R-MAT degree skew (same stand-in
#: methodology as the Pokec row; provenance documented in BENCHMARKS.md).
LJ_V, LJ_E = 4_847_571, 68_993_773

#: Reference Table 7 (docs/BigData_Project.pdf §1.5), normalized to seconds;
#: None = OOM.  Keyed (dataset, column) for the side-by-side report.
REFERENCE_TABLE7 = {
    ("tinyCG", "serial"): 1.686e-3,
    ("tinyCG", "workers1"): 0.5691,
    ("tinyCG", "workers2"): 0.3428,
    ("tinyCG", "workers10"): 1.610,
    ("mediumG", "serial"): 1.275e-3,
    ("mediumG", "workers1"): 2.914,
    ("mediumG", "workers2"): 3.924,
    ("mediumG", "workers10"): 20.94,
    ("largeG", "serial"): 1.170,
    ("largeG", "workers1"): None,
    ("largeG", "workers2"): None,
    ("largeG", "workers10"): None,
}


# --------------------------------------------------------------------------
# dataset loading (child-process side)
# --------------------------------------------------------------------------

def _load_dataset(name: str, scale: int):
    """Returns ``(graph_or_none, dg, source, label)`` — ``dg`` is the
    dst-sorted single-shard DeviceGraph every engine builds its layout from
    (cached for the big graphs)."""
    from .bench import _cached, load_or_build, _generator_backend
    from .graph.csr import Graph, DeviceGraph, build_device_graph

    if name in ("tinyCG", "randomG"):
        from .graph.io import read_sedgewick

        path = os.path.join(_REPO_ROOT, "test-sets", f"{name}.txt")
        g = read_sedgewick(path)
        return g, build_device_graph(g, block=1024), 0, f"{name} ({g.num_vertices} V)"
    if name == "largeG":
        def unpack(z):
            return DeviceGraph(
                num_vertices=int(z["num_vertices"]),
                num_edges=int(z["num_edges"]),
                src=z["src"],
                dst=z["dst"],
            )

        def build():
            from .graph.generators import gnm_graph

            g = gnm_graph(LARGEG_V, LARGEG_E, seed=1)
            dg = build_device_graph(g, block=8 * 1024)
            return dg, dict(
                num_vertices=dg.num_vertices, num_edges=dg.num_edges,
                src=dg.src, dst=dg.dst,
            )

        dg = _cached(f"largeG_gnm_v{LARGEG_V}_e{LARGEG_E}_seed1", unpack, build)
        return None, dg, 0, f"largeG-shape ({LARGEG_V} V)"
    if name == "pokec":
        from .bench import _CACHE_DIR

        def unpack(z):
            return (
                DeviceGraph(
                    num_vertices=int(z["num_vertices"]),
                    num_edges=int(z["num_edges"]),
                    src=z["src"],
                    dst=z["dst"],
                ),
                int(z["source"]),
            )

        def build():
            from .graph.generators import snap_shape_edges
            from .graph.io import read_snap_edge_list, write_snap_edge_list

            # Full SNAP ingest path, end-to-end: synthesize the directed
            # edge list at soc-Pokec's exact shape, WRITE it as a real SNAP
            # text file, then parse it back through the public reader.
            txt = os.path.join(_CACHE_DIR, "soc-pokec-shape.txt")
            if not os.path.exists(txt):
                pairs = snap_shape_edges(POKEC_V, POKEC_E, seed=4)
                tmp = f"{txt}.tmp.{os.getpid()}"
                write_snap_edge_list(
                    pairs, tmp, name="soc-pokec-shape (synthetic, R-MAT skew)",
                    num_vertices=POKEC_V,
                )
                os.replace(tmp, txt)
            g = read_snap_edge_list(txt, num_vertices=POKEC_V)
            dg = build_device_graph(g, block=8 * 1024)
            degrees = np.bincount(g.src, minlength=g.num_vertices)
            source = int(np.argmax(degrees))
            return (dg, source), dict(
                num_vertices=dg.num_vertices, num_edges=dg.num_edges,
                src=dg.src, dst=dg.dst, source=source,
            )

        (dg, source) = _cached(f"pokec_snap_v{POKEC_V}_e{POKEC_E}_seed4", unpack, build)
        return None, dg, source, f"soc-Pokec-shape SNAP ({POKEC_V} V)"
    if name == "livejournal":
        def unpack(z):
            return (
                DeviceGraph(
                    num_vertices=int(z["num_vertices"]),
                    num_edges=int(z["num_edges"]),
                    src=z["src"],
                    dst=z["dst"],
                ),
                int(z["source"]),
            )

        def build():
            from .graph.generators import snap_shape_edges

            pairs = snap_shape_edges(LJ_V, LJ_E, seed=11)
            from .graph.csr import Graph

            g = Graph(
                LJ_V,
                np.concatenate([pairs[:, 0], pairs[:, 1]]),
                np.concatenate([pairs[:, 1], pairs[:, 0]]),
            )
            dg = build_device_graph(g, block=8 * 1024)
            degrees = np.bincount(g.src, minlength=g.num_vertices)
            source = int(np.argmax(degrees))
            return (dg, source), dict(
                num_vertices=dg.num_vertices, num_edges=dg.num_edges,
                src=dg.src, dst=dg.dst, source=source,
            )

        (dg, source) = _cached(f"lj_snapshape_v{LJ_V}_e{LJ_E}_seed11", unpack, build)
        return None, dg, source, f"soc-LiveJournal1-shape ({LJ_V} V)"
    if name == "rmat":
        backend = _generator_backend()
        dg, source = load_or_build(scale, 16, 42, 8 * 1024, backend)
        return None, dg, source, f"R-MAT s{scale} ({dg.num_vertices} V)"
    raise ValueError(f"unknown dataset {name!r}")


def _graph_key(name: str, scale: int) -> str:
    if name == "rmat":
        from .bench import _generator_backend

        return f"{_generator_backend()}_s{scale}_ef16_seed42_block8192"
    return name


# --------------------------------------------------------------------------
# one cell (child-process side)
# --------------------------------------------------------------------------

def _teps(dg, dist, seconds: float) -> float:
    """Graph500-honest TEPS for one tree (see bfs_tpu.bench)."""
    from .graph.csr import unpad_edges

    esrc, _ = unpad_edges(dg)
    reached = dist != np.iinfo(np.int32).max
    return (int(np.count_nonzero(reached[esrc])) / 2) / seconds


def _cached_oracle(dg, source: int, key: str):
    """Cached canonical oracle (dist, min-parent) for cell verification —
    VERDICT round 2 item 6: every matrix cell must assert its result against
    the oracle before publishing a time."""
    from .bench import _cached
    from .graph.csr import Graph, unpad_edges

    def unpack(z):
        return z["dist"], z["parent"]

    def build():
        esrc, edst = unpad_edges(dg)
        g = Graph(dg.num_vertices, esrc, edst)
        from .oracle.native import native_available, native_bfs

        if native_available():
            dist, parent, _ = native_bfs(g, source, policy="canonical")
        else:
            from .oracle.bfs import canonical_bfs

            dist, parent = canonical_bfs(g, source)
        return (dist, parent), dict(dist=dist, parent=parent)

    return _cached(f"oracle_{key}_s{source}", unpack, build)


def _verify_cell(dg, source: int, key: str, dist, parent=None) -> str:
    """Assert dist (and parent when the engine materializes one) against the
    cached canonical oracle; returns "passed" or raises."""
    odist, oparent = _cached_oracle(dg, source, key)
    np.testing.assert_array_equal(dist, odist, err_msg="cell dist != oracle")
    if parent is not None:
        np.testing.assert_array_equal(
            parent, oparent, err_msg="cell parent != oracle (canonical)"
        )
    return "passed" if parent is not None else "passed (dist)"


def run_cell(spec: dict) -> dict:
    dataset = spec["dataset"]
    mode = spec["mode"]
    scale = int(spec.get("scale", 20))
    repeats = int(spec.get("repeats", 3))
    graph, dg, source, label = _load_dataset(dataset, scale)
    out = {"dataset": dataset, "mode": mode, "label": label,
           "num_vertices": dg.num_vertices, "num_directed_edges": dg.num_edges}

    if mode in ("serial-native", "serial-python"):
        from .graph.csr import Graph, unpad_edges
        from .oracle.bfs import queue_bfs
        from .oracle.native import native_available, native_bfs

        if graph is None:
            esrc, edst = unpad_edges(dg)
            graph = Graph(dg.num_vertices, esrc, edst)
        graph.csr()  # construction excluded from timing (paper §1.5 parity)
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            if mode == "serial-native":
                if not native_available():
                    return {**out, "error": "native oracle unavailable"}
                dist, _, _ = native_bfs(graph, source, policy="queue")
            else:
                dist, _ = queue_bfs(graph, source)
            times.append(time.perf_counter() - t0)
        sec = float(np.median(times))
        reached = dist[dist != np.iinfo(np.int32).max]
        checked = _verify_cell(dg, source, _graph_key(dataset, scale), dist)
        return {**out, "seconds": sec, "teps": _teps(dg, dist, sec),
                "supersteps": int(reached.max(initial=0)), "check": checked}

    import jax

    out["device"] = str(jax.devices()[0].platform)

    if mode in ENGINES:
        from .bench import load_or_build_pull, load_or_build_relay
        from .models.bfs import RelayEngine, bfs, _bfs_fused, _bfs_pull_fused
        import jax.numpy as jnp

        key = _graph_key(dataset, scale)
        if mode == "relay":
            from .graph.benes import native_available as benes_ok

            if not benes_ok():
                return {**out, "error": "native benes router unavailable"}
            rg, _ = load_or_build_relay(dg, key)
            eng = RelayEngine(rg)
            s_new = jnp.int32(int(rg.old2new[source]))
            run = lambda: eng._fused(s_new, rg.num_vertices)  # noqa: E731
        elif mode == "pull":
            from .ops.packed import packed_parent_fits, resolve_packed

            pg = load_or_build_pull(dg, key)
            from .graph.ell import device_ell

            ell0, folds = device_ell(pg)
            # Packed fused-word carry when V fits (ops/packed.py); a
            # >62-level cell would fail its oracle assertion rather than
            # ship silently truncated numbers.
            run = lambda: _bfs_pull_fused(  # noqa: E731
                ell0, folds, jnp.int32(source), pg.num_vertices,
                pg.num_vertices,
                resolve_packed(packed_parent_fits(pg.num_vertices)),
            )
        else:
            from .ops.packed import packed_parent_fits, resolve_packed

            src = jnp.asarray(dg.src)
            dst = jnp.asarray(dg.dst)
            run = lambda: _bfs_fused(  # noqa: E731
                src, dst, jnp.int32(source), dg.num_vertices,
                dg.num_vertices,
                resolve_packed(packed_parent_fits(dg.num_vertices)),
            )
        state = run()
        levels = int(state.level)  # sync
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            _ = int(run().level)
            times.append(time.perf_counter() - t0)
        sec = float(np.median(times))
        # Untimed full result (dist AND parent) for the oracle assertion.
        if mode == "relay":
            res = eng.run(source)
        else:
            st = jax.device_get(state)
            from .models.bfs import BfsResult

            res = BfsResult(
                dist=np.asarray(st.dist[: dg.num_vertices]),
                parent=np.asarray(st.parent[: dg.num_vertices]),
                num_levels=levels,
            )
        checked = _verify_cell(
            dg, source, _graph_key(dataset, scale), res.dist, res.parent
        )
        return {**out, "seconds": sec, "teps": _teps(dg, res.dist, sec),
                "supersteps": levels, "check": checked}

    if mode.startswith("sharded-"):
        eng, shards_s = mode.rsplit("-", 2)[-2:]
        shards = int(shards_s)
        from .parallel.sharded import bfs_sharded, make_mesh

        if len(jax.devices()) < shards:
            return {**out, "error": f"need {shards} devices, have {len(jax.devices())}"}
        if eng == "relay":
            from .graph.benes import native_available as benes_ok

            if not benes_ok():
                return {**out, "error": "native benes router unavailable"}
        mesh = make_mesh(graph=shards, batch=1)
        # Layout built ONCE outside the timed repeats (the methodology
        # excludes construction; only the compiled traversal is measured).
        if eng == "relay":
            from .graph.relay import build_sharded_relay_graph

            layout = build_sharded_relay_graph(dg, shards)
        else:
            from .graph.ell import build_sharded_pull_graph

            layout = build_sharded_pull_graph(dg, shards)
        run = lambda: bfs_sharded(layout, source, mesh=mesh, engine=eng)  # noqa: E731
        res = run()  # warm-up/compile
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            res = run()
            times.append(time.perf_counter() - t0)
        sec = float(np.median(times))
        checked = _verify_cell(
            dg, source, _graph_key(dataset, scale), res.dist, res.parent
        )
        # Exchange accounting (VERDICT round 2 item 4): the per-superstep
        # ICI exchange is the frontier-word all-gather (1 bit per global
        # vertex slot) + the scalar termination all-reduce; per-shard static
        # layout bytes let "would N real chips win?" be modeled from data.
        if eng == "relay":
            # Bitmap-arm exchange bytes (parallel/exchange.py): only words
            # holding real vertices travel — n_shards * kw words, ~V/8
            # bytes flat in shard count (the naive block-bit gather grew
            # with per-shard class padding: VERDICT r4 weak #4).  This is
            # the static upper arm; the auto arm's word-list levels ship
            # less — the MULTICHIP bench (BENCH_MESH) measures the real
            # per-level bytes via telemetry.
            from .parallel.sharded import _own_word_table_dev

            gwords = layout.num_shards * _own_word_table_dev(layout).shape[1]
            exch = {
                "exchange_bytes_per_superstep": gwords * 4,
                "per_shard_net_mask_bytes": int(layout.net_masks.nbytes
                                                // layout.num_shards),
                "per_shard_vperm_mask_bytes": int(layout.vperm_masks.nbytes
                                                  // layout.num_shards),
                "per_shard_net_size_log2": int(np.log2(layout.net_size)),
            }
        else:
            gwords = layout.num_shards * layout.block // 32
            exch = {
                "exchange_bytes_per_superstep": gwords * 4,
                "per_shard_ell_bytes": int(
                    (layout.ell0.nbytes + sum(f.nbytes for f in layout.folds))
                    // layout.num_shards
                ),
            }
        return {**out, "shards": shards, "seconds": sec,
                "teps": _teps(dg, res.dist, sec), "supersteps": res.num_levels,
                "check": checked, **exch}

    if mode.startswith("multi-"):
        engine = mode.split("-", 1)[1]
        num_sources = int(spec.get("num_sources", 64))
        chunk = int(spec.get("chunk", 8))
        from .models.multisource import bfs_multi

        rng = np.random.default_rng(12345)
        sources = rng.choice(dg.num_vertices, size=num_sources, replace=False).astype(np.int32)
        chunks = [sources[i : i + chunk] for i in range(0, num_sources, chunk)]
        # Prebuild the engine layout once (cached on disk for the big
        # graphs) so repeats time only the compiled batched traversal; the
        # batch runs in device-resident chunks (a 64-wide batch of V-sized
        # state does not fit HBM at bench scales).
        # Timed region = the compiled batched traversal with an on-device
        # termination scalar read as the sync — the same methodology as the
        # engine cells above.  Full dist/parent materialization (a V-sized
        # device->host pull per chunk, ~100 MB/s-scale through the axon
        # tunnel and therefore 5-10x the traversal itself) happens ONCE
        # outside the timed loop, only to compute the TEPS numerator.
        from .models.multisource import bfs_multi_device

        key = _graph_key(dataset, scale)
        if engine == "elem":
            # element-major batched relay: ALL 64 sources in one program,
            # 32 trees per uint32 element (no chunking; VERDICT r2 item 2)
            from .bench import load_or_build_relay
            from .models.bfs import RelayEngine

            rg, _ = load_or_build_relay(dg, key)
            eng = RelayEngine(rg)
            chunk = num_sources  # single batch
            chunks = [sources]
            run_dev = lambda c: eng.run_multi_elem_device(c)  # noqa: E731
            run_host = lambda c: eng.run_multi_elem(c)  # noqa: E731
        elif engine == "relay":
            from .bench import load_or_build_relay
            from .models.bfs import RelayEngine

            rg, _ = load_or_build_relay(dg, key)
            eng = RelayEngine(rg)
            run_dev = lambda c: eng.run_multi_device(c)  # noqa: E731
            run_host = lambda c: eng.run_multi(c)  # noqa: E731
        elif engine == "pull":
            from .bench import load_or_build_pull

            pg = load_or_build_pull(dg, key)
            run_dev = lambda c: bfs_multi_device(pg, c, engine="pull")[0]  # noqa: E731
            run_host = lambda c: bfs_multi(pg, c, engine="pull")  # noqa: E731
        else:
            run_dev = lambda c: bfs_multi_device(dg, c, engine=engine)[0]  # noqa: E731
            run_host = lambda c: bfs_multi(dg, c, engine=engine)  # noqa: E731
        _ = int(run_dev(chunks[0]).level)  # warm-up/compile + sync
        times = []
        supersteps = 0
        for _ in range(repeats):
            t0 = time.perf_counter()
            supersteps = max(
                supersteps, max(int(run_dev(c).level) for c in chunks)
            )
            times.append(time.perf_counter() - t0)
        sec = float(np.median(times))

        import jax.numpy as jnp
        from .graph.csr import unpad_edges

        esrc, _ = unpad_edges(dg)
        inf = np.iinfo(np.int32).max
        # TEPS numerator per tree = directed edges whose src the tree
        # reached = sum over vertices of reached * outdeg — ONE tiny
        # device-side reduction per chunk instead of materializing every
        # chunk's [S, V] state through the tunnel (ADVICE.md round 2: the
        # host re-runs roughly doubled multi-cell wall time).
        outdeg_by_old = np.bincount(esrc, minlength=dg.num_vertices)
        if engine in ("relay", "elem"):
            odg = jnp.asarray(
                np.concatenate([
                    np.where(
                        eng.relay_graph.new2old >= 0,
                        outdeg_by_old[
                            np.clip(eng.relay_graph.new2old, 0, None)
                        ],
                        0,
                    )
                ]).astype(np.int64)
            )
        else:
            odg = jnp.asarray(
                np.concatenate(
                    [outdeg_by_old, np.zeros(1, np.int64)]
                ).astype(np.int64)
            )

        def chunk_traversed(c):
            st = run_dev(c)
            if engine == "elem":
                # bit-sliced visited: popcount-weighted outdeg per tree
                vis = st.visited  # [G, vr] uint32
                per_bit = [
                    ((vis >> t) & 1).astype(jnp.int64) @ odg
                    for t in range(32)
                ]
                return [int(x) for x in np.asarray(jnp.stack(per_bit).T).reshape(-1)]
            reached = st.dist != inf
            return [
                int(x)
                for x in np.asarray(
                    reached.astype(jnp.int64) @ odg[: reached.shape[1]]
                )
            ]

        traversed = sum(sum(chunk_traversed(c)) for c in chunks)
        # verify every tree of the first chunk against the cached oracle
        # (the only chunk materialized host-side)
        first = run_host(chunks[0])
        key = _graph_key(dataset, scale)
        for i, s0 in enumerate(chunks[0]):
            _verify_cell(dg, int(s0), key, first.dist[i], first.parent[i])
        checked = f"passed (first chunk, {len(chunks[0])} trees)"
        return {**out, "num_sources": num_sources, "seconds": sec,
                "teps": (traversed / 2) / sec,
                "supersteps": supersteps, "check": checked}

    raise ValueError(f"unknown mode {mode!r}")


# --------------------------------------------------------------------------
# orchestration (parent side)
# --------------------------------------------------------------------------

def _child_env(virtual_devices: int | None) -> dict:
    env = dict(os.environ)
    if virtual_devices:
        env["JAX_PLATFORMS"] = "cpu"
        # The axon TPU plugin registers itself from sitecustomize whenever
        # PALLAS_AXON_POOL_IPS is set and force-pins jax_platforms="axon,cpu",
        # overriding the env var — clear it so the child really gets the
        # virtual CPU platform.
        env.pop("PALLAS_AXON_POOL_IPS", None)
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={virtual_devices}"
            ).strip()
    return env


def _run_subprocess(spec: dict, virtual_devices: int | None, timeout: int) -> dict:
    cmd = [sys.executable, "-m", "bfs_tpu.benchmarks", "--cell", json.dumps(spec)]
    try:
        proc = subprocess.run(
            cmd, env=_child_env(virtual_devices), capture_output=True,
            text=True, timeout=timeout, cwd=_REPO_ROOT,
        )
    except subprocess.TimeoutExpired:
        return {**spec, "error": f"timeout after {timeout}s"}
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return {**spec, "error": (proc.stderr or "no output").strip()[-400:]}


def _fmt_secs(s) -> str:
    if s is None:
        return "OOM"
    if isinstance(s, str):
        return s
    if s < 1e-3:
        return f"{s * 1e6:.1f} us"
    if s < 1:
        return f"{s * 1e3:.2f} ms"
    return f"{s:.3f} s"


def _fmt_teps(t) -> str:
    if not isinstance(t, (int, float)):
        return "-"
    if t >= 1e9:
        return f"{t / 1e9:.2f} G"
    if t >= 1e6:
        return f"{t / 1e6:.1f} M"
    return f"{t / 1e3:.1f} k"


def _cell_str(r: dict) -> str:
    if "error" in r:
        return "ERR"
    return f"{_fmt_secs(r['seconds'])} ({_fmt_teps(r['teps'])} TEPS)"


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cell", help="JSON cell spec (child-process mode)")
    ap.add_argument("--datasets", default="tinyCG,randomG,largeG,pokec,livejournal,rmat")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--timeout", type=int, default=3600)
    ap.add_argument("--skip-multi", action="store_true")
    ap.add_argument(
        "--merge", action="store_true",
        help="merge this run's cells into the existing BENCHMARKS.json "
        "(matching dataset+mode cells replaced) instead of starting fresh",
    )
    ap.add_argument(
        "--modes", default="",
        help="comma-separated mode filter (e.g. 'multi-relay,multi-pull'); "
        "empty = all modes",
    )
    args = ap.parse_args(argv)

    if args.cell:
        print(json.dumps(run_cell(json.loads(args.cell))))
        return

    scale = int(os.environ.get("BENCHMARKS_SCALE", "20"))
    datasets = [d for d in args.datasets.split(",") if d]
    results: list[dict] = []
    prior: list[dict] = []
    if args.merge and os.path.exists(os.path.join(_REPO_ROOT, "BENCHMARKS.json")):
        with open(os.path.join(_REPO_ROOT, "BENCHMARKS.json")) as f:
            prior = json.load(f).get("results", [])

    mode_filter = {m for m in args.modes.split(",") if m}

    def cell(dataset, mode, virtual=None, **kw):
        if mode_filter and mode not in mode_filter:
            return None
        spec = {"dataset": dataset, "mode": mode, "scale": scale,
                "repeats": args.repeats, **kw}
        t0 = time.time()
        r = _run_subprocess(spec, virtual, args.timeout)
        r.setdefault("dataset", dataset)
        r.setdefault("mode", mode)
        status = "ERR: " + r["error"][:60] if "error" in r else _cell_str(r)
        print(f"[{time.time() - t0:6.1f}s] {dataset:8s} {mode:16s} {status}",
              file=sys.stderr)
        results.append(r)
        return r

    for ds in datasets:
        cell(ds, "serial-native")
        if ds in ("tinyCG", "randomG"):
            cell(ds, "serial-python")
        for engine in ENGINES:
            cell(ds, engine)
        for n in SHARD_COUNTS:
            cell(ds, f"sharded-pull-{n}", virtual=max(SHARD_COUNTS))
        for n in SHARD_COUNTS:
            cell(ds, f"sharded-relay-{n}", virtual=max(SHARD_COUNTS))
    if not args.skip_multi and "rmat" in datasets:
        for engine in ("pull", "relay", "elem"):
            cell("rmat", f"multi-{engine}", num_sources=64)

    if prior:
        done = {(r.get("dataset"), r.get("mode")) for r in results}
        results = [
            r for r in prior if (r.get("dataset"), r.get("mode")) not in done
        ] + results
    payload = {
        "scale": scale,
        "shard_counts": list(SHARD_COUNTS),
        "reference_table7_seconds": {
            f"{k[0]}/{k[1]}": v for k, v in REFERENCE_TABLE7.items()
        },
        "results": results,
    }
    with open(os.path.join(_REPO_ROOT, "BENCHMARKS.json"), "w") as f:
        json.dump(payload, f, indent=1)
    _write_markdown(results, scale)
    print(json.dumps({"cells": len(results),
                      "errors": sum(1 for r in results if "error" in r)}))


def _headline_rows() -> list[str]:
    """Headline-history table GENERATED from the committed BENCH_r*.json
    driver artifacts (plus BENCH_LOCAL*.json builder captures, if any) so a
    matrix regeneration can never silently drop the headline history
    (VERDICT round 3, weak #3)."""
    import glob

    rows = []
    paths = sorted(
        glob.glob(os.path.join(_REPO_ROOT, "BENCH_r*.json"))
        + glob.glob(os.path.join(_REPO_ROOT, "BENCH_LOCAL*.json"))
    )
    for path in paths:
        try:
            with open(path) as f:
                raw = json.load(f)
        except Exception:
            continue
        recs = raw if isinstance(raw, list) else [raw]
        for rec in recs:
            parsed = rec.get("parsed", rec) if isinstance(rec, dict) else None
            if not isinstance(parsed, dict) or "metric" not in parsed:
                continue
            value = parsed.get("value")
            if not isinstance(value, (int, float)):
                continue  # partial/errored capture — skip, never abort
            vs = parsed.get("vs_baseline")
            vs_str = f"{vs:.1f}x" if isinstance(vs, (int, float)) else "-"
            det = parsed.get("details", {})
            if not isinstance(det, dict):
                det = {}
            rows.append(
                f"| {os.path.basename(path)} | {parsed['metric']} | "
                f"{_fmt_teps(value)} | {vs_str} | "
                f"{det.get('applier', '-')} | {det.get('check', '-')} |"
            )
    if not rows:
        return []
    return [
        "",
        "## Headline history (generated from BENCH_r*.json artifacts)",
        "",
        "Real-TPU headline captures recorded by the round driver "
        "(`bench.py`, R-MAT scale-24 edge-factor-6 unless the metric says "
        "otherwise).  This table is REGENERATED from the committed JSON "
        "artifacts on every matrix run — edit those, not this file.",
        "",
        "| artifact | metric | TEPS | vs 13M serial floor | applier | check |",
        "|---|---|---|---|---|---|",
        *rows,
    ]


def _write_markdown(results: list[dict], scale: int) -> None:
    by = {(r["dataset"], r["mode"]): r for r in results}
    datasets = []
    for r in results:
        if r["dataset"] not in datasets:
            datasets.append(r["dataset"])

    lines = [
        "# BENCHMARKS — serial vs engines vs shard counts",
        "",
        "Reproduction of the reference's Table 7 methodology "
        "(docs/BigData_Project.pdf §1.5) on this framework.  Cells are "
        "`median wall time (Graph500 TEPS)`; timings exclude graph "
        "construction, layout build and compile (the paper likewise excludes "
        "Spark startup and graph construction).  Engine cells run on the "
        "device listed; shard cells run on the single-host virtual 8-device "
        "CPU platform — the paper's own \"N workers, one machine\" "
        "methodology (multi-chip TPU hardware is exercised separately by "
        "`__graft_entry__.dryrun_multichip`).",
        "",
    ]
    dev = next((r.get("device") for r in results
                if r.get("mode") in ENGINES and "device" in r), "?")
    lines.append(f"Engine cells device: **{dev}**.  R-MAT scale: **{scale}**, "
                 "edge factor 16, Graph500 parameters.")
    lines.append("")
    cols = (["serial-native", "serial-python"] + list(ENGINES)
            + [f"sharded-pull-{n}" for n in SHARD_COUNTS]
            + [f"sharded-relay-{n}" for n in SHARD_COUNTS])
    header = ("| dataset | " + " | ".join(
        c.replace("sharded-pull-", "pull ×").replace("sharded-relay-", "relay ×")
        for c in cols) + " |")
    lines.append(header)
    lines.append("|" + "---|" * (len(cols) + 1))
    for ds in datasets:
        row = [by.get((ds, c)) for c in cols]
        label = next((r["label"] for r in results
                      if r["dataset"] == ds and "label" in r), ds)
        lines.append(
            f"| {label} | "
            + " | ".join("-" if r is None else _cell_str(r) for r in row)
            + " |"
        )
    lines += [
        "",
        "## Reference (Spark 1.4, paper Table 7) for comparison",
        "",
        "| dataset | serial (JVM) | 1 worker | 2 workers | 10 workers |",
        "|---|---|---|---|---|",
    ]
    for ds, ref_ds in (("tinyCG", "tinyCG"), ("randomG", "mediumG"),
                       ("largeG", "largeG")):
        if ds not in datasets:
            continue
        vals = [REFERENCE_TABLE7.get((ref_ds, c))
                for c in ("serial", "workers1", "workers2", "workers10")]
        lines.append(f"| {ref_ds} | " + " | ".join(_fmt_secs(v) for v in vals) + " |")
    lines += [
        "",
        "The reference's parallel engine never beat its serial baseline at any "
        "scale and OOMed on largeG (paper §1.5-1.6); the rows above are the "
        "numbers this framework is measured against.",
    ]
    multi = [r for r in results if r.get("mode", "").startswith("multi-")]
    if multi:
        lines += [
            "",
            "## Batched multi-source (BASELINE.json config 5)",
            "",
            "| dataset | engine | sources | time | aggregate TEPS |",
            "|---|---|---|---|---|",
        ]
        for r in multi:
            if "error" in r:
                lines.append(f"| {r['dataset']} | {r['mode']} | - | ERR | - |")
            else:
                lines.append(
                    f"| {r.get('label', r['dataset'])} | "
                    f"{r['mode'].split('-', 1)[1]} | {r['num_sources']} | "
                    f"{_fmt_secs(r['seconds'])} | {_fmt_teps(r['teps'])} |"
                )
    # Per-cell verification summary: every non-error cell is checked against
    # the ported algs4 check() invariants before its time is recorded.
    checked = [r for r in results if "check" in r]
    n_pass = sum(1 for r in checked if str(r["check"]).startswith("passed"))
    n_err = sum(1 for r in results if "error" in r)
    lines += [
        "",
        f"**Verification:** {n_pass}/{len(checked)} measured cells passed "
        "the ported algs4 `check()` optimality invariants (per-cell, before "
        "the time was recorded; see each cell's `check` field in "
        "BENCHMARKS.json)."
        + (
            f"  {n_err} cell(s) marked ERR record a real failure — the "
            "full message is in BENCHMARKS.json (e.g. the pull engine's "
            "ELL layout exceeds single-chip HBM on the LiveJournal-shape "
            "graph; the relay engine runs it)."
            if n_err
            else ""
        ),
    ]
    exch = [
        r for r in results
        if "exchange_bytes_per_superstep" in r and "error" not in r
    ]
    if exch:
        lines += [
            "",
            "## Sharded exchange volume (ICI bytes per superstep)",
            "",
            "Bit-packed frontier all-gather: 1 bit/vertex/superstep across "
            "the mesh (vs the reference shipping every serialized Vertex "
            "record through the Spark shuffle each superstep).",
            "",
            "| dataset | mode | shards | exchange bytes/superstep |",
            "|---|---|---|---|",
        ]
        for r in exch:
            lines.append(
                f"| {r.get('label', r['dataset'])} | {r['mode']} | "
                f"{r.get('shards', '-')} | "
                f"{r['exchange_bytes_per_superstep']:,} |"
            )
    lines += _headline_rows()
    with open(os.path.join(_REPO_ROOT, "BENCHMARKS.md"), "w") as f:
        f.write("\n".join(lines) + "\n")


if __name__ == "__main__":
    main()
