"""HLO-grade static analysis: compile the hot fused programs and check
what XLA actually *emitted*, not just what we asked for.

The three analyzer rungs now police every altitude of a hot program:

* AST (:mod:`.core` — TRC/RCD/LCK/OBS): what the SOURCE says;
* jaxpr (:mod:`.ir` — IR000-IR006): what we ASK XLA to do — donation
  annotations, loop-body eqns, declared collectives;
* HLO (this module — HLO000-HLO005): what XLA actually EMITS — every
  entry in :data:`bfs_tpu.analysis.ir.PROGRAM_SPECS` is
  ``.lower(...).compile()``d and the **optimized** HLO module plus the
  compiled-executable metadata are walked.  IR001 proved the donation
  annotation exists; HLO001 proves the executable realized the aliasing.
  IR004's HBM proof was a hand-rolled static estimate; HLO002 is XLA's
  own buffer assignment (``compiled.memory_analysis()``).

Rules (:mod:`.hlo_rules` implements the walks):

* **HLO001** — donation declared (the spec's ``donate`` map, IR001's
  input) but the parameter is absent from the compiled executable's
  ``input_output_alias`` map: the declaration was silently dropped and
  the carry's HBM doubles at runtime.
* **HLO002** — XLA's buffer assignment (argument/output/temp/generated-
  code bytes) checked against ``BFS_TPU_IR_HBM_GB`` as a *compiler-
  backed* footprint proof, plus a temp-bytes tripwire: a program whose
  temp bytes regress >10% over the committed per-program fingerprint
  (``hlo_fingerprints.json``) fails lint.
* **HLO003** — ``copy``/``transpose``/``bitcast-convert`` ops
  materialized *inside* the superstep ``while`` body (the fusion-break
  detector), plus a fusion-count fingerprint per program: more emitted
  kernels than the committed count is a fusion break.
* **HLO004** — collectives surviving to optimized HLO cross-checked
  against the declared exchange arms: a collective in a program that
  declares no mesh axes, a required exchange axis whose compiled module
  has NO collective at all, a loop collective moving a payload outside
  the declared exchange dtypes, and a loop-collective-count fingerprint
  (catches an all-gather XLA hoists out of — or duplicates into — the
  loop where the source shows exactly one).
* **HLO005** — ``custom-call``/infeed/outfeed/host send-recv surviving
  to optimized HLO in a hot program: an opaque escape hatch in a path
  every byte of which is supposed to be fused XLA.

Like the IR pass this module imports jax and is loaded only by the
``--hlo`` CLI path and the HLO tests.  Compiling every program costs
~30 s cold, so results are content-addressed exactly like the IR cache
(sources + jax version + backend + device count + flavor env +
fingerprint file; ``.bench_cache/hlo/``, ``BFS_TPU_HLO_CACHE``).
Findings share ``baseline.txt`` with line-drift-proof
``hlo:<program>:<detail>`` fingerprints.

The committed fingerprint file ``hlo_fingerprints.json`` pins one
metrics row per program (temp bytes, fusion count, loop-collective and
loop-materialization counts) for the environment it was generated in;
regression rules only fire when the current backend/jax/device-count
matches that environment, so a TPU run never diffs against CPU counts.
``bfs-tpu-lint --hlo --update-fingerprints`` regenerates it.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from dataclasses import dataclass, field

from .. import knobs
from .core import Finding
from .ir import (
    PROGRAM_SPECS,
    Program,
    SkipProgram,
    _ensure_jax_env,
    _source_fingerprint,
    repo_root,
)

#: Bump to invalidate every cached HLO result (rule semantics changed).
HLO_VERSION = 1

#: Env knobs keying the HLO result cache — DERIVED from the registry
#: (``affects`` contains ``hlo``); KNB002 proves membership against
#: bfs_tpu/knobs.py both ways.
_HLO_FLAVOR_ENV = knobs.flavor_env("hlo")

#: Temp-bytes regression tolerance over the committed fingerprint.
TEMP_REGRESSION_RATIO = 0.10

#: HLO collective opcodes that move payload between devices.
COLLECTIVE_OPS = frozenset({
    "all-reduce", "all-gather", "all-to-all", "collective-permute",
    "reduce-scatter", "collective-broadcast",
})

#: Materialized-layout opcodes HLO003 polices inside loop bodies.
MATERIALIZE_OPS = frozenset({"copy", "transpose", "bitcast-convert"})

#: Opcodes that escape the fused-XLA contract entirely (HLO005).
ESCAPE_OPS = frozenset({"custom-call", "infeed", "outfeed", "send", "recv"})

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

#: HLO element type -> the numpy-style dtype names the spec's
#: ``exchange_dtypes`` declares (the IR006/HLO004 shared vocabulary).
HLO_TO_NUMPY_DTYPE = {
    "pred": "bool", "s8": "int8", "u8": "uint8", "s16": "int16",
    "u16": "uint16", "s32": "int32", "u32": "uint32", "s64": "int64",
    "u64": "uint64", "f16": "float16", "bf16": "bfloat16",
    "f32": "float32", "f64": "float64",
}

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
# `%name = <shape> <opcode>(` — shape is non-greedy so the first
# word-followed-by-( after it is the opcode (tuple shapes contain
# brackets/braces but never a bare `word(`).
_INST_RE = re.compile(r"^\s+(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(.*?)\s([\w\-]+)\(")
_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->.*\{\s*$")
_CALLED_RE = re.compile(
    r"(?:calls|to_apply|body|condition|select|scatter)=%([\w.\-]+)"
)
_BRANCHES_RE = re.compile(r"branch_computations=\{([^}]*)\}")
# One aliased-parameter entry inside the module header's
# `input_output_alias={ {out_idx}: (param, {param_idx}, kind) }` map.
_ALIAS_RE = re.compile(r"\((\d+),\s*\{[\d,\s]*\},\s*(?:may|must)-alias\)")


def shape_bytes(shape: str) -> int:
    """Total bytes of an HLO shape string (tuples summed)."""
    total = 0
    for dt, dims in _SHAPE_RE.findall(shape):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def shape_dtypes(shape: str) -> list[str]:
    """HLO element types appearing in a shape string, in order."""
    return [dt for dt, _ in _SHAPE_RE.findall(shape) if dt in _DTYPE_BYTES]


def shape_max_elements(shape: str) -> int:
    """Largest per-array element count in an HLO shape string (tuples:
    the max over members).  The HLO004 payload criterion: a collective
    whose every result is a scalar (<= 1 element) is control plane (the
    ``changed`` reduce, the direction masses), whatever its byte size."""
    best = 0
    for dt, dims in _SHAPE_RE.findall(shape):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        best = max(best, n)
    return best


# `replica_groups={{0,1,...},{...}}` — explicit groups; the first
# group's id list is enough (XLA emits uniform group sizes for the
# mesh-axis collectives this repo compiles).
_GROUPS_RE = re.compile(r"replica_groups=\{\{([\d,\s]*)\}")
# `replica_groups=[G,S]<=[N]` — the iota v2 spelling: G groups of S.
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")


def replica_group_size(text: str) -> int | None:
    """Participants per replica group of a collective instruction line,
    or None when the instruction carries no replica_groups attribute
    (single-group collectives over all devices print ``{}`` on some XLA
    versions — those return None too and the caller falls back to the
    device count)."""
    m = _GROUPS_RE.search(text)
    if m:
        ids = [x for x in m.group(1).split(",") if x.strip()]
        return len(ids) or None
    m = _GROUPS_IOTA_RE.search(text)
    if m:
        return int(m.group(2))
    return None


@dataclass
class Instruction:
    opcode: str
    shape: str
    text: str

    @property
    def nbytes(self) -> int:
        return shape_bytes(self.shape)

    def called(self) -> list[str]:
        """Computation names this instruction invokes.  Fusion
        sub-computations are excluded on purpose: ops inside a fusion
        are codegenned into ONE kernel and never materialize."""
        if self.opcode == "fusion":
            return []
        names = _CALLED_RE.findall(self.text)
        m = _BRANCHES_RE.search(self.text)
        if m:
            names.extend(
                x.strip().lstrip("%") for x in m.group(1).split(",")
                if x.strip()
            )
        return names


@dataclass
class HloModule:
    """One parsed optimized-HLO text module."""

    header: str = ""
    computations: dict = field(default_factory=dict)  # name -> [Instruction]
    entry: str = ""

    @property
    def aliased_params(self) -> frozenset:
        """Entry parameter numbers the executable aliases to an output —
        the compiled reality of donation.  The alias-entry shape
        ``(param, {indices}, may|must-alias)`` appears nowhere else in a
        module header, so the whole header is scanned (the map itself
        nests braces, which defeats a non-greedy region match)."""
        if "input_output_alias" not in self.header:
            return frozenset()
        return frozenset(int(p) for p in _ALIAS_RE.findall(self.header))

    def instructions(self):
        for name, insts in self.computations.items():
            for inst in insts:
                yield name, inst

    def loop_computations(self) -> frozenset:
        """Names of computations that execute once per loop iteration:
        every ``while`` body and condition, transitively through called
        computations (conditional branches, sort comparators) but NOT
        through fusion sub-computations."""
        seeds: list[str] = []
        for _name, inst in self.instructions():
            if inst.opcode == "while":
                seeds.extend(inst.called())  # body= and condition=
        seen: set[str] = set()
        work = list(seeds)
        while work:
            comp = work.pop()
            if comp in seen:
                continue
            seen.add(comp)
            for inst in self.computations.get(comp, ()):
                work.extend(inst.called())
        return frozenset(seen)

    def loop_instructions(self):
        for comp in self.loop_computations():
            for inst in self.computations.get(comp, ()):
                yield comp, inst


def parse_hlo(text: str) -> HloModule:
    """Parse optimized-HLO module text into computations of opcoded
    instructions.  Tolerant by construction: an unrecognized line is
    simply not an instruction."""
    mod = HloModule()
    lines = text.splitlines()
    if lines:
        mod.header = lines[0]
    cur: list[Instruction] | None = None
    for line in lines:
        if not line.startswith(" "):
            m = _COMP_RE.match(line.strip())
            if m:
                name = m.group(2)
                cur = mod.computations.setdefault(name, [])
                if m.group(1):
                    mod.entry = name
                continue
            if line.strip() == "}":
                cur = None
            continue
        if cur is None:
            continue
        m = _INST_RE.match(line)
        if m:
            cur.append(Instruction(
                opcode=m.group(2), shape=m.group(1), text=line,
            ))
    return mod


# --------------------------------------------------------------------------
# Compile + per-program metrics.
# --------------------------------------------------------------------------

def compile_program(prog: Program) -> tuple[HloModule, dict]:
    """``.lower(...).compile()`` the spec's program and return the parsed
    optimized module plus XLA's buffer-assignment stats.

    Jit-wrapped spec fns are lowered DIRECTLY (``fn.lower(...)``): an
    outer ``jax.jit`` wrapper would silently drop the inner pjit's
    donation — exactly the failure mode HLO001 polices, so the analyzer
    must not introduce it itself.  Plain fns get the wrapper (they never
    declare donation)."""
    import jax

    fn = prog.fn
    if hasattr(fn, "lower"):
        lowered = fn.lower(*prog.args, **prog.static_kwargs)
    else:
        # One-shot per analyzed program per cold run; the result cache
        # means the fresh callable identity never recurs at steady state.
        lowered = jax.jit(  # bfs_tpu: ok RCD001 analyzer compiles once per program, result content-address-cached
            lambda *a: fn(*a, **prog.static_kwargs)
        ).lower(*prog.args)
    compiled = lowered.compile()
    module = parse_hlo(compiled.as_text())
    ma = compiled.memory_analysis()
    mem = {}
    if ma is not None:
        mem = {
            "argument_bytes": int(getattr(ma, "argument_size_in_bytes", 0)),
            "output_bytes": int(getattr(ma, "output_size_in_bytes", 0)),
            "temp_bytes": int(getattr(ma, "temp_size_in_bytes", 0)),
            "alias_bytes": int(getattr(ma, "alias_size_in_bytes", 0)),
            "generated_code_bytes": int(
                getattr(ma, "generated_code_size_in_bytes", 0)
            ),
        }
    return module, mem


def materialize_floor(prog: Program) -> int:
    """HLO003's byte floor: a packed frontier-word array is V/32 uint32
    words = V/8 bytes, the smallest per-superstep buffer whose copy
    matters — everything at or above it (word arrays, V-sized state,
    E-sized candidates) is policed; loop-carry scalar copies are not."""
    return max(prog.v_elements // 8, 64)


def program_metrics(prog: Program, module: HloModule, mem: dict) -> dict:
    """The per-program fingerprint row: the compiled-artifact shape a PR
    must not silently regress."""
    floor = materialize_floor(prog)
    fusions = sum(
        1 for _c, i in module.instructions() if i.opcode == "fusion"
    )
    instructions = sum(1 for _ in module.instructions())
    collectives = sum(
        1 for _c, i in module.instructions() if i.opcode in COLLECTIVE_OPS
    )
    loop_coll = sum(
        1 for _c, i in module.loop_instructions()
        if i.opcode in COLLECTIVE_OPS
    )
    loop_mat = sum(
        1 for _c, i in module.loop_instructions()
        if i.opcode in MATERIALIZE_OPS and i.nbytes >= floor
    )
    return {
        "fusions": fusions,
        "instructions": instructions,
        "collectives": collectives,
        "loop_collectives": loop_coll,
        "loop_materializations": loop_mat,
        "temp_bytes": int(mem.get("temp_bytes", 0)),
        "argument_bytes": int(mem.get("argument_bytes", 0)),
        "output_bytes": int(mem.get("output_bytes", 0)),
        "alias_bytes": int(mem.get("alias_bytes", 0)),
        "generated_code_bytes": int(mem.get("generated_code_bytes", 0)),
    }


def analyze_compiled(
    prog: Program, fingerprint: dict | None = None
) -> tuple[list[Finding], dict]:
    """All HLO findings for one program plus its metrics row.
    ``fingerprint`` is the committed metrics row to diff against (None =
    no regression checks — a new or foreign-environment program)."""
    from .hlo_rules import check_compiled

    def make_finding(rule: str, detail: str, message: str) -> Finding:
        return Finding(
            rule=rule, path=prog.path, line=0, col=0,
            message=f"[{prog.name}] {message}",
            snippet=f"hlo:{prog.name}:{detail}",
        )

    try:
        module, mem = compile_program(prog)
    except SkipProgram:
        raise
    except Exception as exc:
        return [make_finding(
            "HLO000", "compile",
            f"could not compile to an executable: "
            f"{type(exc).__name__}: {exc}",
        )], {}
    metrics = program_metrics(prog, module, mem)
    findings = check_compiled(prog, module, mem, metrics, fingerprint,
                              make_finding)
    seen, out = set(), []
    for f in findings:
        key = (f.rule, f.snippet)
        if key not in seen:
            seen.add(key)
            out.append(f)
    out.sort(key=lambda f: (f.path, f.rule, f.snippet))
    return out, metrics


# --------------------------------------------------------------------------
# Committed fingerprints.
# --------------------------------------------------------------------------

def default_fingerprints_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "hlo_fingerprints.json"
    )


def current_env() -> dict:
    import jax

    return {
        "backend": jax.default_backend(),
        "devices": len(jax.devices()),
        "jax": jax.__version__,
    }


def load_fingerprints(path: str | None = None) -> tuple[str, dict]:
    """``(status, programs)`` where status is ``match`` (regression rules
    active), ``foreign`` (file from another backend/jax/device-count —
    counts not comparable, rules skipped) or ``missing``."""
    path = path or default_fingerprints_path()
    if not os.path.exists(path):
        return "missing", {}
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
        programs = doc.get("programs", {})
        env = doc.get("env", {})
    except (ValueError, OSError):
        return "missing", {}
    if env != current_env():
        return "foreign", programs
    return "match", programs


#: The metric keys a fingerprint row pins (regression-checked subset +
#: the context columns tools/hlo_diff.py renders).
FINGERPRINT_KEYS = (
    "temp_bytes", "fusions", "loop_collectives", "loop_materializations",
    "collectives", "argument_bytes", "output_bytes", "alias_bytes",
)


def write_fingerprints(path: str, fingerprints: dict) -> None:
    doc = {
        "env": current_env(),
        "programs": {
            name: {k: row[k] for k in FINGERPRINT_KEYS if k in row}
            for name, row in sorted(fingerprints.items())
        },
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


# --------------------------------------------------------------------------
# Content-addressed result cache + the repo entry point.
# --------------------------------------------------------------------------

def default_cache_dir(root: str | None = None) -> str:
    env = knobs.raw("BFS_TPU_HLO_CACHE") or ""
    if env:
        return env
    return os.path.join(root or repo_root(), ".bench_cache", "hlo")


def _cache_key(root: str, fingerprints_path: str) -> str:
    import jax

    h = hashlib.blake2b(digest_size=16)
    h.update(_source_fingerprint(root).encode())
    h.update(jax.__version__.encode())
    h.update(jax.default_backend().encode())
    h.update(str(len(jax.devices())).encode())
    h.update(str(HLO_VERSION).encode())
    h.update(",".join(sorted(PROGRAM_SPECS)).encode())
    for env in _HLO_FLAVOR_ENV:
        h.update(f"{env}={os.environ.get(env, '')};".encode())
    # The committed fingerprint file is a rule input: edit it and the
    # regression findings change, so the cache must miss.
    try:
        with open(fingerprints_path, "rb") as fh:
            h.update(fh.read())
    except OSError:
        h.update(b"no-fingerprints")
    return h.hexdigest()


def _finding_to_dict(f: Finding) -> dict:
    return {
        "rule": f.rule, "path": f.path, "line": f.line, "col": f.col,
        "message": f.message, "snippet": f.snippet,
    }


def analyze_hlo(
    specs: dict | None = None,
    *,
    use_cache: bool = True,
    cache_dir: str | None = None,
    root: str | None = None,
    fingerprints_path: str | None = None,
) -> tuple[list[Finding], dict]:
    """Run the HLO pass over ``specs`` (default: the canonical
    :data:`~bfs_tpu.analysis.ir.PROGRAM_SPECS` registry).  Returns
    ``(findings, meta)``; ``meta`` carries cache disposition, skipped
    programs, the per-program metrics rows (``meta['fingerprints']``)
    and the committed-fingerprint status.  Custom specs are never
    cached — only the canonical registry is content-addressed."""
    _ensure_jax_env()
    root = root or repo_root()
    fingerprints_path = fingerprints_path or default_fingerprints_path()
    custom = specs is not None
    specs = specs if custom else PROGRAM_SPECS
    fp_status, committed = load_fingerprints(fingerprints_path)
    meta: dict = {
        "cache": "off" if (custom or not use_cache) else "miss",
        "programs": [], "skipped": {}, "fingerprints": {},
        "fingerprint_status": fp_status,
        "unfingerprinted": [],
    }

    cache_path = None
    if not custom and use_cache:
        key = _cache_key(root, fingerprints_path)
        cache_path = os.path.join(
            cache_dir or default_cache_dir(root), f"hlo_{key}.json"
        )
        if os.path.exists(cache_path):
            try:
                with open(cache_path, encoding="utf-8") as fh:
                    doc = json.load(fh)
                meta.update(doc.get("meta", {}))
                meta["cache"] = "hit"
                return [Finding(**d) for d in doc["findings"]], meta
            except (ValueError, KeyError, TypeError):
                pass  # corrupt cache entry: recompute and overwrite

    findings: list[Finding] = []
    for name, build in specs.items():
        fingerprint = committed.get(name) if fp_status == "match" else None
        try:
            prog = build()
            result, metrics = analyze_compiled(prog, fingerprint)
        except SkipProgram as exc:
            meta["skipped"][name] = str(exc)
            continue
        except Exception as exc:
            findings.append(Finding(
                rule="HLO000", path="bfs_tpu/analysis/hlo.py", line=0, col=0,
                message=f"[{name}] spec builder failed: "
                        f"{type(exc).__name__}: {exc}",
                snippet=f"hlo:{name}:builder",
            ))
            continue
        meta["programs"].append(name)
        if metrics:
            meta["fingerprints"][name] = metrics
        if fp_status == "match" and name not in committed:
            meta["unfingerprinted"].append(name)
        findings.extend(result)

    findings.sort(key=lambda f: (f.path, f.rule, f.snippet))
    if cache_path is not None:
        try:
            os.makedirs(os.path.dirname(cache_path), exist_ok=True)
            tmp = f"{cache_path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(
                    {"meta": {k: v for k, v in meta.items()
                              if k != "cache"},
                     "findings": [_finding_to_dict(f) for f in findings]},
                    fh,
                )
            os.replace(tmp, cache_path)
        except OSError:
            pass
    return findings, meta
