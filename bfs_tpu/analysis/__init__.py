"""bfs_tpu.analysis — project linter + runtime sanitizers.

Static half (stdlib-only, never imports jax): three AST analyzer families
over the repo's own sources —

* **transfer/trace-safety** (TRC*): implicit host<->device syncs and
  materializations inside declared hot regions;
* **recompile drift** (RCD*): jit call sites whose callable identity or
  static signature can change per call, and executable-cache keys that
  under- or over-key their build closures;
* **lock discipline** (LCK*): ``# guarded-by:`` annotated shared fields
  must be accessed under their lock.

Runtime half (:mod:`.runtime`): env-gated ``jax.transfer_guard`` regions
and per-function retrace counters.

CLI: ``python -m bfs_tpu.analysis`` (or ``tools/lint.py`` /
``bfs-tpu-lint``).  Exit 0 = clean modulo the committed baseline.
"""

from __future__ import annotations

import os

from .core import RULES, Baseline, Finding, SourceFile
from .locks import check_locks
from .obs import check_obs
from .recompile import check_recompile
from .runtime import (
    format_retrace_report,
    guarded_region,
    hot_region,
    retrace_report,
    traced,
    transfer_guard_level,
)
from .transfer import check_transfer

__all__ = [
    "RULES", "Baseline", "Finding", "SourceFile",
    "analyze_file", "analyze_paths", "default_baseline_path",
    "guarded_region", "hot_region", "traced",
    "retrace_report", "format_retrace_report", "transfer_guard_level",
]

_CHECKERS = (check_transfer, check_recompile, check_locks, check_obs)

#: Directories never linted even when a parent is passed (generated
#: artifacts, caches, VCS internals).  ``fixtures`` keeps deliberately
#: broken test snippets out of a whole-repo run.
_SKIP_DIRS = {
    ".git", "__pycache__", ".bench_cache", "build", "dist",
    "node_modules", ".eggs", "fixtures",
}


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.txt")


def analyze_file(path: str, root: str, text: str | None = None) -> list[Finding]:
    """All findings for one module; a syntax error becomes a single
    error-severity finding rather than an analyzer crash."""
    try:
        src = SourceFile(path, root, text=text)
    except SyntaxError as exc:
        rel = os.path.relpath(os.path.abspath(path), root).replace(os.sep, "/")
        return [
            Finding(
                rule="TRC000", path=rel, line=exc.lineno or 0, col=0,
                message=f"could not parse: {exc.msg}", snippet="",
            )
        ]
    findings: list[Finding] = []
    for line, msg in src.pragma_problems:
        if not src.suppressed(line, "PRG001"):
            findings.append(
                Finding(
                    rule="PRG001", path=src.path, line=line, col=0,
                    message=msg, snippet=src.snippet(line),
                )
            )
    for checker in _CHECKERS:
        findings.extend(checker(src))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def iter_python_files(paths: list[str]):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in _SKIP_DIRS and not d.startswith(".")
            )
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def analyze_paths(paths: list[str], root: str) -> list[Finding]:
    findings: list[Finding] = []
    for path in iter_python_files(paths):
        findings.extend(analyze_file(path, root))
    return findings
