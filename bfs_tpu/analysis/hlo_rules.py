"""The HLO001-HLO005 walks over a compiled program's optimized module.

Split from :mod:`.hlo` the way :mod:`.collectives` is split from
:mod:`.ir`: hlo.py owns compiling, parsing, caching and fingerprints;
this module owns what a finding *is*.  Every check receives the parsed
:class:`~bfs_tpu.analysis.hlo.HloModule`, XLA's buffer-assignment stats,
the freshly computed metrics row and (when the committed fingerprint
file matches the current environment) the committed row to diff against.
"""

from __future__ import annotations

from .hlo import (
    COLLECTIVE_OPS,
    ESCAPE_OPS,
    HLO_TO_NUMPY_DTYPE,
    MATERIALIZE_OPS,
    TEMP_REGRESSION_RATIO,
    materialize_floor,
    replica_group_size,
    shape_max_elements,
)

_WIDE_NUMPY = frozenset({"int64", "uint64", "float64"})


def check_compiled(prog, module, mem, metrics, fingerprint, make_finding):
    """All HLO-rule findings for one compiled program."""
    findings = []
    findings += check_donation_realized(prog, module, mem, make_finding)
    findings += check_buffer_assignment(
        prog, mem, metrics, fingerprint, make_finding
    )
    findings += check_loop_materialization(
        prog, module, metrics, fingerprint, make_finding
    )
    findings += check_compiled_collectives(
        prog, module, metrics, fingerprint, make_finding
    )
    findings += check_escapes(prog, module, make_finding)
    return findings


# --------------------------------------------------------------------------
# HLO001 — declared donation must be REALIZED by the executable.
# --------------------------------------------------------------------------

def check_donation_realized(prog, module, mem, make_finding):
    """The spec's ``donate`` map names carries IR001 already proved are
    *declared* donated.  Here the compiled executable itself must list
    the corresponding entry parameters in ``input_output_alias`` — a
    declaration the compiler dropped (nested-jit inlining, an
    aliasing-hostile layout) doubles the carry's HBM with the jaxpr rung
    still green."""
    if not prog.donate:
        return []
    if not hasattr(prog.fn, "lower"):
        # The analyzer had to wrap the fn in an outer jit to compile it,
        # which itself drops inner donation — aliasing is unprovable.
        return [make_finding(
            "HLO001", "unprovable",
            "spec declares donated carries but its fn is not a jit "
            "artifact — the compiled executable cannot be checked for "
            "realized aliasing; register the jitted program object",
        )]
    import jax

    ranges, start = [], 0
    for a in prog.args:
        n = len(jax.tree_util.tree_leaves(a))
        ranges.append((start, start + n))
        start += n
    aliased = module.aliased_params
    findings = []
    for argidx, label in sorted(prog.donate.items()):
        lo, _hi = ranges[argidx]
        leaves = jax.tree_util.tree_leaves(prog.args[argidx])
        missing = 0
        for off, leaf in enumerate(leaves):
            size = int(getattr(leaf, "size", 0))
            if size >= prog.v_elements and (lo + off) not in aliased:
                missing += size * leaf.dtype.itemsize
        if missing:
            findings.append(make_finding(
                "HLO001", f"donate:{label}",
                f"carry '{label}' is declared donated but the compiled "
                f"executable's input_output_alias map does not alias its "
                f"parameter(s) — the donation was dropped between jaxpr "
                f"and buffer assignment; +{missing} dead input bytes "
                f"stay live next to the output (executable alias bytes: "
                f"{mem.get('alias_bytes', 0)})",
            ))
    return findings


# --------------------------------------------------------------------------
# HLO002 — compiler-backed HBM proof + temp-bytes tripwire.
# --------------------------------------------------------------------------

def check_buffer_assignment(prog, mem, metrics, fingerprint, make_finding):
    findings = []
    if prog.budget_bytes and mem:
        # alias bytes appear in BOTH the argument and the output totals
        # but occupy ONE buffer (that is what a realized donation means)
        # — subtract once or a donated V-sized carry double-counts.
        total = (
            mem.get("argument_bytes", 0) + mem.get("output_bytes", 0)
            + mem.get("temp_bytes", 0) + mem.get("generated_code_bytes", 0)
            - mem.get("alias_bytes", 0)
        )
        if total > prog.budget_bytes:
            findings.append(make_finding(
                "HLO002", "budget",
                f"XLA's buffer assignment needs {total} bytes (arguments "
                f"{mem.get('argument_bytes', 0)} + outputs "
                f"{mem.get('output_bytes', 0)} + temps "
                f"{mem.get('temp_bytes', 0)} + generated code "
                f"{mem.get('generated_code_bytes', 0)} - aliased "
                f"{mem.get('alias_bytes', 0)}) — over the declared "
                f"{prog.budget_bytes}-byte budget; unlike IR004's static "
                "estimate this is the compiler's own allocation, not a "
                "bound",
            ))
    if fingerprint and "temp_bytes" in fingerprint:
        base = int(fingerprint["temp_bytes"])
        now = int(metrics.get("temp_bytes", 0))
        if now > base * (1 + TEMP_REGRESSION_RATIO):
            pct = (now - base) * 100.0 / base if base else float("inf")
            findings.append(make_finding(
                "HLO002", "regress:temp",
                f"temp buffer bytes regressed {base} -> {now} "
                f"(+{pct:.0f}%, tripwire is "
                f"+{TEMP_REGRESSION_RATIO:.0%}) vs the committed "
                "fingerprint — a new scratch buffer or a lost in-place "
                "update in the hot program; re-fingerprint only with "
                "justification (bfs-tpu-lint --hlo --update-fingerprints)",
            ))
    return findings


# --------------------------------------------------------------------------
# HLO003 — materialized layout ops inside the superstep while body.
# --------------------------------------------------------------------------

def check_loop_materialization(prog, module, metrics, fingerprint,
                               make_finding):
    floor = materialize_floor(prog)
    per_op: dict[str, tuple[int, int]] = {}
    for _comp, inst in module.loop_instructions():
        if inst.opcode in MATERIALIZE_OPS and inst.nbytes >= floor:
            n, b = per_op.get(inst.opcode, (0, 0))
            per_op[inst.opcode] = (n + 1, b + inst.nbytes)
    findings = []
    for op in sorted(per_op):
        n, b = per_op[op]
        findings.append(make_finding(
            "HLO003", f"loop:{op}",
            f"{n} materialized '{op}' op(s) ({b} bytes/iteration at lint "
            f"scale, floor {floor}) inside the superstep while body — a "
            "buffer XLA copies every superstep that the source never "
            "asked for (fusion break or copy insertion on a multi-read "
            "carry)",
        ))
    if fingerprint:
        if "fusions" in fingerprint and (
            metrics.get("fusions", 0) > int(fingerprint["fusions"])
        ):
            findings.append(make_finding(
                "HLO003", "regress:fusions",
                f"emitted fusion count grew "
                f"{fingerprint['fusions']} -> {metrics.get('fusions')} vs "
                "the committed fingerprint — a previously fused region "
                "now launches as separate kernels",
            ))
        if "loop_materializations" in fingerprint and (
            metrics.get("loop_materializations", 0)
            > int(fingerprint["loop_materializations"])
        ):
            findings.append(make_finding(
                "HLO003", "regress:loop-materialize",
                f"materialized copy/transpose ops in the while body grew "
                f"{fingerprint['loop_materializations']} -> "
                f"{metrics.get('loop_materializations')} vs the committed "
                "fingerprint — per-superstep HBM traffic nobody asked for",
            ))
    return findings


# --------------------------------------------------------------------------
# HLO004 — collectives as compiled, vs the declared exchange.
# --------------------------------------------------------------------------

def check_compiled_collectives(prog, module, metrics, fingerprint,
                               make_finding):
    findings = []
    all_colls = [
        inst for _c, inst in module.instructions()
        if inst.opcode in COLLECTIVE_OPS
    ]
    if prog.mesh_axes is None and all_colls:
        ops = sorted({i.opcode for i in all_colls})
        findings.append(make_finding(
            "HLO004", "unexpected",
            f"{len(all_colls)} collective op(s) ({', '.join(ops)}) in the "
            "optimized module of a program that declares NO mesh axes — "
            "per-call device traffic nobody budgeted",
        ))
    if prog.required_axes and not all_colls:
        findings.append(make_finding(
            "HLO004", "missing-collective",
            f"spec requires an exchange over "
            f"{sorted(prog.required_axes)} but the optimized module "
            "contains no collective at all — the per-superstep merge "
            "was compiled away (the compiled twin of IR005)",
        ))
    allowed = frozenset(prog.exchange_dtypes)
    for _comp, inst in module.loop_instructions():
        if inst.opcode not in COLLECTIVE_OPS:
            continue
        if inst.nbytes < prog.exchange_floor:
            continue  # control-plane scalar (the `changed` reduce etc.)
        for dt in shape_numpy_dtypes(inst.shape):
            if dt in _WIDE_NUMPY or dt not in allowed:
                findings.append(make_finding(
                    "HLO004", f"payload:{inst.opcode}:{dt}",
                    f"loop-body collective '{inst.opcode}' moves a "
                    f"{inst.nbytes}-byte {dt} payload; the declared "
                    f"exchange format is {sorted(allowed)} — the "
                    "compiled wire format drifted from the spec",
                ))
    if fingerprint and "loop_collectives" in fingerprint:
        base = int(fingerprint["loop_collectives"])
        now = int(metrics.get("loop_collectives", 0))
        if now != base:
            what = "duplicated into" if now > base else "hoisted out of"
            findings.append(make_finding(
                "HLO004", "regress:collectives",
                f"loop-body collective count changed {base} -> {now} vs "
                f"the committed fingerprint — XLA {what} the superstep "
                "loop a collective the source shows once; per-superstep "
                "ICI traffic changed shape",
            ))
    if prog.loop_payload_groups is not None:
        # The per-AXIS contract (2D grid, ISSUE 17): the loop body must
        # compile exactly the declared multiset of PAYLOAD collectives,
        # identified by replica-group size — one group-size-c broadcast
        # over the column axis, one group-size-r reduce over the row
        # axis.  Payload = any non-scalar result: the byte floor would
        # let a tiny-scale lint module misclassify the real wire moves,
        # and the control scalars (changed / direction masses) are
        # scalars at every scale.
        got = sorted(
            replica_group_size(inst.text) or 0
            for _comp, inst in module.loop_instructions()
            if inst.opcode in COLLECTIVE_OPS
            and shape_max_elements(inst.shape) > 1
        )
        want = sorted(int(g) for g in prog.loop_payload_groups)
        if got != want:
            findings.append(make_finding(
                "HLO004", "axis-groups",
                f"loop-body payload collectives compiled with replica "
                f"group sizes {got}, spec declares {want} — the "
                "per-axis exchange contract (one collective per mesh "
                "axis per superstep) does not hold in the optimized "
                "module; a global-group collective here is the 1D O(V) "
                "wire pattern this program exists to avoid",
            ))
    return findings


def shape_numpy_dtypes(shape: str) -> list[str]:
    from .hlo import shape_dtypes

    return [HLO_TO_NUMPY_DTYPE.get(dt, dt) for dt in shape_dtypes(shape)]


# --------------------------------------------------------------------------
# HLO005 — opaque escapes from the fused-XLA contract.
# --------------------------------------------------------------------------

def check_escapes(prog, module, make_finding):
    per_op: dict[str, int] = {}
    for _comp, inst in module.instructions():
        if inst.opcode in ESCAPE_OPS:
            per_op[inst.opcode] = per_op.get(inst.opcode, 0) + 1
    return [
        make_finding(
            "HLO005", f"escape:{op}",
            f"{n} '{op}' op(s) survive to the optimized HLO of a hot "
            "program — an opaque host/library escape in a path that is "
            "supposed to be fused XLA end to end",
        )
        for op, n in sorted(per_op.items())
    ]
