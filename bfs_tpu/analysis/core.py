"""Shared infrastructure for the project linters: findings, pragma
parsing, hot-region discovery, and the committed-baseline mechanism.

Everything here is stdlib-only (``ast`` + ``tokenize``): the pass must run
in tier-1 on a bare CPU image with no third-party linter installed, and it
must never import jax — analyzing ``ops/relay_pallas.py`` should not cost
a backend initialization.

Pragma syntax (all live in comments, so they are invisible to runtime):

``# bfs_tpu: hot``
    Marks the NEXT ``def`` at or below the comment (or the ``def`` on the
    same line) as a hot region: the transfer/trace-safety rules apply to
    its whole body.  Functions decorated with ``jax.jit`` (including
    ``functools.partial(jax.jit, ...)``) or with the
    :func:`bfs_tpu.analysis.runtime.hot_region` decorator are hot
    automatically.

``# bfs_tpu: hot-start`` / ``# bfs_tpu: hot-end``
    Bracket an arbitrary line range (e.g. the bench timed-repeat loop)
    as hot without factoring it into a function.

``# bfs_tpu: ok RULE[,RULE] [reason]``
    Suppress the named rules on this line (and, when the comment stands
    alone on its line, on the immediately following line).  ``ok *``
    suppresses everything — use sparingly; prefer the baseline file,
    which forces a justification.

``# guarded-by: lockname[|alt ...]``
    On a field assignment (``self.x = ...`` in a class, or a module-level
    global), declares that every later read/write must happen inside a
    ``with <lockname>`` block in the same class/module.  ``a|b`` means
    either lock is sufficient (e.g. a ``Condition`` wrapping the lock).

``# bfs_tpu: holds lockname[,lockname]``
    On a ``def``, declares that callers invoke this helper with the named
    locks already held (the ``@RequiresLock`` idiom) — the checker treats
    them as held for the whole body.

Baseline file: one accepted finding per line,
``RULE<TAB>fingerprint<TAB>justification``.  The fingerprint hashes the
rule, the repo-relative path and the stripped source line — NOT the line
number — so unrelated edits above a finding don't invalidate the whole
baseline, while any edit to the flagged line itself forces re-triage.
"""

from __future__ import annotations

import ast
import hashlib
import io
import os
import tokenize
from dataclasses import dataclass, field

SEVERITIES = ("error", "warning")

#: rule id -> (severity, one-line description); the catalog the CLI prints.
RULES: dict[str, tuple[str, str]] = {
    # -- transfer / trace-safety ------------------------------------------
    "TRC001": ("error", ".item() in a hot region forces a device->host sync"),
    "TRC002": ("error",
               "float()/int()/bool() on a non-constant in a hot region "
               "forces a device->host sync"),
    "TRC003": ("error",
               "np.asarray/np.array in a hot region materializes a "
               "device value on the host"),
    "TRC004": ("error",
               "jax.device_get/device_put in a hot region — transfers "
               "must live outside the timed/traced path or carry an "
               "explicit ok-pragma naming why"),
    "TRC005": ("error",
               "print() in a hot region syncs its device-array arguments "
               "and stalls the dispatch pipeline"),
    "TRC006": ("error",
               "Python control flow on a traced value concretizes it at "
               "trace time (use lax.cond/lax.while_loop/jnp.where)"),
    # -- recompile drift --------------------------------------------------
    "RCD001": ("error",
               "jax.jit(lambda/local def) inside a function: a fresh "
               "callable identity per call retraces every call"),
    "RCD002": ("error",
               "static_argnums/static_argnames/donate_* must be literal "
               "— a computed value drifts the static signature between "
               "call sites"),
    "RCD003": ("error",
               "jit()/lower()/compile() inside a loop body recompiles "
               "per iteration"),
    "RCD004": ("warning",
               "compile-cache key element computed per call — confirm "
               "the derivation buckets to a bounded shape set"),
    "RCD005": ("error",
               "executable-cache build closure reads a local that is not "
               "part of the cache key (under-keyed executable)"),
    # -- observability discipline -----------------------------------------
    "OBS001": ("error",
               "telemetry/metrics read inside a declared hot region — "
               "telemetry rides the loop carry and is pulled once at "
               "loop exit (one device_get), never mid-loop"),
    # -- pragma hygiene ---------------------------------------------------
    "PRG001": ("error",
               "overlapping '# bfs_tpu: hot-start' — the previous span "
               "was still open; a span silently dropped from hot "
               "coverage is a policed region that isn't"),
    # -- lock discipline --------------------------------------------------
    "LCK001": ("error",
               "guarded-by field accessed outside its declared lock"),
    "LCK002": ("warning",
               "shared mutable field in a lock-owning class has no "
               "guarded-by annotation"),
    # -- IR-grade rules (bfs_tpu.analysis.ir — lowers the hot fused
    # programs to jaxprs; unlike the AST rules these need jax) ------------
    "IR000": ("error",
              "hot program failed to build/lower for IR analysis — a "
              "policed program that cannot be checked is unpoliced"),
    "IR001": ("error",
              "V-sized carry not donated to its consumer program: both "
              "the dead input and the output stay live, doubling the "
              "carry's HBM bytes for the call"),
    "IR002": ("error",
              "host round-trip (callback/device_put-shaped eqn) inside a "
              "fused loop body — the whole superstep loop must stay one "
              "device-resident program"),
    "IR003": ("error",
              "dtype drift in a fused loop body: packed uint32 state "
              "words widened to f32/f64/i64, or int32 telemetry "
              "accumulators widened to 64-bit"),
    "IR004": ("error",
              "static HBM footprint estimate (operands + carries + "
              "temps from eqn shapes) exceeds the program's declared "
              "byte budget"),
    "IR005": ("error",
              "collective/mesh-axis mismatch: axis used but undeclared, "
              "a required exchange axis has no collective, or a "
              "shard_map result's sharding disagrees with the declared "
              "out_specs"),
    "IR006": ("error",
              "exchange payload regressed: a collective moves a V-scale "
              "payload whose dtype/width is outside the program's "
              "declared exchange format"),
    # -- HLO-grade rules (bfs_tpu.analysis.hlo — compiles the hot
    # programs and walks the OPTIMIZED HLO + executable metadata; the
    # third rung: AST = source, jaxpr = what we ask, HLO = what XLA
    # emits) --------------------------------------------------------------
    "HLO000": ("error",
               "hot program failed to compile for HLO analysis — a "
               "policed executable that cannot be built is unpoliced"),
    "HLO001": ("error",
               "declared donation dropped by the compiler: the carry's "
               "parameter is absent from the executable's "
               "input_output_alias map, so its HBM silently doubles at "
               "runtime with the jaxpr rung still green"),
    "HLO002": ("error",
               "compiler-backed HBM proof failed: XLA's own buffer "
               "assignment (arguments+outputs+temps+code) exceeds the "
               "declared budget, or temp bytes regressed >10% over the "
               "committed per-program fingerprint"),
    "HLO003": ("error",
               "fusion break: copy/transpose/bitcast-convert "
               "materialized inside the superstep while body, or the "
               "emitted fusion/loop-materialization count grew over the "
               "committed fingerprint"),
    "HLO004": ("error",
               "compiled collective drift: a collective in a program "
               "declaring no mesh axes, a required exchange compiled "
               "away, a loop payload outside the declared exchange "
               "dtypes, or a loop-collective count changed vs the "
               "fingerprint (hoisted/duplicated)"),
    "HLO005": ("error",
               "custom-call/infeed/outfeed/send/recv survives to the "
               "optimized HLO of a hot program — an opaque escape from "
               "the fused-XLA contract"),
    # -- Pallas kernel-grade rules (bfs_tpu.analysis.pallas — runs every
    # registered kernel at lint scale under a pallas_call spy; the
    # fourth rung: AST = source, jaxpr = what we ask, HLO = what XLA
    # emits, PAL = what the hand-written kernels do) ----------------------
    "PAL000": ("error",
               "pallas kernel failed to build/run for analysis, the "
               "spec no longer reaches its pallas_call, or a "
               "pallas_call site is missing from KERNEL_SPECS — an "
               "unregistered kernel is an unpoliced kernel"),
    "PAL001": ("error",
               "VMEM residency proof failed: double-buffered block "
               "bytes + declared scratch exceed the per-core budget "
               "(BFS_TPU_PAL_VMEM_MB, default 16 MB) — Mosaic refuses "
               "or spills this on a real chip"),
    "PAL002": ("error",
               "tile misalignment: a block dimension violates the "
               "(8,128) sublane/lane tiling for its dtype (or the "
               "128x128 MXU tiling for a declared MXU kernel) — the "
               "padded lanes burn compute every grid step"),
    "PAL003": ("error",
               "grid write-aliasing: two grid steps map the same output "
               "block (a data race unless accumulation is declared), or "
               "output blocks are left unwritten (garbage output)"),
    "PAL004": ("error",
               "dynamic-slice bounds: a grid block or manual pl.ds DMA "
               "window reads outside its ref, or a non-dividing tile "
               "size silently drops the array's tail rows"),
    "PAL005": ("error",
               "interpret-vs-XLA parity broken: the kernel's "
               "interpret-mode output is not bit-identical to its "
               "shipping XLA fallback twin — one of the two is wrong "
               "on every backend that selects it"),
    # -- Knob-provenance rules (bfs_tpu.analysis.knobs — proves the
    # typed env-knob registry against the sources, the live cache-key
    # builders and the docs; the fifth rung: AST = source, jaxpr = what
    # we ask, HLO = what XLA emits, PAL = what the kernels do, KNB =
    # what the knobs that select between all of the above mean) ----------
    "KNB000": ("error",
               "knob pass could not prove a surface: a lint-surface "
               "module failed to parse or a cache-key provider failed "
               "to import — an unprovable key is an unkeyed one"),
    "KNB001": ("error",
               "knob provenance broken: a raw os.environ read of a "
               "BFS_TPU_* name bypasses the typed accessor, an "
               "accessor reads an unregistered name, or a registered "
               "knob has no live read site (dead registry row)"),
    "KNB002": ("error",
               "cache-key completeness broken: a knob's declared "
               "affects domains disagree with the LIVE flavor tuple a "
               "cache/journal/engine key actually hashes — a warm "
               "entry would replay under a knob it was never keyed on"),
    "KNB003": ("error",
               "knob scope discipline broken: a call-scoped knob is "
               "baked into an import-time constant, or a knob is read "
               "inside a traced region (the value burns into the "
               "compiled program while looking like a runtime switch)"),
    "KNB004": ("error",
               "knob doc coverage broken: a registered knob has no "
               "README reference-table row, or a table row documents a "
               "knob that no longer exists"),
    "KNB005": ("error",
               "knob parser round-trip broken: a registered default is "
               "rejected by its own parser, a canary is accepted, or a "
               "rejection error fails to name the offending env var"),
}


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    message: str
    snippet: str = ""

    @property
    def severity(self) -> str:
        return RULES.get(self.rule, ("error", ""))[0]

    def fingerprint(self) -> str:
        basis = f"{self.rule}|{self.path}|{self.snippet.strip()}"
        return hashlib.blake2b(basis.encode(), digest_size=6).hexdigest()

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"[{self.severity}] {self.message}"
        )


def _parse_pragma(text: str) -> tuple[str, str] | None:
    """``'# bfs_tpu: hot-start'`` -> ``('hot-start', '')``;
    ``'# guarded-by: _lock'`` -> ``('guarded-by', '_lock')``; else None."""
    body = text.lstrip("#").strip()
    if body.startswith("bfs_tpu:"):
        rest = body[len("bfs_tpu:"):].strip()
        if not rest:
            return None
        word, _, arg = rest.partition(" ")
        return word, arg.strip()
    if body.startswith("guarded-by:"):
        return "guarded-by", body[len("guarded-by:"):].strip()
    return None


class SourceFile:
    """One parsed module: AST + pragma maps, ready for the analyzers."""

    def __init__(self, path: str, root: str, text: str | None = None):
        self.abspath = os.path.abspath(path)
        self.path = os.path.relpath(self.abspath, root).replace(os.sep, "/")
        if text is None:
            with open(self.abspath, encoding="utf-8") as f:
                text = f.read()
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=self.path)
        # line -> set of suppressed rules ({'*'} = all)
        self.suppressions: dict[int, set[str]] = {}
        # line -> guard spec string for guarded-by annotations
        self.guard_decls: dict[int, str] = {}
        # def-line pragmas: line -> True when '# bfs_tpu: hot traced'
        # (the body executes under a trace even though the def itself is
        # not jit-decorated — e.g. ops/ kernels called from jitted loops)
        self.hot_pragma_lines: dict[int, bool] = {}
        self.holds_decls: dict[int, list[str]] = {}
        self.hot_spans: list[tuple[int, int]] = []
        # (line, message) pragma-hygiene problems -> PRG* findings
        self.pragma_problems: list[tuple[int, str]] = []
        self._scan_comments()

    # ------------------------------------------------------------ pragmas --
    def _scan_comments(self) -> None:
        open_start: int | None = None
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.text).readline)
            comments = [
                (t.start[0], t.start[1], t.string)
                for t in tokens
                if t.type == tokenize.COMMENT
            ]
        except tokenize.TokenError:
            comments = []
        for lineno, col, text in comments:
            pragma = _parse_pragma(text)
            if pragma is None:
                continue
            kind, arg = pragma
            own_line = self.lines[lineno - 1].strip().startswith("#")
            if kind == "ok":
                rules = {
                    r.strip()
                    for r in arg.split(" ")[0].split(",")
                    if r.strip()
                } or {"*"}
                self.suppressions.setdefault(lineno, set()).update(rules)
                if own_line:  # standalone comment covers the next line too
                    self.suppressions.setdefault(lineno + 1, set()).update(rules)
            elif kind == "hot":
                self.hot_pragma_lines[lineno] = arg.split(" ")[0] == "traced"
            elif kind == "hot-start":
                if open_start is not None:
                    # Keep coverage (close the first span here) AND flag
                    # it: a dropped span would un-police a timed region
                    # with the self-lint still green.
                    self.hot_spans.append((open_start, lineno))
                    self.pragma_problems.append((
                        lineno,
                        f"hot-start while the span opened at line "
                        f"{open_start} is still open (missing hot-end?)",
                    ))
                open_start = lineno
            elif kind == "hot-end":
                if open_start is not None:
                    self.hot_spans.append((open_start, lineno))
                    open_start = None
            elif kind == "holds":
                locks = [x.strip() for x in arg.replace(",", " ").split() if x.strip()]
                self.holds_decls[lineno] = locks
                if own_line:
                    self.holds_decls.setdefault(lineno + 1, locks)
            elif kind == "guarded-by":
                self.guard_decls[lineno] = arg.split(" ")[0] if arg else ""
        if open_start is not None:  # unclosed span: hot to EOF
            self.hot_spans.append((open_start, len(self.lines)))

    # ----------------------------------------------------------- utilities --
    def snippet(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, lineno: int, rule: str) -> bool:
        rules = self.suppressions.get(lineno, ())
        return "*" in rules or rule in rules

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding | None:
        line = getattr(node, "lineno", 0)
        if self.suppressed(line, rule):
            return None
        return Finding(
            rule=rule,
            path=self.path,
            line=line,
            col=getattr(node, "col_offset", 0),
            message=message,
            snippet=self.snippet(line),
        )


# --------------------------------------------------------------------------
# Hot-region + jit-decorator discovery (shared by transfer + recompile).
# --------------------------------------------------------------------------

def dotted_name(node: ast.AST) -> str:
    """``jax.lax.while_loop`` -> that string; '' for anything non-dotted."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}


def is_jit_reference(node: ast.AST) -> bool:
    """True when ``node`` refers to the jit transform itself (``jax.jit``)
    or a partial of it (``functools.partial(jax.jit, ...)``)."""
    if dotted_name(node) in _JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        fn = dotted_name(node.func)
        if fn in ("functools.partial", "partial") and node.args:
            return is_jit_reference(node.args[0])
        # shard_map/custom wrappers that take the jitted fn positionally
        # are out of scope — name the region with a pragma instead.
    return False


def jit_decorated(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    return any(is_jit_reference(d) for d in fn.decorator_list)


_HOT_DECORATORS = {"hot_region", "analysis.hot_region"}


def _pragma_applies(src: SourceFile, fn: ast.FunctionDef) -> bool | None:
    """A ``# bfs_tpu: hot`` comment marks the next def at/below it.
    Returns None (no pragma) or the pragma's traced flag."""
    first = min(
        [d.lineno for d in fn.decorator_list] + [fn.lineno]
    )
    for line, traced in src.hot_pragma_lines.items():
        if line == fn.lineno or (line < first and _no_def_between(src, line, first)):
            return traced
    return None


def _no_def_between(src: SourceFile, lo: int, hi: int) -> bool:
    """True when no OTHER def/class statement starts in (lo, hi) — the
    pragma binds to the nearest following definition."""
    for ln in range(lo + 1, hi):
        stripped = src.lines[ln - 1].lstrip() if ln <= len(src.lines) else ""
        if stripped.startswith(("def ", "async def ", "class ")):
            return False
    return True


@dataclass
class HotRegion:
    """One region the transfer rules police.  ``traced`` regions (jit
    bodies) additionally get the trace-concretization rule TRC006."""

    start: int
    end: int
    traced: bool
    name: str
    node: ast.AST | None = None


def hot_regions(src: SourceFile) -> list[HotRegion]:
    regions: list[HotRegion] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        traced = jit_decorated(node)
        pragma = _pragma_applies(src, node)
        marked = (
            traced
            or pragma is not None
            or any(
                dotted_name(d) in _HOT_DECORATORS
                or (isinstance(d, ast.Call) and dotted_name(d.func) in _HOT_DECORATORS)
                for d in node.decorator_list
            )
        )
        if marked:
            regions.append(
                HotRegion(node.lineno, node.end_lineno or node.lineno,
                          traced or bool(pragma), node.name, node)
            )
    for start, end in src.hot_spans:
        regions.append(HotRegion(start, end, False, f"span@{start}"))
    return regions


# --------------------------------------------------------------------------
# Baseline.
# --------------------------------------------------------------------------

#: Rules that can NEVER be baselined: a PAL005 parity break means one of
#: the two kernel twins computes wrong answers — accepting it would turn
#: the lint green while results are wrong.  An entry for these rules is
#: ignored (and therefore reported stale on a default-surface run, which
#: forces it to be pruned).
NEVER_BASELINE = frozenset({"PAL005"})


@dataclass
class Baseline:
    """The committed accepted-findings file.  ``entries`` maps fingerprint
    -> (rule, justification); ``used`` tracks which entries matched this
    run so the CLI can warn about stale ones.  Rules in
    :data:`NEVER_BASELINE` are never accepted regardless of entries."""

    path: str | None = None
    entries: dict[str, tuple[str, str]] = field(default_factory=dict)
    used: set[str] = field(default_factory=set)

    @classmethod
    def load(cls, path: str | None) -> "Baseline":
        bl = cls(path=path)
        if path is None or not os.path.exists(path):
            return bl
        with open(path, encoding="utf-8") as f:
            for raw in f:
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(None, 2)
                if len(parts) < 2:
                    continue
                rule, fp = parts[0], parts[1]
                just = parts[2] if len(parts) > 2 else ""
                bl.entries[fp] = (rule, just)
        return bl

    def accepts(self, finding: Finding) -> bool:
        if finding.rule in NEVER_BASELINE:
            return False
        fp = finding.fingerprint()
        if fp in self.entries:
            self.used.add(fp)
            return True
        return False

    def stale(self) -> list[str]:
        return [fp for fp in self.entries if fp not in self.used]

    @staticmethod
    def render(findings: list[Finding], justification: str = "TODO: justify") -> str:
        lines = [
            "# bfs_tpu.analysis baseline — accepted findings.",
            "# One per line: RULE  fingerprint  justification.",
            "# Fingerprints hash (rule, path, source line) — line-number",
            "# drift is fine; editing the flagged line forces re-triage.",
        ]
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
            lines.append(
                f"{f.rule}  {f.fingerprint()}  "
                f"[{f.path}:{f.line}] {justification}"
            )
        return "\n".join(lines) + "\n"
