"""Lock-discipline rules (LCK001–LCK002): ``# guarded-by:`` checking.

The serve layer threads shared state through four files (server,
registry, executor, metrics) and its history is exactly the bug class
this checker exists for — the ``_ensure_build_log`` double-install race
(ADVICE round 5) and the unguarded registry-metrics handoff both shipped
because nothing connected "this field is shared" to "this access holds
the lock".  The annotation makes the invariant explicit; the checker
makes it enforced.

Model (deliberately lexical, no interprocedural analysis):

* a field annotated ``# guarded-by: _lock`` on its initializing
  assignment must, in every OTHER method of its class, be read/written
  inside a ``with self._lock`` block (module-level globals: ``with
  _lock`` inside the module's functions);
* ``a|b`` alternates accept either lock — and a field assigned
  ``self._cond = threading.Condition(self._lock)`` makes ``_cond`` an
  alias: holding the condition IS holding the lock;
* ``# bfs_tpu: holds _lock`` on a ``def`` declares a caller-holds-lock
  helper (the ``@RequiresLock`` idiom) — the body is checked as if the
  lock were held throughout;
* ``__init__``/``__new__``/``__post_init__``/``__del__`` are exempt
  (no concurrent readers exist yet / the object is dying);
* nested defs are checked with the locks held at their DEFINITION site —
  a deliberate simplification: a closure that defers execution past the
  ``with`` block needs its own annotation review (mark it with an
  ok-pragma and a reason).

LCK002 (warning) flags mutable containers assigned in ``__init__`` of a
class that owns a lock but carries no annotation — the "documentation
value even where the checker passes" half of the satellite task.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .core import Finding, SourceFile, dotted_name

_EXEMPT_METHODS = {"__init__", "__new__", "__post_init__", "__del__"}
_LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition", "threading.Semaphore",
    # analysis.runtime.make_lock — the lock-order recorder's factory
    # (ISSUE 12): classes building their lock through it still OWN one.
    "make_lock", "runtime.make_lock",
}
_MUTABLE_FACTORIES = {
    "dict", "list", "set", "OrderedDict", "collections.OrderedDict",
    "deque", "collections.deque", "defaultdict", "collections.defaultdict",
}


@dataclass
class _ClassInfo:
    node: ast.ClassDef
    guards: dict[str, set[str]] = field(default_factory=dict)  # field -> locks
    guard_decl_line: dict[str, int] = field(default_factory=dict)
    aliases: dict[str, set[str]] = field(default_factory=dict)  # cond -> locks
    owns_lock: bool = False
    mutable_fields: dict[str, ast.AST] = field(default_factory=dict)


def _parse_guard_spec(spec: str) -> set[str]:
    return {s.strip() for s in spec.split("|") if s.strip()}


def _guard_spec_for(src: SourceFile, node: ast.AST) -> str | None:
    """The guarded-by spec attached to a statement: on a standalone
    comment line directly above it, on its first line, or (multi-line
    assignments) on any line through its last.  A trailing comment on the
    PREVIOUS statement's line never bleeds down."""
    start = getattr(node, "lineno", 0)
    end = getattr(node, "end_lineno", start) or start
    above = src.guard_decls.get(start - 1)
    if above and 1 <= start - 1 <= len(src.lines) and (
        src.lines[start - 2].strip().startswith("#")
    ):
        return above
    for line in range(start, end + 1):
        spec = src.guard_decls.get(line)
        if spec:
            return spec
    return None


def _self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _collect_class(src: SourceFile, cls: ast.ClassDef) -> _ClassInfo:
    info = _ClassInfo(cls)
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        value = node.value
        for tgt in targets:
            name = _self_attr(tgt)
            if name is None:
                continue
            spec = _guard_spec_for(src, node)
            if spec:
                info.guards.setdefault(name, set()).update(_parse_guard_spec(spec))
                info.guard_decl_line[name] = node.lineno
            if isinstance(value, ast.Call):
                fname = dotted_name(value.func)
                if fname in _LOCK_FACTORIES:
                    info.owns_lock = True
                    wrapped = {
                        a
                        for arg in value.args
                        if (a := _self_attr(arg)) is not None
                    }
                    if wrapped:
                        info.aliases.setdefault(name, set()).update(wrapped)
                elif fname in _MUTABLE_FACTORIES:
                    info.mutable_fields.setdefault(name, tgt)
            elif isinstance(value, (ast.Dict, ast.List, ast.Set)):
                info.mutable_fields.setdefault(name, tgt)
    return info


def _held_from_with(item_expr: ast.AST, *, selfish: bool) -> str | None:
    """The lock name a ``with`` item acquires: ``self._lock`` (selfish) or
    a bare module-level ``_lock``; ``cond`` variants look identical."""
    if selfish:
        return _self_attr(item_expr)
    if isinstance(item_expr, ast.Name):
        return item_expr.id
    return None


def _expand(held: set[str], aliases: dict[str, set[str]]) -> set[str]:
    out = set(held)
    for h in held:
        out |= aliases.get(h, set())
    return out


class _AccessChecker(ast.NodeVisitor):
    """Walk one function body tracking lexically-held locks."""

    def __init__(
        self,
        src: SourceFile,
        guards: dict[str, set[str]],
        aliases: dict[str, set[str]],
        *,
        selfish: bool,
        initial: set[str],
        scope: str,
        emit,
    ):
        self.src = src
        self.guards = guards
        self.aliases = aliases
        self.selfish = selfish
        self.held: set[str] = set(initial)
        self.scope = scope
        self.emit = emit
        self.reported: set[tuple[int, str]] = set()

    # ------------------------------------------------------------ holding --
    def visit_With(self, node: ast.With) -> None:
        self._visit_with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._visit_with(node)

    def _visit_with(self, node) -> None:
        acquired = set()
        for item in node.items:
            got = _held_from_with(item.context_expr, selfish=self.selfish)
            if got is not None:
                acquired.add(got)
            self.visit(item.context_expr)
        before = set(self.held)
        self.held |= acquired
        for stmt in node.body:
            self.visit(stmt)
        self.held = before

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # Nested def: checked with definition-site locks (see module doc).
        holds = self.src.holds_decls.get(node.lineno, [])
        before = set(self.held)
        self.held |= set(holds)
        for stmt in node.body:
            self.visit(stmt)
        self.held = before

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # Same definition-site-locks simplification as nested defs.
        before = set(self.held)
        self.visit(node.body)
        self.held = before

    # ----------------------------------------------------------- accesses --
    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self.selfish:
            name = _self_attr(node)
            if name is not None and name in self.guards:
                self._check(node, name)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if not self.selfish and node.id in self.guards:
            self._check(node, node.id)

    def _check(self, node: ast.AST, name: str) -> None:
        needed = self.guards[name]
        if _expand(self.held, self.aliases) & needed:
            return
        key = (node.lineno, name)
        if key in self.reported:
            return
        self.reported.add(key)
        lock_desc = "|".join(sorted(needed))
        self.emit(
            "LCK001", node,
            f"{self.scope}: '{name}' is guarded-by {lock_desc} but this "
            f"access holds none of it",
        )


def check_locks(src: SourceFile) -> list[Finding]:
    findings: list[Finding] = []

    def emit(rule: str, node: ast.AST, msg: str) -> None:
        f = src.finding(rule, node, msg)
        if f is not None:
            findings.append(f)

    # ------------------------------------------------------ module globals --
    mod_guards: dict[str, set[str]] = {}
    for node in src.tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            spec = _guard_spec_for(src, node)
            if spec:
                for tgt in targets:
                    if isinstance(tgt, ast.Name):
                        mod_guards.setdefault(tgt.id, set()).update(
                            _parse_guard_spec(spec)
                        )
    if mod_guards:
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name in _EXEMPT_METHODS:
                    continue
                checker = _AccessChecker(
                    src, mod_guards, {}, selfish=False,
                    initial=set(src.holds_decls.get(node.lineno, [])),
                    scope=f"{node.name}()", emit=emit,
                )
                for stmt in node.body:
                    checker.visit(stmt)

    # ------------------------------------------------------------- classes --
    for cls in [n for n in ast.walk(src.tree) if isinstance(n, ast.ClassDef)]:
        info = _collect_class(src, cls)
        if info.guards:
            for meth in cls.body:
                if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if meth.name in _EXEMPT_METHODS:
                    continue
                holds = set(src.holds_decls.get(meth.lineno, []))
                for d in meth.decorator_list:
                    holds |= set(src.holds_decls.get(d.lineno, []))
                checker = _AccessChecker(
                    src, info.guards, info.aliases, selfish=True,
                    initial=holds,
                    scope=f"{cls.name}.{meth.name}()", emit=emit,
                )
                for stmt in meth.body:
                    checker.visit(stmt)
        if info.owns_lock:
            for name, tgt in sorted(info.mutable_fields.items()):
                if name in info.guards or name in info.aliases:
                    continue
                emit(
                    "LCK002", tgt,
                    f"{cls.name}.{name} is a mutable container in a "
                    "lock-owning class with no '# guarded-by:' annotation "
                    "— annotate it (or mark it ok with why it is "
                    "single-threaded/immutable-after-init)",
                )
    return findings
