"""Sharding & collective checker: the IR005/IR006 half of the IR pass.

Works over walked jaxpr equations (:func:`bfs_tpu.analysis.ir.walk_eqns`)
rather than source text: ``shard_map`` axis use, missing/extra exchange
collectives and payload-format regressions are invisible to the AST
linter because they only exist in what actually lowers.

Checked invariants, per analyzed program (:class:`~bfs_tpu.analysis.ir.Program`):

* **IR005a** — every collective names only axes declared by the program
  spec (``mesh_axes``).  A collective over an undeclared axis (a second
  mesh axis, an outer vmap name) is an *extra* collective: per-superstep
  ICI traffic nobody budgeted.  (A truly unbound axis cannot reach the
  walk at all — jax rejects it at trace time, which surfaces as IR000.)
* **IR005b** — every axis in ``required_axes`` is touched by at least one
  collective.  The sharded relay/push/pull programs are only correct
  because a per-superstep merge rides the ``graph`` axis; a refactor that
  drops the all-reduce produces per-shard-plausible wrong results with no
  runtime error.
* **IR005c** — the ``shard_map`` result shardings (``out_names`` in the
  lowered eqn) match the spec's ``expected_out_names``.  XLA will happily
  return per-shard state where the caller expects replicated state; every
  downstream consumer then silently reads shard 0.
* **IR006** — collectives moving V-scale payloads (>= ``exchange_floor``
  bytes) must use the declared exchange dtypes (packed uint32 words by
  default).  The compressed frontier exchange ROADMAP item 1 needs is
  guarded here the day it lands: a float32 or 64-bit-widened exchange
  doubles (or worse) the per-superstep ICI bytes.

Control-plane scalars (the ``changed`` all-reduce, axis_index) fall under
the floor and are never flagged.
"""

from __future__ import annotations

#: Primitives that move payload across mesh axes.  ``psum2`` is jax's
#: post-0.4.30 spelling of psum inside shard_map.
PAYLOAD_COLLECTIVES = frozenset({
    "psum", "psum2", "pmin", "pmax", "ppermute", "pshuffle",
    "all_gather", "all_to_all", "reduce_scatter",
})

#: Axis-binding eqns that move no payload: mesh-coordinate reads and the
#: replication-rewrite casts shard_map inserts automatically.  They never
#: satisfy a required exchange axis and are never flagged — pbroadcast in
#: particular appears in ANY shard_map body, collective or not.
CONTROL_COLLECTIVES = frozenset({"axis_index", "pbroadcast", "pcast"})

_WIDE_DTYPES = frozenset({"int64", "uint64", "float64"})


def eqn_axis_names(eqn) -> tuple[str, ...]:
    """The mesh-axis names a collective eqn binds (positional vmap axes —
    ints — are not mesh axes and are dropped)."""
    raw = ()
    for key in ("axes", "axis_name", "axis"):
        if key in eqn.params:
            raw = eqn.params[key]
            break
    if raw is None:
        return ()
    if not isinstance(raw, (tuple, list)):
        raw = (raw,)
    return tuple(a for a in raw if isinstance(a, str))


def out_names_sets(eqn) -> tuple[frozenset, ...]:
    """``shard_map`` eqn ``out_names`` (dim -> axis tuple dicts) as one
    frozenset of axis names per flat output — the comparable form of the
    declared ``out_specs``."""
    return tuple(
        frozenset(ax for axs in d.values() for ax in axs)
        for d in eqn.params.get("out_names", ())
    )


def check_collectives(prog, walked, make_finding):
    """IR005/IR006 over ``walked`` eqns.

    ``walked`` is the ``(eqn, ctx)`` sequence from
    :func:`bfs_tpu.analysis.ir.walk_eqns`; ``make_finding(rule, detail,
    message)`` builds the program-anchored finding (ir.py owns paths and
    fingerprint shape).  Returns a list of findings.
    """
    findings = []
    declared = prog.mesh_axes
    used_axes: set[str] = set()

    for eqn, _ctx in walked:
        name = eqn.primitive.name
        if name == "shard_map":
            if prog.expected_out_names is not None:
                actual = out_names_sets(eqn)
                expected = tuple(frozenset(s) for s in prog.expected_out_names)
                if actual != expected:
                    findings.append(make_finding(
                        "IR005", "out_specs",
                        f"shard_map result sharding {_fmt_specs(actual)} "
                        f"disagrees with the declared out_specs "
                        f"{_fmt_specs(expected)} — a consumer expecting "
                        "replicated state would silently read one shard",
                    ))
            continue
        if name not in PAYLOAD_COLLECTIVES:
            continue  # CONTROL_COLLECTIVES never count as an exchange
        axes = eqn_axis_names(eqn)
        if not axes:
            continue
        used_axes.update(axes)
        # A TRULY unbound axis never reaches this walk: jax raises at
        # trace time and analyze_program reports IR000.  What can reach
        # here is an axis bound by something other than the spec's
        # declaration (an outer vmap name, a second mesh axis) — the
        # "extra exchange nobody budgeted" case.
        for ax in axes:
            if declared is not None and ax not in declared:
                findings.append(make_finding(
                    "IR005", f"extra:{ax}",
                    f"collective '{name}' rides undeclared mesh axis "
                    f"'{ax}' (declared: {sorted(declared)}) — an extra "
                    "exchange nobody budgeted",
                ))
        findings.extend(_check_payload(prog, eqn, name, make_finding))

    for ax in sorted(set(prog.required_axes) - used_axes):
        findings.append(make_finding(
            "IR005", f"missing:{ax}",
            f"no collective touches required exchange axis '{ax}' — "
            "the per-superstep merge this program's correctness "
            "depends on is gone from the lowered IR",
        ))
    return findings


def _check_payload(prog, eqn, name, make_finding):
    findings = []
    allowed = frozenset(prog.exchange_dtypes)
    for var in eqn.invars:
        aval = getattr(var, "aval", None)
        if aval is None or not hasattr(aval, "dtype"):
            continue
        nbytes = int(getattr(aval, "size", 0)) * aval.dtype.itemsize
        if nbytes < prog.exchange_floor:
            continue  # control-plane scalar (the `changed` reduce etc.)
        dt = str(aval.dtype)
        if dt in _WIDE_DTYPES or dt not in allowed:
            findings.append(make_finding(
                "IR006", f"payload:{name}:{dt}",
                f"collective '{name}' moves a {nbytes}-byte {dt} payload; "
                f"the declared exchange format is {sorted(allowed)} — "
                "a widened exchange multiplies per-superstep ICI bytes",
            ))
    return findings


def _fmt_specs(specs) -> str:
    return "(" + ", ".join(
        "{" + ",".join(sorted(s)) + "}" if s else "replicated" for s in specs
    ) + ")"
