"""Rule logic for the knob-provenance pass (KNB001–KNB005).

Pure functions over ASTs and the registry — no caching, no CLI; the
pass driver (:mod:`bfs_tpu.analysis.knobs`) owns surfaces and the
content-addressed result cache.  Everything here is stdlib-only
(``ast`` + ``re``): the rung must run in tier-1 on a bare CPU image,
and discovering ``os.environ`` reads must work even in modules that
would fail to import.

The contract being proven (ISSUE 19): every ``BFS_TPU_*`` env read in
the shipped code goes through the typed registry accessors
(:func:`bfs_tpu.knobs.get` / :func:`bfs_tpu.knobs.raw`), every
registered knob is actually read somewhere (a registry row whose read
sites vanished is as fatal as an unregistered read — the PAL000
both-ways pin, applied to knobs), every knob's declared ``affects``
set matches the LIVE key builders (imported, not grepped), no
call-scoped knob is baked into an import-time constant or read inside
a traced region, every knob has a README table row, and every parser
round-trips its default while rejecting its canary.
"""

from __future__ import annotations

import ast
import importlib
import re

from .. import knobs as registry
from .core import Finding, SourceFile, dotted_name, hot_regions

#: Accessor spellings counted as registry reads: ``knobs.get(...)`` /
#: ``knobs.raw(...)`` — the uniform ``from .. import knobs`` binding —
#: plus the in-registry spellings ``get``/``raw``/``parse_value`` used
#: by bfs_tpu/knobs.py itself (exempted from KNB001 separately).
_ACCESSOR_ATTRS = frozenset({"get", "raw"})

_KNOB_NAME = re.compile(r"BFS_TPU_\w+")


def _literal_knob(node) -> str | None:
    """The ``BFS_TPU_*`` literal at ``node``, else None (non-literal
    knob names — e.g. ``for e in _FLAVOR_ENV: os.environ.get(e)`` in
    the key builders — are out of KNB001 scope by design: the loops
    iterate registry-derived tuples that KNB002 proves instead)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if node.value.startswith("BFS_TPU_"):
            return node.value
    return None


def _is_environ(node) -> bool:
    """True for any expression spelling ``...environ`` (``os.environ``,
    a bare ``environ`` import, ``__import__('os').environ``)."""
    return (
        (isinstance(node, ast.Attribute) and node.attr == "environ")
        or (isinstance(node, ast.Name) and node.id == "environ")
    )


def iter_env_reads(tree: ast.AST):
    """Yield ``(node, knob_name, kind)`` for every RAW env read of a
    literal ``BFS_TPU_*`` name: ``kind`` is ``'get'`` (``environ.get``
    / ``getenv``) or ``'subscript'`` (``environ[...]`` in Load
    context).  Writes (``environ[...] = ``, ``setdefault``, ``pop``,
    ``del``) are deliberately NOT reads — the save/restore fixtures and
    the bench's setdefault defaults are legitimate raw-env surface."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr == "get"
                and _is_environ(fn.value)
                and node.args
            ):
                name = _literal_knob(node.args[0])
                if name:
                    yield node, name, "get"
            elif (
                dotted_name(fn) in ("os.getenv", "getenv") and node.args
            ):
                name = _literal_knob(node.args[0])
                if name:
                    yield node, name, "get"
        elif isinstance(node, ast.Subscript):
            if (
                isinstance(node.ctx, ast.Load)
                and _is_environ(node.value)
            ):
                name = _literal_knob(node.slice)
                if name:
                    yield node, name, "subscript"


def iter_accessor_reads(tree: ast.AST):
    """Yield ``(node, knob_name, attr)`` for every ``knobs.get("...")``
    / ``knobs.raw("...")`` call with a literal knob argument."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in _ACCESSOR_ATTRS
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "knobs"
            and node.args
        ):
            name = _literal_knob(node.args[0])
            if name:
                yield node, name, fn.attr


# --------------------------------------------------------------------------
# KNB001 — provenance: raw reads, unregistered names, vanished rows.
# --------------------------------------------------------------------------

def check_provenance(
    sources: list[SourceFile],
    knob_table: dict | None = None,
    registry_path: str = "bfs_tpu/knobs.py",
) -> list[Finding]:
    """KNB001 over the whole surface, both directions:

    * a raw ``os.environ``/``getenv`` read of a literal ``BFS_TPU_*``
      name anywhere outside the registry module itself — registered or
      not — bypasses the typed accessor (a typo'd value silently
      changes what a capture measured);
    * an accessor read of a name the registry doesn't carry (the
      accessor would raise at runtime; the lint catches it statically);
    * a registered knob with NO literal accessor read anywhere on the
      surface — a dead row is a doc/key entry for a knob nothing obeys,
      exactly as wrong as an unregistered read (set equality, pinned
      both ways like PAL000's kernel-site pin).
    """
    table = registry.KNOBS if knob_table is None else knob_table
    findings: list[Finding] = []
    read_names: set[str] = set()
    for src in sources:
        in_registry_module = src.path == registry_path
        for node, name, kind in iter_env_reads(src.tree):
            if in_registry_module:
                continue  # knobs.py IS the accessor implementation
            spelled = (
                "os.environ[...]" if kind == "subscript"
                else "os.environ.get/getenv"
            )
            if name in table:
                msg = (
                    f"raw {spelled} read of registered knob {name} "
                    "bypasses the typed accessor — use knobs.get "
                    "(typed, validated) or knobs.raw (path knobs)"
                )
            else:
                msg = (
                    f"env read of unregistered knob {name} — every "
                    "BFS_TPU_* knob must carry a bfs_tpu/knobs.py row "
                    "(parser, default, affects) before it is read"
                )
            f = src.finding("KNB001", node, msg)
            if f:
                findings.append(f)
        for node, name, _attr in iter_accessor_reads(src.tree):
            read_names.add(name)
            if name not in table:
                f = src.finding(
                    "KNB001", node,
                    f"accessor read of unregistered knob {name} — "
                    "knobs.get/raw would raise KnobError at runtime; "
                    "add the registry row",
                )
                if f:
                    findings.append(f)
    for name in sorted(set(table) - read_names):
        findings.append(Finding(
            rule="KNB001", path=registry_path, line=0, col=0,
            message=(
                f"registered knob {name} has no accessor read site "
                "anywhere on the lint surface — its read sites "
                "vanished; prune the registry row or restore the read "
                "(a dead row documents and keys a knob nothing obeys)"
            ),
            snippet=f"knb:{name}:unread",
        ))
    return findings


# --------------------------------------------------------------------------
# KNB002 — cache-key completeness against the LIVE key builders.
# --------------------------------------------------------------------------

#: domain -> (module, attribute) holding the live tuple of knob names
#: that key that cache/config.  Imported (not grepped): the proof is
#: about what the running key builders actually hash.
KEY_PROVIDERS: dict[str, tuple[str, str]] = {
    "ir": ("bfs_tpu.analysis.ir", "_FLAVOR_ENV"),
    "hlo": ("bfs_tpu.analysis.hlo", "_HLO_FLAVOR_ENV"),
    "pal": ("bfs_tpu.analysis.pallas", "_PAL_FLAVOR_ENV"),
    "probe": ("bfs_tpu.cache.layout", "_PROBE_ENV"),
    "journal": ("bfs_tpu.resilience.journal", "ENV_CONFIG_KEYS"),
    "serve": ("bfs_tpu.serve.registry", "ENGINE_FLAVOR_ENV"),
}


def check_key_completeness(
    knob_table: dict | None = None,
    providers: dict | None = None,
    registry_path: str = "bfs_tpu/knobs.py",
) -> list[Finding]:
    """KNB002/KNB000: import every key provider and set-compare its
    live tuple against the registry's ``affects`` declarations, both
    ways.  A behavior knob missing from a flavor list is the PR 15 bug
    class (a warm cache hit replayed under a knob it was never keyed
    on); an extra name is a key hashing a knob that declares no effect
    — either the declaration or the key builder is lying.  A provider
    that cannot be imported is KNB000: an unprovable key is an unkeyed
    one.  ``providers`` entries may also be ``(tuple, None)``-style
    pre-resolved sequences (test fixtures)."""
    table = registry.KNOBS if knob_table is None else knob_table
    provs = KEY_PROVIDERS if providers is None else providers
    findings: list[Finding] = []
    for domain in sorted(provs):
        spec = provs[domain]
        declared = {
            k.name for k in table.values() if domain in k.affects
        }
        is_ref = (
            isinstance(spec, tuple)
            and len(spec) == 2
            and all(isinstance(s, str) for s in spec)
            and "." in spec[0]
        )
        if is_ref:
            mod_name, attr = spec
            try:
                mod = importlib.import_module(mod_name)
                live = set(getattr(mod, attr))
            except Exception as exc:  # import error, missing attr
                findings.append(Finding(
                    rule="KNB000", path=registry_path, line=0, col=0,
                    message=(
                        f"[{domain}] key provider {mod_name}.{attr} "
                        f"failed to import: {type(exc).__name__}: {exc}"
                        " — a key builder that cannot be checked is "
                        "unproven"
                    ),
                    snippet=f"knb:{domain}:provider",
                ))
                continue
            where = f"{mod_name}.{attr}"
        else:  # pre-resolved sequence (test fixture)
            live = set(spec)
            where = f"<fixture:{domain}>"
        for name in sorted(declared - live):
            findings.append(Finding(
                rule="KNB002", path=registry_path, line=0, col=0,
                message=(
                    f"{name} declares affects['{domain}'] but is "
                    f"MISSING from {where} — a warm cache/journal "
                    "entry would replay under a knob value it was "
                    "never keyed on (the PR 15 stale-flavor bug class)"
                ),
                snippet=f"knb:{name}:{domain}:unkeyed",
            ))
        for name in sorted(live - declared):
            findings.append(Finding(
                rule="KNB002", path=registry_path, line=0, col=0,
                message=(
                    f"{where} keys on {name} which does not declare "
                    f"affects['{domain}'] — either declare it in "
                    "bfs_tpu/knobs.py or stop keying on it"
                ),
                snippet=f"knb:{name}:{domain}:undeclared",
            ))
    return findings


# --------------------------------------------------------------------------
# KNB003 — scope discipline: import-baked call knobs, traced-region reads.
# --------------------------------------------------------------------------

def _enclosing_functions(tree: ast.AST) -> dict[int, bool]:
    """Map of line -> True for lines lexically inside any function body
    (module/class level lines are absent)."""
    covered: dict[int, bool] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for ln in range(node.lineno, (node.end_lineno or node.lineno) + 1):
                covered[ln] = True
    return covered


def check_scope(
    sources: list[SourceFile], knob_table: dict | None = None
) -> list[Finding]:
    """KNB003, two shapes:

    * a ``scope='call'`` knob read at module/class level — the value is
      baked into an import-time constant, so an env change (or a test
      monkeypatch) after import silently does nothing; only knobs
      DECLARED ``scope='import'`` (the kernel-geometry constants) may
      be read there;
    * any knob accessor read lexically inside a TRACED hot region — the
      read executes at trace time and its value is burned into the
      compiled program while looking like a runtime switch; resolve the
      knob outside and pass the value in.
    """
    table = registry.KNOBS if knob_table is None else knob_table
    findings: list[Finding] = []
    for src in sources:
        in_fn = _enclosing_functions(src.tree)
        traced_spans = [
            (r.start, r.end) for r in hot_regions(src) if r.traced
        ]
        for node, name, attr in iter_accessor_reads(src.tree):
            k = table.get(name)
            if k is None:
                continue  # KNB001's finding already covers it
            line = getattr(node, "lineno", 0)
            if not in_fn.get(line) and k.scope != "import":
                f = src.finding(
                    "KNB003", node,
                    f"call-scoped knob {name} read at import time — "
                    "the value is baked into a module constant, so "
                    "later env changes silently do nothing; move the "
                    "read into the resolve path or declare "
                    "scope='import' in its registry row",
                )
                if f:
                    findings.append(f)
            for start, end in traced_spans:
                if start <= line <= end:
                    f = src.finding(
                        "KNB003", node,
                        f"knob {name} read inside traced region "
                        f"(lines {start}-{end}) — the env read "
                        "executes at trace time and the value is "
                        "burned into the compiled program; resolve "
                        "it outside the trace and pass it in",
                    )
                    if f:
                        findings.append(f)
                    break
    return findings


# --------------------------------------------------------------------------
# KNB004 — README doc coverage, both ways.
# --------------------------------------------------------------------------

def readme_knob_rows(readme_text: str) -> dict[str, int]:
    """``{knob name: first line number}`` for every markdown table row
    anywhere in the README whose FIRST cell names a ``BFS_TPU_*`` var
    (backticks stripped).  Separator rows (``| --- |``) don't match."""
    rows: dict[str, int] = {}
    for i, line in enumerate(readme_text.splitlines(), start=1):
        s = line.strip()
        if not s.startswith("|"):
            continue
        first = s.strip("|").split("|", 1)[0].strip().strip("`")
        m = _KNOB_NAME.fullmatch(first)
        if m and first not in rows:
            rows[first] = i
    return rows


def check_docs(
    readme_text: str,
    knob_table: dict | None = None,
    readme_path: str = "README.md",
) -> list[Finding]:
    """KNB004 both ways: every registered knob has a README table row
    (the generated reference table — ``bfs-tpu-lint --knobs
    --write-docs`` — guarantees this mechanically) and every README
    table row whose first cell names a ``BFS_TPU_*`` var names a LIVE
    knob (a stale row documents a knob that no longer exists)."""
    table = registry.KNOBS if knob_table is None else knob_table
    rows = readme_knob_rows(readme_text)
    findings: list[Finding] = []
    for name in sorted(set(table) - set(rows)):
        findings.append(Finding(
            rule="KNB004", path=readme_path, line=0, col=0,
            message=(
                f"registered knob {name} has no README table row — "
                "regenerate the reference table with `bfs-tpu-lint "
                "--knobs --write-docs`"
            ),
            snippet=f"knb:{name}:undocumented",
        ))
    for name in sorted(set(rows) - set(table)):
        findings.append(Finding(
            rule="KNB004", path=readme_path, line=rows[name], col=0,
            message=(
                f"README table row documents {name} which is not a "
                "registered knob — stale doc row; prune it or "
                "register the knob"
            ),
            snippet=f"knb:{name}:stale-row",
        ))
    return findings


# --------------------------------------------------------------------------
# KNB005 — parser round-trip: defaults parse, canaries reject.
# --------------------------------------------------------------------------

#: Kinds whose parsers accept ANY string, so no canary can exist.
_FREEFORM_KINDS = frozenset({"str", "path"})


def check_parsers(
    knob_table: dict | None = None,
    registry_path: str = "bfs_tpu/knobs.py",
) -> list[Finding]:
    """KNB005: for every knob, the registered default must be inside
    its own parser's domain (``knobs.get`` with the var unset must
    never raise), and the registered canary must be REJECTED with a
    :class:`~bfs_tpu.knobs.KnobError` whose message names the knob (the
    operator-facing contract: a typo'd env var tells you WHICH var).
    A missing canary is itself a finding except for the freeform
    ``str``/``path`` kinds, which accept everything."""
    table = registry.KNOBS if knob_table is None else knob_table
    findings: list[Finding] = []
    for name in sorted(table):
        k = table[name]
        try:
            if knob_table is None:
                registry.parse_value(name, k.default)
            else:
                k.parse(k.default)
        except Exception as exc:
            findings.append(Finding(
                rule="KNB005", path=registry_path, line=0, col=0,
                message=(
                    f"{name}: registered default {k.default!r} is "
                    f"rejected by its own parser ({exc}) — every "
                    "unset-env read would raise"
                ),
                snippet=f"knb:{name}:default-rejected",
            ))
            continue
        if k.canary is None:
            if k.kind not in _FREEFORM_KINDS:
                findings.append(Finding(
                    rule="KNB005", path=registry_path, line=0, col=0,
                    message=(
                        f"{name}: no canary registered — a "
                        f"{k.kind}-kind parser must demonstrably "
                        "reject SOMETHING, or validation is "
                        "untestable"
                    ),
                    snippet=f"knb:{name}:no-canary",
                ))
            continue
        try:
            if knob_table is None:
                registry.parse_value(name, k.canary)
            else:
                k.parse(k.canary)
            rejected, named = False, False
        except registry.KnobError as exc:
            rejected, named = True, name in str(exc)
        except (ValueError, TypeError) as exc:
            # Fixture tables call k.parse directly (no KnobError wrap);
            # the live registry path always wraps.
            rejected = True
            named = knob_table is not None or name in str(exc)
        if not rejected:
            findings.append(Finding(
                rule="KNB005", path=registry_path, line=0, col=0,
                message=(
                    f"{name}: canary {k.canary!r} was ACCEPTED by the "
                    "parser — the canary must be outside the domain, "
                    "or the parser lost its validation"
                ),
                snippet=f"knb:{name}:canary-accepted",
            ))
        elif not named:
            findings.append(Finding(
                rule="KNB005", path=registry_path, line=0, col=0,
                message=(
                    f"{name}: rejection error does not name the knob "
                    "— operators must see WHICH env var is bad"
                ),
                snippet=f"knb:{name}:error-unnamed",
            ))
    return findings
