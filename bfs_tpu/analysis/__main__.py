"""CLI for the project linter: ``python -m bfs_tpu.analysis [paths...]``.

Default target set is the shipped code (``bfs_tpu/``, ``tools/``, the
repo-root ``bench.py``) relative to the repo root — tests are excluded by
default because their fixtures deliberately trip rules.  Exit codes:

* 0 — no unsuppressed error-severity findings (baseline-accepted ones
  and warnings don't fail the run);
* 1 — at least one new error, or (on a default-target run) a STALE
  baseline entry — an accepted finding that no longer exists must be
  pruned, not silently carried;
* 2 — usage/configuration problem.

Passes:

* default — the stdlib AST pass (transfer/trace/recompile/lock rules).
* ``--ir`` (or the ``ir`` subcommand) — the IR-grade pass: lowers the
  declared hot fused programs to jaxprs and checks donation, loop-body
  host round-trips, dtype drift, HBM budgets and collective correctness
  (:mod:`bfs_tpu.analysis.ir`).  Imports jax; results are cached
  content-addressed so repeat runs are instant (``--no-cache`` forces).
* ``--hlo`` (or the ``hlo`` subcommand) — the HLO-grade pass: COMPILES
  every hot program and walks the optimized HLO module + executable
  metadata for realized donation, compiler-backed HBM proofs, loop-body
  fusion breaks, compiled collective drift and opaque escapes
  (:mod:`bfs_tpu.analysis.hlo`).  Same caching discipline;
  ``--update-fingerprints`` regenerates the committed per-program
  footprint fingerprints, ``--snapshot PATH`` writes the metrics rows
  for ``tools/hlo_diff.py``.
* ``--pallas`` (or the ``pallas`` subcommand) — the kernel-grade pass:
  RUNS every registered Pallas kernel at lint scale under a
  ``pallas_call`` spy and checks VMEM residency, (8,128)/MXU tile
  alignment, grid write-aliasing, dynamic-slice bounds and
  interpret-vs-XLA-twin bit parity (:mod:`bfs_tpu.analysis.pallas`).
  Same caching discipline.
* ``--knobs`` (or the ``knobs`` subcommand) — the knob-provenance pass:
  proves the typed env-knob registry (:mod:`bfs_tpu.knobs`) against the
  sources (no raw ``BFS_TPU_*`` env reads, no dead registry rows), the
  LIVE cache-key builders (every knob's ``affects`` domains match what
  the IR/HLO/Pallas caches, probe key, bench journal and serve engine
  fingerprint actually hash), scope discipline, README doc coverage and
  parser round-trips (:mod:`bfs_tpu.analysis.knobs`).  Pure stdlib;
  same caching discipline with a jax-free key.  ``--write-docs``
  regenerates the README knob reference table from the registry first.
* ``--all`` (or the ``all`` subcommand) — every pass in one run with
  merged baseline handling and a single exit code: the pre-merge gate
  surface ``tools/ci_gate.sh`` chains after tier-1.

``--changed`` lints only files named by ``git diff --name-only HEAD``
(the pre-commit spelling).  ``--write-baseline`` rewrites the baseline
file from the current AST findings (errors only, warnings never need
baselining) with TODO justifications to fill in; with ``--ir`` or
``--hlo`` it PRINTS the baseline lines instead (those sections are
curated by hand, never clobbered).  ``--no-baseline`` shows everything.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from . import (
    RULES,
    Baseline,
    analyze_paths,
    default_baseline_path,
)


def _repo_root() -> str:
    """The repo root: nearest ancestor of this package carrying the
    project markers, else the package's grandparent (site installs)."""
    here = os.path.dirname(os.path.abspath(__file__))
    cand = os.path.dirname(os.path.dirname(here))  # .../repo (bfs_tpu/..)
    for probe in (cand, os.getcwd()):
        if os.path.exists(os.path.join(probe, "bfs_tpu")):
            return probe
    return cand


def _changed_files(root: str) -> list[str]:
    """Repo files touched vs HEAD (staged + unstaged), absolute paths —
    restricted to the default lint surface (bfs_tpu/, tools/, bench.py):
    tests/ fixtures deliberately trip rules and are never linted, so a
    changed test file must not fail the pre-commit fast path."""
    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            capture_output=True, text=True, cwd=root, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return []
    if out.returncode != 0:
        return []
    picked = []
    for line in out.stdout.splitlines():
        rel = line.strip()
        if not rel.endswith(".py"):
            continue
        if not (rel.startswith("bfs_tpu/") or rel.startswith("tools/")
                or rel == "bench.py"):
            continue
        p = os.path.join(root, rel)
        if os.path.exists(p):
            picked.append(p)
    return picked


def _default_ast_paths(root: str) -> list[str]:
    """The default AST lint surface — ONE definition, shared by the
    plain run and the --all composite so they can never diverge."""
    return [
        p for p in (
            os.path.join(root, "bfs_tpu"),
            os.path.join(root, "tools"),
            os.path.join(root, "bench.py"),
        ) if os.path.exists(p)
    ]


def _family(rule: str) -> str:
    for fam in ("IR", "HLO", "PAL", "KNB"):
        if rule.startswith(fam):
            return fam
    return "AST"


def _meta_suffix(meta: dict, tag: str, noun: str) -> str:
    """The per-pass bracket detail a jax-pass summary carries —
    including the HLO fingerprint status, whose 'missing'/'foreign'
    states mean the regression tripwires are OFF and must be visible
    on every surface that runs the pass."""
    built = meta.get(
        "programs", meta.get("kernels", meta.get("knobs", []))
    )
    return (
        f"{tag}: {len(built)} {noun}(s), cache {meta['cache']}"
        + (f", skipped {sorted(meta['skipped'])}"
           if meta["skipped"] else "")
        + (f", fingerprints {meta['fingerprint_status']}"
           if "fingerprint_status" in meta else "")
        + (f", unfingerprinted {sorted(meta['unfingerprinted'])}"
           if meta.get("unfingerprinted") else "")
    )


def _report(args, findings, baseline, stale_filter, label, meta_suffix,
            json_extra) -> int:
    """Shared tail of every lint run (single-pass AND --all): apply the
    baseline, enforce stale entries through ``stale_filter``, render
    text or JSON, return the exit code.  ONE definition so the two
    surfaces can never diverge on output or exit semantics."""
    fresh = [f for f in findings if not baseline.accepts(f)]
    new_errors = [f for f in fresh if f.severity == "error"]
    warnings = [f for f in fresh if f.severity == "warning"]
    accepted = len(findings) - len(fresh)
    # stale() reads baseline.used, which accepts() populates above.
    stale = [
        fp for fp in baseline.stale()
        if stale_filter(baseline.entries[fp][0])
    ]

    if args.as_json:
        print(json.dumps(
            {
                "findings": [
                    {
                        "rule": f.rule, "severity": f.severity,
                        "path": f.path, "line": f.line, "col": f.col,
                        "message": f.message,
                        "fingerprint": f.fingerprint(),
                    }
                    for f in fresh
                ],
                "accepted_by_baseline": accepted,
                "stale_baseline_entries": stale,
                **json_extra,
            },
            indent=2,
        ))
    else:
        for f in fresh:
            print(f.render())
        summary = (
            f"analysis{label}: {len(new_errors)} error(s), "
            f"{len(warnings)} warning(s), {accepted} baseline-accepted"
            + meta_suffix
        )
        if stale:
            summary += (
                f", {len(stale)} STALE baseline entr"
                f"{'y' if len(stale) == 1 else 'ies'} (fixed or edited — "
                "prune them; stale entries FAIL the self-lint)"
            )
        print(summary, file=sys.stderr)

    if new_errors or stale or (args.strict and warnings):
        return 1
    return 0


def _run_all(args, root: str, baseline_path: str) -> int:
    """The ``--all`` composite surface: AST + IR + HLO + Pallas in one
    run, one merged baseline pass, one exit code.  Scoping flags are
    rejected before this is called (the jax passes cannot be scoped, so
    neither can the composite).  Stale-entry enforcement is per family:
    the AST half always covers its default surface; a jax pass that
    SKIPPED programs (e.g. the mesh specs below 2 devices) proves
    nothing about its entries and exempts its family, exactly like the
    single-pass runs."""
    if args.paths or args.changed:
        print(
            "analysis: --all always analyzes the default surface plus "
            "the whole hot-program registries — it cannot be scoped by "
            "paths or --changed",
            file=sys.stderr,
        )
        return 2
    findings = analyze_paths(_default_ast_paths(root), root)
    from . import hlo, ir, pallas
    from . import knobs as knob_pass

    metas = {}
    for fam, run in (
        ("IR", lambda: ir.analyze_ir(
            use_cache=not args.no_cache, root=root)),
        ("HLO", lambda: hlo.analyze_hlo(
            use_cache=not args.no_cache, root=root)),
        ("PAL", lambda: pallas.analyze_pallas(
            use_cache=not args.no_cache, root=root)),
        ("KNB", lambda: knob_pass.analyze_knobs(
            use_cache=not args.no_cache, root=root)),
    ):
        fam_findings, meta = run()
        findings.extend(fam_findings)
        metas[fam] = meta
    enforced = {"AST": True}
    for fam, meta in metas.items():
        enforced[fam] = not meta["skipped"]

    baseline = (
        Baseline(path=baseline_path)
        if args.no_baseline
        else Baseline.load(baseline_path)
    )
    per_pass = "; ".join(
        _meta_suffix(metas[fam], tag, noun)
        for fam, tag, noun in (("IR", "ir", "program"),
                               ("HLO", "hlo", "program"),
                               ("PAL", "pal", "kernel"),
                               ("KNB", "knb", "knob"))
    )
    return _report(
        args, findings, baseline,
        stale_filter=lambda r: enforced[_family(r)],
        label="[--all]", meta_suffix=f" [{per_pass}]",
        json_extra={"passes": {"ir": metas["IR"], "hlo": metas["HLO"],
                               "pal": metas["PAL"],
                               "knb": metas["KNB"]}},
    )


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    argv = list(argv)
    if argv and argv[0] == "ir":  # subcommand spelling of --ir
        argv = ["--ir"] + argv[1:]
    elif argv and argv[0] == "hlo":  # subcommand spelling of --hlo
        argv = ["--hlo"] + argv[1:]
    elif argv and argv[0] == "pallas":  # subcommand spelling of --pallas
        argv = ["--pallas"] + argv[1:]
    elif argv and argv[0] == "knobs":  # subcommand spelling of --knobs
        argv = ["--knobs"] + argv[1:]
    elif argv and argv[0] == "all":  # subcommand spelling of --all
        argv = ["--all"] + argv[1:]
    ap = argparse.ArgumentParser(
        prog="python -m bfs_tpu.analysis",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: bfs_tpu/ tools/ bench.py)")
    ap.add_argument("--root", default=None,
                    help="repo root for relative paths + default targets")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: bfs_tpu/analysis/baseline.txt)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: show every finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current error findings")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    ap.add_argument("--strict", action="store_true",
                    help="warnings also fail the run")
    ap.add_argument("--rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--ir", action="store_true",
                    help="run the IR-grade pass instead (lowers the hot "
                         "fused programs to jaxprs; imports jax)")
    ap.add_argument("--hlo", action="store_true",
                    help="run the HLO-grade pass instead (COMPILES the hot "
                         "programs and walks the optimized HLO + executable "
                         "metadata; imports jax)")
    ap.add_argument("--pallas", action="store_true",
                    help="run the Pallas kernel-grade pass instead (runs "
                         "every registered kernel at lint scale: VMEM "
                         "proofs, tile alignment, grid-aliasing, ds "
                         "bounds, interpret-vs-XLA parity; imports jax)")
    ap.add_argument("--knobs", action="store_true",
                    help="run the knob-provenance pass instead (proves "
                         "the typed env-knob registry against the "
                         "sources, the live cache-key builders, the "
                         "README table and the parsers; pure stdlib)")
    ap.add_argument("--write-docs", action="store_true",
                    help="knob pass: regenerate the README knob "
                         "reference table from the registry before "
                         "analyzing")
    ap.add_argument("--all", action="store_true", dest="all_passes",
                    help="run every pass (AST + IR + HLO + Pallas + "
                         "Knobs) with merged baseline handling and one "
                         "exit code — the pre-merge gate surface "
                         "(tools/ci_gate.sh)")
    ap.add_argument("--no-cache", action="store_true",
                    help="IR/HLO pass: ignore the content-addressed result "
                         "cache")
    ap.add_argument("--update-fingerprints", action="store_true",
                    help="HLO pass: rewrite the committed per-program "
                         "footprint fingerprint file from this run")
    ap.add_argument("--snapshot", default=None, metavar="PATH",
                    help="HLO pass: also write the per-program metrics "
                         "rows to PATH (the tools/hlo_diff.py input)")
    ap.add_argument("--changed", action="store_true",
                    help="AST pass: lint only files in `git diff "
                         "--name-only HEAD`")
    args = ap.parse_args(argv)

    if args.rules:
        for rule, (sev, desc) in sorted(RULES.items()):
            print(f"{rule}  [{sev:7s}] {desc}")
        return 0

    root = os.path.abspath(args.root) if args.root else _repo_root()
    baseline_path = args.baseline or default_baseline_path()

    picked = [f for f, on in (("--ir", args.ir), ("--hlo", args.hlo),
                              ("--pallas", args.pallas),
                              ("--knobs", args.knobs)) if on]
    if len(picked) > 1:
        print(f"analysis: {' and '.join(picked)} are separate passes — "
              "run one at a time", file=sys.stderr)
        return 2
    if args.all_passes and picked:
        print(f"analysis: --all already includes {picked[0]} — run one at "
              "a time", file=sys.stderr)
        return 2
    if (args.update_fingerprints or args.snapshot) and not args.hlo:
        print("analysis: --update-fingerprints/--snapshot only apply to "
              "the --hlo pass", file=sys.stderr)
        return 2
    if args.write_docs and not args.knobs:
        print("analysis: --write-docs only applies to the --knobs pass",
              file=sys.stderr)
        return 2
    if args.all_passes and args.write_baseline:
        print("analysis: --write-baseline spans one pass at a time — run "
              "it without --all (AST regenerates, --ir/--hlo/--pallas "
              "print candidates)", file=sys.stderr)
        return 2

    if args.all_passes:
        return _run_all(args, root, baseline_path)

    if args.ir or args.hlo or args.pallas or args.knobs:
        pass_name = picked[0]
        if args.paths or args.changed:
            print(
                f"analysis: {pass_name} always analyzes the whole "
                "hot-program registry — it cannot be scoped by paths or "
                "--changed",
                file=sys.stderr,
            )
            return 2
        if args.ir:
            from . import ir

            findings, meta = ir.analyze_ir(
                use_cache=not args.no_cache, root=root
            )
            rule_family = lambda r: _family(r) == "IR"  # noqa: E731
        elif args.pallas:
            from . import pallas

            findings, meta = pallas.analyze_pallas(
                use_cache=not args.no_cache, root=root
            )
            rule_family = lambda r: _family(r) == "PAL"  # noqa: E731
        elif args.knobs:
            # Alias: the pass module shares its name with the registry
            # it proves (bfs_tpu.knobs vs bfs_tpu.analysis.knobs).
            from . import knobs as knob_pass

            if args.write_docs:
                changed = knob_pass.write_docs(root=root)
                print(
                    "analysis: README knob table "
                    + ("regenerated" if changed else "already current"),
                    file=sys.stderr,
                )
            findings, meta = knob_pass.analyze_knobs(
                use_cache=not args.no_cache, root=root
            )
            rule_family = lambda r: _family(r) == "KNB"  # noqa: E731
        else:
            from . import hlo

            findings, meta = hlo.analyze_hlo(
                use_cache=not args.no_cache, root=root
            )
            rule_family = lambda r: _family(r) == "HLO"  # noqa: E731
            if args.snapshot:
                with open(args.snapshot, "w", encoding="utf-8") as fh:
                    json.dump(
                        {"env": hlo.current_env(),
                         "programs": meta["fingerprints"]},
                        fh, indent=1, sort_keys=True,
                    )
                print(f"analysis: wrote HLO metrics snapshot to "
                      f"{args.snapshot}", file=sys.stderr)
            if args.update_fingerprints:
                # A program that failed to compile OR was skipped (e.g.
                # a pre-set XLA_FLAGS leaving too few devices for the
                # mesh specs) has no metrics row — writing now would
                # silently DROP it from the committed file and surface
                # later as a confusing set-inequality failure instead of
                # the actual cause.
                broken = [f for f in findings if f.rule == "HLO000"]
                if broken or meta["skipped"]:
                    for f in broken:
                        print(f.render())
                    reasons = []
                    if broken:
                        reasons.append(f"{len(broken)} program(s) failed "
                                       "to compile (HLO000 above)")
                    if meta["skipped"]:
                        reasons.append(
                            f"{len(meta['skipped'])} program(s) skipped "
                            f"({sorted(meta['skipped'])})"
                        )
                    print(
                        "analysis: refusing to write fingerprints — "
                        + " and ".join(reasons)
                        + "; the committed file must cover the full "
                        "registry",
                        file=sys.stderr,
                    )
                    return 1
                # Show what this run found BEFORE re-pinning: a regress
                # finding written over silently would green every later
                # run against the regressed counts.
                for f in findings:
                    print(f.render())
                path = hlo.default_fingerprints_path()
                hlo.write_fingerprints(path, meta["fingerprints"])
                print(
                    f"analysis: wrote {len(meta['fingerprints'])} program "
                    f"fingerprint(s) to {path}"
                    + (f" — the {len(findings)} finding(s) above are now "
                       "pinned as the new counts; commit with a "
                       "justification" if findings else
                       " — commit with a justification for any regressed "
                       "row"),
                )
                return 0
        # Stale enforcement below only looks at the pass's own entries:
        # an IR/HLO run says nothing about whether AST findings still
        # exist.  And a run that SKIPPED programs (e.g. the mesh specs
        # below 2 devices) proves nothing about their entries either —
        # fingerprints don't name programs, so any skip exempts the
        # whole family.
        default_surface = not meta["skipped"]
    else:
        if args.changed:
            paths = _changed_files(root)
            if not paths:
                print("analysis: no changed python files", file=sys.stderr)
                return 0
            default_surface = False
        elif args.paths:
            paths = [os.path.abspath(p) for p in args.paths]
            default_surface = False
        else:
            paths = _default_ast_paths(root)
            default_surface = True
        if not paths:
            print("analysis: nothing to lint", file=sys.stderr)
            return 2
        findings = analyze_paths(paths, root)
        meta = None
        rule_family = lambda r: _family(r) == "AST"  # noqa: E731

    baseline = (
        Baseline(path=baseline_path)
        if args.no_baseline
        else Baseline.load(baseline_path)
    )

    if args.write_baseline:
        errors = [f for f in findings if f.severity == "error"]
        if args.ir or args.hlo or args.pallas or args.knobs:
            # Never clobber the committed file from the jax/knob passes:
            # its entries span ALL passes.  Print the lines to curate in.
            which = ("IR" if args.ir
                     else "PAL" if args.pallas
                     else "KNB" if args.knobs
                     else "HLO")
            print(Baseline.render(errors), end="")
            print(
                f"analysis: {len(errors)} {which} finding(s) rendered "
                f"above — paste the justified ones into the baseline's "
                f"{which} section",
                file=sys.stderr,
            )
            return 0
        # Regenerating the AST section must not drop the hand-curated
        # IR/HLO/Pallas entries living in the same file: carry them over
        # verbatim.
        kept = [
            f"{rule}  {fp}  {just}".rstrip()
            for fp, (rule, just) in baseline.entries.items()
            if _family(rule) != "AST"
        ]
        with open(baseline_path, "w", encoding="utf-8") as f:
            f.write(Baseline.render(errors))
            if kept:
                f.write(
                    "\n# -- IR/HLO/PAL-pass entries (curated by hand; "
                    "carried over by --write-baseline) --\n"
                )
                f.write("\n".join(kept) + "\n")
        print(
            f"analysis: wrote {len(errors)} accepted finding(s) to "
            f"{baseline_path}"
            + (f" (+{len(kept)} IR/HLO/PAL entr"
               f"{'y' if len(kept) == 1 else 'ies'} carried over)"
               if kept else "")
            + " — fill in the justifications"
        )
        return 0

    if meta is not None:
        tag = ("hlo" if args.hlo
               else "pal" if args.pallas
               else "knb" if args.knobs
               else "ir")
        noun = ("kernel" if args.pallas
                else "knob" if args.knobs
                else "program")
        meta_suffix = f" [{_meta_suffix(meta, tag, noun)}]"
        json_extra = {"ir": meta}
    else:
        meta_suffix = ""
        json_extra = {}
    # Stale entries: only enforced when the run covered the full default
    # surface of its pass — a single-file lint matching nothing proves
    # nothing — and only for the pass's own rule family.
    return _report(
        args, findings, baseline,
        stale_filter=(rule_family if default_surface
                      else (lambda r: False)),
        label="", meta_suffix=meta_suffix, json_extra=json_extra,
    )


if __name__ == "__main__":
    raise SystemExit(main())
