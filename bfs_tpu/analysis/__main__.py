"""CLI for the project linter: ``python -m bfs_tpu.analysis [paths...]``.

Default target set is the shipped code (``bfs_tpu/``, ``tools/``, the
repo-root ``bench.py``) relative to the repo root — tests are excluded by
default because their fixtures deliberately trip rules.  Exit codes:

* 0 — no unsuppressed error-severity findings (baseline-accepted ones
  and warnings don't fail the run);
* 1 — at least one new error;
* 2 — usage/configuration problem.

``--write-baseline`` rewrites the baseline file from the current
findings (errors only, warnings never need baselining) with TODO
justifications to fill in; ``--no-baseline`` shows everything.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import (
    RULES,
    Baseline,
    analyze_paths,
    default_baseline_path,
)


def _repo_root() -> str:
    """The repo root: nearest ancestor of this package carrying the
    project markers, else the package's grandparent (site installs)."""
    here = os.path.dirname(os.path.abspath(__file__))
    cand = os.path.dirname(os.path.dirname(here))  # .../repo (bfs_tpu/..)
    for probe in (cand, os.getcwd()):
        if os.path.exists(os.path.join(probe, "bfs_tpu")):
            return probe
    return cand


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m bfs_tpu.analysis",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: bfs_tpu/ tools/ bench.py)")
    ap.add_argument("--root", default=None,
                    help="repo root for relative paths + default targets")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: bfs_tpu/analysis/baseline.txt)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: show every finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current error findings")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    ap.add_argument("--strict", action="store_true",
                    help="warnings also fail the run")
    ap.add_argument("--rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.rules:
        for rule, (sev, desc) in sorted(RULES.items()):
            print(f"{rule}  [{sev:7s}] {desc}")
        return 0

    root = os.path.abspath(args.root) if args.root else _repo_root()
    if args.paths:
        paths = [os.path.abspath(p) for p in args.paths]
    else:
        paths = [
            p for p in (
                os.path.join(root, "bfs_tpu"),
                os.path.join(root, "tools"),
                os.path.join(root, "bench.py"),
            ) if os.path.exists(p)
        ]
    if not paths:
        print("analysis: nothing to lint", file=sys.stderr)
        return 2

    findings = analyze_paths(paths, root)

    baseline_path = args.baseline or default_baseline_path()
    baseline = (
        Baseline(path=baseline_path)
        if args.no_baseline
        else Baseline.load(baseline_path)
    )

    if args.write_baseline:
        errors = [f for f in findings if f.severity == "error"]
        with open(baseline_path, "w", encoding="utf-8") as f:
            f.write(Baseline.render(errors))
        print(
            f"analysis: wrote {len(errors)} accepted finding(s) to "
            f"{baseline_path} — fill in the justifications"
        )
        return 0

    fresh = [f for f in findings if not baseline.accepts(f)]
    new_errors = [f for f in fresh if f.severity == "error"]
    warnings = [f for f in fresh if f.severity == "warning"]
    accepted = len(findings) - len(fresh)

    if args.as_json:
        print(json.dumps(
            {
                "findings": [
                    {
                        "rule": f.rule, "severity": f.severity,
                        "path": f.path, "line": f.line, "col": f.col,
                        "message": f.message,
                        "fingerprint": f.fingerprint(),
                    }
                    for f in fresh
                ],
                "accepted_by_baseline": accepted,
                "stale_baseline_entries": baseline.stale(),
            },
            indent=2,
        ))
    else:
        for f in fresh:
            print(f.render())
        stale = baseline.stale()
        summary = (
            f"analysis: {len(new_errors)} error(s), {len(warnings)} "
            f"warning(s), {accepted} baseline-accepted"
        )
        if stale:
            summary += (
                f", {len(stale)} STALE baseline entr"
                f"{'y' if len(stale) == 1 else 'ies'} (fixed or edited — "
                "prune them)"
            )
        print(summary, file=sys.stderr)

    if new_errors or (args.strict and warnings):
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
