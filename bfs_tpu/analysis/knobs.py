"""The knob-provenance pass — fifth analyzer rung (KNB).

``bfs-tpu-lint --knobs`` proves the env-knob contract the typed registry
(:mod:`bfs_tpu.knobs`) establishes, the same way the Pallas rung proves
the kernel contract:

* **KNB001** — provenance, both ways: no raw ``os.environ`` read of a
  ``BFS_TPU_*`` name outside the registry module, no accessor read of an
  unregistered name, and no registered knob without a live read site
  (set equality, pinned like PAL000's kernel-site pin).
* **KNB002** — cache-key completeness against the LIVE key builders
  (imported, not grepped): every knob's ``affects`` domains match the
  flavor tuples the IR/HLO/Pallas caches, the probe verdict key, the
  bench journal and the serve engine fingerprint actually hash.
* **KNB003** — scope discipline: call-scoped knobs never baked into
  import-time constants, no knob read inside a traced hot region.
* **KNB004** — README doc coverage, both ways (stale rows fail).
* **KNB005** — parser round-trip: every default parses, every canary is
  rejected with an error naming the knob.

The pass is pure stdlib (AST + the registry + one import per key
provider) — no jax in the cache key, so results are content-addressed on
the lint surface alone and a warm ``--all`` pays zero extra wall time.
Findings share ``baseline.txt`` with the other rungs via synthetic
``knb:<name>:<detail>`` snippets (line-number independent, like the
PAL000 pin).  ``--write-docs`` regenerates the README knob reference
table between the ``knob-table`` markers straight from the registry,
which is what keeps KNB004 mechanically satisfiable.
"""

from __future__ import annotations

import hashlib
import json
import os

from .. import knobs
from . import iter_python_files
from .core import Finding, SourceFile
from .ir import repo_root
from .knob_rules import (
    check_docs,
    check_key_completeness,
    check_parsers,
    check_provenance,
    check_scope,
)

#: Bump on any rule-semantics change: old cached verdicts must not
#: satisfy a stricter pass.
KNB_VERSION = 1

_DOC_BEGIN = "<!-- knob-table:begin -->"
_DOC_END = "<!-- knob-table:end -->"


def default_cache_dir(root: str | None = None) -> str:
    env = knobs.raw("BFS_TPU_KNB_CACHE") or ""
    if env:
        return env
    return os.path.join(root or repo_root(), ".bench_cache", "knb")


def _surface_paths(root: str) -> list[str]:
    """The lint surface: the package, the tools scripts and the root
    ``bench.py`` shim — everywhere shipped code could read env."""
    out = []
    for rel in ("bfs_tpu", "tools", "bench.py"):
        p = os.path.join(root, rel)
        if os.path.exists(p):
            out.append(p)
    return out


def _collect_sources(root: str) -> tuple[list[SourceFile], list[Finding]]:
    sources: list[SourceFile] = []
    findings: list[Finding] = []
    for path in iter_python_files(_surface_paths(root)):
        try:
            sources.append(SourceFile(path, root))
        except SyntaxError as exc:
            rel = os.path.relpath(
                os.path.abspath(path), root
            ).replace(os.sep, "/")
            findings.append(Finding(
                rule="KNB000", path=rel, line=exc.lineno or 0, col=0,
                message=f"could not parse: {exc.msg}",
                snippet=f"knb:parse:{rel}",
            ))
    return sources, findings


def _knb_fingerprint(root: str) -> str:
    """Content hash of everything the pass reads: the lint surface
    (which includes the registry itself and every key-provider module)
    plus the README (KNB004's input) plus the pass version.  No jax
    version, no env values — the pass is static and env-independent, so
    the key must be too (an env change must NOT fork the verdict)."""
    h = hashlib.blake2b(digest_size=16)
    for path in iter_python_files(_surface_paths(root)):
        h.update(os.path.relpath(path, root).encode())
        with open(path, "rb") as f:
            h.update(f.read())
    readme = os.path.join(root, "README.md")
    if os.path.exists(readme):
        with open(readme, "rb") as f:
            h.update(f.read())
    h.update(str(KNB_VERSION).encode())
    return h.hexdigest()


def _finding_to_dict(f: Finding) -> dict:
    return {
        "rule": f.rule, "path": f.path, "line": f.line, "col": f.col,
        "message": f.message, "snippet": f.snippet,
    }


def analyze_knobs(
    knob_table: dict | None = None,
    *,
    providers: dict | None = None,
    readme_text: str | None = None,
    use_cache: bool = True,
    cache_dir: str | None = None,
    root: str | None = None,
) -> tuple[list, dict]:
    """Run the knob pass.  Returns ``(findings, meta)``; ``meta``
    records cache disposition and the knob names checked.  The three
    override parameters feed test fixtures (a synthetic registry, a
    pre-resolved provider map, a README body); any override disables
    the cache and — for a custom table — the live-registry pins, since
    only the canonical registry proves the repo."""
    root = root or repo_root()
    custom = (
        knob_table is not None
        or providers is not None
        or readme_text is not None
    )
    table = knobs.KNOBS if knob_table is None else knob_table
    meta: dict = {
        "cache": "off" if (custom or not use_cache) else "miss",
        "knobs": sorted(table), "skipped": {},
    }

    cache_path = None
    if not custom and use_cache:
        key = _knb_fingerprint(root)
        cache_path = os.path.join(
            cache_dir or default_cache_dir(root), f"knb_{key}.json"
        )
        if os.path.exists(cache_path):
            try:
                with open(cache_path, encoding="utf-8") as fh:
                    doc = json.load(fh)
                meta.update(doc.get("meta", {}))
                meta["cache"] = "hit"
                return [Finding(**d) for d in doc["findings"]], meta
            except (ValueError, KeyError, TypeError):
                pass  # corrupt cache entry: recompute and overwrite

    sources, findings = _collect_sources(root)
    findings.extend(check_provenance(sources, knob_table))
    findings.extend(check_key_completeness(knob_table, providers))
    findings.extend(check_scope(sources, knob_table))
    readme_path = os.path.join(root, "README.md")
    if readme_text is None:
        if os.path.exists(readme_path):
            with open(readme_path, encoding="utf-8") as fh:
                readme_text = fh.read()
        else:
            readme_text = ""
            meta["skipped"]["README.md"] = "missing"
    if "README.md" not in meta["skipped"]:
        findings.extend(check_docs(readme_text, knob_table))
    findings.extend(check_parsers(knob_table))

    findings.sort(key=lambda f: (f.path, f.rule, f.snippet))
    if cache_path is not None:
        try:
            os.makedirs(os.path.dirname(cache_path), exist_ok=True)
            tmp = f"{cache_path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(
                    {"meta": {k: v for k, v in meta.items()
                              if k != "cache"},
                     "findings": [_finding_to_dict(f) for f in findings]},
                    fh,
                )
            os.replace(tmp, cache_path)
        except OSError:
            pass
    return findings, meta


# --------------------------------------------------------------------------
# README reference table (KNB004's mechanical half).
# --------------------------------------------------------------------------

def render_knob_table(knob_table: dict | None = None) -> str:
    """The README reference table, rendered straight from the registry
    — one row per knob, sorted, pipe-escaped.  KNB004 checks the rows;
    ``--write-docs`` writes them, so docs can never drift from code."""
    table = knobs.KNOBS if knob_table is None else knob_table
    lines = [
        "| Knob | Type | Default | Keys | Description |",
        "| --- | --- | --- | --- | --- |",
    ]
    for name in sorted(table):
        k = table[name]
        default = f"`{k.default}`" if k.default else "*(unset)*"
        keys = ", ".join(sorted(k.affects)) if k.affects else "—"
        doc = " ".join(str(k.doc).split()).replace("|", "\\|")
        lines.append(
            f"| `{name}` | {k.kind} | {default} | {keys} | {doc} |"
        )
    return "\n".join(lines)


def write_docs(root: str | None = None) -> bool:
    """Regenerate the README table between the ``knob-table`` markers
    (appending a fresh reference section if the markers are absent).
    Returns True when the README changed on disk."""
    root = root or repo_root()
    readme_path = os.path.join(root, "README.md")
    text = ""
    if os.path.exists(readme_path):
        with open(readme_path, encoding="utf-8") as fh:
            text = fh.read()
    table = render_knob_table()
    block = f"{_DOC_BEGIN}\n{table}\n{_DOC_END}"
    if _DOC_BEGIN in text and _DOC_END in text:
        head, rest = text.split(_DOC_BEGIN, 1)
        _, tail = rest.split(_DOC_END, 1)
        new = head + block + tail
    else:
        section = (
            "\n## Environment knob reference\n\n"
            "Generated from `bfs_tpu/knobs.py` by `bfs-tpu-lint --knobs "
            "--write-docs` — edit the registry, not this table "
            "(KNB004 fails on drift).\n\n"
        )
        new = text.rstrip("\n") + "\n" + section + block + "\n"
    if new == text:
        return False
    tmp = f"{readme_path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(new)
    os.replace(tmp, readme_path)
    return True
