"""IR-grade static analysis: lower the hot fused programs to jaxprs and
check semantic invariants per compiled artifact.

The PR 4 AST linter polices what the SOURCE says; this pass polices what
XLA actually lowers.  Every declared hot program — the push/pull/relay/
direction/multisource fused ``while_loop`` runners, the serve batch
executables, the per-superstep step bodies and the shard_map mesh
programs — is built at a tiny deterministic scale, traced to a jaxpr,
and walked for invariants the AST cannot see:

* **IR001 donation** — V-sized carries (packed state words, frontier
  words) that the program consumes but does not donate: the dead input
  and the live output coexist, doubling the carry's HBM bytes.  The
  finding reports the doubled bytes.
* **IR002 host round-trips** — callback/device_put-shaped eqns inside a
  fused loop body.  One mid-loop callback turns a single compiled
  superstep loop into a per-superstep host sync.
* **IR003 dtype drift** — packed ``level:6|parent:26`` uint32 words
  widened to f32/f64/i64 inside a loop body, or telemetry accumulators
  drifting to 64-bit (an accidental x64 promotion doubles their bytes
  and the exchange that carries them).
* **IR004 HBM budget proof** — a static footprint estimate (operands +
  outputs + a double-buffered temp watermark from eqn shapes) checked
  against the program's byte budget.  The estimate is a LOWER bound: a
  config that fails it cannot fit, full stop.
* **IR005/IR006 collective correctness** — mesh-axis use, required
  exchange collectives and payload dtype/width for the shard_map
  programs (:mod:`bfs_tpu.analysis.collectives`).

Unlike the AST half this module imports jax — it is loaded only by the
``--ir`` CLI path and the IR tests, never by ``bfs_tpu.analysis`` itself.
Tracing every program costs seconds, so results are cached
content-addressed (like the compile cache, models/bfs.compile_exe_cached):
the key hashes every ``bfs_tpu`` source file plus the jax version,
backend, device count and the env knobs that select program flavors.
Tier-1 reruns are a cache hit unless the package actually changed.

Baseline: IR findings share ``baseline.txt``.  Their fingerprints hash
``(rule, path, "ir:<program>:<detail>")`` — stable under any source-line
drift, invalidated exactly when the program or the violation changes.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
from dataclasses import dataclass, field

from .. import knobs
from .core import Finding

#: Bump to invalidate every cached IR result (rule semantics changed).
IR_VERSION = 1

#: Env knobs that change which program flavors the registry builds —
#: DERIVED from the registry (``affects`` contains ``ir``); KNB002
#: proves membership against bfs_tpu/knobs.py both ways instead of a
#: hand-maintained list (the PR 15 stale-cache bug class).
_FLAVOR_ENV = knobs.flavor_env("ir")

#: Primitives whose presence in a loop body is a host round-trip (IR002).
_CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "host_callback_call",
})
_TRANSFER_PRIMS = frozenset({"device_put"})

_LOOP_PRIMS = frozenset({"while", "scan"})

_WIDE = ("int64", "uint64", "float64")


class SkipProgram(Exception):
    """A spec builder may raise this (e.g. too few devices for a mesh
    program) — recorded as skipped, never as a finding."""


@dataclass
class Program:
    """One built hot-program artifact plus its declared invariants.

    ``fn(*args, **static_kwargs)`` must be traceable by
    ``jax.make_jaxpr`` — typically the repo's own jit-wrapped program
    object, so donation/sharding metadata is exactly what ships.
    """

    name: str
    path: str  # repo-relative source anchor for findings
    fn: object
    args: tuple
    static_kwargs: dict = field(default_factory=dict)
    #: arrays with at least this many elements are "V-sized"
    v_elements: int = 0
    packed: bool = False
    #: arg index -> label for carries the program consumes (IR001)
    donate: dict = field(default_factory=dict)
    budget_bytes: int | None = None
    #: mesh axes the program is allowed to exchange over (None = no check)
    mesh_axes: frozenset | None = None
    #: axes that MUST see at least one collective
    required_axes: frozenset = frozenset()
    #: per-flat-output axis sets a shard_map must produce (None = no check)
    expected_out_names: tuple | None = None
    #: allowed dtypes for V-scale collective payloads (IR006)
    exchange_dtypes: tuple = ("uint32", "int32", "bool")
    #: collective payloads under this many bytes are control scalars
    exchange_floor: int = 1024
    #: expected multiset of replica-group SIZES of loop-body PAYLOAD
    #: collectives (payload = any non-scalar result, >1 element — the
    #: control scalars like `changed`/masses psums are excluded by shape,
    #: not by a byte floor).  None = no check.  The 2D grid programs
    #: declare exactly one collective per mesh axis per superstep:
    #: ``(c, r)`` — the column broadcast at group size c, the row
    #: min-reduce at group size r (HLO004).
    loop_payload_groups: tuple | None = None


@dataclass(frozen=True)
class WalkCtx:
    in_loop: bool = False
    mesh_axes: frozenset | None = None


def walk_eqns(jaxpr, ctx: WalkCtx = WalkCtx()):
    """Yield ``(eqn, ctx)`` over a jaxpr and every sub-jaxpr (while/cond
    bodies, pjit calls, shard_map regions, scans, pallas kernels).  The
    context records whether the eqn sits inside a device loop body and
    which mesh axes the nearest enclosing shard_map binds."""
    for eqn in jaxpr.eqns:
        yield eqn, ctx
        name = eqn.primitive.name
        sub_ctx = WalkCtx(
            in_loop=ctx.in_loop or name in _LOOP_PRIMS,
            mesh_axes=(
                frozenset(str(a) for a in eqn.params["mesh"].axis_names)
                if name == "shard_map"
                else ctx.mesh_axes
            ),
        )
        for sub in _eqn_jaxprs(eqn):
            yield from walk_eqns(sub, sub_ctx)


def _eqn_jaxprs(eqn):
    found = []
    for v in eqn.params.values():
        found.extend(_jaxprs_in(v))
    return found


def _jaxprs_in(v):
    if hasattr(v, "eqns"):  # core.Jaxpr or ClosedJaxpr
        return [v]
    if isinstance(v, (tuple, list)):
        out = []
        for x in v:
            out.extend(_jaxprs_in(x))
        return out
    return []


def _aval_bytes(aval) -> int:
    dtype = getattr(aval, "dtype", None)
    size = getattr(aval, "size", None)
    if dtype is None or size is None:
        return 0
    return int(size) * dtype.itemsize


# --------------------------------------------------------------------------
# Per-rule checks.
# --------------------------------------------------------------------------

def _check_donation(prog: Program, closed, make_finding):
    """IR001: declared carries must reach their pjit donated."""
    if not prog.donate:
        return []
    import jax

    ranges, start = [], 0
    for a in prog.args:
        n = len(jax.tree_util.tree_leaves(a))
        ranges.append((start, start + n))
        start += n
    invars = closed.jaxpr.invars
    donated = [False] * len(invars)
    var_index = {id(v): i for i, v in enumerate(invars)}
    for eqn, _ctx in walk_eqns(closed.jaxpr):
        if eqn.primitive.name != "pjit":
            continue
        flags = eqn.params.get("donated_invars") or ()
        for j, v in enumerate(eqn.invars):
            i = var_index.get(id(v))
            if i is not None and j < len(flags) and flags[j]:
                donated[i] = True
    findings = []
    for argidx, label in sorted(prog.donate.items()):
        lo, _hi = ranges[argidx]
        leaves = jax.tree_util.tree_leaves(prog.args[argidx])
        missing = 0
        for off, leaf in enumerate(leaves):
            size = int(getattr(leaf, "size", 0))
            if size >= prog.v_elements and not donated[lo + off]:
                missing += size * leaf.dtype.itemsize
        if missing:
            findings.append(make_finding(
                "IR001", f"donate:{label}",
                f"carry '{label}' is consumed but not donated: "
                f"{missing} dead input bytes stay live next to the "
                f"output — peak HBM for the call is doubled "
                f"(+{missing} bytes); donate argnum {argidx}",
            ))
    return findings


def _check_loop_body(prog: Program, walked, make_finding):
    """IR002 (host round-trips) + IR003 (dtype drift) inside loop bodies."""
    findings = []
    for eqn, ctx in walked:
        name = eqn.primitive.name
        if ctx.in_loop and (
            name in _CALLBACK_PRIMS or name in _TRANSFER_PRIMS
        ):
            findings.append(make_finding(
                "IR002", f"loop:{name}",
                f"'{name}' eqn inside the fused loop body — every "
                "superstep would round-trip through the host",
            ))
        elif name == "convert_element_type":
            in_aval = eqn.invars[0].aval
            new = str(eqn.params.get("new_dtype"))
            src = str(getattr(in_aval, "dtype", ""))
            size = int(getattr(in_aval, "size", 0))
            if not ctx.in_loop or size < prog.v_elements:
                continue
            if src == "uint32" and (new in _WIDE or new == "float32"):
                findings.append(make_finding(
                    "IR003", f"widen:{src}->{new}",
                    f"packed uint32 words ({size} elements) converted to "
                    f"{new} inside the loop body — the level|parent "
                    "packing does not survive a float/64-bit detour",
                ))
            elif src == "int32" and new in _WIDE:
                findings.append(make_finding(
                    "IR003", f"widen:{src}->{new}",
                    f"int32 loop state ({size} elements) widened to "
                    f"{new} inside the loop body (x64 drift doubles its "
                    "bytes)",
                ))
        elif name == "while":
            body = eqn.params["body_jaxpr"]
            for v in body.jaxpr.outvars:
                aval = getattr(v, "aval", None)
                dt = str(getattr(aval, "dtype", ""))
                size = int(getattr(aval, "size", 0))
                if dt in _WIDE:
                    findings.append(make_finding(
                        "IR003", f"carry:{dt}",
                        f"loop carry of dtype {dt} ({size} elements) — "
                        "64-bit state in the fused loop is always drift",
                    ))
                elif (
                    prog.packed and dt == "float32"
                    and size >= prog.v_elements
                ):
                    findings.append(make_finding(
                        "IR003", "carry:float32",
                        f"packed program carries a V-sized float32 array "
                        f"({size} elements) through the loop — the packed "
                        "state contract is uint32 words",
                    ))
    return findings


def _check_budget(prog: Program, closed, walked, make_finding):
    """IR004: operands + outputs + double-buffered temp watermark must
    fit the declared byte budget."""
    if not prog.budget_bytes:
        return []
    operands = sum(_aval_bytes(v.aval) for v in closed.jaxpr.invars)
    consts = sum(
        int(getattr(c, "nbytes", 0)) for c in getattr(closed, "consts", ())
    )
    outputs = sum(
        _aval_bytes(getattr(v, "aval", None)) for v in closed.jaxpr.outvars
    )
    temp = 0
    for eqn, _ctx in walked:
        temp = max(
            temp, sum(_aval_bytes(getattr(v, "aval", None))
                      for v in eqn.outvars)
        )
    estimate = operands + consts + outputs + 2 * temp
    if estimate > prog.budget_bytes:
        return [make_finding(
            "IR004", "budget",
            f"static footprint estimate {estimate} bytes (operands "
            f"{operands + consts} + outputs {outputs} + 2x temp watermark "
            f"{temp}) exceeds the declared budget {prog.budget_bytes} "
            "bytes — this config cannot fit",
        )]
    return []


def analyze_program(prog: Program) -> list[Finding]:
    """All IR findings for one built program (deduped, sorted)."""
    import jax

    from .collectives import check_collectives

    def make_finding(rule: str, detail: str, message: str) -> Finding:
        return Finding(
            rule=rule, path=prog.path, line=0, col=0,
            message=f"[{prog.name}] {message}",
            snippet=f"ir:{prog.name}:{detail}",
        )

    try:
        closed = jax.make_jaxpr(
            lambda *a: prog.fn(*a, **prog.static_kwargs)
        )(*prog.args)
    except SkipProgram:
        raise
    except Exception as exc:
        return [make_finding(
            "IR000", "build",
            f"could not lower to a jaxpr: {type(exc).__name__}: {exc}",
        )]
    walked = list(walk_eqns(closed.jaxpr))
    findings = []
    findings += _check_donation(prog, closed, make_finding)
    findings += _check_loop_body(prog, walked, make_finding)
    findings += _check_budget(prog, closed, walked, make_finding)
    findings += check_collectives(prog, walked, make_finding)
    seen, out = set(), []
    for f in findings:
        key = (f.rule, f.snippet)
        if key not in seen:
            seen.add(key)
            out.append(f)
    out.sort(key=lambda f: (f.path, f.rule, f.snippet))
    return out


# --------------------------------------------------------------------------
# The hot-program registry: every declared fused program, built tiny.
# --------------------------------------------------------------------------

def _hbm_envelope() -> int:
    """Per-chip HBM budget the IR004 proof checks against.
    ``BFS_TPU_IR_HBM_GB`` overrides (e.g. a bench-scale run proving a
    real config); the default is the v5e envelope."""
    return int(knobs.get("BFS_TPU_IR_HBM_GB") * (1 << 30))


_BUILD_CACHE: dict = {}


def _memo(key, build):
    """Memoize expensive spec inputs (graphs, engines, meshes) within a
    process.  The key carries the flavor env so two analyze_ir calls
    under different knobs (tests monkeypatching BFS_TPU_PACKED etc.)
    never share an engine built for the other flavor — the result cache
    keys on the same env, and the two must agree."""
    key = (key, tuple(os.environ.get(e, "") for e in _FLAVOR_ENV))
    if key not in _BUILD_CACHE:
        _BUILD_CACHE[key] = build()
    return _BUILD_CACHE[key]


def _tiny_graph():
    def build():
        from ..graph.generators import rmat_graph

        return rmat_graph(6, 4, seed=3)

    return _memo("graph", build)


def _relay_engine():
    def build():
        from ..models.bfs import RelayEngine

        return RelayEngine(_tiny_graph())

    return _memo("relay_engine", build)


def _spec_push_fused():
    import jax.numpy as jnp

    from ..graph.csr import build_device_graph
    from ..models.bfs import _bfs_fused

    dg = _memo("dg", lambda: build_device_graph(_tiny_graph()))
    v = dg.num_vertices
    return Program(
        name="bfs.push_fused", path="bfs_tpu/models/bfs.py",
        fn=_bfs_fused,
        args=(jnp.asarray(dg.src), jnp.asarray(dg.dst), jnp.int32(0)),
        static_kwargs=dict(
            num_vertices=v, max_levels=v, packed=True, telemetry=True
        ),
        v_elements=v, packed=True, budget_bytes=_hbm_envelope(),
    )


def _spec_pull_fused():
    import jax.numpy as jnp

    from ..graph.ell import build_pull_graph, device_ell
    from ..models.bfs import _bfs_pull_fused

    pg = _memo("pg", lambda: build_pull_graph(_tiny_graph()))
    ell0, folds = _memo("ell", lambda: device_ell(pg))
    return Program(
        name="bfs.pull_fused", path="bfs_tpu/models/bfs.py",
        fn=_bfs_pull_fused,
        args=(ell0, folds, jnp.int32(0)),
        static_kwargs=dict(
            num_vertices=pg.num_vertices, max_levels=pg.num_vertices,
            packed=True, telemetry=True,
        ),
        v_elements=pg.num_vertices, packed=True,
        budget_bytes=_hbm_envelope(),
    )


def _spec_serve_batch(engine: str):
    """The serve batch executables (serve/executor.build_batch_runner
    lowers exactly these multisource programs at power-of-two buckets)."""
    import jax.numpy as jnp

    if engine == "pull":
        from ..graph.ell import build_pull_graph, device_ell
        from ..models.multisource import _bfs_multi_pull_fused

        pg = _memo("pg", lambda: build_pull_graph(_tiny_graph()))
        ell0, folds = _memo("ell", lambda: device_ell(pg))
        v = pg.num_vertices
        args = (ell0, folds, jnp.zeros((4,), jnp.int32))
        fn = _bfs_multi_pull_fused
    else:
        from ..graph.csr import build_device_graph
        from ..models.multisource import _bfs_multi_fused

        dg = _memo("dg", lambda: build_device_graph(_tiny_graph()))
        v = dg.num_vertices
        args = (
            jnp.asarray(dg.src), jnp.asarray(dg.dst),
            jnp.zeros((4,), jnp.int32),
        )
        fn = _bfs_multi_fused
    return Program(
        name=f"serve.batch_{engine}", path="bfs_tpu/serve/executor.py",
        fn=fn, args=args,
        static_kwargs=dict(
            num_vertices=v, max_levels=v, packed=True, telemetry=False
        ),
        v_elements=v, packed=True, budget_bytes=_hbm_envelope(),
    )


def _spec_label_lookup():
    """The serve label tier's point-query program (serve/labels.py):
    one batched gather+min over the uint16[K, V] landmark rows — no
    V-sized carry, no donation (IR001 trivially holds), and its whole
    point is being orders of magnitude smaller than a traversal."""
    import jax.numpy as jnp

    from ..serve.labels import _label_bounds, build_label_index

    idx = _memo("labels", lambda: build_label_index(_tiny_graph(), 3))
    return Program(
        name="serve.label_lookup", path="bfs_tpu/serve/labels.py",
        fn=_label_bounds,
        args=(
            jnp.asarray(idx.dist),
            jnp.zeros((4,), jnp.int32),
            jnp.ones((4,), jnp.int32),
        ),
        v_elements=idx.num_vertices,
        budget_bytes=_hbm_envelope(),
    )


def _spec_direction_fused():
    import jax.numpy as jnp

    from ..models.direction import _bfs_direction_fused, _direction_operands

    dg, ell0, folds, outdeg = _memo(
        "dir_ops", lambda: _direction_operands(_tiny_graph())
    )
    v = dg.num_vertices
    return Program(
        name="direction.fused_auto", path="bfs_tpu/models/direction.py",
        fn=_bfs_direction_fused,
        args=(
            jnp.asarray(dg.src), jnp.asarray(dg.dst), ell0, folds, outdeg,
            jnp.zeros((4,), jnp.int32), jnp.float32(14.0), jnp.float32(24.0),
        ),
        static_kwargs=dict(
            num_vertices=v, max_levels=v, packed=True, mode="auto"
        ),
        v_elements=v, packed=True, budget_bytes=_hbm_envelope(),
    )


def _spec_relay_fused():
    import jax.numpy as jnp

    from ..models.bfs import _relay_fused_program

    eng = _relay_engine()
    fused = _relay_fused_program(
        eng._static, eng.sparse_hybrid, eng._use_pallas(), eng.packed,
        False, eng.direction.key(), eng._phase_sel(),
        eng.relay_graph.num_vertices,
    )
    return Program(
        name="relay.fused", path="bfs_tpu/models/bfs.py",
        fn=fused,
        args=(
            jnp.int32(0), *eng._tensors,
            *eng._sparse_tensors_for(eng.packed),
        ),
        static_kwargs=dict(max_levels=16),
        v_elements=eng.relay_graph.vr, packed=eng.packed,
        budget_bytes=_hbm_envelope(),
    )


def _relay_engine_mxu():
    def build():
        from ..models.bfs import RelayEngine

        return RelayEngine(_tiny_graph(), expansion="mxu")

    return _memo("relay_engine_mxu", build)


def _spec_relay_fused_mxu():
    """The MXU expansion arm's fused program (ISSUE 15): the same loop
    scaffolding as relay.fused with the tiled masked-matmul dense body
    and key-flavor sparse adjacency — donation/transfer/dtype/footprint
    rules must hold for the new arm exactly as for the gather one."""
    import jax.numpy as jnp

    from ..models.bfs import _relay_fused_program

    eng = _relay_engine_mxu()
    fused = _relay_fused_program(
        eng._static, eng.sparse_hybrid, eng._use_pallas(), eng.packed,
        False, eng.direction.key(), eng._phase_sel(),
        eng.relay_graph.num_vertices, eng._expansion_key(),
    )
    return Program(
        name="relay.fused_mxu", path="bfs_tpu/models/bfs.py",
        fn=fused,
        args=(
            jnp.int32(0), *eng._mxu_mask_args(),
            *eng._sparse_tensors_for(eng.packed),
        ),
        static_kwargs=dict(max_levels=16),
        v_elements=eng.relay_graph.vr, packed=eng.packed,
        budget_bytes=_hbm_envelope(),
    )


def _spec_relay_segment_mxu():
    """The mxu arm's checkpointable segment twin (ISSUE 15): carry
    donated per segment like relay.segment."""
    import jax.numpy as jnp

    from ..models.bfs import _relay_segment_program

    eng = _relay_engine_mxu()
    prog = _relay_segment_program(
        eng._static, eng.sparse_hybrid, eng._use_pallas(), eng.packed,
        True, eng.direction.key(), eng._phase_sel(),
        eng.relay_graph.num_vertices, eng._expansion_key(),
    )
    carry = eng.segment_carry(0, telemetry=True)
    return Program(
        name="relay.segment_mxu", path="bfs_tpu/models/bfs.py",
        fn=prog,
        args=(
            carry, jnp.int32(8), *eng._mxu_mask_args(),
            *eng._sparse_tensors_for(eng.packed),
        ),
        static_kwargs=dict(max_levels=16),
        v_elements=eng.relay_graph.vr, packed=eng.packed,
        donate={0: "carry"}, budget_bytes=_hbm_envelope(),
    )


def _spec_sharded_relay_mxu():
    """The sharded mxu arm (ISSUE 15): per-shard tiles against the
    all-gathered global frontier — the exchange contract (IR005/IR006)
    must hold unchanged since the superstep tail is body-agnostic."""
    from ..parallel.sharded import make_mesh

    _need_devices(2)
    import jax.numpy as jnp

    from ..ops.packed import packed_rank_fits, resolve_packed
    from ..parallel.sharded import (
        _bfs_sharded_relay_fused,
        _own_word_table_dev,
        _prepare_relay,
        _resolve_sharded_expansion,
        _sharded_adj_dev,
        _sharded_relay_static,
        _sharded_tiles_dev,
    )

    mesh = _memo("mesh2", lambda: make_mesh(graph=2, batch=1))
    srg = _memo("srg2", lambda: _prepare_relay(_tiny_graph(), mesh))
    packed = resolve_packed(packed_rank_fits(srg.in_classes))
    exp_static, packed = _resolve_sharded_expansion("mxu", srg, packed)
    static = _sharded_relay_static(srg, 2, False, packed, exp_static)
    tiles_arg = _sharded_tiles_dev(srg)[0]
    dummy = jnp.zeros((2, 1), jnp.uint32)
    adj = _sharded_adj_dev(srg, packed, True)
    direction = ("auto", 14.0, 24.0, srg.num_vertices, srg.num_edges)
    return Program(
        name="sharded.relay_mxu", path="bfs_tpu/parallel/sharded.py",
        fn=_bfs_sharded_relay_fused,
        args=(
            tiles_arg, dummy, dummy, _own_word_table_dev(srg), *adj,
            jnp.asarray(srg.outdeg), jnp.int32(0),
        ),
        static_kwargs=dict(
            mesh=mesh, static=static, max_levels=16, telemetry=True,
            direction=direction, exchange=("auto", 8), sparse=True,
        ),
        v_elements=srg.num_vertices, packed=packed,
        budget_bytes=_hbm_envelope(),
        mesh_axes=frozenset({"graph", "batch"}),
        required_axes=frozenset({"graph"}),
    )


def _grid_spec_parts():
    """Shared inputs for the 2D grid specs: the 2x4 mesh over the
    virtual x8 platform, the 8-shard relay graph and its per-cell
    layout operands."""
    _need_devices(8)
    import jax.numpy as jnp

    from ..graph.grid_layout import grid_layout_for
    from ..ops.packed import packed_parent_fits, resolve_packed
    from ..parallel.grid import (
        _grid_dev_operands,
        _grid_static,
        _prepare_grid,
        make_grid_mesh,
    )
    from ..parallel.sharded import _own_word_table_dev

    mesh = _memo("grid_mesh24", lambda: make_grid_mesh(2, 4))
    srg = _memo("grid_srg8", lambda: _prepare_grid(_tiny_graph(), 8))
    packed = resolve_packed(packed_parent_fits(srg.num_vertices))
    layout = grid_layout_for(srg, 2, 4)
    operands = _grid_dev_operands(srg, 2, 4)
    own = _own_word_table_dev(srg)
    outdeg = jnp.asarray(srg.outdeg)
    static = _grid_static(layout, packed)
    return mesh, srg, packed, static, operands, own, outdeg


def _spec_grid_relay(flavor: str):
    """The 2D grid programs (ISSUE 17): candidate production local to
    the r x c cell, a row-axis min-reduce and a column-axis frontier
    broadcast — per-chip wire O(V/sqrt(n)).  ``bitmap`` (forced arm, no
    direction cond) carries the STRICT collective-count contract: the
    loop body must compile exactly one payload collective per mesh axis
    per superstep — group sizes (c, r) = (4, 2) — so a stray global
    all-gather (the 1D O(V) wire pattern) is an HLO004 finding, not a
    silent perf regression.  ``auto`` compiles both density arms under
    ``lax.cond`` (both branches sit in the loop computation, so the
    strict count would double-count) and is policed by the fingerprint
    row instead."""
    import jax.numpy as jnp

    from ..parallel.grid import _bfs_grid_fused

    mesh, srg, packed, static, operands, own, outdeg = _grid_spec_parts()
    if flavor == "auto":
        direction = ("auto", 14.0, 24.0, srg.num_vertices, srg.num_edges)
        exchange = ("auto", 8)
    else:
        direction = None
        exchange = ("bitmap", 8)
    return Program(
        name=f"grid.relay_{flavor}", path="bfs_tpu/parallel/grid.py",
        fn=_bfs_grid_fused,
        args=(*operands, own, outdeg, jnp.int32(int(srg.old2new[0]))),
        static_kwargs=dict(
            mesh=mesh, static=static, max_levels=16,
            telemetry=flavor == "auto", direction=direction,
            exchange=exchange,
        ),
        v_elements=srg.num_vertices, packed=packed,
        budget_bytes=_hbm_envelope(),
        mesh_axes=frozenset({"row", "col"}),
        required_axes=frozenset({"row", "col"}),
        loop_payload_groups=(4, 2) if flavor == "bitmap" else None,
    )


def _spec_grid_segment():
    """The bounded-segment grid program: per-cell checkpoint shards cut
    at the axis-exchange boundary — same per-axis collective contract
    as grid.relay_auto (the fused twin), policed by the fingerprint."""
    import jax.numpy as jnp

    from ..parallel.grid import _bfs_grid_segment, grid_segment_carry

    mesh, srg, packed, static, operands, own, outdeg = _grid_spec_parts()
    direction = ("auto", 14.0, 24.0, srg.num_vertices, srg.num_edges)
    carry = grid_segment_carry(
        srg, 2, 4, int(srg.old2new[0]), packed, True, True, outdeg
    )
    return Program(
        name="grid.segment", path="bfs_tpu/parallel/grid.py",
        fn=_bfs_grid_segment,
        args=(carry, jnp.int32(8), *operands, own, outdeg),
        static_kwargs=dict(
            mesh=mesh, static=static, max_levels=16, telemetry=True,
            direction=direction, exchange=("auto", 8),
        ),
        v_elements=srg.num_vertices, packed=packed,
        budget_bytes=_hbm_envelope(),
        mesh_axes=frozenset({"row", "col"}),
        required_axes=frozenset({"row", "col"}),
    )


def _spec_relay_multi_fused():
    import jax.numpy as jnp

    from ..models.bfs import _relay_multi_fused_program

    eng = _relay_engine()
    fused = _relay_multi_fused_program(
        eng._static, eng._use_pallas(), eng.packed, eng._phase_sel()
    )
    return Program(
        name="relay.multi_fused", path="bfs_tpu/models/bfs.py",
        fn=fused,
        args=(jnp.zeros((4,), jnp.int32), *eng._tensors),
        static_kwargs=dict(max_levels=16),
        v_elements=eng.relay_graph.vr, packed=eng.packed,
        budget_bytes=_hbm_envelope(),
    )


def _spec_relay_step(kind: str):
    """The AOT superstep bodies (RelayEngine._step_body): per-step
    programs whose state input is dead the moment they return — the
    canonical donation carries."""
    eng = _relay_engine()
    state = eng.init_hot_state(0)
    if kind == "sparse":
        args = (state, *eng._sparse_tensors_for(eng.packed)[:3])
    else:
        args = (state, *eng._tensors)
    return Program(
        name=f"relay.step_{kind}", path="bfs_tpu/models/bfs.py",
        fn=eng._step_fn(kind, eng.packed), args=args,
        v_elements=eng.relay_graph.vr, packed=eng.packed,
        donate={0: "state"},
    )


def _spec_relay_segment():
    """The bounded-segment relay program (ISSUE 14): the checkpointable
    twin of relay.fused — its carry is consumed per segment (callers
    reassign), so the whole carry dict is a declared donation (IR001)."""
    import jax.numpy as jnp

    from ..models.bfs import _relay_segment_program

    eng = _relay_engine()
    prog = _relay_segment_program(
        eng._static, eng.sparse_hybrid, eng._use_pallas(), eng.packed,
        True, eng.direction.key(), eng._phase_sel(),
        eng.relay_graph.num_vertices,
    )
    carry = eng.segment_carry(0, telemetry=True)
    return Program(
        name="relay.segment", path="bfs_tpu/models/bfs.py",
        fn=prog,
        args=(
            carry, jnp.int32(8), *eng._tensors,
            *eng._sparse_tensors_for(eng.packed),
        ),
        static_kwargs=dict(max_levels=16),
        v_elements=eng.relay_graph.vr, packed=eng.packed,
        donate={0: "carry"}, budget_bytes=_hbm_envelope(),
    )


def _spec_multi_segment(engine: str):
    """The bounded-segment batched multi-source programs (ISSUE 14) —
    what the serve checkpointing batch runner executes per segment."""
    import jax.numpy as jnp

    from ..models.multisource import multi_segment_init

    if engine == "pull":
        from ..graph.ell import build_pull_graph, device_ell
        from ..models.multisource import _bfs_multi_pull_segment

        pg = _memo("pg", lambda: build_pull_graph(_tiny_graph()))
        ell0, folds = _memo("ell", lambda: device_ell(pg))
        v = pg.num_vertices
        state = multi_segment_init(v, [0, 1, 2, 3], True)
        args = (ell0, folds, state, jnp.int32(8))
        fn = _bfs_multi_pull_segment
    else:
        from ..graph.csr import build_device_graph
        from ..models.multisource import _bfs_multi_segment

        dg = _memo("dg", lambda: build_device_graph(_tiny_graph()))
        v = dg.num_vertices
        state = multi_segment_init(v, [0, 1, 2, 3], True)
        args = (
            jnp.asarray(dg.src), jnp.asarray(dg.dst), state, jnp.int32(8)
        )
        fn = _bfs_multi_segment
    return Program(
        name=f"multisource.segment_{engine}",
        path="bfs_tpu/models/multisource.py",
        fn=fn, args=args,
        static_kwargs=dict(num_vertices=v, max_levels=v, packed=True),
        v_elements=v, packed=True, donate={2: "state"},
        budget_bytes=_hbm_envelope(),
    )


def _spec_sharded_relay_segment():
    """The bounded-segment sharded relay program (ISSUE 14): per-shard
    checkpoint shards cut at the exchange boundary — same collective
    contract as sharded.relay_push (IR005/IR006 police the exchange)."""
    from ..parallel.sharded import make_mesh

    _need_devices(2)
    import jax.numpy as jnp

    from ..ops.packed import packed_rank_fits, resolve_packed
    from ..parallel.sharded import (
        _bfs_sharded_relay_segment,
        _own_word_table_dev,
        _prepare_relay,
        _relay_valid_words,
        _sharded_adj_dev,
        _sharded_relay_mask_args,
        _sharded_relay_static,
        sharded_segment_carry,
    )

    mesh = _memo("mesh2", lambda: make_mesh(graph=2, batch=1))
    srg = _memo("srg2", lambda: _prepare_relay(_tiny_graph(), mesh))
    packed = resolve_packed(packed_rank_fits(srg.in_classes))
    vperm_arg, net_arg = _sharded_relay_mask_args(srg, False)
    static = _sharded_relay_static(srg, 2, False, packed)
    adj = _sharded_adj_dev(srg, packed)
    outdeg = jnp.asarray(srg.outdeg)
    direction = ("auto", 14.0, 24.0, srg.num_vertices, srg.num_edges)
    carry = sharded_segment_carry(
        srg, 2, int(srg.old2new[0]), packed, True, True, outdeg
    )
    return Program(
        name="sharded.relay_segment", path="bfs_tpu/parallel/sharded.py",
        fn=_bfs_sharded_relay_segment,
        args=(
            carry, jnp.int32(8), vperm_arg, net_arg,
            _relay_valid_words(srg), _own_word_table_dev(srg), *adj,
            outdeg,
        ),
        static_kwargs=dict(
            mesh=mesh, static=static, max_levels=16, telemetry=True,
            direction=direction, exchange=("auto", 8), sparse=True,
        ),
        v_elements=srg.num_vertices, packed=packed,
        budget_bytes=_hbm_envelope(),
        mesh_axes=frozenset({"graph", "batch"}),
        required_axes=frozenset({"graph"}),
    )


def _spec_superstep(engine: str):
    def build():
        from ..models.bfs import SuperstepRunner

        return SuperstepRunner(_tiny_graph(), engine=engine)

    runner = _memo(f"runner_{engine}", build)
    state = runner.init(0)
    return Program(
        name=f"superstep.{engine}_step", path="bfs_tpu/models/bfs.py",
        fn=runner._step, args=(state,),
        v_elements=runner.num_vertices, donate={0: "state"},
    )


def _spec_layout_device(prog_name: str):
    """The device layout-builder programs (graph/relay_device.py — the
    first-touch build path since ISSUE 10): classing histograms, relabel,
    slot sorts, permutation assembly, CSR, mask compaction and the
    pure-JAX Beneš route level.  Operands are captured from one real
    device build of the tiny graph (route=jax: no native dependency)."""
    from ..graph.relay_device import ir_operands

    ops = _memo("layout_device_ops", lambda: ir_operands(_tiny_graph()))
    fn, args, statics = ops[prog_name]
    return Program(
        name=prog_name, path="bfs_tpu/graph/relay_device.py",
        fn=fn, args=args, static_kwargs=statics,
        v_elements=_tiny_graph().num_vertices,
        budget_bytes=_hbm_envelope(),
    )


def _need_devices(n: int):
    import jax

    if len(jax.devices()) < n:
        raise SkipProgram(f"needs {n} devices, have {len(jax.devices())}")


def _spec_sharded_push():
    import jax.numpy as jnp

    from ..graph.csr import build_device_graph
    from ..parallel.sharded import make_mesh

    _need_devices(2)
    from ..parallel.sharded import _bfs_sharded_fused

    mesh = _memo("mesh2", lambda: make_mesh(graph=2, batch=1))
    dg = _memo(
        "dg2", lambda: build_device_graph(_tiny_graph(), num_shards=2)
    )
    v = dg.num_vertices
    return Program(
        name="sharded.push_fused", path="bfs_tpu/parallel/sharded.py",
        fn=_bfs_sharded_fused,
        args=(
            jnp.asarray(dg.src).reshape(2, -1),
            jnp.asarray(dg.dst).reshape(2, -1),
            jnp.int32(0),
        ),
        static_kwargs=dict(mesh=mesh, num_vertices=v, max_levels=16),
        v_elements=v, budget_bytes=_hbm_envelope(),
        mesh_axes=frozenset({"graph"}),
        required_axes=frozenset({"graph"}),
        # BfsState(dist, parent, frontier, level, changed) — replicated.
        expected_out_names=(frozenset(),) * 5,
    )


def _spec_sharded_pull():
    import jax.numpy as jnp

    from ..parallel.sharded import _prepare_pull, make_mesh

    _need_devices(2)
    from ..graph.ell import device_ell_sharded
    from ..parallel.sharded import _bfs_sharded_pull_fused

    mesh = _memo("mesh2", lambda: make_mesh(graph=2, batch=1))
    spg = _memo("spg2", lambda: _prepare_pull(_tiny_graph(), mesh, 64))
    ell0, folds = _memo("spg2_ell", lambda: device_ell_sharded(spg))
    return Program(
        name="sharded.pull_fused", path="bfs_tpu/parallel/sharded.py",
        fn=_bfs_sharded_pull_fused,
        args=(ell0, folds, jnp.int32(0)),
        static_kwargs=dict(mesh=mesh, block=spg.block, max_levels=16),
        v_elements=spg.num_vertices, budget_bytes=_hbm_envelope(),
        mesh_axes=frozenset({"graph"}),
        required_axes=frozenset({"graph"}),
        # (dist, parent, level): state distributed, level replicated.
        expected_out_names=(frozenset({"graph"}), frozenset({"graph"}),
                            frozenset()),
    )


def _spec_sharded_relay(flavor: str = "dense"):
    """The sharded relay program family (ISSUE 11): ``dense`` is the
    pull-only bitmap-arm baseline, ``exchange_auto`` compiles the
    word-list/bitmap density cond with the telemetry byte accumulators,
    and ``push`` ships the per-shard adjacency and the direction cond —
    all three must pass IR005/IR006 (collective axes + u32/i32 exchange
    payloads) and the donation/HBM rules."""
    from ..parallel.sharded import make_mesh

    _need_devices(2)
    from ..ops.packed import packed_rank_fits, resolve_packed
    from ..parallel.sharded import (
        _bfs_sharded_relay_fused,
        _own_word_table_dev,
        _prepare_relay,
        _relay_valid_words,
        _sharded_adj_dev,
        _sharded_adj_dummies,
        _sharded_relay_mask_args,
        _sharded_relay_static,
    )

    mesh = _memo("mesh2", lambda: make_mesh(graph=2, batch=1))
    srg = _memo("srg2", lambda: _prepare_relay(_tiny_graph(), mesh))
    packed = resolve_packed(packed_rank_fits(srg.in_classes))
    vperm_arg, net_arg = _sharded_relay_mask_args(srg, False)
    import jax.numpy as jnp

    static = _sharded_relay_static(srg, 2, False, packed)
    sparse = flavor == "push"
    if sparse:
        adj = _sharded_adj_dev(srg, packed)
        outdeg = jnp.asarray(srg.outdeg)
        direction = ("auto", 14.0, 24.0, srg.num_vertices, srg.num_edges)
    else:
        adj = _sharded_adj_dummies(2)
        outdeg = jnp.zeros((1,), jnp.int32)
        direction = None
    exchange = ("auto", 8) if flavor != "dense" else ("bitmap", 8)
    telemetry = flavor != "dense"
    return Program(
        name=f"sharded.relay_{flavor}", path="bfs_tpu/parallel/sharded.py",
        fn=_bfs_sharded_relay_fused,
        args=(
            vperm_arg, net_arg, _relay_valid_words(srg),
            _own_word_table_dev(srg), *adj, outdeg, jnp.int32(0),
        ),
        static_kwargs=dict(
            mesh=mesh, static=static, max_levels=16, telemetry=telemetry,
            direction=direction, exchange=exchange, sparse=sparse,
        ),
        v_elements=srg.num_vertices, packed=packed,
        budget_bytes=_hbm_envelope(),
        mesh_axes=frozenset({"graph", "batch"}),
        required_axes=frozenset({"graph"}),
    )


def _spec_algo_sssp_fused(packed: bool):
    """The semiring SSSP programs (ISSUE 16): min-plus supersteps over
    hash-recomputed weights, unpacked int32 or packed dist:16|parent:16
    carry — same HBM/donation rules as the BFS fused programs."""
    import jax.numpy as jnp

    from ..algo.sssp import _sssp_fused
    from ..graph.csr import build_device_graph

    dg = _memo("dg", lambda: build_device_graph(_tiny_graph()))
    v = dg.num_vertices
    return Program(
        name=f"algo.sssp_fused{'_packed' if packed else ''}",
        path="bfs_tpu/algo/sssp.py",
        fn=_sssp_fused,
        args=(jnp.asarray(dg.src), jnp.asarray(dg.dst), jnp.int32(0)),
        static_kwargs=dict(
            num_vertices=v, max_weight=31, delta=64, max_rounds=64,
            packed=packed,
        ),
        v_elements=v, packed=packed, budget_bytes=_hbm_envelope(),
    )


def _spec_algo_sssp_segment():
    import jax.numpy as jnp

    from ..algo.sssp import _sssp_segment, init_sssp_state
    from ..graph.csr import build_device_graph

    dg = _memo("dg", lambda: build_device_graph(_tiny_graph()))
    v = dg.num_vertices
    return Program(
        name="algo.sssp_segment", path="bfs_tpu/algo/sssp.py",
        fn=_sssp_segment,
        args=(
            init_sssp_state(v, 0, 64), jnp.int32(8),
            jnp.asarray(dg.src), jnp.asarray(dg.dst),
        ),
        static_kwargs=dict(
            num_vertices=v, max_weight=31, delta=64, packed=False
        ),
        v_elements=v, donate={0: "state"}, budget_bytes=_hbm_envelope(),
    )


def _spec_algo_sssp_parents():
    """The exit-time parent canonicalization pass — the one program every
    SSSP arm shares, which is WHY parents are schedule-independent."""
    import jax.numpy as jnp

    from ..algo.sssp import _sssp_parents
    from ..graph.csr import build_device_graph

    dg = _memo("dg", lambda: build_device_graph(_tiny_graph()))
    v = dg.num_vertices
    dist = jnp.zeros((v + 1,), jnp.int32)
    return Program(
        name="algo.sssp_parents", path="bfs_tpu/algo/sssp.py",
        fn=_sssp_parents,
        args=(dist, jnp.asarray(dg.src), jnp.asarray(dg.dst), jnp.int32(0)),
        static_kwargs=dict(num_segments=v + 1, max_weight=31),
        v_elements=v, budget_bytes=_hbm_envelope(),
    )


def _spec_algo_cc_fused(engine: str):
    from ..algo.cc import _cc_fused, _cc_pull_fused

    if engine == "pull":
        from ..graph.ell import build_pull_graph, device_ell

        pg = _memo("pg", lambda: build_pull_graph(_tiny_graph()))
        ell0, folds = _memo("ell", lambda: device_ell(pg))
        v = pg.num_vertices
        fn, args = _cc_pull_fused, (ell0, folds)
    else:
        import jax.numpy as jnp

        from ..graph.csr import build_device_graph

        dg = _memo("dg", lambda: build_device_graph(_tiny_graph()))
        v = dg.num_vertices
        fn, args = _cc_fused, (jnp.asarray(dg.src), jnp.asarray(dg.dst))
    return Program(
        name=f"algo.cc_fused_{engine}", path="bfs_tpu/algo/cc.py",
        fn=fn, args=args,
        static_kwargs=dict(num_vertices=v, max_rounds=v + 1),
        v_elements=v, budget_bytes=_hbm_envelope(),
    )


def _spec_algo_cc_segment():
    import jax.numpy as jnp

    from ..algo.cc import _cc_segment, init_cc_state
    from ..graph.csr import build_device_graph

    dg = _memo("dg", lambda: build_device_graph(_tiny_graph()))
    v = dg.num_vertices
    return Program(
        name="algo.cc_segment", path="bfs_tpu/algo/cc.py",
        fn=_cc_segment,
        args=(
            init_cc_state(v), jnp.int32(8),
            jnp.asarray(dg.src), jnp.asarray(dg.dst),
        ),
        static_kwargs=dict(num_vertices=v),
        v_elements=v, donate={0: "state"}, budget_bytes=_hbm_envelope(),
    )


def _spec_algo_sssp_sharded():
    import jax.numpy as jnp

    from ..parallel.sharded import make_mesh

    _need_devices(2)
    from ..algo.sharded import _sssp_sharded_fused
    from ..graph.csr import build_device_graph

    mesh = _memo("mesh2", lambda: make_mesh(graph=2, batch=1))
    dg = _memo(
        "dg2", lambda: build_device_graph(_tiny_graph(), num_shards=2)
    )
    v = dg.num_vertices
    return Program(
        name="algo.sssp_sharded", path="bfs_tpu/algo/sharded.py",
        fn=_sssp_sharded_fused,
        args=(
            jnp.asarray(dg.src).reshape(2, -1),
            jnp.asarray(dg.dst).reshape(2, -1),
            jnp.int32(0),
        ),
        static_kwargs=dict(
            mesh=mesh, num_vertices=v, max_weight=31, delta=64,
            max_rounds=64,
        ),
        v_elements=v, budget_bytes=_hbm_envelope(),
        mesh_axes=frozenset({"graph"}),
        required_axes=frozenset({"graph"}),
        # SsspState(dist, dirty, threshold, rounds, changed) — replicated.
        expected_out_names=(frozenset(),) * 5,
    )


def _spec_algo_cc_sharded():
    import jax.numpy as jnp

    from ..parallel.sharded import make_mesh

    _need_devices(2)
    from ..algo.sharded import _cc_sharded_fused
    from ..graph.csr import build_device_graph

    mesh = _memo("mesh2", lambda: make_mesh(graph=2, batch=1))
    dg = _memo(
        "dg2", lambda: build_device_graph(_tiny_graph(), num_shards=2)
    )
    v = dg.num_vertices
    return Program(
        name="algo.cc_sharded", path="bfs_tpu/algo/sharded.py",
        fn=_cc_sharded_fused,
        args=(
            jnp.asarray(dg.src).reshape(2, -1),
            jnp.asarray(dg.dst).reshape(2, -1),
        ),
        static_kwargs=dict(mesh=mesh, num_vertices=v, max_rounds=64),
        v_elements=v, budget_bytes=_hbm_envelope(),
        mesh_axes=frozenset({"graph"}),
        required_axes=frozenset({"graph"}),
        # CcState(label, frontier, rounds, changed) — replicated.
        expected_out_names=(frozenset(),) * 4,
    )


#: name -> builder.  Order is the report order.
def _spec_stream_sb_expand():
    """The streamed arm's per-superblock expansion program (ISSUE 18):
    one column superblock's tiles expanded into the candidate grid — the
    candidate carry is donated (callers chain ``cand2d = prog(cand2d,
    ...)``), the streamed operands are the cache's device slabs, and the
    math is the resident XLA twin's per-chunk body with a local
    segment-min, so dtype/transfer/footprint rules must hold exactly as
    for the resident expansion."""
    import jax.numpy as jnp
    import numpy as np

    from ..stream.prefetch import frontier_blocks
    from ..stream.runner import _cand_init_program, _sb_expand_program
    from ..stream.store import HostTileStore

    eng = _relay_engine_mxu()
    at = eng.adj_tiles
    store = HostTileStore(at)
    tiles, row_idx, col_local = store.fetch(0)
    fwords = np.zeros(at.rows // 32 + (1 if at.rows % 32 else 0),
                      dtype=np.uint32)
    fwords[0] = 1
    return Program(
        name="stream.sb_expand", path="bfs_tpu/stream/runner.py",
        fn=_sb_expand_program(store.pad_tiles(0)),
        args=(
            _cand_init_program(at.vtp)(),
            jnp.asarray(frontier_blocks(fwords, at.rtp)),
            jnp.asarray(store.keys2d),
            jnp.asarray(tiles), jnp.asarray(row_idx),
            jnp.asarray(col_local), jnp.int32(0),
        ),
        v_elements=eng.relay_graph.vr, packed=eng.packed,
        donate={0: "cand2d"},
    )


PROGRAM_SPECS = {
    "bfs.push_fused": _spec_push_fused,
    "bfs.pull_fused": _spec_pull_fused,
    "serve.batch_push": lambda: _spec_serve_batch("push"),
    "serve.batch_pull": lambda: _spec_serve_batch("pull"),
    "serve.label_lookup": _spec_label_lookup,
    "direction.fused_auto": _spec_direction_fused,
    "relay.fused": _spec_relay_fused,
    "relay.fused_mxu": _spec_relay_fused_mxu,
    "relay.multi_fused": _spec_relay_multi_fused,
    "relay.step_dense": lambda: _spec_relay_step("dense"),
    "relay.step_sparse": lambda: _spec_relay_step("sparse"),
    "relay.segment": _spec_relay_segment,
    "relay.segment_mxu": _spec_relay_segment_mxu,
    "stream.sb_expand": _spec_stream_sb_expand,
    "multisource.segment_push": lambda: _spec_multi_segment("push"),
    "multisource.segment_pull": lambda: _spec_multi_segment("pull"),
    "sharded.relay_segment": _spec_sharded_relay_segment,
    "superstep.push_step": lambda: _spec_superstep("push"),
    "superstep.pull_step": lambda: _spec_superstep("pull"),
    "sharded.push_fused": _spec_sharded_push,
    "sharded.pull_fused": _spec_sharded_pull,
    "sharded.relay_dense": lambda: _spec_sharded_relay("dense"),
    "sharded.relay_exchange_auto": lambda: _spec_sharded_relay(
        "exchange_auto"
    ),
    "sharded.relay_push": lambda: _spec_sharded_relay("push"),
    "sharded.relay_mxu": _spec_sharded_relay_mxu,
    "grid.relay_bitmap": lambda: _spec_grid_relay("bitmap"),
    "grid.relay_auto": lambda: _spec_grid_relay("auto"),
    "grid.segment": _spec_grid_segment,
    "algo.sssp_fused": lambda: _spec_algo_sssp_fused(False),
    "algo.sssp_fused_packed": lambda: _spec_algo_sssp_fused(True),
    "algo.sssp_segment": _spec_algo_sssp_segment,
    "algo.sssp_parents": _spec_algo_sssp_parents,
    "algo.cc_fused_push": lambda: _spec_algo_cc_fused("push"),
    "algo.cc_fused_pull": lambda: _spec_algo_cc_fused("pull"),
    "algo.cc_segment": _spec_algo_cc_segment,
    "algo.sssp_sharded": _spec_algo_sssp_sharded,
    "algo.cc_sharded": _spec_algo_cc_sharded,
    "layout.device_hist": lambda: _spec_layout_device("layout.device_hist"),
    "layout.device_relabel": lambda: _spec_layout_device(
        "layout.device_relabel"
    ),
    "layout.device_slots": lambda: _spec_layout_device("layout.device_slots"),
    "layout.device_net_assembly": lambda: _spec_layout_device(
        "layout.device_net_assembly"
    ),
    "layout.device_vperm_assembly": lambda: _spec_layout_device(
        "layout.device_vperm_assembly"
    ),
    "layout.device_csr": lambda: _spec_layout_device("layout.device_csr"),
    "layout.device_compact": lambda: _spec_layout_device(
        "layout.device_compact"
    ),
    "layout.route_level": lambda: _spec_layout_device("layout.route_level"),
    "layout.route_mid": lambda: _spec_layout_device("layout.route_mid"),
}


# --------------------------------------------------------------------------
# Content-addressed result cache + the repo entry point.
# --------------------------------------------------------------------------

def repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def _ensure_jax_env() -> None:
    """CLI runs get the test harness's virtual multi-device CPU platform
    (the mesh programs need >= 2 devices).  The ``python -m`` and
    console-script spellings import the parent package (and thus jax)
    before this runs, so "jax already imported" is not the boundary —
    "backend already initialized" is: platform and device count are read
    lazily at first backend init, and config/env set before that still
    take effect.  A caller who explicitly set ``JAX_PLATFORMS`` or an
    initialized backend (tests, library use) is left alone."""
    def _add_device_flag():
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    if "jax" not in sys.modules:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        _add_device_flag()
        return
    import jax

    try:
        from jax._src import xla_bridge

        if xla_bridge.backends_are_initialized():
            return
    except (ImportError, AttributeError):
        return  # cannot tell — do not disturb a possibly-live backend
    # The device-count flag only affects the host (CPU) platform, so it
    # is safe regardless of the platform choice below.
    _add_device_flag()
    if not os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", "cpu")


def _source_fingerprint(root: str) -> str:
    h = hashlib.blake2b(digest_size=16)
    pkg = os.path.join(root, "bfs_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = sorted(
            d for d in dirnames
            if d != "__pycache__" and not d.startswith(".")
        )
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            p = os.path.join(dirpath, fn)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def _cache_key(root: str) -> str:
    import jax

    h = hashlib.blake2b(digest_size=16)
    h.update(_source_fingerprint(root).encode())
    h.update(jax.__version__.encode())
    h.update(jax.default_backend().encode())
    h.update(str(len(jax.devices())).encode())
    h.update(str(IR_VERSION).encode())
    h.update(",".join(sorted(PROGRAM_SPECS)).encode())
    for env in _FLAVOR_ENV:
        h.update(f"{env}={os.environ.get(env, '')};".encode())
    return h.hexdigest()


def default_cache_dir(root: str | None = None) -> str:
    env = knobs.raw("BFS_TPU_IR_CACHE") or ""
    if env:
        return env
    return os.path.join(root or repo_root(), ".bench_cache", "ir")


def _finding_to_dict(f: Finding) -> dict:
    return {
        "rule": f.rule, "path": f.path, "line": f.line, "col": f.col,
        "message": f.message, "snippet": f.snippet,
    }


def analyze_ir(
    specs: dict | None = None,
    *,
    use_cache: bool = True,
    cache_dir: str | None = None,
    root: str | None = None,
) -> tuple[list[Finding], dict]:
    """Run the IR pass.  Returns ``(findings, meta)`` where ``meta``
    records cache disposition and skipped programs.  ``specs`` overrides
    the registry (tests feed fixture programs); custom specs are never
    cached — only the canonical repo registry is content-addressed."""
    _ensure_jax_env()
    root = root or repo_root()
    custom = specs is not None
    specs = specs if custom else PROGRAM_SPECS
    meta: dict = {"cache": "off" if (custom or not use_cache) else "miss",
                  "programs": [], "skipped": {}}

    cache_path = None
    if not custom and use_cache:
        key = _cache_key(root)
        cache_path = os.path.join(
            cache_dir or default_cache_dir(root), f"ir_{key}.json"
        )
        if os.path.exists(cache_path):
            try:
                with open(cache_path, encoding="utf-8") as fh:
                    doc = json.load(fh)
                meta.update(doc.get("meta", {}))
                meta["cache"] = "hit"
                return [Finding(**d) for d in doc["findings"]], meta
            except (ValueError, KeyError, TypeError):
                pass  # corrupt cache entry: recompute and overwrite

    findings: list[Finding] = []
    for name, build in specs.items():
        try:
            prog = build()
            result = analyze_program(prog)
        except SkipProgram as exc:
            meta["skipped"][name] = str(exc)
            continue
        except Exception as exc:
            findings.append(Finding(
                rule="IR000", path="bfs_tpu/analysis/ir.py", line=0, col=0,
                message=f"[{name}] spec builder failed: "
                        f"{type(exc).__name__}: {exc}",
                snippet=f"ir:{name}:builder",
            ))
            continue
        meta["programs"].append(name)
        findings.extend(result)

    findings.sort(key=lambda f: (f.path, f.rule, f.snippet))
    if cache_path is not None:
        try:
            os.makedirs(os.path.dirname(cache_path), exist_ok=True)
            tmp = f"{cache_path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(
                    {"meta": {k: v for k, v in meta.items()
                              if k != "cache"},
                     "findings": [_finding_to_dict(f) for f in findings]},
                    fh,
                )
            os.replace(tmp, cache_path)
        except OSError:
            pass
    return findings, meta
