"""Observability discipline (OBS001).

The telemetry contract (bfs_tpu/obs/telemetry.py): device telemetry is
carried as ``while_loop`` state and pulled EXACTLY ONCE at loop exit —
one ``jax.device_get`` of the ~1 KB accumulators.  Any telemetry or
metrics READ inside a declared hot region (a jitted loop body, a
timed-repeat span, a serve batch runner) would either sync the device
per superstep (the ~107 ms tunnel round-trip the whole design deletes)
or concretize a traced value.  The same goes for the registry/exporter
surfaces: ``snapshot()``, ``artifact_report()``, ``retrace_report()``,
``span_report()``, ``chrome_trace()`` are reporting-path calls — legal
anywhere EXCEPT a hot region.

Span/counter WRITES (``span(...)``, ``instant(...)``, ``bump(...)``) are
not flagged: they are host-side appends with no device interaction, and
wrapping a hot region in a span is the intended usage.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile, dotted_name, hot_regions
from .transfer import _region_for

#: Call names (the dotted tail) that READ telemetry/metrics state.
_OBS_READ_CALLS = {
    "read_telemetry",
    "snapshot",
    "artifact_report",
    "retrace_report",
    "span_report",
    "chrome_trace",
    "stitch_journal_trace",
    "to_prometheus",
}


def check_obs(src: SourceFile) -> list[Finding]:
    regions = hot_regions(src)
    if not regions:
        return []
    findings: list[Finding] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        line = getattr(node, "lineno", None)
        if line is None:
            continue
        region = _region_for(line, regions)
        if region is None:
            continue
        name = dotted_name(node.func)
        tail = name.rsplit(".", 1)[-1] if name else ""
        if tail in _OBS_READ_CALLS:
            f = src.finding(
                "OBS001", node,
                f"hot region '{region.name}': telemetry/metrics read "
                f"{tail}() inside the hot path — carry the accumulator "
                "through the loop and pull it once at loop exit (one "
                "device_get)",
            )
            if f is not None:
                findings.append(f)
    return findings
