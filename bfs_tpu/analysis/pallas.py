"""Kernel-grade static analysis: run every hand-written Pallas kernel at
lint scale and prove the invariants Mosaic will not check for us.

The fourth analyzer rung.  AST (:mod:`.core`) polices what the SOURCE
says, jaxpr (:mod:`.ir`) what we ASK XLA to do, HLO (:mod:`.hlo`) what
XLA EMITS — but the ~1.2k lines of hand-written Pallas kernels in
``ops/relay_pallas.py`` are opaque to all three: a jaxpr walk sees one
``pallas_call`` eqn, the optimized HLO one ``custom-call``-shaped
kernel, and neither knows the kernel's VMEM budget, its grid's output
partition, or whether its manual-DMA windows stay inside the mask
arrays.  Those properties are exactly the ones that fail ONLY on real
TPUs (Mosaic OOMs VMEM, a mis-partitioned grid races, a stale stage
table DMAs past its array) — so they need a compile-free gate that runs
in tier-1 on CPU.

Mechanism: a :data:`KERNEL_SPECS` registry (set-equality-pinned against
every ``pl.pallas_call`` site discovered by AST in ``bfs_tpu/`` — an
unregistered kernel fails lint AND tier-1) whose entries build tiny
deterministic operands and invoke the SHIPPING wrapper functions in
interpret mode under a ``pallas_call`` spy.  The spy records each call's
grid, BlockSpecs, out shapes and scratch allocations — the real
parameters the real code computed, not a re-derivation — and the rules
walk the records:

* **PAL001 VMEM residency proof** — per captured call: grid-blocked
  operand/output blocks are double-buffered by the Pallas pipeline
  (2x block bytes each) and explicit VMEM scratch counted at its full
  declared shape (DMA depth is already in the shape), summed against
  ``BFS_TPU_PAL_VMEM_MB`` (default 16 MB/core).  Reported per kernel
  like the IR004 HBM proof; the bench-scale derivation lives in
  ARCHITECTURE §21.
* **PAL002 tile alignment** — every blocked dimension checked against
  the (8, 128) sublane/lane tiling for its dtype (16/32 sublanes for
  2/1-byte types); specs flagged ``mxu=True`` (the ROADMAP item 2
  expansion arm) must additionally tile to the 128x128 MXU.
* **PAL003 grid write-aliasing** — each output BlockSpec's index map is
  evaluated over every grid step; two steps mapping the same output
  block is the data race ``pl.when``-guarded stores can hide (errors
  unless the spec declares accumulation), and a block no step writes is
  garbage output.
* **PAL004 dynamic-slice bounds** — auto half: every grid-blocked input
  block must lie inside its operand and the grid must cover the whole
  array (a ``tile_rows`` that does not divide the row count silently
  drops the tail — the ADVICE r4 bug class).  Manual half: the spec
  supplies the kernels' ``pl.ds`` DMA windows (computed from the SAME
  static stage tables the kernels consume, via the ``*_windows``
  helpers below) and every window must fit its mask array.
* **PAL005 interpret-vs-XLA parity oracle** — the dynamic leg: the
  captured interpret-mode result is compared bit-identical against the
  kernel's shipping XLA fallback twin (``ops/relay.rowmin_ranks``,
  ``apply_relay_candidates_packed``, ``apply_benes_std``,
  ``relay_elem.apply_benes_elem``).  A kernel whose twin disagrees at
  lint scale is wrong on every TPU.

Like the IR/HLO rungs this module imports jax and is loaded only by the
``--pallas`` CLI path and its tests.  The cold run costs ~20 s of
interpret-mode execution, so results are content-addressed exactly like
the other rungs (sources + jax version/backend/devices + PAL_VERSION +
flavor env; ``.bench_cache/pal/``, ``BFS_TPU_PAL_CACHE``).  Findings
share ``baseline.txt`` with line-drift-proof ``pal:<kernel>:<detail>``
fingerprints and the unified stale-entry semantics.
"""

from __future__ import annotations

import ast
import hashlib
import json
import math
import os
from dataclasses import dataclass, field

from .. import knobs
from .core import Finding
from .ir import (
    SkipProgram,
    _ensure_jax_env,
    _source_fingerprint,
    repo_root,
)

#: Bump to invalidate every cached Pallas result (rule semantics changed).
PAL_VERSION = 1

#: Env knobs that change kernel flavors/shapes — DERIVED from the
#: registry (``affects`` contains ``pal``): the IR flavor set plus the
#: relay_pallas module constants (read at import) and the VMEM budget
#: rule input.  KNB002 proves membership against bfs_tpu/knobs.py.
_PAL_FLAVOR_ENV = knobs.flavor_env("pal")


def vmem_budget_bytes() -> int:
    """Per-core VMEM budget the PAL001 proof checks against.
    ``BFS_TPU_PAL_VMEM_MB`` overrides (e.g. proving a raised
    scoped-vmem config); the default is the classic 16 MB/core."""
    return int(knobs.get("BFS_TPU_PAL_VMEM_MB") * (1 << 20))


# --------------------------------------------------------------------------
# Specs: one registered kernel = one shipping wrapper invocation.
# --------------------------------------------------------------------------

@dataclass
class Window:
    """One manual-DMA window (a ``pl.ds`` row slice) PAL004 must prove
    in-bounds: rows ``[start, start+size)`` of a ``limit``-row ref."""

    label: str
    start: int
    size: int
    limit: int


@dataclass
class KernelCase:
    """One built kernel invocation plus its declared contracts.

    ``run()`` must invoke the shipping wrapper(s) so the ``pallas_call``
    spy captures the real grid/BlockSpecs; ``twin()`` (optional) is the
    XLA fallback the PAL005 oracle diffs against, bit-identical.
    """

    run: object  # () -> result pytree (executed under the capture spy)
    twin: object = None  # () -> the XLA twin's result pytree, or None
    #: manual-DMA windows for PAL004 (refs the kernel slices itself)
    windows: list = field(default_factory=list)
    #: grid steps may write the same output block on purpose (reductions)
    accumulates: bool = False
    #: blocks must tile the 128x128 MXU (the expansion-arm contract)
    mxu: bool = False


@dataclass
class KernelSpec:
    """Registry entry: which ``pallas_call`` sites this kernel covers and
    how to build its lint-scale case."""

    name: str
    path: str  # repo-relative source anchor for findings
    sites: tuple  # ("bfs_tpu/ops/relay_pallas.py::fn", ...) covered
    build: object  # () -> KernelCase


# --------------------------------------------------------------------------
# The pallas_call spy: capture the REAL call parameters.
# --------------------------------------------------------------------------

@dataclass
class SpecInfo:
    """One BlockSpec paired with the array it blocks."""

    block_shape: tuple | None  # None = unblocked (memory_space ref)
    index_map: object
    array_shape: tuple
    itemsize: int
    label: str  # "in0" / "out1" — the finding detail anchor


@dataclass
class CallRecord:
    """One captured ``pl.pallas_call`` invocation."""

    kernel_name: str
    grid: tuple
    in_specs: list
    out_specs: list
    scratch_bytes: int  # explicit VMEM scratch (semaphores excluded)
    scratch_shapes: list  # [(shape, dtype_str), ...] for reporting
    interpret: bool
    #: non-None = the call used a parameter shape the spy cannot decode
    #: (e.g. grid_spec=) — the rules would run vacuously, so analyze
    #: turns this into a loud PAL000 instead of a silent green.
    undecoded: str | None = None


def _leaves(x):
    import jax

    return jax.tree_util.tree_leaves(x)


def _spec_infos(specs, arrays, label: str) -> list:
    import numpy as np

    out = []
    for i, (bs, arr) in enumerate(zip(specs, arrays)):
        block = getattr(bs, "block_shape", None)
        shape = tuple(getattr(arr, "shape", ()))
        dtype = getattr(arr, "dtype", None)
        itemsize = int(np.dtype(dtype).itemsize) if dtype is not None else 4
        if block is not None:
            # None elements mean "whole dimension" in a BlockSpec.
            block = tuple(
                int(d) if b is None else int(b)
                for b, d in zip(block, shape)
            )
        out.append(SpecInfo(
            block_shape=block, index_map=getattr(bs, "index_map", None),
            array_shape=shape, itemsize=itemsize, label=f"{label}{i}",
        ))
    return out


def _scratch_info(scratch_shapes) -> tuple[int, list]:
    import numpy as np

    total, shapes = 0, []
    for s in scratch_shapes or ():
        shape = tuple(getattr(s, "shape", ()))
        dtype = getattr(s, "dtype", None)
        name = str(dtype)
        if "sem" in name:  # semaphores occupy semaphore memory, not VMEM
            continue
        try:
            itemsize = int(np.dtype(dtype).itemsize)
        except TypeError:
            itemsize = 4
        total += int(math.prod(shape)) * itemsize
        shapes.append((shape, name))
    return total, shapes


def capture_pallas_calls(fn):
    """Run ``fn()`` with ``pl.pallas_call`` wrapped so every invocation's
    real parameters are recorded.  The kernels import pallas inside their
    function bodies, so patching the module attribute is seen by every
    call.  Returns ``(result, [CallRecord, ...])``."""
    from jax.experimental import pallas as pl

    records: list[CallRecord] = []
    real = pl.pallas_call

    def spy(kernel, **kwargs):
        inner = real(kernel, **kwargs)

        def call(*operands):
            grid = kwargs.get("grid", ())
            if isinstance(grid, int):
                grid = (grid,)
            in_specs = list(kwargs.get("in_specs", ()) or ())
            out_spec_leaves = _leaves(kwargs.get("out_specs"))
            out_shape_leaves = _leaves(kwargs.get("out_shape"))
            scratch_bytes, scratch_shapes = _scratch_info(
                kwargs.get("scratch_shapes")
            )
            undecoded = None
            if kwargs.get("grid_spec") is not None:
                # grid/in_specs/out_specs live inside the grid_spec
                # object; the rules above would all run over EMPTY spec
                # lists and pass vacuously on a kernel that is anything
                # but policed.
                undecoded = "grid_spec="
            records.append(CallRecord(
                kernel_name=getattr(kernel, "__name__", "<kernel>"),
                grid=tuple(int(g) for g in grid),
                in_specs=_spec_infos(in_specs, operands, "in"),
                out_specs=_spec_infos(
                    out_spec_leaves, out_shape_leaves, "out"
                ),
                scratch_bytes=scratch_bytes,
                scratch_shapes=scratch_shapes,
                interpret=bool(kwargs.get("interpret", False)),
                undecoded=undecoded,
            ))
            return inner(*operands)

        return call

    pl.pallas_call = spy
    try:
        result = fn()
    finally:
        pl.pallas_call = real
    return result, records


# --------------------------------------------------------------------------
# Manual-DMA window enumeration: the kernels' `pl.ds` arithmetic over the
# static stage tables.  This is the ONE deliberate duplication of the
# kernels' offset formulas (st.offset // LANES + pid * rows) — PAL005's
# bit-parity run proves the kernels themselves; these windows prove the
# STATIC TABLES they consume (a stale/corrupt stage table whose offsets
# run past the prepared mask arrays is exactly what PAL004 catches).
# --------------------------------------------------------------------------

def benes_word_windows(pass_static_info, array_rows: list, n: int) -> list:
    """Every mask-DMA window of :func:`ops.relay_pallas.apply_benes_fused`
    for one prepared layout.  ``array_rows``: row counts of the prepared
    mask arrays in ``prepare_pass_masks`` order."""
    from ..ops.relay_pallas import LANES, _is_lane_compact, _stage_rows

    windows: list[Window] = []
    r = n // 32 // LANES
    ai = 0
    for mode, tr, tt, specs in pass_static_info:
        main_rows = array_rows[ai]
        ai += 1
        lane_rows = None
        if mode == "local" and any(_is_lane_compact(st) for st in specs):
            lane_rows = array_rows[ai]
            ai += 1
        if mode == "local_tm":
            block_rows = sum(_stage_rows(st, tr) for st in specs)
            for t in range(max(r // tr, 1)):
                windows.append(Window(
                    f"tm:tile{t}", t * block_rows, block_rows, main_rows
                ))
        elif mode == "local":
            for pid in range(r // tr):
                for st in specs:
                    rows = _stage_rows(st, tr)
                    limit = (
                        lane_rows if _is_lane_compact(st) else main_rows
                    )
                    windows.append(Window(
                        f"local:d{st.d}:p{pid}",
                        st.offset // LANES + pid * rows, rows, limit,
                    ))
        else:  # outer
            span = (r // tr) // 2  # outer stages are always pair-compact
            rows = span * tt
            for pid in range(tr // tt):
                for st in specs:
                    windows.append(Window(
                        f"outer:d{st.d}:p{pid}",
                        st.offset // LANES + pid * rows, rows, main_rows,
                    ))
    return windows


def benes_elem_windows(pass_static_info, array_rows: list, n: int) -> list:
    """Mask-DMA windows of :func:`ops.relay_pallas.apply_benes_elem_fused`
    (vertically-packed masks: one stored row per 32 element rows)."""
    from ..ops.relay_pallas import LANES

    windows: list[Window] = []
    r = n // LANES
    for ai, (mode, tr, tt, specs) in enumerate(pass_static_info):
        main_rows = array_rows[ai]
        if mode == "local":
            for pid in range(r // tr):
                for st in specs:
                    mrows = (tr // 2 if st.compact else tr) // 32
                    windows.append(Window(
                        f"elem-local:d{st.d}:p{pid}",
                        st.offset // LANES + pid * mrows, mrows, main_rows,
                    ))
        else:  # outer
            span = (r // tr) // 2
            mrows = span * (tt // 32)
            for pid in range(tr // tt):
                for st in specs:
                    windows.append(Window(
                        f"elem-outer:d{st.d}:p{pid}",
                        st.offset // LANES + pid * mrows, mrows, main_rows,
                    ))
    return windows


# --------------------------------------------------------------------------
# Per-kernel analysis.
# --------------------------------------------------------------------------

def tree_bit_identical(a, b):
    """``(ok, detail)`` — every leaf bit-identical in shape, dtype and
    value.  The PAL005 contract: the fused kernels are drop-in twins of
    their XLA fallbacks, not approximations."""
    import numpy as np

    la, lb = _leaves(a), _leaves(b)
    if len(la) != len(lb):
        return False, f"leaf count {len(la)} != {len(lb)}"
    for i, (x, y) in enumerate(zip(la, lb)):
        xa, ya = np.asarray(x), np.asarray(y)
        if xa.shape != ya.shape:
            return False, f"leaf {i}: shape {xa.shape} != {ya.shape}"
        if xa.dtype != ya.dtype:
            return False, f"leaf {i}: dtype {xa.dtype} != {ya.dtype}"
        # Raw-byte comparison, not value equality: -0.0 == 0.0 and
        # NaN != NaN would both misjudge a float kernel's parity
        # (review finding) — the contract is the BITS agree.
        ba, bb = xa.tobytes(), ya.tobytes()
        if ba != bb:
            n = max(xa.size, 1)
            va = np.frombuffer(ba, np.uint8).reshape(n, -1)
            vb = np.frombuffer(bb, np.uint8).reshape(n, -1)
            neq = (va != vb).any(axis=1)
            return False, (
                f"leaf {i}: {int(neq.sum())}/{n} elements differ "
                f"bit-wise (first at flat index {int(np.argmax(neq))})"
            )
    return True, ""


def analyze_kernel(spec: KernelSpec) -> list:
    """All PAL findings for one registered kernel (deduped, sorted)."""
    from .pallas_rules import check_kernel

    def make_finding(rule: str, detail: str, message: str) -> Finding:
        return Finding(
            rule=rule, path=spec.path, line=0, col=0,
            message=f"[{spec.name}] {message}",
            snippet=f"pal:{spec.name}:{detail}",
        )

    try:
        case = spec.build()
        result, records = capture_pallas_calls(case.run)
    except SkipProgram:
        raise
    except Exception as exc:
        return [make_finding(
            "PAL000", "build",
            f"could not build/run the kernel case: "
            f"{type(exc).__name__}: {exc}",
        )]
    findings = []
    if not records:
        findings.append(make_finding(
            "PAL000", "no-pallas-call",
            "the case ran without invoking pl.pallas_call — the spec no "
            "longer exercises its kernel (fallback path taken?)",
        ))
    for rec in records:
        if rec.undecoded is not None:
            findings.append(make_finding(
                "PAL000", f"undecoded:{rec.kernel_name}",
                f"kernel '{rec.kernel_name}' passes {rec.undecoded} to "
                "pallas_call, which the capture spy cannot decode — the "
                "static rules would run over empty spec lists and pass "
                "vacuously; extend capture_pallas_calls before "
                "registering this kernel shape",
            ))
    findings += check_kernel(spec, case, records, make_finding)
    if case.twin is not None:
        try:
            expected = case.twin()
        except Exception as exc:
            findings.append(make_finding(
                "PAL000", "twin",
                f"XLA twin failed to run: {type(exc).__name__}: {exc}",
            ))
        else:
            ok, detail = tree_bit_identical(result, expected)
            if not ok:
                findings.append(make_finding(
                    "PAL005", "parity",
                    f"interpret-mode kernel output is NOT bit-identical "
                    f"to its XLA fallback twin: {detail} — the fused "
                    "kernel and the fallback disagree, so one of them "
                    "is wrong on every backend that selects it",
                ))
    seen, out = set(), []
    for f in findings:
        key = (f.rule, f.snippet)
        if key not in seen:
            seen.add(key)
            out.append(f)
    out.sort(key=lambda f: (f.path, f.rule, f.snippet))
    return out


# --------------------------------------------------------------------------
# Site discovery + the set-equality pin.
# --------------------------------------------------------------------------

def discover_pallas_sites(root: str | None = None) -> set:
    """Every ``pl.pallas_call`` call site in ``bfs_tpu/`` as
    ``"<repo-relative path>::<enclosing function>"``.  AST-based and
    stdlib-only: the pin must see sites even in modules that fail to
    import."""
    root = root or repo_root()
    pkg = os.path.join(root, "bfs_tpu")
    sites: set[str] = set()
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = sorted(
            d for d in dirnames
            if d != "__pycache__" and not d.startswith(".")
        )
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            try:
                with open(path, encoding="utf-8") as fh:
                    tree = ast.parse(fh.read(), filename=rel)
            except SyntaxError:
                continue
            stack: list[str] = []

            def walk(node):
                for child in ast.iter_child_nodes(node):
                    if isinstance(
                        child, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        stack.append(child.name)
                        walk(child)
                        stack.pop()
                        continue
                    if isinstance(child, ast.Call):
                        f = child.func
                        name = (
                            f.attr if isinstance(f, ast.Attribute)
                            else getattr(f, "id", "")
                        )
                        if name == "pallas_call":
                            owner = stack[0] if stack else "<module>"
                            sites.add(f"{rel}::{owner}")
                    walk(child)

            walk(tree)
    return sites


def registry_findings(specs: dict, root: str | None = None) -> list:
    """The set-equality pin as lint findings: every discovered
    ``pallas_call`` site must be covered by a spec, and every spec site
    must still exist."""
    discovered = discover_pallas_sites(root)
    covered: set[str] = set()
    for spec_build in specs.values():
        covered.update(getattr(spec_build, "sites", ()))
    findings = []
    for site in sorted(discovered - covered):
        findings.append(Finding(
            rule="PAL000", path=site.split("::")[0], line=0, col=0,
            message=(
                f"pallas_call site '{site}' has no KERNEL_SPECS entry — "
                "an unregistered kernel is an unpoliced kernel; add a "
                "spec covering it (bfs_tpu/analysis/pallas.py)"
            ),
            snippet=f"pal:registry:unregistered:{site}",
        ))
    for site in sorted(covered - discovered):
        findings.append(Finding(
            rule="PAL000", path=site.split("::")[0], line=0, col=0,
            message=(
                f"KERNEL_SPECS covers site '{site}' which no longer "
                "exists — prune or update the spec"
            ),
            snippet=f"pal:registry:missing:{site}",
        ))
    return findings


def registered_sites(specs: dict | None = None) -> set:
    specs = specs if specs is not None else KERNEL_SPECS
    out: set[str] = set()
    for build in specs.values():
        out.update(getattr(build, "sites", ()))
    return out


# --------------------------------------------------------------------------
# The kernel registry: every shipped pallas_call site, built tiny.
# --------------------------------------------------------------------------

_PAL_PATH = "bfs_tpu/ops/relay_pallas.py"
_MXU_PATH = "bfs_tpu/ops/relay_mxu.py"
_BUILD_CACHE: dict = {}


def _memo(key, build):
    if key not in _BUILD_CACHE:
        _BUILD_CACHE[key] = build()
    return _BUILD_CACHE[key]


class _forced_env:
    """Deterministically pin flavor env inside a spec builder (the
    lane-compact spec must build its pass layout with the knob ON no
    matter the ambient env, and restore on exit)."""

    def __init__(self, **env):
        self.env = env
        self.saved: dict = {}

    def __enter__(self):
        for k, v in self.env.items():
            self.saved[k] = os.environ.get(k)
            os.environ[k] = v
        return self

    def __exit__(self, *exc):
        for k, old in self.saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old


def _routed_words(n: int, seed: int):
    """A routed Beneš layout at lint scale: (masks, table, packed input
    words, unpacked reference bits).  Requires the native router (the
    jax route arm exists but the walker is the pinned oracle) — skipped
    when unavailable, like the mesh programs below 2 devices."""
    def build():
        import numpy as np

        from ..graph import benes
        from ..graph.relay import _compact_and_table

        if not benes.native_available():
            raise SkipProgram("native benes router unavailable")
        rng = np.random.default_rng(seed)
        perm = rng.permutation(n).astype(np.int64)
        masks, table = _compact_and_table(benes.route_std(perm), n)
        bits = rng.integers(0, 2, size=n).astype(np.uint8)
        return masks, table, bits

    return _memo(("routed", n, seed), build)


#: Word-pass lint scale: r = n/32/128 = 32 rows; tile_rows=16 keeps two
#: local tiles AND leaves the d >= tr*4096 stages to the outer passes,
#: so one case exercises both the tile-major local kernel and the
#: outer-pass kernel of _run_pass.
_WORD_N = 1 << 17
_WORD_TR = 16


def _spec_benes_word_tile_major() -> KernelCase:
    import jax.numpy as jnp

    from ..ops.relay import apply_benes_std, pack_std
    from ..ops import relay_pallas as RP

    masks, table, bits = _routed_words(_WORD_N, 5)
    with _forced_env(BFS_TPU_LANE_COMPACT="0"):
        ps = RP.pass_static(table, _WORD_N, tile_rows=_WORD_TR)
        arrays = [
            jnp.asarray(a)
            for a in RP.prepare_pass_masks(
                masks, table, _WORD_N, tile_rows=_WORD_TR
            )
        ]
    x = pack_std(jnp.asarray(bits))
    return KernelCase(
        run=lambda: RP.apply_benes_fused(
            x, arrays, ps, _WORD_N, interpret=True
        ),
        twin=lambda: apply_benes_std(
            x, jnp.asarray(masks), table, _WORD_N
        ),
        windows=benes_word_windows(
            ps, [int(a.shape[0]) for a in arrays], _WORD_N
        ),
    )


def _spec_benes_word_lane_compact() -> KernelCase:
    import jax.numpy as jnp

    from ..ops.relay import apply_benes_std, pack_std
    from ..ops import relay_pallas as RP

    masks, table, bits = _routed_words(_WORD_N, 5)
    with _forced_env(BFS_TPU_LANE_COMPACT="1"):
        ps = RP.pass_static(table, _WORD_N, tile_rows=_WORD_TR)
        arrays = [
            jnp.asarray(a)
            for a in RP.prepare_pass_masks(
                masks, table, _WORD_N, tile_rows=_WORD_TR
            )
        ]
        local = next(sp for m, _t, _tt, sp in ps if m == "local")
        if not any(RP._is_lane_compact(st) for st in local):
            raise SkipProgram(
                "no lane-compactable stage at lint scale — the "
                "per-stage path is not exercised"
            )
    x = pack_std(jnp.asarray(bits))
    return KernelCase(
        run=lambda: RP.apply_benes_fused(
            x, arrays, ps, _WORD_N, interpret=True
        ),
        twin=lambda: apply_benes_std(
            x, jnp.asarray(masks), table, _WORD_N
        ),
        windows=benes_word_windows(
            ps, [int(a.shape[0]) for a in arrays], _WORD_N
        ),
    )


#: Element-pass lint scale: r = n/128 = 64 element rows; tile_rows=32
#: forces outer prefix/suffix passes around a 2-tile local run.
_ELEM_N = 1 << 13
_ELEM_TR = 32
_ELEM_TT = 32


def _spec_benes_elem() -> KernelCase:
    def build_ops():
        import numpy as np

        import jax.numpy as jnp

        from ..ops import relay_pallas as RP

        masks, table, _bits = _routed_words(_ELEM_N, 9)
        ps = RP.elem_pass_static(
            table, _ELEM_N, tile_rows=_ELEM_TR, outer_tt=_ELEM_TT
        )
        arrays = [
            jnp.asarray(a)
            for a in RP.prepare_elem_pass_masks(
                masks, table, _ELEM_N, tile_rows=_ELEM_TR,
                outer_tt=_ELEM_TT,
            )
        ]
        rng = np.random.default_rng(13)
        x = jnp.asarray(
            rng.integers(0, 2**32, (2, _ELEM_N), dtype=np.uint32)
        )
        return masks, table, ps, arrays, x

    import jax.numpy as jnp

    from ..ops import relay_pallas as RP
    from ..ops.relay_elem import apply_benes_elem

    masks, table, ps, arrays, x = _memo("elem_case", build_ops)
    return KernelCase(
        run=lambda: RP.apply_benes_elem_fused(
            x, arrays, ps, _ELEM_N, interpret=True
        ),
        twin=lambda: apply_benes_elem(
            x, jnp.asarray(masks), table, _ELEM_N
        ),
        windows=benes_elem_windows(
            ps, [int(a.shape[0]) for a in arrays], _ELEM_N
        ),
    )


def _rowmin_case():
    """Synthetic class layout for the tournament: one fused-eligible
    rank-major class (width 4 — the narrow widths real degree classes
    produce), one vertex-major class on the XLA fallback, and a sentinel
    tail past the last class — the three per-class paths of
    rowmin_ranks_pallas in one call."""
    def build():
        import numpy as np

        import jax.numpy as jnp

        from ..graph.relay import ClassSlice

        a = ClassSlice(width=4, va=0, vb=4096, sa=0, sb=4 * 4096,
                       real=4096, vertex_major=False, real_width=4)
        b = ClassSlice(width=64, va=4096, vb=4096 + 32, sa=4 * 4096,
                       sb=4 * 4096 + 32 * 64, real=32, vertex_major=True,
                       real_width=64)
        rng = np.random.default_rng(17)
        nwords = b.sb // 32
        l1 = jnp.asarray(rng.integers(0, 2**32, nwords, dtype=np.uint32))
        valid = jnp.asarray(
            rng.integers(0, 2**32, nwords, dtype=np.uint32)
        )
        return [a, b], l1, valid, b.vb + 64

    return _memo("rowmin_case", build)


def _spec_rowmin_tournament() -> KernelCase:
    from ..ops import relay_pallas as RP
    from ..ops.relay import rowmin_ranks

    classes, l1, valid, vr = _rowmin_case()
    if not any(RP.rowmin_class_ok(cs) for cs in classes):
        raise SkipProgram("no fused-eligible class at lint scale")
    return KernelCase(
        run=lambda: RP.rowmin_ranks_pallas(
            l1, valid, classes, vr, interpret=True
        ),
        twin=lambda: rowmin_ranks(l1, valid, classes, vr),
    )


def _update_case():
    def build():
        import numpy as np

        import jax.numpy as jnp

        from ..ops.relay import PackedRelayState

        # vr a multiple of 32 (the fwords contract) but NOT of the
        # kernel's 4096 alignment — the sentinel-padded tail path runs.
        vr = 4992
        rng = np.random.default_rng(23)
        packed = np.full(vr, 0xFFFFFFFF, np.uint32)
        packed[rng.integers(0, vr, 800)] = rng.integers(
            0, 1 << 26, 800, dtype=np.uint32
        )
        cand = np.full(vr, 0xFFFFFFFF, np.uint32)
        cand[rng.integers(0, vr, 900)] = rng.integers(
            0, 1 << 26, 900, dtype=np.uint32
        )
        state = PackedRelayState(
            jnp.asarray(packed), jnp.zeros(vr // 32, jnp.uint32),
            jnp.int32(2), jnp.bool_(True),
        )
        return state, jnp.asarray(cand)

    return _memo("update_case", build)


def _spec_update_packed() -> KernelCase:
    from ..ops import relay_pallas as RP
    from ..ops.relay import apply_relay_candidates_packed

    state, cand = _update_case()
    return KernelCase(
        run=lambda: RP.apply_relay_candidates_packed_pallas(
            state, cand, interpret=True
        ),
        twin=lambda: apply_relay_candidates_packed(state, cand),
    )


def _mxu_case():
    """Deterministic lint-scale MXU expansion fixture: a small scrambled-
    key tile layout (host oracle builder) whose geometry is deliberately
    unaligned — rows not a multiple of 128, cols under one superblock —
    so the padding conventions (inert tiles, zero frontier pad block,
    sentinel key rows) all execute."""
    def build():
        import numpy as np

        import jax.numpy as jnp

        from ..graph.adj_tiles import build_adj_tiles_host, keys_from_new2old

        rng = np.random.default_rng(41)
        rows, cols, e = 1376, 800, 4000
        src = rng.integers(0, rows, e)
        dst = rng.integers(0, cols, e)
        keys2d = keys_from_new2old(
            rng.permutation(rows).astype(np.int64), rows
        )
        at = build_adj_tiles_host(
            src, dst, rows=rows, cols=cols, keys2d=keys2d
        )
        fw = rng.integers(0, 2**32, at.rtp // 32, dtype=np.uint32)
        # One guaranteed-empty frontier row block: the early-out branch
        # must execute (and the twin must agree it contributes nothing).
        fw[0:4] = 0
        return at, jnp.asarray(fw[: rows // 32 + (1 if rows % 32 else 0)])

    return _memo("mxu_case", build)


def _spec_expand_mxu() -> KernelCase:
    from ..graph.adj_tiles import TILE
    from ..ops import relay_mxu as RM

    at, fw = _mxu_case()
    ops = RM.mxu_device_operands(at)
    windows = []
    ntp = at.ntp
    rb_limit = at.keys2d.shape[0]
    for t in range(ntp):
        windows.append(Window(f"mxu:tile{t}", t, 1, ntp))
        windows.append(Window(f"mxu:fblk{t}", t, 1, ntp))
        windows.append(Window(
            f"mxu:keys{t}", int(at.row_idx[t]), 1, rb_limit
        ))
    return KernelCase(
        run=lambda: RM.expand_frontier_mxu(
            fw, ops, rows=at.rows, cols=at.cols, rtp=at.rtp, vtp=at.vtp,
            interpret=True,
        ),
        twin=lambda: RM.expand_frontier_mxu_xla(
            fw, ops, rows=at.rows, cols=at.cols, rtp=at.rtp, vtp=at.vtp
        ),
        windows=windows,
        mxu=True,  # the PAL002 128x128 contract — first real consumer
    )


def _make_spec(name, sites, build, path=None):
    spec = KernelSpec(
        name=name, path=path or _PAL_PATH, sites=sites, build=build
    )

    def builder():
        return spec

    builder.sites = sites  # registry_findings reads coverage statically
    builder.spec = spec
    return builder


#: name -> spec builder.  Order is the report order.  Together the specs'
#: ``sites`` must equal :func:`discover_pallas_sites` — set-equality
#: pinned by :func:`registry_findings` and tier-1.
KERNEL_SPECS = {
    "benes.word_tile_major": _make_spec(
        "benes.word_tile_major",
        (f"{_PAL_PATH}::_run_local_tile_major", f"{_PAL_PATH}::_run_pass"),
        _spec_benes_word_tile_major,
    ),
    "benes.word_lane_compact": _make_spec(
        "benes.word_lane_compact",
        (f"{_PAL_PATH}::_run_pass",),
        _spec_benes_word_lane_compact,
    ),
    "benes.elem_passes": _make_spec(
        "benes.elem_passes",
        (f"{_PAL_PATH}::_run_elem_pass",),
        _spec_benes_elem,
    ),
    "rowmin.tournament": _make_spec(
        "rowmin.tournament",
        (f"{_PAL_PATH}::_class_tournament_call",),
        _spec_rowmin_tournament,
    ),
    "update.packed_words": _make_spec(
        "update.packed_words",
        (f"{_PAL_PATH}::apply_relay_candidates_packed_pallas",),
        _spec_update_packed,
    ),
    # The MXU expansion arm (ISSUE 15): mxu=True — the first real
    # consumer of the PAL002 128x128 MXU block contract; PAL005 pins the
    # kernel byte-identical to its XLA twin (the raw-bytes oracle was
    # built for exactly this arm).
    "expand.frontier_mxu": _make_spec(
        "expand.frontier_mxu",
        (_MXU_PATH + "::expand_frontier_mxu",),
        _spec_expand_mxu,
        path=_MXU_PATH,
    ),
}


# --------------------------------------------------------------------------
# Content-addressed result cache + the repo entry point.
# --------------------------------------------------------------------------

def default_cache_dir(root: str | None = None) -> str:
    env = knobs.raw("BFS_TPU_PAL_CACHE") or ""
    if env:
        return env
    return os.path.join(root or repo_root(), ".bench_cache", "pal")


def _cache_key(root: str) -> str:
    import jax

    h = hashlib.blake2b(digest_size=16)
    h.update(_source_fingerprint(root).encode())
    h.update(jax.__version__.encode())
    h.update(jax.default_backend().encode())
    h.update(str(len(jax.devices())).encode())
    h.update(str(PAL_VERSION).encode())
    h.update(",".join(sorted(KERNEL_SPECS)).encode())
    for env in _PAL_FLAVOR_ENV:
        h.update(f"{env}={os.environ.get(env, '')};".encode())
    # SkipProgram results are cached, and the Beneš specs skip on a
    # NON-.py input (_source_fingerprint hashes only package sources):
    # building the native router later must miss the cache, or the
    # skipped verdict replays forever.
    try:
        from ..graph import benes

        h.update(f"native={int(benes.native_available())}".encode())
    except Exception:
        h.update(b"native=?")
    return h.hexdigest()


def _finding_to_dict(f: Finding) -> dict:
    return {
        "rule": f.rule, "path": f.path, "line": f.line, "col": f.col,
        "message": f.message, "snippet": f.snippet,
    }


def analyze_pallas(
    specs: dict | None = None,
    *,
    use_cache: bool = True,
    cache_dir: str | None = None,
    root: str | None = None,
) -> tuple[list, dict]:
    """Run the Pallas pass.  Returns ``(findings, meta)``; ``meta``
    records cache disposition, skipped kernels and per-kernel VMEM
    bytes.  ``specs`` overrides the registry (tests feed fixtures);
    custom specs are never cached and skip the repo-wide site pin —
    only the canonical registry proves coverage."""
    _ensure_jax_env()
    root = root or repo_root()
    custom = specs is not None
    specs = specs if custom else KERNEL_SPECS
    meta: dict = {
        "cache": "off" if (custom or not use_cache) else "miss",
        "kernels": [], "skipped": {}, "vmem_bytes": {},
    }

    cache_path = None
    if not custom and use_cache:
        key = _cache_key(root)
        cache_path = os.path.join(
            cache_dir or default_cache_dir(root), f"pal_{key}.json"
        )
        if os.path.exists(cache_path):
            try:
                with open(cache_path, encoding="utf-8") as fh:
                    doc = json.load(fh)
                meta.update(doc.get("meta", {}))
                meta["cache"] = "hit"
                return [Finding(**d) for d in doc["findings"]], meta
            except (ValueError, KeyError, TypeError):
                pass  # corrupt cache entry: recompute and overwrite

    findings: list[Finding] = []
    if not custom:
        findings.extend(registry_findings(specs, root))
    for name, build in specs.items():
        try:
            spec = build()
            result = analyze_kernel(spec)
        except SkipProgram as exc:
            meta["skipped"][name] = str(exc)
            continue
        except Exception as exc:
            findings.append(Finding(
                rule="PAL000", path="bfs_tpu/analysis/pallas.py",
                line=0, col=0,
                message=f"[{name}] spec builder failed: "
                        f"{type(exc).__name__}: {exc}",
                snippet=f"pal:{name}:builder",
            ))
            continue
        meta["kernels"].append(name)
        vmem = getattr(spec, "_vmem_bytes", None)
        if vmem is not None:
            meta["vmem_bytes"][name] = vmem
        findings.extend(result)

    findings.sort(key=lambda f: (f.path, f.rule, f.snippet))
    if cache_path is not None:
        try:
            os.makedirs(os.path.dirname(cache_path), exist_ok=True)
            tmp = f"{cache_path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(
                    {"meta": {k: v for k, v in meta.items()
                              if k != "cache"},
                     "findings": [_finding_to_dict(f) for f in findings]},
                    fh,
                )
            os.replace(tmp, cache_path)
        except OSError:
            pass
    return findings, meta
