"""Runtime sanitizers: the dynamic half of the analysis pass.

Two families, both zero-overhead when disabled:

**Transfer guard** — :func:`guarded_region` wraps a block in
``jax.transfer_guard("disallow")`` when ``BFS_TPU_TRANSFER_GUARD`` is set,
so an implicit device->host pull (``.item()``, ``float()``, ``__bool__``)
inside the bench timed-repeat region or the serve device batch path raises
at the offending line instead of silently costing a ~107 ms tunnel
round-trip per superstep.  Explicit ``jax.device_get``/``device_put``
remain allowed under ``disallow`` — that is the point: the hot paths are
rewritten to make every intended transfer explicit, and the guard turns
any remaining *implicit* one into a stack trace.  Env values: ``1``/
``disallow`` (default), ``log`` (warn, don't raise), ``0``/unset (off —
the tier-1 CPU default).

**Retrace counter** — :func:`traced` is placed UNDER a ``jax.jit``
decorator (or around a function handed to ``jit``): the wrapped Python
body executes exactly once per trace, so the counter names which function
retraced and how often.  The serve loadgen's "<100% steady-state compile
hit rate" failure and bench recompile stalls become diagnosable:
:func:`retrace_report` is printed by ``tools/serve_loadgen.py`` and
``tools/chaos_run.py`` on exit, and any monitor can poll it.  Counting is
lock-guarded and works under ``jit``, ``lower()``, grad, and vmap alike
(anything that re-executes the traced body).
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading

_lock = threading.Lock()
_retrace_counts: dict[str, int] = {}  # guarded-by: _lock
_hot_registry: dict[str, object] = {}  # guarded-by: _lock


def transfer_guard_level() -> str | None:
    """The configured guard level: ``'disallow'`` / ``'log'`` / None (off).

    ``BFS_TPU_TRANSFER_GUARD`` accepts ``1``/``disallow``, ``log``, or any
    explicit jax level name (``disallow_explicit`` for paranoia runs)."""
    raw = os.environ.get("BFS_TPU_TRANSFER_GUARD", "").strip().lower()
    if raw in ("", "0", "off", "false", "allow"):
        return None
    if raw in ("1", "on", "true", "disallow"):
        return "disallow"
    return raw


@contextlib.contextmanager
def guarded_region(name: str):
    """Context manager for a no-implicit-transfers region.

    No-op unless ``BFS_TPU_TRANSFER_GUARD`` is set; with it, an implicit
    transfer inside raises ``jax.errors.JaxRuntimeError`` (re-raised with
    the region name prepended so a bench log names the phase, not just
    the line)."""
    level = transfer_guard_level()
    if level is None:
        yield
        return
    import jax

    try:
        with jax.transfer_guard(level):
            yield
    except Exception as exc:
        # Name the guarded region in the failure — but ONLY for actual
        # guard violations ("Disallowed host-to-device transfer: ...");
        # any other exception raised inside the region (OOM, a ValueError
        # from the workload, a retry-path error) must pass through
        # untouched or error classifiers downstream would misattribute
        # it to a transfer.  Mutating args keeps the original type and
        # traceback (some runtime error types don't re-construct from a
        # bare string).
        head = str(exc.args[0]) if exc.args else ""
        if "Disallowed" in head and "transfer" in head:
            exc.args = (
                f"[transfer-guard:{name}] {head}",
            ) + tuple(exc.args[1:])
        raise


def hot_region(fn=None, *, name: str | None = None):
    """Decorator marking a function as a hot region.

    The static pass treats the decorated body exactly like a
    ``# bfs_tpu: hot`` pragma; at runtime the call is wrapped in
    :func:`guarded_region` when the env guard is on (free otherwise).
    Usable bare (``@hot_region``) or with a name (``@hot_region(name=...)``).
    """

    def deco(f):
        region = name or f"{f.__module__}.{f.__qualname__}"
        with _lock:
            _hot_registry[region] = f

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            if transfer_guard_level() is None:
                return f(*args, **kwargs)
            with guarded_region(region):
                return f(*args, **kwargs)

        wrapper.__bfs_tpu_hot__ = region
        return wrapper

    return deco if fn is None else deco(fn)


def hot_registry() -> dict[str, object]:
    with _lock:
        return dict(_hot_registry)


# --------------------------------------------------------------------------
# Retrace counting.
# --------------------------------------------------------------------------

def bump_retrace(name: str, by: int = 1) -> None:
    with _lock:
        _retrace_counts[name] = _retrace_counts.get(name, 0) + by


def traced(name: str):
    """Place UNDER ``jax.jit`` (or around the fn handed to ``jit``): the
    wrapper body runs once per trace, so each execution IS one (re)trace.

    ::

        @functools.partial(jax.jit, static_argnames=("n",))
        @traced("relax_superstep")
        def relax_superstep(...): ...
    """

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            bump_retrace(name)
            return fn(*args, **kwargs)

        return wrapper

    return deco


def retrace_report() -> dict[str, int]:
    """Snapshot of per-function trace counts (name -> traces this
    process).  Steady state should freeze every count; a count that moves
    during the steady phase names the function whose signature drifted."""
    with _lock:
        return dict(_retrace_counts)


def reset_retrace_counts() -> None:
    with _lock:
        _retrace_counts.clear()


def format_retrace_report(baseline: dict[str, int] | None = None) -> str:
    """Human-readable retrace table; with ``baseline`` (an earlier
    snapshot) adds a drift column — any non-zero drift after warmup is a
    recompile leak and names its function."""
    now = retrace_report()
    if not now:
        return "retraces: none recorded (no @traced functions executed)"
    lines = ["retraces (traces per function this process):"]
    for name in sorted(now):
        drift = ""
        if baseline is not None:
            d = now[name] - baseline.get(name, 0)
            drift = f"  (+{d} since warmup)" if d else "  (steady)"
        lines.append(f"  {now[name]:6d}  {name}{drift}")
    return "\n".join(lines)
