"""Runtime sanitizers: the dynamic half of the analysis pass.

Three families, all zero-overhead when disabled:

**Transfer guard** — :func:`guarded_region` wraps a block in
``jax.transfer_guard("disallow")`` when ``BFS_TPU_TRANSFER_GUARD`` is set,
so an implicit device->host pull (``.item()``, ``float()``, ``__bool__``)
inside the bench timed-repeat region or the serve device batch path raises
at the offending line instead of silently costing a ~107 ms tunnel
round-trip per superstep.  Explicit ``jax.device_get``/``device_put``
remain allowed under ``disallow`` — that is the point: the hot paths are
rewritten to make every intended transfer explicit, and the guard turns
any remaining *implicit* one into a stack trace.  Env values: ``1``/
``disallow`` (default), ``log`` (warn, don't raise), ``0``/unset (off —
the tier-1 CPU default).

**Retrace counter** — :func:`traced` is placed UNDER a ``jax.jit``
decorator (or around a function handed to ``jit``): the wrapped Python
body executes exactly once per trace, so the counter names which function
retraced and how often.  The serve loadgen's "<100% steady-state compile
hit rate" failure and bench recompile stalls become diagnosable:
:func:`retrace_report` is printed by ``tools/serve_loadgen.py`` and
``tools/chaos_run.py`` on exit, and any monitor can poll it.  Counting is
lock-guarded and works under ``jit``, ``lower()``, grad, and vmap alike
(anything that re-executes the traced body).

**Lock-order recorder** — the dynamic complement to the LCK001/LCK002
static rules (ISSUE 12 satellite).  The static checker proves every
``# guarded-by:`` field is accessed under its lock; it cannot see the
ORDER two locks are taken in across threads, which is where deadlocks
live.  Under ``BFS_TPU_LOCK_ORDER=1`` the serve/registry/executor/health
locks are built by :func:`make_lock` as recording proxies: every
"acquired B while holding A" event adds the edge A→B to a process-global
order graph, and an edge that closes a cycle (B→…→A already recorded —
the two-thread AB/BA deadlock shape) is recorded as a violation
(``BFS_TPU_LOCK_ORDER=raise`` raises :class:`LockOrderError` at the
acquisition instead).  ``lock_order_report()`` returns the edges and
cycles; the chaos serve test asserts it stays cycle-free under the full
fault+swap schedule.  With the env unset :func:`make_lock` returns a
plain ``threading.Lock``/``RLock`` — zero overhead, identical types.
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading

from .. import knobs

_lock = threading.Lock()
_retrace_counts: dict[str, int] = {}  # guarded-by: _lock
_hot_registry: dict[str, object] = {}  # guarded-by: _lock


def transfer_guard_level() -> str | None:
    """The configured guard level: ``'disallow'`` / ``'log'`` / None (off).

    ``BFS_TPU_TRANSFER_GUARD`` accepts ``1``/``disallow``, ``log``, or any
    explicit jax level name (``disallow_explicit`` for paranoia runs)."""
    return knobs.get("BFS_TPU_TRANSFER_GUARD")


@contextlib.contextmanager
def guarded_region(name: str):
    """Context manager for a no-implicit-transfers region.

    No-op unless ``BFS_TPU_TRANSFER_GUARD`` is set; with it, an implicit
    transfer inside raises ``jax.errors.JaxRuntimeError`` (re-raised with
    the region name prepended so a bench log names the phase, not just
    the line)."""
    level = transfer_guard_level()
    if level is None:
        yield
        return
    import jax

    try:
        with jax.transfer_guard(level):
            yield
    except Exception as exc:
        # Name the guarded region in the failure — but ONLY for actual
        # guard violations ("Disallowed host-to-device transfer: ...");
        # any other exception raised inside the region (OOM, a ValueError
        # from the workload, a retry-path error) must pass through
        # untouched or error classifiers downstream would misattribute
        # it to a transfer.  Mutating args keeps the original type and
        # traceback (some runtime error types don't re-construct from a
        # bare string).
        head = str(exc.args[0]) if exc.args else ""
        if "Disallowed" in head and "transfer" in head:
            exc.args = (
                f"[transfer-guard:{name}] {head}",
            ) + tuple(exc.args[1:])
        raise


def hot_region(fn=None, *, name: str | None = None):
    """Decorator marking a function as a hot region.

    The static pass treats the decorated body exactly like a
    ``# bfs_tpu: hot`` pragma; at runtime the call is wrapped in
    :func:`guarded_region` when the env guard is on (free otherwise).
    Usable bare (``@hot_region``) or with a name (``@hot_region(name=...)``).
    """

    def deco(f):
        region = name or f"{f.__module__}.{f.__qualname__}"
        with _lock:
            _hot_registry[region] = f

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            if transfer_guard_level() is None:
                return f(*args, **kwargs)
            with guarded_region(region):
                return f(*args, **kwargs)

        wrapper.__bfs_tpu_hot__ = region
        return wrapper

    return deco if fn is None else deco(fn)


def hot_registry() -> dict[str, object]:
    with _lock:
        return dict(_hot_registry)


# --------------------------------------------------------------------------
# Retrace counting.
# --------------------------------------------------------------------------

def bump_retrace(name: str, by: int = 1) -> None:
    with _lock:
        _retrace_counts[name] = _retrace_counts.get(name, 0) + by


def traced(name: str):
    """Place UNDER ``jax.jit`` (or around the fn handed to ``jit``): the
    wrapper body runs once per trace, so each execution IS one (re)trace.

    ::

        @functools.partial(jax.jit, static_argnames=("n",))
        @traced("relax_superstep")
        def relax_superstep(...): ...
    """

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            bump_retrace(name)
            return fn(*args, **kwargs)

        return wrapper

    return deco


def retrace_report() -> dict[str, int]:
    """Snapshot of per-function trace counts (name -> traces this
    process).  Steady state should freeze every count; a count that moves
    during the steady phase names the function whose signature drifted."""
    with _lock:
        return dict(_retrace_counts)


def reset_retrace_counts() -> None:
    with _lock:
        _retrace_counts.clear()


# --------------------------------------------------------------------------
# Lock-order recording.
# --------------------------------------------------------------------------

class LockOrderError(RuntimeError):
    """An acquisition closed a cycle in the lock-order graph — the
    two-thread deadlock shape, caught at the acquire that creates it."""


_lock_edges: dict[tuple[str, str], int] = {}  # guarded-by: _lock
_lock_cycles: list[list[str]] = []  # guarded-by: _lock
_lock_tls = threading.local()


def lock_order_mode() -> str | None:
    """``'record'`` / ``'raise'`` / None (off — the default)."""
    return knobs.get("BFS_TPU_LOCK_ORDER")


def _held_stack() -> list:
    stack = getattr(_lock_tls, "held", None)
    if stack is None:
        stack = _lock_tls.held = []
    return stack


# bfs_tpu: holds _lock
def _find_path(src: str, dst: str) -> list[str] | None:
    """A path src -> ... -> dst in the edge graph (caller holds _lock)."""
    stack, seen = [(src, [src])], {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for a, b in _lock_edges:
            if a == node and b not in seen:
                seen.add(b)
                stack.append((b, path + [b]))
    return None


def _record_acquire(name: str) -> None:
    """Called BEFORE blocking on ``name``: the ordering edge exists the
    moment the thread commits to the acquisition, whether or not it ever
    returns (that is exactly the deadlocked case)."""
    held = _held_stack()
    cycle = None
    with _lock:
        for h in held:
            if h == name:
                continue  # reentrant acquire orders nothing
            edge = (h, name)
            if edge not in _lock_edges:
                # New edge h -> name: a cycle exists iff name already
                # reaches h through previously recorded edges.
                path = _find_path(name, h)
                if path is not None:
                    cycle = path + [name]
                    _lock_cycles.append(cycle)
            _lock_edges[edge] = _lock_edges.get(edge, 0) + 1
    if cycle is not None and lock_order_mode() == "raise":
        raise LockOrderError(
            "lock-order cycle: " + " -> ".join(cycle)
            + " (acquired '" + name + "' while holding '"
            + cycle[-2] + "')"
        )


class _OrderedLock:
    """A recording proxy around a real lock.  Supports the ``with``
    protocol, plain acquire/release, and ``threading.Condition`` over it
    (Condition only needs acquire/release for a non-RLock inner)."""

    def __init__(self, name: str, inner):
        self._name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1):
        # Only BLOCKING acquires order locks: a try-acquire can never be
        # the blocked arm of a deadlock, and Condition._is_owned probes
        # with acquire(0) while holding arbitrary other locks — recording
        # those would fabricate reversed edges and false cycles.  The
        # blocking edge is recorded BEFORE the call on purpose: the
        # deadlocked interleaving is exactly the one that never returns.
        if blocking:
            _record_acquire(self._name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            _held_stack().append(self._name)
        return got

    def release(self):
        self._inner.release()
        held = _held_stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == self._name:
                del held[i]
                break

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def __repr__(self):
        return f"<_OrderedLock {self._name} {self._inner!r}>"


def make_lock(name: str, kind: str = "lock"):
    """Build a named lock for a ``# guarded-by:`` field.

    With ``BFS_TPU_LOCK_ORDER`` unset (the default, read at CONSTRUCTION
    time) this returns a plain ``threading.Lock``/``RLock`` — identical
    behavior and cost to before.  With it set, a recording proxy.  The
    name keys the order graph, so all instances of one class share a
    node — the checker orders lock CLASSES, not instances (two locks of
    the same name nested record nothing)."""
    inner = threading.RLock() if kind == "rlock" else threading.Lock()
    if lock_order_mode() is None:
        return inner
    return _OrderedLock(name, inner)


def lock_order_report() -> dict:
    """``{"edges": {"a->b": count}, "cycles": [[...], ...]}`` — cycles is
    non-empty iff some interleaving of the recorded acquisitions can
    deadlock."""
    with _lock:
        return {
            "edges": {f"{a}->{b}": n for (a, b), n in sorted(_lock_edges.items())},
            "cycles": [list(c) for c in _lock_cycles],
        }


def reset_lock_order() -> None:
    with _lock:
        _lock_edges.clear()
        _lock_cycles.clear()


def assert_lock_order_clean() -> None:
    """Raise :class:`LockOrderError` if any recorded cycle exists — the
    chaos-test exit gate."""
    report = lock_order_report()
    if report["cycles"]:
        raise LockOrderError(
            f"{len(report['cycles'])} lock-order cycle(s): "
            + "; ".join(" -> ".join(c) for c in report["cycles"])
        )


def format_retrace_report(baseline: dict[str, int] | None = None) -> str:
    """Human-readable retrace table; with ``baseline`` (an earlier
    snapshot) adds a drift column — any non-zero drift after warmup is a
    recompile leak and names its function."""
    now = retrace_report()
    if not now:
        return "retraces: none recorded (no @traced functions executed)"
    lines = ["retraces (traces per function this process):"]
    for name in sorted(now):
        drift = ""
        if baseline is not None:
            d = now[name] - baseline.get(name, 0)
            drift = f"  (+{d} since warmup)" if d else "  (steady)"
        lines.append(f"  {now[name]:6d}  {name}{drift}")
    return "\n".join(lines)
