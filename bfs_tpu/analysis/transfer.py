"""Transfer / trace-safety rules (TRC001–TRC006).

The failure mode these police is the paper's central one: a level-
synchronous superstep only wins while it stays on the accelerator, and a
single stray ``.item()`` / ``np.asarray`` / ``print`` inside the timed
loop re-introduces the per-superstep host round-trip the whole design
exists to delete (round-3 measured it at ~107 ms per sync through the
tunnel — more than an entire dense superstep).

Rules apply only inside HOT REGIONS (see :mod:`.core` for how regions are
declared); the same constructs are perfectly fine in build/reporting code.
TRC006 (Python control flow on traced values) additionally requires the
region to be a *traced* function body (``jax.jit``-decorated): branching
on a device value in host-timed code is a sync (TRC002 covers the
conversions it goes through), but only under a trace does it become a
concretization error.
"""

from __future__ import annotations

import ast

from .core import Finding, HotRegion, SourceFile, dotted_name, hot_regions

#: Call targets that pull a device value to the host when given one.
_MATERIALIZERS = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "np.copy", "numpy.copy",
}
_TRANSFER_CALLS = {
    "jax.device_get", "device_get", "jax.device_put", "device_put",
}
#: jnp/lax namespaces whose call results are traced values inside a jit.
_TRACED_NAMESPACES = ("jnp.", "jax.numpy.", "lax.", "jax.lax.")

_CONST_TYPES = (ast.Constant,)


def _is_constant_expr(node: ast.AST) -> bool:
    """Literals and arithmetic over literals — ``int(1e9)`` is fine."""
    return all(
        isinstance(n, (ast.Constant, ast.BinOp, ast.UnaryOp, ast.operator,
                       ast.unaryop, ast.expr_context))
        for n in ast.walk(node)
    )


def _region_for(line: int, regions: list[HotRegion]) -> HotRegion | None:
    best: HotRegion | None = None
    for r in regions:
        if r.start <= line <= r.end:
            # innermost (largest start) wins so nested defs resolve right
            if best is None or r.start > best.start:
                best = r
    return best


class _TracedValueTracker(ast.NodeVisitor):
    """Names assigned from jnp./lax. calls within one function body —
    the cheap local dataflow TRC006 runs on."""

    def __init__(self) -> None:
        self.traced_names: set[str] = set()

    def visit_Assign(self, node: ast.Assign) -> None:
        if self._traced_rhs(node.value):
            for tgt in node.targets:
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name):
                        self.traced_names.add(n.id)
        self.generic_visit(node)

    def _traced_rhs(self, value: ast.AST) -> bool:
        for n in ast.walk(value):
            if isinstance(n, ast.Call):
                name = dotted_name(n.func)
                if any(name.startswith(ns) for ns in _TRACED_NAMESPACES):
                    return True
        return False


def _expr_is_traced(node: ast.AST, traced_names: set[str]) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in traced_names:
            return True
        if isinstance(n, ast.Call):
            name = dotted_name(n.func)
            if any(name.startswith(ns) for ns in _TRACED_NAMESPACES):
                return True
    return False


def check_transfer(src: SourceFile) -> list[Finding]:
    regions = hot_regions(src)
    if not regions:
        return []
    findings: list[Finding] = []

    def emit(rule: str, node: ast.AST, msg: str) -> None:
        f = src.finding(rule, node, msg)
        if f is not None:
            findings.append(f)

    for node in ast.walk(src.tree):
        line = getattr(node, "lineno", None)
        if line is None:
            continue
        region = _region_for(line, regions)
        if region is None:
            continue

        if isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            # TRC001: .item() on anything
            if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
                emit("TRC001", node,
                     f"hot region '{region.name}': .item() forces a "
                     "device->host sync per call")
            # TRC002: float()/int()/bool() with ANY non-constant argument
            # (``not all`` — one literal arg must not whitelist the call)
            elif fname in ("float", "int", "bool") and node.args and not all(
                _is_constant_expr(a) for a in node.args
            ):
                emit("TRC002", node,
                     f"hot region '{region.name}': {fname}() on a device "
                     "value syncs; hoist it out of the hot region or mark "
                     "the intentional sync with an ok-pragma")
            # TRC003: host materialization
            elif fname in _MATERIALIZERS:
                emit("TRC003", node,
                     f"hot region '{region.name}': {fname}() materializes "
                     "its argument on the host")
            # TRC004: explicit transfer primitives
            elif fname in _TRANSFER_CALLS:
                emit("TRC004", node,
                     f"hot region '{region.name}': {fname}() is a "
                     "host<->device transfer inside the hot path")
            # TRC005: print
            elif fname == "print":
                emit("TRC005", node,
                     f"hot region '{region.name}': print() syncs device-"
                     "array arguments and serializes dispatch")

    # TRC006: per traced-function dataflow.
    for region in regions:
        if not region.traced or region.node is None:
            continue
        tracker = _TracedValueTracker()
        for stmt in getattr(region.node, "body", []):
            tracker.visit(stmt)
        # Only names provably produced by jnp./lax. calls count as traced
        # here: treating every parameter as traced flags the benign
        # container iterations (``for fold in folds:``) and static-config
        # branches (``if axis_name is not None:``) that pytree-shaped
        # kernel signatures are full of — precision over recall for a rule
        # that gates CI.
        traced_names = tracker.traced_names
        for n in ast.walk(region.node):
            if isinstance(n, (ast.If, ast.While)):
                if _expr_is_traced(n.test, traced_names):
                    f = src.finding(
                        "TRC006", n,
                        f"traced function '{region.name}': Python "
                        "if/while on a traced value concretizes at trace "
                        "time — use lax.cond/lax.while_loop/jnp.where",
                    )
                    if f is not None:
                        findings.append(f)
            elif isinstance(n, ast.For):
                if _expr_is_traced(n.iter, traced_names):
                    f = src.finding(
                        "TRC006", n,
                        f"traced function '{region.name}': Python for "
                        "over a traced value unrolls/concretizes — use "
                        "lax.fori_loop or lax.scan",
                    )
                    if f is not None:
                        findings.append(f)
    return findings


