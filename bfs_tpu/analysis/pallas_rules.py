"""The PAL001-PAL004 walks over captured ``pallas_call`` records.

Split from :mod:`.pallas` the way :mod:`.hlo_rules` is split from
:mod:`.hlo`: pallas.py owns the registry, the capture spy, the windows
helpers and the cache; this module owns what a finding *is*.  Every
check receives the spec's built :class:`~bfs_tpu.analysis.pallas.
KernelCase` plus the :class:`~bfs_tpu.analysis.pallas.CallRecord` list
the spy captured from the SHIPPING wrapper — real grids, real
BlockSpecs, real scratch shapes.  (PAL005, the parity oracle, lives in
pallas.py's analyze_kernel because it needs the run's result.)
"""

from __future__ import annotations

import math

import numpy as np


def check_kernel(spec, case, records, make_finding):
    """All static PAL findings for one captured kernel case."""
    findings = []
    vmem_peak = 0
    for rec in records:
        vmem = record_vmem_bytes(rec)
        vmem_peak = max(vmem_peak, vmem)
        findings += check_vmem(rec, vmem, make_finding)
        findings += check_tiles(rec, case, make_finding)
        findings += check_grid_aliasing(rec, case, make_finding)
        findings += check_block_bounds(rec, make_finding)
    findings += check_windows(case, make_finding)
    spec._vmem_bytes = vmem_peak  # meta reporting (analyze_pallas)
    return findings


# --------------------------------------------------------------------------
# Grid-step enumeration shared by the aliasing and bounds walks.
# --------------------------------------------------------------------------

#: Sanity cap on enumerated grid steps — lint-scale grids are tiny; a
#: runaway grid means the spec built bench-scale operands by mistake.
MAX_GRID_STEPS = 65536


def grid_steps(grid):
    if not grid:
        return [()]
    total = int(math.prod(grid))
    if total > MAX_GRID_STEPS:
        raise ValueError(
            f"grid {grid} has {total} steps — lint cases must stay tiny"
        )
    return list(np.ndindex(*tuple(int(g) for g in grid)))


def block_index(info, step):
    """The block-index tuple a BlockSpec maps one grid step to."""
    if info.index_map is None:
        idx = step
    else:
        idx = info.index_map(*step)
    if not isinstance(idx, tuple):
        idx = (idx,)
    return tuple(int(i) for i in idx)


# --------------------------------------------------------------------------
# PAL001 — VMEM residency proof.
# --------------------------------------------------------------------------

def record_vmem_bytes(rec) -> int:
    """Per-grid-step VMEM for one call: grid-blocked operands/outputs are
    double-buffered by the Pallas pipeline (the next block streams in
    while this one computes), explicit VMEM scratch counts at its full
    declared shape (DMA depth is already a dimension of it).  Unblocked
    ``memory_space`` refs stay in HBM and cost nothing here — their
    windows are PAL004's business."""
    total = 0
    for info in rec.in_specs + rec.out_specs:
        if info.block_shape is None:
            continue
        total += 2 * int(math.prod(info.block_shape)) * info.itemsize
    return total + rec.scratch_bytes


def check_vmem(rec, vmem, make_finding):
    from .pallas import vmem_budget_bytes

    budget = vmem_budget_bytes()
    if vmem <= budget:
        return []
    blocked = sum(
        2 * int(math.prod(i.block_shape)) * i.itemsize
        for i in rec.in_specs + rec.out_specs
        if i.block_shape is not None
    )
    return [make_finding(
        "PAL001", f"vmem:{rec.kernel_name}",
        f"kernel '{rec.kernel_name}' needs {vmem} bytes of VMEM per grid "
        f"step (2x {blocked // 2} double-buffered block bytes + "
        f"{rec.scratch_bytes} declared scratch "
        f"{[s for s, _d in rec.scratch_shapes]}) — over the "
        f"{budget}-byte budget (BFS_TPU_PAL_VMEM_MB); Mosaic will refuse "
        "or spill this on a real chip where no CPU test can see it",
    )]


# --------------------------------------------------------------------------
# PAL002 — (8, 128) sublane/lane tiling + MXU readiness.
# --------------------------------------------------------------------------

def _sublane_unit(itemsize: int) -> int:
    # f32/u32: 8 sublanes; bf16/u16: 16; int8/fp8: 32.
    return {4: 8, 2: 16, 1: 32}.get(itemsize, 8)


def check_tiles(rec, case, make_finding):
    findings = []
    for info in rec.in_specs + rec.out_specs:
        block = info.block_shape
        if block is None:
            continue
        lane = block[-1]
        sub = block[-2] if len(block) >= 2 else None
        unit = _sublane_unit(info.itemsize)
        bad = []
        if lane % 128 != 0:
            bad.append(f"lane dim {lane} % 128 != 0")
        if sub is not None and sub % unit != 0:
            bad.append(f"sublane dim {sub} % {unit} != 0")
        if bad:
            findings.append(make_finding(
                "PAL002",
                f"tile:{rec.kernel_name}:{info.label}:"
                f"{'x'.join(map(str, block))}",
                f"kernel '{rec.kernel_name}' {info.label} block "
                f"{block} is not ({unit}, 128)-tileable "
                f"({'; '.join(bad)}) — Mosaic pads the block to the "
                "native tile, wasting the padded lanes/sublanes every "
                "grid step",
            ))
        if case.mxu:
            mxu_bad = [
                d for d in block[-2:] if d % 128 != 0
            ] if len(block) >= 2 else [lane]
            if mxu_bad:
                findings.append(make_finding(
                    "PAL002",
                    f"mxu:{rec.kernel_name}:{info.label}",
                    f"kernel '{rec.kernel_name}' {info.label} block "
                    f"{block} does not tile the 128x128 MXU (dims "
                    f"{mxu_bad}) — the spec declares this an MXU "
                    "kernel (the expansion-arm contract)",
                ))
    return findings


# --------------------------------------------------------------------------
# PAL003 — grid write-aliasing: output blocks must partition the output.
# --------------------------------------------------------------------------

def check_grid_aliasing(rec, case, make_finding):
    findings = []
    try:
        steps = grid_steps(rec.grid)
    except ValueError as exc:
        return [make_finding(
            "PAL003", f"grid:{rec.kernel_name}", str(exc)
        )]
    for info in rec.out_specs:
        if info.block_shape is None:
            continue
        written: dict = {}
        raced = set()
        for step in steps:
            bi = block_index(info, step)
            in_range = all(
                i >= 0 and (i + 1) * b <= d
                for i, b, d in zip(bi, info.block_shape, info.array_shape)
            )
            if not in_range:
                # Out-of-range writes are check_block_bounds' overrun
                # finding; they must NOT count toward coverage here, or
                # a shifted index map (block 0 unwritten, a phantom
                # block past the end "written") passes the partition
                # check with garbage output.
                continue
            if bi in written and written[bi] != step:
                raced.add(bi)
            else:
                written[bi] = step
        if raced and not case.accumulates:
            findings.append(make_finding(
                "PAL003", f"race:{rec.kernel_name}:{info.label}",
                f"kernel '{rec.kernel_name}' output {info.label}: "
                f"{len(raced)} block(s) (e.g. {sorted(raced)[0]}) are "
                f"written by more than one grid step — grid steps may "
                "execute in any order and revisions are not "
                "synchronized, so this is a data race unless the spec "
                "declares accumulation (accumulates=True)",
            ))
        # Coverage: the written blocks must tile the whole output.
        nblocks = tuple(
            -(-d // b) for d, b in zip(info.array_shape, info.block_shape)
        )
        expected = int(math.prod(nblocks))
        if len(written) < expected:
            findings.append(make_finding(
                "PAL003", f"uncovered:{rec.kernel_name}:{info.label}",
                f"kernel '{rec.kernel_name}' output {info.label}: only "
                f"{len(written)} of {expected} output blocks are written "
                f"by the {len(steps)}-step grid — the rest of the "
                f"{info.array_shape} output is garbage",
            ))
    return findings


# --------------------------------------------------------------------------
# PAL004 — dynamic-slice bounds: auto (blocked inputs) + manual windows.
# --------------------------------------------------------------------------

def check_block_bounds(rec, make_finding):
    """Every grid-blocked block (input AND output) must lie inside its
    array, and the grid must read the whole input: a ``tile_rows`` that
    does not divide the row count silently drops the tail rows (the
    ADVICE r4 wrong-permutation class) with interpret mode still green.
    (Unwritten OUTPUT blocks are PAL003's coverage check.)"""
    findings = []
    try:
        steps = grid_steps(rec.grid)
    except ValueError:
        return []  # reported once by check_grid_aliasing
    for kind, info in (
        [("input", i) for i in rec.in_specs]
        + [("output", o) for o in rec.out_specs]
    ):
        if info.block_shape is None:
            continue
        read: set = set()
        overrun = None
        for step in steps:
            bi = block_index(info, step)
            in_range = all(
                i >= 0 and (i + 1) * b <= d
                for i, b, d in zip(bi, info.block_shape, info.array_shape)
            )
            if in_range:
                read.add(bi)
            else:
                overrun = (step, bi)
        if overrun is not None:
            findings.append(make_finding(
                "PAL004", f"block-overrun:{rec.kernel_name}:{info.label}",
                f"kernel '{rec.kernel_name}' {kind} {info.label}: grid "
                f"step {overrun[0]} maps block {overrun[1]} of shape "
                f"{info.block_shape} past the {info.array_shape} array "
                "— an out-of-bounds access the pipeline pads silently",
            ))
        if kind != "input":
            continue  # unwritten OUTPUT blocks are PAL003's coverage
        # Exact block-set coverage, not a high-watermark: an INTERIOR
        # block skipped by a warped index map (review finding) is just
        # as wrong as a dropped tail.
        expected = int(math.prod(
            -(-d // b) for d, b in zip(info.array_shape, info.block_shape)
        ))
        if len(read) < expected:
            findings.append(make_finding(
                "PAL004", f"unread-blocks:{rec.kernel_name}:{info.label}",
                f"kernel '{rec.kernel_name}' input {info.label}: only "
                f"{len(read)} of {expected} input blocks of the "
                f"{info.array_shape} operand ever enter the kernel — "
                "the unread rows never reach compute and the result is "
                "silently wrong (a non-dividing tile size or an "
                "index-map hole)",
            ))
    return findings


def check_windows(case, make_finding):
    """The manual-DMA half: every declared ``pl.ds`` window (computed
    from the static stage tables the kernels consume) must fit its mask
    array."""
    findings = []
    for w in case.windows:
        if w.start < 0 or w.start + w.size > w.limit:
            findings.append(make_finding(
                "PAL004", f"window:{w.label}",
                f"manual DMA window '{w.label}' reads rows "
                f"[{w.start}, {w.start + w.size}) of a {w.limit}-row "
                "ref — the static stage table points past its prepared "
                "mask array (stale offsets or a padded tail the "
                "relayout dropped)",
            ))
    return findings
