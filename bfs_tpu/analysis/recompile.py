"""Recompile-drift rules (RCD001–RCD005).

The cost model: a bench-scale fused-program compile is ~830 s through the
remote-compile service (round-5 ledger) and even the CPU-mesh test
programs cost hundreds of ms — so any call path that can silently hand
jit a NEW trace (fresh callable identity, drifting static argument,
per-iteration ``.lower().compile()``) turns a steady-state serving tick
into a compile storm.  The loadgen already FAILS on a <100% steady-state
compile hit rate; these rules name the call sites that can cause it
before it ships.

RCD004/RCD005 police the serve-layer :class:`ExecutableCache` contract:
the cache key must carry every value the build closure specializes on
(RCD005, error — an under-keyed executable serves wrong-shape programs),
and key elements computed per call (RCD004, warning) must provably bucket
to a bounded set — the power-of-two batch bucket is the accepted example,
recorded in the baseline with its bound.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile, dotted_name, is_jit_reference

_STATIC_KWARGS = (
    "static_argnums", "static_argnames", "donate_argnums", "donate_argnames",
)


def _is_literal(node: ast.AST) -> bool:
    """Literal tuples/lists/strings/ints (the hashable-by-construction
    shapes jit kwargs should be)."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_literal(e) for e in node.elts)
    return False


def _enclosing_stack(tree: ast.AST) -> dict[int, list[ast.AST]]:
    """Map id(node) -> chain of enclosing function/loop nodes."""
    chains: dict[int, list[ast.AST]] = {}

    def walk(node: ast.AST, stack: list[ast.AST]) -> None:
        for child in ast.iter_child_nodes(node):
            chains[id(child)] = stack
            nested = stack
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                 ast.For, ast.While, ast.ClassDef,
                 ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp),
            ):
                nested = stack + [child]
            walk(child, nested)

    walk(tree, [])
    return chains


def _in_function(stack: list[ast.AST]) -> ast.AST | None:
    for node in reversed(stack):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return node
    return None


def _in_loop_inside_same_function(stack: list[ast.AST]) -> bool:
    """True when the innermost loop is closer than the innermost function
    boundary — i.e. the call re-executes per iteration of a host loop."""
    for node in reversed(stack):
        if isinstance(
            node,
            (ast.For, ast.While,
             ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp),
        ):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return False
    return False


def _local_defs(fn: ast.AST) -> set[str]:
    """Names of defs nested directly inside ``fn`` (a jit of one of these
    from inside ``fn`` re-creates the callable per call of ``fn``)."""
    names: set[str] = set()
    for child in ast.walk(fn):
        if child is fn:
            continue
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.add(child.name)
    return names


def check_recompile(src: SourceFile) -> list[Finding]:
    findings: list[Finding] = []
    chains = _enclosing_stack(src.tree)

    def emit(rule: str, node: ast.AST, msg: str) -> None:
        f = src.finding(rule, node, msg)
        if f is not None:
            findings.append(f)

    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        stack = chains.get(id(node), [])
        fname = dotted_name(node.func)

        if is_jit_reference(node.func):
            encl = _in_function(stack)
            # RCD001: jit over a fresh callable identity, per enclosing call
            if encl is not None and node.args:
                target = _unwrap_decorator_calls(node.args[0])
                fresh = isinstance(target, ast.Lambda) or (
                    isinstance(target, ast.Name)
                    and target.id in _local_defs(encl)
                )
                if fresh:
                    emit(
                        "RCD001", node,
                        "jit() over a lambda/locally-defined function "
                        "inside a function body: every call of the "
                        "enclosing function hands jit a NEW callable and "
                        "retraces — hoist to module level or cache the "
                        "jitted callable",
                    )
            # RCD002: non-literal static/donate kwargs
            for kw in node.keywords:
                if kw.arg in _STATIC_KWARGS and not _is_literal(kw.value):
                    emit(
                        "RCD002", kw.value,
                        f"{kw.arg} is computed, not literal: the static "
                        "signature can drift between call sites and every "
                        "drift is a silent retrace",
                    )
            # RCD003: jit inside a host loop
            if _in_loop_inside_same_function(stack):
                emit(
                    "RCD003", node,
                    "jit() in a loop body creates a fresh traced callable "
                    "per iteration",
                )
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in ("lower", "compile")
            and _in_loop_inside_same_function(stack)
            # .compile() on a regex/pattern etc. is fine; require the
            # receiver chain to mention a lowering/jit shape.
            and _looks_like_jax_compile(node)
        ):
            emit(
                "RCD003", node,
                f".{node.func.attr}() inside a loop body recompiles per "
                "iteration — hoist or key through an executable cache",
            )

        # RCD004/RCD005: ExecutableCache.get(key, build) contracts.
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and _receiver_is_exe_cache(node.func.value)
            and len(node.args) >= 2
        ):
            key_node, build_node = node.args[0], node.args[1]
            key_names = {
                n.id for n in ast.walk(key_node) if isinstance(n, ast.Name)
            } | {
                n.attr for n in ast.walk(key_node) if isinstance(n, ast.Attribute)
            }
            # All enclosing functions, innermost first: the get() call often
            # sits in a nested closure while the key elements are assigned
            # one or two frames out.
            encl_fns = [
                n for n in reversed(stack)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))
            ]
            encl = encl_fns[0] if encl_fns else None
            computed = _computed_key_elements(key_node, encl_fns)
            for name, el in computed:
                emit(
                    "RCD004", el,
                    f"compile-cache key element '{name}' is computed per "
                    "call — confirm (and record in the baseline) that the "
                    "derivation buckets to a bounded shape set",
                )
            if isinstance(build_node, ast.Lambda):
                missing = _closure_reads_outside_key(
                    build_node, key_names, encl
                )
                for name in sorted(missing):
                    emit(
                        "RCD005", build_node,
                        f"build closure reads '{name}' which is not part "
                        "of the cache key: two calls differing only in "
                        f"'{name}' would share one executable",
                    )
    return findings


def _unwrap_decorator_calls(node: ast.AST) -> ast.AST:
    """Peel inline decorator applications off a jit target:
    ``traced("x")(lambda s: ...)`` -> the lambda.  Without this, wrapping
    a fresh lambda in the instrumentation decorator would hide it from
    RCD001 — the wrapper call creates just as new an identity per call as
    the bare lambda does."""
    seen = 0
    while (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Call)
        and len(node.args) == 1
        and not node.keywords
        and seen < 8
    ):
        node = node.args[0]
        seen += 1
    return node


def _looks_like_jax_compile(node: ast.Call) -> bool:
    text = ""
    cur: ast.AST = node.func
    while isinstance(cur, (ast.Attribute, ast.Call)):
        if isinstance(cur, ast.Attribute):
            text = cur.attr + "." + text
            cur = cur.value
        else:
            cur = cur.func
    if isinstance(cur, ast.Name):
        text = cur.id + "." + text
    markers = ("jit", "lower", "pjit", "lowered", "compiled")
    return any(m in text for m in markers)


def _receiver_is_exe_cache(node: ast.AST) -> bool:
    name = dotted_name(node)
    return name.rsplit(".", 1)[-1] in ("exe_cache", "executable_cache")


def _assigned_from_call(name: str, fn: ast.AST | None) -> ast.AST | None:
    if fn is None:
        return None
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            for tgt in n.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    return n
    return None


def _computed_key_elements(key_node: ast.AST, encl_fns: list[ast.AST]):
    out = []
    elements = (
        key_node.elts if isinstance(key_node, (ast.Tuple, ast.List)) else [key_node]
    )
    for el in elements:
        if isinstance(el, ast.Name) and any(
            _assigned_from_call(el.id, fn) is not None for fn in encl_fns
        ):
            out.append((el.id, el))
    return out


def _closure_reads_outside_key(
    lam: ast.Lambda, key_names: set[str], fn: ast.AST | None
) -> set[str]:
    """Free variables of the build lambda that are PER-CALL assigned
    locals of the enclosing function and absent from the key.  Bare
    parameters (registry/server handles threaded through) are ambient
    context, not specialization inputs — only values the function derives
    per call can silently under-key the executable."""
    if fn is None:
        return set()
    local_names: set[str] = set()
    for n in ast.walk(fn):
        targets: list[ast.AST] = []
        if isinstance(n, ast.Assign):
            targets = list(n.targets)
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            targets = [n.target]
        elif isinstance(n, ast.For):
            targets = [n.target]
        for tgt in targets:
            for t in ast.walk(tgt):
                if isinstance(t, ast.Name):
                    local_names.add(t.id)
    lam_params = {x.arg for x in lam.args.args + lam.args.kwonlyargs}
    reads: set[str] = set()
    for n in ast.walk(lam.body):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            reads.add(n.id)
        # attribute roots count as their base name read (first.graph -> first)
    return {
        r
        for r in reads
        if r in local_names
        and r not in lam_params
        and r not in key_names
        and r not in ("self",)
        and not _attr_of_read_in_key(lam, r, key_names)
    }


def _attr_of_read_in_key(lam: ast.Lambda, name: str, key_names: set[str]) -> bool:
    """``first`` counts as keyed when the key carries ``first.<attr>`` for
    every attribute the closure reads off it."""
    attrs_read = {
        n.attr
        for n in ast.walk(lam.body)
        if isinstance(n, ast.Attribute)
        and isinstance(n.value, ast.Name)
        and n.value.id == name
    }
    return bool(attrs_read) and attrs_read <= key_names
