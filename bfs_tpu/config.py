"""Framework configuration: the ``service.properties`` layer, TPU-shaped.

Parity with ``ServiceConfiguration`` (ServiceConfiguration.java:30-63): a
``key=value`` properties file loaded once, exposing app name and the
comma-separated problem-file list (``problemFiles``).  TPU-specific keys
replace the Spark master coordinates (ip/port/jar — obsolete: XLA programs
are dispatched to the mesh, not shipped as jars):

    app-name       = BFS with MapReduce, TPU edition
    problemFiles   = test-sets/tinyCG.txt, test-sets/mediumG.txt
    source         = 0
    mesh-batch     = 1
    mesh-graph     = 0            # 0 = all devices
    dump-supersteps = false       # write problemFile_i-style text dumps
    checkpoint-every = 0          # supersteps between .npz checkpoints

Unlike the reference, a missing/corrupt file raises instead of being
swallowed into null getters (ServiceConfiguration.java:40-42 logs and
continues — a latent NPE factory we deliberately do not reproduce).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


def parse_properties(text: str) -> dict[str, str]:
    """Minimal Java-properties subset: ``k=v`` lines, ``#``/``!`` comments,
    whitespace-trimmed keys/values."""
    out: dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#") or line.startswith("!"):
            continue
        if "=" not in line:
            raise ValueError(f"malformed properties line: {raw!r}")
        k, _, v = line.partition("=")
        out[k.strip()] = v.strip()
    return out


@dataclass(frozen=True)
class ServiceConfiguration:
    app_name: str = "BFS with MapReduce, TPU edition"
    problem_files: tuple[str, ...] = ()
    source: int = 0
    mesh_batch: int = 1
    mesh_graph: int = 0  # 0 = use all devices
    dump_supersteps: bool = False
    checkpoint_every: int = 0
    work_dir: str = "."

    @classmethod
    def load(cls, path: str | os.PathLike) -> "ServiceConfiguration":
        with open(path, "r") as f:
            props = parse_properties(f.read())
        files = tuple(
            p.strip() for p in props.get("problemFiles", "").split(",") if p.strip()
        )
        return cls(
            app_name=props.get("app-name", cls.app_name),
            problem_files=files,
            source=int(props.get("source", "0")),
            mesh_batch=int(props.get("mesh-batch", "1")),
            mesh_graph=int(props.get("mesh-graph", "0")),
            dump_supersteps=props.get("dump-supersteps", "false").lower() == "true",
            checkpoint_every=int(props.get("checkpoint-every", "0")),
            work_dir=props.get("work-dir", os.path.dirname(os.fspath(path)) or "."),
        )
