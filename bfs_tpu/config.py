"""Framework configuration: the ``service.properties`` layer, TPU-shaped.

Parity with ``ServiceConfiguration`` (ServiceConfiguration.java:30-63): a
``key=value`` properties file loaded once, exposing app name and the
comma-separated problem-file list (``problemFiles``).  TPU-specific keys
replace the Spark master coordinates (ip/port/jar — obsolete: XLA programs
are dispatched to the mesh, not shipped as jars):

    app-name       = BFS with MapReduce, TPU edition
    problemFiles   = test-sets/tinyCG.txt, test-sets/mediumG.txt
    source         = 0
    mesh-batch     = 1
    mesh-graph     = 0            # 0 = all devices
    dump-supersteps = false       # write problemFile_i-style text dumps
    checkpoint-every = 0          # supersteps between .npz checkpoints

Unlike the reference, a missing/corrupt file raises instead of being
swallowed into null getters (ServiceConfiguration.java:40-42 logs and
continues — a latent NPE factory we deliberately do not reproduce).

This module also owns the ARTIFACT-CACHE directory layout (the cold-path
killer, ISSUE 2): every persistent cache — relay/ELL layout bundles, JAX's
persistent compilation cache, the serialized-executable cache, and the
crash-resume run journals (ISSUE 3) — lives under one root so a driver, a
serving process and ``tools/cache_warm.py`` all share warm artifacts.  Resolution order: explicit env knob per cache, then
``BFS_TPU_CACHE_DIR``, then ``<repo>/.bench_cache`` (the directory the
bench has always used, so pre-existing warm entries keep working).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from . import knobs

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def cache_root() -> str:
    """Root directory for all persistent artifact caches
    (``BFS_TPU_CACHE_DIR``; default ``<repo>/.bench_cache``)."""
    v = knobs.raw("BFS_TPU_CACHE_DIR")
    return v if v is not None else os.path.join(_REPO_ROOT, ".bench_cache")


def layout_cache_dir() -> str:
    """On-disk layout-bundle store (:mod:`bfs_tpu.cache.layout`)."""
    return os.path.join(cache_root(), "layout")


def journal_dir() -> str:
    """Run-journal directory (:mod:`bfs_tpu.resilience.journal`):
    ``BFS_TPU_JOURNAL_DIR`` wins when set (tests point it at a tmp dir so
    kill/resume runs can share warm artifact caches but not journals),
    else ``<cache root>/journal`` — resume state lives with the other
    per-config artifacts it must stay consistent with."""
    v = knobs.raw("BFS_TPU_JOURNAL_DIR")
    return v if v is not None else os.path.join(cache_root(), "journal")


def compile_cache_dir() -> str:
    """JAX persistent compilation cache directory
    (``JAX_COMPILATION_CACHE_DIR`` wins when set)."""
    return os.environ.get(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(cache_root(), "xla")
    )


def exe_cache_dir() -> str:
    """Serialized-executable cache directory (``BFS_TPU_EXE_CACHE`` wins
    when set; an explicitly EMPTY value means disabled and is respected)."""
    v = knobs.raw("BFS_TPU_EXE_CACHE")
    return v if v is not None else os.path.join(cache_root(), "exe")


def enable_compile_cache(*, min_compile_seconds: float = 5.0) -> dict:
    """Turn on BOTH persistent compile caches; call before the first trace.

    * ``jax_compilation_cache_dir`` — JAX's own persistent cache, so the
      ~830 s cold XLA compile of the bench-scale fused programs is paid
      once per (topology, program) ever (VERDICT r5 "missing" #1).
    * ``BFS_TPU_EXE_CACHE`` — the serialized-executable cache
      (models/bfs.py ``compile_exe_cached``), needed because jax's cache
      is inert under the axon remote-compile transport.

    Idempotent; returns the resolved directories so callers can log them.
    Entry points that compile anything (the runners, tools) call this at
    startup; importing the ``bfs_tpu`` package itself must NOT (an
    application's global jax config is not ours to mutate).  The one
    historical exception is ``bfs_tpu.bench``, which enables the caches at
    import — every importer of that module (the bench entry point, the
    profiling tools, benchmarks.py) is itself a bench surface that relies
    on it.
    """
    import jax

    cc_dir = compile_cache_dir()
    jax.config.update("jax_compilation_cache_dir", cc_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", float(min_compile_seconds)
    )
    # setdefault respects an explicit BFS_TPU_EXE_CACHE="" (disabled).
    os.environ.setdefault("BFS_TPU_EXE_CACHE", exe_cache_dir())
    return {
        "jax_compilation_cache_dir": cc_dir,
        "exe_cache_dir": knobs.raw("BFS_TPU_EXE_CACHE"),
        "layout_cache_dir": layout_cache_dir(),
    }


def parse_properties(text: str) -> dict[str, str]:
    """Minimal Java-properties subset: ``k=v`` lines, ``#``/``!`` comments,
    whitespace-trimmed keys/values."""
    out: dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#") or line.startswith("!"):
            continue
        if "=" not in line:
            raise ValueError(f"malformed properties line: {raw!r}")
        k, _, v = line.partition("=")
        out[k.strip()] = v.strip()
    return out


@dataclass(frozen=True)
class ServiceConfiguration:
    app_name: str = "BFS with MapReduce, TPU edition"
    problem_files: tuple[str, ...] = ()
    source: int = 0
    mesh_batch: int = 1
    mesh_graph: int = 0  # 0 = use all devices
    dump_supersteps: bool = False
    checkpoint_every: int = 0
    work_dir: str = "."

    @classmethod
    def load(cls, path: str | os.PathLike) -> "ServiceConfiguration":
        with open(path, "r") as f:
            props = parse_properties(f.read())
        files = tuple(
            p.strip() for p in props.get("problemFiles", "").split(",") if p.strip()
        )
        return cls(
            app_name=props.get("app-name", cls.app_name),
            problem_files=files,
            source=int(props.get("source", "0")),
            mesh_batch=int(props.get("mesh-batch", "1")),
            mesh_graph=int(props.get("mesh-graph", "0")),
            dump_supersteps=props.get("dump-supersteps", "false").lower() == "true",
            checkpoint_every=int(props.get("checkpoint-every", "0")),
            work_dir=props.get("work-dir", os.path.dirname(os.fspath(path)) or "."),
        )
