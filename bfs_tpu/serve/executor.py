"""Compiled-executable cache + per-engine batch runners.

Steady-state serving must never recompile: the round-5 ledger puts the
bench-scale compile at ~830 s, and even the CPU-mesh test programs cost
hundreds of ms — per-tick compiles would dominate every latency percentile.
The cache here is keyed ``(graph, epoch, engine, batch_shape, direction)``:
the server pads every tick's source batch to a power-of-two bucket so a
handful of shapes cover any traffic mix, and after warmup every tick is a
cache hit (the loadgen report asserts exactly this).  The EPOCH element
makes a hot graph swap safe — an executable built for one snapshot can
never be asked to serve another.

For the pull/push engines the runner is an AOT artifact
(``jit(...).lower(...).compile()``): the executable takes the device
operands as ARGUMENTS, so registry eviction + re-upload of a graph's
operands does not invalidate it — same shapes, new buffers.  The relay
engine manages its own compiled programs internally (models/bfs.py); its
runner is a closure and the first tick per shape counts as the miss.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from ..analysis.runtime import make_lock
from ..models.multisource import MultiBfsResult


class ExecutableCache:
    """LRU of batch runners keyed ``(graph, engine, batch)``.

    ``get`` returns the cached runner (a compile hit) or invokes ``build``
    under the lock and records a miss.  Hit/miss totals feed the serve
    report's ``compile_hit_rate``."""

    def __init__(self, capacity: int = 64, metrics=None):
        self.capacity = capacity  # immutable after init
        self.metrics = metrics  # ServeMetrics is internally locked
        self._lock = make_lock("executor._lock")
        self._cache: OrderedDict[tuple, object] = OrderedDict()  # guarded-by: _lock
        self.hits = 0  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock

    def get(self, key: tuple, build):
        with self._lock:
            runner = self._cache.get(key)
            if runner is not None:
                self._cache.move_to_end(key)
                self.hits += 1
                if self.metrics is not None:
                    self.metrics.bump("compile_hits")
                return runner, True
        # Build outside the cache-wide lock: compiles are seconds-long and
        # registration/metrics readers must not stall behind them.  The
        # serving loop is single-threaded, so duplicate builds only happen
        # with concurrent servers sharing a cache — harmless, last wins.
        runner = build()
        with self._lock:
            runner = self._cache.setdefault(key, runner)
            self._cache.move_to_end(key)
            self.misses += 1
            if self.metrics is not None:
                self.metrics.bump("compile_misses")
            while len(self._cache) > self.capacity:
                self._cache.popitem(last=False)
        return runner, False

    def put(self, key: tuple, runner) -> None:
        """Install a runner directly (no miss counted).  The seam the
        resilience tests use to serve a flaky/instrumented runner through
        the real batch path, and a warm-handoff hook for preloaded
        executables."""
        with self._lock:
            self._cache[key] = runner
            self._cache.move_to_end(key)
            while len(self._cache) > self.capacity:
                self._cache.popitem(last=False)

    def drop_graph(self, name: str) -> None:
        """Drop every cached runner for ``name`` across ALL epochs (the
        unregister path; epoch swaps leave old-epoch entries to age out
        of the LRU — their epoch-bearing keys can never serve the new
        graph)."""
        with self._lock:
            for key in [k for k in self._cache if k[0] == name]:
                del self._cache[key]

    def drop_key(self, key: tuple) -> None:
        """Drop ONE cached runner — the quarantine path: a failed
        integrity verdict proves this executable wrong, so the half-open
        canary must rebuild it rather than re-probe the same artifact."""
        with self._lock:
            self._cache.pop(key, None)

    def __contains__(self, key: tuple) -> bool:
        """Presence probe WITHOUT touching LRU order or hit counters —
        the server uses it to decide whether a tick is a cold build (and
        therefore needs the compile-floor watchdog budget) before calling
        :meth:`get` under the watchdog."""
        with self._lock:
            return key in self._cache

    def peek(self, key: tuple):
        """The cached runner (or None) WITHOUT LRU/counter side effects —
        the hung-call resume path inspects the runner's checkpoint
        progress after a watchdog timeout (ISSUE 14) without recording a
        phantom hit."""
        with self._lock:
            return self._cache.get(key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._cache)


def bucket_for(n: int) -> int:
    """Pad a tick's source count to a power-of-two bucket so a handful of
    shapes cover any traffic mix — the executable-cache key's shape
    element.  O(1) via bit_length (the old linear doubling loop re-ran on
    EVERY tick; recompile-drift rule RCD004 documents why a computed key
    element is acceptable here at all: this derivation bounds the distinct
    shape set to log2(max_batch)+1 buckets)."""
    n = int(n)
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


# bfs_tpu: hot
def _state_to_result(state, sources: np.ndarray, num_vertices: int) -> MultiBfsResult:
    """Materialize ONE reply pull: slice the padded device state down to
    the real vertex range ON DEVICE, then make a single explicit
    device_get of exactly (dist, parent, level).  The old path pulled the
    ENTIRE padded state pytree (including frontier words the reply never
    reads) and sliced on the host — the same forced-oversized-pull class
    as the 128 MB bench.py:952 bug ISSUE 2 opened with.  The transfer is
    the reply materialization itself, hence explicit and pragma-accepted:
    """
    import jax

    dist, parent, levels = jax.device_get(  # bfs_tpu: ok TRC004 the one intended reply pull, device-sliced
        (
            state.dist[:, :num_vertices],
            state.parent[:, :num_vertices],
            state.level,
        )
    )
    return MultiBfsResult(
        sources=sources,
        dist=dist,
        parent=parent,
        num_levels=int(levels),  # bfs_tpu: ok TRC002 levels is host-side after the pull above
    )


class _Abandoned(RuntimeError):
    """Raised inside a watchdog-abandoned attempt thread when a NEWER
    attempt has taken over the traversal: the zombie must stop burning
    device time, and its late snapshots must never clobber the live
    attempt's progress."""


class SegmentedBatchRunner:
    """Resumable segmented batch runner (ISSUE 14 serve integration).

    With ``BFS_TPU_CKPT`` enabled, the pull/push batch programs run as
    bounded segments (models/multisource.py ``_bfs_multi_*_segment``)
    with the full carry snapshotted to HOST arrays after every segment —
    in-process checkpoint epochs.  A hung device call (watchdog
    ``HungCallError``) abandons only the attempt THREAD, not the
    process, so the next attempt for the same padded source batch — the
    server's hung-call resume loop, or the breaker's half-open canary
    re-submitting the same query — RESUMES from the newest epoch instead
    of recomputing from the roots.  Results are bit-identical to the
    fused runner for any segmentation (the segment programs' contract).

    Thread safety: each attempt bumps a generation under the lock; a
    watchdog-abandoned thread that wakes up later sees the stale
    generation, aborts (``_Abandoned``) and never overwrites the live
    attempt's progress.
    """

    resumable = True

    def __init__(self, registry, name: str, engine: str, batch: int,
                 epoch: int, num_vertices: int, want_packed: bool,
                 interval: int, metrics=None):
        self.registry = registry
        self.name = name
        self.engine = engine
        self.batch = batch
        self.epoch = epoch
        self.v = num_vertices
        self.want_packed = want_packed
        self.interval = max(1, int(interval))
        self.metrics = metrics
        self._lock = make_lock("executor.SegmentedBatchRunner._lock")
        self._gen = 0  # guarded-by: _lock
        #: (batch key, packed flavor, host snapshot, level) — guarded-by: _lock
        self._progress = None

    def ckpt_progress(self):
        """The resumable superstep (or None) — what the server's
        hung-call loop checks to decide whether another attempt would
        make progress rather than re-wedge from the same point."""
        with self._lock:
            return None if self._progress is None else self._progress[3]

    def _bump(self, counter: str) -> None:
        if self.metrics is not None:
            self.metrics.bump(counter)

    def _segment(self, state, seg_end, packed):
        import jax.numpy as jnp

        from ..models.multisource import (
            _bfs_multi_pull_segment,
            _bfs_multi_segment,
        )

        operands = self.registry.acquire_epoch(
            self.name, self.epoch, self.engine
        )
        if self.engine == "pull":
            ell0, folds = operands
            return _bfs_multi_pull_segment(
                ell0, folds, state, jnp.int32(seg_end), self.v, self.v,
                packed,
            )
        src, dst = operands
        return _bfs_multi_segment(
            src, dst, state, jnp.int32(seg_end), self.v, self.v, packed
        )

    def _run_flavor(self, sources: np.ndarray, key: bytes, my_gen: int,
                    packed: bool):
        import jax

        from ..models.multisource import (
            multi_segment_finish,
            multi_segment_init,
        )
        from ..ops.packed import packed_cap
        from ..resilience.faults import fault_point

        cap = packed_cap(self.v) if packed else self.v
        state = None
        with self._lock:
            if (
                self._progress is not None
                and self._progress[0] == key
                and self._progress[1] == packed
            ):
                state = multi_segment_init(
                    self.v, sources, packed, restore=self._progress[2]
                )
                self._bump("ckpt_resumes")
        if state is None:
            state = multi_segment_init(self.v, sources, packed)
        level, changed = jax.device_get((state.level, state.changed))
        while bool(changed) and int(level) < cap:
            seg_end = min(int(level) + self.interval, cap)
            state = self._segment(state, seg_end, packed)
            level, changed = jax.device_get((state.level, state.changed))
            snap = {
                k: np.asarray(v)
                for k, v in jax.device_get(state)._asdict().items()
            }
            with self._lock:
                if self._gen != my_gen:
                    raise _Abandoned(
                        "a newer attempt owns this traversal"
                    )
                self._progress = (key, packed, snap, int(level))
            self._bump("ckpt_segments")
            if bool(changed) and int(level) < cap:
                # The segment boundary the chaos/hung-call tests target
                # (a delay here is a wedged mid-traversal dispatch).
                fault_point("serve.segment")
        return multi_segment_finish(state, packed), int(level), bool(changed)

    # bfs_tpu: hot
    def __call__(self, sources: np.ndarray) -> MultiBfsResult:
        from ..analysis.runtime import guarded_region
        from ..ops.packed import PACKED_MAX_LEVELS, packed_truncated

        key = np.ascontiguousarray(sources).tobytes()
        with self._lock:
            self._gen += 1
            my_gen = self._gen
        with guarded_region(
            f"serve.device_batch/{self.name}/{self.engine}-segmented"
        ):
            packed = self.want_packed
            state, level, changed = self._run_flavor(
                sources, key, my_gen, packed
            )
            if packed and packed_truncated(changed, level, self.v):
                # Deeper than the packed cap: re-run unpacked (the
                # packed progress cannot feed it).
                with self._lock:
                    if self._gen == my_gen:
                        self._progress = None
                state, level, changed = self._run_flavor(
                    sources, key, my_gen, False
                )
        with self._lock:
            if self._gen == my_gen:
                self._progress = None  # finished: epochs are dead weight
        return _state_to_result(state, sources, self.v)


def build_batch_runner(registry, name: str, engine: str, batch: int,
                       epoch: int | None = None):
    """AOT-compile (or bind) the batched multi-source program for one
    ``(graph epoch, engine, batch)`` shape.  The returned callable maps a
    padded int32[batch] source array to a host :class:`MultiBfsResult`.

    ``epoch`` pins the runner to one graph snapshot (default: the current
    epoch at build time): every per-call ``acquire`` goes through
    :meth:`GraphRegistry.acquire_epoch`, so a runner built before a hot
    swap keeps executing against ITS graph — the executable and the
    operands it runs over can never mix epochs."""
    import jax
    import jax.numpy as jnp

    from ..analysis.runtime import guarded_region
    from ..models.multisource import _bfs_multi_fused, _bfs_multi_pull_fused

    rec = registry.get(name) if epoch is None else registry.get_epoch(name, epoch)
    epoch = rec.epoch
    v = rec.num_vertices

    # The per-tick source upload is EXPLICIT device_put, not an implicit
    # jnp.asarray conversion: under the runtime transfer guard
    # (BFS_TPU_TRANSFER_GUARD=1, jax.transfer_guard("disallow")) implicit
    # host->device transfers raise while intended explicit ones pass —
    # the serving tick declares its one upload and its one pull, and the
    # guard proves there are no others.

    # Packed fused-word state (ops/packed.py) whenever parent ids fit its
    # 26-bit field: half the per-superstep dist/parent HBM bytes per tick.
    # A graph deeper than the packed 62-level cap is detected on the FIRST
    # truncated reply (state.changed still set at the cap), latched, and
    # every subsequent tick runs the lazily-compiled unpacked executable —
    # one extra compile once per (graph, batch) shape, never a wrong reply.
    from ..ops.packed import (
        PACKED_MAX_LEVELS,
        packed_parent_fits,
        packed_truncated,
        resolve_packed,
    )

    want_packed = resolve_packed(packed_parent_fits(v))

    # ISSUE 14: with BFS_TPU_CKPT enabled the pull/push batch programs
    # run as bounded segments with in-process checkpoint epochs, so a
    # hung-call retry or a breaker half-open canary on a deep-graph tick
    # RESUMES mid-traversal instead of recomputing from the roots
    # (server._execute_batch's hung-call resume loop reads
    # ``ckpt_progress``).  Off (the default) keeps the fused AOT runners
    # below byte-for-byte.
    from ..resilience.superstep_ckpt import resolve_ckpt

    ckpt_cfg = resolve_ckpt()
    if ckpt_cfg.enabled and engine in ("pull", "push"):
        return SegmentedBatchRunner(
            registry, name, engine, batch, epoch, v, want_packed,
            interval=ckpt_cfg.k,
            metrics=getattr(registry, "metrics", None),
        )

    # A graph shallower than the cap can never truncate — skip the
    # per-tick flag pull entirely (the common case; v-vertex BFS depth
    # is bounded by v).
    needs_depth_check = want_packed and v > PACKED_MAX_LEVELS

    def _packed_runner_pair(lower):
        """(packed executable, lazy unpacked executable holder)."""
        state = {
            "packed": lower(True) if want_packed else None,
            "unpacked": None if want_packed else lower(False),
            "use_packed": want_packed,
        }

        def call(*operands):
            if state["use_packed"]:
                out = state["packed"](*operands)
                if not needs_depth_check:
                    return out
                # ONE combined pull (not two syncs) ahead of the reply
                # pull — only on graphs deep enough to possibly truncate.
                changed, level = jax.device_get((out.changed, out.level))
                if not packed_truncated(changed, level, v):
                    return out
                state["use_packed"] = False  # latch: deeper than the cap
            if state["unpacked"] is None:
                state["unpacked"] = lower(False)
            return state["unpacked"](*operands)

        return call

    if engine == "pull":
        ell0, folds = registry.acquire_epoch(name, epoch, engine)
        compiled = _packed_runner_pair(
            lambda p: _bfs_multi_pull_fused.lower(
                ell0, folds, jnp.zeros((batch,), jnp.int32), v, v, p
            ).compile()
        )

        # bfs_tpu: hot
        def run(sources: np.ndarray) -> MultiBfsResult:
            # Re-acquire per call: eviction may have dropped the operands,
            # and acquire re-uploads same-shaped buffers the executable
            # accepts unchanged.  Epoch-pinned: a hot swap between ticks
            # must not hand this runner the NEW graph's operands.
            ell0, folds = registry.acquire_epoch(name, epoch, engine)
            with guarded_region(f"serve.device_batch/{name}/pull"):
                state = compiled(ell0, folds, jax.device_put(sources))  # bfs_tpu: ok TRC004 explicit per-tick source upload
            return _state_to_result(state, sources, v)

        return run

    if engine == "push":
        src, dst = registry.acquire_epoch(name, epoch, engine)
        compiled = _packed_runner_pair(
            lambda p: _bfs_multi_fused.lower(
                src, dst, jnp.zeros((batch,), jnp.int32), v, v, p
            ).compile()
        )

        # bfs_tpu: hot
        def run(sources: np.ndarray) -> MultiBfsResult:
            src, dst = registry.acquire_epoch(name, epoch, engine)
            with guarded_region(f"serve.device_batch/{name}/push"):
                state = compiled(src, dst, jax.device_put(sources))  # bfs_tpu: ok TRC004 explicit per-tick source upload
            return _state_to_result(state, sources, v)

        return run

    if engine == "relay":
        def run(sources: np.ndarray) -> MultiBfsResult:
            eng = registry.acquire_epoch(name, epoch, engine)
            if sources.shape[0] % 32 == 0:
                # Element-major mode, 32 trees per uint32 element; falls
                # back to the vmapped path automatically past 31 levels
                # (models/bfs.py run_multi_elem).
                return eng.run_multi_elem(sources)
            return eng.run_multi(sources)

        return run

    raise ValueError(f"unknown engine {engine!r}")


def run_oracle_batch(graph, sources: np.ndarray) -> MultiBfsResult:
    """Sequential degradation path: per-source canonical BFS on the host.

    Uses :func:`~bfs_tpu.oracle.bfs.canonical_bfs` (min-parent tie-break)
    so the dist AND parent rows are bit-exact with the device engines —
    a degraded-path reply is indistinguishable from a device reply."""
    from ..oracle.bfs import canonical_bfs

    dist_rows, parent_rows = [], []
    for s in np.asarray(sources).tolist():
        d, p = canonical_bfs(graph, int(s))
        dist_rows.append(d)
        parent_rows.append(p)
    dist = np.stack(dist_rows)
    return MultiBfsResult(
        sources=np.asarray(sources, dtype=np.int32),
        dist=dist,
        parent=np.stack(parent_rows),
        num_levels=int(dist[dist != np.iinfo(np.int32).max].max(initial=0)) + 1,
    )
